// Native single-seed discrete-event baseline — the honest denominator.
//
// bench.py's vs_baseline has so far divided by THIS ENGINE at batch=1,
// which stands in for the reference's per-seed execution model
// (madsim/src/sim/task.rs:110-124: pop task from a heap-ordered queue,
// poll it, advance virtual time) but pays XLA per-step dispatch overhead
// a native loop does not. This file is the native stand-in the
// environment can actually compile: the SAME flagship workload bench.py
// measures (5-node Raft under rolling kill/restart + partition/heal +
// 5% packet loss, 1-10ms link latency, 24 proposals per leader stint —
// bench.py _make_runtime), implemented the way the reference would run
// it — one seed, sequential handlers, a binary heap of (deadline,
// random-priority) events (the random tie-break mirrors madsim's
// random-pop queue, mpsc.rs:75), RNG draws per send for loss + latency.
//
// Deliberately NOT included: the per-event global invariant and the
// schedule hash. The reference model has neither (its supervisor can
// only observe at its own wakeups), so charging the native loop for
// them would understate the baseline.
//
// Exported (ctypes, see madsim_tpu/native.py):
//   simloop_run(seed, max_events, out[4])
//     out = {events_dispatched, wall_ns, max_commit_seen, elections}

#include <algorithm>
#include <chrono>
#include <cstdint>
#include <cstring>
#include <queue>
#include <vector>

namespace {

constexpr int NN = 5;          // cluster size (bench flagship)
constexpr int L = 32;          // log capacity
constexpr int PW = 8;          // payload words
constexpr int N_CMDS = 24;     // proposals per leader stint
constexpr int MAJ = NN / 2 + 1;

// virtual time: microsecond ticks (core/types.py TICKS_PER_SEC = 1e6)
constexpr int64_t MS = 1000;
constexpr int64_t SEC = 1000 * MS;
constexpr int64_t E_MIN = 150 * MS, E_MAX = 300 * MS;  // election timeout
constexpr int64_t HB = 50 * MS;                        // heartbeat
constexpr int64_t PROP = 100 * MS;                     // propose tick
constexpr int64_t LAT_LO = 1 * MS, LAT_HI = 10 * MS;   // link latency
constexpr double LOSS = 0.05;

enum Kind : uint8_t { MSG, TIMER, SUPER };
enum MTag : int32_t { RV = 1, RVR, AE, AER };
enum TTag : int32_t { T_ELECTION = 1, T_HEARTBEAT, T_PROPOSE };
enum STag : int32_t { KILL_RANDOM = 1, RESTART_RANDOM, PARTITION, HEAL };
enum Role : int32_t { FOLLOWER, CANDIDATE, LEADER };

struct Rng {  // splitmix64
  uint64_t s;
  uint64_t next() {
    uint64_t z = (s += 0x9e3779b97f4a7c15ull);
    z = (z ^ (z >> 30)) * 0xbf58476d1ce4e5b9ull;
    z = (z ^ (z >> 27)) * 0x94d049bb133111ebull;
    return z ^ (z >> 31);
  }
  int64_t range(int64_t lo, int64_t hi) {  // inclusive
    return lo + (int64_t)(next() % (uint64_t)(hi - lo + 1));
  }
  bool bernoulli(double p) { return (next() >> 11) * 0x1.0p-53 < p; }
};

struct Ev {
  int64_t deadline;
  uint32_t pri;     // random: uniform tie-break among equal deadlines
  Kind kind;
  int32_t node, src, tag;
  int32_t gen;      // dst boot generation at insert (kill clears queue)
  int32_t payload[PW];
};
struct EvCmp {  // min-heap on (deadline, pri)
  bool operator()(const Ev& a, const Ev& b) const {
    return a.deadline != b.deadline ? a.deadline > b.deadline
                                    : a.pri > b.pri;
  }
};

struct Node {
  // persistent (stable storage — survives kill/restart)
  int32_t term = 0, voted_for = -1, log_len = 0;
  int32_t log_term[L] = {}, log_cmd[L] = {};
  // volatile
  int32_t role = FOLLOWER, votes = 0, commit = 0, nprop = 0;
  int32_t next[NN] = {}, match[NN] = {};
  int32_t egen = 0, hgen = 0;
  void reset_volatile() {
    role = FOLLOWER; votes = 0; commit = 0; nprop = 0;
    std::memset(next, 0, sizeof next);
    std::memset(match, 0, sizeof match);
    egen = 0; hgen = 0;
  }
};

struct Sim {
  Rng rng;
  std::priority_queue<Ev, std::vector<Ev>, EvCmp> q;
  Node nd[NN];
  bool alive[NN];
  bool cut[NN][NN] = {};   // partition link matrix
  int32_t boot_gen[NN] = {};
  int64_t now = 0;
  int64_t events = 0, elections = 0;
  int32_t max_commit = 0;

  void push(Kind k, int64_t at, int n, int src, int tag,
            const int32_t* pl, int npl) {
    Ev e{};
    e.deadline = at;
    e.pri = (uint32_t)rng.next();
    e.kind = k; e.node = (int32_t)n; e.src = (int32_t)src;
    e.tag = tag; e.gen = boot_gen[n];
    if (pl) std::memcpy(e.payload, pl, npl * sizeof(int32_t));
    q.push(e);
  }
  void send(int from, int to, int tag, const int32_t* pl, int npl) {
    if (cut[from][to]) return;                 // clogged link
    if (rng.bernoulli(LOSS)) return;           // packet loss
    int64_t lat = rng.range(LAT_LO, LAT_HI);
    push(MSG, now + lat, to, from, tag, pl, npl);
  }
  void set_timer(int n, int64_t delay, int tag, const int32_t* pl, int npl) {
    push(TIMER, now + delay, n, n, tag, pl, npl);
  }

  int32_t last_term(const Node& s) {
    return s.log_len > 0 ? s.log_term[s.log_len - 1] : 0;
  }
  void arm_election(int n) {
    Node& s = nd[n];
    s.egen++;
    int32_t pl[1] = {s.egen};
    set_timer(n, rng.range(E_MIN, E_MAX), T_ELECTION, pl, 1);
  }
  void node_init(int n) {  // boot / restart (Raft.init)
    arm_election(n);
    int32_t pl[1] = {0};
    set_timer(n, rng.range(0, PROP), T_PROPOSE, pl, 1);
  }

  void on_timer(int n, int tag, const int32_t* pl) {
    Node& s = nd[n];
    if (tag == T_ELECTION) {
      if (pl[0] != s.egen || s.role == LEADER) return;
      s.term++; s.role = CANDIDATE; s.voted_for = n; s.votes = 1;
      elections++;
      arm_election(n);  // candidate retries on split vote
      int32_t rv[3] = {s.term, s.log_len, last_term(s)};
      for (int p = 0; p < NN; p++)
        if (p != n) send(n, p, RV, rv, 3);
    } else if (tag == T_HEARTBEAT) {
      if (pl[0] != s.hgen || s.role != LEADER) return;
      for (int p = 0; p < NN; p++) {
        if (p == n) continue;
        int32_t nxt = s.next[p];
        int32_t prev_t = nxt > 0 ? s.log_term[std::min(nxt - 1, L - 1)] : 0;
        int32_t cnt = std::min(std::max(s.log_len - nxt, 0), 1);
        int32_t ei = std::min(std::max(nxt, 0), L - 1);
        int32_t ae[7] = {s.term, nxt, prev_t, s.commit, cnt,
                         s.log_term[ei], s.log_cmd[ei]};
        send(n, p, AE, ae, 7);
      }
      int32_t hb[1] = {s.hgen};
      set_timer(n, HB, T_HEARTBEAT, hb, 1);
    } else if (tag == T_PROPOSE) {
      if (s.role == LEADER && s.nprop < N_CMDS && s.log_len < L) {
        s.log_term[s.log_len] = s.term;
        s.log_cmd[s.log_len] = n * 65536 + s.nprop;
        s.log_len++;
        s.match[n] = s.log_len;
        s.nprop++;
      }
      int32_t pr[1] = {0};
      set_timer(n, PROP, T_PROPOSE, pr, 1);  // re-arms unconditionally
    }
  }

  void advance_commit(Node& s) {  // §5.4.2: current-term entries only
    for (int32_t k = s.commit; k < s.log_len; k++) {
      if (s.log_term[k] != s.term) continue;
      int c = 0;
      for (int p = 0; p < NN; p++) c += s.match[p] >= k + 1;
      if (c >= MAJ) s.commit = k + 1;
    }
  }

  void on_message(int n, int src, int tag, const int32_t* pl) {
    Node& s = nd[n];
    int32_t term_in = pl[0];
    if (term_in > s.term) {  // §5.1 step-down
      s.term = term_in; s.role = FOLLOWER; s.voted_for = -1;
    }
    bool reset_el = false;
    if (tag == RV) {
      int32_t clen = pl[1], clast = pl[2], mylast = last_term(s);
      bool log_ok = clast > mylast || (clast == mylast && clen >= s.log_len);
      bool grant = term_in == s.term && log_ok &&
                   (s.voted_for == -1 || s.voted_for == src);
      if (grant) { s.voted_for = src; reset_el = true; }
      int32_t rvr[2] = {s.term, grant};
      send(n, src, RVR, rvr, 2);
    } else if (tag == RVR) {
      if (s.role == CANDIDATE && term_in == s.term && pl[1] == 1) {
        s.votes++;
        if (s.votes == MAJ) {  // become leader, exactly once
          s.role = LEADER;
          for (int p = 0; p < NN; p++) { s.next[p] = s.log_len; s.match[p] = 0; }
          s.match[n] = s.log_len;
          s.hgen++;
          int32_t hb[1] = {s.hgen};
          set_timer(n, 0, T_HEARTBEAT, hb, 1);
        }
      }
    } else if (tag == AE) {
      int32_t prev = pl[1], prev_t = pl[2], lcommit = pl[3], cnt = pl[4];
      bool from_leader = term_in == s.term;
      if (from_leader && s.role == CANDIDATE) s.role = FOLLOWER;
      if (from_leader) reset_el = true;
      bool prev_ok = prev <= s.log_len &&
                     (prev == 0 || s.log_term[prev - 1] == prev_t);
      bool ok = from_leader && prev_ok && (cnt == 0 || prev < L);
      int32_t n_acc = 0;
      if (ok && cnt > 0) {
        int32_t e_term = pl[5], e_cmd = pl[6];
        if (prev < s.log_len && s.log_term[prev] != e_term)
          s.log_len = prev;  // §5.3 conflict truncation
        s.log_term[prev] = e_term;
        s.log_cmd[prev] = e_cmd;
        s.log_len = std::max(s.log_len, prev + 1);
        n_acc = 1;
      }
      // commit clamps to the VERIFIED prefix (Figure 2 "last new entry"),
      // not the local log length — same rule the engine unit-tests
      int32_t match = ok ? prev + n_acc : 0;
      if (ok) s.commit = std::max(s.commit, std::min(lcommit, match));
      int32_t aer[3] = {s.term, ok, match};
      send(n, src, AER, aer, 3);
    } else if (tag == AER) {
      if (s.role == LEADER && term_in == s.term) {
        bool succ = pl[1] == 1;
        int32_t mlen = pl[2];
        if (succ) {
          s.match[src] = std::max(s.match[src], mlen);
          s.next[src] = std::max(s.next[src], s.match[src]);
        } else {
          s.next[src] = std::max(s.next[src] - 1, 0);
        }
        advance_commit(s);
      }
    }
    max_commit = std::max(max_commit, s.commit);
    if (reset_el) arm_election(n);
  }

  void on_super(int op, const int32_t* pl) {
    if (op == KILL_RANDOM || op == RESTART_RANDOM) {
      bool want = op == KILL_RANDOM;  // kill among alive, restart among dead
      int cand[NN], nc = 0;
      for (int p = 0; p < NN; p++)
        if (alive[p] == want) cand[nc++] = p;
      if (!nc) return;
      int t = cand[rng.next() % nc];
      boot_gen[t]++;  // clears the node's queued events (lazy drop on pop)
      if (op == KILL_RANDOM) {
        alive[t] = false;
      } else {
        alive[t] = true;
        nd[t].reset_volatile();  // process memory; log/term/vote persist
        node_init(t);
      }
    } else if (op == PARTITION) {
      int32_t a = pl[0], b = pl[1];
      for (int i = 0; i < NN; i++)
        for (int j = 0; j < NN; j++) {
          bool ia = i == a || i == b, ja = j == a || j == b;
          cut[i][j] = ia != ja;
        }
    } else if (op == HEAL) {
      std::memset(cut, 0, sizeof cut);
    }
  }

  void run(int64_t max_events) {
    for (int n = 0; n < NN; n++) { alive[n] = true; }
    for (int n = 0; n < NN; n++) push(SUPER, 0, n, 0, 0, nullptr, 0);  // boot
    for (int t = 0; t < 8; t++) {  // bench.py's rolling chaos script
      int32_t ab[2] = {t % NN, (t + 1) % NN};
      push(SUPER, (1 + t) * SEC, 0, 0, KILL_RANDOM, nullptr, 0);
      push(SUPER, (1 + t) * SEC + 400 * MS, 0, 0, RESTART_RANDOM, nullptr, 0);
      push(SUPER, (1 + t) * SEC + 600 * MS, 0, 0, PARTITION, ab, 2);
      push(SUPER, (1 + t) * SEC + 900 * MS, 0, 0, HEAL, nullptr, 0);
    }
    while (events < max_events && !q.empty()) {
      Ev e = q.top();
      q.pop();
      if (e.kind != SUPER && e.gen != boot_gen[e.node])
        continue;  // queue cleared at kill — removed, not dispatched
      now = std::max(now, e.deadline);
      events++;
      if (e.kind == SUPER) {
        if (e.tag == 0) node_init(e.node);  // boot row
        else on_super(e.tag, e.payload);
      } else if (!alive[e.node]) {
        // dispatched as a drop (messages to dead nodes still pop)
      } else if (e.kind == MSG) {
        on_message(e.node, e.src, e.tag, e.payload);
      } else {
        on_timer(e.node, e.tag, e.payload);
      }
    }
  }
};

}  // namespace

extern "C" void simloop_run(uint64_t seed, int64_t max_events,
                            int64_t* out /* [4] */) {
  Sim* sim = new Sim();
  sim->rng.s = seed * 0x9e3779b97f4a7c15ull + 0x2545f4914f6cdd1dull;
  auto t0 = std::chrono::steady_clock::now();
  sim->run(max_events);
  auto t1 = std::chrono::steady_clock::now();
  out[0] = sim->events;
  out[1] = std::chrono::duration_cast<std::chrono::nanoseconds>(t1 - t0)
               .count();
  out[2] = sim->max_commit;
  out[3] = sim->elections;
  delete sim;
}
