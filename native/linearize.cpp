// Linearizability checker for single-register histories (Wing & Gong DFS
// with memoization on (remaining-set, register-value) — the Lowe
// just-in-time optimization shape). Host-side native component: checking is
// sequential search, the one part of the fuzz pipeline that does not
// vectorize onto the TPU, so it runs as C++ over histories extracted from
// device state (the analog of the reference keeping its perf-critical
// checker code native rather than in a scripting layer).
//
// Contract (see madsim_tpu/native.py):
//   op[i]  : 1 = PUT, 2 = GET
//   val[i] : value written (PUT) or value observed (GET)
//   inv[i] : invocation time
//   resp[i]: response time, or < 0 for an operation with no response
//            (crashed/timed-out client) — such an op may have taken effect
//            at any point after inv, or never.
// Returns 1 if the history is linearizable w.r.t. a register initialized
// to 0, else 0. n must be <= 57 (memo packs the set and a value index into
// one 64-bit key).

#include <cstddef>
#include <cstdint>
#include <unordered_set>
#include <vector>

namespace {

struct Ctx {
    int n;
    const int32_t* op;
    const int32_t* val;
    const int64_t* inv;
    const int64_t* resp;
    std::vector<int> validx;          // value -> dense index (per op's val)
    std::unordered_set<uint64_t> seen;
};

bool dfs(Ctx& c, uint64_t mask, int32_t value, int value_idx) {
    if (mask == 0) return true;
    uint64_t key = (mask << 7) | (uint64_t)(value_idx & 0x7f);
    if (!c.seen.insert(key).second) return false;

    // minimal ops: no *completed* remaining op responded before their
    // invocation
    int64_t minresp = INT64_MAX;
    for (int i = 0; i < c.n; i++)
        if ((mask >> i) & 1)
            if (c.resp[i] >= 0 && c.resp[i] < minresp) minresp = c.resp[i];

    for (int i = 0; i < c.n; i++) {
        if (!((mask >> i) & 1)) continue;
        if (c.inv[i] > minresp) continue;  // some op finished before i began
        uint64_t rest = mask & ~(1ull << i);
        if (c.op[i] == 1) {  // PUT: takes effect
            if (dfs(c, rest, c.val[i], c.validx[i])) return true;
        } else {             // GET: must observe the current value
            if (c.val[i] == value && dfs(c, rest, value, value_idx))
                return true;
        }
        if (c.resp[i] < 0) {  // pending op may also never take effect
            if (dfs(c, rest, value, value_idx)) return true;
        }
    }
    return false;
}

}  // namespace

extern "C" int lin_check_register(int n, const int32_t* op,
                                  const int32_t* val, const int64_t* inv,
                                  const int64_t* resp) {
    if (n <= 0) return 1;
    if (n > 57) return -1;  // caller must split
    Ctx c{n, op, val, inv, resp, {}, {}};
    // dense value indices for the memo key (initial value 0 gets index 0)
    c.validx.resize(n);
    std::vector<int32_t> vals{0};
    for (int i = 0; i < n; i++) {
        int idx = -1;
        for (std::size_t j = 0; j < vals.size(); j++)
            if (vals[j] == val[i]) { idx = (int)j; break; }
        if (idx < 0) { idx = (int)vals.size(); vals.push_back(val[i]); }
        c.validx[i] = idx;
    }
    return dfs(c, (n == 64 ? ~0ull : ((1ull << n) - 1)), 0, 0) ? 1 : 0;
}
