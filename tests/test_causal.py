"""Causal lineage layer (r10): happens-before edges, Lamport clocks,
crash explanation, prefix-coverage divergence telemetry.

Load-bearing properties (DESIGN §12):
(1) lineage + sketch are OBSERVERS — every non-trace leaf is
bit-identical whether they are compiled out, compiled in but unsampled,
or fully sampling, across the chunked AND fused runners (the fast-lane
single-config check lives here; the raft/wal_kv/shard_kv chaos-matrix
equivalence rides the `slow` lane in test_obs, whose ring-equivalence
sweeps now carry the lineage columns and a compiled-in sketch too);
(2) parent edges are DISPATCH INDICES, meaningful across ring wrap —
a chain truncates honestly instead of mis-resolving;
(3) Lamport clocks respect the happens-before order along any chain;
(4) the sketch folds the schedule prefix so divergence DEPTH is
readable per lane without any mid-run host traffic.
"""

import io
import json

import numpy as np
import pytest

from madsim_tpu import (NetConfig, Runtime, Scenario, SimConfig,
                        divergence_profile, explain_crash, fuzz, ms, sec,
                        summarize)
from madsim_tpu.core import types as T
from madsim_tpu.core.state import TRACE_FIELDS as _TRACE_FIELDS
from madsim_tpu.models.pingpong import PingPong, state_spec
from madsim_tpu.obs import (causal_fingerprint, code_fingerprint,
                            export_chrome_trace, fingerprints_match,
                            happens_before, ring_records,
                            sketch_divergence, to_chrome_events)
from madsim_tpu.parallel.stats import first_divergence_slots
from madsim_tpu.search.corpus import Corpus
from madsim_tpu.search.mutate import KnobPlan


def _pingpong_rt(trace_cap=0, sketch_slots=0, sketch_every=64, target=3,
                 n_nodes=2, scenario=None, loss=0.0):
    cfg = SimConfig(n_nodes=n_nodes, time_limit=sec(5), trace_cap=trace_cap,
                    sketch_slots=sketch_slots, sketch_every=sketch_every,
                    net=NetConfig(packet_loss_rate=loss,
                                  send_latency_min=ms(1),
                                  send_latency_max=ms(4)))
    return Runtime(cfg, [PingPong(n_nodes, target=target)], state_spec(),
                   scenario=scenario)


def _crashrich_wal_kv(trace_cap=0, sketch_slots=0):
    """The crash-rich wal_kv chaos matrix. bench owns the ONE canonical
    definition (the r9 rule: tests exercise exactly the workload the
    bench measures — test_search imports its saturating runtime the
    same way), so retuning the bench can't silently fork this test."""
    from bench import _make_crashrich_runtime
    return _make_crashrich_runtime("wal_kv", trace_cap=trace_cap,
                                   sketch_slots=sketch_slots)


def _nontrace_state(state) -> dict:
    out = {}
    for name in type(state).__dataclass_fields__:
        if name in _TRACE_FIELDS or name in ("node_state", "ext"):
            continue
        out[name] = np.asarray(getattr(state, name))
    for i, leaf in enumerate(__import__("jax").tree.leaves(state.node_state)):
        out[f"node_state_{i}"] = np.asarray(leaf)
    return out


class TestNeverPerturbs:
    """The fast-lane r10 equivalence: lineage + sketch columns never
    perturb the trajectory, leaf for leaf, on both runners."""

    def test_lineage_and_sketch_never_perturb(self):
        seeds = np.arange(16, dtype=np.uint32)
        rt0 = _pingpong_rt()
        base, _ = rt0.run(rt0.init_batch(seeds), 256, 64)
        ref = _nontrace_state(base)
        for cap, sk, lanes in ((8, 0, None), (8, 8, []), (8, 8, [0, 3]),
                               (0, 8, None)):
            rt = _pingpong_rt(trace_cap=cap, sketch_slots=sk,
                              sketch_every=16)
            kw = {} if cap == 0 or lanes is None else dict(
                trace_lanes=lanes)
            st, _ = rt.run(rt.init_batch(seeds, **kw), 256, 64)
            got = _nontrace_state(st)
            assert ref.keys() == got.keys()
            for k in ref:
                assert (ref[k] == got[k]).all(), \
                    f"cap={cap} sketch={sk} lanes={lanes} perturbed {k}"

    def test_fused_equals_chunked_with_lineage_and_sketch(self):
        rt = _pingpong_rt(trace_cap=8, sketch_slots=8, sketch_every=16,
                          target=40)
        seeds = np.arange(8, dtype=np.uint32)
        chunked, _ = rt.run(rt.init_batch(seeds), 256, 64)
        fused = rt.run_fused(rt.init_batch(seeds), 256, 64)
        assert (rt.fingerprints(chunked) == rt.fingerprints(fused)).all()
        for f in _TRACE_FIELDS:
            assert (np.asarray(getattr(chunked, f))
                    == np.asarray(getattr(fused, f))).all(), f

    def test_fingerprints_ignore_lineage_and_sketch(self):
        seeds = np.arange(8, dtype=np.uint32)
        on = _pingpong_rt(trace_cap=8, sketch_slots=4)
        off = _pingpong_rt()
        a, _ = on.run(on.init_batch(seeds), 256, 64)
        b, _ = off.run(off.init_batch(seeds), 256, 64)
        assert (on.fingerprints(a) == off.fingerprints(b)).all()


class TestLineage:
    def test_parent_edges_resolve_and_precede(self):
        rt = _pingpong_rt(trace_cap=128, target=12)
        st = rt.run_fused(rt.init_batch(np.arange(2, dtype=np.uint32)),
                          256, 64)
        recs = ring_records(st, lane=0)
        assert "parent" in recs and "lamport" in recs
        edges = happens_before(recs)
        assert edges, "no resolvable happens-before edges"
        for p, c in edges:
            assert p < c, (p, c)
        # nothing dropped (cap > steps), so every non-external parent
        # resolves: the ring IS the full happens-before DAG here
        steps = set(recs["step"].tolist())
        for par, s in zip(recs["parent"], recs["step"]):
            assert par == -1 or int(par) in steps, (par, s)
        # the t=0 boots are external causes
        assert int(recs["parent"][0]) == -1

    def test_lamport_clocks_respect_happens_before(self):
        rt = _pingpong_rt(trace_cap=128, target=12)
        st = rt.run_fused(rt.init_batch(np.arange(2, dtype=np.uint32)),
                          256, 64)
        recs = ring_records(st, lane=1)
        by_step = {int(s): i for i, s in enumerate(recs["step"])}
        for p, c in happens_before(recs):
            assert (recs["lamport"][by_step[p]]
                    < recs["lamport"][by_step[c]]), (p, c)

    def test_explain_crash_chain_ends_at_crash_dispatch(self):
        rt = _crashrich_wal_kv(trace_cap=128)
        seeds = np.arange(24, dtype=np.uint32)
        st = rt.run_fused(rt.init_batch(seeds), 4096, 512)
        crashed = np.nonzero(np.asarray(st.crashed))[0]
        assert crashed.size, "crash-rich matrix produced no crash"
        lane = int(crashed[0])
        exp = explain_crash(st, lane)
        assert exp["crashed"] and exp["chain"]
        assert exp["crash_code"] == int(np.asarray(st.crash_code)[lane])
        assert (exp["chain"][-1]["step"]
                == int(np.asarray(st.steps)[lane]) - 1)
        # chain is causally ordered and linked: each record's parent is
        # the previous record's step
        steps = [c["step"] for c in exp["chain"]]
        assert steps == sorted(steps)
        for prev, cur in zip(exp["chain"], exp["chain"][1:]):
            assert cur["parent"] == prev["step"]
        lams = [c["lamport"] for c in exp["chain"]]
        assert lams == sorted(lams) and len(set(lams)) == len(lams)
        assert exp["truncated"] or exp["root_external"]

    def test_chain_truncates_after_wrap(self):
        # tiny ring on a long run: the walk must stop at the wrap
        # horizon and SAY so, not resolve a parent to a wrong record
        rt = _pingpong_rt(trace_cap=4, target=40)
        st = rt.run_fused(rt.init_batch(np.arange(1, dtype=np.uint32)),
                          512, 64)
        recs = ring_records(st, lane=0)
        assert recs["dropped"] > 0
        exp = explain_crash(st, 0)
        assert exp["chain"]
        assert len(exp["chain"]) <= 4
        assert exp["truncated"] or exp["root_external"]

    def test_wrap_preserves_lineage_tail(self):
        # the small ring's surviving records must equal the tail of a
        # big ring's — parent/lamport included (dispatch indices, not
        # slot indices, so wrap cannot skew them)
        seeds = np.arange(2, dtype=np.uint32)
        small = _pingpong_rt(trace_cap=4, target=40)
        big = _pingpong_rt(trace_cap=128, target=40)
        ss = small.run_fused(small.init_batch(seeds), 256, 64)
        sb = big.run_fused(big.init_batch(seeds), 256, 64)
        rs, rb = ring_records(ss, 0), ring_records(sb, 0)
        n = len(rs["now"])
        for col in ("step", "parent", "lamport", "now", "tag"):
            assert (rs[col] == rb[col][-n:]).all(), col

    def test_explain_crash_requires_lineage(self):
        rt = _pingpong_rt(trace_cap=0)
        st, _ = rt.run(rt.init_batch(np.arange(2)), 128, 64)
        with pytest.raises(ValueError, match="compiled out"):
            explain_crash(st, 0)

    def test_injected_op_is_external(self):
        rt = _pingpong_rt(trace_cap=64, target=40)
        st = rt.init_batch(np.arange(1, dtype=np.uint32))
        st, _ = rt.run(st, 64, 32)
        st = rt.kill(st, 1)
        st, _ = rt.run(st, 64, 32)
        recs = ring_records(st, 0)
        kills = np.nonzero((recs["kind"] == T.EV_SUPER)
                           & (recs["tag"] == T.OP_KILL))[0]
        assert kills.size, "injected kill never dispatched"
        assert (recs["parent"][kills] == -1).all()


class TestCausalFingerprint:
    """(r11) crash-dedup fingerprints over explain_crash chains: lane-
    and wrap-invariant, matched by deepest common suffix so a chain
    truncated at different ring-wrap points stays ONE bucket."""

    def _exp(self, toks, code=301, node=2, truncated=False,
             root_external=True, step0=0, now_scale=10):
        chain = [dict(step=step0 + i, now=(step0 + i) * now_scale,
                      kind=k, node=n, src=s, tag=t,
                      parent=step0 + i - 1, lamport=i + 1)
                 for i, (k, n, s, t) in enumerate(toks)]
        return dict(chain=chain, truncated=truncated,
                    root_external=root_external, crashed=True,
                    crash_code=code, crash_node=node, lane=0, dropped=0)

    TOKS = [(1, 0, 0, 5), (2, 1, 0, 7), (2, 0, 1, 7), (3, 1, 1, 2),
            (2, 2, 1, 7)]

    def test_lane_invariant(self):
        # same causal content at different steps/times/lane: same key
        a = causal_fingerprint(self._exp(self.TOKS))
        b = causal_fingerprint(self._exp(self.TOKS, step0=500,
                                         now_scale=77))
        assert a["key"] == b["key"]

    def test_content_sensitive(self):
        a = causal_fingerprint(self._exp(self.TOKS))
        other = [*self.TOKS[:-1], (3, 0, 1, 2)]   # different crash node
        assert a["key"] != causal_fingerprint(self._exp(other))["key"]
        assert a["key"] != causal_fingerprint(
            self._exp(self.TOKS, code=302))["key"]

    def test_wrap_points_do_not_split_buckets(self):
        """The satellite contract: one bug truncated at DIFFERENT wrap
        points matches via the deepest common suffix."""
        full = causal_fingerprint(self._exp(self.TOKS))
        cuts = [causal_fingerprint(self._exp(
            self.TOKS[k:], truncated=True, root_external=False))
            for k in (1, 2, 3)]
        for cut in cuts:
            assert fingerprints_match(full, cut)
            assert fingerprints_match(cut, full)
        for a in cuts:
            for b in cuts:
                assert fingerprints_match(a, b)

    def test_different_bugs_do_not_merge(self):
        a = causal_fingerprint(self._exp(self.TOKS))
        # two COMPLETE chains of different length are different bugs
        # even though one's tokens are the other's suffix
        b = causal_fingerprint(self._exp(self.TOKS[1:]))
        assert a["complete"] and b["complete"]
        assert not fingerprints_match(a, b)
        # a CUT chain longer than a complete chain cannot be it either
        short_full = causal_fingerprint(self._exp(self.TOKS[3:]))
        long_cut = causal_fingerprint(self._exp(
            self.TOKS[1:], truncated=True, root_external=False))
        assert not fingerprints_match(short_full, long_cut)
        # ... nor a cut chain of EQUAL depth: a cut chain always hides
        # at least one more record than it shows, so a same-bug cut
        # observation is strictly shorter than the complete history
        equal_cut = causal_fingerprint(self._exp(
            self.TOKS[3:], truncated=True, root_external=False))
        assert not fingerprints_match(short_full, equal_cut)
        assert not fingerprints_match(equal_cut, short_full)
        # and different suffix content never matches
        other = [*self.TOKS[:-1], (3, 3, 1, 2)]
        assert not fingerprints_match(a, causal_fingerprint(self._exp(
            other, truncated=True, root_external=False)))

    def test_depth_cap_bounds_resolution(self):
        deep_a = [(1, 0, 0, 9)] * 4 + self.TOKS
        deep_b = [(1, 1, 1, 3)] * 4 + self.TOKS
        a = causal_fingerprint(self._exp(deep_a), depth=5)
        b = causal_fingerprint(self._exp(deep_b), depth=5)
        assert a["key"] == b["key"]       # differ only past the horizon
        assert not a["complete"] and a["depth"] == 5

    def test_code_fingerprint_fallback(self):
        fp = code_fingerprint(301, 2)
        assert fp["kind"] == "code" and fp["depth"] == 0
        assert fingerprints_match(fp, code_fingerprint(301, 2))
        assert not fingerprints_match(fp, code_fingerprint(302, 2))
        # code fingerprints never suffix-match causal ones
        assert not fingerprints_match(
            fp, causal_fingerprint(self._exp(self.TOKS)))

    def test_empty_chain_rejected(self):
        with pytest.raises(ValueError, match="empty"):
            causal_fingerprint(dict(chain=[], truncated=False,
                                    root_external=False, crash_code=1,
                                    crash_node=0))

    def test_ring_wrap_stability_on_real_rings(self):
        """Ground the synthetic contract in the engine: the same
        trajectory recorded through a 4-slot ring (wrapped, truncated
        chain) and a 128-slot ring (full chain) fingerprints into the
        same bucket."""
        seeds = np.arange(2, dtype=np.uint32)
        small = _pingpong_rt(trace_cap=4, target=40)
        big = _pingpong_rt(trace_cap=128, target=40)
        ss = small.run_fused(small.init_batch(seeds), 256, 64)
        sb = big.run_fused(big.init_batch(seeds), 256, 64)
        for lane in range(2):
            es, eb = explain_crash(ss, lane), explain_crash(sb, lane)
            assert len(es["chain"]) <= len(eb["chain"])
            fs = causal_fingerprint(es)
            fb = causal_fingerprint(eb)
            assert fingerprints_match(fs, fb), (lane, fs, fb)


class TestSketch:
    def test_sketch_slots_fill_in_order(self):
        rt = _pingpong_rt(trace_cap=0, sketch_slots=4, sketch_every=8,
                          target=40)
        st = rt.run_fused(rt.init_batch(np.arange(2, dtype=np.uint32)),
                          256, 64)
        sk = np.asarray(st.cov_sketch)
        steps = np.asarray(st.steps)
        for lane in range(2):
            filled = min(int(steps[lane]) // 8, 4)
            assert (sk[lane, :filled] != 0).all()
            assert (sk[lane, filled:] == 0).all()

    def test_identical_seeds_identical_sketches(self):
        rt = _pingpong_rt(sketch_slots=4, sketch_every=8, loss=0.2,
                          n_nodes=4, target=6)
        st = rt.run_fused(rt.init_batch(np.asarray([7, 7, 9], np.uint32)),
                          512, 64)
        sk = np.asarray(st.cov_sketch)
        assert (sk[0] == sk[1]).all()
        assert (sk[2] != sk[0]).any()
        d = sketch_divergence(st, 0, 1)
        assert d["slot"] == d["slots"]          # never diverged
        assert sketch_divergence(st, 0, 2)["slot"] < d["slots"]

    def test_first_divergence_slots_math(self):
        sk = np.array([[1, 2, 3],
                       [1, 2, 3],
                       [1, 9, 9],
                       [8, 8, 8]], np.uint32)
        first = first_divergence_slots(sk)
        # consensus prefix is [1, 2, 3] (modal per slot)
        assert first.tolist() == [3, 3, 1, 0]

    def test_divergence_profile_in_summarize(self):
        rt = _pingpong_rt(sketch_slots=8, sketch_every=8, loss=0.2,
                          n_nodes=4, target=6)
        seeds = np.arange(16, dtype=np.uint32)
        st = rt.run_fused(rt.init_batch(seeds), 512, 64)
        rep = summarize(rt, st, seeds=seeds)
        prof = rep["first_divergence"]
        assert prof is not None and prof["diverged"] > 0
        assert prof["every"] == 8 and prof["slots"] == 8
        assert prof["p10"] <= prof["p50"] <= prof["p90"]
        assert rep["first_divergence"] == divergence_profile(st)
        # compiled-out build reports None, not a fake zero profile
        rt0 = _pingpong_rt(n_nodes=4, target=6)
        st0, _ = rt0.run(rt0.init_batch(seeds), 512, 64)
        assert summarize(rt0, st0)["first_divergence"] is None


class TestDivergenceEnergy:
    def _plan(self):
        sc = Scenario()
        sc.at(ms(40)).kill_random()
        sc.at(ms(400)).restart_random()
        rt = _pingpong_rt(n_nodes=4, target=6, scenario=sc,
                          sketch_slots=4)
        return KnobPlan.from_runtime(rt)

    def test_early_divergence_boosts_admission_energy(self):
        plan = self._plan()
        corpus = Corpus(plan, rng=np.random.default_rng(0), div_bonus=1.0)
        knobs = KnobPlan.stack([plan.base_knobs() for _ in range(3)])
        sketches = np.array([[1, 2, 3, 4],      # consensus
                             [1, 2, 9, 9],      # diverges at slot 2
                             [7, 7, 7, 7]],     # diverges at slot 0
                            np.uint32)
        corpus.observe(knobs, seeds=np.arange(3),
                       hashes_u64=np.arange(10, 13, dtype=np.uint64),
                       crashed=np.zeros(3, bool), codes=np.zeros(3),
                       parent_ids=np.full(3, -1), round_no=0,
                       sketches=sketches)
        e = {en["div_slot"]: en["energy"] for en in corpus.entries}
        assert e[0] > e[2] > e[4]               # earlier split = hotter
        assert e[4] == 1.0                      # consensus lane: no bonus

    def test_div_bonus_zero_is_hash_only(self):
        plan = self._plan()
        corpus = Corpus(plan, rng=np.random.default_rng(0), div_bonus=0.0)
        knobs = KnobPlan.stack([plan.base_knobs() for _ in range(2)])
        corpus.observe(knobs, seeds=np.arange(2),
                       hashes_u64=np.arange(2, dtype=np.uint64),
                       crashed=np.zeros(2, bool), codes=np.zeros(2),
                       parent_ids=np.full(2, -1), round_no=0,
                       sketches=np.array([[1, 2], [3, 4]], np.uint32))
        assert all(en["energy"] == 1.0 for en in corpus.entries)

    def test_fuzz_threads_sketches_into_corpus(self):
        sc = Scenario()
        sc.at(ms(40)).kill_random()
        sc.at(ms(400)).restart_random()
        rt = _pingpong_rt(n_nodes=4, target=6, scenario=sc,
                          sketch_slots=4, sketch_every=16)
        from madsim_tpu.obs import JsonlObserver
        obs = JsonlObserver(io.StringIO())
        corpus = Corpus(KnobPlan.from_runtime(rt),
                        rng=np.random.default_rng(0))
        fuzz(rt, max_steps=512, batch=16, max_rounds=2, dry_rounds=3,
             chunk=128, corpus=corpus, observer=obs)
        assert any(e["div_slot"] is not None for e in corpus.entries)
        rounds = [r for r in obs.records if r["kind"] == "fuzz_round"]
        assert all("div_slot_p50" in r for r in rounds)


class TestFlowExport:
    def test_flow_events_golden(self):
        # hand-built lineage ring -> exact JSON: three dispatches where
        # step 5 (a boot, external) enqueued 6, and 6 enqueued 7
        recs = dict(now=np.array([100, 300, 900]),
                    step=np.array([5, 6, 7]),
                    kind=np.array([T.EV_SUPER, T.EV_MSG, T.EV_TIMER]),
                    node=np.array([0, 1, 1]),
                    src=np.array([0, 0, 1]),
                    tag=np.array([T.OP_INIT, 7, 3]),
                    parent=np.array([-1, 5, 6]),
                    lamport=np.array([1, 2, 3]))
        evs = to_chrome_events(recs)
        assert evs == [
            {"name": "SUPER:INIT", "ph": "i", "s": "t", "ts": 100,
             "pid": 0, "tid": 0,
             "args": {"src": 0, "tag": T.OP_INIT, "step": 5,
                      "lamport": 1, "parent": -1}},
            {"name": "MSG:tag7", "ph": "i", "s": "t", "ts": 300,
             "pid": 0, "tid": 1,
             "args": {"src": 0, "tag": 7, "step": 6, "lamport": 2,
                      "parent": 5}},
            {"name": "TIMER:tag3", "ph": "i", "s": "t", "ts": 900,
             "pid": 0, "tid": 1,
             "args": {"src": 1, "tag": 3, "step": 7, "lamport": 3,
                      "parent": 6}},
            {"name": "causal", "cat": "causal", "id": 6, "pid": 0,
             "ph": "s", "ts": 100, "tid": 0},
            {"name": "causal", "cat": "causal", "id": 6, "pid": 0,
             "ph": "f", "bp": "e", "ts": 300, "tid": 1},
            {"name": "causal", "cat": "causal", "id": 7, "pid": 0,
             "ph": "s", "ts": 300, "tid": 1},
            {"name": "causal", "cat": "causal", "id": 7, "pid": 0,
             "ph": "f", "bp": "e", "ts": 900, "tid": 1},
        ]

    def test_ring_export_contains_paired_flows(self, tmp_path):
        rt = _pingpong_rt(trace_cap=128, target=12)
        st = rt.run_fused(rt.init_batch(np.arange(2, dtype=np.uint32)),
                          256, 64)
        p = str(tmp_path / "t.json")
        n = export_chrome_trace(p, state=st, lane=0)
        with open(p) as f:
            doc = json.load(f)
        inst = [e for e in doc["traceEvents"] if e["ph"] == "i"]
        assert n == len(inst)                  # flows not counted
        for e in inst:
            assert {"step", "lamport", "parent"} <= set(e["args"])
        flows = [e for e in doc["traceEvents"] if e["ph"] in ("s", "f")]
        assert flows
        starts = {e["id"] for e in flows if e["ph"] == "s"}
        ends = {e["id"] for e in flows if e["ph"] == "f"}
        assert starts == ends
        # flow count matches the resolvable happens-before edges
        assert len(flows) == 2 * len(happens_before(ring_records(st, 0)))

    def test_stream_export_carries_step_only(self):
        # collect_events records have no lineage columns; their args
        # carry the dispatch index (k-th fired record IS dispatch k)
        rt = _pingpong_rt(target=3)
        _, events = rt.run(rt.init_batch(np.arange(2)), 256, 64,
                           collect_events=True)
        evs = to_chrome_events(events, b=0)
        assert [e["args"]["step"] for e in evs] == list(range(len(evs)))
        assert all("parent" not in e["args"] for e in evs)
        assert all(e["ph"] == "i" for e in evs)


@pytest.mark.slow
class TestChaosMatrixEquivalence:
    """The full-matrix r10 never-perturb contract: flagship chaos
    workloads with lineage + sketch compiled in but masked off are
    leaf-for-leaf identical to the compiled-out build, on the chunked
    AND fused runners (the fast lane keeps the single-config pingpong
    check; this is the raft/wal_kv analog of test_obs's ring sweeps)."""

    def _assert_off_on_equal(self, make_rt, seeds, max_steps, chunk):
        rt0 = make_rt(0, 0)
        rt1 = make_rt(16, 8)
        ref, _ = rt0.run(rt0.init_batch(seeds), max_steps, chunk)
        for runner in ("run", "run_fused"):
            if runner == "run":
                st, _ = rt1.run(rt1.init_batch(seeds, trace_lanes=[]),
                                max_steps, chunk)
            else:
                st = rt1.run_fused(rt1.init_batch(seeds, trace_lanes=[]),
                                   max_steps, chunk)
            a, b = _nontrace_state(ref), _nontrace_state(st)
            assert a.keys() == b.keys()
            for k in a:
                assert (a[k] == b[k]).all(), (runner, k)

    def test_raft_chaos_matrix(self):
        from madsim_tpu.models.raft import make_raft_runtime

        def make(cap, sk):
            cfg = SimConfig(n_nodes=5, event_capacity=128,
                            time_limit=sec(3), trace_cap=cap,
                            sketch_slots=sk,
                            net=NetConfig(packet_loss_rate=0.05,
                                          send_latency_min=ms(1),
                                          send_latency_max=ms(10)))
            sc = Scenario()
            sc.at(sec(1)).kill_random()
            sc.at(sec(1) + ms(400)).restart_random()
            return make_raft_runtime(5, 8, n_cmds=4, scenario=sc, cfg=cfg)

        self._assert_off_on_equal(make, np.arange(64, dtype=np.uint32),
                                  1500, 256)

    def test_wal_kv_chaos_matrix(self):
        def make(cap, sk):
            return _crashrich_wal_kv(trace_cap=cap, sketch_slots=sk)

        self._assert_off_on_equal(make, np.arange(64, dtype=np.uint32),
                                  4096, 512)
