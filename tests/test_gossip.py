"""Gossip dissemination under faults + propagation-time statistics."""

import numpy as np
import pytest

pytestmark = pytest.mark.slow  # chaos-sweep-heavy (r7 durations triage);
# tier-1/ci.sh fast skip it so the fast lane fits its 870s budget cold

from madsim_tpu import Scenario, SimConfig, NetConfig, ms, sec
from madsim_tpu.harness.simtest import run_seeds
from madsim_tpu.models.gossip import make_gossip_runtime

SEEDS = np.arange(16)


class TestGossip:
    def test_full_dissemination_clean(self):
        rt = make_gossip_runtime(n_nodes=8, n_rumors=4)
        state = run_seeds(rt, SEEDS, max_steps=20_000)
        have = np.asarray(state.node_state["have"])
        assert (have == 15).all()
        # propagation-time distribution exists and varies across seeds
        t_inf = np.asarray(state.node_state["infected_at"])
        assert (t_inf >= 0).all()
        assert len(set(np.asarray(state.now).tolist())) > 4

    def test_dissemination_through_partition_heal(self):
        cfg = SimConfig(n_nodes=8, event_capacity=192, time_limit=sec(20),
                        net=NetConfig(packet_loss_rate=0.2))
        sc = Scenario()
        sc.at(ms(0)).partition([0])   # isolate the origin immediately
        sc.at(sec(2)).heal()
        rt = make_gossip_runtime(n_nodes=8, n_rumors=4, scenario=sc, cfg=cfg)
        state = run_seeds(rt, SEEDS, max_steps=40_000)
        have = np.asarray(state.node_state["have"])
        assert (have == 15).all()
        # a single push carries the full digest, so a pre-cut crossing can
        # seed the other side (t=0 tie-break race) — but for these fixed
        # seeds the cut must delay most trajectories past the heal
        delayed = (np.asarray(state.now) > sec(2))
        assert delayed.mean() >= 0.75, delayed

    def test_64_node_cluster_with_multiword_partition(self):
        # width test: a 64-node cluster — node ids span THREE payload
        # words — survives a partition whose membership mask and a
        # random-kill pool both live beyond word 0 (the r3 multi-word
        # packing), and still fully disseminates after the heal
        n = 64
        cfg = SimConfig(n_nodes=n, event_capacity=640, time_limit=sec(60),
                        net=NetConfig(packet_loss_rate=0.05))
        sc = Scenario()
        sc.at(ms(50)).partition(range(32, 64))     # words 1-2 membership
        sc.at(ms(80)).kill_random(among=range(40, 48))  # pool in word 1
        sc.at(sec(2)).heal()
        sc.at(sec(2) + ms(100)).restart_random()
        rt = make_gossip_runtime(n_nodes=n, n_rumors=4, scenario=sc,
                                 cfg=cfg, require_all_alive=True)
        state = run_seeds(rt, np.arange(8), max_steps=120_000)
        have = np.asarray(state.node_state["have"])
        assert (have == 15).all()
        # the cut delayed dissemination past the heal in most lanes —
        # i.e. the word-1/2 partition mask actually bit
        assert (np.asarray(state.now) > sec(2)).mean() >= 0.75

    def test_restart_gets_reinfected(self):
        # kill mid-dissemination and restart shortly after: the restarted
        # node comes back AMNESIC (volatile state) and must be re-infected
        # for the run to halt — this exercises the full recovery path
        # (init re-arms the gossip timer, peers re-push)
        sc = Scenario()
        sc.at(ms(30)).kill_random(among=range(1, 8))   # not the origin
        sc.at(ms(200)).restart_random()
        rt = make_gossip_runtime(n_nodes=8, n_rumors=4, scenario=sc,
                                 require_all_alive=True)
        state = run_seeds(rt, SEEDS, max_steps=40_000)
        have = np.asarray(state.node_state["have"])
        alive = np.asarray(state.alive)
        assert alive.all()              # every victim restarted
        assert (have == 15).all()       # ...and was re-infected
