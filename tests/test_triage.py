"""Campaign triage plane (r18): snapshots, diffs, attribution, audits.

Load-bearing contracts (DESIGN §19):
(1) snapshot IDENTITY — the snapshot body is a pure function of the
store's durable contents: same store -> byte-identical bytes, no
wall-clock fields, and triage_diff(s, s) is provably empty;
(2) bucket LIFECYCLE — a planted bucket classifies `new`, a removed or
newly-quiet one `stale`, a quiet-then-reobserved one `regressed`;
(3) attribution ACCOUNTING — per-recipe and per-operator attributions
each sum EXACTLY to their totals over the frozen grayfail_mix
regression corpus, with unattributable rows in an explicit `base`
class (zero silent leakage);
(4) the repro-health audit records a planted failing handle as `fail`
(and a broken one as `flaky`) WITHOUT aborting the sweep;
(5) the satellite fixes hold: bucket observations dedup by
(fingerprint, worker, round), and a finished campaign's last-syncing
worker is never flagged stale;
(6) per-node hasher seeding (the r18 robustness satellite): a node's
hash stream is a pure (seed, node) function — schedule-independent,
node-decoupled, and consuming it never moves any other draw.
"""

import json
import os
import shutil

import jax.numpy as jnp
import numpy as np
import pytest

from madsim_tpu import (NetConfig, Program, Runtime, Scenario, SimConfig,
                        fuzz, ms)
from madsim_tpu.core import prng
from madsim_tpu.obs.causal import causal_fingerprint
from madsim_tpu.obs.dashboard import render_html, sparkline_svg
from madsim_tpu.runtime.scenario import (RECIPE_FAMILIES, classify_recipe,
                                         row_recipe_class)
from madsim_tpu.search.corpus import YIELD_NAMES
from madsim_tpu.search.mutate import KnobPlan
from madsim_tpu.service import (CorpusStore, CrashBuckets, audit_buckets,
                                campaign_stats, campaign_timeline,
                                merged_buckets, store_signature,
                                triage_diff, triage_snapshot)
from madsim_tpu.service.triage import (BASE_CLASS, classify_knobs,
                                       list_snapshots, load_audit,
                                       load_snapshot, snapshot_path)

FROZEN = os.path.join(os.path.dirname(__file__), "data",
                      "regression_corpus", "grayfail_mix")


@pytest.fixture()
def frozen(tmp_path):
    """A writable copy of the committed grayfail_mix campaign (the
    frozen store itself must stay byte-pristine — triage writes a
    triage/ subdir into the store)."""
    dst = tmp_path / "grayfail_mix"
    shutil.copytree(FROZEN, dst)
    return CorpusStore(str(dst), create=False)


@pytest.fixture(scope="module")
def grayfail_plan():
    """The frozen campaign's KnobPlan (REGRESSION.json: factory mix,
    dup_slots 2) — row-table source for attribution. Construction only;
    nothing compiles."""
    from bench import _make_grayfail_runtime
    rt = _make_grayfail_runtime("mix")
    return KnobPlan.from_runtime(rt, dup_slots=2)


def _snap_bytes(store, n):
    with open(snapshot_path(store, n), "rb") as f:
        return f.read()


def _plant_bucket(store, knobs, *, code=999, seed=12345, round_no=9,
                  worker_id=0, tok=77):
    """Open a bucket with a deliberately DISTINCT causal fingerprint
    (unique token chain) + a real knobs npz + one observation line —
    the diff's planted `new` bucket."""
    chain = [dict(step=i, now=i * 10, kind=1, node=0, src=0,
                  tag=tok + i, parent=i - 1, lamport=i + 1)
             for i in range(3)]
    fp = causal_fingerprint(dict(
        chain=chain, truncated=False, root_external=True, crashed=True,
        crash_code=code, crash_node=0, lane=0, dropped=0))
    bk = CrashBuckets(store)
    key, opened = bk.observe(fp, seed=seed, knobs=knobs,
                             round_no=round_no, worker_id=worker_id,
                             chain=chain)
    assert opened
    return key


# ---------------------------------------------------------------------------
# (1) snapshot identity
# ---------------------------------------------------------------------------

class TestSnapshotIdentity:
    def test_same_store_twice_byte_identical(self, frozen, grayfail_plan):
        frozen.write_triage_rows(grayfail_plan)
        n1, _ = triage_snapshot(frozen)
        n2, _ = triage_snapshot(frozen)
        assert n2 == n1 + 1
        b1, b2 = _snap_bytes(frozen, n1), _snap_bytes(frozen, n2)
        assert b1 == b2
        # and a FRESH handle over the same dir (cold caches) agrees
        n3, _ = triage_snapshot(CorpusStore(frozen.dir, create=False))
        assert _snap_bytes(frozen, n3) == b1

    def test_no_wallclock_fields(self, frozen):
        _n, body = triage_snapshot(frozen)
        blob = json.dumps(body)
        assert "created_at" not in blob and "measured_at" not in blob

    def test_self_diff_is_empty(self, frozen):
        _n1, s1 = triage_snapshot(frozen)
        _n2, s2 = triage_snapshot(frozen)
        d = triage_diff(s1, s2)
        assert d["empty"]
        assert not any(d["buckets"].values())
        assert d["coverage"] == dict(added=0, removed=0)
        assert not any(d["attribution"].values())
        assert not d["workers"] and not d["audit"] and not d["p99"]
        # literal self-diff too
        assert triage_diff(s1, s1)["empty"]

    def test_history_numbers_monotonic(self, frozen):
        ns = [triage_snapshot(frozen)[0] for _ in range(3)]
        assert ns == sorted(ns)
        assert list_snapshots(frozen)[-3:] == ns
        assert load_snapshot(frozen, "last")["store"]["entries"] == 256
        assert load_snapshot(frozen, "prev") == load_snapshot(frozen,
                                                              ns[-2])


# ---------------------------------------------------------------------------
# (2) bucket lifecycle
# ---------------------------------------------------------------------------

class TestLifecycle:
    def test_planted_bucket_new_and_removed_stale(self, frozen,
                                                  grayfail_plan):
        frozen.write_triage_rows(grayfail_plan)
        _n, before = triage_snapshot(frozen)
        key = _plant_bucket(
            frozen, grayfail_plan.base_knobs())   # observe() logs too
        _n, after = triage_snapshot(frozen)
        d = triage_diff(before, after)
        assert d["buckets"]["new"] == [key]
        assert not d["empty"]
        # the planted bucket classifies by its knob vector, not "other"
        assert after["buckets"][key]["recipe"] in RECIPE_FAMILIES
        # removed -> stale (diff the other way)
        d_rev = triage_diff(after, before)
        assert key in d_rev["buckets"]["stale"]
        assert d_rev["buckets"]["new"] == []

    def _mini(self, max_round, buckets):
        return dict(
            store=dict(max_round=max_round, entries=0, coverage_total=0,
                       buckets_total=len(buckets),
                       crash_observations=0, workers={}),
            coverage=dict(keys=[]), buckets=buckets,
            attribution={}, workers_health={}, audit={},
            quiet_rounds=2)

    def _b(self, obs, last_round, key="k1"):
        return dict(crash_code=1, crash_node=0, members=[key],
                    observations=obs, first_round=0, last_round=last_round,
                    workers=[0], recipe="none", op="base",
                    repro=dict(seed=0, round=0, worker_id=0),
                    minimized=False)

    def test_quiet_then_reobserved_is_regressed(self):
        prev = self._mini(10, {"k1": self._b(3, 2)})   # quiet: 10-2 >= 2
        cur = self._mini(12, {"k1": self._b(4, 12)})
        d = triage_diff(prev, cur)
        assert d["buckets"]["regressed"] == ["k1"]
        assert d["buckets"]["grew"] == []

    def test_active_and_growing_is_grew(self):
        prev = self._mini(3, {"k1": self._b(3, 2)})    # active at prev
        cur = self._mini(5, {"k1": self._b(4, 5)})
        d = triage_diff(prev, cur)
        assert d["buckets"]["grew"] == ["k1"]
        assert d["buckets"]["regressed"] == []

    def test_newly_quiet_is_stale(self):
        prev = self._mini(2, {"k1": self._b(3, 2)})    # active at prev
        cur = self._mini(9, {"k1": self._b(3, 2)})     # quiet at cur
        d = triage_diff(prev, cur)
        assert d["buckets"]["stale"] == ["k1"]
        # still quiet on both sides -> no lifecycle event
        d2 = triage_diff(cur, cur)
        assert d2["empty"]

    def test_canonical_reelection_not_new_plus_stale(self):
        """A deeper member arriving can re-elect a merged bucket's
        canonical key; member overlap must keep it ONE bug."""
        prev = self._mini(3, {"k1": self._b(2, 3)})
        deeper = self._b(3, 4, key="k2")
        deeper["members"] = ["k2", "k1"]
        cur = self._mini(4, {"k2": deeper})
        d = triage_diff(prev, cur)
        assert d["buckets"]["new"] == []
        assert d["buckets"]["stale"] == []
        assert d["buckets"]["grew"] == ["k2"]


# ---------------------------------------------------------------------------
# (3) attribution accounting (the frozen regression corpus)
# ---------------------------------------------------------------------------

class TestAttribution:
    def test_sums_exact_on_frozen_corpus(self, frozen, grayfail_plan):
        frozen.write_triage_rows(grayfail_plan)
        _n, s = triage_snapshot(frozen)
        a = s["attribution"]
        assert a["rows_known"]
        # recipe side: every DISTINCT coverage key exactly once
        assert sum(a["recipe_coverage"].values()) \
            == s["store"]["coverage_total"] == 256
        # operator side: every committed admission exactly once
        assert sum(a["operator_coverage"].values()) \
            == s["store"]["entries"] == 256
        # bucket side: every merged bucket exactly once, both dims
        assert sum(a["recipe_buckets"].values()) \
            == s["store"]["buckets_total"] == 4
        assert sum(a["operator_buckets"].values()) == 4
        # no silent classes: only the declared families/operators
        assert set(a["recipe_coverage"]) \
            == set(RECIPE_FAMILIES) | {BASE_CLASS}
        assert set(a["operator_coverage"]) == set(YIELD_NAMES)
        # the mix campaign's gray rows dominate; nothing leaked to base
        assert a["recipe_coverage"][BASE_CLASS] == 0
        assert a["recipe_coverage"]["torn_write"] > 0

    def test_without_rows_everything_is_explicit_base(self, frozen):
        _n, s = triage_snapshot(frozen)           # no ROWS.json written
        a = s["attribution"]
        assert not a["rows_known"]
        assert a["recipe_coverage"][BASE_CLASS] \
            == s["store"]["coverage_total"]
        assert sum(a["recipe_coverage"].values()) \
            == s["store"]["coverage_total"]
        # operator attribution rides op_yield and still works rowless
        assert sum(a["operator_coverage"].values()) == 256

    def test_classifier_respects_knob_state(self, grayfail_plan):
        plan = grayfail_plan
        rows = dict(
            op=[int(x) for x in np.asarray(plan.base["op"])],
            drop_ok=[bool(x) for x in plan.drop_ok],
            torn_ok=[bool(x) for x in plan.torn_ok],
            base_torn=[int(x) for x in
                       np.asarray(plan.base["payload"])[:, -2] & 1])
        kn = plan.base_knobs()
        base_fam = classify_knobs(rows, kn)
        assert base_fam == "torn_write"          # the mix recipe's head
        # flipping the torn flag off every disk row demotes to the next
        # family present
        kn2 = {k: np.array(v) for k, v in kn.items()}
        kn2["row_flag"] = np.where(plan.torn_ok, 0, kn2["row_flag"])
        fam2 = classify_knobs(rows, kn2)
        assert fam2 == "slow_disk"
        # dropping EVERY droppable row leaves only pinned rows -> none
        kn3 = {k: np.array(v) for k, v in kn.items()}
        kn3["row_on"] = ~np.asarray(plan.drop_ok)
        assert classify_knobs(rows, kn3) == "none"
        # no row table -> explicit base
        assert classify_knobs(None, kn) == BASE_CLASS

    def test_row_and_scenario_classifiers(self):
        from madsim_tpu.core import types as T
        assert row_recipe_class(T.OP_SET_DISK, torn=True) == "torn_write"
        assert row_recipe_class(T.OP_SET_DISK) == "slow_disk"
        assert row_recipe_class(T.OP_SET_SKEW) == "clock_skew"
        assert row_recipe_class(T.OP_PARTITION_ONEWAY) == "asym_partition"
        assert row_recipe_class(T.OP_SET_LOSS) == "loss_latency"
        assert row_recipe_class(T.OP_KILL) == "none"
        assert classify_recipe(["none", "clock_skew",
                                "slow_disk"]) == "slow_disk"
        assert classify_recipe([]) == "none"
        from madsim_tpu.runtime import chaos
        sc = chaos.torn_write_kill(ms(10), 1, down=ms(5))
        assert sc.recipe_class() == "torn_write"
        sc2 = chaos.clock_drift(ms(10), 300, node=0)
        assert sc2.recipe_class() == "clock_skew"
        sc3 = Scenario()
        sc3.at(ms(1)).kill(0)
        sc3.at(ms(2)).halt()
        assert sc3.recipe_class() == "none"


# ---------------------------------------------------------------------------
# (4) repro-health audit
# ---------------------------------------------------------------------------

def _crashrich_rt():
    from bench import _make_crashrich_runtime
    return _make_crashrich_runtime("wal_kv", trace_cap=128)


class TestAudit:
    def test_fail_and_flaky_recorded_without_abort(self, tmp_path):
        rt = _crashrich_rt()
        d = str(tmp_path / "campaign")
        res = fuzz(rt, max_steps=3000, batch=16, max_rounds=2,
                   dry_rounds=8, chunk=512, corpus_dir=d, worker_id=0,
                   rng_seed=0)
        assert res["buckets_total"] >= 1, "crashrich campaign found none"
        store = CorpusStore(d, create=False)
        plan = KnobPlan.from_runtime(rt)
        # planted FAILING handle: every droppable chaos row disabled —
        # the replay runs the clean protocol and cannot crash
        benign = plan.base_knobs()
        benign["row_on"] = np.where(plan.drop_ok, False, True)
        fail_key = _plant_bucket(store, benign, code=901, tok=501)
        # planted BROKEN handle: bucket json without its knobs npz
        flaky_key = _plant_bucket(store, benign, code=902, tok=601)
        os.unlink(store.bucket_path(flaky_key, ".npz"))
        out = audit_buckets(rt, store, max_steps=3000, chunk=512,
                            budget=len(store.bucket_keys()))
        by_key = {a["bucket"]: a["status"] for a in out["audited"]}
        assert by_key[fail_key] == "fail"
        assert by_key[flaky_key] == "flaky"
        # the real bucket(s) still replay red — and the sweep finished
        real = [k for k in by_key if k not in (fail_key, flaky_key)]
        assert real and all(by_key[k] == "pass" for k in real)
        # verdicts fold into the next snapshot
        _n, snap = triage_snapshot(store)
        assert snap["audit"][fail_key]["status"] == "fail"
        assert snap["audit"][flaky_key]["status"] == "flaky"

    def test_rotation_cursor_advances(self, tmp_path):
        rt = _crashrich_rt()
        d = str(tmp_path / "c2")
        fuzz(rt, max_steps=3000, batch=16, max_rounds=2, dry_rounds=8,
             chunk=512, corpus_dir=d, worker_id=0, rng_seed=0)
        store = CorpusStore(d, create=False)
        plan = KnobPlan.from_runtime(rt)
        _plant_bucket(store, plan.base_knobs(), code=903, tok=701)
        keys = store.bucket_keys()
        assert len(keys) >= 2
        first = audit_buckets(rt, store, max_steps=3000, chunk=512,
                              budget=1)
        second = audit_buckets(rt, store, max_steps=3000, chunk=512,
                               budget=1)
        assert first["audited"][0]["bucket"] \
            != second["audited"][0]["bucket"]
        # the cursor is the last audited KEY (insertion-stable: a new
        # bucket sorting below it can't make the rotation re-audit)
        assert load_audit(store)["cursor_key"] \
            == second["audited"][0]["bucket"]


# ---------------------------------------------------------------------------
# (5) satellite fixes
# ---------------------------------------------------------------------------

class TestSatelliteFixes:
    def test_bucket_observations_deduped(self, frozen):
        line = dict(kind="crash", bucket="245503b450c447fe",
                    fp_key="245503b450c447fe", crash_code=501, seed=6,
                    round=0, worker_id=0, opened=False)
        base_obs = {m["key"]: m["observations"]
                    for m in merged_buckets(frozen)}
        base_stats = campaign_stats(frozen.dir, store=frozen)
        # a killed worker's resumed round re-appends IDENTICAL lines
        for _ in range(3):
            frozen.append_bucket_log(line)
        obs = {m["key"]: m["observations"] for m in merged_buckets(frozen)}
        assert obs == base_obs                       # replay never counts
        stats = campaign_stats(frozen.dir, store=frozen)
        assert stats["crash_observations"] \
            == base_stats["crash_observations"]
        # a DIFFERENT round of the same worker still counts
        frozen.append_bucket_log(dict(line, round=7))
        obs2 = {m["key"]: m["observations"]
                for m in merged_buckets(frozen)}
        assert obs2["245503b450c447fe"] \
            == base_obs["245503b450c447fe"] + 1
        # and so does another worker in the same round
        frozen.append_bucket_log(dict(line, worker_id=3))
        obs3 = {m["key"]: m["observations"]
                for m in merged_buckets(frozen)}
        assert obs3["245503b450c447fe"] \
            == base_obs["245503b450c447fe"] + 2

    def test_finished_campaign_worker_not_stale(self, tmp_path):
        rt_dir = str(tmp_path / "tl")
        store = CorpusStore(rt_dir, signature=["sig"])
        for i in range(4):
            store.append_metrics(0, dict(t=100.0 + 10 * i, worker=0,
                                         rounds_done=i + 1, coverage=i,
                                         wall_s=1.0 * i))
        # long after the campaign finished, from a wall-clock `now`:
        # the single worker IS the newest activity -> healthy
        tl = campaign_timeline(store, now=99999.0)
        h = tl["workers_health"]["w0000"]
        assert not h["stale"]
        assert h["age_s"] > 0                  # age still reports vs now

    def test_worker_behind_campaign_activity_is_stale(self, tmp_path):
        rt_dir = str(tmp_path / "tl2")
        store = CorpusStore(rt_dir, signature=["sig"])
        for i in range(4):
            store.append_metrics(0, dict(t=100.0 + 10 * i, worker=0,
                                         rounds_done=i + 1, coverage=i))
        for i in range(40):
            store.append_metrics(1, dict(t=100.0 + 10 * i, worker=1,
                                         rounds_done=i + 1, coverage=i))
        tl = campaign_timeline(store)
        assert tl["workers_health"]["w0000"]["stale"]
        assert not tl["workers_health"]["w0001"]["stale"]


class TestSuperviseHooks:
    def test_segments_accrete_diffable_history(self, frozen,
                                               grayfail_plan, capsys):
        """supervise_campaign snapshots between segments: a 3-segment
        run leaves a monotonically growing triage/ history whose last
        pair `service.report --against prev` diffs — re-reading raw
        entry files at most once per snapshot (the cached-classification
        contract rides the long-lived store handle supervise holds)."""
        from madsim_tpu.service import supervise_campaign
        frozen.write_triage_rows(grayfail_plan)
        loads = {"n": 0}
        orig = CorpusStore.load_entry

        def counting(self, name):
            loads["n"] += 1
            return orig(self, name)

        def fake_segment(factory, corpus_dir, **kw):
            return dict(rounds_done=4, coverage_keys=256, buckets=4,
                        worker_results={})

        recs = []

        class Rec:
            def on_round(self, r):
                recs.append(r)

        CorpusStore.load_entry = counting
        try:
            out = supervise_campaign(
                "bench:_make_grayfail_runtime", frozen.dir, workers=1,
                segments=3, rounds_per_segment=4, max_steps=100,
                run_segment=fake_segment, observer=Rec())
            n_supervise = loads["n"]
            # marginal snapshot cost on a long-lived handle: the first
            # walk classifies every immutable entry file once, the
            # second re-reads NONE (O(new files), like the poll loop)
            handle = CorpusStore(frozen.dir, create=False)
            triage_snapshot(handle)
            first = loads["n"] - n_supervise
            triage_snapshot(handle)
            assert loads["n"] - n_supervise == first
        finally:
            CorpusStore.load_entry = orig
        snaps = [s["snapshot"] for s in out["segments"]]
        assert snaps == sorted(snaps) and None not in snaps
        assert list_snapshots(handle)[:3] == snaps
        # across the whole 3-segment supervise run the snapshots read
        # each entry file at most once (the final campaign_report's own
        # coverage scan on its fresh handle accounts for the second 256)
        assert n_supervise <= 2 * 256 + len(frozen.bucket_keys())
        # unchanged store between segments -> triage records say so
        triage_recs = [r for r in recs if r.get("kind") == "triage"]
        assert len(triage_recs) == 3
        assert all(r.get("empty") for r in triage_recs[1:])
        # and the CLI diffs the last pair without error
        from madsim_tpu.service.report import main
        assert main([frozen.dir, "--against", "prev"]) == 0
        assert "EMPTY" in capsys.readouterr().out


# ---------------------------------------------------------------------------
# dashboard + report (structure, not pixels)
# ---------------------------------------------------------------------------

class TestDashboard:
    def test_golden_html_structure(self, frozen, grayfail_plan):
        frozen.write_triage_rows(grayfail_plan)
        _n, s1 = triage_snapshot(frozen)
        key = _plant_bucket(frozen, grayfail_plan.base_knobs())
        _n, s2 = triage_snapshot(frozen)
        d = triage_diff(s1, s2)
        html = render_html(s2, d)
        # structural smoke: root class, sparkline svg, attribution
        # panels, bucket rows with lifecycle + audit badges, repro line
        assert "triage-root" in html and "<svg" in html
        assert "Coverage by recipe" in html
        assert "Buckets by operator" in html
        assert key[:16] in html
        assert 'class="badge new"' in html
        assert "seed=12345" in html
        assert "torn_write" in html
        # every value/label wears text ink: no series-colored text
        assert 'color: var(--series-1)' not in html
        # dark mode is selected, not inverted
        assert "prefers-color-scheme: dark" in html

    def test_sparkline_shapes(self):
        assert "&mdash;" in sparkline_svg([])
        svg = sparkline_svg([[0, 1], [10, 5], [20, 3]], unit="us")
        assert svg.count("<title>") == 3        # per-point hover
        assert 'stroke-width="2"' in svg        # the line spec
        assert 'r="4"' in svg                   # end dot >= 8px diameter

    def test_report_cli_roundtrip(self, frozen, grayfail_plan, capsys):
        from madsim_tpu.service.report import main
        frozen.write_triage_rows(grayfail_plan)
        triage_snapshot(frozen)
        _plant_bucket(frozen, grayfail_plan.base_knobs())
        out_html = os.path.join(frozen.dir, "dash.html")
        rc = main([frozen.dir, "--snapshot", "--against", "prev",
                   "--html", out_html])
        assert rc == 0
        text = capsys.readouterr().out
        assert "1 new" in text
        assert "recipe coverage" in text
        assert os.path.exists(out_html)


# ---------------------------------------------------------------------------
# (6) per-node deterministic hasher seeding
# ---------------------------------------------------------------------------

class _HashProbe(Program):
    """Records each node's first hash-stream draw (and a plain randint
    beside it) into node_state at boot."""

    def __init__(self, use_hash: bool = True):
        self.use_hash = use_hash

    def init(self, ctx):
        st = dict(ctx.state)
        if self.use_hash:
            st["hseed"] = ctx.hash_randint(0, 2**20)
            st["hseed2"] = ctx.hash_randint(0, 2**20, stream=1)
        st["plain"] = ctx.randint(0, 2**20)
        ctx.state = st

    def on_timer(self, ctx, tag, payload):
        pass


def _probe_rt(n=4, use_hash=True, extra_chaos=False):
    sc = Scenario()
    if extra_chaos:
        # schedule reshaping: node 3 boots at ms(1) — AFTER the t=0
        # group, which also grew an extra supervisor op — so its init
        # dispatches at step 4 instead of somewhere in steps 0..3, with
        # a guaranteed-different per-step handler key
        sc.at(0).set_loss(0.1)
        sc.at(ms(1)).boot(3)
    sc.at(ms(5)).halt()
    spec = dict(hseed=jnp.asarray(0, jnp.int32),
                hseed2=jnp.asarray(0, jnp.int32),
                plain=jnp.asarray(0, jnp.int32))
    cfg = SimConfig(n_nodes=n, event_capacity=32, payload_words=2,
                    time_limit=ms(10))
    return Runtime(cfg, [_HashProbe(use_hash)], spec, scenario=sc)


class TestHasherSeeding:
    def test_stream_is_pure_seed_node_function(self):
        rt = _probe_rt()
        st = rt.run_fused(rt.init_batch(np.asarray([3, 9], np.uint32)),
                          200, 64)
        hs = np.asarray(st.node_state["hseed"])      # [B, N]
        hs2 = np.asarray(st.node_state["hseed2"])
        for b, seed in enumerate((3, 9)):
            for node in range(4):
                want = int(prng.randint(
                    prng.node_hash_key(seed, node), 0, 2**20))
                assert int(hs[b, node]) == want, (b, node)
                want2 = int(prng.randint(
                    prng.node_hash_key(seed, node, stream=1), 0, 2**20))
                assert int(hs2[b, node]) == want2
        # decoupled: distinct across nodes and seeds
        assert len({int(x) for x in hs.reshape(-1)}) == hs.size
        assert len({int(x) for x in hs2.reshape(-1)}) == hs2.size

    def test_schedule_independent_where_rand_key_is_not(self):
        """The whole point: a different schedule (chaos reordering
        dispatches) moves ctx.randint draws but NOT the hash stream."""
        seeds = np.asarray([5], np.uint32)
        a = _probe_rt(extra_chaos=False)
        b = _probe_rt(extra_chaos=True)
        sa = a.run_fused(a.init_batch(seeds), 200, 64)
        sb = b.run_fused(b.init_batch(seeds), 200, 64)
        ha = np.asarray(sa.node_state["hseed"])[0]
        hb = np.asarray(sb.node_state["hseed"])[0]
        assert (ha == hb).all(), "hash stream coupled to the schedule"
        # control: the PLAIN per-event draws DO move when the boot
        # steps shift — that coupling is exactly what hash_key removes
        pa = np.asarray(sa.node_state["plain"])[0]
        pb = np.asarray(sb.node_state["plain"])[0]
        assert (pa != pb).any()

    def test_consuming_hash_stream_moves_nothing(self):
        """Bit-identity for everyone else: a model that drains the hash
        stream draws the same plain randint as one that never touches
        it (the stream consumes nothing from the trajectory key)."""
        seeds = np.asarray([11, 12], np.uint32)
        with_h = _probe_rt(use_hash=True)
        without = _probe_rt(use_hash=False)
        sw = with_h.run_fused(with_h.init_batch(seeds), 200, 64)
        so = without.run_fused(without.init_batch(seeds), 200, 64)
        assert (np.asarray(sw.node_state["plain"])
                == np.asarray(so.node_state["plain"])).all()
        # trajectories identical outside the probe's own record
        assert (np.asarray(sw.sched_hash) == np.asarray(so.sched_hash)).all()
        assert int(np.asarray(sw.now)[0]) == int(np.asarray(so.now)[0])

    def test_hash_base_leaf_is_frozen_seed_key(self):
        rt = _probe_rt()
        st = rt.init_batch(np.asarray([7], np.uint32))
        assert (np.asarray(st.hash_base)[0]
                == np.asarray(prng.seed_key(7))).all()
        fin = rt.run_fused(st, 200, 64)
        assert (np.asarray(fin.hash_base)[0]
                == np.asarray(prng.seed_key(7))).all()   # never written
        assert (np.asarray(fin.key)[0]
                != np.asarray(prng.seed_key(7))).any()   # key split away

    def test_ctx_without_base_raises(self):
        from madsim_tpu.core.api import Ctx
        from madsim_tpu.core.types import SimConfig as _SC
        ctx = Ctx(_SC(n_nodes=2, event_capacity=8, payload_words=2,
                      time_limit=100), 0, 0, prng.seed_key(0), {})
        with pytest.raises(ValueError, match="hash base"):
            ctx.hash_key()
