"""Coverage-driven exploration: loop-until-dry seed sweeps over the
schedule-hash metric (the measured upgrade of MADSIM_TEST_NUM's fixed
iteration count, macros lib.rs:152-167)."""

import numpy as np

from madsim_tpu import Runtime, Scenario, SimConfig, NetConfig, ms, sec
from madsim_tpu.models.pingpong import PingPong, state_spec
from madsim_tpu.parallel.explore import explore


class TestExplore:
    def test_tiny_schedule_space_saturates(self):
        # two nodes, constant latency, no chaos: only a handful of
        # distinct dispatch orders exist, so successive rounds stop
        # finding new ones and the dry-round stop fires early
        cfg = SimConfig(n_nodes=2, time_limit=sec(5),
                        net=NetConfig(send_latency_min=ms(1),
                                      send_latency_max=ms(1)))
        rt = Runtime(cfg, [PingPong(2, target=3)], state_spec())
        out = explore(rt, max_steps=2000, batch=32, max_rounds=8,
                      dry_rounds=2)
        assert out["saturated"], out
        assert out["rounds"] < 8
        assert out["distinct_schedules"] >= 1
        assert out["new_per_round"][-1] == 0      # the dry tail
        assert not out["crash_first_seed_by_code"]

    def test_wider_space_keeps_finding_schedules(self):
        # random latency + random kills: every round keeps producing
        # fresh interleavings, so no saturation within the budget
        sc = Scenario()
        sc.at(ms(5)).kill_random()
        sc.at(ms(300)).restart_random()
        cfg = SimConfig(n_nodes=4, time_limit=sec(5),
                        net=NetConfig(packet_loss_rate=0.1))
        rt = Runtime(cfg, [PingPong(4, target=4)], state_spec(),
                     scenario=sc)
        out = explore(rt, max_steps=3000, batch=64, max_rounds=4,
                      dry_rounds=2)
        assert not out["saturated"]
        assert out["distinct_schedules"] > 64     # more than one round's worth
        assert all(n > 0 for n in out["new_per_round"])

    def test_crashes_harvested_not_aborted(self):
        # a known-red workload (WAL sync removed + power-fail chaos):
        # explore keeps sweeping, collects the crash code with its first
        # seed, and that seed reproduces single-lane
        from madsim_tpu.models import wal_kv
        from madsim_tpu.models.wal_kv import make_wal_kv_runtime

        sc = Scenario()
        for t in range(6):
            sc.at(ms(150) + ms(250) * t).kill(0)
            sc.at(ms(210) + ms(250) * t).restart(0)
        rt = make_wal_kv_runtime(n_clients=2, n_ops=12, wal_cap=64,
                                 sync_wal=False, scenario=sc)
        out = explore(rt, max_steps=60_000, batch=16, max_rounds=2,
                      dry_rounds=2)
        assert out["crashes"] > 0
        assert wal_kv.CRASH_LOST_WRITE in out["crash_first_seed_by_code"]
        seed = out["crash_first_seed_by_code"][wal_kv.CRASH_LOST_WRITE]
        st, _ = rt.run_single(seed, max_steps=60_000, collect_events=False)
        assert bool(np.asarray(st.crashed).any())
