"""ShardKV: multi-group raft + reconfiguration + shard migration.

The model is madsim_tpu/models/shard_kv.py (MadRaft shardkv-lab analog).
These tests are the lab's assertions re-shaped for batched fuzzing:
configs actually advance and move shards, clients finish against live
migrations, histories stay linearizable under chaos, and the safety
invariants hold per group.

All batch tests share ONE runtime shape (same n_ops/max_cfg/batch/config
statics) so the step program compiles once; chaos differences ride the
dynamic knobs (scenario tables, loss via net_override).
"""

import numpy as np
import pytest

from madsim_tpu import NetConfig, Scenario, SimConfig, ms, sec
from madsim_tpu.harness.simtest import run_seeds
from madsim_tpu.models.shard_kv import (
    extract_histories, grp_of, make_shard_runtime)
from madsim_tpu.native import check_kv_history

RC, RG, G, NC = 3, 3, 2, 2
CLIENTS_BASE = RC + G * RG
N = CLIENTS_BASE + NC
N_OPS, MAX_CFG, B = 5, 4, 12


pytestmark = pytest.mark.slow  # measured in --durations; ci.sh fast skips

def _runtime(scenario=None):
    cfg = SimConfig(n_nodes=N, event_capacity=160, payload_words=12,
                    time_limit=sec(60),
                    net=NetConfig(send_latency_min=ms(1),
                                  send_latency_max=ms(10)))
    return make_shard_runtime(n_groups=G, rg=RG, rc=RC, n_clients=NC,
                              n_ops=N_OPS, max_cfg=MAX_CFG,
                              scenario=scenario, cfg=cfg)


def _final_cfgs(state):
    """Controller-majority view of the final config number, per lane."""
    return np.asarray(state.node_state["cfg_n"])[:, :RC].max(axis=1)


class TestShardKv:
    def test_migration_completes_and_linearizable(self):
        state = run_seeds(_runtime(), np.arange(B), max_steps=60_000)
        # every lane finished its client workload
        done = np.asarray(state.node_state["c_opn"])[:, CLIENTS_BASE:]
        assert (done >= N_OPS).all()
        # configs advanced past the initial assignment in most lanes —
        # i.e. shard moves actually happened while clients ran
        cfgs = _final_cfgs(state)
        assert (cfgs >= 1).all()
        assert (cfgs >= 2).mean() > 0.5, cfgs
        for h in extract_histories(state, CLIENTS_BASE, NC):
            assert len(h["op"]) > 0
            assert check_kv_history(h)

    def test_chaos_histories_linearizable(self):
        # kills/restarts across ALL raft nodes (controller included),
        # a partition, and packet loss — during live shard migration
        servers = range(CLIENTS_BASE)
        sc = Scenario()
        for t in range(3):
            sc.at(ms(1200 + 1500 * t)).kill_random(among=servers)
            sc.at(ms(1900 + 1500 * t)).restart_random(among=servers)
        sc.at(sec(2)).partition([0, RC, RC + 1])
        sc.at(sec(3)).heal()
        state = run_seeds(_runtime(sc), np.arange(B), max_steps=120_000,
                          net_override=NetConfig(packet_loss_rate=0.05,
                                                 send_latency_min=ms(1),
                                                 send_latency_max=ms(10)))
        hists = extract_histories(state, CLIENTS_BASE, NC)
        assert sum(len(h["op"]) for h in hists) > 0
        ok = 0
        for h in hists:
            assert check_kv_history(h)
            ok += int((np.asarray(h["resp"]) >= 0).sum())
        assert ok > 0, "no operation completed under chaos"

    def test_sessions_migrate_with_shards(self):
        # with migrations on and retries forced by loss, exactly-once must
        # hold ACROSS group handoffs: duplicate client calls answered by a
        # different group than the one that executed them. Linearizability
        # of the histories is exactly that property (a re-executed PUT
        # would surface as a second write of the same unique value; a GET
        # replayed against a stale shard copy surfaces as a stale read).
        state = run_seeds(_runtime(), np.arange(B), max_steps=120_000,
                          net_override=NetConfig(packet_loss_rate=0.15,
                                                 send_latency_min=ms(1),
                                                 send_latency_max=ms(10)))
        moved = 0
        for h in extract_histories(state, CLIENTS_BASE, NC):
            assert check_kv_history(h)
            moved += len(h["op"])
        assert moved > 0
        cfgs = _final_cfgs(state)
        assert (cfgs >= 2).any(), "no lane saw a migration"

    def test_determinism_replay(self):
        assert _runtime().check_determinism(11, 20_000)

    def test_wrong_group_rejected_until_ready(self):
        # the packing helper the gates are built on
        asn = (1 << 0) | (0 << 3) | (1 << 6) | (1 << 9)
        assert int(grp_of(asn, 0)) == 1
        assert int(grp_of(asn, 1)) == 0
        assert int(grp_of(asn, 2)) == 1
        assert int(grp_of(asn, 3)) == 1
        # the serving gate itself: owned-but-not-READY must refuse (this is
        # the edge that prevents dual-serving during migration), as must
        # not-owned and config-0
        import jax.numpy as jnp
        from madsim_tpu.models.shard_kv import ShardServer
        srv = ShardServer(N, 64, gid=1, rc=RC, rg=RG, n_groups=G,
                          n_keys=8, n_shards=4, n_clients=NC,
                          max_cfg=MAX_CFG)
        st = dict(my_cfg=jnp.asarray(2), my_asn=jnp.asarray(asn),
                  ready=jnp.asarray(0b0101))
        assert bool(srv._owns(st, jnp.asarray(0)))          # owned + ready
        assert not bool(srv._owns(st, jnp.asarray(1)))      # other group's
        assert not bool(srv._owns(st, jnp.asarray(3)))      # owned, ~ready
        st0 = dict(st, my_cfg=jnp.asarray(0))
        assert not bool(srv._owns(st0, jnp.asarray(0)))     # no config yet
