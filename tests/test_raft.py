"""Raft fuzzing tests — the MadRaft-equivalent suite (BASELINE.md configs 2/4).

Follows the reference's chaos-test idiom (SURVEY.md §4.7): spawn nodes,
schedule faults at virtual-time checkpoints, and assert protocol invariants —
except invariants here are checked after EVERY event, and each test sweeps a
whole seed batch at once.
"""

import jax.numpy as jnp
import numpy as np
import pytest

pytestmark = pytest.mark.slow  # chaos-sweep-heavy (r7 durations triage);
# tier-1/ci.sh fast skip it so the fast lane fits its 870s budget cold

from madsim_tpu import Scenario, SimConfig, NetConfig, ms, sec
from madsim_tpu.harness.simtest import SimFailure, run_seeds
from madsim_tpu.models import raft as R
from madsim_tpu.models.raft import make_raft_runtime
from madsim_tpu.runtime.runtime import Runtime

N = 5
L = 16
SEEDS = np.arange(8)


def _rt(scenario=None, halt_on_commit=0, n_cmds=6, time_limit=sec(10),
        loss=0.0, **raft_kw):
    cfg = SimConfig(n_nodes=N, event_capacity=256, time_limit=time_limit,
                    net=NetConfig(packet_loss_rate=loss,
                                  send_latency_min=ms(1),
                                  send_latency_max=ms(10)))
    return make_raft_runtime(N, L, n_cmds=n_cmds,
                             halt_on_commit=halt_on_commit,
                             scenario=scenario, cfg=cfg, **raft_kw)


class TestElection:
    def test_leader_elected_and_stable(self):
        rt = _rt(time_limit=sec(3))
        state = run_seeds(rt, SEEDS, max_steps=6000)
        ns = state.node_state
        role = np.asarray(ns["role"])
        # every trajectory elected exactly one current leader
        assert (np.sum(role == R.LEADER, axis=1) == 1).all()
        # all nodes converged on the leader's term
        term = np.asarray(ns["term"])
        assert (term.max(axis=1) == term.min(axis=1)).all()

    def test_different_seeds_elect_different_leaders(self):
        rt = _rt(time_limit=sec(3))
        state = run_seeds(rt, np.arange(16), max_steps=6000)
        role = np.asarray(state.node_state["role"])
        leaders = role.argmax(axis=1)
        assert len(set(leaders.tolist())) >= 2  # schedule diversity

    def test_election_after_leader_kill(self):
        # kill whoever leads at 1s (random node is close enough: kill_random
        # may hit a follower — then the old leader just continues; either
        # way safety holds and someone leads at the end)
        sc = Scenario()
        sc.at(sec(1)).kill_random()
        rt = _rt(scenario=sc, time_limit=sec(4))
        state = run_seeds(rt, SEEDS, max_steps=8000)
        role = np.asarray(state.node_state["role"])
        alive = np.asarray(state.alive)
        lead_alive = ((role == R.LEADER) & alive).sum(axis=1)
        assert (lead_alive >= 1).all()


class TestReplication:
    def test_commit_reached_clean_network(self):
        rt = _rt(halt_on_commit=4, time_limit=sec(8))
        state = run_seeds(rt, SEEDS, max_steps=10_000)
        commit = np.asarray(state.node_state["commit"])
        assert (commit.max(axis=1) >= 4).all()
        # halting early, well before the scenario HALT at 8s
        assert (np.asarray(state.now) < sec(8)).all()

    def test_commit_under_packet_loss(self):
        rt = _rt(halt_on_commit=3, time_limit=sec(10), loss=0.1)
        state = run_seeds(rt, SEEDS, max_steps=20_000)
        assert (np.asarray(state.node_state["commit"]).max(axis=1) >= 3).all()

    def test_logs_match_on_committed_prefix(self):
        rt = _rt(halt_on_commit=4, time_limit=sec(8))
        state = run_seeds(rt, SEEDS, max_steps=10_000)
        cmd = np.asarray(state.node_state["log_cmd"])
        commit = np.asarray(state.node_state["commit"])
        for b in range(len(SEEDS)):
            for i in range(N):
                for j in range(N):
                    c = min(commit[b, i], commit[b, j])
                    assert (cmd[b, i, :c] == cmd[b, j, :c]).all()


class TestChaos:
    def test_partition_minority_still_commits(self):
        sc = Scenario()
        sc.at(ms(500)).partition([0, 1])      # majority {2,3,4} can proceed
        sc.at(sec(4)).heal()
        rt = _rt(scenario=sc, halt_on_commit=3, time_limit=sec(10))
        state = run_seeds(rt, SEEDS, max_steps=20_000)
        assert (np.asarray(state.node_state["commit"]).max(axis=1) >= 3).all()

    def test_kill_restart_chaos_safety(self):
        # rolling random kills/restarts — safety must hold throughout
        # (checked per-event by the invariant; this test passing means no
        # event in ~8 seeds x 20k events violated it)
        sc = Scenario()
        for t in range(6):
            sc.at(ms(800 + 700 * t)).kill_random()
            sc.at(ms(1100 + 700 * t)).restart_random()
        rt = _rt(scenario=sc, time_limit=sec(6), n_cmds=6)
        state = run_seeds(rt, SEEDS, max_steps=20_000)
        assert bool(state.halted.all())

    def test_persistence_across_restart(self):
        # a restarted node must come back with its persisted term/log
        # (stable-storage semantics; without them Raft is unsound)
        sc = Scenario()
        sc.at(sec(2)).kill(0)
        sc.at(sec(3)).restart(0)
        rt = _rt(scenario=sc, halt_on_commit=4, time_limit=sec(10))
        state = run_seeds(rt, SEEDS, max_steps=20_000)
        term = np.asarray(state.node_state["term"])
        # node 0 was killed after elections began; on restart it kept a
        # non-zero persisted term (state_spec default is 0)
        assert (term[:, 0] > 0).all()

    def test_buggy_quorum_caught_by_invariant(self):
        # inject a real protocol bug: quorum of 2 in a 5-node cluster can
        # elect two leaders in the same term; the per-event invariant must
        # catch it and report a reproducible seed
        rt = _rt(time_limit=sec(5), majority_override=2)
        with pytest.raises(SimFailure) as ei:
            run_seeds(rt, np.arange(32), max_steps=20_000)
        assert ei.value.code == R.CRASH_TWO_LEADERS
        # the reported seed reproduces solo (replay-by-seed)
        state, _ = rt.run_single(ei.value.seed, max_steps=20_000)
        assert bool(state.crashed.all())
        assert int(np.asarray(state.crash_code)[0]) == R.CRASH_TWO_LEADERS


class TestDeterminism:
    def test_raft_replay_stable(self):
        rt = _rt(time_limit=sec(2))
        assert rt.check_determinism(seed=2024, max_steps=4000)


class TestCommitClamp:
    def test_leadercommit_clamps_to_verified_prefix(self):
        # Figure 2: commit = min(leaderCommit, index of last NEW entry).
        # "Last new entry" is the VERIFIED prefix (prev + accepted), not
        # the follower's log length: a follower holding an uncommitted
        # stale suffix must not commit it just because leaderCommit is
        # numerically past it. Red if the commit rule clamps to new_len.
        from madsim_tpu.core import prng
        from madsim_tpu.core.api import Ctx

        cfg = SimConfig(n_nodes=3, payload_words=8)
        prog = R.Raft(3, log_capacity=8)
        z = jnp.asarray(0, jnp.int32)
        st = dict(
            term=jnp.asarray(3, jnp.int32),
            voted_for=jnp.asarray(-1, jnp.int32),
            # entries 2..5 are a STALE term-2 suffix this leader never
            # verified (its AE only proves the prefix up to prev=2)
            log_term=jnp.asarray([1, 1, 2, 2, 2, 2, 0, 0], jnp.int32),
            log_len=jnp.asarray(6, jnp.int32),
            snap_len=z, snap_term=z, snap_digest=z,
            role=z, votes=z, commit=jnp.asarray(2, jnp.int32),
            next_idx=jnp.zeros(3, jnp.int32),
            match_idx=jnp.zeros(3, jnp.int32),
            egen=z, hgen=z, nprop=z,
            log_cmd=jnp.zeros(8, jnp.int32),
        )
        ctx = Ctx(cfg, jnp.asarray(1, jnp.int32), z, prng.seed_key(0), st)
        # heartbeat AE from the term-3 leader: prev=2 (term 1, matches),
        # zero entries, leaderCommit=6
        payload = jnp.asarray([3, 2, 1, 6, 0, 0, 0, 0], jnp.int32)
        prog.on_message(ctx, jnp.asarray(0, jnp.int32),
                        jnp.asarray(R.AE, jnp.int32), payload)
        assert int(ctx.state["commit"]) == 2   # not 6


class TestMultiEntryAE:
    """ae_batch > 1: k entries per AppendEntries (payload-packed, static k).

    With ae_batch=1 a lagging follower gains at most one entry per
    heartbeat round-trip — log catch-up serializes through event-table
    rows. Batched AE cuts the rounds by ~k; the catch-up-window test
    below is red if ae_batch degrades to single-entry behavior."""

    def _rt(self, k, tlimit, scenario=None, **kw):
        cfg = SimConfig(n_nodes=N, event_capacity=256, time_limit=tlimit,
                        payload_words=5 + k * 2,
                        net=NetConfig(packet_loss_rate=0.0,
                                      send_latency_min=ms(1),
                                      send_latency_max=ms(10)))
        return make_raft_runtime(N, L, scenario=scenario, cfg=cfg,
                                 ae_batch=k, **kw)

    def _catchup(self, k):
        # node 4 sleeps through 12 proposals, then gets a ~350ms window
        # to catch up: ~6 heartbeat round-trips — enough for 12 entries
        # only when each AE carries several
        sc = Scenario()
        sc.at(ms(300)).kill(4)
        sc.at(ms(2500)).restart(4)
        rt = self._rt(k, tlimit=ms(2850), scenario=sc, n_cmds=12,
                      propose_every=ms(60))
        state = run_seeds(rt, SEEDS, max_steps=30_000)
        lens = np.asarray(state.node_state["log_len"])
        return lens[:, 4], lens[:, :4].max(axis=1)

    def test_batched_catchup_beats_single(self):
        got1, full1 = self._catchup(1)
        got4, full4 = self._catchup(4)
        assert (full1 >= 12).all() and (full4 >= 12).all()
        # k=4: every seed fully caught up inside the window
        assert (got4 == full4).all(), (got4, full4)
        # k=1: the window only fits ~6 single-entry round-trips
        assert (got1 < full1).all(), (got1, full1)
        assert got1.mean() + 4 <= got4.mean()

    def test_batched_safety_under_chaos(self):
        sc = Scenario()
        for t in range(5):
            sc.at(ms(700 + 600 * t)).kill_random()
            sc.at(ms(1000 + 600 * t)).restart_random()
        rt = self._rt(4, tlimit=sec(5), scenario=sc, n_cmds=10)
        state = run_seeds(rt, SEEDS, max_steps=25_000)
        assert bool(state.halted.all())

    def test_batched_replay_stable(self):
        assert self._rt(4, tlimit=sec(2)).check_determinism(
            seed=77, max_steps=5000)


class TestInvariantForms:
    """The two State-Machine-Safety forms (raft_invariant window_slides):
    pairwise [N,N,L+1] (sound for any snap_len) vs commit-sorted adjacent
    chain (O(N*L), valid ONLY when the window never slides). These tests
    pin (a) their equivalence on never-sliding states — the condition the
    static gate encodes — and (b) the compaction soundness gap that makes
    the gate necessary (the code-review scenario, verbatim)."""

    def test_forms_agree_on_no_compaction_chaos_states(self):
        # a wrong-quorum cluster manufactures REAL violations (two
        # leaders, divergent committed prefixes); crashed lanes freeze at
        # their first violating state, so the final batch holds a mix of
        # clean and violating configurations — both forms must agree on
        # every lane, bad flag AND code
        import jax

        sc = Scenario()
        sc.at(ms(400)).partition([0, 1])
        sc.at(ms(900)).heal()
        cfg = SimConfig(n_nodes=5, event_capacity=96, time_limit=sec(3))
        rt = make_raft_runtime(5, log_capacity=16, n_cmds=6,
                               majority_override=2, scenario=sc, cfg=cfg)
        st, _ = rt.run(rt.init_batch(np.arange(64)), 8000)
        assert bool(np.asarray(st.crashed).any())   # violations happened
        inv_pair = R.raft_invariant(5, 16, window_slides=True)
        inv_adj = R.raft_invariant(5, 16, window_slides=False)
        bad_p, code_p = jax.vmap(inv_pair)(st)
        bad_a, code_a = jax.vmap(inv_adj)(st)
        np.testing.assert_array_equal(np.asarray(bad_p), np.asarray(bad_a))
        np.testing.assert_array_equal(
            np.asarray(code_p)[np.asarray(bad_p)],
            np.asarray(code_a)[np.asarray(bad_a)])

    def _slid_window_divergence_state(self):
        """Three peers, committed-prefix divergence, one node compacted
        past another's commit: A(ec=5, sl=0) diverges from the true
        history at index 2; B(ec=10, sl=8) compacted to 8; C(ec=20,
        sl=0) holds the true history. Pairwise checks (A,C) at 5 and
        fires; the adjacent chain's A->B link is voided (5 < sl_B=8), so
        transitivity breaks and it misses the divergence."""
        N, L = 3, 32
        rt = make_raft_runtime(N, log_capacity=L, n_cmds=0)
        s = rt._template
        ns = {k: np.asarray(v).copy() for k, v in s.node_state.items()}
        true_cmds = np.arange(1, 21, dtype=np.int32)        # 1..20
        term = 1

        def chain_digest(cmds):         # digest of a compacted prefix
            powP = np.asarray(R._pow_table(len(cmds)), np.int64)
            dig = 0
            h = [int(R.entry_hash(jnp.asarray(term), [jnp.asarray(int(c))]))
                 for c in cmds]
            n = len(cmds)
            for k in range(n):
                dig = (dig + h[k] * int(powP[n - 1 - k])) % (1 << 32)
            return np.int32(dig - (1 << 32) if dig >= (1 << 31) else dig)

        for i in range(N):
            ns["role"][i] = R.FOLLOWER
            ns["term"][i] = term
        # A: full history from 0, len 5, commit 5, DIVERGENT at index 2
        a_cmds = true_cmds[:5].copy()
        a_cmds[2] = 999
        ns["snap_len"][0], ns["snap_digest"][0] = 0, 0
        ns["log_len"][0], ns["commit"][0] = 5, 5
        ns["log_term"][0, :5] = term
        ns["log_cmd"][0, :5] = a_cmds
        # B: compacted to 8 over the TRUE history, entries 8..9 live
        ns["snap_len"][1] = 8
        ns["snap_term"][1] = term
        ns["snap_digest"][1] = chain_digest(true_cmds[:8])
        ns["log_len"][1], ns["commit"][1] = 10, 10
        ns["log_term"][1, :2] = term
        ns["log_cmd"][1, :2] = true_cmds[8:10]
        # C: full true history, len 20, commit 20
        ns["snap_len"][2], ns["snap_digest"][2] = 0, 0
        ns["log_len"][2], ns["commit"][2] = 20, 20
        ns["log_term"][2, :20] = term
        ns["log_cmd"][2, :20] = true_cmds
        return s.replace(node_state={k: jnp.asarray(v)
                                     for k, v in ns.items()}), N, L

    def test_pairwise_catches_slid_window_divergence(self):
        st, N, L = self._slid_window_divergence_state()
        bad, code = R.raft_invariant(N, L, window_slides=True)(st)
        assert bool(bad)
        assert int(code) == R.CRASH_LOG_MISMATCH

    def test_adjacent_form_misses_it_hence_the_gate(self):
        # NOT a desired property — this documents the exact coverage gap
        # that forbids the cheap form whenever the window can slide. If
        # this test ever FAILS (the adjacent form starts catching it),
        # the static gate in raft_invariant can be revisited.
        st, N, L = self._slid_window_divergence_state()
        bad, _ = R.raft_invariant(N, L, window_slides=False)(st)
        assert not bool(bad)
