"""DetSan (r12): determinism linter, happens-before schedule-race
detector with forced-commute confirmation, and the detsan double-run
sanitizer.

Load-bearing properties (DESIGN §14):
(1) every lint rule FIRES on a planted hazard and HONORS its
`# detsan: ok(<rule>)` suppression — a toothless linter passes any
repo, so the positive controls are the real test;
(2) the rules apply only to TRACED scopes — host driver code may use
clocks and RNG freely (flagging it would bury real findings);
(3) the repo's own models/examples pass the gate (satellite 1);
(4) a seeded schedule race in the wal_kv mutant is detected from the
rings, confirmed by forcing the commuted tie-break order via the PCT
nudge, carries a (seed, knobs, nudge) repro that REPLAYS to the
confirming lane's exact fingerprint, and dedupes into ONE bucket;
(5) detsan: identity vs permuted lane placement is leaf-for-leaf
bit-identical for clean runtimes (raft/wal_kv fast, shard_kv slow),
and the differ pins a planted divergence to its leaf + lane + seed;
(6) identity-token signature degradation is no longer silent: it emits
a COMPILE_LOG record naming qualname + cell (satellite 2).
"""

import os
import sys
import threading

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from madsim_tpu import DetSanFailure, Program, detsan_check, run_seeds
from madsim_tpu.analyze.lint import (DeterminismLintError, active,
                                     lint_callable, lint_paths,
                                     lint_program, lint_runtime,
                                     lint_source)
from madsim_tpu.analyze.races import (confirm_race, find_races,
                                      replay_race, scan_races)
from madsim_tpu.harness.simtest import detsan_perm, diff_states
from madsim_tpu.obs.causal import fingerprints_match, race_fingerprint

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_PRELUDE = (
    "import time, random, os, uuid, secrets\n"
    "import numpy as np\n"
    "from madsim_tpu.core.api import Program\n")


def _rules(src, suppressed_too=False):
    fs = lint_source(_PRELUDE + src, "t.py")
    return {f.rule for f in (fs if suppressed_too else active(fs))}


# ---------------------------------------------------------------------------
# lint rules: positive + suppressed, one per rule
# ---------------------------------------------------------------------------


class TestLintRules:
    def test_host_time_positive(self):
        src = ("class P(Program):\n"
               "    def on_timer(self, ctx, tag, payload):\n"
               "        t = time.time()\n")
        assert _rules(src) == {"host-time"}

    def test_host_time_suppressed(self):
        src = ("class P(Program):\n"
               "    def on_timer(self, ctx, tag, payload):\n"
               "        t = time.time()  # detsan: ok(host-time)\n")
        assert _rules(src) == set()
        assert _rules(src, suppressed_too=True) == {"host-time"}

    def test_host_random_positive(self):
        src = ("class P(Program):\n"
               "    def on_message(self, ctx, src_, tag, payload):\n"
               "        a = random.random()\n"
               "        b = np.random.rand()\n"
               "        c = os.urandom(4)\n"
               "        d = uuid.uuid4()\n"
               "        e = secrets.token_bytes(4)\n")
        fs = active(lint_source(_PRELUDE + src, "t.py"))
        assert {f.rule for f in fs} == {"host-random"}
        assert len(fs) == 5

    def test_host_random_suppressed_line_above(self):
        src = ("class P(Program):\n"
               "    def on_message(self, ctx, src_, tag, payload):\n"
               "        # detsan: ok(host-random)\n"
               "        a = random.random()\n")
        assert _rules(src) == set()

    def test_jax_random_not_flagged(self):
        src = ("import jax\n"
               "class P(Program):\n"
               "    def init(self, ctx):\n"
               "        k = jax.random.split(ctx.rand_key())\n")
        assert _rules(src) == set()

    def test_unordered_iter_positive(self):
        src = ("class P(Program):\n"
               "    def init(self, ctx):\n"
               "        for x in {1, 2, 3}:\n"
               "            pass\n"
               "        ys = [k for k in vars(self)]\n"
               "        for k in set(ys).keys() if False else set(ys):\n"
               "            pass\n")
        assert _rules(src) == {"unordered-iter"}

    def test_unordered_iter_suppressed(self):
        src = ("class P(Program):\n"
               "    def init(self, ctx):\n"
               "        for x in {1, 2}:  # detsan: ok(unordered-iter)\n"
               "            pass\n")
        assert _rules(src) == set()

    def test_dict_iteration_not_flagged(self):
        # py3.7+ dicts iterate in insertion order — deterministic
        src = ("class P(Program):\n"
               "    def init(self, ctx):\n"
               "        st = dict(ctx.state)\n"
               "        for k in st:\n"
               "            pass\n")
        assert _rules(src) == set()

    def test_host_callback_positive(self):
        src = ("import jax\n"
               "class P(Program):\n"
               "    def on_timer(self, ctx, tag, payload):\n"
               "        jax.pure_callback(int, None)\n")
        assert _rules(src) == {"host-callback"}

    def test_host_callback_suppressed(self):
        src = ("import jax\n"
               "class P(Program):\n"
               "    def on_timer(self, ctx, tag, payload):\n"
               "        jax.pure_callback(int, None)"
               "  # detsan: ok(host-callback)\n")
        assert _rules(src) == set()

    def test_star_suppression(self):
        src = ("class P(Program):\n"
               "    def init(self, ctx):\n"
               "        t = time.time()  # detsan: ok(*)\n")
        assert _rules(src) == set()

    def test_parse_error_is_a_finding(self):
        fs = lint_source("def broken(:\n", "t.py")
        assert [f.rule for f in fs] == ["parse-error"]


class TestLintScoping:
    def test_host_driver_code_not_flagged(self):
        src = ("def host_driver():\n"
               "    time.sleep(1)\n"
               "    return random.random()\n")
        assert _rules(src) == set()

    def test_invariant_kwarg_scopes(self):
        src = ("def make(n):\n"
               "    return Runtime(None, [], {},\n"
               "                   invariant=my_inv_factory(n),\n"
               "                   halt_when=lambda s: time.monotonic())\n"
               "def my_inv_factory(n):\n"
               "    def invariant(state):\n"
               "        return random.random() < 0.5, 0\n"
               "    return invariant\n")
        assert _rules(src) == {"host-random", "host-time"}

    def test_invariant_factory_reached_without_call_site(self):
        # raft.py defines raft_invariant; raft_kv constructs with
        # R.raft_invariant(...) from ANOTHER file — the factory's own
        # module must still lint the closure
        src = ("def chain_invariant(n):\n"
               "    def invariant(state):\n"
               "        return time.time() > 0, 0\n"
               "    return invariant\n")
        assert _rules(src) == {"host-time"}

    def test_cross_module_model_inheritance(self):
        src = ("from madsim_tpu.models import raft as R\n"
               "class CfgRaft(R.Raft):\n"
               "    def on_timer(self, ctx, tag, payload):\n"
               "        t = time.time()\n")
        assert _rules(src) == {"host-time"}

    def test_repo_gate_clean(self):
        # satellite 1: the repo-wide `python -m madsim_tpu.analyze` gate
        bad = active(lint_paths([os.path.join(_REPO, "madsim_tpu"),
                                 os.path.join(_REPO, "examples")]))
        assert not bad, "\n".join(f.format() for f in bad)


# ---------------------------------------------------------------------------
# runtime-side rules: closures, Program attributes, the lint= flag
# ---------------------------------------------------------------------------


def _make_bad_time_program():
    import time as _time

    class BadClock(Program):
        def on_timer(self, ctx, tag, payload):
            t = _time.time()
            return t

    return BadClock()


class TestRuntimeLint:
    def test_mutable_capture_closure(self):
        log = []

        def inv(state):
            log.append(1)
            return False, 0

        fs = lint_callable(inv, name="inv")
        assert "mutable-capture" in {f.rule for f in active(fs)}

    def test_mutable_capture_program_attribute(self):
        class P(Program):
            def __init__(self):
                self.table = [1, 2, 3]

        fs = lint_program(P())
        assert "mutable-capture" in {f.rule for f in active(fs)}

    def test_sig_degrade_closure(self):
        lock = threading.Lock()      # freeze() -> identity token

        def inv(state):
            return bool(lock), 0

        fs = lint_callable(inv, name="inv")
        assert "sig-degrade" in {f.rule for f in active(fs)}

    def test_clean_flagships(self):
        from madsim_tpu.models.raft import make_raft_runtime
        from madsim_tpu.models.wal_kv import make_wal_kv_runtime
        for rt in (make_raft_runtime(3, 8), make_wal_kv_runtime()):
            assert active(lint_runtime(rt)) == []

    def test_lint_flag_raises_and_warn_passes(self, capsys):
        from madsim_tpu.models.pingpong import state_spec
        from madsim_tpu import Runtime, SimConfig, sec
        cfg = SimConfig(n_nodes=2, event_capacity=64, time_limit=sec(1))
        prog = _make_bad_time_program()
        with pytest.raises(DeterminismLintError) as ei:
            Runtime(cfg, [prog], state_spec(), lint=True)
        assert "host-time" in str(ei.value)
        Runtime(cfg, [prog], state_spec(), lint="warn")   # must construct
        assert "detsan warn" in capsys.readouterr().out

    def test_degrade_warning_emitted(self):
        # satellite 2: identity-token degradation is a named COMPILE_LOG
        # record (qualname + cell), fanned out to on_compile observers
        from madsim_tpu import Runtime, SimConfig, SweepObserver, sec
        from madsim_tpu.compile.cache import COMPILE_LOG
        from madsim_tpu.models.pingpong import PingPong, state_spec

        lock = threading.Lock()

        def degraded_invariant(state):
            _ = lock
            return state.now < 0, 0

        class Catch(SweepObserver):
            def __init__(self):
                self.recs = []

            def on_compile(self, rec):
                if rec.get("label") == "signature_degrade":
                    self.recs.append(rec)

        obs = Catch()
        COMPILE_LOG.attach(obs)
        try:
            cfg = SimConfig(n_nodes=2, event_capacity=64,
                            time_limit=sec(1))
            Runtime(cfg, [PingPong(2)], state_spec(),
                    invariant=degraded_invariant)
        finally:
            COMPILE_LOG.detach(obs)
        assert obs.recs, "no signature_degrade record emitted"
        rec = obs.recs[0]
        assert rec["cell"] == "lock"
        assert "degraded_invariant" in rec["owner"]
        assert "signature degrade" in COMPILE_LOG.summary()


# ---------------------------------------------------------------------------
# schedule races: detect from rings, confirm by forced commute, bucket
# ---------------------------------------------------------------------------


def _racy_rt(trace_cap=256):
    """The race-rich wal_kv mutant. bench owns the ONE canonical
    definition (the r9 rule: tests exercise exactly the workload
    --analyze-smoke gates)."""
    from bench import _make_racy_runtime
    return _make_racy_runtime(trace_cap=trace_cap)


class TestRaces:
    def test_race_fingerprint_symmetric_dedup(self):
        a = dict(step=5, now=100, kind=1, node=0, src=1, tag=7,
                 parent=2, lamport=3)
        b = dict(step=6, now=100, kind=1, node=0, src=2, tag=7,
                 parent=2, lamport=3)
        cand_ab = dict(lane=0, node=0, now=100, a=a, b=b)
        cand_ba = dict(lane=3, node=0, now=900, a=b, b=a)
        fp1, fp2 = race_fingerprint(cand_ab), race_fingerprint(cand_ba)
        assert fp1["key"] == fp2["key"]          # order-normalized
        assert fp1["kind"] == "race"
        assert fingerprints_match(fp1, fp2)
        other = race_fingerprint(dict(cand_ab, node=1))
        assert other["key"] != fp1["key"]
        assert not fingerprints_match(fp1, other)

    def test_seeded_race_confirms_and_replays(self, tmp_path):
        from madsim_tpu.search.mutate import KnobPlan
        from madsim_tpu.service.buckets import CrashBuckets
        from madsim_tpu.service.store import CorpusStore, store_signature
        rt = _racy_rt()
        plan = KnobPlan.from_runtime(rt)
        store = CorpusStore(str(tmp_path / "c"),
                            signature=store_signature(rt, plan))
        buckets = CrashBuckets(store)
        seeds = np.arange(32, dtype=np.uint32)
        res = scan_races(rt, seeds, 20_000, buckets=buckets,
                         max_confirm=2)
        assert res["candidates"] >= 1
        assert res["confirmed"], res
        conf = res["confirmed"][0]
        assert conf["status"] == "confirmed" and conf["nudge"] != 0
        # the (seed, knobs, nudge) repro replays ALONE to the confirming
        # lane's exact fingerprint (lane independence, DESIGN §4)
        rep = replay_race(rt, conf["repro"])
        assert rep["fingerprint"] == conf["diff"]["fingerprint"][1]
        # bucketed as a first-class finding with the nudge in the handle
        rec = store.load_bucket(res["bucket_keys"][0])
        assert rec["fingerprint"]["kind"] == "race"
        assert rec["repro"]["nudge"] == conf["nudge"]
        # dedup: rescanning the same seeds opens no new buckets
        n0 = len(store.bucket_keys())
        scan_races(rt, seeds, 20_000, buckets=buckets, max_confirm=2)
        assert len(store.bucket_keys()) == n0

    def test_candidates_are_unordered_same_instant_pairs(self):
        rt = _racy_rt()
        seeds = np.arange(16, dtype=np.uint32)
        state = rt.run_fused(rt.init_batch(seeds), 20_000, 512)
        lanes = np.nonzero(np.asarray(state.crashed))[0]
        assert len(lanes), "race-rich mutant produced no crash"
        cands = find_races(state, int(lanes[0]))
        for c in cands:
            assert c["a"]["now"] == c["b"]["now"] == c["now"]
            assert c["a"]["node"] == c["b"]["node"] == c["node"]
            # b must not descend from a (the detector's HB contract)
            assert c["b"]["parent"] != c["a"]["step"]

    def test_confirm_baseline_uses_mutant_nudge(self):
        # a fuzz mutant may carry its own tie-break policy: the baseline
        # lane must replay THAT policy (not 0), and the sweep must not
        # waste a lane on a baseline clone
        from madsim_tpu.search.mutate import KnobPlan
        rt = _racy_rt()
        plan = KnobPlan.from_runtime(rt)
        knobs = plan.base_knobs()
        knobs["prio_nudge"] = np.int32(5)
        state = rt.run_fused(rt.init_batch(np.arange(8, dtype=np.uint32)),
                             20_000, 512)
        lanes = np.nonzero(np.asarray(state.crashed))[0]
        cand = find_races(state, int(lanes[0]))[0]
        conf = confirm_race(rt, 1, cand, knobs=knobs, plan=plan,
                            nudges=np.asarray([5, 6]), max_steps=20_000)
        assert conf["swept"] == [6]          # 5 == baseline, dropped
        assert conf["baseline"] is not None

    def test_confirm_requires_commuted_order(self):
        # a candidate whose tokens never co-occur in any nudged lane is
        # inconclusive, not confirmed — no false positives from
        # fingerprint drift alone
        rt = _racy_rt()
        seeds = np.arange(8, dtype=np.uint32)
        state = rt.run_fused(rt.init_batch(seeds), 20_000, 512)
        lanes = np.nonzero(np.asarray(state.crashed))[0]
        cands = find_races(state, int(lanes[0]))
        fake = dict(cands[0])
        fake["a"] = dict(cands[0]["a"], kind=99, tag=12345)   # no such event
        conf = confirm_race(rt, int(seeds[lanes[0]]), fake,
                            nudges=np.arange(1, 5), max_steps=20_000)
        assert conf["status"] == "inconclusive"


# ---------------------------------------------------------------------------
# detsan: permuted-lane double run
# ---------------------------------------------------------------------------


class TestDetSan:
    def test_perm_is_a_real_permutation(self):
        for B in (1, 2, 3, 16, 512):
            p = detsan_perm(B)
            assert sorted(p.tolist()) == list(range(B))
            if B > 1:
                assert (p != np.arange(B)).any()

    def test_raft_equivalence(self):
        from madsim_tpu.models.raft import make_raft_runtime
        rep = detsan_check(make_raft_runtime(3, 8), np.arange(24), 2048,
                           chunk=256)
        assert rep["ok"] and rep["diffs"] == []

    def test_wal_kv_equivalence(self):
        from madsim_tpu.models.wal_kv import make_wal_kv_runtime
        rep = detsan_check(make_wal_kv_runtime(), np.arange(24), 2048,
                           chunk=256)
        assert rep["ok"] and rep["diffs"] == []

    @pytest.mark.slow
    def test_shard_kv_equivalence(self):
        from madsim_tpu.models.shard_kv import make_shard_runtime
        rep = detsan_check(make_shard_runtime(), np.arange(16), 8192,
                           chunk=512)
        assert rep["ok"] and rep["diffs"] == []

    def test_planted_diff_is_pinned_to_leaf_lane_seed(self):
        from bench import _make_light_runtime
        rt = _make_light_runtime(n_nodes=2)
        seeds = np.arange(8)
        a = rt.run_fused(rt.init_batch(seeds), 256, 64)
        bad = a.replace(now=a.now.at[3].add(1))
        diffs = diff_states(a, bad, align=np.arange(8))
        assert len(diffs) == 1
        assert "now" in diffs[0]["leaf"] and diffs[0]["lanes"] == [3]
        # end to end: a baseline that disagrees with the permuted replay
        # raises with the seed of the differing lane
        with pytest.raises(DetSanFailure) as ei:
            detsan_check(rt, seeds, 256, 64, baseline_state=bad)
        assert ei.value.seed == 3
        assert "MADSIM_TEST_DETSAN" in str(ei.value)

    def test_run_seeds_detsan_flag_and_env(self):
        from bench import _make_light_runtime
        rt = _make_light_runtime(n_nodes=2)
        state = run_seeds(rt, np.arange(8), 256, chunk=64, detsan=True)
        assert np.asarray(state.now).shape == (8,)   # ran + sanitized
        os.environ["MADSIM_TEST_DETSAN"] = "1"
        try:
            run_seeds(rt, np.arange(8), 256, chunk=64)
        finally:
            del os.environ["MADSIM_TEST_DETSAN"]
