"""The emission_write lowering knob (types.py) must be value-invisible:
"onehot" and "scatter" are two XLA lowerings of the SAME table write, so
trajectories, fingerprints, and schedule hashes must be BIT-IDENTICAL
across them — this knob must never become a replay domain. The cheap
form differentially pins the fast form."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from madsim_tpu import NetConfig, Scenario, SimConfig, ms, sec
from madsim_tpu.models.raft import make_raft_runtime
from madsim_tpu.ops import select as sel


class TestFirstKFreeLowerings:
    @pytest.mark.parametrize("k", [1, 3, 8])
    def test_scatter_matches_rank_match(self, k):
        rng = np.random.default_rng(7)
        for _ in range(32):
            free = jnp.asarray(rng.random(24) < rng.random())
            s_a, ok_a = sel.first_k_free(free, k, scatter=False)
            s_b, ok_b = sel.first_k_free(free, k, scatter=True)
            assert (np.asarray(ok_a) == np.asarray(ok_b)).all()
            # not-ok rows are gated by callers; compare only the real ones
            m = np.asarray(ok_a)
            assert (np.asarray(s_a)[m] == np.asarray(s_b)[m]).all()

    def test_all_free_and_none_free(self):
        for free in (jnp.ones(16, bool), jnp.zeros(16, bool)):
            s_a, ok_a = sel.first_k_free(free, 4, scatter=False)
            s_b, ok_b = sel.first_k_free(free, 4, scatter=True)
            assert (np.asarray(ok_a) == np.asarray(ok_b)).all()
            m = np.asarray(ok_a)
            assert (np.asarray(s_a)[m] == np.asarray(s_b)[m]).all()


def _rt(emission_write):
    sc = Scenario()
    sc.at(ms(300)).kill_random()
    sc.at(ms(700)).restart_random()
    sc.at(ms(900)).partition([0, 1])
    sc.at(ms(1300)).heal()
    cfg = SimConfig(n_nodes=5, event_capacity=96, time_limit=sec(30),
                    net=NetConfig(packet_loss_rate=0.05),
                    emission_write=emission_write)
    return make_raft_runtime(5, log_capacity=16, n_cmds=6, scenario=sc,
                             cfg=cfg)


class TestEndToEndBitIdentical:
    def test_chaos_raft_state_identical_across_lowerings(self):
        seeds = np.arange(8)
        final = {}
        for mode in ("onehot", "scatter"):
            rt = _rt(mode)
            st, _ = rt.run(rt.init_batch(seeds), 768)
            final[mode] = jax.tree.map(np.asarray, st)
        a, b = final["onehot"], final["scatter"]
        for la, lb in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
            assert la.dtype == lb.dtype
            assert (la == lb).all()
        # the knob must not leak into replay identity: schedule hashes
        # agree too
        assert (np.asarray(a.sched_hash) == np.asarray(b.sched_hash)).all()
