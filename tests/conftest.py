"""Test env: force a virtual 8-device CPU mesh before any test imports jax.

Multi-chip TPU hardware is not available in CI; sharding correctness is
validated on a host-platform virtual mesh (the driver separately dry-runs
the multi-chip path via __graft_entry__.dryrun_multichip). The environment's
sitecustomize may pre-register a TPU backend and pin jax_platforms, so the
config update below (not just the env var) is what actually forces CPU.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# Persistent compilation cache: most suite wall-clock is XLA recompiles of
# near-identical step programs (every test builds a Runtime with its own
# static shapes). Caching them across runs cuts the suite from ~12min to
# the actual execution time.
jax.config.update("jax_compilation_cache_dir",
                  os.path.join(os.path.dirname(__file__), "..", ".jax_cache"))
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
