"""Test env: force a virtual 8-device CPU mesh before any test imports jax.

Multi-chip TPU hardware is not available in CI; sharding correctness is
validated on a host-platform virtual mesh (the driver separately dry-runs
the multi-chip path via __graft_entry__.dryrun_multichip). The environment's
sitecustomize may pre-register a TPU backend and pin jax_platforms, so the
config update below (not just the env var) is what actually forces CPU.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# Persistent compilation cache (DESIGN §10): most suite wall-clock is XLA
# recompiles of near-identical step programs. Two tiers cut it: the
# process-level PROGRAM_CACHE (madsim_tpu/compile) shares executables
# across Runtime constructions WITHIN the run, and this on-disk cache
# reuses them ACROSS runs. scripts/ci.sh exports JAX_COMPILATION_CACHE_DIR
# (workspace-local); default to the same path for bare pytest runs.
jax.config.update(
    "jax_compilation_cache_dir",
    os.environ.get("JAX_COMPILATION_CACHE_DIR",
                   os.path.join(os.path.dirname(__file__), "..",
                                ".jax_cache")))
# Floor RAISED from 1.0s (r16): this jaxlib's deserialized-executable
# first-invocation corruption (ROADMAP r12 open item) reproduces WITHOUT
# concurrency at ~1/5 per fresh process on small fused runners read from
# this cache (a masked lane-gate came back all-False — repro in the r16
# notes; the r15 profiler masked tests flake the same way standalone).
# Small programs recompile in ~a second anyway — keeping only compiles
# ≥5s persistent removes the high-traffic deserializations from the
# corruption surface while the expensive flagship executables (the
# reason this cache exists) stay cached. Retire with the r12 item when
# the toolchain moves.
jax.config.update("jax_persistent_cache_min_compile_time_secs", 5.0)


def pytest_sessionfinish(session, exitstatus):
    """Print the compile-counter summary at suite end (scripts/ci.sh sets
    MADSIM_COMPILE_SUMMARY=1): how many retraces the suite paid, by
    runner label, plus program-cache hit rates and jax stage seconds."""
    if not os.environ.get("MADSIM_COMPILE_SUMMARY"):
        return
    try:
        from madsim_tpu.compile.cache import COMPILE_LOG
        print(f"\n{COMPILE_LOG.summary()}")
    except Exception:  # noqa: BLE001 - reporting must never fail the suite
        pass
