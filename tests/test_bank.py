"""Bank workload: money conservation under chaos (the Jepsen bank test
shape), both as a per-event invariant and on client-observed snapshots."""

import numpy as np
import pytest

from madsim_tpu import Scenario, SimConfig, NetConfig, ms, sec
from madsim_tpu.harness.simtest import SimFailure, run_seeds
from madsim_tpu.models import bank as B
from madsim_tpu.models.bank import make_bank_runtime

SEEDS = np.arange(8)
TOTAL = 6 * 100


pytestmark = pytest.mark.slow  # measured in --durations; ci.sh fast skips

class TestBank:
    def test_clean_run_conserves(self):
        rt = make_bank_runtime(n_raft=3, n_clients=2, n_ops=6,
                               log_capacity=32)
        state = run_seeds(rt, SEEDS, max_steps=40_000)
        totals = np.asarray(state.node_state["h_total"])[:, 3:]
        resp = np.asarray(state.node_state["h_resp"])[:, 3:]
        seen = totals[resp >= 0]
        assert len(seen) > 0
        assert (seen == TOTAL).all()

    def test_chaos_conserves(self):
        cfg = SimConfig(n_nodes=8, event_capacity=96, payload_words=13,
                        time_limit=sec(8),
                        net=NetConfig(packet_loss_rate=0.05))
        sc = Scenario()
        for t in range(4):
            sc.at(ms(800 + 800 * t)).kill_random(among=range(5))
            sc.at(ms(1300 + 800 * t)).restart_random(among=range(5))
        sc.at(sec(2)).partition([0, 1])
        sc.at(sec(3)).heal()
        rt = make_bank_runtime(n_raft=5, n_clients=3, n_ops=8,
                               log_capacity=48, scenario=sc, cfg=cfg)
        state = run_seeds(rt, SEEDS, max_steps=60_000)
        totals = np.asarray(state.node_state["h_total"])[:, 5:]
        resp = np.asarray(state.node_state["h_resp"])[:, 5:]
        seen = totals[resp >= 0]
        assert len(seen) > 0
        assert (seen == TOTAL).all()

    def test_corruption_detector(self):
        # sabotage replication: flip an amount on one node's committed log
        # entry via a poisoned program variant — the per-event conservation
        # invariant must catch it with a reproducing seed
        class Leaky(B.RaftBank):
            def _extra_message(self, ctx, st, src, tag, payload):
                super()._extra_message(ctx, st, src, tag, payload)
                # bug: the 5th appended entry's amount gets inflated
                import jax.numpy as jnp
                bad = (st["log_len"] == 5) & (st["log_op"][4] == B.OP_TRANSFER)
                st["log_amt"] = st["log_amt"].at[4].set(
                    jnp.where(bad, st["log_amt"][4] + 7, st["log_amt"][4]))

        from madsim_tpu.models.bank import (BankClient, all_clients_done,
                                            bank_invariant, bank_persist_spec,
                                            bank_state_spec)
        from madsim_tpu import Runtime
        n_raft, n_clients = 3, 2
        n = n_raft + n_clients
        cfg = SimConfig(n_nodes=n, event_capacity=96, payload_words=13,
                        time_limit=sec(20))
        rt = Runtime(cfg, [Leaky(n, 6, 100, 32, n_peers=n_raft),
                           BankClient(n_raft, 6, 6)],
                     bank_state_spec(n, 32, 6),
                     node_prog=np.asarray([0] * n_raft + [1] * n_clients),
                     invariant=bank_invariant(n, 32, n_raft, 6, 100),
                     persist=bank_persist_spec(),
                     halt_when=all_clients_done(n_raft, 6))
        with pytest.raises(SimFailure) as ei:
            run_seeds(rt, np.arange(16), max_steps=40_000)
        assert ei.value.code in (B.CRASH_MONEY_LEAK,
                                 102)  # money leak or log-matching divergence
        state, _ = rt.run_single(ei.value.seed, max_steps=40_000)
        assert bool(state.crashed.all())
