"""Single-process sharded-engine tests on the virtual 8-device CPU mesh
(tests/conftest.py forces it): the seed batch shards with no per-step
communication, and the engine lowering knobs (int16 table columns,
scatter emission writes) compile and run under a mesh too — the
in-process complement of the driver's dryrun_multichip and the
2-process suite."""

import jax
import numpy as np

# back in tier-1 (r8 durations re-triage): the file was `slow` because it
# compiles many distinct step programs per run; with the shared
# ProgramCache + persistent compile cache live it measures ~15s warm /
# well inside tier-1's headroom cold (ROADMAP wall-clock item)

from madsim_tpu import Runtime, Scenario, SimConfig, NetConfig, ms
from madsim_tpu.core.types import sec
from madsim_tpu.models.pingpong import PingPong, state_spec
from madsim_tpu.parallel.mesh import seed_mesh, shard_batch
from madsim_tpu.utils.hashing import fingerprint

B = 64


def _rt(**cfg_kw):
    n = 3
    sc = Scenario()
    sc.at(ms(5)).kill_random()
    sc.at(ms(300)).restart_random()
    cfg = SimConfig(n_nodes=n, time_limit=sec(5),
                    net=NetConfig(packet_loss_rate=0.1), **cfg_kw)
    return Runtime(cfg, [PingPong(n, target=4, retry=ms(20))], state_spec(),
                   scenario=sc)


def _fps(rt, state):
    return np.asarray(jax.vmap(fingerprint)(state))


class TestShardedEngine:
    def test_sharded_run_bit_matches_unsharded(self):
        rt = _rt()
        plain, _ = rt.run(rt.init_batch(np.arange(B)), max_steps=4000)
        mesh = seed_mesh()
        assert mesh.devices.size >= 8          # conftest's virtual mesh
        sharded = shard_batch(rt.init_batch(np.arange(B)), mesh)
        sharded, _ = rt.run(sharded, max_steps=4000)
        assert bool(sharded.halted.all())
        np.testing.assert_array_equal(_fps(rt, plain), _fps(rt, sharded))

    def test_int16_columns_shard(self):
        # the narrow-dtype state shards and stays bit-identical to the
        # unsharded int32 run
        rt32 = _rt()
        plain, _ = rt32.run(rt32.init_batch(np.arange(B)), max_steps=4000)
        rt16 = _rt(table_dtype="int16")
        sharded = shard_batch(rt16.init_batch(np.arange(B)), seed_mesh())
        sharded, _ = rt16.run(sharded, max_steps=4000)
        assert bool(sharded.halted.all())
        np.testing.assert_array_equal(_fps(rt32, plain),
                                      _fps(rt16, sharded))

    def test_scatter_emission_shards(self):
        # the scatter emission lowering partitions along the seed axis
        # and stays bit-identical to the one-hot run under the mesh
        rt_oh = _rt(emission_write="onehot")
        plain, _ = rt_oh.run(rt_oh.init_batch(np.arange(B)), max_steps=4000)
        rt_sc = _rt(emission_write="scatter")
        sharded = shard_batch(rt_sc.init_batch(np.arange(B)), seed_mesh())
        state, _ = rt_sc.run(sharded, max_steps=4000)
        assert bool(state.halted.all())
        assert not bool(state.crashed.any())
        np.testing.assert_array_equal(_fps(rt_oh, plain),
                                      _fps(rt_sc, state))
