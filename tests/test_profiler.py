"""Sim-profiler counter plane (DESIGN §16): counters as pure observers.

The load-bearing properties: (1) profiling is an observation lever —
trajectories are bit-identical leaf-for-leaf with the plane on, off,
compiled out, or lane-masked, and the pf_* columns are excluded from
fingerprints so partial profiling can never split `distinct_outcomes`;
(2) counters SATURATE at int32 max, never wrap; (3) the counters agree
with a host-replayed reference computed from the collect_events stream;
(4) fuzzer yield attribution sums to admissions; (5) the durable
campaign timeline folds with no gaps and no double-counted rounds, and
stale workers are flagged.
"""

import io
import json
import os

import jax
import numpy as np
import pytest

from madsim_tpu import (JsonlObserver, NetConfig, Runtime, Scenario,
                        SimConfig, ms, sec, summarize)
from madsim_tpu.core.state import N_EV_KINDS, TRACE_FIELDS
from madsim_tpu.models.pingpong import PingPong, state_spec
from madsim_tpu.obs import (counter_track_events, export_profile_trace,
                            format_profile, profile_summary)
from madsim_tpu.parallel.stats import profile_counters, profile_digest

I32_MAX = 2**31 - 1


def _pingpong_rt(profile=True, target=6, n_nodes=2, scenario=None,
                 loss=0.0, trace_cap=0, sketch_slots=0):
    cfg = SimConfig(n_nodes=n_nodes, time_limit=sec(5), profile=profile,
                    trace_cap=trace_cap, sketch_slots=sketch_slots,
                    net=NetConfig(packet_loss_rate=loss,
                                  send_latency_min=ms(1),
                                  send_latency_max=ms(4)))
    return Runtime(cfg, [PingPong(n_nodes, target=target)], state_spec(),
                   scenario=scenario)


def _nonprofile_state(state) -> dict:
    out = {}
    for name in type(state).__dataclass_fields__:
        if name in TRACE_FIELDS or name in ("node_state", "ext"):
            continue
        out[name] = np.asarray(getattr(state, name))
    for i, leaf in enumerate(jax.tree.leaves(state.node_state)):
        out[f"node_state_{i}"] = np.asarray(leaf)
    return out


class TestCounterPlane:
    def test_profile_never_perturbs_trajectory(self):
        # same workload, plane compiled out vs on vs lane-masked: every
        # non-observation field bit-identical (profile is an observation
        # lever, not a replay domain)
        seeds = np.arange(16, dtype=np.uint32)
        rt0 = _pingpong_rt(profile=False)
        base, _ = rt0.run(rt0.init_batch(seeds), 256, 64)
        ref = _nonprofile_state(base)
        for lanes in (None, [0, 3], []):
            rt = _pingpong_rt(profile=True)
            st, _ = rt.run(rt.init_batch(seeds, profile_lanes=lanes),
                           256, 64)
            got = _nonprofile_state(st)
            assert ref.keys() == got.keys()
            for k in ref:
                assert (ref[k] == got[k]).all(), f"lanes={lanes}: {k}"
            assert (rt0.fingerprints(base) == rt.fingerprints(st)).all()

    def test_fused_equals_chunked_on_counters(self):
        rt = _pingpong_rt(profile=True, target=40)
        seeds = np.arange(8, dtype=np.uint32)
        chunked, _ = rt.run(rt.init_batch(seeds), 256, 64)
        fused = rt.run_fused(rt.init_batch(seeds), 256, 64)
        for f in TRACE_FIELDS:
            assert (np.asarray(getattr(chunked, f))
                    == np.asarray(getattr(fused, f))).all(), f

    def test_partial_lanes_cannot_split_outcomes(self):
        # fingerprint exclusion: profiling half the lanes must leave
        # distinct_outcomes a trajectory metric
        seeds = np.arange(8, dtype=np.uint32)
        rt = _pingpong_rt(profile=True)
        sampled, _ = rt.run(rt.init_batch(seeds, profile_lanes=[0, 1]),
                            256, 64)
        allon, _ = rt.run(rt.init_batch(seeds), 256, 64)
        assert (rt.fingerprints(sampled) == rt.fingerprints(allon)).all()
        assert (summarize(rt, sampled, seeds)["distinct_outcomes"]
                == summarize(rt, allon, seeds)["distinct_outcomes"])

    def test_masked_lanes_count_nothing(self):
        rt = _pingpong_rt(profile=True, target=40)
        st = rt.run_fused(rt.init_batch(np.arange(4), profile_lanes=[2]),
                          128, 64)
        disp = np.asarray(st.pf_dispatch)
        assert disp[2].sum() > 0
        assert disp[[0, 1, 3]].sum() == 0
        assert np.asarray(st.pf_busy)[[0, 1, 3]].sum() == 0
        assert (np.asarray(st.pf_qmax)[[0, 1, 3]] == 0).all()

    def test_profile_lanes_requires_compiled_plane(self):
        rt = _pingpong_rt(profile=False)
        with pytest.raises(ValueError, match="profile"):
            rt.init_batch(np.arange(4), profile_lanes=[0])

    def test_dispatch_counts_and_busy_match_host_replay(self):
        # the seeded-reference contract: counters equal what a host
        # walk of the collect_events stream computes (fixed kill
        # targets so super attribution is record-visible)
        sc = Scenario()
        sc.at(ms(6)).kill(1)
        sc.at(ms(9)).restart(1)
        rt = _pingpong_rt(profile=True, target=12, scenario=sc)
        state, events = rt.run(rt.init_batch(np.arange(4)), 512, 128,
                               collect_events=True)
        fired = np.asarray(events["fired"])
        kind = np.asarray(events["kind"])
        node = np.asarray(events["node"])
        now = np.asarray(events["now"])
        disp = np.asarray(state.pf_dispatch)
        busy = np.asarray(state.pf_busy)
        for b in range(4):
            idx = np.nonzero(fired[:, b])[0]
            ref_d = np.zeros((2, N_EV_KINDS), np.int64)
            ref_b = np.zeros(2, np.int64)
            prev = 0
            for i in idx:
                ref_d[int(node[i, b]), int(kind[i, b])] += 1
                ref_b[int(node[i, b])] += int(now[i, b]) - prev
                prev = int(now[i, b])
            assert (disp[b] == ref_d).all(), b
            assert (busy[b] == ref_b).all(), b
        # the scheduled kill/restart landed on node 1, every lane
        assert (np.asarray(state.pf_kill)[:, 1] == 2).all()  # kill+restart
        assert (np.asarray(state.pf_restart)[:, 1] == 2).all()  # boot+restart

    def test_busy_sums_to_now(self):
        rt = _pingpong_rt(profile=True, target=40)
        st = rt.run_fused(rt.init_batch(np.arange(8)), 256, 64)
        assert (np.asarray(st.pf_busy).sum(-1) == np.asarray(st.now)).all()

    def test_qmax_positive_and_bounded(self):
        rt = _pingpong_rt(profile=True, target=40)
        st = rt.run_fused(rt.init_batch(np.arange(8)), 256, 64)
        q = np.asarray(st.pf_qmax)
        assert (q > 0).all()
        assert (q <= rt.cfg.event_capacity).all()

    def test_counters_saturate_no_wraparound(self):
        # plant counters at the brink: further increments must peg at
        # int32 max, never wrap negative
        import jax.numpy as jnp
        rt = _pingpong_rt(profile=True, target=40)
        st = rt.init_batch(np.arange(4))
        st = st.replace(
            pf_delay=jnp.full_like(st.pf_delay, I32_MAX - 3),
            pf_busy=jnp.full_like(st.pf_busy, I32_MAX - 1),
            pf_dispatch=jnp.full_like(st.pf_dispatch, I32_MAX))
        final = rt.run_fused(st, 256, 64)
        for f in ("pf_delay", "pf_busy", "pf_dispatch"):
            v = np.asarray(getattr(final, f))
            assert (v >= 0).all(), f
            assert (v <= I32_MAX).all(), f
        assert (np.asarray(final.pf_delay) == I32_MAX).all()
        assert (np.asarray(final.pf_busy) == I32_MAX).all()
        assert (np.asarray(final.pf_dispatch) == I32_MAX).all()

    def test_drops_counted_on_lossy_net(self):
        rt = _pingpong_rt(profile=True, target=1 << 30, loss=0.3)
        st = rt.run_fused(rt.init_batch(np.arange(8)), 256, 64)
        assert int(np.asarray(st.pf_drop).sum()) > 0
        assert int(np.asarray(st.pf_delay).sum()) > 0


class TestFlagshipEquivalence:
    """Leaf-for-leaf equivalence with profiling on/off/compiled-out over
    the flagships — the r7 ring pattern: the fast lane holds pingpong
    (above) plus wal_kv here; the full raft/wal_kv/shard_kv matrix is
    `slow`."""

    def _assert_profile_transparent(self, make_rt, seeds, steps, chunk):
        rt_on = make_rt(True)
        rt_off = make_rt(False)
        on, _ = rt_on.run(rt_on.init_batch(seeds), steps, chunk)
        off, _ = rt_off.run(rt_off.init_batch(seeds), steps, chunk)
        fused = rt_on.run_fused(rt_on.init_batch(seeds), steps, chunk)
        ref = _nonprofile_state(off)
        got = _nonprofile_state(on)
        assert ref.keys() == got.keys()
        for k in ref:
            assert (ref[k] == got[k]).all(), k
        assert (rt_on.fingerprints(on) == rt_off.fingerprints(off)).all()
        for f in TRACE_FIELDS:
            assert (np.asarray(getattr(on, f))
                    == np.asarray(getattr(fused, f))).all(), f
        return on

    def test_wal_kv_profile_transparent(self):
        from madsim_tpu.models.wal_kv import make_wal_kv_runtime

        def make(profile):
            sc = Scenario()
            for t in range(6):
                sc.at(ms(150) + ms(250) * t).kill(0)
                sc.at(ms(210) + ms(250) * t).restart(0)
            cfg = SimConfig(n_nodes=3, event_capacity=256, payload_words=8,
                            time_limit=sec(10), profile=profile,
                            net=NetConfig(send_latency_min=ms(1),
                                          send_latency_max=ms(8)))
            return make_wal_kv_runtime(n_clients=2, n_ops=8, wal_cap=64,
                                       sync_wal=False, scenario=sc, cfg=cfg)

        on = self._assert_profile_transparent(
            make, np.arange(16, dtype=np.uint32), 2048, 512)
        # the chaos matrix's kills landed and were counted at node 0
        assert int(np.asarray(on.pf_kill)[:, 0].sum()) > 0

    @pytest.mark.slow
    def test_raft_profile_transparent(self):
        from madsim_tpu.models.raft import make_raft_runtime

        def make(profile):
            cfg = SimConfig(n_nodes=5, event_capacity=128,
                            time_limit=sec(3), profile=profile,
                            net=NetConfig(packet_loss_rate=0.05,
                                          send_latency_min=ms(1),
                                          send_latency_max=ms(10)))
            sc = Scenario()
            sc.at(sec(1)).kill_random()
            sc.at(sec(1) + ms(400)).restart_random()
            return make_raft_runtime(5, 8, n_cmds=4, scenario=sc, cfg=cfg)

        self._assert_profile_transparent(
            make, np.arange(64, dtype=np.uint32), 1500, 256)

    @pytest.mark.slow
    def test_shard_kv_profile_transparent(self):
        from madsim_tpu.models.shard_kv import make_shard_runtime

        def make(profile):
            cfg = SimConfig(n_nodes=11, event_capacity=160,
                            payload_words=12, time_limit=sec(60),
                            profile=profile,
                            net=NetConfig(send_latency_min=ms(1),
                                          send_latency_max=ms(10)))
            return make_shard_runtime(n_groups=2, rg=3, rc=3, n_clients=2,
                                      n_ops=4, max_cfg=4, cfg=cfg)

        self._assert_profile_transparent(
            make, np.arange(64, dtype=np.uint32), 4096, 512)


class TestDigestAndReport:
    def test_digest_compiled_out_is_none(self):
        rt = _pingpong_rt(profile=False)
        st, _ = rt.run(rt.init_batch(np.arange(2)), 128, 64)
        assert profile_digest(st) is None
        assert profile_counters(st) is None
        assert profile_summary(st) is None
        assert summarize(rt, st)["profile"] is None
        assert "compiled out" in format_profile(None)

    def test_summary_sums_and_masking(self):
        rt = _pingpong_rt(profile=True, target=40)
        st = rt.run_fused(rt.init_batch(np.arange(8),
                                        profile_lanes=[1, 4]), 256, 64)
        c = profile_counters(st)
        assert c["lanes"] == 2
        steps = np.asarray(st.steps)
        assert c["dispatch"].sum() == steps[[1, 4]].sum()
        # per-lane percentiles cover only the profiled lanes
        assert c["steps_max"] == steps[[1, 4]].max()
        assert c["now_sum"] == np.asarray(st.now)[[1, 4]].sum()
        s = profile_summary(st)
        assert s["dispatches"] == int(steps[[1, 4]].sum())
        assert abs(sum(s["busy_pct"]) - 100.0) < 1.0
        txt = format_profile(s, node_names=["ping", "pong"])
        assert "ping" in txt and str(s["dispatches"]) in txt
        rep = summarize(rt, st, np.arange(8))
        assert rep["profile"]["lanes"] == 2

    def test_batch_sums_do_not_wrap_int32(self):
        # the digest's batch sums must stay exact past 2^31: 64 lanes
        # of pegged counters sum to 64*IMAX — a plain int32 reduction
        # would wrap negative (the reading the saturating per-lane
        # counters exist to prevent)
        import jax.numpy as jnp
        rt = _pingpong_rt(profile=True)
        st = rt.init_batch(np.arange(64))
        st = st.replace(pf_busy=jnp.full_like(st.pf_busy, I32_MAX),
                        pf_delay=jnp.full_like(st.pf_delay, I32_MAX))
        c = profile_counters(st)
        assert (c["busy"] == 64 * I32_MAX).all()
        assert c["delay"] == 64 * I32_MAX > 2**31

    def test_all_masked_batch_reports_zero_percentiles(self):
        # the ship-with-it masked shape: no profiled lanes must read as
        # zeros, not as the int32-max sort sentinel
        rt = _pingpong_rt(profile=True, target=40)
        st = rt.run_fused(rt.init_batch(np.arange(8), profile_lanes=[]),
                          256, 64)
        c = profile_counters(st)
        assert c["lanes"] == 0
        assert c["qmax_p50"] == c["qmax_max"] == 0
        assert c["steps_max"] == 0 and c["now_max"] == 0
        assert c["dispatch"].sum() == 0

    def test_counter_tracks_and_export(self, tmp_path):
        rt = _pingpong_rt(profile=True, target=40, trace_cap=32,
                          sketch_slots=4)
        st = rt.run_fused(rt.init_batch(np.arange(4)), 192, 64)
        evs = counter_track_events(st, lane=0)
        names = {e["name"] for e in evs}
        assert "queue_depth" in names
        assert any(n.startswith("busy_pct:") for n in names)
        assert "cov_divergence" in names
        depths = [e["args"]["depth"] for e in evs
                  if e["name"] == "queue_depth"]
        assert depths and all(0 < d <= rt.cfg.event_capacity
                              for d in depths)
        p = str(tmp_path / "prof.json")
        n = export_profile_trace(p, st, lane=0, node_names=["a", "b"])
        with open(p) as f:
            doc = json.load(f)
        assert n == len([e for e in doc["traceEvents"]
                         if e.get("ph") == "i"]) > 0
        assert [e for e in doc["traceEvents"] if e.get("ph") == "C"]

    def test_qlen_column_needs_both_gates(self):
        from madsim_tpu.obs import ring_records
        rt = _pingpong_rt(profile=False, target=40, trace_cap=16)
        st = rt.run_fused(rt.init_batch(np.arange(2)), 128, 64)
        assert "qlen" not in ring_records(st, 0)
        rt2 = _pingpong_rt(profile=True, target=40, trace_cap=16)
        st2 = rt2.run_fused(rt2.init_batch(np.arange(2)), 128, 64)
        recs = ring_records(st2, 0)
        assert "qlen" in recs and (recs["qlen"] > 0).all()


class TestYieldAttribution:
    def test_mutate_returns_last_op(self):
        from bench import _make_saturating_runtime
        from madsim_tpu.search.mutate import N_MUT_OPS, KnobPlan
        rt = _make_saturating_runtime()
        plan = KnobPlan.from_runtime(rt, dup_slots=2)
        _, hist, last = plan.mutate(plan.base_batch(16),
                                    jax.random.PRNGKey(0), havoc=4)
        last = np.asarray(last)
        assert last.shape == (16,)
        assert ((last >= -1) & (last < N_MUT_OPS)).all()
        assert (last >= 0).any()        # some operator landed somewhere
        _, z_hist, z_last = plan.mutate(plan.base_batch(4),
                                        jax.random.PRNGKey(0), havoc=0)
        assert (np.asarray(z_last) == -1).all()
        assert np.asarray(z_hist).sum() == 0

    def test_mutate_masked_clears_attribution(self):
        from bench import _make_saturating_runtime
        from madsim_tpu.search.mutate import KnobPlan
        rt = _make_saturating_runtime()
        plan = KnobPlan.from_runtime(rt, dup_slots=2)
        mask = np.zeros(16, bool)
        mask[8:] = True
        _, _, last = plan.mutate_masked(plan.base_batch(16),
                                        jax.random.PRNGKey(0), mask,
                                        havoc=4)
        last = np.asarray(last)
        assert (last[:8] == -1).all()
        assert (last[8:] >= 0).any()

    def test_round_yield_sums_to_admissions(self):
        from bench import _make_saturating_runtime
        from madsim_tpu.search.fuzz import fuzz
        rt = _make_saturating_runtime()
        obs = JsonlObserver(io.StringIO())
        res = fuzz(rt, max_steps=400, batch=32, max_rounds=4,
                   dry_rounds=9, chunk=128, rng_seed=0, observer=obs)
        rounds = [r for r in obs.records if r.get("kind") == "fuzz_round"]
        assert rounds
        for rec in rounds:
            assert sum(rec["op_yield"].values()) == rec["admitted"]
            assert rec["corpus_energy"]["entries"] == rec["corpus_size"]
        assert (sum(res["mutation_yield"].values())
                == sum(r["admitted"] for r in rounds))
        assert res["corpus_energy"]["entries"] == res["corpus_size"]

    def test_corpus_energy_summary(self):
        from bench import _make_saturating_runtime
        from madsim_tpu.search.corpus import Corpus
        from madsim_tpu.search.mutate import KnobPlan
        rt = _make_saturating_runtime()
        plan = KnobPlan.from_runtime(rt)
        c = Corpus(plan)
        assert c.energy_summary() == dict(entries=0)
        kb = plan.base_batch(3)
        c.observe(kb, np.arange(3), np.asarray([1, 2, 3], np.uint64),
                  np.asarray([False, True, False]),
                  np.asarray([0, 5, 0], np.int64),
                  np.full(3, -1, np.int64), 0)
        es = c.energy_summary()
        assert es["entries"] == 3 and es["crash_entries"] == 1
        assert es["max"] >= es["p50"] >= 0


class TestCampaignTimeline:
    def _fuzz_kw(self):
        return dict(max_steps=400, batch=16, dry_rounds=9, chunk=128,
                    rng_seed=0)

    def test_killed_and_resumed_timeline_no_gaps_no_dups(self, tmp_path):
        # the acceptance shape, in-process: a campaign interrupted at
        # round 2 and resumed to 4 (the kill+resume contract: a resumed
        # run re-derives the interrupted round identically) plus a
        # second worker — the folded timeline must be gapless and
        # dedup'd per worker
        from bench import _make_saturating_runtime
        from madsim_tpu.search.fuzz import fuzz
        from madsim_tpu.service.campaign import (campaign_report,
                                                 campaign_timeline)
        from madsim_tpu.service.store import CorpusStore
        d = str(tmp_path / "c")
        rt = _make_saturating_runtime()
        # warm every executable OUTSIDE the measured campaign (the
        # bench.py A/B pattern): the staleness flag compares real wall
        # gaps, and a cold compile landing inside one worker's run but
        # not another's (e.g. the first suite run after a structural-
        # signature bump) would skew age-vs-cadence into a flake
        fuzz(rt, corpus_dir=str(tmp_path / "warm"), worker_id=0,
             max_rounds=2, **self._fuzz_kw())
        fuzz(rt, corpus_dir=d, worker_id=0, max_rounds=2,
             **self._fuzz_kw())
        fuzz(rt, corpus_dir=d, worker_id=0, max_rounds=4,
             **self._fuzz_kw())
        fuzz(rt, corpus_dir=d, worker_id=1, max_rounds=3, base_seed=7,
             **self._fuzz_kw())
        store = CorpusStore(d, create=False)
        tl = campaign_timeline(store)
        for w, want in (("w0000", [1, 2, 3, 4]), ("w0001", [1, 2, 3])):
            rd = [r["rounds_done"] for r in tl["timeline"]
                  if r["worker"] == w]
            assert rd == want, (w, rd)
        cov = [c for _, c in tl["coverage_curve"]]
        assert cov == sorted(cov) and cov[-1] > 0
        assert tl["rate_curve"]
        # health check with headroom: these "workers" ran SEQUENTIALLY
        # in one process, so worker 0's age at the campaign's newest row
        # is worker 1's whole run — harness serialization, not campaign
        # dynamics. The default 3x-cadence window is calibrated for
        # concurrent workers (test_stale_worker_flagged covers the
        # positive case synthetically); here a suite-load wobble of
        # ~100ms must not read as a dead worker.
        tl10 = campaign_timeline(store, stale_after=10.0)
        assert not any(h["stale"] for h in tl10["workers_health"].values())
        rep = campaign_report(d, stale_after=10.0)
        assert rep["stale_workers"] == []
        assert rep["coverage_curve"] == tl["coverage_curve"]
        # per-round op_yield survives the resume in the worker state
        ws = store.load_worker_state(0)
        assert sum(ws["op_yield"]) > 0

    def test_duplicate_rows_dedup_keep_last(self, tmp_path):
        from madsim_tpu.service.campaign import campaign_timeline
        from madsim_tpu.service.store import CorpusStore, store_signature
        d = str(tmp_path / "c")
        store = CorpusStore(d, signature=["sig"])
        t0 = 1000.0
        store.append_metrics(0, dict(t=t0, rounds_done=1, coverage=3,
                                     wall_s=1.0))
        # the kill-between-append-and-commit shape: same round
        # re-appended on resume — the LAST occurrence wins
        store.append_metrics(0, dict(t=t0 + 1, rounds_done=1, coverage=3,
                                     wall_s=1.0))
        store.append_metrics(0, dict(t=t0 + 2, rounds_done=2, coverage=5,
                                     wall_s=2.0))
        tl = campaign_timeline(store)
        rows = [r for r in tl["timeline"] if r["worker"] == "w0000"]
        assert [r["rounds_done"] for r in rows] == [1, 2]
        assert rows[0]["t"] == t0 + 1

    def test_rate_curve_uses_campaign_wall_not_row_wall(self, tmp_path):
        # a young worker's first sync (tiny own wall) must not spike the
        # schedules/s curve against the campaign-global coverage — the
        # denominator is the max over workers' walls so far, the
        # campaign_stats rule over time
        from madsim_tpu.service.campaign import campaign_timeline
        from madsim_tpu.service.store import CorpusStore
        d = str(tmp_path / "c")
        store = CorpusStore(d, signature=["sig"])
        store.append_metrics(0, dict(t=1000.0, rounds_done=1,
                                     coverage=10000, wall_s=100.0))
        store.append_metrics(1, dict(t=1001.0, rounds_done=1,
                                     coverage=10, wall_s=1.0))
        tl = campaign_timeline(store)
        assert tl["rate_curve"][0][1] == 100.0          # 10000 / 100
        assert tl["rate_curve"][1][1] == 100.0          # not 10000 / 1

    def test_stale_worker_flagged(self, tmp_path):
        from madsim_tpu.service.campaign import campaign_timeline
        from madsim_tpu.service.store import CorpusStore
        d = str(tmp_path / "c")
        store = CorpusStore(d, signature=["sig"])
        t0 = 1000.0
        for r in range(4):      # healthy cadence: a row every 2s
            store.append_metrics(0, dict(t=t0 + 2 * r, rounds_done=r + 1,
                                         coverage=r, wall_s=r + 1.0))
        # worker 1 stopped syncing long before the campaign's last
        # activity (> 3x its own 2s cadence)
        store.append_metrics(1, dict(t=t0 - 100, rounds_done=1,
                                     coverage=1, wall_s=1.0))
        store.append_metrics(1, dict(t=t0 - 98, rounds_done=2,
                                     coverage=2, wall_s=2.0))
        tl = campaign_timeline(store)
        assert tl["workers_health"]["w0001"]["stale"] is True
        assert tl["workers_health"]["w0000"]["stale"] is False

    def test_jsonl_observer_fsync(self, tmp_path):
        p = str(tmp_path / "log.jsonl")
        obs = JsonlObserver(p, fsync=True)
        obs.on_round(dict(kind="fuzz_round", round=1))
        obs.close()
        with open(p) as f:
            assert json.loads(f.readline())["round"] == 1
        with pytest.raises(io.UnsupportedOperation):
            JsonlObserver(io.StringIO(), fsync=True)


class TestCheckpointMigration:
    def test_pre_r15_checkpoint_rejected_by_leaf_count(self, tmp_path):
        # the MIGRATION r15 contract: a pre-r15 checkpoint (no pf_* or
        # tr_qlen leaves — 9 fewer) fails load() loudly on the leaf
        # count, not by silent misalignment
        from madsim_tpu.runtime import checkpoint
        rt = _pingpong_rt(profile=True)
        st = rt.init_batch(np.arange(2))
        p = str(tmp_path / "ck.npz")
        checkpoint.save(p, st)
        with np.load(p) as z:
            leaves = {k: z[k] for k in z.files}
        n = len([k for k in leaves if k.startswith("leaf_")])
        stripped = {k: v for k, v in leaves.items()
                    if not k.startswith("leaf_")}
        for i in range(n - 9):      # a pre-r15 file simply has fewer
            stripped[f"leaf_{i}"] = leaves[f"leaf_{i}"]
        p2 = str(tmp_path / "old.npz")
        np.savez_compressed(p2, **stripped)
        with pytest.raises(ValueError, match="leaves"):
            checkpoint.load(p2, st)
