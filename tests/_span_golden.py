"""Shared harness for the r23 bit-identical-when-disabled contract.

The critical-path attribution plane (r23) added engine machinery — the
per-row accumulated span columns (`ev_span`), the tail-attribution
counters (`sa_tail`, `sa_bottleneck`), the `tr_qw` ring column, the
`sp_on` lane gate — that is compiled out at the default
`span_attr=False` and masked to identity when compiled in but no lane
records. The contract is that a workload never enabling the plane
produces trajectories BIT-IDENTICAL to r22, leaf for leaf, chunked and
fused.

Same frozen workload builders as the r17/r19/r21 harnesses
(_grayfail_golden — the canonical engine-equivalence workloads); digests
were captured AT r22 HEAD by scripts/capture_golden.py into
tests/data/golden_r22_leaves.json, before any r23 engine change landed.
Every r22 leaf must still exist and hash identically — the only new
leaves the r23 plane may add are the span plane's own
(`.sp_on` and the zero-size span columns the simconfig-v8 signature
gates).
"""

from __future__ import annotations

import os

import _grayfail_golden as _g

GOLDEN_PATH = os.path.join(os.path.dirname(__file__), "data",
                           "golden_r22_leaves.json")

# the frozen definition is shared with the r17/r19/r21 harnesses — one
# set of engine workloads, four captured truths (r16, r18, r20, r22)
RUNS = _g.RUNS
BUILDERS = _g.BUILDERS
leaf_digests = _g.leaf_digests
run_workload = _g.run_workload


def capture(path: str = GOLDEN_PATH) -> dict:
    return _g.capture(path)


def load_golden(path: str = GOLDEN_PATH) -> dict:
    with open(path) as f:
        import json
        return json.load(f)
