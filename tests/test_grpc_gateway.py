"""Third-party wire interop (PARITY §2.2): a vanilla gRPC client reaches a
RealRuntime-hosted generated service through the HTTP/2 gateway — the
real-tonic analog (production madsim-tonic re-exports real tonic,
madsim-tonic/src/lib.rs:7-8; here the standard wire is fronted by
examples/grpc_gateway.py instead of being the runtime's native format)."""

import os
import sys

import pytest

grpc = pytest.importorskip("grpc")

sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "examples"))
import grpc_gateway  # noqa: E402


@pytest.mark.realworld
class TestGrpcGateway:
    def test_vanilla_grpc_client_round_trips(self):
        # run_demo() is the example's own orchestration (spawn backend,
        # gateway up, client, teardown incl. kill-fallback) — reused, not
        # re-implemented, so the test cannot drift from the demo
        results = grpc_gateway.run_demo()
        # Put(0,100) + Put(1,101) landed; key 3 never written
        assert results[0] == (100, 1)
        assert results[1] == (101, 1)
        assert results[3] == (0, 0)

    def test_unknown_method_rejected(self):
        methods = grpc_gateway.schema_methods()
        assert "/store.Store/Put" in methods
        # the gateway's generic handler returns None for unknown paths —
        # grpc then surfaces UNIMPLEMENTED to the caller (checked without
        # sockets: the handler table simply has no such entry)
        assert "/store.Store/Nope" not in methods

    def test_request_width_validated(self):
        # a malformed third-party request must fail loudly at the gateway,
        # not truncate into the payload
        bridge = None
        try:
            bridge = grpc_gateway.UdpBridge(grpc_gateway.schema_methods())
            with pytest.raises(AssertionError, match="request bytes"):
                bridge.round_trip("/store.Store/Put", b"\x01\x02")
        finally:
            if bridge is not None:
                bridge.sock.close()
