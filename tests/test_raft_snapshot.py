"""Raft log compaction + InstallSnapshot tests (Raft §7).

The reference's MadRaft suite includes snapshot tests (BASELINE.md config 4);
here the log window (`log_capacity`) is deliberately SMALLER than the total
number of proposals, so trajectories only survive if compaction slides the
window and lagging nodes recover via InstallSnapshot. Safety below the
snapshot boundary is enforced by the digest-chain invariant, checked after
every event.
"""

import jax
import numpy as np
import pytest

pytestmark = pytest.mark.slow  # chaos-sweep-heavy (r7 durations triage);
# tier-1/ci.sh fast skip it so the fast lane fits its 870s budget cold

from madsim_tpu import Scenario, SimConfig, NetConfig, ms, sec
from madsim_tpu.harness.simtest import run_seeds
from madsim_tpu.models import raft as R
from madsim_tpu.models.raft import make_raft_runtime

N = 5
L = 12          # window much smaller than total proposals
CMDS = 30       # proposals > log_capacity: only works with compaction
SEEDS = np.arange(6)


def _rt(scenario=None, halt_on_commit=0, time_limit=sec(8), loss=0.0,
        **raft_kw):
    cfg = SimConfig(n_nodes=N, event_capacity=256, time_limit=time_limit,
                    net=NetConfig(packet_loss_rate=loss,
                                  send_latency_min=ms(1),
                                  send_latency_max=ms(10)))
    raft_kw.setdefault("compact_threshold", 4)
    return make_raft_runtime(N, L, n_cmds=CMDS,
                             halt_on_commit=halt_on_commit,
                             scenario=scenario, cfg=cfg, **raft_kw)


class TestCompaction:
    def test_log_wraps_past_capacity(self):
        # commit far more entries than the window holds; every live node
        # must have compacted, and live window occupancy stays <= L
        rt = _rt(halt_on_commit=CMDS, time_limit=sec(12))
        state = run_seeds(rt, SEEDS, max_steps=30_000)
        ns = state.node_state
        commit = np.asarray(ns["commit"])
        snap = np.asarray(ns["snap_len"])
        loglen = np.asarray(ns["log_len"])
        assert (commit.max(axis=1) >= CMDS).all()
        assert (snap.max(axis=1) > 0).all()            # compaction happened
        assert (loglen - snap <= L).all()              # window never overflows
        assert (snap <= commit).all()                  # only committed compacts
        # the invariant ran every event — reaching here means no violation

    def test_equal_snapshots_have_equal_digests(self):
        rt = _rt(halt_on_commit=CMDS, time_limit=sec(12))
        state = run_seeds(rt, SEEDS, max_steps=30_000)
        ns = state.node_state
        snap = np.asarray(ns["snap_len"])
        dig = np.asarray(ns["snap_digest"])
        for b in range(len(SEEDS)):
            for i in range(N):
                for j in range(N):
                    if snap[b, i] == snap[b, j] and snap[b, i] > 0:
                        assert dig[b, i] == dig[b, j], (b, i, j)

    def test_follower_catches_up_via_installsnapshot(self):
        # node 0 dies before replication gets going; the rest commit and
        # compact far past its log, so after restart AE alone cannot catch
        # it up — only InstallSnapshot can
        sc = Scenario()
        sc.at(ms(400)).kill(0)
        sc.at(sec(4)).restart(0)
        rt = _rt(scenario=sc, time_limit=sec(10))
        state = run_seeds(rt, SEEDS, max_steps=40_000)
        ns = state.node_state
        snap = np.asarray(ns["snap_len"])
        commit = np.asarray(ns["commit"])
        assert (commit.max(axis=1) >= CMDS).all()
        # node 0 received a snapshot (its own log never reached snap_len
        # entries before the kill) and caught up to the cluster
        assert (snap[:, 0] > 0).all()
        assert (commit[:, 0] >= CMDS - L).all()

    def test_window_full_at_threshold_under_chaos(self):
        # capacity edge: compact_threshold == log_capacity, so the window
        # must fill COMPLETELY (live == L, where _append starts dropping
        # proposals) before a slide becomes possible at all — progress
        # then depends on the full-window compact firing exactly at the
        # boundary. Red if the `live < L` append guard or the
        # shift >= threshold compare is off by one.
        sc = Scenario()
        sc.at(ms(900)).kill_random()
        sc.at(ms(1400)).restart_random()
        rt = _rt(scenario=sc, halt_on_commit=2 * L + 2,
                 time_limit=sec(12), compact_threshold=L)
        state = run_seeds(rt, SEEDS, max_steps=40_000)
        ns = state.node_state
        commit = np.asarray(ns["commit"])
        snap = np.asarray(ns["snap_len"])
        loglen = np.asarray(ns["log_len"])
        # committed past two full windows -> at least one full-window slide
        assert (commit.max(axis=1) >= 2 * L + 2).all()
        assert (snap.max(axis=1) >= L).all()
        # slides are exact multiples of nothing less than the threshold:
        # every snapshot boundary is >= L entries deep or still zero
        assert ((snap == 0) | (snap >= L)).all()
        assert (loglen - snap <= L).all()
        assert (np.asarray(state.oops) == 0).all()

    def test_chaos_with_compaction_safety(self):
        # rolling kills/restarts + a partition while the window wraps:
        # the per-event invariant (incl. digest chain) must hold throughout
        sc = Scenario()
        for t in range(4):
            sc.at(ms(700 + 900 * t)).kill_random()
            sc.at(ms(1200 + 900 * t)).restart_random()
        sc.at(sec(2)).partition([0, 1])
        sc.at(sec(3)).heal()
        rt = _rt(scenario=sc, time_limit=sec(8), loss=0.05)
        state = run_seeds(rt, SEEDS, max_steps=40_000)
        assert bool(state.halted.all())
        assert not bool(np.asarray(state.crashed).any())


class TestDigestChecker:
    def test_tampered_digest_is_caught(self):
        # the digest chain is a real safety net: corrupt one node's
        # snapshot digest and the invariant must flag LOG_MISMATCH
        rt = _rt(halt_on_commit=CMDS, time_limit=sec(12))
        state = run_seeds(rt, SEEDS, max_steps=30_000)
        s0 = jax.tree.map(lambda a: a[0], state)
        inv = R.raft_invariant(N, L)
        bad, _ = inv(s0)
        assert not bool(bad)
        ns = dict(s0.node_state)
        victim = int(np.asarray(ns["snap_len"]).argmax())
        ns["snap_digest"] = ns["snap_digest"].at[victim].add(1)
        bad, code = inv(s0.replace(node_state=ns))
        assert bool(bad)
        assert int(code) == R.CRASH_LOG_MISMATCH

    def test_tampered_live_entry_is_caught(self):
        rt = _rt(halt_on_commit=CMDS, time_limit=sec(12))
        state = run_seeds(rt, SEEDS, max_steps=30_000)
        s0 = jax.tree.map(lambda a: a[0], state)
        inv = R.raft_invariant(N, L)
        ns = dict(s0.node_state)
        # corrupt a committed live entry on the node with the most commits
        victim = int(np.asarray(ns["commit"]).argmax())
        snap = int(np.asarray(ns["snap_len"])[victim])
        commit = int(np.asarray(ns["commit"])[victim])
        assert commit > snap  # a live committed entry exists
        ns["log_cmd"] = ns["log_cmd"].at[victim, 0].add(7)
        bad, code = inv(s0.replace(node_state=ns))
        assert bool(bad)
        assert int(code) == R.CRASH_LOG_MISMATCH


class TestDeterminism:
    def test_replay_stable_with_compaction(self):
        rt = _rt(time_limit=sec(3))
        assert rt.check_determinism(seed=7, max_steps=8000)
