"""Failure detection: the heartbeat/suspect helpers driving a monitored
cluster — kill flips the victim into every survivor's suspect set within
a bounded delay, restart rehabilitates it."""

import jax.numpy as jnp
import numpy as np

from madsim_tpu import Program, Runtime, Scenario, SimConfig, NetConfig, ms
from madsim_tpu.core.types import sec
from madsim_tpu.utils import detector as fd

FD_TICK = 1
N = 5
PERIOD = ms(50)
TIMEOUT = ms(200)


class Monitored(Program):
    """Every node heartbeats and maintains its suspect mask."""

    def init(self, ctx):
        st = dict(ctx.state)
        st = fd.reset(st, ctx.now)      # boot grace period
        ctx.set_timer(ctx.randint(0, PERIOD), FD_TICK)
        ctx.state = st

    def on_timer(self, ctx, tag, payload):
        st = dict(ctx.state)
        tick = tag == FD_TICK
        st = fd.saw(st, ctx.node, ctx.now, when=tick)    # self-refresh
        fd.beat(ctx, N, when=tick)
        st["fd_susp"] = jnp.where(tick,
                                  fd.suspects(st, ctx.now, TIMEOUT),
                                  st["fd_susp"])
        ctx.set_timer(PERIOD, FD_TICK, when=tick)
        ctx.state = st

    def on_message(self, ctx, src, tag, payload):
        st = dict(ctx.state)
        st = fd.saw(st, src, ctx.now, when=tag == fd.TAG_HEARTBEAT)
        ctx.state = st


def _run(scenario, until, seeds=32):
    cfg = SimConfig(n_nodes=N, event_capacity=160, time_limit=until,
                    net=NetConfig(packet_loss_rate=0.05))
    rt = Runtime(cfg, [Monitored()], fd.detector_state(N),
                 scenario=scenario)
    state, _ = rt.run(rt.init_batch(np.arange(seeds)), max_steps=40_000)
    assert bool(state.halted.all()) and not bool(state.crashed.any())
    return np.asarray(state.node_state["fd_susp"]), np.asarray(state.alive)


class TestDetector:
    def test_clean_cluster_never_suspects(self):
        susp, _ = _run(None, until=sec(2))
        assert (susp == 0).all()

    def test_kill_is_detected_by_all_survivors(self):
        sc = Scenario()
        sc.at(sec(1)).kill(2)
        susp, alive = _run(sc, until=sec(2))
        assert (~alive[:, 2]).all()
        others = [i for i in range(N) if i != 2]
        # every survivor suspects the victim (>= TIMEOUT+PERIOD elapsed)
        assert (susp[:, others, 2] == 1).all()
        # and nobody suspects a live node
        assert (susp[:, others][:, :, others] == 0).all()

    def test_restart_rehabilitates(self):
        sc = Scenario()
        sc.at(sec(1)).kill(2)
        sc.at(sec(2)).restart(2)
        susp, alive = _run(sc, until=sec(3))
        assert alive[:, 2].all()
        # victim beats again: suspicion cleared everywhere, and the
        # restarted node (whose memory died) doesn't suspect anyone
        assert (susp == 0).all()
