"""Core engine tests: selection primitives, state init, basic stepping.

Mirrors the reference's inline unit-test strategy (SURVEY.md §4): every test
builds a fresh runtime and drives virtual time; a whole "cluster" runs in
one process with no real sleeping.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from madsim_tpu import Program, Runtime, Scenario, SimConfig, NetConfig, ms
from madsim_tpu.core import types as T
from madsim_tpu.core import prng
from madsim_tpu.models.pingpong import PingPong, state_spec
from madsim_tpu.ops import select as sel


class TestSelectOps:
    def test_masked_choice_uniform(self):
        mask = jnp.asarray([False, True, False, True, True])
        hits = set()
        for s in range(40):
            idx, valid = sel.masked_choice(prng.seed_key(s), mask)
            assert bool(valid)
            assert int(idx) in (1, 3, 4)
            hits.add(int(idx))
        assert hits == {1, 3, 4}  # all eligible slots reachable

    def test_masked_choice_empty(self):
        idx, valid = sel.masked_choice(prng.seed_key(0), jnp.zeros(4, bool))
        assert not bool(valid)

    def test_min_deadline(self):
        d = jnp.asarray([5, 3, 3, 9], jnp.int32)
        elig = jnp.asarray([True, True, True, False])
        dmin, at_min, any_e = sel.min_deadline(d, elig, T.T_INF)
        assert int(dmin) == 3
        assert list(np.asarray(at_min)) == [False, True, True, False]
        assert bool(any_e)

    def test_min_deadline_none(self):
        d = jnp.full(4, T.T_INF, jnp.int32)
        _, _, any_e = sel.min_deadline(d, jnp.zeros(4, bool), T.T_INF)
        assert not bool(any_e)

    def test_first_k_free(self):
        free = jnp.asarray([False, True, False, True, True])
        slots, ok = sel.first_k_free(free, 4)
        assert list(np.asarray(slots)) == [1, 3, 4, 0]
        assert list(np.asarray(ok)) == [True, True, True, False]

    def test_take1_matches_gather(self):
        vec = jnp.asarray([10, 20, 30, 40], jnp.int32)
        # scalar, vector, and matrix index shapes; int and bool vecs
        assert int(sel.take1(vec, jnp.asarray(2))) == 30
        idx = jnp.asarray([[0, 3], [1, 1]], jnp.int32)
        assert np.asarray(sel.take1(vec, idx)).tolist() == [[10, 40],
                                                            [20, 20]]
        bvec = jnp.asarray([True, False, True, False])
        assert np.asarray(sel.take1(bvec, idx)).tolist() == [[True, False],
                                                             [False, False]]

    def test_take_row_put_row(self):
        mat = jnp.arange(12, dtype=jnp.int32).reshape(3, 4)
        assert np.asarray(sel.take_row(mat, jnp.asarray(1))).tolist() == \
            [4, 5, 6, 7]
        bmat = mat > 5
        assert np.asarray(sel.take_row(bmat, jnp.asarray(2))).tolist() == \
            [True, True, True, True]
        # put_row: row write, broadcasting scalar val, mask=False no-op
        out = sel.put_row(mat, jnp.asarray(2), jnp.asarray(-1, jnp.int32))
        assert np.asarray(out).tolist() == [[0, 1, 2, 3], [4, 5, 6, 7],
                                            [-1, -1, -1, -1]]
        row = jnp.asarray([9, 9, 9, 9], jnp.int32)
        noop = sel.put_row(mat, jnp.asarray(0), row, mask=jnp.asarray(False))
        assert (np.asarray(noop) == np.asarray(mat)).all()
        # 1-D mats (the Raft log columns) and masked scalar write
        vec = jnp.asarray([1, 2, 3], jnp.int32)
        out = sel.put_row(vec, jnp.asarray(1), jnp.asarray(7, jnp.int32),
                          mask=jnp.asarray(True))
        assert np.asarray(out).tolist() == [1, 7, 3]
        # under vmap (per-lane scalar index — the engine's actual use)
        idxs = jnp.asarray([0, 2], jnp.int32)
        rows = jax.vmap(lambda i: sel.take_row(mat, i))(idxs)
        assert np.asarray(rows).tolist() == [[0, 1, 2, 3], [8, 9, 10, 11]]


def _pingpong_rt(n_nodes=3, target=5, **cfg_kw):
    cfg = SimConfig(n_nodes=n_nodes, time_limit=T.sec(30), **cfg_kw)
    return Runtime(cfg, [PingPong(n_nodes, target=target)], state_spec())


class TestPingPong:
    def test_single_seed_completes(self):
        rt = _pingpong_rt()
        state, _ = rt.run(rt.init_single(42), max_steps=4000)
        assert bool(state.halted.all())
        assert not bool(state.crashed.any())
        st = state.node_state
        assert int(np.asarray(st["acked"])[0, 0]) >= 5
        # pongs came from peers
        assert int(np.asarray(st["pings_got"])[0, 1:].sum()) >= 5

    def test_batch_completes(self):
        rt = _pingpong_rt()
        state, _ = rt.run(rt.init_batch(np.arange(32)), max_steps=4000)
        assert bool(state.halted.all())
        assert not bool(state.crashed.any())
        acked = np.asarray(state.node_state["acked"])[:, 0]
        assert (acked >= 5).all()

    def test_virtual_time_advances(self):
        rt = _pingpong_rt()
        state, _ = rt.run(rt.init_single(7), max_steps=4000)
        # 5 round trips at >= 2ms each must take >= 10ms of virtual time
        assert int(np.asarray(state.now)[0]) >= ms(10)

    def test_packet_loss_still_completes(self):
        # retry timers must mask 30% loss (config.rs packet_loss_rate knob)
        rt = _pingpong_rt(net=NetConfig(packet_loss_rate=0.3))
        state, _ = rt.run(rt.init_batch(np.arange(16)), max_steps=20_000)
        assert bool(state.halted.all())
        assert not bool(state.crashed.any())
        assert int(np.asarray(state.msg_dropped).sum()) > 0

    def test_determinism_same_seed(self):
        rt = _pingpong_rt()
        assert rt.check_determinism(seed=123, max_steps=4000)

    def test_schedule_diversity_across_seeds(self):
        # the task.rs:572-596 property: distinct seeds -> distinct schedules
        rt = _pingpong_rt()
        state, _ = rt.run(rt.init_batch(np.arange(10)), max_steps=4000)
        fps = rt.fingerprints(state)
        assert len(set(fps.tolist())) >= 8

    def test_batch_consistent_with_single(self):
        # seed i in a batch == seed i alone (replay-by-seed survives vmap)
        rt = _pingpong_rt()
        sb, _ = rt.run(rt.init_batch(np.asarray([5, 6, 7])), max_steps=4000)
        s6, _ = rt.run(rt.init_single(6), max_steps=4000)
        assert rt.fingerprints(sb)[1] == rt.fingerprints(s6)[0]


class TestLifecycleFaults:
    def test_deadlock_detected(self):
        class Idle(Program):
            pass

        cfg = SimConfig(n_nodes=1, time_limit=T.sec(1))
        sc = Scenario()  # auto-halt at 1s; but Idle schedules nothing, so
        # after boot there is nothing runnable until the halt op -> halts fine
        rt = Runtime(cfg, [Idle()], dict(x=jnp.asarray(0, jnp.int32)),
                     scenario=sc)
        state, _ = rt.run(rt.init_single(0), max_steps=100)
        assert bool(state.halted.all())
        assert not bool(state.crashed.any())  # HALT op keeps it live

    def test_kill_breaks_pingpong_and_restart_recovers(self):
        n, target = 3, 50
        cfg = SimConfig(n_nodes=n, time_limit=T.sec(60))
        sc = Scenario()
        # kill both responders early, restart them later; pinger's retry
        # timer must carry it through (Handle::kill/restart semantics)
        sc.at(ms(5)).kill(1)
        sc.at(ms(5)).kill(2)
        sc.at(T.sec(2)).restart(1)
        sc.at(T.sec(2)).restart(2)
        rt = Runtime(cfg, [PingPong(n, target=target)], state_spec(),
                     scenario=sc)
        state, _ = rt.run(rt.init_batch(np.arange(8)), max_steps=40_000)
        assert bool(state.halted.all())
        assert not bool(state.crashed.any())
        # progress stalled during the dead window => finish time > 2s
        assert (np.asarray(state.now) > T.sec(2)).all()

    def test_partition_stalls_heal_recovers(self):
        n = 3
        cfg = SimConfig(n_nodes=n, time_limit=T.sec(60))
        sc = Scenario()
        sc.at(ms(2)).partition([0])      # isolate the pinger
        sc.at(T.sec(3)).heal()
        rt = Runtime(cfg, [PingPong(n, target=20)], state_spec(),
                     scenario=sc)
        state, _ = rt.run(rt.init_batch(np.arange(8)), max_steps=40_000)
        assert bool(state.halted.all())
        assert not bool(state.crashed.any())
        assert (np.asarray(state.now) > T.sec(3)).all()
        assert int(np.asarray(state.msg_dropped).sum()) > 0

    def test_pause_parks_events_resume_replays(self):
        n = 3
        cfg = SimConfig(n_nodes=n, time_limit=T.sec(60))
        sc = Scenario()
        sc.at(ms(2)).pause(0)
        sc.at(T.sec(5)).resume(0)
        rt = Runtime(cfg, [PingPong(n, target=10)], state_spec(),
                     scenario=sc)
        state, _ = rt.run(rt.init_single(3), max_steps=40_000)
        assert bool(state.halted.all())
        assert not bool(state.crashed.any())
        assert int(np.asarray(state.now)[0]) >= T.sec(5)  # parked until resume


class TestHarnessOops:
    def test_event_overflow_flagged(self):
        class Bomb(Program):
            def init(self, ctx):
                ctx.set_timer(1, 1)

            def on_timer(self, ctx, tag, payload):
                for _ in range(4):
                    ctx.set_timer(1, 1)  # exponential timer growth

        cfg = SimConfig(n_nodes=1, event_capacity=16, time_limit=T.sec(1))
        rt = Runtime(cfg, [Bomb()], dict(x=jnp.asarray(0, jnp.int32)))
        state, _ = rt.run(rt.init_single(0), max_steps=200)
        assert int(np.asarray(state.oops)[0]) & T.OOPS_EVENT_OVERFLOW


class TestRandomTargets:
    def test_kill_random_varies_victim_across_seeds(self):
        # regression: NODE_RANDOM must survive to the supervisor (a clip once
        # collapsed it to node 0, degenerating all random faults)
        from madsim_tpu import Scenario
        from madsim_tpu.core.types import sec as _sec
        n = 4
        sc = Scenario()
        sc.at(ms(5)).kill_random()
        cfg = SimConfig(n_nodes=n, time_limit=_sec(1))
        rt = Runtime(cfg, [PingPong(n, target=3)], state_spec(), scenario=sc)
        state, _ = rt.run(rt.init_batch(np.arange(64)), max_steps=4000)
        dead = np.asarray(~state.alive)
        assert (dead.sum(axis=1) == 1).all()        # exactly one victim
        victims = dead.argmax(axis=1)
        assert len(set(victims.tolist())) >= 3      # victims vary by seed

    def test_pool_beyond_31_nodes(self):
        # pools pack 31 nodes/word across ALL payload words (VERDICT r2
        # next #6): a 36-node cluster with the candidate pool entirely in
        # word 1 must kill only pool members, varying by seed
        from madsim_tpu import Scenario
        from madsim_tpu.core.types import sec as _sec
        n = 36
        sc = Scenario()
        sc.at(ms(5)).kill_random(among=range(32, 36))
        cfg = SimConfig(n_nodes=n, time_limit=_sec(1))
        rt = Runtime(cfg, [PingPong(n, target=2)], state_spec(), scenario=sc)
        state, _ = rt.run(rt.init_batch(np.arange(48)), max_steps=3000)
        dead = np.asarray(~state.alive)
        assert (dead.sum(axis=1) == 1).all()        # exactly one victim
        victims = dead.argmax(axis=1)
        assert set(victims.tolist()) <= set(range(32, 36))  # pool respected
        assert len(set(victims.tolist())) >= 2      # still random within it


class CancelDemo(Program):
    """Arms a long SLOW timer, then (optionally) cancels it shortly
    after — the Sleep::reset / abort analog, red/green testable."""

    SLOW, DO_CANCEL = 1, 2

    def __init__(self, do_cancel: bool):
        self.do_cancel = do_cancel

    def init(self, ctx):
        ctx.set_timer(ms(500), self.SLOW, when=ctx.node == 0)
        ctx.set_timer(ms(10), self.DO_CANCEL, when=ctx.node == 0)

    def on_timer(self, ctx, tag, payload):
        st = dict(ctx.state)
        st["fired"] = st["fired"] + (tag == self.SLOW)
        ctx.cancel_timer(self.SLOW,
                         when=(tag == self.DO_CANCEL) & self.do_cancel)
        ctx.state = st


class TestCancelTimer:
    def _run(self, do_cancel):
        cfg = SimConfig(n_nodes=1, time_limit=T.sec(1))
        rt = Runtime(cfg, [CancelDemo(do_cancel)],
                     dict(fired=jnp.asarray(0, jnp.int32)))
        state, _ = rt.run(rt.init_batch(np.arange(16)), max_steps=500)
        assert bool(state.halted.all()) and not bool(state.crashed.any())
        assert rt.check_determinism(seed=4, max_steps=500)
        return np.asarray(state.node_state["fired"])

    def test_cancelled_timer_never_fires(self):
        assert (self._run(do_cancel=True) == 0).all()

    def test_uncancelled_timer_fires(self):
        # the control: without the cancel the same program fires
        assert (self._run(do_cancel=False) == 1).all()


class TestNarrowTableColumns:
    def test_int16_columns_bit_identical_to_int32(self):
        # table_dtype is a pure bandwidth lever: t_kind/t_node/t_src in
        # int16 must yield BIT-IDENTICAL trajectories (values unchanged,
        # fingerprints cover every leaf's values)
        from madsim_tpu import Scenario
        from madsim_tpu.core.types import sec as _sec
        from madsim_tpu.utils.hashing import fingerprint

        def run(dtype):
            n = 4
            sc = Scenario()
            sc.at(ms(5)).kill_random()
            sc.at(ms(300)).restart_random()
            cfg = SimConfig(n_nodes=n, time_limit=_sec(2),
                            net=NetConfig(packet_loss_rate=0.1),
                            table_dtype=dtype)
            rt = Runtime(cfg, [PingPong(n, target=4, retry=ms(20))],
                         state_spec(), scenario=sc)
            state, _ = rt.run(rt.init_batch(np.arange(64)), max_steps=4000)
            assert bool(state.halted.all())
            return np.asarray(jax.vmap(fingerprint)(state))

        np.testing.assert_array_equal(run("int32"), run("int16"))


class TestContinuationIdiom:
    """A handler is atomic here (a deliberate transform of madsim's
    poll-level interleaving, DESIGN.md §3); `ctx.defer` splits a
    multi-phase handler into same-deadline continuations so its phases
    interleave with other nodes' events again. The schedule-coverage
    metric must MEASURE that widening across a seed batch."""

    START, DONE, PH = 1, 2, 1

    def _spec(self):
        z = jnp.asarray(0, jnp.int32)
        return dict(phase=z, acc=z, done=z)

    def _summarize(self, prog, n=4, seeds=64):
        from madsim_tpu.core.types import sec as _sec
        from madsim_tpu.parallel.stats import summarize
        # constant latency: deliveries land at identical deadlines, so the
        # same-deadline random tie-break is the ONLY schedule freedom and
        # the metric isolates exactly what defer() adds
        cfg = SimConfig(n_nodes=n, time_limit=_sec(5),
                        net=NetConfig(send_latency_min=ms(1),
                                      send_latency_max=ms(1)))
        rt = Runtime(cfg, [prog], self._spec())
        state, _ = rt.run(rt.init_batch(np.arange(seeds)), max_steps=4000)
        assert bool(state.halted.all()) and not bool(state.crashed.any())
        return summarize(rt, state), state

    def test_defer_widens_schedule_coverage(self):
        n = 4
        outer = self

        class Base(Program):
            def init(self, ctx):
                for d in range(1, n):
                    ctx.send(d, outer.START, when=ctx.node == 0)

        class Atomic(Base):
            # three work phases inside ONE handler: invisible to the
            # scheduler, so the only explored orderings are arrival orders
            def on_message(self, ctx, src, tag, payload):
                st = dict(ctx.state)
                is_start = tag == outer.START
                st["acc"] = st["acc"] + 3 * is_start
                ctx.send(0, outer.DONE, when=is_start)
                if_done = tag == outer.DONE
                done = st["done"] + if_done
                st["done"] = jnp.where(if_done, done, st["done"])
                ctx.halt_if(if_done & (st["done"] >= n - 1))
                ctx.state = st

            def on_timer(self, ctx, tag, payload):
                pass

        class Split(Base):
            # same work, each phase deferred: continuations land in the
            # event table and the random tie-break interleaves them with
            # the other workers' phases
            def on_message(self, ctx, src, tag, payload):
                st = dict(ctx.state)
                is_start = tag == outer.START
                st["phase"] = jnp.where(is_start, 1, st["phase"])
                ctx.defer(outer.PH, when=is_start)
                if_done = tag == outer.DONE
                done = st["done"] + if_done
                st["done"] = jnp.where(if_done, done, st["done"])
                ctx.halt_if(if_done & (st["done"] >= n - 1))
                ctx.state = st

            def on_timer(self, ctx, tag, payload):
                st = dict(ctx.state)
                fire = tag == outer.PH
                st["acc"] = st["acc"] + fire
                more = fire & (st["phase"] < 3)
                st["phase"] = st["phase"] + fire
                ctx.defer(outer.PH, when=more)
                ctx.send(0, outer.DONE, when=fire & ~more)
                ctx.state = st

        atomic, ast = self._summarize(Atomic())
        split, sst = self._summarize(Split())
        # identical work done...
        assert (np.asarray(ast.node_state["acc"])[:, 1:]
                == np.asarray(sst.node_state["acc"])[:, 1:]).all()
        # ...but the split version explores strictly more interleavings
        assert split["distinct_schedules"] > atomic["distinct_schedules"], \
            (split["distinct_schedules"], atomic["distinct_schedules"])


class TestPayloadStructs:
    def test_layout_pack_unpack_roundtrip(self):
        import jax.numpy as jnp
        from madsim_tpu.utils.structs import Layout
        L = Layout("term", "prev", "commit")
        assert (L.term, L.prev, L.commit, L.width) == (0, 1, 2, 3)
        words = L.pack(term=7, commit=9)
        assert [int(w) for w in words] == [7, 0, 9]
        payload = jnp.asarray([7, 0, 9, 0], jnp.int32)
        got = L.unpack(payload)
        assert int(got["term"]) == 7 and int(got["commit"]) == 9

    def test_float_bitcast_lossless(self):
        import numpy as np
        from madsim_tpu.utils.structs import f32_to_word, word_to_f32
        vals = np.asarray([0.0, 1.5, -3.25e-7, 1e30], np.float32)
        back = np.asarray(word_to_f32(f32_to_word(vals)))
        np.testing.assert_array_equal(back, vals)


class TestChaosRecipes:
    def test_recipes_compose_and_run(self):
        import numpy as np
        from madsim_tpu import SimConfig, NetConfig, ms, sec
        from madsim_tpu.harness.simtest import run_seeds
        from madsim_tpu.models.raft import make_raft_runtime
        from madsim_tpu.runtime import chaos

        sc = chaos.madraft_churn(servers=range(5), rounds=3)
        sc = chaos.flaky_network(at=ms(500), loss=0.15, until=sec(2), sc=sc)
        cfg = SimConfig(n_nodes=5, event_capacity=256, time_limit=sec(6),
                        net=NetConfig(send_latency_min=ms(1),
                                      send_latency_max=ms(10)))
        rt = make_raft_runtime(5, 16, n_cmds=6, scenario=sc, cfg=cfg)
        state = run_seeds(rt, np.arange(6), max_steps=30_000)
        assert bool(state.halted.all())
        # the loss window actually dropped packets somewhere in the batch
        assert int(np.asarray(state.msg_dropped).sum()) > 0
