"""Differential tests: the fused Pallas scheduler kernel vs ops/select.

Run through the pallas interpreter on CPU (the kernel auto-selects
interpret mode off-TPU), so semantics are pinned before the kernel ever
touches hardware. dmin/any/slots/ok must match ops/select EXACTLY; the
uniform tie-break is a different (still uniform, still deterministic)
draw, so it is checked for validity + determinism + rough uniformity.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from madsim_tpu.ops import select as sel
from madsim_tpu.ops.pallas_select import fused_schedule

INF = 2**31 - 1


def _random_tables(rng, B, C, frac_elig=0.6, frac_free=0.3):
    deadlines = rng.integers(0, 50, size=(B, C)).astype(np.int32)
    eligible = rng.random((B, C)) < frac_elig
    free = rng.random((B, C)) < frac_free
    rand_bits = rng.integers(-2**31, 2**31 - 1, size=(B,)).astype(np.int32)
    return (jnp.asarray(deadlines), jnp.asarray(eligible),
            jnp.asarray(free), jnp.asarray(rand_bits))


def _reference(deadlines, eligible, free, E):
    """ops/select, vmapped — the engine's unfused path."""
    def one(dl, el, fr):
        dmin, at_min, any_el = sel.min_deadline(dl, el, INF)
        slots, ok = sel.first_k_free(fr, E)
        return dmin, at_min, any_el, slots, ok
    return jax.vmap(one)(deadlines, eligible, free)


def test_lane_entry_matches_reference_under_vmap():
    # the engine's actual entry (SimConfig(scheduler="fused")): per-lane,
    # lifted over the seed batch by vmap's pallas batching rule
    from madsim_tpu.ops.pallas_select import fused_select_lane

    rng = np.random.default_rng(7)
    B, C = 12, 96
    dl, el, _, rnd = _random_tables(rng, B, C)
    dmin, idx, any_el = jax.vmap(
        lambda d, e, r: fused_select_lane(d, e, r, inf=INF))(dl, el, rnd)
    rdmin, rat_min, rany, _, _ = _reference(dl, el, jnp.zeros_like(el), 1)

    mask = np.asarray(rany)
    np.testing.assert_array_equal(np.asarray(any_el), mask)
    np.testing.assert_array_equal(np.asarray(dmin)[mask],
                                  np.asarray(rdmin)[mask])
    at = np.asarray(rat_min)
    for b in range(B):
        if mask[b]:
            assert at[b, int(np.asarray(idx)[b])]


def test_fused_scheduler_end_to_end():
    # the flag is real: a chaos workload completes, replays bit-stable,
    # and varies schedules by seed under the fused scheduler
    from madsim_tpu import Runtime, Scenario, SimConfig, ms, sec
    from madsim_tpu.models.pingpong import PingPong, state_spec

    n = 3
    sc = Scenario()
    sc.at(ms(5)).kill_random()
    sc.at(ms(200)).restart_random()
    cfg = SimConfig(n_nodes=n, time_limit=sec(10), scheduler="fused")
    rt = Runtime(cfg, [PingPong(n, target=4, retry=ms(20))], state_spec(),
                 scenario=sc)
    state, _ = rt.run(rt.init_batch(np.arange(32)), max_steps=4000)
    assert bool(state.halted.all()) and not bool(state.crashed.any())
    from madsim_tpu.parallel.stats import sched_hash_u64
    assert len(set(sched_hash_u64(state).tolist())) >= 16
    assert rt.check_determinism(seed=5, max_steps=4000)
    # distinct replay domain: the reference scheduler on the same seed
    # yields a DIFFERENT config hash, so repro lines pin the scheduler
    ref_cfg = SimConfig(n_nodes=n, time_limit=sec(10))
    assert cfg.hash() != ref_cfg.hash()


@pytest.mark.parametrize("B,C,E", [(16, 96, 6), (8, 200, 12), (3, 40, 4)])
def test_matches_reference(B, C, E):
    rng = np.random.default_rng(42)
    dl, el, fr, rnd = _random_tables(rng, B, C)
    dmin, idx, any_el, slots, ok = fused_schedule(dl, el, fr, rnd,
                                                  n_free=E, inf=INF)
    rdmin, rat_min, rany, rslots, rok = _reference(dl, el, fr, E)

    mask = np.asarray(rany)
    np.testing.assert_array_equal(np.asarray(any_el), mask)
    np.testing.assert_array_equal(np.asarray(dmin)[mask],
                                  np.asarray(rdmin)[mask])
    np.testing.assert_array_equal(np.asarray(ok), np.asarray(rok))
    # slots must match wherever valid
    okn = np.asarray(rok)
    np.testing.assert_array_equal(np.asarray(slots)[okn],
                                  np.asarray(rslots)[okn])
    # the chosen index is always a member of the tie set
    at = np.asarray(rat_min)
    for b in range(B):
        if mask[b]:
            assert at[b, int(np.asarray(idx)[b])]


def test_tie_break_deterministic_and_uniform():
    B, C = 1, 64
    deadlines = jnp.zeros((B, C), jnp.int32)      # everything ties
    eligible = jnp.ones((B, C), bool)
    free = jnp.zeros((B, C), bool)

    picks = []
    for r in range(512):
        rnd = jnp.asarray([r * 2654435761 % 2**31], jnp.int32)
        _, idx, _, _, _ = fused_schedule(deadlines, eligible, free, rnd,
                                         n_free=1, inf=INF)
        picks.append(int(idx[0]))
    # deterministic: same bits -> same pick
    rnd = jnp.asarray([123456], jnp.int32)
    a = fused_schedule(deadlines, eligible, free, rnd, n_free=1, inf=INF)
    b = fused_schedule(deadlines, eligible, free, rnd, n_free=1, inf=INF)
    assert int(a[1][0]) == int(b[1][0])
    # roughly uniform over the 64 ties: every slot hit at least once and
    # no slot grossly over-represented across 512 draws (E[x]=8)
    counts = np.bincount(picks, minlength=C)
    assert (counts > 0).sum() >= C - 4
    assert counts.max() <= 32


def test_empty_cases():
    B, C = 4, 96
    dl = jnp.zeros((B, C), jnp.int32)
    none = jnp.zeros((B, C), bool)
    rnd = jnp.arange(B, dtype=jnp.int32)
    dmin, idx, any_el, slots, ok = fused_schedule(dl, none, none, rnd,
                                                  n_free=3, inf=INF)
    assert not bool(np.asarray(any_el).any())
    assert not bool(np.asarray(ok).any())
    assert (np.asarray(idx) == 0).all()
