"""Windowed telemetry plane (r21, DESIGN §22): sim-time metric series
as pure observers, failing-to-heal as a crash code.

The load-bearing properties: (1) the plane is an observation lever —
trajectories are bit-identical leaf-for-leaf with it on, off, compiled
out, or lane-masked, and the sr_*/window_len columns ride TRACE_FIELDS
out of fingerprints (golden gate vs r20 captured truth); (2) the window
rule is exact — a dispatch at post-advance `now` lands in
min(now // window_len, W-1), a boundary dispatch opens the NEXT window,
overflow clamps into the last window, windows never wrap; (3) counters
SATURATE; (4) window_len is a DYNAMIC operand — retuning re-buckets
without recompiling or perturbing trajectories; (5) the batch digest
(`series_counters`) is an exact masked merge of the recording lanes;
(6) `recovery_invariant` judges only complete windows past the grace
period after the LAST disruptive fault, heals don't restart the clock,
and it fires deterministically with CRASH_RECOVERY; (7) the fuzzer's
burst_bonus scales admission energy by the deepest TRANSIENT spike;
(8) pre-r21 checkpoints are rejected loudly (v7 then; simconfig-v8
since the r23 attribution plane).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from madsim_tpu import (CRASH_RECOVERY, NetConfig, Runtime, Scenario,
                        SimConfig, format_series, lane_series, ms,
                        recovery_invariant, sec, series_summary, summarize)
from madsim_tpu.core import types as T
from madsim_tpu.core.state import TRACE_FIELDS
from madsim_tpu.core.types import EV_MSG
from madsim_tpu.models.pingpong import PingPong, state_spec
from madsim_tpu.obs import (counter_track_events, fault_names,
                            ring_records, series_counter_track_events)
from madsim_tpu.parallel.stats import (lane_burst, series_counters,
                                       series_digest)

import _series_golden as golden

I32_MAX = 2**31 - 1
TAG_PING = 1        # pingpong's ping message tag (models/pingpong.py)

# the 11 leaves the r21 plane added (MIGRATION r21)
SR_LEAVES = ("sr_on", "window_len", "sr_dispatch", "sr_busy", "sr_qhw",
             "sr_drop", "sr_dup", "sr_complete", "sr_slo_miss",
             "sr_lat", "sr_fault")


def _pingpong_rt(windows=0, window_len=None, target=6, n_nodes=2,
                 scenario=None, lat=0, trace_cap=0, invariant=None):
    kw = {}
    if window_len is not None:
        kw["window_len"] = window_len
    cfg = SimConfig(n_nodes=n_nodes, time_limit=sec(5),
                    series_windows=windows,
                    latency_hist=lat, trace_cap=trace_cap,
                    complete_kinds=(((EV_MSG, TAG_PING),) if lat else ()),
                    net=NetConfig(send_latency_min=ms(1),
                                  send_latency_max=ms(4)),
                    **kw)
    return Runtime(cfg, [PingPong(n_nodes, target=target)], state_spec(),
                   scenario=scenario, invariant=invariant)


def _nonseries_state(state) -> dict:
    out = {}
    for name in type(state).__dataclass_fields__:
        if name in TRACE_FIELDS or name in ("node_state", "ext"):
            continue
        out[name] = np.asarray(getattr(state, name))
    for i, leaf in enumerate(jax.tree.leaves(state.node_state)):
        out[f"node_state_{i}"] = np.asarray(leaf)
    return out


# ---------------------------------------------------------------------------
# 1. bit-identical-when-disabled, against r20 captured truth
# ---------------------------------------------------------------------------

class TestEquivalenceR20:
    @pytest.mark.parametrize("workload", sorted(golden.BUILDERS))
    def test_leaf_for_leaf_vs_r20_golden(self, workload):
        # scripts/capture_golden.py froze these digests AT r20 HEAD,
        # before any r21 engine change: every r20 leaf must still hash
        # identically, chunked and fused; the ONLY new leaves are the
        # series plane's own (zero-size sr_* columns here — the frozen
        # workloads never set series_windows)
        gold = golden.load_golden()[workload]
        got = golden.run_workload(workload)
        for runner in ("run", "run_fused"):
            missing = [k for k in gold[runner] if k not in got[runner]]
            assert not missing, (runner, missing)
            diff = [k for k in gold[runner]
                    if gold[runner][k] != got[runner][k]]
            assert not diff, (runner, diff)
            new = set(got[runner]) - set(gold[runner])
            # the r23 attribution plane's leaves ride along (zero-size
            # here — the frozen workloads never set span_attr; their
            # own golden gate lives in tests/test_spans.py)
            span = {".sp_on", ".ev_span", ".sa_tail", ".sa_bottleneck",
                    ".tr_qw"}
            assert new == {"." + n for n in SR_LEAVES} | span, new


# ---------------------------------------------------------------------------
# 2. the observation-lever contract on live runs
# ---------------------------------------------------------------------------

class TestSeriesPlane:
    def test_series_never_perturbs_trajectory(self):
        seeds = np.arange(16, dtype=np.uint32)
        rt0 = _pingpong_rt(windows=0)
        base, _ = rt0.run(rt0.init_batch(seeds), 256, 64)
        ref = _nonseries_state(base)
        for lanes in (None, [0, 3], []):
            rt = _pingpong_rt(windows=8)
            st, _ = rt.run(rt.init_batch(seeds, series_lanes=lanes),
                           256, 64)
            got = _nonseries_state(st)
            assert ref.keys() == got.keys()
            for k in ref:
                assert (ref[k] == got[k]).all(), f"lanes={lanes}: {k}"
            assert (rt0.fingerprints(base) == rt.fingerprints(st)).all()

    def test_fused_equals_chunked_on_series_columns(self):
        rt = _pingpong_rt(windows=8, window_len=ms(25), target=40,
                          lat=24, trace_cap=32)
        seeds = np.arange(8, dtype=np.uint32)
        chunked, _ = rt.run(rt.init_batch(seeds), 256, 64)
        fused = rt.run_fused(rt.init_batch(seeds), 256, 64)
        for f in TRACE_FIELDS:
            assert (np.asarray(getattr(chunked, f))
                    == np.asarray(getattr(fused, f))).all(), f
        assert int(np.asarray(fused.sr_dispatch).sum()) > 0

    def test_partial_lanes_cannot_split_outcomes(self):
        seeds = np.arange(8, dtype=np.uint32)
        rt = _pingpong_rt(windows=8)
        sampled, _ = rt.run(rt.init_batch(seeds, series_lanes=[0, 1]),
                            256, 64)
        allon, _ = rt.run(rt.init_batch(seeds), 256, 64)
        assert (rt.fingerprints(sampled) == rt.fingerprints(allon)).all()
        assert (summarize(rt, sampled, seeds)["distinct_outcomes"]
                == summarize(rt, allon, seeds)["distinct_outcomes"])

    def test_masked_lanes_record_nothing(self):
        rt = _pingpong_rt(windows=4, window_len=ms(25), target=40)
        st = rt.run_fused(rt.init_batch(np.arange(4), series_lanes=[2]),
                          256, 64)
        disp = np.asarray(st.sr_dispatch)
        assert disp[[0, 1, 3]].sum() == 0
        assert disp[2].sum() > 0
        # lane_series refuses to render a masked lane as a healthy
        # flatline — None means "not recorded"
        assert lane_series(st, 0) is None
        assert lane_series(st, 2) is not None

    def test_series_lanes_requires_compiled_plane(self):
        rt = _pingpong_rt(windows=0)
        with pytest.raises(ValueError, match="series"):
            rt.init_batch(np.arange(4), series_lanes=[0])

    def test_signature_and_window_len_is_not_structural(self):
        # v7 here at r21; the r23 attribution plane bumped it to v8 —
        # test_spans.py owns the authoritative version assertion
        cfg = SimConfig(n_nodes=2)
        assert cfg.structural_signature()[0] == "simconfig-v8"
        # the window COUNT shapes the program; the window LENGTH is an
        # operand (the r8 structural/dynamic discipline)
        a = SimConfig(n_nodes=2, series_windows=8)
        b = SimConfig(n_nodes=2, series_windows=4)
        c = SimConfig(n_nodes=2, series_windows=8, window_len=ms(10))
        assert a.structural_signature() != b.structural_signature()
        assert a.structural_signature() == c.structural_signature()

    def test_device_series_equals_ring_replay(self):
        # the host-replay contract on a live run: bucket every ring
        # record by the window rule and the per-(window, node) dispatch
        # counts, per-window completions and window latency histograms
        # must equal the device sr_* columns bit for bit
        rt = _pingpong_rt(windows=4, window_len=ms(25), target=60,
                          lat=24, trace_cap=2048)
        W, wl, LB = 4, ms(25), 24
        st = rt.run_fused(rt.init_batch(np.arange(2)), 1024, 256)
        for b in range(2):
            recs = ring_records(st, b)
            assert recs["dropped"] == 0
            ref_d = np.zeros((W, rt.cfg.n_nodes), np.int64)
            ref_c = np.zeros(W, np.int64)
            ref_l = np.zeros((W, LB), np.int64)
            lat = np.asarray(recs["lat"])
            for i in range(len(recs["now"])):
                w = min(int(recs["now"][i]) // wl, W - 1)
                ref_d[w, int(recs["node"][i])] += 1
                if lat[i] >= 0:
                    ref_c[w] += 1
                    v = int(lat[i])
                    bkt = 0 if v == 0 else min(v.bit_length(), LB - 1)
                    ref_l[w, bkt] += 1
            assert (np.asarray(st.sr_dispatch[b]) == ref_d).all()
            assert (np.asarray(st.sr_complete[b]) == ref_c).all()
            assert (np.asarray(st.sr_lat[b]) == ref_l).all()
            assert ref_c.sum() > 0

    def test_boundary_dispatch_opens_next_window(self):
        # a scenario row dispatches at exactly its at() time; at
        # now == window_len the window rule reads min(wl // wl, W-1)
        # = 1 — the boundary belongs to the NEXT window. unclog on an
        # unclogged link is a pure marker (SRF_HEAL, no disruption).
        sc = Scenario()
        sc.at(ms(25)).unclog_node(0)
        rt = _pingpong_rt(windows=4, window_len=ms(25), target=60,
                          scenario=sc)
        st = rt.run_fused(rt.init_batch(np.arange(2)), 1024, 256)
        f = np.asarray(st.sr_fault)
        assert (f[:, 1] & T.SRF_HEAL != 0).all()
        # window 0 keeps only its own markers (the t=0 boots)
        assert (f[:, 0] & T.SRF_HEAL == 0).all()
        assert (f[:, 0] & T.SRF_BOOT != 0).all()

    def test_overflow_clamps_into_last_window(self):
        # windows never wrap: an event past W * window_len lands in the
        # LAST window (min(3, W-1) = 1 here), never evicts window 0
        sc = Scenario()
        sc.at(ms(90)).unclog_node(0)
        rt = _pingpong_rt(windows=2, window_len=ms(25), target=60,
                          scenario=sc)
        st = rt.run_fused(rt.init_batch(np.arange(2)), 1024, 256)
        f = np.asarray(st.sr_fault)
        assert (f[:, 1] & T.SRF_HEAL != 0).all()
        assert (f[:, 0] & T.SRF_HEAL == 0).all()
        ls = lane_series(st, 0)
        assert ls["touched"] == 2 and ls["windows"] == 2

    def test_counters_saturate_no_wraparound(self):
        rt = _pingpong_rt(windows=4, window_len=ms(25), target=40, lat=24)
        st = rt.init_batch(np.arange(4))
        st = st.replace(
            sr_dispatch=jnp.full_like(st.sr_dispatch, I32_MAX),
            sr_busy=jnp.full_like(st.sr_busy, I32_MAX - 1),
            sr_qhw=jnp.full_like(st.sr_qhw, I32_MAX),
            sr_drop=jnp.full_like(st.sr_drop, I32_MAX),
            sr_dup=jnp.full_like(st.sr_dup, I32_MAX),
            sr_complete=jnp.full_like(st.sr_complete, I32_MAX),
            sr_slo_miss=jnp.full_like(st.sr_slo_miss, I32_MAX),
            sr_lat=jnp.full_like(st.sr_lat, I32_MAX - 1))
        final = rt.run_fused(st, 256, 64)
        for f in ("sr_dispatch", "sr_busy", "sr_qhw", "sr_drop", "sr_dup",
                  "sr_complete", "sr_slo_miss", "sr_lat"):
            v = np.asarray(getattr(final, f))
            assert (v >= 0).all() and (v <= I32_MAX).all(), f
        assert (np.asarray(final.sr_dispatch) == I32_MAX).all()

    def test_window_len_is_dynamic(self):
        # same executable, different bucketing: totals and trajectories
        # identical, only the window axis moves
        rt = _pingpong_rt(windows=4, window_len=ms(25), target=40)
        base = rt.run_fused(rt.init_batch(np.arange(4)), 256, 64)
        spread = np.asarray(base.sr_dispatch).sum(-1)     # [B, W]
        assert (spread[:, 1:].sum(-1) > 0).all()          # multi-window
        wide = rt.set_window_len(rt.init_batch(np.arange(4)), sec(30))
        wide = rt.run_fused(wide, 256, 64)
        coarse = np.asarray(wide.sr_dispatch).sum(-1)
        assert (coarse[:, 1:] == 0).all()                 # all in w0
        assert (coarse.sum(-1) == spread.sum(-1)).all()
        assert (rt.fingerprints(base) == rt.fingerprints(wide)).all()
        rt0 = _pingpong_rt(windows=0)
        with pytest.raises(ValueError, match="series"):
            rt0.set_window_len(rt0.init_batch(np.arange(2)), ms(10))
        with pytest.raises(ValueError, match="window_len"):
            rt.set_window_len(rt.init_batch(np.arange(2)), 0)


# ---------------------------------------------------------------------------
# 3. digest, report, counter tracks
# ---------------------------------------------------------------------------

class TestDigestAndReport:
    def test_compiled_out_is_none(self):
        rt = _pingpong_rt(windows=0)
        st, _ = rt.run(rt.init_batch(np.arange(2)), 128, 64)
        assert series_digest(st) is None
        assert series_counters(st) is None
        assert series_summary(st) is None
        assert lane_series(st) is None
        assert lane_burst(st) is None
        assert summarize(rt, st)["series"] is None
        assert "compiled out" in format_series(None)
        assert series_counter_track_events(st) == []

    def test_counters_merge_exactly_over_recording_lanes(self):
        rt = _pingpong_rt(windows=4, window_len=ms(25), target=40, lat=24)
        st = rt.run_fused(rt.init_batch(np.arange(8),
                                        series_lanes=[1, 4]), 256, 64)
        c = series_counters(st)
        assert c["lanes"] == 2 and c["window_len"] == ms(25)
        disp = np.asarray(st.sr_dispatch).astype(np.int64)
        assert (c["dispatch"] == disp[[1, 4]].sum(0)).all()
        assert c["qhw"] == np.asarray(st.sr_qhw)[[1, 4]].max(0).tolist()
        comp = np.asarray(st.sr_complete).astype(np.int64)
        assert c["complete"] == comp[[1, 4]].sum(0).tolist()
        # all-masked batch reads zero, not garbage
        st0 = rt.run_fused(rt.init_batch(np.arange(4), series_lanes=[]),
                           128, 64)
        c0 = series_counters(st0)
        assert c0["lanes"] == 0 and c0["dispatch"].sum() == 0

    def test_window_p99_is_bucket_cdf_lower_bound(self):
        # crafted window histograms: window 0 holds 100 samples in
        # bucket 3 ([4, 8)) and 1 in bucket 10 ([512, 1024)) — p99
        # reads edge 4; window 1 holds 7 in bucket 10 — edge 512;
        # untouched windows read 0. Exact, deterministic.
        rt = _pingpong_rt(windows=4, window_len=ms(25), target=40, lat=24)
        st = rt.init_batch(np.arange(2))
        sl = np.zeros(np.asarray(st.sr_lat).shape, np.int32)
        sl[:, 0, 3] = 100
        sl[:, 0, 10] = 1
        sl[:, 1, 10] = 7
        st = st.replace(sr_lat=jnp.asarray(sl))
        c = series_counters(st)
        assert c["e2e_p99_by_window"] == [4, 512, 0, 0]
        ls = lane_series(st, 0)
        assert ls["e2e_p99"].tolist() == [4, 512, 0, 0]

    def test_summary_rows_and_render(self):
        sc = Scenario()
        sc.at(ms(30)).unclog_node(0)
        rt = _pingpong_rt(windows=4, window_len=ms(25), target=60,
                          lat=24, scenario=sc)
        st = rt.run_fused(rt.init_batch(np.arange(4)), 1024, 256)
        s = series_summary(st)
        assert s["windows"] == 4 and len(s["rows"]) == 4
        assert [r["t0_us"] for r in s["rows"]] == [0, ms(25), ms(50),
                                                   ms(75)]
        assert s["rows"][0]["faults"] == ["boot"]    # the t=0 boots
        assert s["rows"][1]["faults"] == ["heal"]
        assert sum(r["dispatches"] for r in s["rows"]) > 0
        txt = format_series(s)
        assert "p99_us" in txt and "heal" in txt
        rep = summarize(rt, st, np.arange(4))["series"]
        assert rep["windows"] == 4 and rep["dispatch_peak"] > 0
        assert rep["fault_windows"] == [0, 1]
        assert fault_names(T.SRF_PARTITION | T.SRF_HEAL) == ["partition",
                                                             "heal"]

    def test_counter_tracks_ride_true_sim_time(self):
        rt = _pingpong_rt(windows=4, window_len=ms(25), target=60,
                          lat=24, trace_cap=64)
        st = rt.run_fused(rt.init_batch(np.arange(2)), 1024, 256)
        evs = counter_track_events(st, lane=0)   # prefers the series
        names = {e["name"] for e in evs}
        assert {"queue_depth", "e2e_p99", "fault"} <= names
        qd = sorted(e["ts"] for e in evs if e["name"] == "queue_depth")
        assert qd[0] == 0 and qd[1] - qd[0] == ms(25)
        # masked lane -> [] and the caller falls back to the ring path
        stm = rt.run_fused(rt.init_batch(np.arange(2), series_lanes=[1]),
                           1024, 256)
        assert series_counter_track_events(stm, lane=0) == []
        fb = {e["name"] for e in counter_track_events(stm, lane=0)}
        assert "queue_depth" not in fb
        assert any(n.startswith("e2e_p99:") for n in fb)

    def test_counter_tracks_on_series_only_build(self):
        # ring compiled out entirely: the series tracks stand on their
        # own instead of raising the ring's "compiled out" ValueError
        rt = _pingpong_rt(windows=4, window_len=ms(25), target=60, lat=24)
        st = rt.run_fused(rt.init_batch(np.arange(2)), 1024, 256)
        names = {e["name"] for e in counter_track_events(st, lane=0)}
        assert {"queue_depth", "e2e_p99", "fault"} <= names
        # both planes out -> still the honest ring error
        rt0 = _pingpong_rt()
        st0 = rt0.run_fused(rt0.init_batch(np.arange(2)), 256, 256)
        with pytest.raises(ValueError, match="compiled out"):
            counter_track_events(st0, lane=0)

    def test_dashboard_sim_time_sparklines(self):
        from madsim_tpu.obs.dashboard import (render_html,
                                              series_sparklines_html)
        rt = _pingpong_rt(windows=4, window_len=ms(25), target=60, lat=24)
        st = rt.run_fused(rt.init_batch(np.arange(2)), 1024, 256)
        s = series_summary(st)
        html = series_sparklines_html(s)
        assert "<svg" in html and "Sim-time telemetry" in html
        assert "4 windows" in html and "25000us" in html
        assert "Dispatches / window" in html
        assert "e2e p99 / window" in html       # latency build only
        assert "boot" in html                   # w0 fault-marker footnote
        assert series_sparklines_html(None) == ""
        # render_html includes the section iff the snapshot carries it
        attr = {k: {"base": 1} for k in
                ("recipe_coverage", "recipe_buckets",
                 "operator_coverage", "operator_buckets")}
        cur = {"store": {}, "curves": {}, "attribution": attr,
               "buckets": {}}
        assert "Sim-time telemetry" in render_html(dict(cur, series=s),
                                                   None)
        assert "Sim-time telemetry" not in render_html(cur, None)


# ---------------------------------------------------------------------------
# 4. the recovery oracle
# ---------------------------------------------------------------------------

class TestRecoveryInvariant:
    def _oracle_rt(self, **kw):
        return _pingpong_rt(windows=4, window_len=ms(100), target=40,
                            lat=24, invariant=recovery_invariant(**kw))

    def _prime(self, rt, fault_w=0, qhw=(0, 0, 0, 0), heal_w=None):
        # craft a lane history: now deep enough that all 4 windows are
        # complete, a disruptive marker in fault_w, optional heal
        # marker, per-window queue high-waters — then step once so the
        # oracle judges it
        st = rt.init_batch(np.arange(4))
        f = np.zeros(np.asarray(st.sr_fault).shape, np.int32)
        f[:, fault_w] = T.SRF_PARTITION
        if heal_w is not None:
            f[:, heal_w] |= T.SRF_HEAL
        q = np.broadcast_to(np.asarray(qhw, np.int32),
                            np.asarray(st.sr_qhw).shape)
        st = st.replace(sr_fault=jnp.asarray(f), sr_qhw=jnp.asarray(q),
                        now=jnp.full_like(st.now, ms(450)))
        out, _ = rt.run(st, 1, 1)
        return out

    def test_arg_validation(self):
        with pytest.raises(ValueError, match="p99_le"):
            recovery_invariant()
        with pytest.raises(ValueError, match="within"):
            recovery_invariant(qhw_le=5, within=0)

    def test_raises_on_compiled_out_plane(self):
        rt = _pingpong_rt(windows=0,
                          invariant=recovery_invariant(qhw_le=5))
        with pytest.raises(ValueError, match="series_windows"):
            rt.run(rt.init_batch(np.arange(2)), 64, 64)

    def test_p99_form_needs_latency_plane(self):
        rt = _pingpong_rt(windows=4,
                          invariant=recovery_invariant(p99_le=ms(1)))
        with pytest.raises(ValueError, match="latency plane"):
            rt.run(rt.init_batch(np.arange(2)), 64, 64)

    def test_judges_only_past_grace_and_fires_with_crash_recovery(self):
        rt = self._oracle_rt(qhw_le=8, within=2)
        # fault in w0, queue still deep in w3 (a judged window): red
        red = self._prime(rt, fault_w=0, qhw=(50, 50, 50, 50))
        assert (np.asarray(red.crash_code) == CRASH_RECOVERY).all()
        # deep queue only INSIDE the grace windows (w0-w1): tolerated
        green = self._prime(rt, fault_w=0, qhw=(50, 50, 3, 3))
        assert not np.asarray(green.crashed).any()

    def test_heal_does_not_restart_the_clock(self):
        # the cure is not the disease: a heal marker after the fault
        # leaves judging anchored at the DISRUPTIVE window, so a
        # still-deep queue in w3 fires even with a heal in w2
        rt = self._oracle_rt(qhw_le=8, within=2)
        st = self._prime(rt, fault_w=0, qhw=(50, 50, 3, 50), heal_w=2)
        assert (np.asarray(st.crash_code) == CRASH_RECOVERY).all()

    def test_fault_too_late_leaves_nothing_to_judge(self):
        rt = self._oracle_rt(qhw_le=8, within=2)
        st = self._prime(rt, fault_w=3, qhw=(50, 50, 50, 50))
        assert not np.asarray(st.crashed).any()

    def test_no_fault_never_fires(self):
        # the oracle judges recovery, not steady state: a fault-free
        # run is green even with an unattainable envelope
        rt = self._oracle_rt(qhw_le=0, within=1)
        st = rt.run_fused(rt.init_batch(np.arange(4)), 256, 64)
        assert not np.asarray(st.crashed).any()

    @pytest.mark.slow
    def test_flagship_green_red_and_seed_replay(self):
        # the canonical recovery flagship (bench._make_recovery_runtime):
        # a clogged-then-unclogged echo cluster recovers inside the
        # grace windows (green); the unhealed latency fault keeps p99
        # pinned past them (red, CRASH_RECOVERY), and the crash replays
        # fingerprint-exact by seed — the repro contract
        from bench import _make_recovery_runtime
        inv = recovery_invariant(p99_le=ms(20), within=4, min_count=8)
        seeds = np.arange(8, dtype=np.uint32)
        rt_g = _make_recovery_runtime("heal", invariant=inv)
        g = rt_g.run_fused(rt_g.init_batch(seeds), 40000, 2048)
        assert not np.asarray(g.crashed).any()
        f = np.asarray(g.sr_fault)
        assert (f[:, 1] & T.SRF_PARTITION != 0).all()
        assert (f[:, 4] & T.SRF_HEAL != 0).all()
        rt_r = _make_recovery_runtime("noheal", invariant=inv)
        a = rt_r.run_fused(rt_r.init_batch(seeds), 40000, 2048)
        b = rt_r.run_fused(rt_r.init_batch(seeds), 40000, 2048)
        assert (np.asarray(a.crash_code) == CRASH_RECOVERY).all()
        assert (rt_r.fingerprints(a) == rt_r.fingerprints(b)).all()
        single, _ = rt_r.run_single(int(seeds[3]), 40000, 2048)
        assert int(np.asarray(single.crash_code)[0]) == CRASH_RECOVERY


# ---------------------------------------------------------------------------
# 5. burst-guided fuzzing
# ---------------------------------------------------------------------------

class TestBurstBonus:
    def test_corpus_burst_bonus_scales_admission_energy(self):
        from bench import _make_saturating_runtime
        from madsim_tpu.search.corpus import Corpus
        from madsim_tpu.search.mutate import KnobPlan
        rt = _make_saturating_runtime()
        plan = KnobPlan.from_runtime(rt)
        c = Corpus(plan, burst_bonus=1.0)
        kb = plan.base_batch(2)
        c.observe(kb, np.arange(2), np.asarray([1, 2], np.uint64),
                  np.zeros(2, bool), np.zeros(2, np.int64),
                  np.full(2, -1, np.int64), 0,
                  burst=np.asarray([100, 1000], np.int32))
        by_hash = {e["hash"]: e["energy"] for e in c.entries}
        assert by_hash[2] == pytest.approx(2.0)    # worst spike: x(1+1)
        assert by_hash[1] == pytest.approx(1.1)    # 100/1000 relative
        # burst-blind corpus ignores the signal entirely
        c0 = Corpus(plan, burst_bonus=0.0)
        c0.observe(kb, np.arange(2), np.asarray([1, 2], np.uint64),
                   np.zeros(2, bool), np.zeros(2, np.int64),
                   np.full(2, -1, np.int64), 0,
                   burst=np.asarray([100, 1000], np.int32))
        assert all(e["energy"] == 1.0 for e in c0.entries)

    def test_lane_burst_reads_deepest_transient_spike(self):
        # lane 0's spike lives in window 0 (p99 edge 4), lane 1's in
        # window 1 (edge 512): the per-lane metric keeps windows
        # separate and maxes over them — the signal an aggregate p99
        # would dilute
        rt = _pingpong_rt(windows=4, window_len=ms(25), target=40, lat=24)
        st = rt.init_batch(np.arange(2))
        sl = np.zeros(np.asarray(st.sr_lat).shape, np.int32)
        sl[0, 0, 3] = 100
        sl[1, 1, 10] = 100
        st = st.replace(sr_lat=jnp.asarray(sl))
        assert lane_burst(st).tolist() == [4, 512]
        # latency-less builds fall back to the queue high-water
        rt0 = _pingpong_rt(windows=2, window_len=ms(25), target=40)
        st0 = rt0.init_batch(np.arange(2))
        st0 = st0.replace(sr_qhw=jnp.asarray([[5, 2], [1, 9]], jnp.int32))
        assert lane_burst(st0).tolist() == [5, 9]


# ---------------------------------------------------------------------------
# 6. checkpoint migration
# ---------------------------------------------------------------------------

class TestCheckpointMigration:
    def test_pre_r21_checkpoint_rejected_by_leaf_count(self, tmp_path):
        # the MIGRATION r21 contract: a pre-r21 checkpoint (no sr_*/
        # window_len leaves — 11 fewer) fails load() loudly on the leaf
        # count, not by silent misalignment
        from madsim_tpu.runtime import checkpoint
        rt = _pingpong_rt(windows=4, lat=24)
        st = rt.init_batch(np.arange(2))
        p = str(tmp_path / "ck.npz")
        checkpoint.save(p, st)
        with np.load(p) as z:
            leaves = {k: z[k] for k in z.files}
        n = len([k for k in leaves if k.startswith("leaf_")])
        stripped = {k: v for k, v in leaves.items()
                    if not k.startswith("leaf_")}
        for i in range(n - len(SR_LEAVES)):
            stripped[f"leaf_{i}"] = leaves[f"leaf_{i}"]
        p2 = str(tmp_path / "old.npz")
        np.savez_compressed(p2, **stripped)
        with pytest.raises(ValueError, match="leaves"):
            checkpoint.load(p2, st)
