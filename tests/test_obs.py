"""Flight recorder + observability layer: ring semantics, exporters,
sweep observers.

The load-bearing properties, per the observability discipline (DESIGN.md):
(1) the ring is an OBSERVER — all non-trace state must be bit-identical
whether the ring is compiled out, compiled in, or sampling; (2) the ring
survives `lax.while_loop`, so `run_fused` sweeps yield traces bitwise
equal to the chunked runner's; (3) exporters honor the overshoot
contract — frozen-lane `fired=False` records never reach a trace.
"""

import io
import json

import jax
import numpy as np
import pytest

from madsim_tpu import (JsonlObserver, NetConfig, ProgressObserver, Runtime,
                        Scenario, SimConfig, explore, ms, sec, summarize)
from madsim_tpu.core import types as T
from madsim_tpu.core.state import TRACE_FIELDS as _TRACE_FIELDS
from madsim_tpu.obs import (export_chrome_trace, ring_records, sampled_lanes,
                            to_chrome_events)
from madsim_tpu.obs.metrics import TeeObserver
from madsim_tpu.models.pingpong import PingPong, state_spec


def _pingpong_rt(trace_cap=0, target=3, n_nodes=2, scenario=None, loss=0.0):
    cfg = SimConfig(n_nodes=n_nodes, time_limit=sec(5), trace_cap=trace_cap,
                    net=NetConfig(packet_loss_rate=loss,
                                  send_latency_min=ms(1),
                                  send_latency_max=ms(4)))
    return Runtime(cfg, [PingPong(n_nodes, target=target)], state_spec(),
                   scenario=scenario)


def _nontrace_state(state) -> dict:
    out = {}
    for name in type(state).__dataclass_fields__:
        if name in _TRACE_FIELDS or name in ("node_state", "ext"):
            continue
        out[name] = np.asarray(getattr(state, name))
    for i, leaf in enumerate(jax.tree.leaves(state.node_state)):
        out[f"node_state_{i}"] = np.asarray(leaf)
    return out


class TestRing:
    def test_wraparound_at_capacity(self):
        # far more events than ring rows: the ring must hold exactly the
        # LAST cap events in chronological order and report the drop
        rt = _pingpong_rt(trace_cap=4, target=40)
        state, events = rt.run(rt.init_batch(np.arange(2)), 512, 64,
                               collect_events=True)
        recs = ring_records(state, lane=1)
        steps = int(np.asarray(state.steps)[1])
        assert recs["total"] == steps > 4          # every event counted
        assert recs["dropped"] == steps - 4
        assert len(recs["now"]) == 4
        # chronological and exactly the tail of the collect_events stream
        fired = np.asarray(events["fired"])[:, 1]
        idx = np.nonzero(fired)[0][-4:]
        for col in ("now", "kind", "node", "src", "tag"):
            assert (recs[col] == np.asarray(events[col])[idx, 1]).all(), col
        assert (np.diff(recs["step"]) == 1).all()
        assert (np.diff(recs["now"]) >= 0).all()

    def test_ring_not_wrapped_holds_everything(self):
        rt = _pingpong_rt(trace_cap=64, target=3)
        state, events = rt.run(rt.init_batch(np.arange(2)), 256, 64,
                               collect_events=True)
        recs = ring_records(state, lane=0)
        steps = int(np.asarray(state.steps)[0])
        assert recs["total"] == steps and recs["dropped"] == 0
        fired = np.asarray(events["fired"])[:, 0]
        assert (recs["now"] == np.asarray(events["now"])[fired, 0]).all()

    def test_lane_sampling_mask(self):
        rt = _pingpong_rt(trace_cap=8, target=40)
        state = rt.run_fused(rt.init_batch(np.arange(8),
                                           trace_lanes=[2, 5]), 128, 64)
        pos = np.asarray(state.trace_pos)
        assert (pos[[2, 5]] > 0).all()
        assert (pos[[0, 1, 3, 4, 6, 7]] == 0).all()
        assert sampled_lanes(state).tolist() == [2, 5]
        with pytest.raises(ValueError, match="not sampled"):
            ring_records(state, lane=0)

    def test_bool_mask_form(self):
        rt = _pingpong_rt(trace_cap=8, target=40)
        mask = np.zeros(4, bool)
        mask[1] = True
        state = rt.run_fused(rt.init_batch(np.arange(4), trace_lanes=mask),
                             128, 64)
        assert sampled_lanes(state).tolist() == [1]

    def test_trace_lanes_requires_compiled_ring(self):
        rt = _pingpong_rt(trace_cap=0)
        with pytest.raises(ValueError, match="trace_cap"):
            rt.init_batch(np.arange(4), trace_lanes=[0])

    def test_ring_compiled_out_raises_on_read(self):
        rt = _pingpong_rt(trace_cap=0)
        state, _ = rt.run(rt.init_batch(np.arange(2)), 128, 64)
        with pytest.raises(ValueError, match="compiled out"):
            ring_records(state, lane=0)


class TestRingEquivalence:
    """run_fused with trace_cap > 0 bitwise-equal to chunked run() on all
    state (ring included), and the ring itself an observer that never
    perturbs the trajectory. The raft/wal_kv/shard_kv chaos sweeps are
    `slow` (r7 durations triage); the fast lane keeps the pingpong
    perturbation check here plus the fused-equality assert inside
    `bench.py --obs-smoke` (ci.sh fast)."""

    def _assert_fused_equals_chunked(self, rt, seeds, max_steps, chunk):
        chunked, _ = rt.run(rt.init_batch(seeds), max_steps, chunk)
        fused = rt.run_fused(rt.init_batch(seeds), max_steps, chunk)
        # fingerprints cover all non-trace state (the recorder is
        # excluded by design — utils/hashing); the ring columns are
        # compared explicitly so the fused runner must reproduce the
        # recorder's contents exactly too, not just the trajectory
        assert (rt.fingerprints(chunked) == rt.fingerprints(fused)).all()
        for f in _TRACE_FIELDS:
            assert (np.asarray(getattr(chunked, f))
                    == np.asarray(getattr(fused, f))).all(), f
        return fused

    @pytest.mark.slow
    def test_raft_fused_equals_chunked_with_ring(self):
        from madsim_tpu.models.raft import make_raft_runtime
        cfg = SimConfig(n_nodes=5, event_capacity=128, time_limit=sec(3),
                        trace_cap=16,
                        net=NetConfig(packet_loss_rate=0.05,
                                      send_latency_min=ms(1),
                                      send_latency_max=ms(10)))
        sc = Scenario()
        sc.at(sec(1)).kill_random()
        sc.at(sec(1) + ms(400)).restart_random()
        rt = make_raft_runtime(5, 8, n_cmds=4, scenario=sc, cfg=cfg)
        fused = self._assert_fused_equals_chunked(
            rt, np.arange(64, dtype=np.uint32), 1500, 256)
        assert (np.asarray(fused.trace_pos) > 0).all()

    @pytest.mark.slow
    def test_wal_kv_fused_equals_chunked_with_ring(self):
        # mid-sweep crashes: crashed lanes freeze their rings exactly
        # where the chunked runner froze them
        from madsim_tpu.models.wal_kv import make_wal_kv_runtime
        sc = Scenario()
        for t in range(6):
            sc.at(ms(150) + ms(250) * t).kill(0)
            sc.at(ms(210) + ms(250) * t).restart(0)
        # the factory's default cfg with the recorder switched on
        cfg = SimConfig(n_nodes=3, event_capacity=256, payload_words=8,
                        time_limit=sec(10), trace_cap=16,
                        net=NetConfig(send_latency_min=ms(1),
                                      send_latency_max=ms(8)))
        rt = make_wal_kv_runtime(n_clients=2, n_ops=12, wal_cap=64,
                                 sync_wal=False, scenario=sc, cfg=cfg)
        fused = self._assert_fused_equals_chunked(
            rt, np.arange(64, dtype=np.uint32), 4096, 512)
        crashed = np.asarray(fused.crashed)
        assert crashed.any() and not crashed.all()

    @pytest.mark.slow
    def test_shard_kv_fused_equals_chunked_with_ring(self):
        from madsim_tpu.models.shard_kv import make_shard_runtime
        cfg = SimConfig(n_nodes=11, event_capacity=160, payload_words=12,
                        time_limit=sec(60), trace_cap=16,
                        net=NetConfig(send_latency_min=ms(1),
                                      send_latency_max=ms(10)))
        rt = make_shard_runtime(n_groups=2, rg=3, rc=3, n_clients=2,
                                n_ops=4, max_cfg=4, cfg=cfg)
        self._assert_fused_equals_chunked(
            rt, np.arange(64, dtype=np.uint32), 4096, 512)

    def test_fingerprints_ignore_sampling_mask(self):
        # partial lane sampling must not split fingerprints: the same
        # seeds with different trace_lanes masks (and a cap=0 build)
        # fingerprint identically, so distinct_outcomes stays a
        # trajectory metric, not a which-lanes-were-sampled metric
        seeds = np.arange(8, dtype=np.uint32)
        rt = _pingpong_rt(trace_cap=8)
        sampled, _ = rt.run(rt.init_batch(seeds, trace_lanes=[0]), 256, 64)
        allon, _ = rt.run(rt.init_batch(seeds), 256, 64)
        assert (rt.fingerprints(sampled) == rt.fingerprints(allon)).all()
        rt0 = _pingpong_rt(trace_cap=0)
        off, _ = rt0.run(rt0.init_batch(seeds), 256, 64)
        assert (rt0.fingerprints(off) == rt.fingerprints(sampled)).all()

    def test_ring_never_perturbs_trajectory(self):
        # same workload, ring compiled out vs compiled in vs sampling:
        # every non-trace field bit-identical (trace_cap is an
        # observation lever, not a replay domain)
        seeds = np.arange(16, dtype=np.uint32)
        base, _ = _pingpong_rt(trace_cap=0).run(
            _pingpong_rt(trace_cap=0).init_batch(seeds), 256, 64)
        ref = _nontrace_state(base)
        for cap, lanes in ((8, None), (8, [0, 3]), (64, [])):
            rt = _pingpong_rt(trace_cap=cap)
            st, _ = rt.run(rt.init_batch(seeds, trace_lanes=lanes), 256, 64)
            got = _nontrace_state(st)
            assert ref.keys() == got.keys()
            for k in ref:
                assert (ref[k] == got[k]).all(), \
                    f"trace_cap={cap} lanes={lanes} perturbed {k}"


class TestChromeExport:
    def _kill_restart_rt(self, **kw):
        sc = Scenario()
        sc.at(ms(6)).kill(1)
        sc.at(ms(9)).restart(1)
        return _pingpong_rt(scenario=sc, target=12, **kw)

    def test_event_count_equals_fired_count(self, tmp_path):
        rt = self._kill_restart_rt()
        state, events = rt.run_single(7, 512, chunk=128)
        p = str(tmp_path / "t.json")
        n = export_chrome_trace(p, events=events)
        with open(p) as f:
            doc = json.load(f)                     # valid JSON
        inst = [e for e in doc["traceEvents"] if e["ph"] == "i"]
        fired = int(np.asarray(events["fired"])[:, 0].sum())
        assert n == len(inst) == fired == int(np.asarray(state.steps)[0])

    def test_frozen_lane_records_excluded(self):
        # overshoot: lanes halt at different steps but every lane's chunk
        # tail keeps emitting fired=False records — none may export
        rt = _pingpong_rt(target=3)
        state, events = rt.run(rt.init_batch(np.arange(4)), 4096, 256,
                               collect_events=True)
        assert np.asarray(events["fired"]).shape[0] \
            > int(np.asarray(state.steps).max())
        for lane in range(4):
            evs = to_chrome_events(events, b=lane)
            assert len(evs) == int(np.asarray(state.steps)[lane])

    def test_kill_restart_render_on_right_node_track(self, tmp_path):
        rt = self._kill_restart_rt()
        _, events = rt.run_single(3, 512, chunk=128)
        p = str(tmp_path / "t.json")
        export_chrome_trace(p, events=events, node_names=["ping", "pong"])
        with open(p) as f:
            doc = json.load(f)
        kills = [e for e in doc["traceEvents"] if e["name"] == "SUPER:KILL"]
        restarts = [e for e in doc["traceEvents"]
                    if e["name"] == "SUPER:RESTART"]
        assert kills and restarts
        assert all(e["tid"] == 1 and e["ph"] == "i" for e in kills + restarts)
        assert kills[0]["ts"] == T.ms(6) and restarts[0]["ts"] == T.ms(9)
        names = {e["tid"]: e["args"]["name"] for e in doc["traceEvents"]
                 if e["ph"] == "M"}
        assert names[1] == "pong"

    def test_ring_export_matches_collect_events_export(self, tmp_path):
        # cap big enough that nothing dropped: the fused sweep's ring
        # must export the identical event list as the chunked
        # collect_events stream for the same seed
        rt = self._kill_restart_rt(trace_cap=128)
        seeds = np.arange(2, dtype=np.uint32)
        _, events = rt.run(rt.init_batch(seeds), 512, 128,
                           collect_events=True)
        fused = rt.run_fused(rt.init_batch(seeds), 512, 128)
        from_events = to_chrome_events(events, b=1)
        from_ring = to_chrome_events(ring_records(fused, lane=1))
        # the ring source carries MORE than the stream: lineage args
        # (lamport/parent, r10) on each instant plus causal flow arrows
        # appended after them. The shared contract is the dispatch
        # timeline itself — instants must match field-for-field once the
        # ring-only lineage args are set aside.
        ring_instants = [dict(e, args={k: v for k, v in e["args"].items()
                                       if k not in ("lamport", "parent")})
                         for e in from_ring if e["ph"] == "i"]
        assert ring_instants == from_events
        # and every instant the ring exports DOES carry the lineage pair
        assert all({"lamport", "parent"} <= e["args"].keys()
                   for e in from_ring if e["ph"] == "i")

    def test_golden_roundtrip(self, tmp_path):
        # hand-built record stream -> exact expected JSON document
        events = dict(
            fired=np.array([[True], [True], [True], [False]]),
            now=np.array([[0], [1000], [2500], [2500]]),
            kind=np.array([[T.EV_SUPER], [T.EV_MSG], [T.EV_TIMER],
                           [T.EV_MSG]]),
            node=np.array([[0], [1], [1], [0]]),
            src=np.array([[0], [0], [1], [1]]),
            tag=np.array([[T.OP_INIT], [7], [3], [9]]),
        )
        p = str(tmp_path / "golden.json")
        n = export_chrome_trace(p, events=events)
        assert n == 3                              # fired=False dropped
        with open(p) as f:
            doc = json.load(f)
        assert doc == {
            "traceEvents": [
                {"name": "thread_name", "ph": "M", "pid": 0, "tid": 0,
                 "args": {"name": "node0"}},
                {"name": "thread_name", "ph": "M", "pid": 0, "tid": 1,
                 "args": {"name": "node1"}},
                # args.step (r10): the dispatch index — a stream's k-th
                # fired record IS dispatch k, so Perfetto queries can
                # join the timeline against explain_crash chains and
                # divergence reports
                {"name": "SUPER:INIT", "ph": "i", "s": "t", "ts": 0,
                 "pid": 0, "tid": 0,
                 "args": {"src": 0, "tag": T.OP_INIT, "step": 0}},
                {"name": "MSG:tag7", "ph": "i", "s": "t", "ts": 1000,
                 "pid": 0, "tid": 1, "args": {"src": 0, "tag": 7, "step": 1}},
                {"name": "TIMER:tag3", "ph": "i", "s": "t", "ts": 2500,
                 "pid": 0, "tid": 1, "args": {"src": 1, "tag": 3, "step": 2}},
            ],
            "displayTimeUnit": "ms",
        }

    def test_exactly_one_source(self):
        with pytest.raises(ValueError, match="exactly one"):
            export_chrome_trace("/tmp/x.json")


class TestSweepObservers:
    def test_run_observer_sees_chunks_and_done(self):
        rt = _pingpong_rt(target=3)
        buf = io.StringIO()
        with JsonlObserver(buf) as obs:
            state, _ = rt.run(rt.init_batch(np.arange(8)), 1024, 128,
                              observer=obs)
        recs = [json.loads(l) for l in buf.getvalue().splitlines()]
        assert recs == obs.records
        assert [r["kind"] for r in recs][-1] == "done"
        chunks = [r for r in recs if r["kind"] == "chunk"]
        assert chunks and chunks[0]["batch"] == 8
        assert (np.diff([c["steps_done"] for c in chunks]) == 128).all()
        done = recs[-1]
        assert done["lanes_halted"] == 8
        assert done["lane_steps_per_sec"] > 0

    def test_explore_observer_matches_result(self):
        rt = _pingpong_rt(target=3, loss=0.1, n_nodes=4)
        buf = io.StringIO()
        with JsonlObserver(buf) as obs:
            res = explore(rt, max_steps=1024, batch=16, max_rounds=4,
                          dry_rounds=2, observer=obs)
        rounds = [r for r in obs.records if r["kind"] == "round"]
        assert len(rounds) == res["rounds"]
        assert [r["new_schedules"] for r in rounds] == res["new_per_round"]
        assert rounds[-1]["distinct_total"] == res["distinct_schedules"]
        assert obs.records[-1]["kind"] == "done"
        assert obs.records[-1]["distinct_total"] == res["distinct_schedules"]

    def test_compacting_observer_sees_repack(self):
        # loss-driven retries spread halt steps across lanes (measured
        # 31..61 over this batch) and a fine chunk catches the spread
        # mid-flight, so the re-pack actually triggers; tiny min_batch
        cfg = SimConfig(n_nodes=2, time_limit=sec(60),
                        net=NetConfig(packet_loss_rate=0.3,
                                      send_latency_min=ms(1),
                                      send_latency_max=ms(40)))
        rt = Runtime(cfg, [PingPong(2, target=6)], state_spec())
        seeds = np.arange(64, dtype=np.uint32)
        ref, _ = rt.run(rt.init_batch(seeds), 8192, 16)
        obs = JsonlObserver(io.StringIO())
        final = rt.run_compacting(rt.init_batch(seeds), 8192, 16,
                                  compact_when=0.3, min_batch=8,
                                  observer=obs)
        assert (rt.fingerprints(final) == rt.fingerprints(ref)).all()
        compacts = [r for r in obs.records if r["kind"] == "compact"]
        assert compacts, "workload never triggered a re-pack"
        assert all(c["to_batch"] < c["from_batch"] for c in compacts)
        assert obs.records[-1]["kind"] == "done"
        assert obs.records[-1]["repacks"] == len(compacts)
        assert obs.records[-1]["lanes_halted"] == 64

    def test_progress_and_tee(self):
        rt = _pingpong_rt(target=3)
        out = io.StringIO()
        jl = JsonlObserver(io.StringIO())
        prog = ProgressObserver(stream=out, min_interval=0.0)
        rt.run(rt.init_batch(np.arange(8)), 512, 128,
               observer=TeeObserver(jl, prog))
        assert "halted 8/8" in out.getvalue()
        assert jl.records[-1]["kind"] == "done"


def _fake_state(cap, pos, on=True, batch=None):
    """Synthetic ring state: slot values encode (event index + 1) * 10 so
    unwrap order is checkable without running the engine. Exercises
    rings.py's host-side math at zero compile cost."""
    from types import SimpleNamespace
    if cap > 0:
        vals = np.zeros(cap, np.int32)
        for e in range(pos):            # replay the writer's slot rule
            vals[e % cap] = (e + 1) * 10
    else:
        vals = np.zeros(0, np.int32)
    cols = {f"tr_{k}": vals.copy() for k in
            ("now", "step", "kind", "node", "src", "tag")}
    st = SimpleNamespace(trace_pos=np.int32(pos), trace_on=np.bool_(on),
                         trace_cap=np.int32(cap),   # the dynamic capacity
                         **cols)                    # operand (DESIGN §10)
    if batch is not None:
        for k, v in vars(st).items():
            setattr(st, k, np.stack([np.asarray(v)] * batch))
    return st


class TestRingUnwrapMath:
    def test_empty_ring(self):
        recs = ring_records(_fake_state(4, 0))
        assert recs["total"] == 0 and recs["dropped"] == 0
        assert len(recs["now"]) == 0

    def test_partial_fill_is_prefix(self):
        recs = ring_records(_fake_state(4, 3))
        assert recs["now"].tolist() == [10, 20, 30]
        assert recs["dropped"] == 0

    def test_exactly_full_no_wrap(self):
        recs = ring_records(_fake_state(4, 4))
        assert recs["now"].tolist() == [10, 20, 30, 40]
        assert recs["dropped"] == 0

    def test_wrap_by_one(self):
        recs = ring_records(_fake_state(4, 5))
        assert recs["now"].tolist() == [20, 30, 40, 50]
        assert recs["dropped"] == 1

    def test_wrap_to_slot_zero_boundary(self):
        # pos a multiple of cap after wrapping: oldest is at slot 0 again
        recs = ring_records(_fake_state(4, 8))
        assert recs["now"].tolist() == [50, 60, 70, 80]
        assert recs["dropped"] == 4

    def test_batched_lane_select(self):
        recs = ring_records(_fake_state(4, 5, batch=3), lane=2)
        assert recs["now"].tolist() == [20, 30, 40, 50]

    def test_unsampled_lane_raises(self):
        with pytest.raises(ValueError, match="not sampled"):
            ring_records(_fake_state(4, 0, on=False))

    def test_chrome_events_from_ring_dict(self):
        # a ring_records dict feeds the exporter without a fired column
        evs = to_chrome_events(dict(
            now=np.array([5, 9]), kind=np.array([T.EV_MSG, T.EV_TIMER]),
            node=np.array([1, 0]), src=np.array([0, 0]),
            tag=np.array([7, 2])))
        assert [e["ts"] for e in evs] == [5, 9]
        assert evs[0]["name"] == "MSG:tag7"
        assert evs[1]["name"] == "TIMER:tag2"


class TestObserverPlumbing:
    def test_jsonl_rounds_floats_and_appends(self, tmp_path):
        p = str(tmp_path / "m.jsonl")
        with JsonlObserver(p) as obs:
            obs.on_chunk(dict(kind="chunk", wall_s=1.23456))
        with JsonlObserver(p) as obs:       # append, not truncate
            obs.on_done(dict(kind="done", wall_s=2.0))
        recs = [json.loads(l) for l in open(p)]
        assert [r["kind"] for r in recs] == ["chunk", "done"]
        assert recs[0]["wall_s"] == 1.235

    def test_tee_fans_out_every_hook(self):
        seen = []

        class Probe(JsonlObserver):
            def __init__(self, name):
                super().__init__(io.StringIO())
                self.name = name

            def _emit(self, rec):
                seen.append((self.name, rec["kind"]))

            on_chunk = on_compact = on_round = on_done = _emit

        tee = TeeObserver(Probe("a"), Probe("b"))
        tee.on_chunk(dict(kind="chunk"))
        tee.on_compact(dict(kind="compact"))
        tee.on_round(dict(kind="round"))
        tee.on_done(dict(kind="done"))
        assert seen == [("a", "chunk"), ("b", "chunk"),
                        ("a", "compact"), ("b", "compact"),
                        ("a", "round"), ("b", "round"),
                        ("a", "done"), ("b", "done")]

    def test_progress_rate_formatting(self):
        from madsim_tpu.obs.progress import _rate
        assert _rate(512.0) == "512"
        assert _rate(2_500.0) == "2.5k"
        assert _rate(3_400_000.0) == "3.4M"
        assert _rate(1.2e9) == "1.2G"

    def test_base_observer_is_noop(self):
        from madsim_tpu import SweepObserver
        obs = SweepObserver()
        obs.on_chunk({})
        obs.on_compact({})
        obs.on_round({})
        obs.on_done({})


class TestSummarizeLabels:
    def test_labels_are_explicit(self):
        rt = _pingpong_rt(target=3)
        seeds = np.arange(100, 108, dtype=np.uint32)
        state, _ = rt.run(rt.init_batch(seeds), 512, 128)
        assert summarize(rt, state)["seed_labels"] == "lane_index"
        rep = summarize(rt, state, seeds=seeds)
        assert rep["seed_labels"] == "seed"
