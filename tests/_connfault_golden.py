"""Shared harness for the r19 bit-identical-when-disabled contract.

The connection-fault plane (r19) added engine machinery — reset-peer
conn/stream teardown, per-node duplicate-delivery rate — that is
DYNAMIC: always compiled in, masked to identity at the zero defaults.
The contract is that a scenario using none of the new ops produces
trajectories BIT-IDENTICAL to r18, leaf for leaf, chunked and fused.

Same frozen workload builders as the r17 harness (_grayfail_golden —
they are the canonical engine-equivalence workloads, deliberately
conn/stream-free so the library-level wire-format change cannot touch
them); digests were captured AT r18 HEAD by scripts/capture_golden.py
into tests/data/golden_r18_leaves.json, before any r19 engine change
landed. Every r18 leaf must still exist and hash identically — the
only new leaf the r19 plane may add is `.dup_rate`.
"""

from __future__ import annotations

import os

import _grayfail_golden as _g

GOLDEN_PATH = os.path.join(os.path.dirname(__file__), "data",
                           "golden_r18_leaves.json")

# the frozen definition is shared with the r17 harness — one set of
# engine workloads, two captured truths (r16 and r18)
RUNS = _g.RUNS
BUILDERS = _g.BUILDERS
leaf_digests = _g.leaf_digests
run_workload = _g.run_workload


def capture(path: str = GOLDEN_PATH) -> dict:
    return _g.capture(path)


def load_golden(path: str = GOLDEN_PATH) -> dict:
    return _g.load_golden(path)
