"""KV-on-Raft linearizability fuzz — BASELINE.md config 4 — plus unit tests
for the checker itself (C++ and Python implementations, differentially)."""

import numpy as np
import pytest

from madsim_tpu import Scenario, SimConfig, NetConfig, ms, sec
from madsim_tpu.harness.simtest import run_seeds
from madsim_tpu.models.raft_kv import extract_histories, make_kv_runtime
from madsim_tpu.native import check_kv_history, check_register

PUT, GET = 1, 2


pytestmark = pytest.mark.slow  # measured in --durations; ci.sh fast skips

def H(*ops):
    """ops: (op, val, inv, resp) tuples -> checker args."""
    a = np.asarray(ops, np.int64).reshape(-1, 4)
    return a[:, 0], a[:, 1], a[:, 2], a[:, 3]


class TestCheckerUnit:
    CASES = [
        # (ops, expected)
        ([(GET, 0, 0, 1)], True),                       # read initial value
        ([(GET, 5, 0, 1)], False),                      # read from nowhere
        ([(PUT, 5, 0, 1), (GET, 5, 2, 3)], True),
        ([(PUT, 5, 0, 1), (GET, 0, 2, 3)], False),      # stale read
        # concurrent put/get: either order is fine
        ([(PUT, 5, 0, 10), (GET, 5, 1, 2)], True),
        ([(PUT, 5, 0, 10), (GET, 0, 1, 2)], True),
        # sequential reads observing value regression -> not linearizable
        ([(PUT, 1, 0, 1), (PUT, 2, 2, 3), (GET, 2, 4, 5), (GET, 1, 6, 7)],
         False),
        # pending put may or may not apply: both observations OK
        ([(PUT, 9, 0, -1), (GET, 9, 5, 6)], True),
        ([(PUT, 9, 0, -1), (GET, 0, 5, 6)], True),
        # but a pending put cannot apply before its invocation
        ([(GET, 9, 0, 1), (PUT, 9, 5, -1)], False),
        # two concurrent puts, reads pin the final order
        ([(PUT, 1, 0, 10), (PUT, 2, 0, 10), (GET, 1, 11, 12),
          (GET, 2, 13, 14)], False),  # 2 then 1 impossible after seeing 1
    ]

    @pytest.mark.parametrize("ops,expected", CASES)
    def test_cpp_and_python_agree(self, ops, expected):
        op, val, inv, resp = H(*ops)
        assert check_register(op, val, inv, resp) is expected
        assert check_register(op, val, inv, resp,
                              force_python=True) is expected

    def test_native_library_builds(self):
        from madsim_tpu import native
        assert native._load() is not None, "g++ build of the checker failed"


def _chaos_scenario(n_raft):
    servers = range(n_raft)  # kill servers, never the client harness nodes
    sc = Scenario()
    for t in range(4):
        sc.at(ms(900 + 900 * t)).kill_random(among=servers)
        sc.at(ms(1400 + 900 * t)).restart_random(among=servers)
    sc.at(sec(2)).partition([0, 1])
    sc.at(sec(3)).heal()
    return sc


class TestKvFuzz:
    def test_clean_network_all_linearizable(self):
        rt = make_kv_runtime(n_raft=3, n_clients=2, n_keys=2, n_ops=6,
                             log_capacity=32)
        state = run_seeds(rt, np.arange(8), max_steps=30_000)
        hists = extract_histories(state, 3, 2)
        assert all(len(h["op"]) > 0 for h in hists)
        for h in hists:
            assert check_kv_history(h)

    def test_chaos_histories_linearizable(self):
        # kills/partitions/loss: ops may time out (pending), leaders churn,
        # but every observed response must stay linearizable
        cfg = SimConfig(n_nodes=8, event_capacity=128, payload_words=12,
                        time_limit=sec(8),
                        net=NetConfig(packet_loss_rate=0.05))
        rt = make_kv_runtime(n_raft=5, n_clients=3, n_keys=3, n_ops=8,
                             log_capacity=48,
                             scenario=_chaos_scenario(5), cfg=cfg)
        state = run_seeds(rt, np.arange(8), max_steps=60_000)
        hists = extract_histories(state, 5, 3)
        completed = sum(int((h["resp"] >= 0).sum()) for h in hists)
        assert completed > 0
        for h in hists:
            assert check_kv_history(h)

    def test_python_fallback_beyond_57_ops(self, monkeypatch):
        # the native checker splits at 57 ops/key (linearize.cpp memo-key
        # width); a REAL fuzz producing a >57-op single-key history must
        # flow through the Python fallback end-to-end and still verdict
        from madsim_tpu import native
        assert native._load() is not None  # the native path exists...
        calls = {"py": 0}
        orig = native._check_register_py

        def counting(*a):
            calls["py"] += 1
            return orig(*a)
        monkeypatch.setattr(native, "_check_register_py", counting)
        # 3 clients x 20 ops on ONE key = 60 ops > 57
        rt = make_kv_runtime(n_raft=3, n_clients=3, n_keys=1, n_ops=20,
                             log_capacity=96)
        state = run_seeds(rt, np.arange(4), max_steps=60_000)
        hists = extract_histories(state, 3, 3)
        big = [h for h in hists if len(h["op"]) > 57]
        assert big, "fuzz failed to produce a >57-op history"
        for h in hists:
            assert check_kv_history(h)
        assert calls["py"] > 0  # ...but the >57 histories took the fallback

    def test_detector_catches_corruption(self):
        # mutate one observed GET: the checker must reject the history
        rt = make_kv_runtime(n_raft=3, n_clients=2, n_keys=1, n_ops=6,
                             log_capacity=32)
        state = run_seeds(rt, np.arange(4), max_steps=30_000)
        hists = extract_histories(state, 3, 2)
        h = hists[0]
        gets = np.nonzero((h["op"] == GET) & (h["resp"] >= 0))[0]
        puts = np.nonzero(h["op"] == PUT)[0]
        if len(gets) == 0 or len(puts) == 0:
            pytest.skip("history lacks a completed GET/PUT pair")
        h["val"][gets[0]] = 999_999  # a value nobody ever wrote
        assert not check_kv_history(h)

    def test_minority_server_failure_tolerated(self):
        # one server dead forever: quorum must be over the 5 raft peers
        # (3 of 5), not peers+clients, so every client op still completes
        sc = Scenario()
        sc.at(ms(50)).kill(1)
        rt = make_kv_runtime(n_raft=5, n_clients=2, n_keys=2, n_ops=6,
                             log_capacity=32, scenario=sc)
        state = run_seeds(rt, np.arange(8), max_steps=60_000)
        opn = np.asarray(state.node_state["c_opn"])[:, 5:]
        assert (opn >= 6).all()
        for h in extract_histories(state, 5, 2):
            assert check_kv_history(h)

    def test_batch_vs_single_replay_equivalence(self):
        # the replay-by-seed contract on the FULL stack: seed i inside a
        # chaos batch reaches bit-identical state to seed i run alone
        cfg = SimConfig(n_nodes=8, event_capacity=128, payload_words=12,
                        time_limit=sec(4),
                        net=NetConfig(packet_loss_rate=0.05))
        rt = make_kv_runtime(n_raft=5, n_clients=3, n_keys=3, n_ops=6,
                             log_capacity=48,
                             scenario=_chaos_scenario(5), cfg=cfg)
        batch, _ = rt.run(rt.init_batch(np.arange(12)), 40_000)
        solo, _ = rt.run(rt.init_single(7), 40_000)
        assert rt.fingerprints(batch)[7] == rt.fingerprints(solo)[0]

    def test_checkpoint_mid_chaos_resumes_identically(self):
        from madsim_tpu.runtime import checkpoint
        cfg = SimConfig(n_nodes=8, event_capacity=128, payload_words=12,
                        time_limit=sec(4),
                        net=NetConfig(packet_loss_rate=0.05))
        rt = make_kv_runtime(n_raft=5, n_clients=3, n_keys=3, n_ops=6,
                             log_capacity=48,
                             scenario=_chaos_scenario(5), cfg=cfg)
        seeds = np.arange(8)
        full, _ = rt.run(rt.init_batch(seeds), 40_000)
        half, _ = rt.run(rt.init_batch(seeds), 2048, chunk=2048)
        import tempfile, os
        p = os.path.join(tempfile.mkdtemp(), "kv.npz")
        checkpoint.save(p, half)
        resumed, _ = rt.run(checkpoint.load(p, rt.init_batch(seeds)), 40_000)
        assert (rt.fingerprints(full) == rt.fingerprints(resumed)).all()
