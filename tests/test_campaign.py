"""Persistent fuzzing campaigns (r11): durable corpus store, causal-
fingerprint crash buckets, resumable multi-process service.

Load-bearing contracts (DESIGN §13):
(1) save -> load -> resume is BIT-IDENTICAL: a restored corpus schedules
the same parents and derives the same mutants leaf-for-leaf, and a
split fuzz campaign ends byte-equal to an uninterrupted one;
(2) the store REJECTS mismatches loudly (format version, structural
signature) instead of merging unreplayable entries;
(3) a kill at any instant leaves a loadable store (write-then-rename:
tmp leftovers ignored, half-synced own entries quarantined until the
re-run rewrites them);
(4) entry ids are worker-namespaced — collision-free across processes,
so by-id parent rewards/evictions stay sound under merge;
(5) crash buckets dedup by causal fingerprint across workers, and a
bucket's (seed, knobs) handle replays its crash.
"""

import json
import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

from madsim_tpu import fuzz
from madsim_tpu.obs.causal import causal_fingerprint
from madsim_tpu.search.corpus import Corpus, split_entry_id
from madsim_tpu.search.fuzz import WORKER_SEED_STRIDE
from madsim_tpu.search.mutate import N_MUT_OPS, KnobPlan
from madsim_tpu.service import (CorpusStore, CrashBuckets, StoreMismatch,
                                campaign_report, merged_buckets,
                                replay_bucket, store_signature, worker_cmd)
from madsim_tpu.service.store import CORPUS_VERSION


def _saturating_rt(trace_cap=16, sketch_slots=4):
    """One canonical workload definition (the r9 rule): the bench owns
    it, tests import it."""
    from bench import _make_saturating_runtime
    return _make_saturating_runtime(trace_cap=trace_cap,
                                    sketch_slots=sketch_slots)


def _crashrich_rt():
    # trace_cap/batch/steps chosen to SHARE executables with
    # test_causal's fast-lane wal_kv runs (one compile, two files)
    from bench import _make_crashrich_runtime
    return _make_crashrich_runtime("wal_kv", trace_cap=128)


def _mk_store(tmp_path, rt, plan, name="corpus"):
    return CorpusStore(str(tmp_path / name),
                       signature=store_signature(rt, plan))


def _observe_round(corpus, plan, n=8, hash0=100, round_no=0, crashed=None,
                   sketches=None):
    knobs = KnobPlan.stack([plan.base_knobs() for _ in range(n)])
    corpus.observe(
        knobs, seeds=np.arange(n), crashed=(crashed if crashed is not None
                                            else np.zeros(n, bool)),
        hashes_u64=np.arange(hash0, hash0 + n, dtype=np.uint64),
        codes=np.full(n, 7), parent_ids=np.full(n, -1),
        round_no=round_no, sketches=sketches)


class TestStoreRoundTrip:
    def test_next_round_mutants_bit_identical(self, tmp_path):
        """The satellite contract: save -> load -> the next round's
        parent draws AND derived mutants are leaf-for-leaf identical."""
        import jax
        rt = _saturating_rt()
        plan = KnobPlan.from_runtime(rt)
        c1 = Corpus(plan, rng=np.random.default_rng(7))
        c1.track_evictions = True
        sk = np.arange(24, dtype=np.uint32).reshape(8, 3) % 5
        _observe_round(c1, plan, round_no=0, sketches=sk,
                       crashed=np.asarray([1, 0, 0, 0, 0, 0, 0, 1], bool))
        _observe_round(c1, plan, n=4, hash0=300, round_no=1)
        store = _mk_store(tmp_path, rt, plan)
        store.sync(c1, 0, rounds_done=2, dry=0,
                   op_hist=np.zeros(N_MUT_OPS, np.int64), wall_s=1.0)
        c2 = CorpusStore(str(tmp_path / "corpus"),
                         signature=store_signature(rt, plan)
                         ).load_corpus(plan, worker_id=0, rng_seed=7)
        assert [e["id"] for e in c2.entries] == [e["id"] for e in c1.entries]
        assert [e["energy"] for e in c2.entries] \
            == [e["energy"] for e in c1.entries]
        assert c2.coverage_keys() == c1.coverage_keys()
        assert c2.crash_codes == c1.crash_codes
        assert c2._slot_counts == c1._slot_counts
        assert (c2.consensus_sketch() == c1.consensus_sketch()).all()
        p1, i1 = c1.schedule(16)
        p2, i2 = c2.schedule(16)
        assert (i1 == i2).all()
        for k in p1:
            assert (np.asarray(p1[k]) == np.asarray(p2[k])).all(), k
        key = jax.random.PRNGKey(3)
        m1, h1, _ = plan.mutate(p1, key)
        m2, h2, _ = plan.mutate(p2, key)
        assert (np.asarray(h1) == np.asarray(h2)).all()
        for k in m1:
            assert (np.asarray(m1[k]) == np.asarray(m2[k])).all(), k

    def test_split_fuzz_equals_continuous(self, tmp_path):
        """The durability proof, in-process: interrupt a campaign at the
        round boundary, resume it, and the store ends byte-equal to an
        uninterrupted run (coverage keys, entry files, ids, knobs)."""
        kw = dict(max_steps=400, batch=16, dry_rounds=9, chunk=128)
        da, db = str(tmp_path / "a"), str(tmp_path / "b")
        fuzz(_saturating_rt(), max_rounds=2, corpus_dir=da, **kw)
        ra = fuzz(_saturating_rt(), max_rounds=4, corpus_dir=da, **kw)
        rb = fuzz(_saturating_rt(), max_rounds=4, corpus_dir=db, **kw)
        assert ra["rounds"] == 2 and ra["rounds_done_total"] == 4
        assert rb["rounds"] == 4
        assert ra["distinct_schedules"] == rb["distinct_schedules"]
        sa = CorpusStore(da, create=False)
        sb = CorpusStore(db, create=False)
        assert sa.coverage_keys() == sb.coverage_keys()
        assert sa.entry_names() == sb.entry_names()
        for n in sa.entry_names():
            ea, eb = sa.load_entry(n), sb.load_entry(n)
            assert ea["hash"] == eb["hash"] and ea["id"] == eb["id"]
            for k in ea["knobs"]:
                assert (np.asarray(ea["knobs"][k])
                        == np.asarray(eb["knobs"][k])).all(), (n, k)
        # a third call on the finished campaign is a durable no-op
        r3 = fuzz(_saturating_rt(), max_rounds=4, corpus_dir=da, **kw)
        assert r3["rounds"] == 0
        assert r3["distinct_schedules"] == ra["distinct_schedules"]


class TestStoreContracts:
    def _store(self, tmp_path):
        rt = _saturating_rt()
        plan = KnobPlan.from_runtime(rt)
        return _mk_store(tmp_path, rt, plan), rt, plan

    def test_version_mismatch_rejects(self, tmp_path):
        store, rt, plan = self._store(tmp_path)
        p = os.path.join(store.dir, "MANIFEST.json")
        man = json.load(open(p))
        man["version"] = CORPUS_VERSION + 1
        json.dump(man, open(p, "w"))
        with pytest.raises(StoreMismatch, match="version"):
            CorpusStore(store.dir, signature=store_signature(rt, plan))

    def test_signature_mismatch_rejects(self, tmp_path):
        store, rt, plan = self._store(tmp_path)
        other = _crashrich_rt()
        with pytest.raises(StoreMismatch, match="structurally different"):
            CorpusStore(store.dir, signature=store_signature(
                other, KnobPlan.from_runtime(other)))

    def test_not_a_corpus_dir_rejects(self, tmp_path):
        d = tmp_path / "x"
        d.mkdir()
        json.dump({"format": "something-else"},
                  open(d / "MANIFEST.json", "w"))
        with pytest.raises(StoreMismatch, match="not a corpus"):
            CorpusStore(str(d))

    def test_missing_dir_without_create(self, tmp_path):
        with pytest.raises(FileNotFoundError):
            CorpusStore(str(tmp_path / "nope"), create=False)

    def test_kill_mid_write_leaves_loadable_store(self, tmp_path):
        """The atomic-rename contract: a writer killed mid-write leaves
        only `.tmp-` siblings, which every reader ignores."""
        store, rt, plan = self._store(tmp_path)
        c = Corpus(plan, rng=np.random.default_rng(0))
        c.track_evictions = True
        _observe_round(c, plan)
        store.sync(c, 0, rounds_done=1, dry=0,
                   op_hist=np.zeros(N_MUT_OPS), wall_s=0.5)
        # simulate kills mid-write of every file class
        for rel in ("entries/w0000-000000000099.npz.tmp-777",
                    "state/w0000.json.tmp-777",
                    "buckets/deadbeef.json.tmp-777",
                    "MANIFEST.json.tmp-777"):
            with open(os.path.join(store.dir, rel), "w") as f:
                f.write("torn half-write garbage")
        s2 = CorpusStore(store.dir, signature=store_signature(rt, plan))
        c2 = s2.load_corpus(plan, worker_id=0, rng_seed=0)
        assert c2.coverage_keys() == c.coverage_keys()
        assert s2.bucket_keys() == []
        assert len(s2.entry_names()) == len(c.entries)

    def test_half_synced_own_entries_quarantined(self, tmp_path):
        """A kill DURING sync (entry files renamed, state json not yet):
        own-namespace entries at/past the persisted counter are ignored
        on load — the interrupted round re-runs and rewrites them —
        so the resumed corpus equals the uninterrupted one."""
        store, rt, plan = self._store(tmp_path)
        c = Corpus(plan, rng=np.random.default_rng(0))
        c.track_evictions = True
        _observe_round(c, plan)          # counters 0..7, next_counter=8
        store.sync(c, 0, rounds_done=1, dry=0,
                   op_hist=np.zeros(N_MUT_OPS), wall_s=0.5)
        orphan = dict(c.entries[0], id=(0 << 40) | 42, hash=999_999)
        store.write_entry(orphan)        # counter 42 >= next_counter 8
        c2 = CorpusStore(store.dir, signature=store_signature(rt, plan)
                         ).load_corpus(plan, worker_id=0, rng_seed=0)
        assert 999_999 not in c2.coverage_keys()
        assert all(e["id"] != orphan["id"] for e in c2.entries)

    def test_evicted_coverage_survives_resume(self, tmp_path):
        """Eviction never forgets: a hash admitted then evicted between
        syncs still blocks re-admission after a resume."""
        store, rt, plan = self._store(tmp_path)
        c = Corpus(plan, rng=np.random.default_rng(0), max_entries=4)
        c.track_evictions = True
        _observe_round(c, plan, n=8)     # 8 admissions into 4 slots
        assert len(c.entries) == 4 and len(c.coverage_keys()) == 8
        store.sync(c, 0, rounds_done=1, dry=0,
                   op_hist=np.zeros(N_MUT_OPS), wall_s=0.5)
        c2 = CorpusStore(store.dir, signature=store_signature(rt, plan)
                         ).load_corpus(plan, worker_id=0, rng_seed=0,
                                       max_entries=4)
        assert c2.coverage_keys() == c.coverage_keys()
        assert len(c2.entries) == 4


class TestWorkerNamespacing:
    def test_durable_fuzz_rejects_mismatched_corpus_namespace(self,
                                                              tmp_path):
        """A passed-in corpus minting ids outside fuzz's worker_id would
        persist a worker state pointing at entry files sync never
        writes — reject before touching the dir."""
        rt = _saturating_rt()
        corpus = Corpus(KnobPlan.from_runtime(rt),
                        rng=np.random.default_rng(0), worker_id=0)
        with pytest.raises(ValueError, match="worker_id"):
            fuzz(rt, max_steps=200, batch=8, max_rounds=1, chunk=64,
                 corpus=corpus, corpus_dir=str(tmp_path / "c"),
                 worker_id=3)

    def test_ids_collision_free_across_workers(self):
        rt = _saturating_rt()
        plan = KnobPlan.from_runtime(rt)
        c0 = Corpus(plan, rng=np.random.default_rng(0), worker_id=0)
        c3 = Corpus(plan, rng=np.random.default_rng(0), worker_id=3)
        _observe_round(c0, plan)
        _observe_round(c3, plan)
        ids0 = {e["id"] for e in c0.entries}
        ids3 = {e["id"] for e in c3.entries}
        assert not ids0 & ids3
        for eid in ids3:
            w, cnt = split_entry_id(eid)
            assert w == 3 and 0 <= cnt < 8
        # same-hash entries dedupe on merge, ids stay foreign
        merged = sum(c0.admit_foreign(e) for e in c3.entries)
        assert merged == 0               # identical hashes: nothing new

    def test_merge_foreign_rewards_stay_sound(self, tmp_path):
        """The r9 by-id reward contract under merge: a lane bred from a
        FOREIGN parent rewards exactly that merged entry — or nobody
        after its eviction — never a colliding local id."""
        rt = _saturating_rt()
        plan = KnobPlan.from_runtime(rt)
        store = _mk_store(tmp_path, rt, plan)
        c0 = Corpus(plan, rng=np.random.default_rng(0), worker_id=0)
        c0.track_evictions = True
        _observe_round(c0, plan, hash0=100)
        store.sync(c0, 0, rounds_done=1, dry=0,
                   op_hist=np.zeros(N_MUT_OPS), wall_s=0.1)
        c1 = store.load_corpus(plan, worker_id=1, rng_seed=1)
        assert len(c1.entries) == 8      # all of w0's merged in
        foreign = c1.entries[0]
        assert split_entry_id(foreign["id"])[0] == 0
        e0 = foreign["energy"]
        knobs = KnobPlan.stack([plan.base_knobs() for _ in range(2)])
        c1.observe(knobs, seeds=np.arange(2),
                   hashes_u64=np.asarray([900, 901], np.uint64),
                   crashed=np.zeros(2, bool), codes=np.zeros(2),
                   parent_ids=np.asarray([foreign["id"], -1]),
                   round_no=1)
        assert foreign["energy"] > e0 * 0.9  # rewarded (net of decay)
        new_ids = {e["id"] for e in c1.entries} - {e["id"] for e in
                                                   c0.entries}
        assert all(split_entry_id(i)[0] == 1 for i in new_ids)


class TestCrashBuckets:
    def _exp(self, toks, code=301, node=2, truncated=False,
             root_external=True):
        chain = [dict(step=i, now=i * 10, kind=k, node=n, src=s, tag=t,
                      parent=i - 1, lamport=i + 1)
                 for i, (k, n, s, t) in enumerate(toks)]
        return dict(chain=chain, truncated=truncated,
                    root_external=root_external, crashed=True,
                    crash_code=code, crash_node=node, lane=0, dropped=0)

    def test_bucket_files_and_repro_roundtrip(self, tmp_path):
        rt = _saturating_rt()
        plan = KnobPlan.from_runtime(rt)
        store = _mk_store(tmp_path, rt, plan)
        bk = CrashBuckets(store)
        toks = [(1, 0, 0, 5), (2, 1, 0, 7), (2, 0, 1, 7)]
        knobs = plan.base_knobs()
        key, opened = bk.observe(
            causal_fingerprint(self._exp(toks)), seed=11, knobs=knobs,
            round_no=0, worker_id=0,
            chain=self._exp(toks)["chain"])
        assert opened and store.bucket_keys() == [key]
        rec = store.load_bucket(key)
        assert rec["crash_code"] == 301
        assert len(rec["chain"]) == 3
        seed, kn = store.load_bucket_repro(key)
        assert seed == 11
        for k in knobs:
            assert (np.asarray(kn[k]) == np.asarray(knobs[k])).all(), k

    def test_wrap_truncated_rebucket_dedups(self, tmp_path):
        """One bug, observed complete and then wrap-truncated at two
        different depths: one bucket, three observations."""
        rt = _saturating_rt()
        plan = KnobPlan.from_runtime(rt)
        store = _mk_store(tmp_path, rt, plan)
        bk = CrashBuckets(store)
        toks = [(1, 0, 0, 5), (2, 1, 0, 7), (2, 0, 1, 7), (3, 1, 1, 2)]
        full = causal_fingerprint(self._exp(toks))
        cut3 = causal_fingerprint(self._exp(
            toks[1:], truncated=True, root_external=False))
        cut2 = causal_fingerprint(self._exp(
            toks[2:], truncated=True, root_external=False))
        k0, o0 = bk.observe(full, seed=1, knobs=plan.base_knobs(),
                            round_no=0, worker_id=0)
        k1, o1 = bk.observe(cut3, seed=2, knobs=plan.base_knobs(),
                            round_no=1, worker_id=1)
        k2, o2 = bk.observe(cut2, seed=3, knobs=plan.base_knobs(),
                            round_no=2, worker_id=0)
        assert o0 and not o1 and not o2
        assert k0 == k1 == k2
        assert len(store.bucket_keys()) == 1
        m = merged_buckets(store)
        assert len(m) == 1 and m[0]["observations"] == 3

    def test_merged_buckets_repairs_concurrent_open_race(self, tmp_path):
        """Two workers opening buckets for one bug at different wrap
        depths in the same instant (no refresh between): the read-side
        merge folds them."""
        rt = _saturating_rt()
        plan = KnobPlan.from_runtime(rt)
        store = _mk_store(tmp_path, rt, plan)
        toks = [(1, 0, 0, 5), (2, 1, 0, 7), (2, 0, 1, 7)]
        full = causal_fingerprint(self._exp(toks))
        cut = causal_fingerprint(self._exp(
            toks[1:], truncated=True, root_external=False))
        # two writers, neither saw the other's bucket before writing
        CrashBuckets(store).observe(full, seed=1, knobs=None,
                                    round_no=0, worker_id=0)
        rec = dict(key=cut["key"], fingerprint=cut, crash_code=301,
                   crash_node=2, chain=[],
                   repro=dict(seed=2, round=0, worker_id=1))
        store.write_bucket(cut["key"], rec)
        assert len(store.bucket_keys()) == 2
        m = merged_buckets(store)
        assert len(m) == 1
        assert set(m[0]["members"]) == {full["key"], cut["key"]}
        # deepest chain is canonical
        assert m[0]["key"] == full["key"]


class TestCampaignDedup:
    def test_two_workers_share_buckets(self, tmp_path):
        """Cross-process dedup, deterministically: worker 1 replays
        worker 0's seed space (base_seed offset cancels the worker
        stride), so both observe the SAME crashes — one bucket set, two
        observations each, and zero duplicate corpus entries."""
        d = str(tmp_path / "camp")
        kw = dict(max_steps=4096, batch=24, max_rounds=1, dry_rounds=3,
                  chunk=512)
        r0 = fuzz(_crashrich_rt(), corpus_dir=d, worker_id=0, **kw)
        assert r0["crashes"] > 0 and r0["buckets_total"] >= 1
        r1 = fuzz(_crashrich_rt(), corpus_dir=d, worker_id=1,
                  base_seed=-WORKER_SEED_STRIDE, **kw)
        store = CorpusStore(d, create=False)
        # same seeds -> same coverage: worker 1 admits nothing new
        assert {split_entry_id(store.load_entry(n)["id"])[0]
                for n in store.entry_names()} == {0}
        assert r1["distinct_schedules"] == r0["distinct_schedules"]
        # ... and the same crashes: same buckets, doubled observations
        assert r1["buckets_total"] == r0["buckets_total"]
        assert not r1["buckets_opened"]
        log = store.bucket_log()
        assert {li["worker_id"] for li in log} == {0, 1}
        per_bucket = {}
        for li in log:
            per_bucket.setdefault(li["bucket"], []).append(li["worker_id"])
        for key, ws in per_bucket.items():
            assert sorted(ws) == [0, 1], (key, ws)
        rep = campaign_report(d)
        assert rep["buckets_merged"] == len(store.bucket_keys())


@pytest.mark.slow
class TestCampaignProcesses:
    """The real multi-process contracts (subprocess workers pay a jax
    import + compile each; scripts/ci.sh fast covers the same ground
    through `bench.py --campaign-smoke`)."""

    def _env(self):
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        env.pop("PALLAS_AXON_POOL_IPS", None)
        env.setdefault("JAX_COMPILATION_CACHE_DIR", os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            ".jax_cache"))
        return env

    def _cmd(self, d, worker, rounds):
        return worker_cmd(
            d, worker, "bench:_make_crashrich_runtime",
            factory_kwargs=dict(kind="wal_kv", trace_cap=64,
                                sketch_slots=4),
            max_steps=4096, batch=16, max_rounds=rounds, chunk=512)

    def test_sigkill_resume_equals_uninterrupted(self, tmp_path):
        dk, dc = str(tmp_path / "kill"), str(tmp_path / "ctrl")
        p = subprocess.Popen(self._cmd(dk, 0, 3), env=self._env(),
                             stdout=subprocess.DEVNULL)
        state = os.path.join(dk, "state", "w0000.json")
        deadline = time.time() + 300
        while time.time() < deadline and not os.path.exists(state):
            assert p.poll() is None, "worker died before first sync"
            time.sleep(0.2)
        assert os.path.exists(state), "no sync within 300s"
        p.send_signal(signal.SIGKILL)
        p.wait()
        assert json.load(open(state))["rounds_done"] < 3
        for d in (dk, dc):
            subprocess.run(self._cmd(d, 0, 3), env=self._env(),
                           check=True, stdout=subprocess.DEVNULL)
        sk = CorpusStore(dk, create=False)
        sc = CorpusStore(dc, create=False)
        assert sk.coverage_keys() == sc.coverage_keys()
        assert sk.entry_names() == sc.entry_names()
        assert sk.bucket_keys() == sc.bucket_keys()

    def test_replay_bucket_reproduces_crash(self, tmp_path):
        d = str(tmp_path / "camp")
        res = fuzz(_crashrich_rt(), max_steps=4096, batch=24,
                   max_rounds=1, dry_rounds=3, chunk=512, corpus_dir=d,
                   worker_id=0)
        assert res["buckets_total"] >= 1
        store = CorpusStore(d, create=False)
        key = store.bucket_keys()[0]
        crashed, code, exp = replay_bucket(_crashrich_rt(), d, key,
                                           max_steps=4096, chunk=512)
        assert crashed
        assert code == store.load_bucket(key)["crash_code"]
        assert exp is not None and exp["chain"]
