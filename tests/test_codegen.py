"""Schema codegen (net/codegen.py — the madsim-tonic-build analog):
generate a module from a proto3-subset schema, implement the handler
hooks, and drive the generated client stubs through a live simulation."""

import jax.numpy as jnp
import numpy as np
import pytest

# back in tier-1 (r8 durations re-triage): the file was `slow` because it
# compiles many distinct step programs per run; with the shared
# ProgramCache + persistent compile cache live it measures ~20s warm /
# well inside tier-1's headroom cold (ROADMAP wall-clock item)

from madsim_tpu import Program, Runtime, SimConfig, ms, sec
from madsim_tpu.harness.simtest import run_seeds
from madsim_tpu.net import codegen, rpc, stream, streaming

SCHEMA = """
syntax = "proto3";
// a counter with a float average — exercises the bitcast path
message AddReq { int32 delta = 1; }
message AddRsp { int32 total = 1; float mean = 2; }
message GetReq { }
message GetRsp { int32 total = 1; }

service Counter {
  rpc Add(AddReq) returns (AddRsp);
  rpc Get(GetReq) returns (GetRsp);
}
"""

T_RETRY = 1


def _load(schema=SCHEMA):
    src = codegen.generate(schema)
    mod = {}
    exec(compile(src, "<generated>", "exec"), mod)
    return mod


class TestParseAndGenerate:
    def test_parse_shape(self):
        messages, services = codegen.parse(SCHEMA)
        assert messages["AddRsp"] == [("int32", "total"), ("float", "mean")]
        assert messages["GetReq"] == []
        (meth, req, req_s, rsp, rsp_s), *_ = services["Counter"]
        assert (meth, req, rsp) == ("Add", "AddReq", "AddRsp")
        assert not req_s and not rsp_s

    def test_repeated_and_unknown_types_rejected(self):
        with pytest.raises(AssertionError, match="repeated"):
            codegen.parse("message M { repeated int32 xs = 1; }")
        with pytest.raises(AssertionError, match="unsupported"):
            codegen.parse("message M { string s = 1; }")

    def test_nested_constructs_rejected_not_dropped(self):
        # valid proto3 the subset does NOT support must assert with a
        # message, never silently drop the block (the [^{}]* regex trap)
        with pytest.raises(AssertionError, match="nested messages"):
            codegen.parse(
                "message O { message I { int32 x = 1; } int32 y = 1; }")
        with pytest.raises(AssertionError, match="options blocks"):
            codegen.parse(
                "message A { }\n"
                "service S { rpc F(A) returns (A) {} }")

    def test_float_roundtrip_via_layout(self):
        mod = _load()
        words = mod["pack_add_rsp"](total=7, mean=2.5)
        d = mod["unpack_add_rsp"](jnp.stack(words))
        assert int(d["total"]) == 7
        assert float(d["mean"]) == 2.5

    def test_stream_rpc_generates_stream_stub(self):
        mod = _load(SCHEMA.replace(
            "rpc Get(GetReq) returns (GetRsp);",
            "rpc Watch(GetReq) returns (stream GetRsp);"))
        base = mod["CounterBase"]
        assert hasattr(base.Watch, "_rpc_stream_tag")
        # no unary client stub for a streaming method
        assert "counter_watch" not in mod


MOD = _load()


class CounterImpl(MOD["CounterBase"]):
    def handle_add(self, ctx, st, req, when):
        st["total"] = st["total"] + jnp.where(when, req["delta"], 0)
        st["n"] = st["n"] + jnp.asarray(when, jnp.int32)
        mean = st["total"].astype(jnp.float32) / jnp.maximum(st["n"], 1)
        return dict(total=st["total"], mean=mean)

    def handle_get(self, ctx, st, req, when):
        return dict(total=st["total"])


class GenDriver(Program):
    """add(5) x3 then get(); expect total 15 and mean 5.0."""

    def init(self, ctx):
        st = dict(ctx.state)
        st["call_id"] = rpc.new_call_id(ctx)
        MOD["counter_add"](ctx, 0, st["call_id"], retry_timer_tag=T_RETRY,
                           timeout=ms(40), delta=5)
        ctx.state = st

    def _issue(self, ctx, st, step, call_id, when):
        is_get = step >= 3
        MOD["counter_add"](ctx, 0, call_id, retry_timer_tag=T_RETRY,
                           timeout=ms(40), delta=5, when=when & ~is_get)
        MOD["counter_get"](ctx, 0, call_id, retry_timer_tag=T_RETRY,
                           timeout=ms(40), when=when & is_get)

    def on_timer(self, ctx, tag, payload):
        st = dict(ctx.state)
        retry = ((tag == T_RETRY) & (payload[0] == st["call_id"])
                 & (st["step"] < 4))
        self._issue(ctx, st, st["step"], st["call_id"], retry)
        ctx.state = st

    def on_message(self, ctx, src, tag, payload):
        st = dict(ctx.state)
        hit = rpc.is_reply(tag) & rpc.matches(payload, st["call_id"])
        is_add = tag == rpc.reply_tag(MOD["CounterBase"].Add.tag)
        add_rsp = MOD["unpack_add_rsp"](payload[1:])
        get_rsp = MOD["unpack_get_rsp"](payload[1:])
        # the third add's reply carries total 15, mean exactly 5.0
        third = hit & is_add & (st["step"] == 2)
        ctx.crash_if(third & (add_rsp["total"] != 15), 401)
        ctx.crash_if(third & (add_rsp["mean"] != 5.0), 402)
        done = hit & ~is_add
        ctx.crash_if(done & (get_rsp["total"] != 15), 403)
        st["step"] = st["step"] + hit
        new_id = rpc.new_call_id(ctx)
        self._issue(ctx, st, st["step"], new_id, hit & ~done)
        st["call_id"] = jnp.where(hit & ~done, new_id, st["call_id"])
        ctx.halt_if(done & (ctx.node == 1))
        ctx.state = st


STREAM_SCHEMA = """
message StartReq { int32 n = 1; }
message TickRsp { int32 v = 1; }
service Ticker { rpc Watch(StartReq) returns (stream TickRsp); }
"""
SMOD = _load(STREAM_SCHEMA)
N_ITEMS = 3
T_TICK = 3
CRASH_BAD_ITEM, CRASH_BAD_COUNT = 501, 502


class TickerImpl(SMOD["TickerBase"]):
    """Server half of the generated STREAMING method: the @rpc_stream
    wrapper dispatches every delivered frame here; on the opening call
    we stream N_ITEMS values and the StreamEnd marker."""

    def handle_watch(self, ctx, st, src, kind, call_id, body, when):
        opened = when & (kind == streaming.K_CALL)
        tag = SMOD["TickerBase"].Watch.tag
        for j in range(N_ITEMS):
            streaming.push(ctx, st, src, call_id, [100 + j], method=tag,
                           when=opened)
        streaming.finish(ctx, st, src, call_id, method=tag, when=opened)

    # symmetric reliability: the server retransmits its unacked frames
    # too, so the test would survive loss, not just the default
    # lossless fabric
    def init(self, ctx):
        ctx.set_timer(ms(20), T_TICK)

    def on_timer(self, ctx, tag, payload):
        st = dict(ctx.state)
        is_tick = tag == T_TICK
        streaming.tick(ctx, st, [1], when=is_tick)
        ctx.set_timer(ms(20), T_TICK, when=is_tick)
        ctx.state = st


class WatchClient(Program):
    """Opens the generated method by tag, verifies the ordered item
    values in-model, halts on StreamEnd."""

    def init(self, ctx):
        st = dict(ctx.state)
        st["cid"] = rpc.new_call_id(ctx)
        streaming.open_call(ctx, st, 0, SMOD["TickerBase"].Watch.tag,
                            st["cid"], [N_ITEMS])
        ctx.set_timer(ms(20), T_TICK)
        ctx.state = st

    def on_timer(self, ctx, tag, payload):
        st = dict(ctx.state)
        is_tick = tag == T_TICK
        streaming.tick(ctx, st, [0], when=is_tick)
        ctx.set_timer(ms(20), T_TICK, when=is_tick)
        ctx.state = st

    def on_message(self, ctx, src, tag, payload):
        st = dict(ctx.state)
        kinds, methods, cids, bodies, mask = streaming.on_stream(
            ctx, st, src, tag, payload)
        for i in stream.delivered_slots(mask):
            mine = mask[i] & (cids[i] == st["cid"])
            item = mine & (kinds[i] == streaming.K_ITEM)
            # exactly-once in-order fabric: values must arrive in order
            ctx.crash_if(item & (bodies[i][0] != 100 + st["got"]),
                         CRASH_BAD_ITEM)
            st["got"] = st["got"] + item
            done = mine & (kinds[i] == streaming.K_END)
            ctx.crash_if(done & (st["got"] != N_ITEMS), CRASH_BAD_COUNT)
            ctx.halt_if(done)
        ctx.state = st


class TestGeneratedStreamingEndToEnd:
    def test_generated_server_streaming(self):
        z = jnp.asarray(0, jnp.int32)
        spec = dict(**streaming.streaming_state(2, window=6, body_words=1),
                    cid=z, got=z)
        cfg = SimConfig(n_nodes=2, time_limit=sec(20))
        rt = Runtime(cfg, [TickerImpl(), WatchClient()], spec,
                     node_prog=[0, 1])
        state = run_seeds(rt, np.arange(8), max_steps=10_000)
        assert (np.asarray(state.node_state["got"])[:, 1] == N_ITEMS).all()
        assert rt.check_determinism(seed=4, max_steps=10_000)


class TestGeneratedServiceEndToEnd:
    def test_generated_flow(self):
        z = jnp.asarray(0, jnp.int32)
        spec = dict(total=z, n=z, call_id=z, step=z)
        cfg = SimConfig(n_nodes=2, time_limit=sec(20))
        rt = Runtime(cfg, [CounterImpl(), GenDriver()], spec,
                     node_prog=[0, 1])
        state = run_seeds(rt, np.arange(8), max_steps=10_000)
        assert (np.asarray(state.node_state["total"])[:, 0] == 15).all()

    def test_cli(self, tmp_path):
        schema = tmp_path / "svc.proto"
        schema.write_text(SCHEMA)
        out = tmp_path / "svc_pb.py"
        codegen.main([str(schema), "-o", str(out)])
        assert "class CounterBase(Service)" in out.read_text()
