"""Stream layer (simulated-TCP analog): exactly-once in-order delivery over
a network that loses and reorders — the property tcp/mod.rs:57-218 tests,
including recovery through a clogged window (stream.rs:185-209)."""

import jax.numpy as jnp
import numpy as np

from madsim_tpu import Program, Runtime, Scenario, SimConfig, NetConfig, ms, sec
from madsim_tpu.harness.simtest import run_seeds
from madsim_tpu.net import stream

T_PUMP = 1       # sender: try to push more data
T_RETX = 2       # sender: retransmission tick
K = 24           # values to stream
W = 4


def spec(n):
    z = jnp.asarray(0, jnp.int32)
    return dict(
        pushed=z, got=z,
        rx_log=jnp.full((K,), -1, jnp.int32),
        **stream.stream_state(n, window=W),
    )


class Pipe(Program):
    """Node 0 streams 0..K-1 to node 1; node 1 logs deliveries in order."""

    def init(self, ctx):
        ctx.set_timer(0, T_PUMP, when=ctx.node == 0)
        ctx.set_timer(ms(15), T_RETX, when=ctx.node == 0)

    def on_timer(self, ctx, tag, payload):
        st = dict(ctx.state)
        is_pump = (tag == T_PUMP) & (ctx.node == 0)
        for _ in range(2):  # push up to 2 values per tick
            ok = stream.send(ctx, st, 1, st["pushed"],
                             when=is_pump & (st["pushed"] < K))
            st["pushed"] = st["pushed"] + ok
        ctx.set_timer(ms(5), T_PUMP, when=is_pump & (st["pushed"] < K))
        is_retx = (tag == T_RETX) & (ctx.node == 0)
        stream.retransmit(ctx, st, 1, when=is_retx)
        ctx.set_timer(ms(15), T_RETX, when=is_retx)
        ctx.state = st

    def on_message(self, ctx, src, tag, payload):
        st = dict(ctx.state)
        vals, mask = stream.on_message(ctx, st, src, tag, payload)
        # receiver: append the in-order batch to the log
        for i in range(W):
            idx = jnp.clip(st["got"], 0, K - 1)
            take = mask[i] & (ctx.node == 1) & (st["got"] < K)
            st["rx_log"] = st["rx_log"].at[idx].set(
                jnp.where(take, vals[i], st["rx_log"][idx]))
            st["got"] = st["got"] + take
        ctx.halt_if((ctx.node == 1) & (st["got"] >= K))
        ctx.state = st


def _run(loss, seeds=8, time_limit=sec(30)):
    cfg = SimConfig(n_nodes=2, event_capacity=128, time_limit=time_limit,
                    net=NetConfig(packet_loss_rate=loss,
                                  send_latency_min=ms(1),
                                  send_latency_max=ms(30)))  # heavy reorder
    rt = Runtime(cfg, [Pipe()], spec(2))
    return run_seeds(rt, np.arange(seeds), max_steps=60_000)


class TestStream:
    def test_in_order_exactly_once_clean(self):
        state = _run(loss=0.0)
        logs = np.asarray(state.node_state["rx_log"])[:, 1]
        assert (logs == np.arange(K)).all()

    def test_in_order_exactly_once_lossy(self):
        # 30% loss + 30x latency jitter: retransmits + dup-acks + reorder
        state = _run(loss=0.3)
        logs = np.asarray(state.node_state["rx_log"])[:, 1]
        assert (logs == np.arange(K)).all()
        assert int(np.asarray(state.msg_dropped).sum()) > 0

    def test_survives_temporary_clog(self):
        # clog the link mid-stream; retransmission recovers after heal
        # (the tcp disconnect-and-recovery test shape, tcp/mod.rs:99-172)
        cfg = SimConfig(n_nodes=2, event_capacity=128, time_limit=sec(30),
                        net=NetConfig(send_latency_min=ms(1),
                                      send_latency_max=ms(10)))
        sc = Scenario()
        sc.at(ms(20)).clog_link(0, 1)
        sc.at(ms(800)).unclog_link(0, 1)
        rt = Runtime(cfg, [Pipe()], spec(2), scenario=sc)
        state = run_seeds(rt, np.arange(8), max_steps=60_000)
        logs = np.asarray(state.node_state["rx_log"])[:, 1]
        assert (logs == np.arange(K)).all()
        assert (np.asarray(state.now) > ms(800)).all()
