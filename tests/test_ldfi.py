"""Lineage-driven fault injection (r22, DESIGN §23): support extraction
over synthetic happens-before graphs, the hitting-set pool, knob-plane
synthesis bounds, and the fuzz-arm contracts (additive store schema,
zero retraces on warm caches)."""

import json
import os

import numpy as np
import pytest

from madsim_tpu.core import types as T
from madsim_tpu.harness.witness import success_witness
from madsim_tpu.obs.causal import walk_lineage
from madsim_tpu.obs.support import support_from_records
from madsim_tpu.search.ldfi import SupportPool, synthesize
from madsim_tpu.search.mutate import KnobPlan


def _recs(rows):
    """Synthetic ring_records dict: one row per record, ring order."""
    keys = ("step", "now", "kind", "node", "src", "tag", "parent",
            "lamport")
    return {k: np.asarray([r.get(k, 0) for r in rows], np.int64)
            for k in keys}


def _msg(step, parent, src, dst, now, tag=1):
    return dict(step=step, now=now, kind=T.EV_MSG, node=dst, src=src,
                tag=tag, parent=parent)


def _timer(step, parent, node, now, tag=2):
    return dict(step=step, now=now, kind=T.EV_TIMER, node=node, src=-1,
                tag=tag, parent=parent)


class TestWalkLineage:
    def test_chain_walks_to_external_root(self):
        recs = _recs([_msg(0, -1, 0, 1, 10), _msg(1, 0, 1, 0, 20),
                      _msg(2, 1, 0, 1, 30), _msg(3, 2, 1, 0, 40)])
        walk = walk_lineage(recs)
        assert [c["step"] for c in walk["chain"]] == [0, 1, 2, 3]
        assert walk["root_external"] and not walk["truncated"]

    def test_diamond_follows_single_parent_path(self):
        # A -> {B, C}, C -> D: the lineage walk from D is D, C, A —
        # B happened, but D did not causally depend on it
        recs = _recs([_msg(0, -1, 0, 1, 10),   # A
                      _msg(1, 0, 1, 2, 20),    # B (off-path)
                      _msg(2, 0, 1, 3, 25),    # C
                      _msg(3, 2, 3, 0, 40)])   # D
        walk = walk_lineage(recs, from_step=3)
        assert [c["step"] for c in walk["chain"]] == [0, 2, 3]
        assert walk["root_external"]

    def test_wrap_truncation_is_honest(self):
        # the oldest surviving record's parent was overwritten by wrap:
        # the walk stops there and says so (r11 suffix contract)
        recs = _recs([_msg(5, 2, 0, 1, 50), _msg(6, 5, 1, 0, 60),
                      _msg(7, 6, 0, 1, 70)])
        walk = walk_lineage(recs, from_step=7)
        assert [c["step"] for c in walk["chain"]] == [5, 6, 7]
        assert walk["truncated"] and not walk["root_external"]

    def test_bad_from_step_and_empty_ring_raise(self):
        recs = _recs([_msg(0, -1, 0, 1, 10)])
        with pytest.raises(ValueError):
            walk_lineage(recs, from_step=99)
        with pytest.raises(ValueError):
            walk_lineage(_recs([]))


class TestWitnessAndSupport:
    def test_default_witness_is_last_dispatch(self):
        recs = _recs([_msg(0, -1, 0, 1, 10), _msg(1, 0, 1, 0, 20)])
        sup = support_from_records(recs)
        assert sup["witness_step"] == 1
        assert sup["msg_edges"] == [(0, 1, 10), (1, 0, 20)]
        assert sup["depth"] == 2 and sup["root_external"]

    def test_witness_filters_kind_tag_node(self):
        recs = _recs([_msg(0, -1, 0, 1, 10, tag=7),
                      _timer(1, 0, 1, 30, tag=9),
                      _msg(2, 1, 1, 2, 40, tag=7),
                      _msg(3, 2, 2, 1, 50, tag=8)])
        w = success_witness(kinds=(T.EV_MSG,), tags=(7,), node=2)
        sup = support_from_records(recs, w)
        # last match is step 2 (the tag-8 record fails the tag filter)
        assert sup["witness_step"] == 2
        assert sup["msg_edges"] == [(0, 1, 10), (1, 2, 40)]
        assert sup["timer_edges"] == [(1, 30)]

    def test_unmatched_witness_returns_none(self):
        recs = _recs([_msg(0, -1, 0, 1, 10)])
        assert support_from_records(
            recs, success_witness(kinds=(T.EV_SUPER,))) is None
        assert support_from_records(_recs([])) is None

    def test_wrap_truncated_flag_propagates(self):
        recs = _recs([_msg(5, 2, 0, 1, 50), _msg(6, 5, 1, 0, 60)])
        sup = support_from_records(recs)
        assert sup["truncated"] and not sup["root_external"]
        pool = SupportPool()
        assert pool.add(sup)
        assert pool.truncated == 1


class TestSupportPool:
    def _sup(self, msg=(), timer=(), truncated=False):
        return dict(msg_edges=list(msg), timer_edges=list(timer),
                    depth=len(msg) + len(timer), witness_step=0,
                    truncated=truncated, root_external=not truncated)

    def test_external_sends_are_not_candidates(self):
        pool = SupportPool()
        # only an external (src < 0) edge: nothing cuttable
        assert not pool.add(self._sup(msg=[(-1, 2, 10)]))
        assert len(pool) == 0

    def test_ranked_is_a_greedy_hitting_set(self):
        pool = SupportPool()
        a, b, c, d = (0, 1, 5), (1, 2, 6), (2, 0, 7), (0, 2, 8)
        pool.add(self._sup(msg=[a, b]))
        pool.add(self._sup(msg=[a, c]))
        pool.add(self._sup(msg=[d]))
        ranked = pool.ranked(8)
        keys = [r["key"] for r in ranked]
        # a hits 2 uncovered supports -> first; d covers the last
        # uncovered one -> second; b/c pad by (-hits, key) order
        assert keys[0] == ("msg", 0, 1)
        assert keys[1] == ("msg", 0, 2)
        assert set(keys[2:]) == {("msg", 1, 2), ("msg", 2, 0)}
        assert ranked[0]["hits"] == 2 and ranked[0]["times"] == [5, 5]

    def test_merge_pools_across_shards(self):
        p1, p2 = SupportPool(), SupportPool()
        p1.add(self._sup(msg=[(0, 1, 5)]))
        p2.add(self._sup(msg=[(0, 1, 9)], truncated=True))
        p2.add(self._sup(timer=[(2, 7)]))
        p1.merge(p2)
        assert len(p1) == 3 and p1.truncated == 1
        assert p1.times[("msg", 0, 1)] == [5, 9]
        assert ("timer", 2, -1) in p1.times


def _echo_ldfi_rt(trace_cap=64, target=3):
    """rpc_echo under a 4-family chaos script: every synthesis-relevant
    fault op (oneway / reset / skew / dup) has a mutable row."""
    from madsim_tpu import SimConfig, sec, ms
    from madsim_tpu.models.rpc_echo import make_echo_runtime
    from madsim_tpu.runtime import chaos
    from madsim_tpu.runtime.scenario import Scenario
    sc = Scenario()
    sc = chaos.asymmetric_partition(ms(400), [1], ms(300), sc=sc)
    sc = chaos.conn_reset_storm(rounds=2, first=ms(300), period=ms(450),
                                node=2, sc=sc)
    sc = chaos.clock_drift(ms(200), 128, node=1, until=ms(900), sc=sc)
    sc = chaos.retransmit_storm(ms(250), 0.3, ms(800), node=1, sc=sc)
    cfg = SimConfig(n_nodes=4, event_capacity=256, time_limit=sec(20),
                    trace_cap=trace_cap)
    return make_echo_runtime(n_nodes=4, target=target, cfg=cfg,
                             scenario=sc)


class TestSynthesize:
    def _pool(self):
        pool = SupportPool()
        pool.add(dict(msg_edges=[(1, 0, 5000), (0, 1, 9000)],
                      timer_edges=[(2, 4000)], depth=3, witness_step=9,
                      truncated=False, root_external=True))
        pool.add(dict(msg_edges=[(1, 0, 7000)], timer_edges=[],
                      depth=1, witness_step=5, truncated=False,
                      root_external=True))
        return pool

    def test_vectors_stay_on_the_knob_plane(self):
        plan = KnobPlan.from_runtime(_echo_ldfi_rt(), dup_slots=2)
        vecs = synthesize(plan, self._pool(), 6)
        assert vecs
        base = plan.base_knobs()
        for kn in vecs:
            changed = [r for r in range(plan.R)
                       if any(kn[f][r] != base[f][r]
                              for f in ("row_time", "row_node", "row_val",
                                        "row_flag", "row_on"))]
            assert changed
            for r in changed:
                assert plan.time_ok[r]
                node = int(kn["row_node"][r])
                assert node == T.NODE_RANDOM or (
                    0 <= node < plan.N and plan.pool_ok[r, node + 1])
                assert plan.val_lo[r] <= int(kn["row_val"][r]) \
                    <= plan.val_hi[r]
                assert bool(kn["row_on"][r])

    def test_oneway_direction_tracks_group_mask(self):
        # scenario group A = {1}: an edge 1 -> 0 leaves the group, so
        # the cut keeps direction 0 (A's outbound sends vanish); the
        # row fires `lead` before the observed instant
        plan = KnobPlan.from_runtime(_echo_ldfi_rt(), dup_slots=2)
        pool = SupportPool()
        pool.add(dict(msg_edges=[(1, 0, 5000)], timer_edges=[], depth=1,
                      witness_step=3, truncated=False,
                      root_external=True))
        vecs = synthesize(plan, pool, 1, max_cuts=1, lead=1000)
        assert len(vecs) == 1
        ops = np.asarray(plan.base["op"])
        rows = [r for r in range(plan.R)
                if vecs[0]["row_time"][r] == 4000
                and ops[r] == T.OP_PARTITION_ONEWAY]
        assert rows and int(vecs[0]["row_flag"][rows[0]]) == 0

    def test_oneway_cut_drags_its_heal_with_duration(self):
        # the scenario's asymmetric_partition cuts at 400ms and heals
        # at 700ms; re-aiming the cut to t=4000 must re-aim the paired
        # OP_HEAL to 4000 + the base 300ms delta — a permanent cut
        # makes protocols abort cleanly instead of exposing torn state
        plan = KnobPlan.from_runtime(_echo_ldfi_rt(), dup_slots=2)
        pool = SupportPool()
        pool.add(dict(msg_edges=[(1, 0, 5000)], timer_edges=[], depth=1,
                      witness_step=3, truncated=False,
                      root_external=True))
        vecs = synthesize(plan, pool, 1, max_cuts=1, lead=1000)
        assert len(vecs) == 1
        ops = np.asarray(plan.base["op"])
        times = np.asarray(plan.base["time"])
        heal = [r for r in range(plan.R) if ops[r] == T.OP_HEAL]
        assert len(heal) == 1
        cut = [r for r in range(plan.R)
               if ops[r] == T.OP_PARTITION_ONEWAY
               and vecs[0]["row_time"][r] == 4000]
        assert cut
        delta = int(times[heal[0]]) - int(times[cut[0]])
        assert int(vecs[0]["row_time"][heal[0]]) == 4000 + delta
        assert bool(vecs[0]["row_on"][heal[0]])

    def test_synthesize_pins_the_support_seed(self):
        # edge instants are seed-specific: vectors carry the green seed
        # their first cut was timed against so the driver can replay
        # THAT trajectory with the cut injected
        plan = KnobPlan.from_runtime(_echo_ldfi_rt(), dup_slots=2)
        pool = SupportPool()
        pool.add(dict(msg_edges=[(1, 0, 5000)], timer_edges=[], depth=1,
                      witness_step=3, truncated=False,
                      root_external=True), seed=42)
        vecs, seeds = synthesize(plan, pool, 2, max_cuts=1,
                                 with_seeds=True)
        assert vecs and all(s == 42 for s in seeds)
        # an un-seeded pool yields None pins (driver keeps fresh seeds)
        anon = SupportPool()
        anon.add(dict(msg_edges=[(1, 0, 5000)], timer_edges=[], depth=1,
                      witness_step=3, truncated=False,
                      root_external=True))
        vecs2, seeds2 = synthesize(plan, anon, 1, max_cuts=1,
                                   with_seeds=True)
        assert vecs2 and seeds2 == [None]
        # merge keeps first-seen pins (the sharded pool contract)
        pool.merge(anon)
        assert pool.seed_of[(("msg", 1, 0), 5000)] == 42

    def test_deterministic_and_empty_cases(self):
        plan = KnobPlan.from_runtime(_echo_ldfi_rt(), dup_slots=2)
        a = synthesize(plan, self._pool(), 4)
        b = synthesize(plan, self._pool(), 4)
        assert len(a) == len(b)
        for ka, kb in zip(a, b):
            for f in ka:
                assert (np.asarray(ka[f]) == np.asarray(kb[f])).all(), f
        assert synthesize(plan, SupportPool(), 4) == []
        # a plan with no fault rows cannot express any cut
        from madsim_tpu import SimConfig, sec
        from madsim_tpu.models.rpc_echo import make_echo_runtime
        bare = make_echo_runtime(
            n_nodes=4, target=3,
            cfg=SimConfig(n_nodes=4, event_capacity=256,
                          time_limit=sec(20), trace_cap=64))
        assert synthesize(KnobPlan.from_runtime(bare, dup_slots=2),
                          self._pool(), 4) == []


class TestFuzzArmContracts:
    def test_ldfi_none_store_schema_untouched(self, tmp_path):
        # the additive contract: without ldfi, no entry carries an
        # origin member and no worker state carries targeted_yield —
        # the store bytes are the pre-r22 schema exactly
        from madsim_tpu.search import fuzz
        from madsim_tpu.service.store import CorpusStore
        rt = _echo_ldfi_rt()
        fuzz(rt, max_steps=3000, batch=12, max_rounds=2, dry_rounds=3,
             chunk=256, corpus_dir=str(tmp_path))
        store = CorpusStore(str(tmp_path), create=False)
        names = store.entry_names()
        assert names
        for name in names:
            assert "origin" not in store.load_entry(name)
        sdir = os.path.join(str(tmp_path), "state")
        for f in os.listdir(sdir):
            with open(os.path.join(sdir, f)) as fh:
                assert "targeted_yield" not in json.load(fh)

    def test_targeted_arm_accounting_and_entry_origin(self, tmp_path):
        from madsim_tpu.search import LdfiConfig, fuzz
        from madsim_tpu.service.store import CorpusStore
        rt = _echo_ldfi_rt()
        res = fuzz(rt, max_steps=3000, batch=12, max_rounds=3,
                   dry_rounds=4, chunk=256, corpus_dir=str(tmp_path),
                   ldfi=LdfiConfig(lanes=4, frac=0.25))
        t = res["targeted"]
        assert t["supports"] >= 1
        assert t["lanes_run"] >= 1
        assert 0 <= t["admitted"] <= t["lanes_run"]
        store = CorpusStore(str(tmp_path), create=False)
        origins = [store.load_entry(n).get("origin")
                   for n in store.entry_names()]
        assert origins.count("targeted") == t["admitted"]
        # the cumulative admission ledger survives in the worker state
        sdir = os.path.join(str(tmp_path), "state")
        states = [json.load(open(os.path.join(sdir, f)))
                  for f in os.listdir(sdir)]
        assert any(s.get("targeted_yield") == t["admitted"]
                   for s in states)

    def test_ldfi_needs_the_flight_recorder(self):
        from madsim_tpu.search import LdfiConfig, fuzz
        with pytest.raises(ValueError, match="flight recorder"):
            fuzz(_echo_ldfi_rt(trace_cap=0), max_steps=500, batch=8,
                 max_rounds=1, dry_rounds=2, chunk=256,
                 ldfi=LdfiConfig())

    def test_warm_targeted_campaign_never_recompiles(self):
        # the acceptance gate: a targeted round is mask + host splice +
        # the SAME module-level mutate/apply/run programs — a warm-cache
        # ldfi campaign adds ZERO compile traces
        from madsim_tpu.compile.cache import COMPILE_LOG
        from madsim_tpu.search import LdfiConfig, fuzz
        kw = dict(max_steps=3000, batch=12, max_rounds=3, dry_rounds=4,
                  chunk=256, ldfi=LdfiConfig(lanes=4, frac=0.25))
        fuzz(_echo_ldfi_rt(), **kw)              # warm
        before = COMPILE_LOG.snapshot()["traces_total"]
        res = fuzz(_echo_ldfi_rt(), **kw)        # fresh Runtime + plan
        after = COMPILE_LOG.snapshot()["traces_total"]
        assert after == before, COMPILE_LOG.recent(8)
        assert res["targeted"]["lanes_run"] >= 1
