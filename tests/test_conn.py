"""Connection lifecycle over the simulated network: handshake, refusal,
reset, and data-over-connection (the tcp listener/stream test shapes,
tcp/mod.rs:57-218)."""

import jax.numpy as jnp
import numpy as np

from madsim_tpu import Program, Runtime, Scenario, SimConfig, NetConfig, ms, sec
from madsim_tpu.harness.simtest import run_seeds
from madsim_tpu.net import conn, stream

T_CONNECT, T_PUMP, T_RETX = 1, 2, 3
K = 12
W = 4


def spec(n):
    z = jnp.asarray(0, jnp.int32)
    return dict(pushed=z, got=z, refused=z, established=z,
                rx_log=jnp.full((K,), -1, jnp.int32),
                **conn.conn_state(n), **stream.stream_state(n, window=W))


class Client(Program):
    """Node 0 connects to node 1, then streams 0..K-1 over the connection.
    Node 2 (if present) tries to connect to a NON-listening node 0 and must
    be refused."""

    def init(self, ctx):
        st = dict(ctx.state)
        conn.listen(ctx, st, when=ctx.node == 1)     # only node 1 listens
        ctx.set_timer(ms(1), T_CONNECT,
                      when=(ctx.node == 0) | (ctx.node == 2))
        ctx.set_timer(ms(15), T_RETX, when=ctx.node == 0)
        ctx.state = st

    def on_timer(self, ctx, tag, payload):
        st = dict(ctx.state)
        # node 0 dials node 1; node 2 dials node 0 (refused); retry dialing
        want = jnp.where(ctx.node == 0, 1, 0)
        dialing = (tag == T_CONNECT) & ((ctx.node == 0) | (ctx.node == 2))
        conn.connect(ctx, st, want, when=dialing)
        ctx.set_timer(ms(20), T_CONNECT,
                      when=dialing & ~conn.is_established(st, want)
                      & (st["refused"] == 0))

        # pump data once established (sender = node 0 only)
        est = conn.is_established(st, 1) & (ctx.node == 0)
        is_pump = ((tag == T_PUMP) | (tag == T_CONNECT)) & est
        for _ in range(2):
            ok = stream.send(ctx, st, 1, st["pushed"],
                             when=is_pump & (st["pushed"] < K))
            st["pushed"] = st["pushed"] + ok
        ctx.set_timer(ms(5), T_PUMP, when=is_pump & (st["pushed"] < K))
        is_retx = (tag == T_RETX) & (ctx.node == 0)
        stream.retransmit(ctx, st, 1, when=is_retx & est)
        ctx.set_timer(ms(15), T_RETX, when=is_retx)
        ctx.state = st

    def on_message(self, ctx, src, tag, payload):
        st = dict(ctx.state)
        accepted, established, was_rst = conn.on_message(ctx, st, src, tag,
                                                         payload)
        st["established"] = st["established"] + established
        st["refused"] = st["refused"] + (was_rst & (ctx.node == 2))

        # only consume data over an ESTABLISHED connection
        vals, mask = stream.on_message(ctx, st, src, tag, payload)
        for i in range(W):
            idx = jnp.clip(st["got"], 0, K - 1)
            take = (mask[i] & (ctx.node == 1) & (st["got"] < K)
                    & (st["cn_state"][src] == conn.ESTABLISHED))
            st["rx_log"] = st["rx_log"].at[idx].set(
                jnp.where(take, vals[i], st["rx_log"][idx]))
            st["got"] = st["got"] + take
        ctx.halt_if((ctx.node == 1) & (st["got"] >= K))
        ctx.state = st


class TestConn:
    def _run(self, n=3, loss=0.0, seeds=8):
        cfg = SimConfig(n_nodes=n, event_capacity=128, time_limit=sec(20),
                        net=NetConfig(packet_loss_rate=loss,
                                      send_latency_min=ms(1),
                                      send_latency_max=ms(10)))
        rt = Runtime(cfg, [Client()], spec(n))
        return run_seeds(rt, np.arange(seeds), max_steps=40_000)

    def test_handshake_then_ordered_data(self):
        state = self._run()
        logs = np.asarray(state.node_state["rx_log"])[:, 1]
        assert (logs == np.arange(K)).all()
        # handshake completed exactly once on the initiator
        assert (np.asarray(state.node_state["established"])[:, 0] == 1).all()

    def test_connect_to_non_listener_refused(self):
        state = self._run()
        refused = np.asarray(state.node_state["refused"])[:, 2]
        assert (refused >= 1).all()                  # node 2 got RST
        cn = np.asarray(state.node_state["cn_state"])
        assert (cn[:, 2, 0] == conn.CLOSED).all()    # and stays closed

    def test_handshake_survives_loss(self):
        state = self._run(loss=0.25)
        logs = np.asarray(state.node_state["rx_log"])[:, 1]
        assert (logs == np.arange(K)).all()
