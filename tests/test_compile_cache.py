"""Shared step-program cache: signature soundness, cross-Runtime
executable reuse, trace_cap bucketing, and warm-cache correctness.

The load-bearing property is the compile-domain / replay-domain split
(DESIGN §10): configs differing only in DYNAMIC knobs (time limit, loss,
latency, jitter bound, exact trace_cap within its power-of-two bucket)
must share ONE executable — asserted with the compile counter — and a
warm-cache run must be bitwise-equal to a fresh-compile control (state,
fingerprints, ring columns). Anything less would make the cache a replay
domain, which DESIGN §4 forbids.
"""

import numpy as np
import pytest

from madsim_tpu import Runtime, Scenario, SimConfig, NetConfig, ms, sec
from madsim_tpu.compile.cache import COMPILE_LOG, PROGRAM_CACHE
from madsim_tpu.compile.signature import (freeze, next_pow2,
                                          runtime_signature)
from madsim_tpu.core.state import TRACE_FIELDS
from madsim_tpu.models.pingpong import PingPong, state_spec
from madsim_tpu.obs import ring_records
from madsim_tpu.utils.hostcopy import owned_host_copy

# distinctive structural shape (payload_words=3) so compile-counter
# deltas cannot be polluted by entries other test files already primed
def _pp(time_limit=sec(5), loss=0.0, lat_hi=ms(4), trace_cap=0,
        target=6, share=True):
    cfg = SimConfig(n_nodes=2, event_capacity=16, payload_words=3,
                    time_limit=time_limit, trace_cap=trace_cap,
                    net=NetConfig(packet_loss_rate=loss,
                                  send_latency_min=ms(1),
                                  send_latency_max=lat_hi))
    return Runtime(cfg, [PingPong(2, target=target)], state_spec(),
                   share_programs=share)


def _chunk_traces():
    return COMPILE_LOG.snapshot()["traces"].get("chunk_runner", 0)


def _assert_states_equal(a, b, what=""):
    """Bitwise leaf-by-leaf comparison of two final states — INCLUDING
    the recorder columns (the warm-cache contract covers observation
    state too)."""
    import jax
    la, lb = jax.tree.leaves(a), jax.tree.leaves(b)
    assert len(la) == len(lb)
    for i, (x, y) in enumerate(zip(la, lb)):
        assert (np.asarray(x) == np.asarray(y)).all(), f"{what} leaf {i}"


class TestStructuralSignature:
    def test_dynamic_knobs_do_not_key_compiles(self):
        a = SimConfig(n_nodes=3, time_limit=sec(1),
                      net=NetConfig(packet_loss_rate=0.0))
        b = SimConfig(n_nodes=3, time_limit=sec(9),
                      net=NetConfig(packet_loss_rate=0.3,
                                    send_latency_min=ms(2),
                                    send_latency_max=ms(50)))
        assert a.structural_signature() == b.structural_signature()
        # ...but they ARE distinct replay domains: the repro hash differs
        assert a.hash() != b.hash()

    @pytest.mark.parametrize("kw", [
        dict(event_capacity=256), dict(payload_words=4),
        dict(table_dtype="int16"), dict(emission_write="onehot"),
        dict(collect_stats=False), dict(trace_cap=8),
        dict(net=NetConfig(op_jitter_max=3)),   # the static jitter GATE
        dict(latency_hist=16),                  # the r16 latency plane
        dict(latency_hist=16, complete_kinds=((1, 7),)),
        dict(latency_hist=16, root_kinds=((2, 4),)),
    ])
    def test_structural_fields_key_compiles(self, kw):
        base = SimConfig(n_nodes=3)
        assert (SimConfig(n_nodes=3, **kw).structural_signature()
                != base.structural_signature())

    def test_jitter_value_is_dynamic_once_enabled(self):
        a = SimConfig(n_nodes=3, net=NetConfig(op_jitter_max=3))
        b = SimConfig(n_nodes=3, net=NetConfig(op_jitter_max=7))
        assert a.structural_signature() == b.structural_signature()

    def test_slo_target_is_dynamic(self):
        # the SLO target rides SimState (retune/fuzz without recompile)
        a = SimConfig(n_nodes=3, latency_hist=16, slo_target=100)
        b = SimConfig(n_nodes=3, latency_hist=16, slo_target=9000)
        assert a.structural_signature() == b.structural_signature()
        assert a.hash() != b.hash()     # hash() covers every field

    def test_trace_cap_buckets(self):
        assert next_pow2(0) == 0 and next_pow2(1) == 1
        assert next_pow2(17) == 32 and next_pow2(32) == 32
        sigs = {SimConfig(n_nodes=2, trace_cap=c).structural_signature()
                for c in range(17, 33)}
        assert len(sigs) == 1          # one executable for the whole sweep
        assert (SimConfig(n_nodes=2, trace_cap=33).structural_signature()
                not in sigs)
        for c in range(17, 33):
            assert SimConfig(n_nodes=2, trace_cap=c).trace_cap_bucket == 32


class TestRuntimeSignature:
    def test_same_construction_shares(self):
        assert _pp(sec(5), 0.0)._sig == _pp(sec(8), 0.2)._sig

    def test_program_params_key_compiles(self):
        # target is baked into the handler trace
        assert _pp(target=6)._sig != _pp(target=7)._sig

    def test_factory_closures_freeze_by_value(self):
        # the flagship factories build invariant/halt_when CLOSURES per
        # call; freezing by (code, defaults, cells) makes two identical
        # constructions equal — this is what makes sharing reach the
        # real models, not just bare Programs
        from madsim_tpu.models.raft import make_raft_runtime
        a = make_raft_runtime(5, 8, n_cmds=4)
        b = make_raft_runtime(5, 8, n_cmds=4)
        c = make_raft_runtime(5, 16, n_cmds=4)
        assert a._sig == b._sig
        assert a._sig != c._sig

    def test_kwonly_defaults_key_the_freeze(self):
        # keyword-only defaults bake into the trace exactly like
        # positional ones — two closures differing only there must NOT
        # freeze equal (a false hit would run the wrong invariant)
        def mk(k):
            def inv(s, *, thresh=k):
                return thresh
            return inv
        assert freeze(mk(1)) != freeze(mk(2))
        assert freeze(mk(3)) == freeze(mk(3))

    def test_module_globals_key_the_freeze(self):
        # CPython compares code objects by VALUE: byte-identical source
        # in two modules yields EQUAL code objects even when the module
        # globals they read differ — the freeze must fold those bindings
        # in (a false hit would run the wrong invariant silently)
        import types as _t
        src = "THRESH = %d\ndef inv(s):\n    return s > THRESH\n"
        m1, m2, m3 = (_t.ModuleType(f"_sigmod{i}") for i in range(3))
        exec(src % 5, m1.__dict__)
        exec(src % 9, m2.__dict__)
        exec(src % 5, m3.__dict__)
        assert m1.inv.__code__ == m2.inv.__code__   # the trap
        assert freeze(m1.inv) != freeze(m2.inv)     # the fix
        assert freeze(m1.inv) == freeze(m3.inv)     # same binding shares

    def test_recursive_function_freezes_stably(self):
        # a recursive function's own global binding is a reference
        # cycle; it must encode as a stable marker, not an identity
        # token (which would silently disable sharing for the module)
        import types as _t
        src = ("def fact(n):\n"
               "    return 1 if n <= 1 else n * fact(n - 1)\n")
        m1, m2 = _t.ModuleType("_sigr1"), _t.ModuleType("_sigr2")
        exec(src, m1.__dict__)
        exec(src, m2.__dict__)
        assert freeze(m1.fact) == freeze(m1.fact)
        assert freeze(m1.fact) == freeze(m2.fact)

    def test_unknown_objects_never_false_hit(self):
        class Opaque:
            __slots__ = ()              # no attribute dict to freeze
        x, y = Opaque(), Opaque()
        # soundness: opaque values NEVER compare equal across objects
        # (losing sharing is acceptable; a false cache hit is not)
        assert freeze(x) != freeze(y)

    def test_unknown_with_attrs_is_stable_per_object(self):
        # an object whose attributes cannot freeze gets an identity
        # token stashed on it — the SAME object keeps one signature
        class Weird:
            def __init__(self):
                self.gen = (i for i in range(3))   # unfreezable attr
        w = Weird()
        assert freeze(w) == freeze(w)
        assert freeze(w) != freeze(Weird())


class TestSharedExecutables:
    def test_chunk_runner_shared_one_trace_bitwise_equal(self):
        seeds = np.arange(48)
        rt1 = _pp(sec(5), 0.0)
        rt2 = _pp(sec(7), 0.1)          # dynamic knobs only
        assert rt1._run_chunk[False] is rt2._run_chunk[False]
        before = _chunk_traces()
        s1, _ = rt1.run(rt1.init_batch(seeds), 192, 64)
        s2, _ = rt2.run(rt2.init_batch(seeds), 192, 64)
        assert _chunk_traces() - before <= 1   # one retrace for the pair
        # warm-cache run == fresh-compile control, bitwise
        ctrl = _pp(sec(7), 0.1, share=False)
        sc, _ = ctrl.run(ctrl.init_batch(seeds), 192, 64)
        assert (ctrl.fingerprints(sc) == rt2.fingerprints(s2)).all()
        _assert_states_equal(sc, s2, "chunk")

    def test_fused_runner_shared_bitwise_equal(self):
        seeds = np.arange(48)
        rt1 = _pp(sec(5), 0.05)
        rt2 = _pp(sec(6), 0.15)
        assert rt1._fused_runner is rt2._fused_runner
        f1 = rt1.run_fused(rt1.init_batch(seeds), 192, 64)
        f2 = rt2.run_fused(rt2.init_batch(seeds), 192, 64)
        ctrl = _pp(sec(6), 0.15, share=False)
        fc = ctrl.run_fused(ctrl.init_batch(seeds), 192, 64)
        assert (ctrl.fingerprints(fc) == rt2.fingerprints(f2)).all()
        _assert_states_equal(fc, f2, "fused")
        del f1

    def test_dynamic_knob_sweep_costs_one_trace(self):
        # the explore()/harness shape: N configs, one structure — the
        # whole sweep must pay one chunk-runner retrace (same B/chunk)
        seeds = np.arange(16)
        rts = [_pp(sec(2 + i), 0.02 * i) for i in range(4)]
        rts[0].run(rts[0].init_batch(seeds), 64, 32)   # prime
        before = _chunk_traces()
        for rt in rts[1:]:
            rt.run(rt.init_batch(seeds), 64, 32)
        assert _chunk_traces() == before   # all warm

    def test_inject_shared(self):
        rt1, rt2 = _pp(sec(5)), _pp(sec(9))
        assert rt1._inject is rt2._inject

    def test_share_programs_false_is_private(self):
        rt1 = _pp(sec(5), share=False)
        rt2 = _pp(sec(5), share=False)
        assert rt1._run_chunk[False] is not rt2._run_chunk[False]


class TestTraceCapBucketing:
    def _traced(self, cap, share=True):
        return _pp(sec(50), 0.0, trace_cap=cap, target=1 << 30,
                   share=share)

    def test_caps_in_one_bucket_share_executable(self):
        rt24, rt32 = self._traced(24), self._traced(32)
        assert rt24._sig == rt32._sig
        assert rt24._run_chunk[False] is rt32._run_chunk[False]

    def test_ring_bit_identical_vs_unbucketed(self):
        # cap=32 IS its own bucket — the compiled program is exactly what
        # an unbucketed build would produce — so the bucketed cap=24
        # ring must equal the chronological tail-24 of the cap=32 ring,
        # and all non-trace state must match bitwise
        seeds = np.arange(8)
        rt24, rt32 = self._traced(24), self._traced(32)
        s24, _ = rt24.run(rt24.init_batch(seeds), 256, 64)
        s32, _ = rt32.run(rt32.init_batch(seeds), 256, 64)
        for lane in (0, 3):
            r24 = ring_records(s24, lane=lane)
            r32 = ring_records(s32, lane=lane)
            assert r24["total"] == r32["total"] > 32
            assert r24["dropped"] == r24["total"] - 24
            for k in ("now", "step", "kind", "node", "src", "tag"):
                assert (r24[k] == r32[k][-24:]).all(), (lane, k)
        for f in type(s24).__dataclass_fields__:
            if f in TRACE_FIELDS or f in ("node_state", "ext"):
                continue
            assert (np.asarray(getattr(s24, f))
                    == np.asarray(getattr(s32, f))).all(), f
        assert (rt24.fingerprints(s24) == rt32.fingerprints(s32)).all()

    def test_bucketed_ring_matches_fresh_compile_control(self):
        seeds = np.arange(8)
        rt = self._traced(24)
        ctrl = self._traced(24, share=False)
        s, _ = rt.run(rt.init_batch(seeds), 256, 64)
        sc, _ = ctrl.run(ctrl.init_batch(seeds), 256, 64)
        _assert_states_equal(sc, s, "ring")


class TestWarmCacheCompacting:
    """The hostcopy satellite: stashed lanes must be OWNED copies, so a
    warm-cache double run (second run reuses executables whose buffer
    lifetimes differ from the fresh-compile path) returns identical,
    uncorrupted results."""

    def _halting(self, share=True):
        # loss staggers per-lane completion so compaction actually fires
        # (measured: ~70% of lanes halt around chunk 4 of 16-step chunks)
        return _pp(sec(30), 0.3, target=16, share=share)

    def test_forced_warm_cache_double_run(self):
        from madsim_tpu.obs import SweepObserver

        class CompactCount(SweepObserver):
            n = 0

            def on_compact(self, rec):
                CompactCount.n += 1

        seeds = np.arange(64)
        rt = self._halting()
        ref, _ = rt.run(rt.init_batch(seeds), 4096, 16)
        fp_ref = rt.fingerprints(ref)
        kw = dict(chunk=16, compact_when=0.25, min_batch=8)
        c1 = rt.run_compacting(rt.init_batch(seeds), 4096,
                               observer=CompactCount(), **kw)
        # second run: every executable now comes from the warm cache
        c2 = rt.run_compacting(rt.init_batch(seeds), 4096,
                               observer=CompactCount(), **kw)
        assert CompactCount.n >= 2, "compaction never fired — vacuous test"
        assert (rt.fingerprints(c1) == fp_ref).all()
        assert (rt.fingerprints(c2) == fp_ref).all()
        _assert_states_equal(c1, c2, "double-run")

    def test_owned_host_copy_owns(self):
        import jax.numpy as jnp
        src = {"a": jnp.arange(8), "b": np.arange(4.0)}
        out = owned_host_copy(src)
        assert out["a"].flags.owndata and out["b"].flags.owndata
        out["a"][0] = 99    # owned: writable, no aliasing with the source
        assert int(np.asarray(src["a"])[0]) == 0


class TestPersistentCacheWiring:
    def test_enable_persistent_cache(self, tmp_path, monkeypatch):
        import jax
        from madsim_tpu.compile.persistent import enable_persistent_cache
        prior = jax.config.jax_compilation_cache_dir
        try:
            monkeypatch.delenv("JAX_COMPILATION_CACHE_DIR", raising=False)
            d = str(tmp_path / "cc")
            assert enable_persistent_cache(d) == d
            assert jax.config.jax_compilation_cache_dir == d
            # env-var path (what scripts/ci.sh exports)
            d2 = str(tmp_path / "cc2")
            monkeypatch.setenv("JAX_COMPILATION_CACHE_DIR", d2)
            assert enable_persistent_cache() == d2
        finally:
            jax.config.update("jax_compilation_cache_dir", prior)

    def test_noop_without_config(self, monkeypatch):
        import jax
        from madsim_tpu.compile.persistent import enable_persistent_cache
        prior = jax.config.jax_compilation_cache_dir
        monkeypatch.delenv("JAX_COMPILATION_CACHE_DIR", raising=False)
        assert enable_persistent_cache() is None
        assert jax.config.jax_compilation_cache_dir == prior


@pytest.mark.slow
class TestCacheMatrixFlagships:
    """The full warm-vs-fresh matrix (ISSUE satellite): raft / wal_kv /
    shard_kv at 64 seeds through all three runners plus
    run_fused_sharded — warm-cache executables bitwise-equal to
    fresh-compile controls. Chaos- and compile-heavy; ci.sh full runs it.
    """

    def _pair(self, build):
        """(prime+warm runtime, fresh-compile control) for one factory."""
        prime = build()     # populates the cache
        warm = build()      # same signature: every runner is a cache hit
        assert prime._sig == warm._sig
        assert prime._run_chunk[False] is warm._run_chunk[False]
        ctrl = build()
        ctrl._sig = None            # private jits: the fresh-compile arm
        for attr in ("_run_chunk", "_fused_runner", "_inject"):
            ctrl.__dict__.pop(attr, None)
        return prime, warm, ctrl

    def _check(self, build, max_steps, chunk, expect_crash=False):
        seeds = np.arange(64, dtype=np.uint32)
        prime, warm, ctrl = self._pair(build)
        # prime the shared executables once
        prime.run(prime.init_batch(seeds), max_steps, chunk)
        for runner in ("run", "run_fused", "run_compacting", "sharded"):
            if runner == "run":
                w, _ = warm.run(warm.init_batch(seeds), max_steps, chunk)
                c, _ = ctrl.run(ctrl.init_batch(seeds), max_steps, chunk)
            elif runner == "run_fused":
                w = warm.run_fused(warm.init_batch(seeds), max_steps,
                                   chunk)
                c = ctrl.run_fused(ctrl.init_batch(seeds), max_steps,
                                   chunk)
            elif runner == "run_compacting":
                w = warm.run_compacting(warm.init_batch(seeds), max_steps,
                                        chunk=chunk, min_batch=8)
                c = ctrl.run_compacting(ctrl.init_batch(seeds), max_steps,
                                        chunk=chunk, min_batch=8)
            else:
                from madsim_tpu.parallel.distributed import \
                    run_fused_sharded
                w = run_fused_sharded(warm, seeds, max_steps, chunk)
                c = run_fused_sharded(ctrl, seeds, max_steps, chunk)
            assert (warm.fingerprints(w) == ctrl.fingerprints(c)).all(), \
                runner
            _assert_states_equal(c, w, runner)
        if expect_crash:
            assert np.asarray(w.crashed).any()

    def test_raft(self):
        from madsim_tpu.models.raft import make_raft_runtime

        def build():
            cfg = SimConfig(n_nodes=5, event_capacity=128,
                            time_limit=sec(3),
                            net=NetConfig(packet_loss_rate=0.05,
                                          send_latency_min=ms(1),
                                          send_latency_max=ms(10)))
            sc = Scenario()
            sc.at(sec(1)).kill_random()
            sc.at(sec(1) + ms(400)).restart_random()
            return make_raft_runtime(5, 8, n_cmds=4, scenario=sc, cfg=cfg)

        self._check(build, 1500, 256)

    def test_wal_kv_mid_sweep_crash(self):
        from madsim_tpu.models.wal_kv import make_wal_kv_runtime

        def build():
            sc = Scenario()
            for t in range(6):
                sc.at(ms(150) + ms(250) * t).kill(0)
                sc.at(ms(210) + ms(250) * t).restart(0)
            return make_wal_kv_runtime(n_clients=2, n_ops=12, wal_cap=64,
                                       sync_wal=False, scenario=sc)

        self._check(build, 4096, 512, expect_crash=True)

    def test_shard_kv(self):
        from madsim_tpu.models.shard_kv import make_shard_runtime

        def build():
            cfg = SimConfig(n_nodes=11, event_capacity=160,
                            payload_words=12, time_limit=sec(60),
                            net=NetConfig(send_latency_min=ms(1),
                                          send_latency_max=ms(10)))
            return make_shard_runtime(n_groups=2, rg=3, rc=3, n_clients=2,
                                      n_ops=8, max_cfg=8, log_capacity=48,
                                      cfg=cfg)

        self._check(build, 3000, 512)
