"""Streaming RPC shapes (madsim-tonic parity): client/server/bidi streaming
with StreamEnd markers, under loss and kill-mid-stream chaos.

Reference shape: tonic-example/src/server.rs:126-253 runs the four method
shapes against a sim net; madsim-tonic client.rs:52-124 + codec.rs:30-45 is
the mechanism being mirrored. The in-model crash_if oracles verify payload
correctness per frame, so `run_seeds` completing without SimFailure is the
assertion that every delivered frame was right.
"""

import numpy as np
import pytest

from madsim_tpu import Scenario, SimConfig, NetConfig, ms, sec
from madsim_tpu.harness.simtest import run_seeds
from madsim_tpu.models.stream_echo import make_stream_echo_runtime

SEEDS = np.arange(8)


pytestmark = pytest.mark.slow  # measured in --durations; ci.sh fast skips

def _cfg(loss=0.0, time_limit=sec(8)):
    return SimConfig(n_nodes=3, event_capacity=64, payload_words=8,
                     time_limit=time_limit,
                     net=NetConfig(packet_loss_rate=loss,
                                   send_latency_min=ms(1),
                                   send_latency_max=ms(8)))


def _done(state):
    return np.asarray(state.node_state["c_done"])[:, 1:]


class TestShapesClean:
    @pytest.mark.parametrize("mode", ["bidi", "sum", "download"])
    def test_all_clients_complete(self, mode):
        rt = make_stream_echo_runtime(mode, n_clients=2, n_items=6,
                                      cfg=_cfg())
        state = run_seeds(rt, SEEDS, max_steps=20_000)
        assert (_done(state) == 1).all()
        # finished well before the time limit (no stall-retry needed)
        assert (np.asarray(state.now) < sec(4)).all()


class TestAdversity:
    @pytest.mark.parametrize("mode", ["bidi", "sum", "download"])
    def test_complete_under_loss(self, mode):
        # 10% loss: Go-Back-N retransmission must push every frame through,
        # in order, exactly once (the oracles crash on any violation)
        rt = make_stream_echo_runtime(mode, n_clients=2, n_items=6,
                                      cfg=_cfg(loss=0.10))
        state = run_seeds(rt, SEEDS, max_steps=40_000)
        assert (_done(state) == 1).all()

    def test_kill_mid_stream(self):
        # the server dies while streams are open and returns with amnesia:
        # clients must detect the stall, reset the fabric, and re-run the
        # call to completion (kill-mid-stream, the tonic-example crash test)
        sc = Scenario()
        sc.at(ms(40)).kill(0)   # before ANY 10-item stream can complete
        sc.at(ms(400)).restart(0)
        rt = make_stream_echo_runtime("bidi", n_clients=2, n_items=10,
                                      scenario=sc, cfg=_cfg())
        state = run_seeds(rt, SEEDS, max_steps=40_000)
        assert (_done(state) == 1).all()
        # the kill interrupted every open stream, so completion is only
        # possible after the restart (stall-detect -> reset -> re-run)
        assert (np.asarray(state.now) > ms(400)).all()

    def test_kill_mid_stream_with_loss(self):
        sc = Scenario()
        sc.at(ms(80)).kill(0)
        sc.at(ms(500)).restart(0)
        rt = make_stream_echo_runtime("download", n_clients=2, n_items=6,
                                      scenario=sc,
                                      cfg=_cfg(loss=0.05, time_limit=sec(10)))
        state = run_seeds(rt, SEEDS, max_steps=60_000)
        assert (_done(state) == 1).all()


class TestDeterminism:
    def test_streaming_replay_stable(self):
        rt = make_stream_echo_runtime("bidi", n_clients=2, n_items=6,
                                      cfg=_cfg(loss=0.05))
        assert rt.check_determinism(seed=5, max_steps=20_000)
