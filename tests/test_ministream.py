"""ministream: barrier-aligned exactly-once epochs under loss and mapper
chaos — green with the alignment gate, red the moment a barrier may
overtake in-flight data (the classic streaming-checkpoint bug)."""

import numpy as np
import pytest

from madsim_tpu import Scenario, ms
from madsim_tpu.harness.simtest import SimFailure, run_seeds
from madsim_tpu.models import ministream as msv
from madsim_tpu.models.ministream import make_ministream_runtime

pytestmark = pytest.mark.slow  # chaos epochs; ci.sh fast skips

SEEDS = np.arange(48)


def _committed(state):
    return np.asarray(state.node_state["k_committed"])[:, msv.SINK]


class TestMiniStream:
    def test_exactly_once_under_loss(self):
        # 5% loss, no kills: retransmission + the completeness gate carry
        # every epoch to an aligned, exact commit
        rt = make_ministream_runtime(k=8, epochs=4)
        state = run_seeds(rt, SEEDS, max_steps=60_000)
        assert (np.asarray(state.node_state["s_done"])[:, msv.SOURCE]
                == 1).all()
        assert (_committed(state) == 4).all()

    def test_exactly_once_under_mapper_chaos(self):
        # kill/restart random mappers mid-stream: HELLO bumps the
        # attempt, the epoch replays, stale counts can't pair — totals
        # stay exact in every surviving schedule
        sc = Scenario()
        for t in range(3):
            sc.at(ms(300 + 700 * t)).kill_random(among=(msv.MAP_A,
                                                        msv.MAP_B))
            sc.at(ms(600 + 700 * t)).restart_random(among=(msv.MAP_A,
                                                           msv.MAP_B))
        rt = make_ministream_runtime(k=8, epochs=4, scenario=sc)
        state = run_seeds(rt, SEEDS, max_steps=80_000)
        assert (_committed(state) == 4).all()

    def test_barrier_overtaking_data_caught(self):
        # red: drop the completeness gate and a lost record's barrier
        # commits a short epoch — the exactly-once oracle MUST fire
        rt = make_ministream_runtime(k=8, epochs=4, strict_barrier=False)
        with pytest.raises(SimFailure) as ei:
            run_seeds(rt, np.arange(32), max_steps=60_000)
        assert ei.value.code == msv.CRASH_STREAM_LOST_OR_DUP

    def test_k_at_bitmask_ceiling(self):
        # K=31 fills every bit of the one-word idx bitmask (the documented
        # capacity edge, ministream.py): exactly-once must hold AT the
        # ceiling under mapper chaos, and K=32 must be rejected, not wrap
        from madsim_tpu.core.types import NetConfig, SimConfig, sec
        with pytest.raises(AssertionError):
            make_ministream_runtime(k=32, epochs=2)
        sc = Scenario()
        sc.at(ms(300)).kill_random(among=(msv.MAP_A, msv.MAP_B))
        sc.at(ms(700)).restart_random(among=(msv.MAP_A, msv.MAP_B))
        cfg = SimConfig(n_nodes=4, event_capacity=320, time_limit=sec(60),
                        net=NetConfig(packet_loss_rate=0.05))
        rt = make_ministream_runtime(k=31, epochs=2, scenario=sc, cfg=cfg)
        state = run_seeds(rt, np.arange(16), max_steps=80_000)
        assert (np.asarray(state.oops) == 0).all()
        assert (_committed(state) == 2).all()

    def test_replay_stable(self):
        sc = Scenario()
        sc.at(ms(400)).kill_random(among=(msv.MAP_A, msv.MAP_B))
        sc.at(ms(800)).restart_random(among=(msv.MAP_A, msv.MAP_B))
        rt = make_ministream_runtime(k=8, epochs=3, scenario=sc)
        assert rt.check_determinism(seed=9, max_steps=60_000)
