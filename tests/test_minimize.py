"""Chaos-script minimization: a failing seed's scenario shrinks to the
load-bearing rows, and the shrunken script still reproduces. Batched
ddmin (r9): every deletion candidate of a round runs as one lane of one
batched dispatch instead of one single-lane run each."""

import numpy as np
import pytest

from madsim_tpu import Scenario, ms
from madsim_tpu.harness.minimize import minimize_scenario
from madsim_tpu.models import wal_kv
from madsim_tpu.models.wal_kv import make_wal_kv_runtime


def _chaos(pairs):
    sc = Scenario()
    for t in range(pairs):
        sc.at(ms(150) + ms(250) * t).kill(0)
        sc.at(ms(210) + ms(250) * t).restart(0)
    return sc


class TestMinimize:
    def test_shrinks_and_still_reproduces(self):
        # 6 kill/restart pairs of power-fail chaos on the unsynced-WAL
        # red case: most pairs are noise — losing THE acked write needs
        # one well-placed kill and a restart for the client's GET to
        # observe it
        rt = make_wal_kv_runtime(n_clients=2, n_ops=12, wal_cap=64,
                                 sync_wal=False, scenario=_chaos(6))
        seed = 0                         # known red (tests/test_fs.py)
        minimal, info = minimize_scenario(rt, seed, max_steps=60_000)
        assert info["mode"] in ("batched", "batched+serial_fallback")

        assert info["crash_code"] == wal_kv.CRASH_LOST_WRITE
        assert info["kept"] < info["kept"] + info["dropped"]  # shrank
        assert info["kept"] <= 6, info    # most chaos rows were noise
        # rt restored: the full script is back in place
        assert len(rt.scenario.rows) == info["kept"] + info["dropped"]

        # the shrunken script reproduces in a FRESH runtime
        rt2 = make_wal_kv_runtime(n_clients=2, n_ops=12, wal_cap=64,
                                  sync_wal=False, scenario=minimal)
        st, _ = rt2.run(rt2.init_single(seed), 60_000,
                        collect_events=False)
        assert bool(np.asarray(st.crashed).any())
        assert int(np.asarray(st.crash_code).reshape(-1)[0]) \
            == wal_kv.CRASH_LOST_WRITE

        # 1-minimality: every surviving row is load-bearing (HALT rows
        # are pinned by the minimizer — set_scenario re-adds one — so
        # they're exempt from the droppability check)
        from madsim_tpu.core import types as T
        for i in range(len(minimal.rows)):
            if minimal.rows[i].op == T.OP_HALT:
                continue
            sub = Scenario()
            sub.rows = minimal.rows[:i] + minimal.rows[i + 1:]
            rt2.set_scenario(sub)
            st, _ = rt2.run(rt2.init_single(seed), 60_000,
                            collect_events=False)
            crashed = bool(np.asarray(st.crashed).any())
            code = int(np.asarray(st.crash_code).reshape(-1)[0])
            assert not (crashed and code == wal_kv.CRASH_LOST_WRITE), \
                f"row {i} of the minimal script is droppable"

    @pytest.mark.slow
    def test_batched_ddmin_cuts_dispatch_count(self):
        # the r9 satellite's measurement: the batched pass evaluates a
        # whole candidate round per device dispatch, so its run count
        # drops far below the serial one-single-lane-run-per-candidate
        # loop — and both converge to scripts that reproduce
        rt = make_wal_kv_runtime(n_clients=2, n_ops=12, wal_cap=64,
                                 sync_wal=False, scenario=_chaos(6))
        min_b, info_b = minimize_scenario(rt, 0, max_steps=60_000)
        min_s, info_s = minimize_scenario(rt, 0, max_steps=60_000,
                                          batched=False)
        assert info_s["mode"] == "serial"
        if info_b["mode"] == "batched":          # no fallback taken
            # the drop: a handful of batched dispatches (two per ddmin
            # round) vs one single-lane run per candidate deletion
            assert info_b["runs"] < info_s["runs"], (info_b, info_s)
        assert info_b["crash_code"] == info_s["crash_code"] \
            == wal_kv.CRASH_LOST_WRITE
        for minimal in (min_b, min_s):
            rt.set_scenario(minimal)
            st, _ = rt.run(rt.init_single(0), 60_000,
                           collect_events=False)
            rt.set_scenario(_chaos(6))
            assert int(np.asarray(st.crash_code).reshape(-1)[0]) \
                == wal_kv.CRASH_LOST_WRITE

    @pytest.mark.slow
    def test_knob_domain_minimize(self):
        # the fuzzer hand-off (search/fuzz.py minimize=True): ddmin over a
        # knob vector's fault rows, candidate evaluation and replay in the
        # SAME apply-knobs domain
        from madsim_tpu.harness.minimize import minimize_knobs
        from madsim_tpu.search import KnobPlan

        rt = make_wal_kv_runtime(n_clients=2, n_ops=12, wal_cap=64,
                                 sync_wal=False, scenario=_chaos(6))
        plan = KnobPlan.from_runtime(rt, dup_slots=2)
        minimal, info = minimize_knobs(rt, plan, plan.base_knobs(), seed=0,
                                       max_steps=60_000)
        assert info["crash_code"] == wal_kv.CRASH_LOST_WRITE
        assert info["kept"] < info["kept"] + info["dropped"]
        assert "kill node 0" in info["script"]
        # the minimal knob vector replays to the same crash
        state = plan.apply(rt.init_batch(np.asarray([0], np.uint32)),
                           plan.stack([minimal]))
        state, _ = rt.run(state, 60_000, collect_events=False)
        assert int(np.asarray(state.crash_code)[0]) \
            == wal_kv.CRASH_LOST_WRITE

    def test_green_scenario_refuses(self):
        import pytest
        rt = make_wal_kv_runtime(n_clients=2, n_ops=8, wal_cap=8,
                                 sync_wal=True, scenario=_chaos(2))
        with pytest.raises(ValueError, match="does not crash"):
            minimize_scenario(rt, seed=3, max_steps=40_000)

    def test_env_knob_adds_minimal_script_to_failure(self, monkeypatch):
        # MADSIM_TEST_MINIMIZE=1: the SimFailure report carries the
        # ddmin'd chaos script in human-readable form
        import pytest

        from madsim_tpu.harness.simtest import SimFailure, run_seeds

        monkeypatch.setenv("MADSIM_TEST_MINIMIZE", "1")
        rt = make_wal_kv_runtime(n_clients=2, n_ops=12, wal_cap=64,
                                 sync_wal=False, scenario=_chaos(6))
        with pytest.raises(SimFailure) as ei:
            run_seeds(rt, np.arange(8), max_steps=60_000)
        msg = str(ei.value)
        assert "minimal chaos script" in msg
        assert "kill node 0" in msg and "restart node 0" in msg
        assert "MADSIM_TEST_SEED=" in msg          # repro line intact

    def test_set_scenario_overflow_rolls_back(self):
        # a capacity-overflowing script must not leave the runtime with
        # rt.scenario describing rows the state template doesn't encode
        import pytest

        from madsim_tpu import SimConfig, sec
        from madsim_tpu.models.pingpong import PingPong, state_spec
        from madsim_tpu.runtime.runtime import Runtime

        cfg = SimConfig(n_nodes=2, event_capacity=8, time_limit=sec(1))
        rt = Runtime(cfg, [PingPong(2, target=1)], state_spec())
        before = rt.scenario
        big = Scenario()
        for t in range(20):
            big.at(ms(t + 1)).kill(0)
        with pytest.raises(ValueError, match="exceeds event_capacity"):
            rt.set_scenario(big)
        assert rt.scenario is before     # old script still in force
