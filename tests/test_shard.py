"""Mesh-sharded fuzzing campaigns (r13, search/shard.py + DESIGN §15).

Load-bearing contracts:
(1) a 1-shard sharded campaign is BIT-IDENTICAL to the unsharded fuzzer —
down to store bytes (entry files, coverage keys, scheduler order and
energies, buckets) — over the saturating, crash-rich wal_kv, and
flagship raft workloads;
(2) an N-shard campaign's merged coverage is a superset of every shard's
own view, and the cross-shard merge actually DELIVERS (each shard's live
corpus holds foreign-namespace entries; the consensus tally folds every
shard's deltas exactly once);
(3) shard namespaces are worker ids: worker_id*shards+s, disjoint seed
spaces, group state committed in one atomic write, split == continuous
on resume;
(4) the r13 run-twice verify guards (fuzz/fuzz_sharded verify_resume,
replay_bucket verify) contain a corrupted first invocation and raise on
real nondeterminism;
(5) the supervisor pass rotates round targets, counts dead-worker
restarts, and prunes cold entries without forgetting coverage.

The suite runs on the conftest-forced 8-device virtual CPU mesh.
"""

import io
import json
import os

import numpy as np
import pytest

from madsim_tpu import fuzz, fuzz_sharded
from madsim_tpu.obs.progress import ProgressObserver
from madsim_tpu.parallel import stats
from madsim_tpu.search.corpus import (Corpus, merge_consensus,
                                      split_entry_id)
from madsim_tpu.search.mutate import KnobPlan
from madsim_tpu.search.shard import shard_worker_id
from madsim_tpu.service import (CorpusStore, prune_cold_entries,
                                replay_bucket, supervise_campaign)


def _saturating_rt(**kw):
    from bench import _make_saturating_runtime
    return _make_saturating_runtime(**kw)


def _crashrich_rt(trace_cap=128):
    from bench import _make_crashrich_runtime
    return _make_crashrich_runtime("wal_kv", trace_cap=trace_cap)


KW = dict(max_steps=400, batch=16, max_rounds=3, dry_rounds=9, chunk=128)


def _store_bytes(d):
    s = CorpusStore(d, create=False)
    return {n: open(os.path.join(d, "entries", n), "rb").read()
            for n in s.entry_names()}


def _assert_stores_equal(da, db, sharded_side="b"):
    """fuzz() store vs fuzz_sharded(shards=1) store: byte-equal entries,
    equal coverage, equal scheduler order/energies/rng."""
    sa, sb = CorpusStore(da, create=False), CorpusStore(db, create=False)
    assert sa.entry_names() == sb.entry_names()
    assert sa.coverage_keys() == sb.coverage_keys()
    assert _store_bytes(da) == _store_bytes(db)
    wa = sa.load_worker_state(0)
    gb = sb.load_shard_group_state(0)
    assert gb["shards"] == 1
    sh = gb["shard_states"][0]
    for k in ("next_counter", "order", "crash_codes", "sketch_counts",
              "rng_state"):
        assert wa[k] == sh[k], k
    assert wa["rounds_done"] == gb["rounds_done"]
    assert sorted(sa.bucket_keys()) == sorted(sb.bucket_keys())


class TestOneShardBitIdentity:
    def test_saturating(self, tmp_path):
        da, db = str(tmp_path / "a"), str(tmp_path / "b")
        r1 = fuzz(_saturating_rt(), corpus_dir=da, **KW)
        r2 = fuzz_sharded(_saturating_rt(), shards=1, corpus_dir=db, **KW)
        assert r1["distinct_schedules"] == r2["distinct_schedules"]
        assert r1["new_per_round"] == r2["new_per_round"]
        assert r1["crashes"] == r2["crashes"]
        assert r1["mutation_ops"] == r2["mutation_ops"]
        assert r1["crash_first_seed_by_code"] == r2["crash_first_seed_by_code"]
        _assert_stores_equal(da, db)

    def test_crashrich_wal_kv(self, tmp_path):
        kw = dict(max_steps=1500, batch=8, max_rounds=2, dry_rounds=9,
                  chunk=256)
        da, db = str(tmp_path / "a"), str(tmp_path / "b")
        r1 = fuzz(_crashrich_rt(), corpus_dir=da, **kw)
        r2 = fuzz_sharded(_crashrich_rt(), shards=1, corpus_dir=db, **kw)
        assert r1["crashes"] == r2["crashes"] > 0
        assert sorted(r1["crash_repros"]) == sorted(r2["crash_repros"])
        _assert_stores_equal(da, db)

    @pytest.mark.slow
    def test_flagship_raft(self, tmp_path):
        from bench import _make_runtime
        kw = dict(max_steps=512, batch=8, max_rounds=2, dry_rounds=9,
                  chunk=256)
        da, db = str(tmp_path / "a"), str(tmp_path / "b")
        r1 = fuzz(_make_runtime(), corpus_dir=da, **kw)
        r2 = fuzz_sharded(_make_runtime(), shards=1, corpus_dir=db, **kw)
        assert r1["distinct_schedules"] == r2["distinct_schedules"]
        _assert_stores_equal(da, db)

    def test_in_memory_results_match(self):
        r1 = fuzz(_saturating_rt(), **KW)
        r2 = fuzz_sharded(_saturating_rt(), shards=1, **KW)
        assert r1["distinct_schedules"] == r2["distinct_schedules"]
        assert r1["new_per_round"] == r2["new_per_round"]
        assert r1["mutation_ops"] == r2["mutation_ops"]
        assert r2["shards"] == 1


class TestShardMerge:
    def test_merged_coverage_superset_of_each_shard(self, tmp_path):
        d = str(tmp_path / "c")
        res = fuzz_sharded(_saturating_rt(sketch_slots=8), shards=2,
                           corpus_dir=d, **KW)
        assert res["shards"] == 2
        for row in res["per_shard"]:
            assert row["coverage"] <= res["distinct_schedules"]
            # the documented result row schema
            for k in ("shard", "worker_id", "corpus_size", "coverage",
                      "crashes", "seeds_run"):
                assert k in row
            assert row["seeds_run"] == res["rounds"] * KW["batch"]
        # the campaign union really is the union of the shard views
        assert res["distinct_schedules"] <= sum(
            row["coverage"] for row in res["per_shard"])
        # every shard's LIVE corpus received the other's entries
        g = CorpusStore(d, create=False).load_shard_group_state(0)
        for s, st in enumerate(g["shard_states"]):
            owners = {split_entry_id(int(e))[0] for e, _ in st["order"]}
            assert owners == {0, 1}, (s, owners)

    def test_four_shard_namespaces_and_entries(self, tmp_path):
        d = str(tmp_path / "c")
        res = fuzz_sharded(_saturating_rt(), shards=4, corpus_dir=d,
                           worker_id=1, **KW)
        # shard s of worker 1 at 4 shards owns namespace 4+s
        assert [row["worker_id"] for row in res["per_shard"]] == [4, 5, 6, 7]
        store = CorpusStore(d, create=False)
        owners = {split_entry_id(
            CorpusStore._parse_entry_name(n))[0]
            for n in store.entry_names()}
        assert owners == {4, 5, 6, 7}
        # group state is keyed by the BASE worker id, one file
        assert store.shard_group_ids() == [1]
        assert store.load_shard_group_state(1)["shards"] == 4

    def test_shard_worker_id_mapping(self):
        assert shard_worker_id(0, 0, 1) == 0          # the identity case
        assert shard_worker_id(3, 0, 1) == 3
        assert shard_worker_id(0, 2, 4) == 2
        assert shard_worker_id(2, 1, 4) == 9
        # groups are disjoint
        ids = {shard_worker_id(w, s, 4) for w in range(3) for s in range(4)}
        assert len(ids) == 12

    def test_disjoint_seed_spaces(self):
        from madsim_tpu.search.fuzz import WORKER_SEED_STRIDE
        res = fuzz_sharded(_saturating_rt(), shards=2,
                           **dict(KW, max_rounds=1))
        # base knob bootstrap crashes record real seeds from each
        # shard's stride-separated space
        for row in res["per_shard"]:
            assert row["worker_id"] in (0, 1)
        assert WORKER_SEED_STRIDE * 1 < 2**32


class TestConsensus:
    def test_allreduce_matches_host_rule(self):
        rng = np.random.default_rng(0)
        sk = rng.integers(0, 5, size=(64, 7)).astype(np.uint32)
        modal = stats.consensus_allreduce(sk)
        # the host rule: per-slot modal, ties to the smallest value
        expect = np.zeros(7, np.uint32)
        for j in range(7):
            vals, counts = np.unique(sk[:, j], return_counts=True)
            expect[j] = vals[np.argmax(counts)]
        assert (modal == expect).all()
        # and first_divergence_slots agrees with its own default
        assert (stats.first_divergence_slots(sk, consensus=modal)
                == stats.first_divergence_slots(sk)).all()

    def test_merge_consensus_counts_each_fold_once(self):
        plan = KnobPlan.from_runtime(_saturating_rt(sketch_slots=4))
        cs = [Corpus(plan, worker_id=w) for w in range(2)]
        for c in cs:
            c.track_admissions = True
        sk0 = np.zeros((4, 3), np.uint32)          # 4 lanes of value 0
        sk1 = np.ones((6, 3), np.uint32)           # 6 lanes of value 1
        cs[0]._fold_sketches(sk0)
        cs[1]._fold_sketches(sk1)
        tally = merge_consensus(cs, None)
        assert tally[0] == {0: 4, 1: 6}
        # both corpora hold the merged view; a second merge with no new
        # folds must NOT double-count the shared history
        assert cs[0]._slot_counts[0] == {0: 4, 1: 6}
        tally = merge_consensus(cs, tally)
        assert tally[0] == {0: 4, 1: 6}
        # new folds enter exactly once
        cs[0]._fold_sketches(np.full((3, 3), 1, np.uint32))
        tally = merge_consensus(cs, tally)
        assert tally[0] == {0: 4, 1: 9}
        assert cs[1]._slot_counts[0] == {0: 4, 1: 9}
        # consensus flips to the hotter value on every shard
        assert int(cs[0].consensus_sketch()[0]) == 1

    def test_single_corpus_merge_is_value_noop(self):
        plan = KnobPlan.from_runtime(_saturating_rt(sketch_slots=4))
        c = Corpus(plan)
        c.track_admissions = True
        c._fold_sketches(np.arange(12, dtype=np.uint32).reshape(4, 3) % 3)
        before = [dict(s) for s in c._slot_counts]
        merge_consensus([c], None)
        assert c._slot_counts == before


class TestShardedResume:
    def test_split_equals_continuous_two_shards(self, tmp_path):
        dc, dd = str(tmp_path / "c"), str(tmp_path / "d")
        kw = dict(KW, shards=2)
        fuzz_sharded(_saturating_rt(), corpus_dir=dc,
                     **dict(kw, max_rounds=2))
        rs = fuzz_sharded(_saturating_rt(), corpus_dir=dc,
                          **dict(kw, max_rounds=4))
        rc = fuzz_sharded(_saturating_rt(), corpus_dir=dd,
                          **dict(kw, max_rounds=4))
        assert rs["rounds"] == 2 and rs["rounds_done_total"] == 4
        assert rc["rounds"] == 4
        assert _store_bytes(dc) == _store_bytes(dd)
        gc_ = CorpusStore(dc, create=False).load_shard_group_state(0)
        gd = CorpusStore(dd, create=False).load_shard_group_state(0)
        assert [s["order"] for s in gc_["shard_states"]] \
            == [s["order"] for s in gd["shard_states"]]
        assert [s["rng_state"] for s in gc_["shard_states"]] \
            == [s["rng_state"] for s in gd["shard_states"]]
        assert gc_["tally"] == gd["tally"]
        # finished campaign: a further call is a durable no-op
        r3 = fuzz_sharded(_saturating_rt(), corpus_dir=dc,
                          **dict(kw, max_rounds=4))
        assert r3["rounds"] == 0

    def test_resume_rejects_different_shard_count(self, tmp_path):
        from madsim_tpu.service import StoreMismatch
        d = str(tmp_path / "c")
        fuzz_sharded(_saturating_rt(), shards=2, corpus_dir=d,
                     **dict(KW, max_rounds=2))
        with pytest.raises(StoreMismatch):
            fuzz_sharded(_saturating_rt(), shards=4, corpus_dir=d,
                         **dict(KW, max_rounds=4))

    def test_namespace_collision_guard(self, tmp_path):
        """The worker_id*shards+s mapping numerically overlaps plain
        worker ids — mixing owners of one namespace on one dir must be
        refused at open, in both directions, before any entry file
        could collide."""
        from madsim_tpu.service import StoreMismatch
        d = str(tmp_path / "c")
        # group 0 at 2 shards owns namespaces 0 and 1 ...
        fuzz_sharded(_saturating_rt(), shards=2, corpus_dir=d,
                     **dict(KW, max_rounds=1))
        with pytest.raises(StoreMismatch, match="owned by"):
            fuzz(_saturating_rt(), corpus_dir=d, worker_id=1,
                 **dict(KW, max_rounds=1))
        # ... and a plain worker blocks a group that would claim it
        d2 = str(tmp_path / "d")
        fuzz(_saturating_rt(), corpus_dir=d2, worker_id=1,
             **dict(KW, max_rounds=1))
        with pytest.raises(StoreMismatch, match="owned by"):
            fuzz_sharded(_saturating_rt(), shards=2, worker_id=0,
                         corpus_dir=d2, **dict(KW, max_rounds=1))
        # disjoint namespaces still compose (worker 1 at 2 shards owns
        # 2 and 3 — fine next to group 0's 0 and 1)
        fuzz_sharded(_saturating_rt(), shards=2, worker_id=1,
                     corpus_dir=d, **dict(KW, max_rounds=1))


class _FlakyRuntime:
    """Delegates to a real Runtime, but corrupts the FIRST `run_fused`
    result (sched_hash xored, crash lanes cleared) — the shape of the
    persistent-cache first-invocation transient (ROADMAP r12)."""

    def __init__(self, rt, corrupt_calls=1):
        self._rt = rt
        self._left = corrupt_calls
        self.calls = 0

    def __getattr__(self, name):
        return getattr(self._rt, name)

    def run_fused(self, state, max_steps, chunk=512):
        import jax.numpy as jnp
        out = self._rt.run_fused(state, max_steps, chunk)
        self.calls += 1
        if self._left > 0:
            self._left -= 1
            out = out.replace(
                sched_hash=out.sched_hash ^ jnp.uint32(0xBAD),
                crashed=jnp.zeros_like(out.crashed),
                crash_code=jnp.zeros_like(out.crash_code))
        return out


class TestVerifyGuards:
    def test_verify_resume_contains_corrupted_first_invocation(
            self, tmp_path):
        dc, dd = str(tmp_path / "c"), str(tmp_path / "d")
        fuzz(_saturating_rt(), corpus_dir=dc, **dict(KW, max_rounds=2))
        flaky = _FlakyRuntime(_saturating_rt())
        fuzz(flaky, corpus_dir=dc, verify_resume=True,
             **dict(KW, max_rounds=4))
        fuzz(_saturating_rt(), corpus_dir=dd, **dict(KW, max_rounds=4))
        assert flaky.calls >= 3      # round + at least one re-dispatch
        assert _store_bytes(dc) == _store_bytes(dd)

    def test_without_verify_corruption_forks_the_campaign(self, tmp_path):
        dc, dd = str(tmp_path / "c"), str(tmp_path / "d")
        fuzz(_saturating_rt(), corpus_dir=dc, **dict(KW, max_rounds=2))
        fuzz(_FlakyRuntime(_saturating_rt()), corpus_dir=dc,
             verify_resume=False, **dict(KW, max_rounds=4))
        fuzz(_saturating_rt(), corpus_dir=dd, **dict(KW, max_rounds=4))
        assert _store_bytes(dc) != _store_bytes(dd)

    def test_verify_resume_sharded(self, tmp_path):
        dc, dd = str(tmp_path / "c"), str(tmp_path / "d")
        kw = dict(KW, shards=2)
        fuzz_sharded(_saturating_rt(), corpus_dir=dc,
                     **dict(kw, max_rounds=2))
        flaky = _FlakyRuntime(_saturating_rt())
        fuzz_sharded(flaky, corpus_dir=dc, verify_resume=True,
                     **dict(kw, max_rounds=4))
        fuzz_sharded(_saturating_rt(), corpus_dir=dd,
                     **dict(kw, max_rounds=4))
        assert _store_bytes(dc) == _store_bytes(dd)

    def test_verify_raises_on_real_nondeterminism(self, tmp_path):
        dc = str(tmp_path / "c")
        fuzz(_saturating_rt(), corpus_dir=dc, **dict(KW, max_rounds=2))
        # corrupting every invocation differently is real nondeterminism
        class _Chaos(_FlakyRuntime):
            def run_fused(self, state, max_steps, chunk=512):
                import jax.numpy as jnp
                out = self._rt.run_fused(state, max_steps, chunk)
                self.calls += 1
                return out.replace(
                    sched_hash=out.sched_hash ^ jnp.uint32(self.calls))
        with pytest.raises(RuntimeError, match="deterministic"):
            fuzz(_Chaos(_saturating_rt()), corpus_dir=dc,
                 verify_resume=True, **dict(KW, max_rounds=4))

    def test_replay_bucket_verify(self, tmp_path):
        d = str(tmp_path / "c")
        kw = dict(max_steps=1500, batch=8, max_rounds=2, dry_rounds=9,
                  chunk=256)
        res = fuzz(_crashrich_rt(), corpus_dir=d, **kw)
        assert res["buckets_total"] > 0
        key = CorpusStore(d, create=False).bucket_keys()[0]
        plain = replay_bucket(_crashrich_rt(), d, key, max_steps=1500,
                              chunk=256, verify=False)
        verified = replay_bucket(_crashrich_rt(), d, key, max_steps=1500,
                                 chunk=256, verify=True)
        assert plain[:2] == verified[:2]
        assert verified[0] is True    # the bucket's crash reproduces
        # a corrupted first invocation is contained under verify
        flaky = _FlakyRuntime(_crashrich_rt())
        crashed, code, _ = replay_bucket(flaky, d, key, max_steps=1500,
                                         chunk=256, verify=True)
        assert (crashed, code) == plain[:2]
        assert flaky.calls >= 3


class TestSupervisor:
    def _mk_store_with_states(self, tmp_path):
        rt = _saturating_rt()
        plan = KnobPlan.from_runtime(rt)
        from madsim_tpu.service import store_signature
        d = str(tmp_path / "c")
        store = CorpusStore(d, signature=store_signature(rt, plan))
        from madsim_tpu.service.store import _atomic_json
        _atomic_json(store.worker_state_path(0), dict(
            worker_id=0, rounds_done=2, dry=0, wall_s=1.0, op_hist=[],
            next_counter=5, rng_state={}, crash_codes=[],
            sketch_counts=None,
            order=[[i, e] for i, e in
                   enumerate([5.0, 0.05, 2.0, 0.01, 0.06, 3.0])]))
        _atomic_json(store.shard_group_path(1), dict(
            worker_id=1, shards=2, rounds_done=2, dry=0, wall_s=1.0,
            op_hist=[], tally=None, shard_states=[
                dict(worker_id=2, next_counter=1, rng_state={},
                     crash_codes=[], sketch_counts=None,
                     order=[[9, 0.01], [10, 4.0], [11, 0.02], [12, 0.3],
                            [13, 0.01]]),
                dict(worker_id=3, next_counter=0, rng_state={},
                     crash_codes=[], sketch_counts=None,
                     order=[[20, 0.01]])]))
        return d, store

    def test_prune_cold_entries(self, tmp_path):
        d, store = self._mk_store_with_states(tmp_path)
        out = prune_cold_entries(d, below=0.1, keep_min=2)
        ws = store.load_worker_state(0)
        # cold rows dropped, hot rows kept, order preserved
        assert [e for _, e in ws["order"]] == [5.0, 2.0, 3.0]
        gs = store.load_shard_group_state(1)
        assert [e for _, e in gs["shard_states"][0]["order"]] \
            == [4.0, 0.3]
        # keep_min floor: a tiny corpus is never pruned below it
        assert gs["shard_states"][1]["order"] == [[20, 0.01]]
        assert out["pruned"] == 3 + 3
        # everything else in the states is untouched
        assert ws["next_counter"] == 5 and gs["shards"] == 2

    def test_supervise_campaign_rotates_restarts_prunes(self, tmp_path):
        d, store = self._mk_store_with_states(tmp_path)
        calls = []

        def fake_segment(factory, corpus_dir, **kw):
            calls.append(kw["max_rounds"])
            dead = {"0": {"returncode": 137, "result": None}} \
                if len(calls) == 1 else {}
            return dict(rounds_done=2 * len(calls), coverage_keys=7,
                        buckets=1,
                        worker_results={"1": {"returncode": 0},
                                        **dead})

        out = supervise_campaign(
            "bench:_make_saturating_runtime", d, workers=2, segments=3,
            rounds_per_segment=4, max_steps=100,
            run_segment=fake_segment)
        assert calls == [4, 8, 12]            # the rotation
        assert out["restarts"] == 1           # the SIGKILLed worker
        # default keep_min=4 protects the hottest rows: worker 0 loses
        # its 2 cold unprotected rows, group shard 0 loses 1, the
        # 1-entry shard is floored — 3 pruned on the first boundary,
        # nothing left on the second
        assert out["pruned"] == 3
        assert [s["max_rounds"] for s in out["segments"]] == [4, 8, 12]
        assert out["segments"][0]["dead_workers"] == [0]
        assert out["report"]["kind"] == "campaign"


class TestShardObservability:
    def test_round_records_carry_per_shard_rows(self):
        recs = []

        class Rec:
            def on_round(self, r):
                recs.append(r)

            def on_done(self, r):
                pass

        fuzz_sharded(_saturating_rt(), shards=2, observer=Rec(),
                     **dict(KW, max_rounds=2))
        assert recs and all(r["kind"] == "fuzz_round" for r in recs)
        for r in recs:
            assert r["shards"] == 2
            assert "new_crash_codes" in r     # the fuzz_round schema
            assert len(r["per_shard"]) == 2
            for row in r["per_shard"]:
                for k in ("shard", "worker_id", "corpus_size", "coverage",
                          "new", "crashes", "seeds_run"):
                    assert k in row

    def test_progress_observer_renders_shard_rows(self):
        buf = io.StringIO()
        obs = ProgressObserver(stream=buf, min_interval=0.0)
        obs.on_round(dict(
            kind="fuzz_round", round=1, batch=16, shards=2, seeds_run=32,
            new_schedules=5, distinct_total=5, crashes=0, corpus_size=5,
            dry_rounds=0, wall_s=1.0,
            per_shard=[dict(shard=0, worker_id=0, corpus_size=3,
                            coverage=3, new=3, crashes=0, seeds_run=16),
                       dict(shard=1, worker_id=1, corpus_size=2,
                            coverage=2, new=2, crashes=0, seeds_run=16)]))
        text = buf.getvalue()
        assert "x2 shards" in text
        assert "shard 0 (w0)" in text and "shard 1 (w1)" in text
        obs.on_round(dict(kind="supervisor", segment=0, max_rounds=4,
                          dead_workers=[1], restarts=1, pruned=3))
        assert "supervisor seg 0" in buf.getvalue()


class TestRunFusedSharded:
    def test_method_matches_unsharded(self):
        rt = _saturating_rt()
        seeds = np.arange(16, dtype=np.uint32)
        a = rt.run_fused(rt.init_batch(seeds), 400, 128)
        b = rt.run_fused_sharded(rt.init_batch(seeds), 400, 128)
        np.testing.assert_array_equal(np.asarray(a.sched_hash),
                                      np.asarray(b.sched_hash))
        np.testing.assert_array_equal(rt.fingerprints(a),
                                      rt.fingerprints(b))
