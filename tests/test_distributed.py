"""Multi-PROCESS smoke test: two jax.distributed processes on localhost
split a seed sweep and agree with the single-process run — the DCN-path
analog of the reference's multi-host deployments, runnable without
hardware (CPU backend, loopback coordinator)."""

import os
import subprocess
import sys

import numpy as np
import pytest

WORKER = r"""
import os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
import jax
jax.config.update("jax_platforms", "cpu")

# distributed init MUST precede anything that initializes the XLA backend
# (including the flax import chain inside madsim_tpu)
pid = int(sys.argv[1])
jax.distributed.initialize(coordinator_address="127.0.0.1:{port}",
                           num_processes=2, process_id=pid)
assert jax.process_count() == 2, jax.process_count()

sys.path.insert(0, {root!r})
from madsim_tpu.parallel.distributed import host_seed_slice, shard_global
from madsim_tpu.models.pingpong import PingPong, state_spec
from madsim_tpu import Runtime, SimConfig
from madsim_tpu.core.types import sec
import numpy as np
rt = Runtime(SimConfig(n_nodes=3, time_limit=sec(30)),
             [PingPong(3, target=5)], state_spec())
seeds = host_seed_slice(32)
state = shard_global(rt, seeds)
state, _ = rt.run(state, 4000, chunk=512)
# cross-process reduction over the sharded batch rides the collective path
total_acked = int(jax.jit(lambda s: s.node_state["acked"][:, 0].sum())(state))
halted = bool(jax.jit(lambda s: s.halted.all())(state))
print(f"RESULT pid={{pid}} local_seeds={{len(seeds)}} "
      f"total_acked={{total_acked}} halted={{halted}}", flush=True)
"""


WORKER2 = r"""
import os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
import jax
jax.config.update("jax_platforms", "cpu")

pid = int(sys.argv[1])
jax.distributed.initialize(coordinator_address="127.0.0.1:{port}",
                           num_processes=2, process_id=pid)

sys.path.insert(0, {root!r})
import numpy as np
from madsim_tpu import Runtime, SimConfig, NetConfig
from madsim_tpu.core.types import sec
from madsim_tpu.models.pingpong import PingPong, state_spec
from madsim_tpu.parallel.distributed import (host_seed_slice,
                                             run_compacting_sharded)
from madsim_tpu.utils.hashing import fingerprint

# loss spreads halting times so compaction actually fires
rt = Runtime(SimConfig(n_nodes=3, time_limit=sec(60),
                       net=NetConfig(packet_loss_rate=0.3)),
             [PingPong(3, target=5)], state_spec())
seeds = host_seed_slice(32)

# ground truth: this host's slice, no compaction
plain, _ = rt.run(rt.init_batch(seeds), 20_000, chunk=256)
fp_plain = np.asarray(jax.vmap(fingerprint)(plain))

# per-host compaction + global assembly (BASELINE config 4 at scale)
gstate = run_compacting_sharded(rt, seeds, 20_000, chunk=256,
                                compact_when=0.25, min_batch=4)
halted = bool(jax.jit(lambda s: s.halted.all())(gstate))

# fingerprints of the compacted local slice must match the plain run
# bit-for-bit (lane re-packing must be invisible to trajectory content)
comp_local = rt.run_compacting(rt.init_batch(seeds), 20_000, chunk=256,
                               compact_when=0.25, min_batch=4)
fp_comp = np.asarray(jax.vmap(fingerprint)(comp_local))
print(f"RESULT pid={{pid}} fp_match={{bool((fp_plain == fp_comp).all())}} "
      f"halted={{halted}}", flush=True)
"""


# this jaxlib's CPU backend may not implement cross-process collectives
# at all ("Multiprocess computations aren't implemented on the CPU
# backend") — an environment capability, not a code defect. The workers
# run to the first collective either way, so the marker in their output
# distinguishes "backend can't" (skip, precisely) from a real failure.
MULTIPROC_UNSUPPORTED = "Multiprocess computations aren't implemented"
_multiproc_broken = False    # memo: once one worker pair proves the
                             # backend can't, later tests skip instantly


def _run_two_workers(tmp_path, template, name):
    """Launch the two-process worker script on a loopback coordinator and
    return the RESULT lines; skip if the backend lacks multiprocess CPU
    support, fail on anything else."""
    import socket

    global _multiproc_broken
    if _multiproc_broken:
        pytest.skip("this jaxlib's CPU backend lacks multiprocess "
                    "collectives (established earlier in this session)")

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    with socket.socket() as s:  # ephemeral port: no CI collisions
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    f = tmp_path / name
    f.write_text(template.format(root=root, port=port))
    env = {k: v for k, v in os.environ.items()
           if k not in ("PALLAS_AXON_POOL_IPS",)}
    procs = [subprocess.Popen([sys.executable, str(f), str(i)],
                              stdout=subprocess.PIPE,
                              stderr=subprocess.STDOUT, text=True,
                              env=env)
             for i in range(2)]
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=240)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            pytest.fail("distributed worker timed out")
        outs.append(out)
    results = [l for o in outs for l in o.splitlines()
               if l.startswith("RESULT")]
    if len(results) != 2:
        if any(MULTIPROC_UNSUPPORTED in o for o in outs):
            _multiproc_broken = True
            pytest.skip("this jaxlib's CPU backend lacks multiprocess "
                        "collectives; the DCN path needs real multi-host "
                        "(or a jaxlib with CPU cross-process support)")
        pytest.fail(f"workers failed:\n{outs[0]}\n{outs[1]}")
    return results


class TestDistributed:
    def test_two_process_sweep(self, tmp_path):
        results = _run_two_workers(tmp_path, WORKER, "worker.py")
        # both processes see the same GLOBAL reduction over 32 seeds
        acked = [int(r.split("total_acked=")[1].split()[0]) for r in results]
        halted = [r.split("halted=")[1].strip() for r in results]
        assert acked[0] == acked[1] and acked[0] >= 32 * 5
        assert halted == ["True", "True"]

    def test_two_process_compacting_matches_plain(self, tmp_path):
        # VERDICT r2 next #5: compact-per-host-slice-then-reassemble. Each
        # process compacts its local slice; per-lane state fingerprints
        # must be bit-identical to the non-compacting run, and the
        # assembled global state must report all-halted.
        results = _run_two_workers(tmp_path, WORKER2, "worker2.py")
        for r in results:
            assert "fp_match=True" in r, r
            assert "halted=True" in r, r
