"""The coverage-guided schedule fuzzer (madsim_tpu/search, r9): PCT
tie-break perturbation, on-device knob mutation bounds, corpus
bookkeeping, the fuzz loop, and its compile discipline."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from madsim_tpu import (Corpus, KnobPlan, NetConfig, Runtime, Scenario,
                        SimConfig, explore, fuzz, ms, pct_sweep, sec,
                        with_prio_nudge)
from madsim_tpu.core import types as T
from madsim_tpu.models.pingpong import PingPong, state_spec


def _saturating_rt(target=6):
    """Fixed-latency chaos: seeds alone exhaust the schedule space fast —
    the regime where search beats sampling. ONE definition, shared with
    bench --mode search_ab and examples/fuzz_search.py."""
    import os
    import sys
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    from bench import _make_saturating_runtime
    return _make_saturating_runtime(target=target)


def _chaos_raft(n_cmds=4):
    from madsim_tpu.models.raft import make_raft_runtime
    from madsim_tpu.runtime import chaos
    cfg = SimConfig(n_nodes=5, event_capacity=128, time_limit=sec(6),
                    net=NetConfig(packet_loss_rate=0.05))
    sc = chaos.madraft_churn(servers=range(5), rounds=3)
    return make_raft_runtime(5, log_capacity=8, n_cmds=n_cmds,
                             scenario=sc, cfg=cfg)


def _leaves_equal(a, b):
    la, lb = jax.tree.leaves(a), jax.tree.leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


class TestPct:
    def test_zero_nudge_bit_identical(self):
        # the prio_nudge==0 contract: explicitly setting the nudge to 0
        # changes NOTHING — same trajectories, every leaf, both runners.
        # (Pre-PR equivalence rides on this plus the untouched golden
        # model tests: at nudge 0 the hook's pick is discarded by the
        # `where` and the PRNG stream never shifts.)
        rt = _chaos_raft()
        seeds = np.arange(24)
        plain, _ = rt.run(rt.init_batch(seeds), 800, 256)
        zeroed, _ = rt.run(with_prio_nudge(rt.init_batch(seeds), 0),
                           800, 256)
        _leaves_equal(plain, zeroed)
        fused = rt.run_fused(with_prio_nudge(rt.init_batch(seeds), 0),
                             800, 256)
        _leaves_equal(plain, fused)

    def test_nonzero_nudge_changes_schedules_deterministically(self):
        rt = _chaos_raft()
        seeds = np.arange(16)

        def run(nudge):
            s = with_prio_nudge(rt.init_batch(seeds), nudge)
            return rt.run_fused(s, 800, 256)

        base = run(0)
        nudged = run(np.arange(1, 17, dtype=np.int32))
        # the lever moves: most lanes take a different dispatch order
        h0 = np.asarray(base.sched_hash)
        h1 = np.asarray(nudged.sched_hash)
        assert (h0 != h1).any(axis=-1).sum() > 8
        # and deterministically: same (seed, nudge) = same trajectory
        again = run(np.arange(1, 17, dtype=np.int32))
        _leaves_equal(nudged, again)

    def test_pct_sweep_enumerates_policies(self):
        res = pct_sweep(_saturating_rt(), seed=3, nudges=np.arange(24),
                        max_steps=1000, chunk=256)
        assert res["distinct_schedules"] > 1
        # nudge 0 is in the sweep and equals the plain run of that seed
        rt = _saturating_rt()
        plain = rt.run_fused(rt.init_single(3), 1000, 256)
        from madsim_tpu.parallel.stats import sched_hash_u64
        assert res["hashes"][0] == sched_hash_u64(plain)[0]


class TestMutateApply:
    def _mutated_state(self, rt, batch=24, rounds=4, havoc=6):
        plan = KnobPlan.from_runtime(rt, dup_slots=2)
        knobs = {k: jnp.asarray(v) for k, v in
                 plan.base_batch(batch).items()}
        key = jax.random.PRNGKey(7)
        for i in range(rounds):     # stack mutations to push extremes
            knobs, _, _ = plan.mutate(knobs, jax.random.fold_in(key, i),
                                      havoc=havoc)
        state = plan.apply(rt.init_batch(np.arange(batch)), knobs)
        return plan, knobs, state

    def test_heavily_mutated_knobs_stay_in_bounds(self):
        # chaos-recipe composition with pool-restricted NODE_RANDOM rows:
        # whatever the mutator does, what lands in the event table must
        # honor every bound the engine (and the recipe's among= pools)
        # relies on
        from madsim_tpu.models.raft import make_raft_runtime
        from madsim_tpu.runtime import chaos
        cfg = SimConfig(n_nodes=5, event_capacity=128, time_limit=sec(6))
        sc = chaos.rolling_kills(rounds=3, among=[0, 1, 2])
        sc = chaos.split_brain(at=sec(2), group=[0, 1],
                               heal_after=sec(1), sc=sc)
        rt = make_raft_runtime(5, log_capacity=8, n_cmds=4,
                               scenario=sc, cfg=cfg)
        plan, knobs, state = self._mutated_state(rt)
        n0, R, D, N = plan.n_init, plan.R, plan.D, plan.N
        dl = np.asarray(state.t_deadline)[:, n0:n0 + R + D]
        kind = np.asarray(state.t_kind)[:, n0:n0 + R + D]
        node = np.asarray(state.t_node)[:, n0:n0 + R + D]
        tlim = int(cfg.time_limit)
        assert (((dl >= 0) & (dl <= tlim)) | (dl == T.T_INF)).all()
        assert np.isin(kind, [T.EV_FREE, T.EV_SUPER]).all()
        assert ((node >= -1) & (node < N)).all()
        # pool-restricted rows: mutated targets stay inside the recipe's
        # among= pool (or NODE_RANDOM)
        for r in range(R):
            if plan.node_ok[r] and plan.base["payload"][r].any():
                tgt = node[:, r]
                assert plan.pool_ok[r][tgt + 1].all(), (r, np.unique(tgt))
        # the HALT row is pinned: exactly at the time limit, still armed
        halt_rows = np.nonzero(plan.base["op"] == T.OP_HALT)[0]
        assert halt_rows.size == 1
        assert (dl[:, halt_rows[0]] == tlim).all()
        assert (kind[:, halt_rows[0]] == T.EV_SUPER).all()
        # scalar knobs in bounds
        loss = np.asarray(state.loss)
        lo, hi = np.asarray(state.lat_lo), np.asarray(state.lat_hi)
        assert ((loss >= 0) & (loss <= 0.99)).all()
        assert ((lo >= 0) & (lo <= hi)).all()
        # jitterless build: the jitter bound must not have moved
        assert (np.asarray(state.jitter) == 0).all()

    @pytest.mark.parametrize(
        "make", ["raft",
                 pytest.param("wal_kv", marks=pytest.mark.slow)])
    def test_mutated_scenarios_run_vs_fused_bit_identical(self, make):
        # per-lane mutated scenarios (incl. NODE_RANDOM chaos) through the
        # chunked and the fused runner: bitwise-equal final state — the
        # fuzzer may trust either runner for any mutant batch
        if make == "raft":
            rt = _chaos_raft()
            steps = 1000
        else:
            from madsim_tpu.models.wal_kv import make_wal_kv_runtime
            sc = Scenario()
            for t in range(3):
                sc.at(ms(150) + ms(250) * t).kill_random(among=[0])
                sc.at(ms(210) + ms(250) * t).restart_random(among=[0])
            rt = make_wal_kv_runtime(n_clients=2, n_ops=8, wal_cap=64,
                                     sync_wal=False, scenario=sc)
            steps = 20_000
        plan = KnobPlan.from_runtime(rt, dup_slots=2)
        knobs, _, _ = plan.mutate(plan.base_batch(24),
                                  jax.random.PRNGKey(3), havoc=4)
        chunked, _ = rt.run(
            plan.apply(rt.init_batch(np.arange(24)), knobs), steps, 256)
        fused = rt.run_fused(
            plan.apply(rt.init_batch(np.arange(24)), knobs), steps, 256)
        _leaves_equal(chunked, fused)
        # in-bounds under execution too: no capacity/time overflow oops
        assert (np.asarray(chunked.oops) == 0).all()

    def test_dup_slots_capacity_bounded(self):
        # a scenario that nearly fills the table gets fewer (or zero) dup
        # slots instead of a template overflow
        cfg = SimConfig(n_nodes=2, event_capacity=8, time_limit=sec(1))
        sc = Scenario()
        for t in range(4):
            sc.at(ms(t + 1)).kill(0)
        rt = Runtime(cfg, [PingPong(2, target=1)], state_spec(),
                     scenario=sc)
        plan = KnobPlan.from_runtime(rt, dup_slots=8)
        assert plan.D == cfg.event_capacity - 2 - plan.R
        assert plan.D >= 0

    def test_apply_enforces_pool_on_foreign_knobs(self):
        # apply is the safety boundary (DESIGN §11), not just the mutator:
        # a knob vector that never went through mutate() — hand-edited,
        # corpus-loaded, or from a saved repro — with an out-of-pool
        # target must snap to NODE_RANDOM, while in-pool and non-node
        # rows pass through bit-exactly
        cfg = SimConfig(n_nodes=4, time_limit=sec(2))
        sc = Scenario()
        sc.at(ms(100)).kill_random(among=[0, 1])
        rt = Runtime(cfg, [PingPong(4, target=2)], state_spec(),
                     scenario=sc)
        plan = KnobPlan.from_runtime(rt, dup_slots=0)
        r = int(np.argmax(plan.node_ok))
        kn = plan.base_knobs()
        kn["row_node"][r] = 3                      # outside among=[0, 1]
        st = plan.apply(rt.init_batch(np.asarray([1], np.uint32)),
                        plan.stack([kn]))
        assert int(np.asarray(st.t_node)[0, plan.n_init + r]) \
            == T.NODE_RANDOM
        kn["row_node"][r] = 1                      # inside the pool
        st = plan.apply(rt.init_batch(np.asarray([1], np.uint32)),
                        plan.stack([kn]))
        assert int(np.asarray(st.t_node)[0, plan.n_init + r]) == 1


class TestCorpus:
    def _plan(self):
        return KnobPlan.from_runtime(_saturating_rt(), dup_slots=1)

    def test_dedupe_by_schedule_hash(self):
        plan = self._plan()
        c = Corpus(plan, rng=np.random.default_rng(0))
        kb = plan.base_batch(4)
        stats = c.observe(kb, seeds=np.arange(4),
                          hashes_u64=np.asarray([1, 2, 2, 3]),
                          crashed=np.asarray([False, True, False, False]),
                          codes=np.asarray([0, 9, 0, 0]),
                          parent_ids=np.full(4, -1), round_no=0)
        assert stats["new"] == 3 and len(c) == 3
        assert stats["new_crash_codes"] == [9]
        # re-observing the same hashes admits nothing
        stats = c.observe(kb, np.arange(4), np.asarray([1, 2, 2, 3]),
                          np.zeros(4, bool), np.zeros(4, int),
                          np.full(4, -1), 1)
        assert stats["new"] == 0 and len(c) == 3
        # the crashed lane entered hot
        crash_entry = [e for e in c.entries if e["hash"] == 2][0]
        assert crash_entry["energy"] > [e for e in c.entries
                                        if e["hash"] == 1][0]["energy"]

    def test_energy_weighted_scheduling_with_fresh_floor(self):
        plan = self._plan()
        c = Corpus(plan, rng=np.random.default_rng(1), fresh_frac=0.25)
        kb = plan.base_batch(3)
        c.observe(kb, np.arange(3), np.asarray([10, 11, 12]),
                  np.zeros(3, bool), np.zeros(3, int), np.full(3, -1), 0)
        c.entries[1]["energy"] = 50.0          # make one entry hot
        _, ids = c.schedule(400)
        fresh = (ids == -1).sum()
        assert 40 <= fresh <= 180              # ~25% exploration floor
        picked = ids[ids >= 0]
        # the hot entry dominates the mutation budget
        assert (picked == 1).sum() > 0.7 * picked.size

    def test_parent_reward(self):
        plan = self._plan()
        c = Corpus(plan, rng=np.random.default_rng(2))
        kb = plan.base_batch(1)
        c.observe(kb, [0], np.asarray([1]), np.zeros(1, bool),
                  np.zeros(1, int), np.full(1, -1), 0)
        e0 = c.entries[0]["energy"]
        # a child of entry 0 discovers a new schedule -> parent rewarded
        c.observe(kb, [1], np.asarray([2]), np.zeros(1, bool),
                  np.zeros(1, int), np.asarray([0]), 1)
        assert c.entries[0]["energy"] > e0 * 1.2


class TestFuzz:
    def test_beats_blind_explore_on_saturating_space(self):
        # the subsystem's reason to exist: where seed sampling goes dry,
        # knob search keeps finding interleavings — strictly more distinct
        # schedules at the same rounds x batch x steps budget
        kw = dict(max_steps=1000, batch=48, max_rounds=3, dry_rounds=4,
                  chunk=256)
        blind = explore(_saturating_rt(), **kw)
        res = fuzz(_saturating_rt(), **kw)
        assert res["distinct_schedules"] > blind["distinct_schedules"]
        assert res["corpus_size"] >= blind["distinct_schedules"]
        assert sum(res["mutation_ops"].values()) > 0

    def test_dry_stop_and_campaign_determinism(self):
        kw = dict(max_steps=600, batch=32, max_rounds=8, dry_rounds=2,
                  chunk=128, rng_seed=11)

        def tiny():
            # the test_explore saturating workload: two nodes, constant
            # latency, NO chaos — a handful of dispatch orders exist
            cfg = SimConfig(n_nodes=2, time_limit=sec(5),
                            net=NetConfig(send_latency_min=ms(1),
                                          send_latency_max=ms(1)))
            return Runtime(cfg, [PingPong(2, target=3)], state_spec())

        # havoc=0 (no mutation) reduces the fuzzer to blind sampling: on
        # a trivially tiny space the dry-round stop must fire. (With
        # mutation ON even small spaces keep yielding new interleavings —
        # that resistance to drying IS the subsystem, and is what
        # test_beats_blind_explore_on_saturating_space measures.)
        r1 = fuzz(tiny(), havoc=0, **kw)
        assert r1["saturated"] and r1["rounds"] < 8
        assert sum(r1["mutation_ops"].values()) == 0
        # and a campaign is replayable: same rng_seed = same coverage
        r2 = fuzz(_saturating_rt(target=2), **kw)
        r3 = fuzz(_saturating_rt(target=2), **kw)
        assert r2["new_per_round"] == r3["new_per_round"]
        assert r2["distinct_schedules"] == r3["distinct_schedules"]

    @pytest.mark.slow
    def test_crash_harvest_and_repro_replays(self):
        # the wal_kv known-red workload: the campaign harvests the crash
        # with a FULL (seed, knobs) repro that replays single-lane
        from madsim_tpu.models import wal_kv
        from madsim_tpu.models.wal_kv import make_wal_kv_runtime

        sc = Scenario()
        for t in range(6):
            sc.at(ms(150) + ms(250) * t).kill(0)
            sc.at(ms(210) + ms(250) * t).restart(0)
        rt = make_wal_kv_runtime(n_clients=2, n_ops=12, wal_cap=64,
                                 sync_wal=False, scenario=sc)
        res = fuzz(rt, max_steps=60_000, batch=16, max_rounds=2,
                   dry_rounds=3, chunk=512)
        assert res["crashes"] > 0
        assert wal_kv.CRASH_LOST_WRITE in res["crash_repros"]
        rep = res["crash_repros"][wal_kv.CRASH_LOST_WRITE]
        assert "kill node 0" in rep["script"]
        plan = KnobPlan.from_runtime(rt, dup_slots=2)
        state = plan.apply(
            rt.init_batch(np.asarray([rep["seed"]], np.uint32)),
            plan.stack([rep["knobs"]]))
        state, _ = rt.run(state, 60_000, 512)
        assert bool(np.asarray(state.crashed)[0])
        assert int(np.asarray(state.crash_code)[0]) \
            == wal_kv.CRASH_LOST_WRITE

    def test_observer_sees_fuzz_rounds(self):
        from madsim_tpu.obs import SweepObserver

        class Rec(SweepObserver):
            def __init__(self):
                self.rounds, self.done = [], []

            def on_round(self, rec):
                self.rounds.append(rec)

            def on_done(self, rec):
                self.done.append(rec)

        obs = Rec()
        fuzz(_saturating_rt(), max_steps=600, batch=16, max_rounds=2,
             dry_rounds=3, chunk=128, observer=obs)
        assert len(obs.rounds) == 2
        assert obs.rounds[0]["kind"] == "fuzz_round"
        assert "corpus_size" in obs.rounds[0]
        assert obs.done and obs.done[0]["kind"] == "done"


class TestCompileDiscipline:
    def test_warm_campaign_never_recompiles(self):
        # satellite: a full fuzz campaign (>= 3 mutation rounds, mixed
        # operators) on warm caches must trigger exactly the warm-cache
        # number of traces — ZERO. Mutation is pure operand traffic.
        from madsim_tpu.compile.cache import COMPILE_LOG
        kw = dict(max_steps=800, batch=32, max_rounds=4, dry_rounds=5,
                  chunk=256)
        fuzz(_saturating_rt(), **kw)             # warm: mutate/apply/fused
        before = COMPILE_LOG.snapshot()["traces_total"]
        res = fuzz(_saturating_rt(), **kw)       # a fresh Runtime + plan
        after = COMPILE_LOG.snapshot()["traces_total"]
        assert after == before, COMPILE_LOG.recent(8)
        assert res["rounds"] == 4                # >= 3 mutation rounds
        assert len([v for v in res["mutation_ops"].values() if v]) >= 3


@pytest.mark.slow
class TestFlagshipAcceptance:
    def test_fuzzer_vs_blind_flagship_raft_chaos(self):
        # flagship Raft chaos at B=512, equal device-dispatch budget.
        # Randomized election timeouts put every seed on a distinct
        # schedule, so blind explore() sits at the per-lane ceiling here;
        # the fuzzer must MATCH that ceiling (its mutants may not collapse
        # coverage) while it strictly dominates where blind saturates
        # (test_beats_blind_explore_on_saturating_space and
        # bench --mode search_ab measure that regime).
        import os
        import sys
        sys.path.insert(0, os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        from bench import _make_runtime
        kw = dict(max_steps=768, batch=512, max_rounds=2, dry_rounds=3,
                  chunk=256)
        blind = explore(_make_runtime(), **kw)
        res = fuzz(_make_runtime(), **kw)
        assert res["distinct_schedules"] >= blind["distinct_schedules"]
        assert res["distinct_schedules"] == res["seeds_run"]  # ceiling

    def test_zero_nudge_equivalence_shard_kv(self):
        # the third flagship of the equivalence matrix (raft and wal_kv
        # run in the fast lane, TestPct/TestMutateApply)
        from madsim_tpu.models.shard_kv import make_shard_runtime
        rt = make_shard_runtime(n_groups=2, rg=3, rc=3, n_clients=2,
                                n_ops=8, max_cfg=4, log_capacity=64)
        seeds = np.arange(16)
        plain, _ = rt.run(rt.init_batch(seeds), 4000, 512)
        zeroed = rt.run_fused(with_prio_nudge(rt.init_batch(seeds), 0),
                              4000, 512)
        _leaves_equal(plain, zeroed)
