"""Service-layer tests: @rpc dispatch, stable tags, full client-server flow
under loss (the tonic-example idiom with the macro sugar)."""

import jax.numpy as jnp
import numpy as np

from madsim_tpu import Program, Runtime, SimConfig, NetConfig, ms, sec
from madsim_tpu.harness.simtest import run_seeds
from madsim_tpu.net import rpc
from madsim_tpu.net.service import Service, rpc as rpc_method

T_RETRY = 1


class Calc(Service):
    @rpc_method
    def add(self, ctx, st, payload, when):
        st["total"] = st["total"] + jnp.where(when, payload[1], 0)
        return [st["total"]]

    @rpc_method
    def mul(self, ctx, st, payload, when):
        st["total"] = st["total"] * jnp.where(when, payload[1], 1)
        return [st["total"]]


class Driver(Program):
    """Client: add(3) x4 then mul(2), expect 24, assert via crash_if."""

    STEPS = [(Calc.add.tag, 3)] * 4 + [(Calc.mul.tag, 2)]

    def init(self, ctx):
        st = dict(ctx.state)
        st["call_id"] = rpc.new_call_id(ctx)
        rpc.call(ctx, 0, Calc.add.tag, [3], st["call_id"],
                 retry_timer_tag=T_RETRY, timeout=ms(40))
        ctx.state = st

    def _step_tag(self, i):
        tags = jnp.asarray([t for t, _ in self.STEPS], jnp.int32)
        args = jnp.asarray([a for _, a in self.STEPS], jnp.int32)
        i = jnp.clip(i, 0, len(self.STEPS) - 1)
        return tags[i], args[i]

    def on_timer(self, ctx, tag, payload):
        st = dict(ctx.state)
        retry = ((tag == T_RETRY) & (payload[0] == st["call_id"])
                 & (st["step"] < len(self.STEPS)))
        t, a = self._step_tag(st["step"])
        rpc.call(ctx, 0, t, [a], st["call_id"],
                 retry_timer_tag=T_RETRY, timeout=ms(40), when=retry)
        ctx.state = st

    def on_message(self, ctx, src, tag, payload):
        st = dict(ctx.state)
        hit = rpc.is_reply(tag) & rpc.matches(payload, st["call_id"])
        st["step"] = st["step"] + hit
        done = st["step"] >= len(self.STEPS)
        # final reply carries the computed total
        ctx.crash_if(hit & done & (payload[1] != 24), 301)
        new_id = rpc.new_call_id(ctx)
        t, a = self._step_tag(st["step"])
        rpc.call(ctx, 0, t, [a], new_id,
                 retry_timer_tag=T_RETRY, timeout=ms(40), when=hit & ~done)
        st["call_id"] = jnp.where(hit & ~done, new_id, st["call_id"])
        ctx.halt_if(hit & done & (ctx.node == 1))
        ctx.state = st


def _spec():
    z = jnp.asarray(0, jnp.int32)
    return dict(total=z, call_id=z, step=z)


class TestService:
    def test_tags_stable_and_distinct(self):
        assert Calc.add.tag != Calc.mul.tag
        assert Calc.add.tag == Calc.add.tag  # stable within process
        assert 0 < Calc.add.tag < (1 << 29)

    def test_calc_flow_clean(self):
        cfg = SimConfig(n_nodes=2, time_limit=sec(20))
        rt = Runtime(cfg, [Calc(), Driver()], _spec(), node_prog=[0, 1])
        state = run_seeds(rt, np.arange(8), max_steps=10_000)
        assert (np.asarray(state.node_state["total"])[:, 0] == 24).all()

    def test_calc_flow_under_loss(self):
        # NOTE: add/mul are not idempotent; loss-with-retry would legally
        # double-apply (at-least-once). Dedup belongs to the app layer
        # (raft_kv does it); here we only check the service still answers.
        cfg = SimConfig(n_nodes=2, time_limit=sec(20),
                        net=NetConfig(packet_loss_rate=0.2))
        rt = Runtime(cfg, [Calc(), Driver()], _spec(), node_prog=[0, 1])
        state, _ = rt.run(rt.init_batch(np.arange(8)), 20_000)
        # weaker check than the clean test: crash 301 may legitimately fire
        # for double-applied retries (at-least-once), so only require the
        # service kept answering — non-crashed halted seeds made all 5 steps
        steps = np.asarray(state.node_state["step"])[:, 1]
        halted = np.asarray(state.halted)
        crashed = np.asarray(state.crashed)
        done_ok = halted & ~crashed
        assert halted.any()
        assert (steps[done_ok] >= 5).all()
