"""The gray-failure fault plane (r17): asymmetric partitions, per-node
clock skew, slow-disk/torn-write faults, and the Percolator-lite
flagship they break.

Load-bearing properties: (1) with every new fault at its zero default,
trajectories are BIT-IDENTICAL to r16 — enforced against per-leaf golden
digests captured at r16 HEAD (tests/_grayfail_golden.py), chunked and
fused; (2) a one-way cut is directional and composes (two opposite cuts
= a full partition; only HEAL clears them); (3) skew is a deterministic
clock-RATE lever — observed `ctx.now` drifts, timer delays stretch
inversely, replay is exact; (4) slow-disk delays every emission of the
node, torn-write kills flush a random PREFIX of the unsynced tail
(synced words never tear); (5) the new ops round-trip through
describe()/parse() — the script re-entry contract; (6) the KnobPlan
picks the new dimensions up bounded (skew clipped, values bounded per
row, direction one bit, pools still confine targets); (7) Percolator-
lite is green with no faults and each gray recipe flips its
snapshot-isolation oracle red; (8) pre-r17 checkpoints are rejected
loudly (simconfig-v5).
"""

import jax.numpy as jnp
import numpy as np
import pytest

from madsim_tpu import (NODE_RANDOM, Ctx, KnobPlan, NetConfig, Program,
                        Runtime, Scenario, SimConfig, ms, sec)
from madsim_tpu.core import types as T
from madsim_tpu.harness.simtest import SimFailure, run_seeds

import _grayfail_golden as golden


# ---------------------------------------------------------------------------
# 1. bit-identical-when-disabled, against r16 captured truth
# ---------------------------------------------------------------------------

class TestEquivalenceR16:
    @pytest.mark.parametrize("workload", sorted(golden.BUILDERS))
    def test_leaf_for_leaf_vs_r16_golden(self, workload):
        # scripts/capture_golden.py froze these digests AT r16 HEAD,
        # before any r17 engine change: every r16 leaf must still hash
        # identically, chunked and fused. New leaves are allowed only by
        # name: r17's gray-failure plane (skew/disk_lat/torn, gated by
        # simconfig-v5), r18's hash_base (the frozen seed key — a
        # constant that consumes nothing, which is exactly why every
        # OTHER leaf must still match r16 bit for bit), r19's
        # dup_rate (connection-fault plane, simconfig-v6 — its own
        # golden gate lives in tests/test_connfault.py vs r18 truth),
        # r21's windowed-telemetry plane (sr_*/window_len,
        # simconfig-v7 — zero-size columns here since series_windows=0;
        # its own golden gate lives in tests/test_series.py vs r20
        # truth), and r23's attribution plane (sp_on/ev_span/sa_*/tr_qw,
        # simconfig-v8 — zero-size here since span_attr is off; its own
        # golden gate lives in tests/test_spans.py vs r22 truth).
        gold = golden.load_golden()[workload]
        got = golden.run_workload(workload)
        for runner in ("run", "run_fused"):
            missing = [k for k in gold[runner] if k not in got[runner]]
            assert not missing, (runner, missing)
            diff = [k for k in gold[runner]
                    if gold[runner][k] != got[runner][k]]
            assert not diff, (runner, diff)
            new = set(got[runner]) - set(gold[runner])
            assert new == {".skew", ".disk_lat", ".torn",
                           ".hash_base", ".dup_rate",
                           ".sr_on", ".window_len", ".sr_dispatch",
                           ".sr_busy", ".sr_qhw", ".sr_drop", ".sr_dup",
                           ".sr_complete", ".sr_slo_miss", ".sr_lat",
                           ".sr_fault",
                           ".sp_on", ".ev_span", ".sa_tail",
                           ".sa_bottleneck", ".tr_qw"}, new


# ---------------------------------------------------------------------------
# probes
# ---------------------------------------------------------------------------

_PROBE_SPEC = dict(seen=jnp.asarray(0, jnp.int32),
                   fires=jnp.asarray(0, jnp.int32))


class _TimerProbe(Program):
    """Every node re-arms a fixed-delay timer and records the observed
    ctx.now of its last firing — the skew plane's measurement bench."""

    def __init__(self, period=ms(100), fires=8):
        self.period = period
        self.max_fires = fires

    def init(self, ctx: Ctx):
        ctx.set_timer(self.period, 1, [0])

    def on_timer(self, ctx: Ctx, tag, payload):
        st = dict(ctx.state)
        st["seen"] = ctx.now
        st["fires"] = st["fires"] + 1
        ctx.set_timer(self.period, 1, [0], when=st["fires"] < self.max_fires)
        ctx.state = st


class _EchoProbe(Program):
    """Node 0 messages node 1 at boot; receivers record arrival time —
    the slow-disk plane's measurement bench."""

    def init(self, ctx: Ctx):
        ctx.send(1, 1, [0], when=ctx.node == 0)

    def on_message(self, ctx: Ctx, src, tag, payload):
        st = dict(ctx.state)
        st["seen"] = ctx.now
        ctx.state = st


def _probe_rt(prog, n=2, scenario=None, lat=ms(1), tlimit=sec(5)):
    cfg = SimConfig(n_nodes=n, time_limit=tlimit,
                    net=NetConfig(send_latency_min=lat,
                                  send_latency_max=lat))
    return Runtime(cfg, [prog], _PROBE_SPEC, scenario=scenario)


# ---------------------------------------------------------------------------
# 2. one-way partitions
# ---------------------------------------------------------------------------

class TestOneWayPartition:
    def _final_clog(self, sc, steps=60):
        rt = _probe_rt(_TimerProbe(fires=100), n=4, scenario=sc)
        return np.asarray(rt.state_at(0, steps).clog_link)[0]

    def test_directional_and_composes(self):
        sc = Scenario()
        sc.at(ms(10)).partition_oneway([0, 1], direction=0)
        cl = self._final_clog(sc)
        # A -> not-A cut, nothing else: rows 0/1 to cols 2/3 only
        want = np.zeros((4, 4), bool)
        want[np.ix_([0, 1], [2, 3])] = True
        np.testing.assert_array_equal(cl, want)
        # the reverse direction is the transpose
        sc = Scenario()
        sc.at(ms(10)).partition_oneway([0, 1], direction=1)
        np.testing.assert_array_equal(self._final_clog(sc), want.T)
        # two opposite one-way cuts COMPOSE into the full partition
        sc = Scenario()
        sc.at(ms(10)).partition_oneway([0, 1], direction=0)
        sc.at(ms(20)).partition_oneway([0, 1], direction=1)
        np.testing.assert_array_equal(self._final_clog(sc), want | want.T)

    def test_heal_clears_oneway_cuts(self):
        sc = Scenario()
        sc.at(ms(10)).partition_oneway([0, 1], direction=0)
        sc.at(ms(30)).heal()
        assert not self._final_clog(sc, steps=120).any()

    def test_oneway_cut_drops_only_cut_direction(self):
        # node 0's send to 1 vanishes under an outbound cut of {0}, but
        # 1's sends still arrive at 0 (echo both ways)
        class Both(Program):
            def init(self, ctx):
                ctx.send(1 - ctx.node, 1, [0])

            def on_message(self, ctx, src, tag, payload):
                st = dict(ctx.state)
                st["seen"] = 1 + ctx.now
                ctx.state = st

        sc = Scenario()
        sc.at(0).partition_oneway([0], direction=0)
        rt = _probe_rt(Both(), n=2, scenario=sc, tlimit=sec(1))
        fin = rt.run_fused(rt.init_batch(np.arange(8)), 200, 64)
        seen = np.asarray(fin.node_state["seen"])
        # whether the cut fires before the boots is a t=0 tie-break, so
        # assert per lane: node 1 hearing from 0 implies the cut came
        # too late for that lane — but node 0 must ALWAYS hear node 1
        assert (seen[:, 0] > 0).all(), "inbound direction must stay alive"
        assert (seen[:, 1] == 0).any(), "outbound cut must drop sends"


# ---------------------------------------------------------------------------
# 3. clock skew
# ---------------------------------------------------------------------------

class TestClockSkew:
    def test_timer_stretch_and_observed_drift(self):
        sc = Scenario()
        sc.at(0).set_skew(1, 512)        # node 1's clock runs 1.5x
        rt = _probe_rt(_TimerProbe(), scenario=sc)
        st = rt.state_at(3, 40)
        fires = np.asarray(st.node_state["fires"])[0]
        seen = np.asarray(st.node_state["seen"])[0]
        assert fires.tolist() == [8, 8]
        # node 0: 8 unstretched 100ms periods, observed = global
        assert seen[0] == 8 * ms(100)
        # node 1: its timers fire EARLIER in global time (d_eff = 50ms
        # once the skew op landed) and it OBSERVES a 1.5x clock. The
        # t=0 tie-break decides whether the FIRST period was stretched,
        # so its last fire lands at global 400ms (all 8 stretched) or
        # 450ms (first one full) — observed through the 1.5x clock:
        assert seen[1] < seen[0]
        assert int(seen[1]) in (600_000, 675_000)

    def test_skew_value_clipped_at_apply(self):
        sc = Scenario()
        sc.at(0).set_skew(0, 10_000)     # way past SKEW_CAP
        rt = _probe_rt(_TimerProbe(), scenario=sc)
        st = rt.state_at(0, 4)
        assert int(np.asarray(st.skew)[0][0]) == T.SKEW_CAP

    def test_skew_replay_deterministic(self):
        sc = Scenario()
        sc.at(ms(5)).set_skew_random(300, among=[0, 1])
        sc.at(ms(400)).set_skew_random(0, among=[0, 1])
        rt = _probe_rt(_TimerProbe(), scenario=sc)
        assert rt.check_determinism(9, 2_000)


# ---------------------------------------------------------------------------
# 4. disk faults
# ---------------------------------------------------------------------------

class TestDiskFaults:
    def test_slow_disk_delays_emissions(self):
        # arm the disk fault at boot via a deferred send: node 0 pings
        # at init; with set_disk(0) racing the boot at t=0 the delta is
        # either the full disk latency or 0 — inject at a quiet instant
        # instead: scenario op at t=0, probe send re-armed at ms(50)
        class LatePing(Program):
            def init(self, ctx):
                ctx.set_timer(ms(50), 1, [0], when=ctx.node == 0)

            def on_timer(self, ctx, tag, payload):
                ctx.send(1, 2, [0])

            def on_message(self, ctx, src, tag, payload):
                st = dict(ctx.state)
                st["seen"] = ctx.now
                ctx.state = st

        def arrival(disk_lat):
            sc = Scenario()
            if disk_lat:
                sc.at(ms(1)).set_disk(0, disk_lat)
            rt = _probe_rt(LatePing(), scenario=sc, tlimit=sec(1))
            st = rt.state_at(1, 20)
            return int(np.asarray(st.node_state["seen"])[0][1])

        base = arrival(0)
        slow = arrival(ms(40))
        assert slow - base == ms(40)

    def test_torn_kill_flushes_random_prefix(self):
        # wal_kv with sync_wal=False: nothing is ever synced, so a
        # CLEAN kill leaves dlen == 0 everywhere; a TORN kill flushes a
        # random prefix of the unsynced tail — including mid-record
        # (odd) cuts, the partially-written final record
        from madsim_tpu.models.wal_kv import SERVER, make_wal_kv_runtime

        def final_dlen(torn):
            sc = Scenario()
            sc.at(500).set_disk(SERVER, 0, torn=torn)
            sc.at(ms(60)).kill(SERVER)
            sc.at(ms(120)).restart(SERVER)
            rt = make_wal_kv_runtime(n_clients=2, n_ops=8, wal_cap=64,
                                     sync_wal=False, scenario=sc)
            fin = rt.run_fused(
                rt.init_batch(np.arange(64, dtype=np.uint32)),
                40_000, 512)
            return np.asarray(fin.node_state["fs_dlen"])[:, SERVER, 0]

        clean = final_dlen(False)
        assert (clean == 0).all()
        torn = final_dlen(True)
        assert (torn > 0).any(), "torn kill must flush some prefix"
        assert (torn % 2 == 1).any(), "some cuts must land mid-record"

    def test_disk_value_clipped_and_pooled(self):
        sc = Scenario()
        sc.at(0).set_disk_random(10 * T.DISK_LAT_CAP, among=[1])
        rt = _probe_rt(_TimerProbe(), scenario=sc)
        st = rt.state_at(0, 4)
        dl = np.asarray(st.disk_lat)[0]
        assert dl[0] == 0 and dl[1] == T.DISK_LAT_CAP


# ---------------------------------------------------------------------------
# 5. scenario round-trip (the script re-entry contract)
# ---------------------------------------------------------------------------

class TestScenarioRoundTrip:
    def test_describe_parse_identity_all_ops(self):
        cfg = SimConfig(n_nodes=4, payload_words=8, time_limit=sec(2))
        sc = Scenario()
        sc.at(ms(1)).kill_random(among=[1, 2])
        sc.at(ms(2)).partition_oneway([0, 1], direction=1)
        sc.at(ms(3)).set_skew(2, -300)
        sc.at(ms(4)).set_skew_random(128, among=[0, 3])
        sc.at(ms(5)).set_disk(1, ms(7), torn=True)
        sc.at(ms(6)).set_disk_random(0, among=[1])
        sc.at(ms(7)).set_loss(0.1)
        sc.at(ms(8)).set_latency(ms(1), ms(9))
        sc.at(ms(9)).clog_link(1, 2)
        sc.at(ms(10)).partition([2, 3])
        sc.at(ms(11)).heal()
        sc.at(ms(12)).boot(3)
        sc.at(ms(13)).restart_random()
        sc.at(ms(14)).pause(2)
        sc.at(ms(15)).clog_node_random()
        sc.at(ms(16)).halt()
        text = sc.describe()
        re = Scenario.parse(text)
        # text-level identity AND row-level identity: the re-entered
        # script must ENCODE the identical event-table rows
        assert re.describe() == text
        b1, b2 = sc.build(cfg), re.build(cfg)
        for k in b1:
            np.testing.assert_array_equal(b1[k], b2[k])

    def test_to_scenario_random_value_rows_round_trip(self):
        # KnobPlan.to_scenario bakes values into the FULL payload (no
        # payload_tail); describe() must not bit-decode them as phantom
        # pool members, and the script must still re-enter (the review
        # finding: a skew of -300 at word P-1 read as dozens of pool ids)
        import jax
        from madsim_tpu.models.percolator import make_percolator_runtime
        sc = Scenario()
        sc.at(ms(5)).set_skew_random(-300, among=[0, 1])
        sc.at(ms(6)).set_disk_random(ms(9), torn=True, among=[1])
        rt = make_percolator_runtime(scenario=sc)
        plan = KnobPlan.from_runtime(rt)
        kn = plan.base_knobs()
        text = plan.to_scenario(kn).describe()
        assert "random among [0, 1] skew=-300" in text
        assert "random among [1] lat=9000us torn=1" in text
        re = Scenario.parse(text)
        assert re.describe() == text
        # and a mutated vector still parses (values move, pools don't)
        out, _, _ = plan.mutate(plan.base_batch(8), jax.random.PRNGKey(2),
                                havoc=8)
        for i in range(8):
            t2 = plan.to_scenario(KnobPlan.lane(out, i)).describe()
            assert Scenario.parse(t2).describe() == t2

    def test_value_overlapping_pool_segment_refused(self):
        # N > 31 with a tight payload: the tail value word would land
        # inside the pool segment and bit-decode as phantom members —
        # build() refuses instead of mistargeting
        cfg = SimConfig(n_nodes=40, payload_words=2, time_limit=sec(2))
        sc = Scenario()
        sc.at(ms(1)).set_skew_random(100, among=[1])
        with pytest.raises(ValueError, match="pool segment"):
            sc.build(cfg)

    def test_value_and_pool_coexist_in_payload(self):
        cfg = SimConfig(n_nodes=4, payload_words=8, time_limit=sec(2))
        sc = Scenario()
        sc.at(ms(1)).set_skew_random(-77, among=[1, 3])
        rows = sc.build(cfg)
        assert rows["payload"][0, 0] == (1 << 1) | (1 << 3)   # pool head
        assert rows["payload"][0, 7] == -77                   # value tail

    def test_tail_overflow_raises(self):
        cfg = SimConfig(n_nodes=2, payload_words=1, time_limit=sec(2))
        sc = Scenario()
        sc.at(ms(1)).set_disk(0, ms(5), torn=True)   # needs 2 tail words
        with pytest.raises(ValueError, match="payload words"):
            sc.build(cfg)


# ---------------------------------------------------------------------------
# 6. fuzzer knob plane
# ---------------------------------------------------------------------------

def _gray_rt():
    import bench
    return bench._make_grayfail_runtime("mix", trace_cap=0, n_ops=8)


class TestKnobPlan:
    def test_guards_and_bounds(self):
        import jax
        rt = _gray_rt()
        plan = KnobPlan.from_runtime(rt)
        assert plan.val_ok.sum() >= 6          # skew x4 + disk x4 rows
        assert plan.dir_ok.sum() == 1
        assert plan.torn_ok.sum() >= 2
        # base knobs read the encoded values back
        kb = plan.base_knobs()
        assert (np.abs(kb["row_val"][plan.val_ok])
                <= np.maximum(T.SKEW_CAP, T.DISK_LAT_CAP)).all()
        # mutants stay in bounds and the new operator actually runs
        out, hist, _ = plan.mutate(plan.base_batch(64),
                                   jax.random.PRNGKey(0), havoc=6)
        assert int(hist[-1]) > 0, "fault_perturb never applied"
        rv = np.asarray(out["row_val"])
        assert (rv[:, plan.val_ok] >= plan.val_lo[plan.val_ok]).all()
        assert (rv[:, plan.val_ok] <= plan.val_hi[plan.val_ok]).all()
        assert set(np.asarray(out["row_flag"]).ravel().tolist()) <= {0, 1}

    def test_apply_clips_hand_edited_values(self):
        rt = _gray_rt()
        plan = KnobPlan.from_runtime(rt)
        kn = plan.base_knobs()
        kn["row_val"] = np.full(plan.R, 10**9, np.int32)   # way out
        kn["row_flag"] = np.full(plan.R, 7, np.int32)      # not a bit
        state = plan.apply(rt.init_batch(np.arange(2, dtype=np.uint32)),
                           KnobPlan.stack([kn] * 2))
        pay = np.asarray(state.t_payload)[0]
        P = rt.cfg.payload_words
        rows = slice(plan.n_init, plan.n_init + plan.R)
        vals = pay[rows, P - 1][plan.val_ok]
        assert (vals <= plan.val_hi[plan.val_ok]).all()
        src = np.asarray(state.t_src)[0][rows][plan.dir_ok]
        assert set(src.tolist()) <= {0, 1}

    def test_pool_confinement_still_holds(self):
        # an out-of-pool target on a pool-restricted fault row snaps
        # back to NODE_RANDOM (the r9 contract, extended to the new ops)
        from madsim_tpu.models.percolator import make_percolator_runtime
        sc = Scenario()
        sc.at(ms(5)).set_skew_random(200, among=[0, 1])
        rt = make_percolator_runtime(scenario=sc)
        plan = KnobPlan.from_runtime(rt)
        kn = plan.base_knobs()
        r = int(np.nonzero(plan.base["op"] == T.OP_SET_SKEW)[0][0])
        kn["row_node"] = kn["row_node"].copy()
        kn["row_node"][r] = 3                  # a client — out of pool
        state = plan.apply(rt.init_batch(np.arange(1, dtype=np.uint32)),
                           KnobPlan.stack([kn]))
        tnode = np.asarray(state.t_node)[0][plan.n_init + r]
        assert tnode == NODE_RANDOM


# ---------------------------------------------------------------------------
# 7. the Percolator-lite flagship
# ---------------------------------------------------------------------------

class TestPercolator:
    def test_green_without_faults(self):
        from madsim_tpu.models.percolator import make_percolator_runtime
        rt = make_percolator_runtime()
        state = run_seeds(rt, np.arange(24), max_steps=60_000)
        done = np.asarray(state.node_state["c_done"])[:, 2:]
        assert (done == 1).all()

    def test_slow_disk_recipe_fractures_snapshots(self):
        from madsim_tpu.models.percolator import (CRASH_SNAPSHOT,
                                                  make_percolator_runtime)
        from madsim_tpu.runtime import chaos
        sc = chaos.slow_disk(ms(100), ms(20), ms(700), node=0)
        rt = make_percolator_runtime(n_ops=12, scenario=sc)
        with pytest.raises(SimFailure) as ei:
            run_seeds(rt, np.arange(32), max_steps=80_000)
        assert ei.value.code == CRASH_SNAPSHOT

    @pytest.mark.slow
    def test_every_gray_recipe_goes_red(self):
        import bench
        from madsim_tpu.models.percolator import CRASH_SNAPSHOT
        for recipe in ("skew", "asym", "disk", "torn"):
            rt = bench._make_grayfail_runtime(recipe, trace_cap=0)
            fin = rt.run_fused(
                rt.init_batch(np.arange(192, dtype=np.uint32)),
                80_000, 512)
            codes = np.asarray(fin.crash_code)
            assert (codes == CRASH_SNAPSHOT).any(), recipe


# ---------------------------------------------------------------------------
# 8. migration: pre-r17 checkpoints are rejected
# ---------------------------------------------------------------------------

class TestCheckpointMigration:
    def test_pre_r17_checkpoint_rejected_by_leaf_count(self, tmp_path):
        # the MIGRATION r17 contract: a pre-r17 checkpoint (no skew/
        # disk_lat/torn leaves — 3 fewer) fails load() loudly on the
        # leaf count, not by silent misalignment
        from madsim_tpu.runtime import checkpoint
        rt = _probe_rt(_TimerProbe())
        st = rt.init_batch(np.arange(2))
        p = str(tmp_path / "ck.npz")
        checkpoint.save(p, st)
        with np.load(p) as z:
            leaves = {k: z[k] for k in z.files}
        n = len([k for k in leaves if k.startswith("leaf_")])
        stripped = {k: v for k, v in leaves.items()
                    if not k.startswith("leaf_")}
        for i in range(n - 3):
            stripped[f"leaf_{i}"] = leaves[f"leaf_{i}"]
        p2 = str(tmp_path / "old.npz")
        np.savez_compressed(p2, **stripped)
        with pytest.raises(ValueError, match="leaves"):
            checkpoint.load(p2, st)

    def test_signature_is_current(self):
        # r17 introduced v5; the r19 connection-fault plane bumped it to
        # v6, the r21 windowed-telemetry plane to v7, and the r23
        # attribution plane to v8 — test_spans.py owns the
        # authoritative version assertion
        cfg = SimConfig(n_nodes=2)
        assert cfg.structural_signature()[0] == "simconfig-v8"
