"""Shared harness for the r21 bit-identical-when-disabled contract.

The windowed telemetry plane (r21) added engine machinery — per-window
dispatch/queue/busy/latency series leaves, the dynamic `window_len`
operand, the recovery oracle — that is compiled out at the default
`series_windows=0` and masked to identity when compiled in but no lane
records. The contract is that a workload never enabling the plane
produces trajectories BIT-IDENTICAL to r20, leaf for leaf, chunked and
fused.

Same frozen workload builders as the r17/r19 harnesses
(_grayfail_golden — the canonical engine-equivalence workloads); digests
were captured AT r20 HEAD by scripts/capture_golden.py into
tests/data/golden_r20_leaves.json, before any r21 engine change landed.
Every r20 leaf must still exist and hash identically — the only new
leaves the r21 plane may add are the series plane's own
(`.window_len`, `.sr_on`, and the zero-size `sr_*` columns the
simconfig-v7 signature gates).
"""

from __future__ import annotations

import os

import _grayfail_golden as _g

GOLDEN_PATH = os.path.join(os.path.dirname(__file__), "data",
                           "golden_r20_leaves.json")

# the frozen definition is shared with the r17/r19 harnesses — one set
# of engine workloads, three captured truths (r16, r18, r20)
RUNS = _g.RUNS
BUILDERS = _g.BUILDERS
leaf_digests = _g.leaf_digests
run_workload = _g.run_workload


def capture(path: str = GOLDEN_PATH) -> dict:
    return _g.capture(path)


def load_golden(path: str = GOLDEN_PATH) -> dict:
    with open(path) as f:
        import json
        return json.load(f)
