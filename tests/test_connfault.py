"""The connection-fault plane (r19): TCP-grade transport faults, peer
incarnations, and the exactly-once flagship under connection churn.

Load-bearing properties: (1) with every new fault at its zero default,
trajectories are BIT-IDENTICAL to r18 — enforced against per-leaf golden
digests captured at r18 HEAD (tests/_connfault_golden.py), chunked and
fused; (2) OP_RESET_PEER tears conn/stream state touching the target on
BOTH sides and bumps both incarnation epochs, where a kill deliberately
leaves the survivor half-open; (3) OP_SET_DUP redelivers dispatched
messages at the knob-plane rate, deterministically per seed; (4) the
incarnation guards reject stale RSTs, stale segments, and stale ACKs,
adopt missed resets, and make a post-reset retransmit timer a no-op —
each with the pre-r19 behavior compilable as the red control; (5) the
new ops round-trip through describe()/parse(); (6) the KnobPlan picks
the new dimensions up bounded with zero warm-campaign recompiles;
(7) minipg is green on the no-fault baseline AND under the reset+dup
storm with guards on, and measurably red with guards compiled to the
pre-r19 behavior; (8) pre-r19 checkpoints are rejected loudly
(simconfig-v6).
"""

import os
import sys

import jax.numpy as jnp
import numpy as np
import pytest

from madsim_tpu import (NODE_RANDOM, Ctx, KnobPlan, NetConfig, Program,
                        Runtime, Scenario, SimConfig, ms, sec)
from madsim_tpu.core import prng, types as T
from madsim_tpu.net import conn, stream

import _connfault_golden as golden

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


# ---------------------------------------------------------------------------
# 1. bit-identical-when-disabled, against r18 captured truth
# ---------------------------------------------------------------------------

class TestEquivalenceR18:
    @pytest.mark.parametrize("workload", sorted(golden.BUILDERS))
    def test_leaf_for_leaf_vs_r18_golden(self, workload):
        # scripts/capture_golden.py froze these digests AT r18 HEAD,
        # before any r19 engine change: every r18 leaf must still hash
        # identically, chunked and fused. The ONLY new leaf the plane
        # may add is dup_rate (gated by simconfig-v6); in particular the
        # dup decision/delay draws must consume nothing at rate 0 —
        # they ride keys folded off the already-consumed scheduler key.
        gold = golden.load_golden()[workload]
        got = golden.run_workload(workload)
        for runner in ("run", "run_fused"):
            missing = [k for k in gold[runner] if k not in got[runner]]
            assert not missing, (runner, missing)
            diff = [k for k in gold[runner]
                    if gold[runner][k] != got[runner][k]]
            assert not diff, (runner, diff)
            assert set(got[runner]) - set(gold[runner]) \
                == {".dup_rate",
                    ".sr_on", ".window_len", ".sr_dispatch", ".sr_busy",
                    ".sr_qhw", ".sr_drop", ".sr_dup", ".sr_complete",
                    ".sr_slo_miss", ".sr_lat", ".sr_fault",
                    ".sp_on", ".ev_span", ".sa_tail",
                    ".sa_bottleneck", ".tr_qw"}, \
                (runner, set(got[runner]) - set(gold[runner]))


# ---------------------------------------------------------------------------
# probes
# ---------------------------------------------------------------------------

class _CountPing(Program):
    """Node 0 sends ONE message to node 1 at ms(2); node 1 counts every
    delivery — the duplicate-delivery plane's measurement bench."""

    def init(self, ctx: Ctx):
        ctx.set_timer(ms(2), 1, [0], when=ctx.node == 0)

    def on_timer(self, ctx: Ctx, tag, payload):
        ctx.send(1, 7, [0])

    def on_message(self, ctx: Ctx, src, tag, payload):
        st = dict(ctx.state)
        st["seen"] = st["seen"] + 1
        ctx.state = st


_COUNT_SPEC = dict(seen=jnp.asarray(0, jnp.int32))


def _count_rt(scenario=None, tlimit=sec(1)):
    cfg = SimConfig(n_nodes=2, time_limit=tlimit,
                    net=NetConfig(send_latency_min=ms(1),
                                  send_latency_max=ms(4)))
    return Runtime(cfg, [_CountPing()], _COUNT_SPEC, scenario=scenario)


def _unit_ctx(n=2, payload_words=8):
    cfg = SimConfig(n_nodes=n, payload_words=payload_words)
    return Ctx(cfg, jnp.asarray(0, jnp.int32), jnp.asarray(0, jnp.int32),
               prng.seed_key(7), {})


# ---------------------------------------------------------------------------
# 2. OP_SET_DUP — duplicate delivery at the datagram layer
# ---------------------------------------------------------------------------

class TestDupStorm:
    def _seen(self, rate):
        sc = Scenario()
        if rate:
            sc.at(500).set_dup(1, rate)
        rt = _count_rt(sc)
        fin = rt.run_fused(rt.init_batch(np.arange(64, dtype=np.uint32)),
                           4_000, 256)
        return np.asarray(fin.node_state["seen"])[:, 1]

    def test_zero_rate_is_exactly_once(self):
        assert (self._seen(0.0) == 1).all()

    def test_storm_redelivers_byte_identical_payload(self):
        seen = self._seen(0.8)
        assert (seen >= 1).all()
        assert (seen >= 2).any(), "a 0.8 dup rate must redeliver somewhere"
        # geometric storm: some lane should chain more than one copy
        assert seen.max() >= 3

    def test_rate_clipped_at_apply(self):
        sc = Scenario()
        sc.at(500).set_dup(1, 5.0)          # way past the cap
        rt = _count_rt(sc)
        st = rt.state_at(0, 4)
        assert int(np.asarray(st.dup_rate)[0][1]) == T.DUP_RATE_CAP

    def test_dup_replay_deterministic(self):
        sc = Scenario()
        sc.at(500).set_dup_random(0.7, among=[0, 1])
        rt = _count_rt(sc)
        assert rt.check_determinism(11, 4_000)


# ---------------------------------------------------------------------------
# 3. OP_RESET_PEER — both-sides teardown vs the kill's half-open
# ---------------------------------------------------------------------------

class TestResetPeer:
    def _final(self, reset: bool):
        from madsim_tpu.models.minipg import make_minipg_runtime
        sc = Scenario()
        if reset:
            sc.at(ms(400)).reset_peer(0)
        else:
            sc.at(ms(400)).kill(0)
        sc.at(ms(401)).halt()      # sample before watchdog recovery
        rt = make_minipg_runtime(n_clients=2, n_txns=50, scenario=sc)
        return rt.run_fused(rt.init_batch(np.arange(8, dtype=np.uint32)),
                            20_000, 512)

    def test_reset_tears_both_sides_and_bumps_epochs(self):
        fin = self._final(True)
        cn = np.asarray(fin.node_state["cn_state"])
        ep = np.asarray(fin.node_state["cn_epoch"])
        sx = np.asarray(fin.node_state["sx_seq"])
        assert (cn[:, 0, 1:] == conn.CLOSED).all()
        assert (cn[:, 1:, 0] == conn.CLOSED).all()
        assert (ep[:, 0, 1:] >= 1).all() and (ep[:, 1:, 0] >= 1).all()
        # stream sequence space RESTARTED on every touched pairing: the
        # server (quiescent between reset and halt) reads exactly 0; a
        # client may already have pushed the first send of the fresh
        # incarnation into the sampling window, so "restarted" there
        # means at most one post-wipe send — against the dozens of
        # frames 50 pipelined txns had in flight before the tear
        assert (sx[:, 0, 1:] == 0).all()
        assert (sx[:, 1:, 0] <= 1).all()

    def test_kill_leaves_survivors_half_open(self):
        fin = self._final(False)
        cn = np.asarray(fin.node_state["cn_state"])
        # the killed server's own rows reset at restart; the SURVIVORS
        # keep ESTABLISHED state toward the corpse — the half-open
        # regime only a reset clears (conn.py's documented contract)
        assert (cn[:, 1:, 0] == conn.ESTABLISHED).any()

    def test_inert_without_conn_state(self):
        # a model with no conn/stream leaves: the op resolves, dispatches
        # and does nothing — no crash, no oops
        sc = Scenario()
        sc.at(500).reset_peer_random()
        rt = _count_rt(sc)
        fin = rt.run_fused(rt.init_batch(np.arange(8, dtype=np.uint32)),
                           4_000, 256)
        assert not np.asarray(fin.crashed).any()
        assert (np.asarray(fin.node_state["seen"])[:, 1] == 1).all()


# ---------------------------------------------------------------------------
# 4. incarnation guards (unit level, both worlds' eager path)
# ---------------------------------------------------------------------------

def _established_pair():
    st = dict(**conn.conn_state(2), **stream.stream_state(2, window=4))
    st["cn_state"] = st["cn_state"].at[1].set(conn.ESTABLISHED)
    st["cn_epoch"] = st["cn_epoch"].at[1].set(3)
    st["st_epoch"] = st["st_epoch"].at[1].set(3)
    return st


class TestIncarnationGuards:
    def test_stale_rst_rejected(self):
        # satellite fix: a delayed RST from a pre-reset incarnation must
        # NOT close the successor connection
        ctx = _unit_ctx()
        st = _established_pair()
        conn.on_message(ctx, st, 1, conn.TAG_RST, jnp.asarray([2] + [0] * 7))
        assert int(st["cn_state"][1]) == conn.ESTABLISHED
        assert int(st["cn_epoch"][1]) == 3
        # the CURRENT incarnation's RST does tear it down (and bumps)
        _, _, rst = conn.on_message(ctx, st, 1, conn.TAG_RST,
                                    jnp.asarray([3] + [0] * 7))
        assert bool(rst) and int(st["cn_state"][1]) == conn.CLOSED
        assert int(st["cn_epoch"][1]) == 4

    def test_stale_rst_closes_without_guard(self):
        # the pre-r19 red control: ANY RST closes an ESTABLISHED conn
        ctx = _unit_ctx()
        st = _established_pair()
        _, _, rst = conn.on_message(ctx, st, 1, conn.TAG_RST,
                                    jnp.asarray([2] + [0] * 7),
                                    epoch_guard=False)
        assert bool(rst) and int(st["cn_state"][1]) == conn.CLOSED

    def test_stale_segment_dropped_fresh_adopted(self):
        ctx = _unit_ctx()
        st = _established_pair()

        def data(seq, ep, val):
            return jnp.asarray([seq, ep, val] + [0] * 5)

        # stale epoch: no buffer, no delivery, no ack, no window motion
        vals, mask = stream.on_message(ctx, st, 1, stream.TAG_DATA,
                                       data(0, 2, 41))
        assert not bool(mask.any())
        assert int(st["sr_next"][1]) == 0 and len(ctx._sends) == 0
        # current epoch: delivered + acked
        vals, mask = stream.on_message(ctx, st, 1, stream.TAG_DATA,
                                       data(0, 3, 42))
        assert bool(mask[0]) and int(vals[0]) == 42
        assert int(st["sr_next"][1]) == 1 and len(ctx._sends) == 1
        # NEWER epoch (a reset this side missed): adopt — wipe, jump,
        # deliver into the fresh window
        vals, mask = stream.on_message(ctx, st, 1, stream.TAG_DATA,
                                       data(0, 5, 43))
        assert bool(mask[0]) and int(vals[0]) == 43
        assert int(st["st_epoch"][1]) == 5 and int(st["sr_next"][1]) == 1

    def test_stale_segment_accepted_without_guard(self):
        ctx = _unit_ctx()
        st = _established_pair()
        vals, mask = stream.on_message(ctx, st, 1, stream.TAG_DATA,
                                       jnp.asarray([0, 2, 666] + [0] * 5),
                                       epoch_guard=False)
        # pre-r19: the stale segment lands in the fresh window — exactly
        # the corruption the flagship's red direction measures
        assert bool(mask[0]) and int(vals[0]) == 666

    def test_stale_ack_cannot_slide_window(self):
        ctx = _unit_ctx()
        st = _established_pair()
        stream.send(ctx, st, 1, 10)
        stream.send(ctx, st, 1, 11)
        assert int(st["sx_seq"][1]) == 2
        stream.on_message(ctx, st, 1, stream.TAG_ACK,
                          jnp.asarray([2, 2] + [0] * 6))   # stale epoch
        assert int(st["sx_base"][1]) == 0
        stream.on_message(ctx, st, 1, stream.TAG_ACK,
                          jnp.asarray([2, 3] + [0] * 6))   # current
        assert int(st["sx_base"][1]) == 2

    def test_retransmit_after_reset_is_noop(self):
        # satellite fix: a retransmit timer armed before reset_peer tore
        # the fabric must send NOTHING for the new incarnation
        ctx = _unit_ctx()
        st = _established_pair()
        stream.send(ctx, st, 1, 10)
        stream.send(ctx, st, 1, 11)
        n_before = len(ctx._sends)
        stream.reset_peer(st, 1)
        assert int(st["st_epoch"][1]) == 4
        stream.retransmit(ctx, st, 1, when=True)
        assert len(ctx._sends) == n_before, \
            "stale retransmit injected segments after reset_peer"
        # and frames the NEW incarnation does send stamp the new epoch
        stream.send(ctx, st, 1, 12)
        assert int(ctx._sends[-1]["payload"][1]) == 4

    def test_frames_stamp_current_epoch(self):
        ctx = _unit_ctx()
        st = _established_pair()
        stream.send(ctx, st, 1, 99)
        sent = ctx._sends[-1]["payload"]
        assert int(sent[0]) == 0 and int(sent[1]) == 3

    def test_duplicate_syn_does_not_reopen_window(self):
        # review finding (r19): a network-DUPLICATED SYN of the current
        # generation — exactly what OP_SET_DUP produces — must be a
        # true no-op: re-wiping the fabric at the same epoch would
        # reopen the receive window and deliver already-delivered
        # same-epoch segments AGAIN, breaking exactly-once with the
        # guards ON
        ctx = _unit_ctx()
        st = dict(**conn.conn_state(2), **stream.stream_state(2, window=4))
        conn.listen(ctx, st)
        syn = jnp.asarray([3] + [0] * 7)
        conn.on_message(ctx, st, 1, conn.TAG_SYN, syn)
        assert int(st["st_epoch"][1]) == 3
        data = jnp.asarray([0, 3, 42] + [0] * 5)
        vals, mask = stream.on_message(ctx, st, 1, stream.TAG_DATA, data)
        assert bool(mask[0]) and int(st["sr_next"][1]) == 1
        # the dup-storm redelivers the SYN: same epoch, no wipe
        conn.on_message(ctx, st, 1, conn.TAG_SYN, syn)
        assert int(st["sr_next"][1]) == 1, "duplicate SYN reopened window"
        # the peer's Go-Back-N retransmit of seq 0 must NOT deliver again
        vals, mask = stream.on_message(ctx, st, 1, stream.TAG_DATA, data)
        assert not bool(mask.any()), "same-epoch segment delivered twice"

    def test_handshake_negotiates_past_torn_generation(self):
        # listener side: a SYN proposing epoch 5 against a local counter
        # of 3 accepts at 5 and echoes it; the stream fabric re-bases
        ctx = _unit_ctx()
        st = _established_pair()
        conn.listen(ctx, st)
        accept, _, _ = conn.on_message(ctx, st, 1, conn.TAG_SYN,
                                       jnp.asarray([5] + [0] * 7))
        assert bool(accept)
        assert int(st["cn_epoch"][1]) == 5
        assert int(st["st_epoch"][1]) == 5
        syn_ack = ctx._sends[-1]
        assert int(syn_ack["tag"]) == conn.TAG_SYN_ACK
        assert int(syn_ack["payload"][0]) == 5


# ---------------------------------------------------------------------------
# 5. scenario round-trip (the script re-entry contract)
# ---------------------------------------------------------------------------

class TestScenarioRoundTrip:
    def test_describe_parse_identity_full_op_table(self):
        cfg = SimConfig(n_nodes=4, payload_words=8, time_limit=sec(2))
        sc = Scenario()
        sc.at(ms(1)).reset_peer(2)
        sc.at(ms(2)).reset_peer_random(among=[0, 1])
        sc.at(ms(3)).set_dup(1, 0.25)
        sc.at(ms(4)).set_dup_random(0.5, among=[2, 3])
        sc.at(ms(5)).set_skew(2, -300)
        sc.at(ms(6)).set_disk(1, ms(7), torn=True)
        sc.at(ms(7)).kill_random(among=[1, 2])
        sc.at(ms(8)).partition_oneway([0, 1], direction=1)
        sc.at(ms(9)).set_loss(0.1)
        sc.at(ms(10)).heal()
        sc.at(ms(11)).halt()
        text = sc.describe()
        re = Scenario.parse(text)
        assert re.describe() == text
        b1, b2 = sc.build(cfg), re.build(cfg)
        for k in b1:
            np.testing.assert_array_equal(b1[k], b2[k])

    def test_to_scenario_mutants_still_parse(self):
        import jax
        import bench
        rt = bench._make_connfault_runtime("mix", trace_cap=0)
        plan = KnobPlan.from_runtime(rt)
        text = plan.to_scenario(plan.base_knobs()).describe()
        assert "set_dup" in text and "reset_peer" in text
        assert Scenario.parse(text).describe() == text
        out, _, _ = plan.mutate(plan.base_batch(8), jax.random.PRNGKey(2),
                                havoc=8)
        for i in range(8):
            t2 = plan.to_scenario(KnobPlan.lane(out, i)).describe()
            assert Scenario.parse(t2).describe() == t2

    def test_recipe_class_is_conn_fault(self):
        from madsim_tpu.runtime import chaos
        from madsim_tpu.runtime.scenario import row_recipe_class
        assert row_recipe_class(T.OP_RESET_PEER) == "conn_fault"
        assert row_recipe_class(T.OP_SET_DUP) == "conn_fault"
        sc = chaos.retransmit_storm(ms(5), 0.3, ms(500), node=0)
        sc = chaos.slow_disk(ms(10), ms(5), ms(400), node=0, sc=sc)
        # conn_fault outranks the gray families by precedence
        assert sc.recipe_class() == "conn_fault"


# ---------------------------------------------------------------------------
# 6. fuzzer knob plane
# ---------------------------------------------------------------------------

class TestKnobPlan:
    def test_bounds_and_pools(self):
        import jax
        import bench
        rt = bench._make_connfault_runtime("mix", trace_cap=0)
        plan = KnobPlan.from_runtime(rt)
        dup_rows = plan.base["op"] == T.OP_SET_DUP
        rp_rows = plan.base["op"] == T.OP_RESET_PEER
        assert dup_rows.sum() >= 3 and rp_rows.sum() >= 5
        assert plan.val_ok[dup_rows].all()
        assert (plan.val_hi[dup_rows] == T.DUP_RATE_CAP).all()
        assert plan.node_ok[rp_rows].all()
        out, hist, _ = plan.mutate(plan.base_batch(64),
                                   jax.random.PRNGKey(0), havoc=6)
        rv = np.asarray(out["row_val"])
        assert (rv[:, plan.val_ok] >= plan.val_lo[plan.val_ok]).all()
        assert (rv[:, plan.val_ok] <= plan.val_hi[plan.val_ok]).all()
        assert int(hist[-1]) > 0, "fault_perturb never applied"

    def test_apply_clips_hand_edited_rate(self):
        import bench
        rt = bench._make_connfault_runtime("mix", trace_cap=0)
        plan = KnobPlan.from_runtime(rt)
        kn = plan.base_knobs()
        kn["row_val"] = np.full(plan.R, 10**9, np.int32)
        state = plan.apply(rt.init_batch(np.arange(2, dtype=np.uint32)),
                           KnobPlan.stack([kn] * 2))
        pay = np.asarray(state.t_payload)[0]
        P = rt.cfg.payload_words
        rows = slice(plan.n_init, plan.n_init + plan.R)
        dup_rows = plan.base["op"] == T.OP_SET_DUP
        assert (pay[rows, P - 1][dup_rows] <= T.DUP_RATE_CAP).all()

    def test_warm_campaign_never_recompiles(self):
        # the TestCompileDiscipline pattern over the NEW knob rows: a
        # warm fuzz campaign whose scenario carries reset_peer/set_dup
        # rows must add ZERO traces — mutation stays operand traffic
        from madsim_tpu import fuzz
        from madsim_tpu.compile.cache import COMPILE_LOG
        import bench
        kw = dict(max_steps=2_000, batch=16, max_rounds=3, dry_rounds=4,
                  chunk=256)
        fuzz(bench._make_connfault_runtime("mix", trace_cap=0), **kw)
        before = COMPILE_LOG.snapshot()["traces_total"]
        fuzz(bench._make_connfault_runtime("mix", trace_cap=0), **kw)
        after = COMPILE_LOG.snapshot()["traces_total"]
        assert after == before, COMPILE_LOG.recent(8)


# ---------------------------------------------------------------------------
# 7. the exactly-once flagship under connection churn
# ---------------------------------------------------------------------------

class TestFlagship:
    def test_green_no_fault_baseline(self):
        from madsim_tpu.models.minipg import make_minipg_runtime
        rt = make_minipg_runtime(n_clients=2, n_txns=4)
        fin = rt.run_fused(
            rt.init_batch(np.arange(48, dtype=np.uint32)), 60_000, 512)
        done = np.asarray(fin.node_state["c_done"])[:, 1:]
        assert (done == 1).all()
        assert not np.asarray(fin.crashed).any()

    def test_green_under_churn_with_guards(self):
        import bench
        rt = bench._make_connfault_runtime("mix", guard=True)
        fin = rt.run_fused(
            rt.init_batch(np.arange(48, dtype=np.uint32)), 120_000, 512)
        done = np.asarray(fin.node_state["c_done"])[:, 1:]
        assert (done == 1).all()
        assert not np.asarray(fin.crashed).any()

    def test_red_without_guards(self):
        import bench
        rt = bench._make_connfault_runtime("mix")      # guards OFF
        fin = rt.run_fused(
            rt.init_batch(np.arange(48, dtype=np.uint32)), 120_000, 512)
        crashed = np.asarray(fin.crashed)
        assert crashed.any(), \
            "pre-r19 transport must corrupt under the reset+dup storm"
        # the observed failure is stale-segment corruption surfacing
        # through the client's own oracles, not an engine artifact
        codes = np.asarray(fin.crash_code)[crashed]
        assert (codes > 0).any(), codes

    @pytest.mark.slow
    def test_red_opens_replaying_causal_bucket(self):
        import shutil
        import tempfile
        import bench
        from madsim_tpu import fuzz, replay_bucket
        tmp = tempfile.mkdtemp(prefix="connfault_bucket_")
        try:
            rt = bench._make_connfault_runtime("mix")
            res = fuzz(rt, max_steps=30_000, batch=64, max_rounds=3,
                       dry_rounds=4, chunk=512, corpus_dir=tmp)
            assert res["buckets_total"] >= 1, res
            opened = res["buckets_opened"]
            assert opened
            crashed, code, _ = replay_bucket(rt, tmp, opened[0], 30_000)
            assert crashed, (opened[0], code)
        finally:
            shutil.rmtree(tmp, ignore_errors=True)

    @pytest.mark.slow
    def test_recovery_recipes_green_with_guards(self):
        import bench
        for recipe in ("reset", "dup", "half"):
            rt = bench._make_connfault_runtime(recipe)
            fin = rt.run_fused(
                rt.init_batch(np.arange(48, dtype=np.uint32)),
                120_000, 512)
            done = np.asarray(fin.node_state["c_done"])[:, 1:]
            assert (done == 1).all(), recipe
            assert not np.asarray(fin.crashed).any(), recipe


# ---------------------------------------------------------------------------
# 8. migration: pre-r19 checkpoints are rejected
# ---------------------------------------------------------------------------

class TestCheckpointMigration:
    def test_pre_r19_checkpoint_rejected_by_leaf_count(self, tmp_path):
        from madsim_tpu.runtime import checkpoint
        rt = _count_rt()
        st = rt.init_batch(np.arange(2))
        p = str(tmp_path / "ck.npz")
        checkpoint.save(p, st)
        with np.load(p) as z:
            leaves = {k: z[k] for k in z.files}
        n = len([k for k in leaves if k.startswith("leaf_")])
        stripped = {k: v for k, v in leaves.items()
                    if not k.startswith("leaf_")}
        for i in range(n - 1):       # drop one leaf: the r19 dup_rate
            stripped[f"leaf_{i}"] = leaves[f"leaf_{i}"]
        p2 = str(tmp_path / "old.npz")
        np.savez_compressed(p2, **stripped)
        with pytest.raises(ValueError, match="leaves"):
            checkpoint.load(p2, st)

    def test_signature_is_current(self):
        # v6 (r19) was bumped to v7 by the r21 windowed-telemetry
        # plane and to v8 by the r23 attribution plane —
        # test_spans.py owns the authoritative assertion
        cfg = SimConfig(n_nodes=2)
        assert cfg.structural_signature()[0] == "simconfig-v8"
