"""Aux subsystems: checkpoint/resume, trace formatting, divergence finder,
config hashing (SURVEY.md §5 parity)."""

import os

import numpy as np
import pytest

from madsim_tpu import Program, Runtime, SimConfig, NetConfig, ms, sec
from madsim_tpu.harness.determinism import find_divergence
from madsim_tpu.models.pingpong import PingPong, state_spec
from madsim_tpu.runtime import checkpoint
from madsim_tpu.runtime.trace import format_trace
from madsim_tpu.core import types as T


def _rt(target=20):
    cfg = SimConfig(n_nodes=3, time_limit=sec(30))
    return Runtime(cfg, [PingPong(3, target=target)], state_spec())


class TestCheckpoint:
    def test_save_resume_matches_straight_run(self, tmp_path):
        rt = _rt()
        seeds = np.arange(16)
        # straight run
        full, _ = rt.run(rt.init_batch(seeds), 4000)
        # run half, checkpoint, reload, resume
        half, _ = rt.run(rt.init_batch(seeds), 512, chunk=512)
        p = str(tmp_path / "ckpt.npz")
        checkpoint.save(p, half)
        loaded = checkpoint.load(p, rt.init_batch(seeds))
        resumed, _ = rt.run(loaded, 4000)
        assert (rt.fingerprints(full) == rt.fingerprints(resumed)).all()

    def test_load_rejects_wrong_shape(self, tmp_path):
        rt = _rt()
        s = rt.init_batch(np.arange(4))
        p = str(tmp_path / "ckpt.npz")
        checkpoint.save(p, s)
        with pytest.raises(ValueError):
            checkpoint.load(p, rt.init_batch(np.arange(8)))


class TestTrace:
    def test_format_trace_renders_events(self):
        rt = _rt(target=3)
        state, events = rt.run_single(5, 2000, collect_events=True)
        lines = format_trace(events, 0)
        assert len(lines) > 10
        assert any("SUPER" in l and "INIT" in l for l in lines)
        assert any("MSG" in l for l in lines)
        assert any("TIMER" in l for l in lines)
        # time filter drops early records
        filtered = format_trace(events, 0, time_start=T.ms(5))
        assert len(filtered) < len(lines)


class TestDivergence:
    def test_no_divergence_on_deterministic_program(self):
        rt = _rt(target=5)
        assert find_divergence(rt, seed=3, max_steps=2000) is None

    def test_state_at_matches_chunked_run(self):
        # time travel lands on the exact step regardless of chunking:
        # state_at(seed, k) == running k steps in one arbitrary chunk
        rt = _rt(target=8)
        for k in (1, 37, 100):
            direct, _ = rt.run(rt.init_single(3), max_steps=k, chunk=k)
            tt = rt.state_at(3, k)
            assert rt.fingerprints(direct)[0] == rt.fingerprints(tt)[0], k

    def test_binary_search_localizes_exact_step(self):
        # red path with a duck-typed runtime whose "replica B" (every odd
        # runner call — find_divergence alternates A,B strictly) perturbs
        # state while executing step K: the bisection must name exactly
        # step K and return its event, never touching donated buffers
        import jax.numpy as jnp

        K = 37
        calls = {"n": 0}

        def runner(state, chunk):
            is_b = calls["n"] % 2 == 1
            calls["n"] += 1
            step = int(state["step"][0])
            x = state["x"]
            if is_b and step <= K < step + chunk:
                x = x + 1
            ev = dict(step=jnp.arange(step, step + chunk,
                                      dtype=jnp.int32)[:, None])
            return dict(x=x, step=state["step"] + chunk), ev

        class FakeRT:
            _run_chunk = {True: runner}

            def init_single(self, seed):
                return dict(x=jnp.zeros((1,), jnp.int32),
                            step=jnp.zeros((1,), jnp.int32))

        out = find_divergence(FakeRT(), seed=0, max_steps=64, probe=64)
        assert out is not None and out["step"] == K, out
        assert int(out["event"]["step"]) == K


class TestInterval:
    def test_missed_tick_behaviors(self):
        from madsim_tpu.utils.interval import BURST, DELAY, SKIP, next_tick
        # tick scheduled at 100, period 50, fired late at 230
        assert int(next_tick(230, 100, 50, BURST)) == 150   # burn backlog
        assert int(next_tick(230, 100, 50, DELAY)) == 280   # restart cadence
        assert int(next_tick(230, 100, 50, SKIP)) == 250    # next multiple
        # on-time tick: all behaviors agree
        assert int(next_tick(100, 100, 50, BURST)) == 150
        assert int(next_tick(100, 100, 50, SKIP)) == 150


class TestImperativeSupervisor:
    def test_host_driven_kill_restart(self):
        # Handle-style imperative control between run() chunks
        rt = _rt(target=500)  # big enough that 256 steps cannot finish
        state = rt.init_batch(np.arange(8))
        state, _ = rt.run(state, 256, chunk=256)
        assert not np.asarray(state.halted).any()
        state = rt.kill(state, 1)
        state = rt.kill(state, 2)
        state, _ = rt.run(state, 256, chunk=256)
        assert not np.asarray(state.alive)[:, 1].any()
        state = rt.restart(state, 1)
        state = rt.restart(state, 2)
        state, _ = rt.run(state, 20_000, chunk=1024)
        assert bool(state.halted.all())
        assert not bool(state.crashed.any())
        assert np.asarray(state.alive)[:, 1:].all()


class TestStats:
    def test_summarize(self):
        from madsim_tpu.parallel.stats import summarize
        rt = _rt(target=5)
        state, _ = rt.run(rt.init_batch(np.arange(16)), 4000)
        s = summarize(rt, state)
        assert s["batch"] == 16 and s["halted"] == 16 and s["crashed"] == 0
        assert s["distinct_outcomes"] >= 12      # schedule diversity
        # outcomes refine schedules: fingerprints cover sched_hash too
        assert 1 <= s["distinct_schedules"] <= s["distinct_outcomes"]
        assert s["msgs_sent"] > 0 and s["events_total"] > 0
        assert s["first_crash_seed"] is None

    def test_schedule_representatives(self):
        from madsim_tpu.parallel.stats import (schedule_representatives,
                                               sched_hash_u64)
        rt = _rt(target=5)
        seeds = np.arange(100, 116)
        state, _ = rt.run(rt.init_batch(seeds), 4000)
        reps = schedule_representatives(state, seeds)
        hashes = sched_hash_u64(state).tolist()
        assert len(reps) == len(set(hashes))     # one per distinct class
        assert set(reps.values()) <= set(seeds.tolist())
        # each representative is the FIRST seed with that hash
        for h, s in reps.items():
            first = seeds[hashes.index(h)]
            assert s == int(first)


class TestOpJitter:
    """NetConfig.op_jitter_max — the per-op micro-delay analog of the
    reference's 0-5 us random delay before every network op
    (net/mod.rs:151-156)."""

    class _TwoSends(Program):
        """Node 0 emits send A, then send B 2 us later (a sub-jitter gap).
        With FIXED latency and no loss, A's delivery strictly precedes B's
        on every seed — one arrival order, deterministically. Jitter >
        the gap lets the order flip: the interleavings the knob unlocks
        are exactly those separated by gaps the tie-break cannot reach
        (ties it already explores uniformly — see DESIGN §3)."""

        def init(self, ctx):
            ctx.send(1, 1, when=ctx.node == 0)
            ctx.set_timer(2, 7, when=ctx.node == 0)

        def on_timer(self, ctx, tag, payload):
            ctx.send(2, 2, when=ctx.node == 0)

        def on_message(self, ctx, src, tag, payload):
            ctx.state = dict(got=ctx.state["got"] + 1)

    def _rt(self, jitter, prog=None, tlimit=sec(30)):
        cfg = SimConfig(n_nodes=3, time_limit=tlimit,
                        net=NetConfig(send_latency_min=1000,
                                      send_latency_max=1000,
                                      op_jitter_max=jitter))
        if prog is None:
            return Runtime(cfg, [PingPong(3, target=12)], state_spec())
        return Runtime(cfg, [prog], dict(got=np.int32(0)))

    def test_jitter_reorders_sub_jitter_gaps(self):
        from madsim_tpu.parallel.stats import sched_hash_u64
        seeds = np.arange(64)
        counts = {}
        for j in (0, 5):
            rt = self._rt(j, prog=self._TwoSends(), tlimit=ms(10))
            state, _ = rt.run(rt.init_batch(seeds), 400)
            assert bool(state.halted.all())
            counts[j] = len(np.unique(sched_hash_u64(state)))
        # jitter-off: the only schedule variation is the t=0 init-event
        # tie-break permutation; jitter-on adds the A/B arrival flip on
        # top (guard: remove the jitter fold in step.py §4 and the two
        # counts collapse to equal)
        assert counts[5] > counts[0], counts

    def test_jitter_replays_deterministically(self):
        assert self._rt(5).check_determinism(seed=11, max_steps=4000)

    def test_jitter_toml_and_override(self):
        from madsim_tpu.harness.simtest import apply_net_override
        net = NetConfig.from_toml('[net]\nop_jitter_max = "5us"\n')
        assert net.op_jitter_max == 5
        # bound override on an ENABLED build: dynamic, no recompile
        rt = self._rt(1)
        st = apply_net_override(rt.init_batch(np.arange(4)), net,
                                cfg=rt.cfg)
        assert (np.asarray(st.jitter) == 5).all()
        # jitter override on a jitterless build would be a silent no-op
        # (the fold is compiled out) — must refuse loudly instead
        rt0 = self._rt(0)
        with pytest.raises(ValueError, match="jitter"):
            apply_net_override(rt0.init_batch(np.arange(4)), net,
                               cfg=rt0.cfg)


class TestCompaction:
    def test_compacting_run_matches_plain_run(self):
        # long-tailed completion: trajectories halt at widely different
        # event counts; compaction must not change ANY final state
        rt = _rt(target=20)
        seeds = np.arange(384)
        plain, _ = rt.run(rt.init_batch(seeds), 6000, chunk=512)
        compacted = rt.run_compacting(rt.init_batch(seeds), 6000,
                                      chunk=512, min_batch=64)
        assert bool(np.asarray(compacted.halted).all())
        assert (rt.fingerprints(plain) == rt.fingerprints(compacted)).all()


class TestSimtestHarness:
    def test_simtest_decorator_and_env_knobs(self, monkeypatch, tmp_path):
        from madsim_tpu import simtest

        calls = {}

        @simtest(num_seeds=4, max_steps=4000, seed=7)
        def my_test():
            rt = _rt(target=3)
            def check(state):
                calls["checked"] = int(np.asarray(state.halted).sum())
            return rt, check

        state = my_test()
        assert calls["checked"] == 4

        # env overrides: seed base, batch size, TOML net config
        cfgf = tmp_path / "net.toml"
        cfgf.write_text('[net]\npacket_loss_rate = 0.25\n'
                        'send_latency = "2ms..8ms"\n')
        monkeypatch.setenv("MADSIM_TEST_SEED", "100")
        monkeypatch.setenv("MADSIM_TEST_NUM", "6")
        monkeypatch.setenv("MADSIM_TEST_CONFIG", str(cfgf))
        state = my_test()
        assert np.asarray(state.halted).shape[0] == 6
        assert float(np.asarray(state.loss)[0]) == 0.25
        assert int(np.asarray(state.lat_lo)[0]) == 2000
        assert int(np.asarray(state.lat_hi)[0]) == 8000
        assert int(np.asarray(state.msg_dropped).sum()) > 0  # loss applied

    def test_time_limit_env_knob(self, monkeypatch):
        # MADSIM_TEST_TIME_LIMIT (seconds) shortens the run WITHOUT a
        # recompile: the limit is dynamic state (macros lib.rs:157-159)
        from madsim_tpu import simtest

        @simtest(num_seeds=4, max_steps=8000, seed=3)
        def long_test():
            return _rt(target=10_000)   # never halts by itself

        monkeypatch.setenv("MADSIM_TEST_TIME_LIMIT", "1")
        state = long_test()
        assert bool(np.asarray(state.halted).all())
        now = np.asarray(state.now)
        assert (now <= sec(1)).all()            # halted AT the new limit,
        assert (now >= sec(1) - ms(50)).all()   # not before it
        assert (np.asarray(state.tlimit) == sec(1)).all()

    def test_set_time_limit_handle(self):
        # the imperative Handle::set_time_limit analog moves BOTH the
        # hard-stop and the auto-HALT scenario row
        rt = _rt(target=10_000)
        state = rt.set_time_limit(rt.init_batch(np.arange(4)), sec(2))
        state, _ = rt.run(state, 8000)
        assert bool(np.asarray(state.halted).all())
        assert not bool(np.asarray(state.crashed).any())
        assert (np.asarray(state.now) <= sec(2)).all()

    def test_failure_reports_repro_seed(self):
        from madsim_tpu import Program, simtest
        from madsim_tpu.harness.simtest import SimFailure
        import jax.numpy as jnp

        class Bad(Program):
            def init(self, ctx):
                ctx.set_timer(ms(1), 1)

            def on_timer(self, ctx, tag, payload):
                ctx.crash_if(ctx.uniform() < 0.5, 99)
                ctx.set_timer(ms(1), 1)

        @simtest(num_seeds=8, max_steps=200, seed=0)
        def failing():
            cfg = SimConfig(n_nodes=1, time_limit=T.sec(1))
            return Runtime(cfg, [Bad()], dict(x=jnp.asarray(0, jnp.int32)))

        try:
            failing()
            assert False, "expected SimFailure"
        except SimFailure as e:
            assert "MADSIM_TEST_SEED=" in str(e)
            assert e.code == 99


class TestLateBoot:
    def test_scenario_boot_defers_node_creation(self):
        # Handle::create_node analog: a node with a scheduled boot does not
        # exist until then — the pinger can make no progress before sec(1)
        from madsim_tpu import Scenario
        from madsim_tpu.harness.simtest import run_seeds
        from madsim_tpu.models.pingpong import PingPong, state_spec
        sc = Scenario()
        sc.at(sec(1)).boot(1)
        cfg = SimConfig(n_nodes=2, time_limit=sec(10))
        rt = Runtime(cfg, [PingPong(2, target=5)], state_spec(),
                     scenario=sc)
        state = run_seeds(rt, np.arange(8), max_steps=20_000)
        acked = np.asarray(state.node_state["acked"])[:, 0]
        now = np.asarray(state.now)
        assert (acked >= 5).all()
        assert (now > sec(1)).all()      # nothing could complete earlier


class TestChromeTrace:
    def test_export_chrome_trace(self, tmp_path):
        import json
        from madsim_tpu.runtime.trace import export_chrome_trace
        rt = _rt(target=3)
        _, events = rt.run_single(5, 2000, collect_events=True)
        p = str(tmp_path / "trace.json")
        n = export_chrome_trace(events, p)
        assert n > 10
        doc = json.load(open(p))
        evs = [e for e in doc["traceEvents"] if e["ph"] == "i"]
        names = [e for e in doc["traceEvents"] if e["ph"] == "M"]
        assert len(evs) == n and len(names) >= 3
        assert any("SUPER:INIT" in e["name"] for e in evs)
        # timestamps are virtual microseconds, monotone nondecreasing
        ts = [e["ts"] for e in evs]
        assert ts == sorted(ts)


class TestLogTimeStart:
    def test_env_var_filters_trace(self, monkeypatch):
        # MADSIM_LOG_TIME_START (ms) is the default time filter
        # (runtime/mod.rs:349-358)
        from madsim_tpu.models.pingpong import PingPong, state_spec
        from madsim_tpu.runtime.trace import format_trace
        rt = Runtime(SimConfig(n_nodes=3, time_limit=sec(5)),
                     [PingPong(3, target=4)], state_spec())
        _, events = rt.run_single(3, 4000, collect_events=True)
        full = format_trace(events, 0)
        monkeypatch.setenv("MADSIM_LOG_TIME_START", "5")
        filtered = format_trace(events, 0)
        assert 0 < len(filtered) < len(full)
        explicit = format_trace(events, 0, time_start=T.ms(5))
        assert filtered == explicit
