"""Fast-tier smoke coverage of the flagship workloads.

`ci.sh fast` deselects the slow suites (shard_kv, minipg, kv fuzz, bank,
streaming, ministream) for iteration speed — which left the default
green signal blind to the flagship stacks (VERDICT r3 weak #6). Each
smoke here runs the SAME compiled program as its slow suite (identical
SimConfig statics and batch size, so the persistent XLA cache is shared
and no extra compile is paid) with a reduced step budget: deep enough
that the full protocol stack executes and every per-event invariant is
checked on every dispatched event, shallow enough for the fast tier.
Completion-grade assertions stay in the slow suites; a crash or a
capacity overflow anywhere in these stacks fails HERE, in the default
tier.
"""

import numpy as np
import pytest

pytestmark = pytest.mark.slow  # chaos-sweep-heavy (r7 durations triage);
# tier-1/ci.sh fast skip it so the fast lane fits its 870s budget cold

from madsim_tpu import NetConfig, SimConfig, ms, sec
from madsim_tpu.harness.simtest import run_seeds
from madsim_tpu.native import check_kv_history


def _healthy(state):
    # run_seeds already raised on any crash (per-event invariants
    # included); overflow bits and basic traffic are the smoke floor
    assert (np.asarray(state.oops) == 0).all()
    assert int(np.asarray(state.msg_delivered).sum()) > 0


class TestFlagshipSmoke:
    def test_shard_kv_stack(self):
        # statics mirror tests/test_shard_kv.py exactly (shared program)
        from madsim_tpu.models.shard_kv import make_shard_runtime
        cfg = SimConfig(n_nodes=3 + 2 * 3 + 2, event_capacity=160,
                        payload_words=12, time_limit=sec(60),
                        net=NetConfig(send_latency_min=ms(1),
                                      send_latency_max=ms(10)))
        rt = make_shard_runtime(n_groups=2, rg=3, rc=3, n_clients=2,
                                n_ops=5, max_cfg=4, cfg=cfg)
        state = run_seeds(rt, np.arange(12), max_steps=12_000)
        _healthy(state)
        # the controller assigned at least the initial config somewhere
        assert (np.asarray(state.node_state["cfg_n"])[:, :3] >= 1).any()

    def test_minipg_stack(self):
        from madsim_tpu.models.minipg import make_minipg_runtime
        cfg = SimConfig(n_nodes=3, event_capacity=64, payload_words=8,
                        time_limit=sec(10),
                        net=NetConfig(send_latency_min=ms(1),
                                      send_latency_max=ms(8)))
        rt = make_minipg_runtime(n_clients=2, n_txns=4, cfg=cfg)
        state = run_seeds(rt, np.arange(8), max_steps=8_000)
        _healthy(state)

    def test_kv_on_raft_stack(self):
        # partial histories (resp = -1 pending) are valid checker input:
        # the fast tier really does run the linearizability oracle
        from madsim_tpu.models.raft_kv import (extract_histories,
                                               make_kv_runtime)
        rt = make_kv_runtime(n_raft=3, n_clients=2, n_keys=2, n_ops=6,
                             log_capacity=32)
        state = run_seeds(rt, np.arange(8), max_steps=8_000)
        _healthy(state)
        for h in extract_histories(state, 3, 2):
            assert check_kv_history(h)

    def test_bank_stack(self):
        from madsim_tpu.models.bank import make_bank_runtime
        rt = make_bank_runtime(n_raft=3, n_clients=2, n_ops=6,
                               log_capacity=32)
        state = run_seeds(rt, np.arange(8), max_steps=10_000)
        _healthy(state)
        totals = np.asarray(state.node_state["h_total"])[:, 3:]
        resp = np.asarray(state.node_state["h_resp"])[:, 3:]
        seen = totals[resp >= 0]
        assert (seen == 600).all()      # conservation on whatever landed

    def test_streaming_stack(self):
        from madsim_tpu.models.stream_echo import make_stream_echo_runtime
        cfg = SimConfig(n_nodes=3, event_capacity=64, payload_words=8,
                        time_limit=sec(8),
                        net=NetConfig(send_latency_min=ms(1),
                                      send_latency_max=ms(8)))
        rt = make_stream_echo_runtime("bidi", n_clients=2, n_items=6,
                                      cfg=cfg)
        state = run_seeds(rt, np.arange(8), max_steps=6_000)
        _healthy(state)

    def test_ministream_stack(self):
        from madsim_tpu.models.ministream import make_ministream_runtime
        rt = make_ministream_runtime(k=8, epochs=4)
        state = run_seeds(rt, np.arange(48), max_steps=10_000)
        _healthy(state)
