"""Critical-path attribution plane (r23, DESIGN §24): where a tail
request's time went, answered identically on device and host.

The load-bearing properties: (1) the plane is an observation lever —
span-on/compiled-out trajectories are bit-identical leaf-for-leaf
against the captured r22 truth, chunked and fused, and the
sp_on/ev_span/sa_*/tr_qw leaves are excluded from fingerprints; (2) the
device's per-(lane, node) `sa_tail` fold — tail count, queue-wait, net,
hops — EQUALS a host parent-walk of the flight-recorder ring, and every
tail completion names exactly one `sa_bottleneck` node, agreeing with
the host's first-strict-max dominant rule; (3) host request spans
TELESCOPE: Σ wait + Σ transit == the ring's e2e latency, exactly;
(4) `explain_latency` names the same request on re-run and recovers
wrap-truncated chains by r20 window replay; (5) the Chrome-trace export
grows `ph:"b"/"e"` request duration spans exactly when the plane is on
— a span-off document is byte-identical to the frozen r22 capture;
(6) pre-r23 checkpoints are rejected loudly (simconfig-v8).
"""

import json
import os

import numpy as np
import pytest

from madsim_tpu import (CheckpointLog, NetConfig, Runtime, Scenario,
                        SimConfig, explain_latency, export_chrome_trace,
                        format_span, ms, request_spans, ring_records, sec,
                        summarize)
from madsim_tpu.core.state import TRACE_FIELDS
from madsim_tpu.core.types import EV_MSG
from madsim_tpu.models.pingpong import PingPong, state_spec
from madsim_tpu.models.rpc_echo import TAG_ECHO, make_echo_runtime
from madsim_tpu.net import rpc
from madsim_tpu.obs.spans import request_span
from madsim_tpu.parallel.stats import (attribution_brief,
                                       attribution_counters)

import _span_golden as golden

# the 5 leaves the r23 plane added (MIGRATION r23)
SPAN_LEAVES = ("sp_on", "ev_span", "sa_tail", "sa_bottleneck", "tr_qw")

RTAG = rpc.reply_tag(TAG_ECHO)
SLO = ms(8)
SEEDS = np.arange(8, dtype=np.uint32)


def _echo_rt(span):
    """Chaos rpc_echo: kill/restart mid-run, reply deliveries both
    complete a call and re-mint the next request's root."""
    sc = Scenario()
    sc.at(ms(300)).kill(0)
    sc.at(ms(420)).restart(0)
    cfg = SimConfig(
        n_nodes=4, event_capacity=64, time_limit=sec(5),
        latency_hist=24, trace_cap=512,
        complete_kinds=((EV_MSG, RTAG),),
        root_kinds=((EV_MSG, RTAG),),
        slo_target=SLO, span_attr=span,
        net=NetConfig(send_latency_min=ms(1), send_latency_max=ms(8)))
    return make_echo_runtime(n_nodes=4, target=8, scenario=sc, cfg=cfg)


def _pp_rt(trace_cap=1024):
    """Pause/resume pingpong: parked deadlines produce NONZERO
    queue-wait — the span component a chaos-free EDF never exercises."""
    sc = Scenario()
    sc.at(ms(30)).pause(1)
    sc.at(ms(90)).resume(1)
    cfg = SimConfig(n_nodes=3, time_limit=sec(5), latency_hist=24,
                    trace_cap=trace_cap, complete_kinds=((EV_MSG, 1),),
                    slo_target=ms(6), span_attr=True,
                    net=NetConfig(send_latency_min=ms(1),
                                  send_latency_max=ms(4)))
    return Runtime(cfg, [PingPong(3, target=40)], state_spec(),
                   scenario=sc)


@pytest.fixture(scope="module")
def echo_states():
    rt_on, rt_off = _echo_rt(True), _echo_rt(False)
    on, _ = rt_on.run(rt_on.init_batch(SEEDS), 2048, 256)
    off, _ = rt_off.run(rt_off.init_batch(SEEDS), 2048, 256)
    fused = rt_on.run_fused(rt_on.init_batch(SEEDS), 2048, 256)
    return rt_on, rt_off, on, off, fused


@pytest.fixture(scope="module")
def pp_state():
    rt = _pp_rt()
    st, _ = rt.run(rt.init_batch(SEEDS), 400, 100)
    return rt, st


# ---------------------------------------------------------------------------
# 1. bit-identical-when-disabled, against r22 captured truth
# ---------------------------------------------------------------------------

class TestEquivalenceR22:
    @pytest.mark.parametrize("workload", sorted(golden.BUILDERS))
    def test_leaf_for_leaf_vs_r22_golden(self, workload):
        # scripts/capture_golden.py froze these digests AT r22 HEAD,
        # before any r23 engine change: every r22 leaf must still hash
        # identically, chunked and fused; the ONLY new leaves are the
        # attribution plane's own (zero-size here — the frozen
        # workloads never set span_attr)
        gold = golden.load_golden()[workload]
        got = golden.run_workload(workload)
        for runner in ("run", "run_fused"):
            missing = [k for k in gold[runner] if k not in got[runner]]
            assert not missing, (runner, missing)
            diff = [k for k in gold[runner]
                    if gold[runner][k] != got[runner][k]]
            assert not diff, (runner, diff)
            new = set(got[runner]) - set(gold[runner])
            assert new == {"." + n for n in SPAN_LEAVES}, new


# ---------------------------------------------------------------------------
# 2. the observation-lever contract on live runs
# ---------------------------------------------------------------------------

class TestSpanPlane:
    def test_span_never_perturbs_trajectory(self, echo_states):
        rt_on, rt_off, on, off, fused = echo_states
        assert (rt_on.fingerprints(on) == rt_off.fingerprints(off)).all()
        assert (rt_on.fingerprints(on) == rt_on.fingerprints(fused)).all()
        for f in TRACE_FIELDS:
            assert (np.asarray(getattr(on, f))
                    == np.asarray(getattr(fused, f))).all(), f

    def test_masked_lanes_accumulate_nothing(self, echo_states):
        rt_on, _, on, _, _ = echo_states
        masked = rt_on.run_fused(
            rt_on.init_batch(SEEDS, span_lanes=[0, 3]), 2048, 256)
        assert (rt_on.fingerprints(masked) == rt_on.fingerprints(on)).all()
        sa = np.asarray(masked.sa_tail)
        sb = np.asarray(masked.sa_bottleneck)
        rec = np.zeros(len(SEEDS), bool)
        rec[[0, 3]] = True
        assert (sa[~rec] == 0).all() and (sb[~rec] == 0).all()
        assert (sa[rec] == np.asarray(on.sa_tail)[rec]).all()
        assert (sb[rec] == np.asarray(on.sa_bottleneck)[rec]).all()

    def test_span_lanes_requires_compiled_plane(self, echo_states):
        _, rt_off, _, _, _ = echo_states
        with pytest.raises(ValueError, match="span"):
            rt_off.init_batch(SEEDS, span_lanes=[0])

    def test_span_attr_requires_latency_plane(self):
        with pytest.raises(AssertionError, match="span_attr"):
            SimConfig(n_nodes=2, span_attr=True)

    def test_signature_is_v8_and_span_attr_is_structural(self):
        # r23's bump — this file owns the authoritative assertion
        cfg = SimConfig(n_nodes=2)
        assert cfg.structural_signature()[0] == "simconfig-v8"
        a = SimConfig(n_nodes=2, latency_hist=24,
                      complete_kinds=((EV_MSG, 1),), span_attr=True)
        b = SimConfig(n_nodes=2, latency_hist=24,
                      complete_kinds=((EV_MSG, 1),))
        assert a.structural_signature() != b.structural_signature()


# ---------------------------------------------------------------------------
# 3. device fold == host parent-walk, component for component
# ---------------------------------------------------------------------------

class TestDeviceHostAgreement:
    def test_tail_count_and_bottleneck_close(self, echo_states):
        rt_on, _, on, _, _ = echo_states
        sa = np.asarray(on.sa_tail)
        # the SA_COUNT component IS the latency plane's slo-miss
        # counter, per node — one fold, two consumers
        assert (sa[:, :, 0] == np.asarray(on.lh_slo_miss)).all()
        assert sa[:, :, 0].sum() > 0, "workload produced no tails"
        # every tail completion names exactly one dominant node
        assert np.asarray(on.sa_bottleneck).sum() == sa[:, :, 0].sum()

    def test_device_attribution_equals_host_walk(self, echo_states):
        rt_on, _, on, _, _ = echo_states
        sa = np.asarray(on.sa_tail)
        walked = 0
        for b in range(len(SEEDS)):
            recs = ring_records(on, b)
            assert recs["dropped"] == 0, "ring must hold the history"
            lat = np.asarray(recs["lat"])
            qw = np.asarray(recs["qw"])
            step_at = {int(s): i for i, s in enumerate(recs["step"])}
            hq = hn = hh = 0
            for i in np.nonzero(lat >= 0)[0]:
                if lat[i] <= SLO:
                    continue            # only tails attribute
                # parent-walk to the root: sum each hop's queue-wait,
                # count hops; the remainder of e2e is transit. An
                # externally minted element IS the root (core/step.py
                # root rule) — its own wait belongs to no request.
                j, q, hops = int(i), 0, 0
                while True:
                    p = int(recs["parent"][j])
                    if p < 0 or p not in step_at:
                        break           # j is the external root
                    q += int(qw[j])
                    hops += 1
                    jp = step_at[p]
                    if (int(recs["kind"][jp]) == EV_MSG
                            and int(recs["tag"][jp]) == RTAG):
                        break           # completion -> root re-mint
                    j = jp
                hq += q
                hn += int(lat[i]) - q
                hh += hops
                walked += 1
            assert (hq, hn, hh) == (sa[b, :, 1].sum(), sa[b, :, 2].sum(),
                                    sa[b, :, 3].sum()), b
        assert walked == sa[:, :, 0].sum() > 0

    def test_spans_telescope_and_match_device(self, pp_state):
        rt, st = pp_state
        sa = np.asarray(st.sa_tail)
        assert sa[:, :, 1].sum() > 0, \
            "pause/resume must produce nonzero queue-wait"
        for b in range(len(SEEDS)):
            spans = request_spans(st, b, slo_target=ms(6))
            assert spans
            for sp in spans:
                if not sp["truncated"]:
                    assert (sp["wait_us"] + sp["transit_us"]
                            == sp["lat_us"]), sp
            tl = [s for s in spans if s["tail"] and not s["truncated"]]
            assert sum(s["wait_us"] for s in tl) == sa[b, :, 1].sum()
            assert sum(s["transit_us"] for s in tl) == sa[b, :, 2].sum()
            assert sum(len(s["hops"]) for s in tl) == sa[b, :, 3].sum()
            # the host's first-strict-max dominant fold == the device's
            # bottleneck histogram, node for node
            bn = np.zeros(3, np.int64)
            for s in tl:
                bn[s["dominant"]["node"]] += 1
            assert (bn == np.asarray(st.sa_bottleneck)[b]).all(), b

    def test_spans_raise_without_plane(self, echo_states):
        _, _, _, off, _ = echo_states
        with pytest.raises(ValueError, match="span_attr"):
            request_spans(off, 0)


# ---------------------------------------------------------------------------
# 4. explain_latency: deterministic naming, replay recovery
# ---------------------------------------------------------------------------

class TestExplainLatency:
    def test_names_slowest_deterministically(self, pp_state):
        rt, st = pp_state
        e1 = explain_latency(st, 2, rt=rt)
        e2 = explain_latency(st, 2, rt=rt)
        assert e1 == e2
        lat = np.asarray(ring_records(st, 2)["lat"])
        assert e1["lat_us"] == int(lat[lat >= 0].max())
        assert e1["slo_target"] == ms(6) and e1["slo_miss"]
        assert not e1["truncated"] and not e1["replayed"]
        assert format_span(e1)

    def test_rank_walks_down_the_tail(self, pp_state):
        rt, st = pp_state
        lats = [explain_latency(st, 2, rank=r, rt=rt)["lat_us"]
                for r in range(3)]
        assert lats == sorted(lats, reverse=True)
        with pytest.raises(ValueError, match="rank"):
            explain_latency(st, 2, rank=10_000, rt=rt)

    def test_replay_recovers_wrapped_chain(self, tmp_path):
        # a 16-slot ring wraps long before the pingpong chains root
        # (no root_kinds -> chains reach the t=0 external mint), so the
        # live answer is a truncated suffix; window replay from the
        # harvested checkpoint log must recover the FULL chain and
        # agree with a full-size-ring control, hop for hop
        rt = _pp_rt(trace_cap=16)
        log = CheckpointLog()
        st, _ = rt.run(rt.init_batch(SEEDS), 400, 100,
                       ckpt_every=64, ckpt_log=log)
        live = explain_latency(st, 1, rt=rt)
        assert live["truncated"], "specimen must wrap"
        trace = str(tmp_path / "replayed.json")
        rec = explain_latency(st, 1, rt=rt, replay=True, ckpts=log,
                              export_trace=trace)
        assert rec["replayed"] and not rec["truncated"]
        assert rec["step"] == live["step"]
        assert rec["lat_us"] == live["lat_us"]
        assert rec["wait_us"] + rec["transit_us"] == rec["lat_us"]
        assert os.path.exists(rec["trace_path"])

        rt_big, big = _pp_rt(trace_cap=2048), None
        big, _ = rt_big.run(rt_big.init_batch(SEEDS), 400, 100)
        ctrl = request_span(ring_records(big, 1), rec["step"])
        assert not ctrl["truncated"]
        assert len(rec["hops"]) == len(ctrl["hops"])
        assert rec["wait_us"] == ctrl["wait_us"]
        assert rec["transit_us"] == ctrl["transit_us"]
        assert rec["dominant"] == ctrl["dominant"]
        assert rec["root"]["step"] == ctrl["root"]["step"]

    def test_replay_without_runtime_raises(self, tmp_path):
        rt = _pp_rt(trace_cap=16)
        st, _ = rt.run(rt.init_batch(SEEDS), 400, 100)
        with pytest.raises(ValueError, match="rt="):
            explain_latency(st, 1, replay=True)


# ---------------------------------------------------------------------------
# 5. the host rollups: stats triple, summarize, trace export
# ---------------------------------------------------------------------------

class TestRollups:
    def test_attribution_counters_and_brief(self, echo_states):
        rt_on, _, on, _, _ = echo_states
        c = attribution_counters(on)
        sa = np.asarray(on.sa_tail).astype(np.int64)
        assert (c["tail"] == sa.sum(0)).all()
        assert c["bottleneck"] == np.asarray(on.sa_bottleneck) \
            .sum(0).tolist()
        assert c["slo_target"] == SLO
        brief = attribution_brief(on)
        assert brief["tails"] == int(sa[:, :, 0].sum())
        assert brief["qwait_us"] + brief["net_us"] > 0
        assert 0.0 <= brief["wait_share"] <= 1.0
        s = summarize(rt_on, on, SEEDS)
        assert s["attribution"]["tails"] == brief["tails"]
        assert s["latency"]["slo_target"] == SLO

    def test_rollups_none_when_compiled_out(self, echo_states):
        _, rt_off, _, off, _ = echo_states
        assert attribution_brief(off) is None
        assert summarize(rt_off, off, SEEDS)["attribution"] is None

    def test_trace_grows_request_spans_iff_on(self, pp_state, tmp_path,
                                              echo_states):
        _, st = pp_state
        _, _, _, off, _ = echo_states
        p = str(tmp_path / "t.json")
        export_chrome_trace(p, state=st, lane=2)
        with open(p) as f:
            doc = json.load(f)["traceEvents"]
        spans = [e for e in doc if e.get("ph") in ("b", "e")]
        assert spans and len(spans) % 2 == 0
        lat = np.asarray(ring_records(st, 2)["lat"])
        assert len(spans) == 2 * int((lat >= 0).sum())
        b0 = next(e for e in doc if e.get("ph") == "b")
        assert b0["cat"] == "request" and b0["args"]["lat_us"] >= 0
        export_chrome_trace(p, state=off, lane=0)
        with open(p) as f:
            phs = {e.get("ph") for e in json.load(f)["traceEvents"]}
        assert "b" not in phs and "e" not in phs

    def test_span_off_trace_is_byte_identical_to_r22(self, tmp_path):
        # the frozen pingpong golden workload (span never on), exported
        # at r22 HEAD into data/golden_r22_trace.json: the r23 export
        # path must reproduce it byte for byte
        rt = golden.BUILDERS["pingpong"]()
        run = golden.RUNS["pingpong"]
        st, _ = rt.run(
            rt.init_batch(np.arange(run["seeds"], dtype=np.uint32)),
            run["max_steps"], run["chunk"])
        p = str(tmp_path / "pp.json")
        export_chrome_trace(p, state=st, lane=0)
        gold = os.path.join(os.path.dirname(__file__), "data",
                            "golden_r22_trace.json")
        with open(p, "rb") as a, open(gold, "rb") as g:
            assert a.read() == g.read(), \
                "span-off export must stay byte-identical to r22"


# ---------------------------------------------------------------------------
# 6. pre-r23 checkpoints are rejected loudly
# ---------------------------------------------------------------------------

class TestCheckpointGate:
    def test_pre_r23_checkpoint_rejected(self, tmp_path):
        # a pre-r23 batch checkpoint (no span leaves — 5 fewer) fails
        # load() loudly on the leaf count, not by silent misalignment
        from madsim_tpu.runtime import checkpoint
        rt = _pp_rt()
        st = rt.init_batch(np.arange(2))
        p = str(tmp_path / "ck.npz")
        checkpoint.save(p, st)
        with np.load(p) as z:
            leaves = {k: z[k] for k in z.files}
        n = len([k for k in leaves if k.startswith("leaf_")])
        stripped = {k: v for k, v in leaves.items()
                    if not k.startswith("leaf_")}
        for i in range(n - len(SPAN_LEAVES)):
            stripped[f"leaf_{i}"] = leaves[f"leaf_{i}"]
        p2 = str(tmp_path / "old.npz")
        np.savez_compressed(p2, **stripped)
        with pytest.raises(ValueError, match="leaves"):
            checkpoint.load(p2, st)
