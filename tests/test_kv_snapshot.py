"""KV-on-Raft with log compaction + chunked InstallSnapshot.

The full-stack version of tests/test_raft_snapshot.py: log_capacity is much
smaller than the total client workload, so servers must compact their
applied prefix into the (kv, sessions) image and catch lagging peers up by
streaming that image in fixed-width chunks. Linearizability of the observed
client histories is the end-to-end oracle.
"""

import numpy as np

from madsim_tpu import Scenario, SimConfig, NetConfig, ms, sec
from madsim_tpu.harness.simtest import run_seeds
from madsim_tpu.models.raft_kv import extract_histories, make_kv_runtime
from madsim_tpu.native import check_kv_history

N_RAFT, N_CLIENTS, N_OPS = 5, 3, 10
L = 12  # total committed entries (30 ops + no-ops) far exceed the window


import pytest

pytestmark = pytest.mark.slow  # measured in --durations; ci.sh fast skips

def _cfg(time_limit=sec(12), loss=0.0):
    return SimConfig(n_nodes=N_RAFT + N_CLIENTS, event_capacity=128,
                     payload_words=12, time_limit=time_limit,
                     net=NetConfig(packet_loss_rate=loss,
                                   send_latency_min=ms(1),
                                   send_latency_max=ms(10)))


def _rt(scenario=None, cfg=None, **kw):
    kw.setdefault("compact_threshold", 4)
    return make_kv_runtime(N_RAFT, N_CLIENTS, n_keys=3, n_ops=N_OPS,
                           log_capacity=L, scenario=scenario,
                           cfg=cfg or _cfg(), **kw)


class TestKvSnapshot:
    def test_workload_exceeds_log_capacity(self):
        rt = _rt()
        state = run_seeds(rt, np.arange(6), max_steps=60_000)
        ns = state.node_state
        opn = np.asarray(ns["c_opn"])[:, N_RAFT:]
        assert (opn >= N_OPS).all()  # every client finished every op
        snap = np.asarray(ns["snap_len"])[:, :N_RAFT]
        commit = np.asarray(ns["commit"])[:, :N_RAFT]
        assert (snap.max(axis=1) > 0).all()           # compaction happened
        assert (commit.max(axis=1) > L).all()         # log wrapped capacity
        for h in extract_histories(state, N_RAFT, N_CLIENTS):
            assert check_kv_history(h)

    def test_chunked_snapshot_catchup(self):
        # server 0 dies before any real replication (its persisted log is
        # near-empty) and returns only AFTER the whole workload committed
        # and every peer compacted — the missing entries no longer exist in
        # ANY log window, so AE cannot recover node 0: only the chunked
        # image transfer can. The run continues past client completion
        # (halt_when_all_done=False) so the recovery is observable.
        sc = Scenario()
        sc.at(ms(300)).kill(0)
        sc.at(sec(4)).restart(0)
        rt = _rt(scenario=sc, cfg=_cfg(time_limit=sec(6)),
                 halt_when_all_done=False)
        state = run_seeds(rt, np.arange(6), max_steps=80_000)
        ns = state.node_state
        opn = np.asarray(ns["c_opn"])[:, N_RAFT:]
        assert (opn >= N_OPS).all()
        snap = np.asarray(ns["snap_len"])
        applied = np.asarray(ns["applied"])
        kv = np.asarray(ns["kv"])
        total = N_CLIENTS * N_OPS
        # peers compacted far past anything node 0 ever held
        assert (snap[:, 1:N_RAFT].min(axis=1) >= total - L).all()
        # node 0 caught all the way up — impossible without InstallSnapshot
        assert (applied[:, 0] >= total - L).all()
        assert (snap[:, 0] > 0).all()
        # node 0's materialized kv agrees with any peer at the same applied
        # index (the image transfer preserved the state machine)
        for b in range(snap.shape[0]):
            for p in range(1, N_RAFT):
                if applied[b, p] == applied[b, 0]:
                    assert (kv[b, p] == kv[b, 0]).all()
        for h in extract_histories(state, N_RAFT, N_CLIENTS):
            assert check_kv_history(h)

    def test_chaos_with_compaction_linearizable(self):
        sc = Scenario()
        servers = range(N_RAFT)
        for t in range(4):
            sc.at(ms(900 + 900 * t)).kill_random(among=servers)
            sc.at(ms(1400 + 900 * t)).restart_random(among=servers)
        sc.at(sec(2)).partition([0, 1])
        sc.at(sec(3)).heal()
        rt = _rt(scenario=sc, cfg=_cfg(time_limit=sec(12), loss=0.05))
        state = run_seeds(rt, np.arange(6), max_steps=80_000)
        hists = extract_histories(state, N_RAFT, N_CLIENTS)
        completed = sum(int((h["resp"] >= 0).sum()) for h in hists)
        assert completed > 0
        for h in hists:
            assert check_kv_history(h)

    def test_replay_stable(self):
        rt = _rt(cfg=_cfg(time_limit=sec(4)))
        assert rt.check_determinism(seed=11, max_steps=10_000)

    def test_batch_vs_single_with_compaction(self):
        # the replay-by-seed contract must survive the round's newest
        # machinery: sliding-window logs, digest folds, chunked snapshot
        # transfer — seed 5 inside a chaos batch reaches bit-identical
        # state to seed 5 run alone
        sc = Scenario()
        sc.at(ms(400)).kill(0)
        sc.at(sec(2)).restart(0)
        rt = _rt(scenario=sc, cfg=_cfg(time_limit=sec(4)))
        batch, _ = rt.run(rt.init_batch(np.arange(8)), 30_000)
        solo, _ = rt.run(rt.init_single(5), 30_000)
        assert rt.fingerprints(batch)[5] == rt.fingerprints(solo)[0]
