"""Fused on-device sweep engine: while_loop runner equivalence, the
frozen-lane overshoot contract, the device-side coverage reduction, and
the pipelined explore().

The load-bearing property is bitwise determinism-equivalence: `run_fused`
is the SAME vmapped-scan chunk body under the SAME continue condition as
the chunked `run()`, merely with the `halted.all()` predicate evaluated
on-device — so final states must match bit-for-bit, crashed lanes and
all. Anything less means the fused path is a separate replay domain,
which DESIGN §4 forbids.
"""

import numpy as np
import pytest

pytestmark = pytest.mark.slow  # chaos-sweep-heavy (r7 durations triage);
# tier-1/ci.sh fast skip it so the fast lane fits its 870s budget cold

from madsim_tpu import Runtime, Scenario, SimConfig, NetConfig, ms, sec
from madsim_tpu.models.pingpong import PingPong, state_spec
from madsim_tpu.parallel import stats
from madsim_tpu.parallel.explore import explore


def _raft_rt(time_limit=sec(3)):
    from madsim_tpu.models.raft import make_raft_runtime
    cfg = SimConfig(n_nodes=5, event_capacity=128, time_limit=time_limit,
                    net=NetConfig(packet_loss_rate=0.05,
                                  send_latency_min=ms(1),
                                  send_latency_max=ms(10)))
    sc = Scenario()
    sc.at(sec(1)).kill_random()
    sc.at(sec(1) + ms(400)).restart_random()
    return make_raft_runtime(5, 8, n_cmds=4, scenario=sc, cfg=cfg)


def _fps_both(rt, seeds, max_steps, chunk):
    """Fingerprints from the chunked and fused runners on fresh batches
    (both runners donate their input buffers)."""
    chunked, _ = rt.run(rt.init_batch(seeds), max_steps, chunk)
    fused = rt.run_fused(rt.init_batch(seeds), max_steps, chunk)
    return rt.fingerprints(chunked), rt.fingerprints(fused), fused


class TestFusedEquivalence:
    def test_raft_bitwise_match_64_seeds(self):
        # chaos Raft, 64 seeds, a max_steps that is NOT a chunk multiple
        # (both runners round up identically), short enough time limit
        # that lanes halt mid-sweep at different steps
        rt = _raft_rt()
        seeds = np.arange(64, dtype=np.uint32)
        f_chunked, f_fused, _ = _fps_both(rt, seeds, max_steps=1500,
                                          chunk=256)
        assert (f_chunked == f_fused).all()

    def test_mid_sweep_crash_seed_matches(self):
        # a known-red workload (WAL sync removed + power-fail chaos, the
        # test_explore repro): some lanes crash mid-sweep while others
        # run on — the fused predicate must keep stepping the live lanes
        # and freeze the crashed ones exactly like the chunked runner
        from madsim_tpu.models.wal_kv import make_wal_kv_runtime
        sc = Scenario()
        for t in range(6):
            sc.at(ms(150) + ms(250) * t).kill(0)
            sc.at(ms(210) + ms(250) * t).restart(0)
        rt = make_wal_kv_runtime(n_clients=2, n_ops=12, wal_cap=64,
                                 sync_wal=False, scenario=sc)
        seeds = np.arange(64, dtype=np.uint32)
        f_chunked, f_fused, fused = _fps_both(rt, seeds, max_steps=4096,
                                              chunk=512)
        crashed = np.asarray(fused.crashed)
        assert crashed.any() and not crashed.all()  # genuinely mid-sweep
        assert (f_chunked == f_fused).all()

    @pytest.mark.slow
    def test_shard_kv_bitwise_match_64_seeds(self):
        from madsim_tpu.models.shard_kv import make_shard_runtime
        cfg = SimConfig(n_nodes=11, event_capacity=160, payload_words=12,
                        time_limit=sec(60),
                        net=NetConfig(send_latency_min=ms(1),
                                      send_latency_max=ms(10)))
        rt = make_shard_runtime(n_groups=2, rg=3, rc=3, n_clients=2,
                                n_ops=4, max_cfg=4, cfg=cfg)
        seeds = np.arange(64, dtype=np.uint32)
        f_chunked, f_fused, _ = _fps_both(rt, seeds, max_steps=4096,
                                          chunk=512)
        assert (f_chunked == f_fused).all()

    def test_early_exit_stops_at_halt(self):
        # all lanes halt quickly; the fused runner's on-device predicate
        # must exit instead of burning the full max_steps budget. steps
        # stays a per-lane count, so equivalence covers it too.
        cfg = SimConfig(n_nodes=2, time_limit=sec(5),
                        net=NetConfig(send_latency_min=ms(1),
                                      send_latency_max=ms(1)))
        rt = Runtime(cfg, [PingPong(2, target=3)], state_spec())
        seeds = np.arange(32, dtype=np.uint32)
        f_chunked, f_fused, fused = _fps_both(rt, seeds, max_steps=100_000,
                                              chunk=64)
        assert bool(np.asarray(fused.halted).all())
        assert (f_chunked == f_fused).all()


class TestOvershootContract:
    def test_overshoot_records_are_unfired(self):
        # Runtime.run(collect_events=True) always runs full chunks, so a
        # trajectory that halts mid-chunk (or a max_steps that is not a
        # chunk multiple) emits frozen-lane records past its halt. The
        # contract: those records carry fired=False — consumers filter on
        # `fired`, never on step count.
        cfg = SimConfig(n_nodes=2, time_limit=sec(5),
                        net=NetConfig(send_latency_min=ms(1),
                                      send_latency_max=ms(1)))
        rt = Runtime(cfg, [PingPong(2, target=3)], state_spec())
        state, events = rt.run(rt.init_batch(np.arange(4, dtype=np.uint32)),
                               max_steps=4096, chunk=256,
                               collect_events=True)
        assert bool(np.asarray(state.halted).all())
        fired = np.asarray(events["fired"])        # [steps, B]
        steps = np.asarray(state.steps)            # [B] true event counts
        assert fired.shape[0] > int(steps.max())   # overshoot happened
        for lane in range(fired.shape[1]):
            n = int(steps[lane])
            assert fired[:n, lane].all()           # real events fired
            assert not fired[n:, lane].any()       # frozen tail is unfired
        # per-lane fired count equals the engine's own step counter
        assert (fired.sum(axis=0) == steps).all()


class TestCoverageDigest:
    def _state(self):
        cfg = SimConfig(n_nodes=4, time_limit=sec(5),
                        net=NetConfig(packet_loss_rate=0.1))
        rt = Runtime(cfg, [PingPong(4, target=4)], state_spec())
        state, _ = rt.run(rt.init_batch(np.arange(96, dtype=np.uint32)),
                          max_steps=2000, chunk=256)
        return state

    def test_digest_matches_host_unique(self):
        state = self._state()
        pairs, n = stats.coverage_digest(state)
        dev = stats.digest_hashes(pairs, n)
        host = np.unique(stats.sched_hash_u64(state))
        assert dev.dtype == np.uint64
        assert (dev == host).all()                 # sorted + deduped match
        assert stats.distinct_schedules(state) == len(host)

    def test_summarize_uses_device_count(self):
        state = self._state()
        cfg = SimConfig(n_nodes=4, time_limit=sec(5),
                        net=NetConfig(packet_loss_rate=0.1))
        rt = Runtime(cfg, [PingPong(4, target=4)], state_spec())
        out = stats.summarize(rt, state)
        assert out["distinct_schedules"] == len(
            np.unique(stats.sched_hash_u64(state)))


class TestPipelinedExplore:
    def _rt(self):
        cfg = SimConfig(n_nodes=2, time_limit=sec(5),
                        net=NetConfig(send_latency_min=ms(1),
                                      send_latency_max=ms(1)))
        return Runtime(cfg, [PingPong(2, target=3)], state_spec())

    def test_pipelined_equals_serial(self):
        # pipelining reorders host work only; every reported number must
        # be identical to the serial chunked path
        rt = self._rt()
        kw = dict(max_steps=2000, batch=32, max_rounds=8, dry_rounds=2)
        piped = explore(rt, pipeline=True, fused=True, **kw)
        serial = explore(rt, pipeline=False, fused=False, **kw)
        assert piped == serial
        assert piped["saturated"]

    def test_crashes_harvested_through_fused_path(self):
        from madsim_tpu.models import wal_kv
        from madsim_tpu.models.wal_kv import make_wal_kv_runtime
        sc = Scenario()
        for t in range(6):
            sc.at(ms(150) + ms(250) * t).kill(0)
            sc.at(ms(210) + ms(250) * t).restart(0)
        rt = make_wal_kv_runtime(n_clients=2, n_ops=12, wal_cap=64,
                                 sync_wal=False, scenario=sc)
        out = explore(rt, max_steps=60_000, batch=16, max_rounds=2,
                      dry_rounds=2, pipeline=True, fused=True)
        assert out["crashes"] > 0
        assert wal_kv.CRASH_LOST_WRITE in out["crash_first_seed_by_code"]


class TestFusedSharded:
    def test_fused_runs_on_virtual_mesh(self):
        # the conftest forces an 8-device CPU mesh; the fused while_loop
        # (with its all-reduce predicate) must compile and run SPMD and
        # agree bitwise with the unsharded run
        from madsim_tpu.parallel.distributed import (host_seed_slice,
                                                     run_fused_sharded)
        rt = self._pingpong()
        seeds = host_seed_slice(32)
        sharded = run_fused_sharded(rt, seeds, max_steps=2000, chunk=256)
        plain = rt.run_fused(rt.init_batch(seeds), 2000, 256)
        assert (rt.fingerprints(sharded) == rt.fingerprints(plain)).all()

    def _pingpong(self):
        cfg = SimConfig(n_nodes=2, time_limit=sec(5),
                        net=NetConfig(send_latency_min=ms(1),
                                      send_latency_max=ms(1)))
        return Runtime(cfg, [PingPong(2, target=3)], state_spec())
