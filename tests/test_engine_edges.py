"""Engine edge cases: degenerate configs and supervisor-op interplay
(the awkward corners the reference covers in its per-module inline tests)."""

import jax.numpy as jnp
import numpy as np

from madsim_tpu import (Program, Runtime, Scenario, SimConfig, NetConfig,
                        ms, sec)
from madsim_tpu.core import types as T
from madsim_tpu.models.pingpong import PingPong, state_spec


class SelfPinger(Program):
    """Sends to ITSELF — loopback messages must deliver (localhost works
    in the reference too)."""

    def init(self, ctx):
        ctx.set_timer(0, 1)

    def on_timer(self, ctx, tag, payload):
        ctx.send(ctx.node, 7, [41], when=ctx.state["got"] < 5)

    def on_message(self, ctx, src, tag, payload):
        st = dict(ctx.state)
        hit = (tag == 7) & (src == ctx.node) & (payload[0] == 41)
        st["got"] = st["got"] + hit
        ctx.send(ctx.node, 7, [41], when=hit & (st["got"] < 5))
        ctx.halt_if(st["got"] >= 5)
        ctx.state = st


class TestEdges:
    def test_send_to_self(self):
        rt = Runtime(SimConfig(n_nodes=1, time_limit=sec(5)),
                     [SelfPinger()], dict(got=jnp.asarray(0, jnp.int32)))
        state, _ = rt.run(rt.init_batch(np.arange(4)), 2000)
        assert bool(state.halted.all()) and not bool(state.crashed.any())
        assert (np.asarray(state.node_state["got"])[:, 0] == 5).all()

    def test_total_loss_no_progress_no_deadlock(self):
        # loss=1.0: nothing delivers, retry timers keep the world alive,
        # the scenario HALT ends the run cleanly
        cfg = SimConfig(n_nodes=3, time_limit=sec(1),
                        net=NetConfig(packet_loss_rate=1.0))
        rt = Runtime(cfg, [PingPong(3, target=5)], state_spec())
        state, _ = rt.run(rt.init_batch(np.arange(4)), 20_000)
        assert bool(state.halted.all()) and not bool(state.crashed.any())
        assert (np.asarray(state.node_state["acked"])[:, 0] == 0).all()
        assert int(np.asarray(state.msg_dropped).sum()) > 0

    def test_zero_latency_network(self):
        cfg = SimConfig(n_nodes=3, time_limit=sec(5),
                        net=NetConfig(send_latency_min=0,
                                      send_latency_max=0))
        rt = Runtime(cfg, [PingPong(3, target=10)], state_spec())
        state, _ = rt.run(rt.init_batch(np.arange(8)), 8000)
        assert bool(state.halted.all()) and not bool(state.crashed.any())
        assert rt.check_determinism(3, 4000)

    def test_redundant_supervisor_ops_are_noops(self):
        # kill a dead node, resume a never-paused node, restart an alive
        # node (= reboot), pause a dead node: nothing crashes or wedges
        sc = Scenario()
        sc.at(ms(10)).kill(1)
        sc.at(ms(20)).kill(1)          # kill dead
        sc.at(ms(30)).resume(2)        # resume non-paused
        sc.at(ms(40)).pause(1)         # pause dead (parked forever = fine)
        sc.at(ms(50)).restart(0)       # reboot alive pinger
        sc.at(ms(60)).restart(1)       # genuine restart
        rt = Runtime(SimConfig(n_nodes=3, time_limit=sec(30)),
                     [PingPong(3, target=10)], state_spec(), scenario=sc)
        state, _ = rt.run(rt.init_batch(np.arange(8)), 20_000)
        assert bool(state.halted.all()) and not bool(state.crashed.any())
        # note: restart clears the pause flag (kill/boot reset semantics)
        assert not np.asarray(state.paused).any()

    def test_kill_clears_pause_clog_survives_restart(self):
        # pause -> kill: pause flag cleared (task.rs kill semantics);
        # clog_node is NETWORK state, not process state: it survives
        # kill/restart (NetSim reset clears sockets, not clogs)
        sc = Scenario()
        sc.at(ms(5)).pause(1)
        sc.at(ms(10)).clog_node(1)
        sc.at(ms(15)).kill(1)
        sc.at(ms(20)).restart(1)
        rt = Runtime(SimConfig(n_nodes=3, time_limit=sec(1)),
                     [PingPong(3, target=500)], state_spec(), scenario=sc)
        state, _ = rt.run(rt.init_single(0), 20_000)
        assert not bool(np.asarray(state.paused)[0, 1])
        assert bool(np.asarray(state.clog_node)[0, 1])   # still clogged
        assert bool(np.asarray(state.alive)[0, 1])

    def test_single_node_cluster(self):
        rt = Runtime(SimConfig(n_nodes=1, time_limit=sec(2)),
                     [PingPong(1, target=3)],
                     state_spec())
        state, _ = rt.run(rt.init_single(0), 4000)
        assert bool(state.halted.all()) and not bool(state.crashed.any())


class SlowTicker(Program):
    """One timer every 30 simulated seconds, forever — walks the virtual
    clock to the int32 tick cap in ~72 events."""

    def init(self, ctx):
        ctx.set_timer(sec(30), 1)

    def on_timer(self, ctx, tag, payload):
        ctx.set_timer(sec(30), 1)


class TestTickCap:
    def test_time_overflow_oopses_instead_of_wrapping(self):
        # the documented ~35-min ceiling (types.py: int32 ticks): driving
        # a trajectory to the cap must set OOPS_TIME_OVERFLOW — red if
        # the guard in step.py §4 is removed — and the clock must never
        # wrap negative (deadlines that overflow fire "now", monotone)
        cfg = SimConfig(n_nodes=1, time_limit=int(T.T_INF) - 1)
        rt = Runtime(cfg, [SlowTicker()],
                     dict(x=jnp.asarray(0, jnp.int32)))
        state, _ = rt.run(rt.init_batch(np.arange(4)), 200)
        oops = np.asarray(state.oops)
        now = np.asarray(state.now)
        assert (oops & T.OOPS_TIME_OVERFLOW != 0).all()
        assert (now >= 0).all() and (now <= T.T_INF).all()
        assert not bool(np.asarray(state.crashed).any())


class TestStatsFlag:
    def test_collect_stats_off_keeps_counters_zero(self):
        cfg = SimConfig(n_nodes=3, time_limit=sec(5), collect_stats=False)
        rt = Runtime(cfg, [PingPong(3, target=5)], state_spec())
        state, _ = rt.run(rt.init_batch(np.arange(4)), 4000)
        assert bool(state.halted.all()) and not bool(state.crashed.any())
        assert int(np.asarray(state.msg_sent).sum()) == 0
        assert int(np.asarray(state.ev_peak).sum()) == 0
        assert int(np.asarray(state.steps).sum()) > 0   # steps still count
