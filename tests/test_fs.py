"""Filesystem simulator: read_at/write_all_at/set_len/sync_all semantics and
REAL power-fail — unsynced writes must die with the process (fs.rs:154-246;
power-fail was TODO at fs.rs:48-51, here it is load-bearing and tested red).
"""

import numpy as np
import pytest

from madsim_tpu import Scenario, SimConfig, NetConfig, ms, sec
from madsim_tpu import fs
from madsim_tpu.harness.simtest import SimFailure, run_seeds
from madsim_tpu.models import wal_kv
from madsim_tpu.models.wal_kv import make_wal_kv_runtime

SEEDS = np.arange(8)


class TestFileApi:
    # the helpers are plain masked array ops — unit-testable without a sim
    def test_write_read_roundtrip(self):
        st = fs.fs_state(2, 16)
        ok = fs.write_all_at(st, 0, 3, [7, 8, 9])
        assert bool(ok)
        assert fs.read_at(st, 0, 3, 3).tolist() == [7, 8, 9]
        assert int(fs.file_len(st, 0)) == 6
        assert int(fs.file_len(st, 1)) == 0          # other file untouched

    def test_write_past_capacity_refused(self):
        st = fs.fs_state(1, 8)
        ok = fs.write_all_at(st, 0, 6, [1, 2, 3])    # would end at 9 > 8
        assert not bool(ok)
        assert int(fs.file_len(st, 0)) == 0

    def test_set_len_truncates_and_zeroes(self):
        st = fs.fs_state(1, 8)
        fs.write_all_at(st, 0, 0, [1, 2, 3, 4])
        fs.set_len(st, 0, 2)
        assert int(fs.file_len(st, 0)) == 2
        # the dropped words read as zero even if length grows back
        fs.set_len(st, 0, 4)
        assert fs.read_at(st, 0, 0, 4).tolist() == [1, 2, 0, 0]

    def test_sync_gates_durability(self):
        st = fs.fs_state(1, 8)
        fs.write_all_at(st, 0, 0, [5, 6])
        fs.sync_all(st, 0)
        fs.write_all_at(st, 0, 2, [7])               # never synced
        # power-fail: volatile view lost, remount from disk
        st["fs_mem"] = np.zeros_like(st["fs_mem"])
        st["fs_mlen"] = np.zeros_like(st["fs_mlen"])
        fs.mount(st)
        assert int(fs.file_len(st, 0)) == 2
        assert fs.read_at(st, 0, 0, 3).tolist() == [5, 6, 0]


def _chaos(n_rounds=4, first=ms(250), gap=ms(400), down=ms(120)):
    sc = Scenario()
    for t in range(n_rounds):
        sc.at(first + gap * t).kill(wal_kv.SERVER)
        sc.at(first + gap * t + down).restart(wal_kv.SERVER)
    return sc


class TestWalRecovery:
    def test_synced_wal_survives_kill_chaos(self):
        # acked writes keep their promise across repeated power-fails
        rt = make_wal_kv_runtime(n_clients=2, n_ops=12, wal_cap=8,
                                 sync_wal=True, scenario=_chaos())
        state = run_seeds(rt, SEEDS, max_steps=40_000)
        done = np.asarray(state.node_state["c_done"])[:, 1:]
        assert (done == 1).all()

    def test_checkpoint_truncation_path(self):
        # tiny WAL: every few PUTs checkpoint to the DB file and truncate —
        # recovery must compose DB load + WAL replay correctly mid-chaos
        rt = make_wal_kv_runtime(n_clients=2, n_ops=16, wal_cap=3,
                                 sync_wal=True, scenario=_chaos(5))
        state = run_seeds(rt, SEEDS, max_steps=60_000)
        done = np.asarray(state.node_state["c_done"])[:, 1:]
        assert (done == 1).all()

    def test_unsynced_wal_loses_acked_writes(self):
        # remove the one sync between append and ack: with power-fail chaos
        # the durability oracle MUST catch a lost acked write — this test
        # flipping red is the proof the sync gate is real
        rt = make_wal_kv_runtime(n_clients=2, n_ops=12, wal_cap=64,
                                 sync_wal=False,
                                 scenario=_chaos(6, first=ms(150),
                                                 gap=ms(250), down=ms(60)))
        with pytest.raises(SimFailure) as ei:
            run_seeds(rt, np.arange(16), max_steps=60_000)
        assert ei.value.code == wal_kv.CRASH_LOST_WRITE

    def test_replay_stable(self):
        rt = make_wal_kv_runtime(n_clients=2, n_ops=8, wal_cap=4,
                                 sync_wal=True, scenario=_chaos(2))
        assert rt.check_determinism(seed=3, max_steps=20_000)


def _torn_chaos(n_rounds=4, first=ms(250), gap=ms(400), down=ms(120)):
    """The kill matrix of `_chaos` with torn-write mode armed (r17):
    every power-fail flushes a random prefix of the unsynced tail."""
    sc = Scenario()
    sc.at(500).set_disk(wal_kv.SERVER, 0, torn=True)
    for t in range(n_rounds):
        sc.at(first + gap * t).kill(wal_kv.SERVER)
        sc.at(first + gap * t + down).restart(wal_kv.SERVER)
    return sc


class TestTornWrites:
    """The r17 torn-write matrix: a SYNCED record can never tear (the
    flush touches only words at/past fs_dlen), so the sync-gated WAL
    keeps its promise even when crashes leave partially-written final
    records; remove the sync and the same torn chaos loses acked
    writes."""

    def test_synced_wal_survives_torn_kill_chaos(self):
        # sync_wal=True: every acked record is durable BEFORE the ack,
        # so torn kills (which only tear the unsynced tail) stay green
        rt = make_wal_kv_runtime(n_clients=2, n_ops=12, wal_cap=8,
                                 sync_wal=True, scenario=_torn_chaos())
        state = run_seeds(rt, SEEDS, max_steps=40_000)
        done = np.asarray(state.node_state["c_done"])[:, 1:]
        assert (done == 1).all()

    def test_unsynced_wal_torn_kill_loses_acked_writes(self):
        rt = make_wal_kv_runtime(n_clients=2, n_ops=12, wal_cap=64,
                                 sync_wal=False,
                                 scenario=_torn_chaos(6, first=ms(150),
                                                      gap=ms(250),
                                                      down=ms(60)))
        with pytest.raises(SimFailure) as ei:
            run_seeds(rt, np.arange(16), max_steps=60_000)
        assert ei.value.code == wal_kv.CRASH_LOST_WRITE

    def test_torn_cut_never_touches_synced_words(self):
        # direct engine check: with a synced prefix on disk, every torn
        # kill leaves dlen >= the synced length and the synced words
        # byte-identical; the tail beyond is a prefix of the memory view
        sc = Scenario()
        sc.at(500).set_disk(wal_kv.SERVER, 0, torn=True)
        sc.at(ms(200)).kill(wal_kv.SERVER)
        sc.at(ms(260)).restart(wal_kv.SERVER)
        # sync_wal=True: the WAL is synced at every ack, so at kill time
        # the unsynced tail is empty mid-quiescence but may hold the
        # in-flight record — either way dlen never shrinks
        rt = make_wal_kv_runtime(n_clients=2, n_ops=10, wal_cap=32,
                                 sync_wal=True, scenario=sc)
        fin = rt.run_fused(rt.init_batch(np.arange(32, dtype=np.uint32)),
                           40_000, 512)
        dlen = np.asarray(fin.node_state["fs_dlen"])[:, wal_kv.SERVER, 0]
        mlen = np.asarray(fin.node_state["fs_mlen"])[:, wal_kv.SERVER, 0]
        assert (dlen <= mlen).all()
        mem = np.asarray(fin.node_state["fs_mem"])[:, wal_kv.SERVER, 0]
        disk = np.asarray(fin.node_state["fs_disk"])[:, wal_kv.SERVER, 0]
        for b in range(dlen.shape[0]):
            np.testing.assert_array_equal(disk[b, :dlen[b]],
                                          mem[b, :dlen[b]])
