"""Time-travel replay plane (r20, DESIGN §21).

Load-bearing contracts:
(1) CHECKPOINT FIDELITY — `seed_batch_from(checkpoint_lane(...))`
continues leaf-for-leaf bit-identical (fingerprint, crash verdict,
every leaf including the observation planes) to the uninterrupted
parent lane, on the chunked AND fused runners; harvesting itself
(`run(ckpt_every=...)`) never perturbs trajectories.
(2) UPGRADE SOUNDNESS — a checkpoint re-seeded into a runtime with
MORE observability compiled in (ring/profiler/latency, any combo)
preserves fingerprints and crash verdicts; a DIFFERENT world shape
raises CheckpointMismatch (StoreMismatch-style), never garbage.
(3) TIME TRAVEL — a crash recorded with a wrapped 4-slot ring replays
from a harvested checkpoint to a complete (`truncated=False`) chain,
bit-stable across replays, whose fingerprint stays bucket-compatible
with the live truncated observation (deepest-common-suffix), and the
bucket record upgrades to the complete chain.
(4) MICROSCOPE — `divergence_report` names the same first divergent
dispatch on every re-run of the same pair.
"""

import os

import jax
import numpy as np
import pytest

from madsim_tpu import (CheckpointLog, CheckpointMismatch, LaneCheckpoint,
                        checkpoint_lane, divergence_report, explain_crash,
                        fuzz, replay_bucket, replay_window, seed_batch_from)
from madsim_tpu.obs.causal import (causal_fingerprint, fingerprints_match,
                                   sketch_divergence)
from madsim_tpu.obs.timetravel import (ReplayDivergence, advance_exact,
                                       full_chain_replay)


def _crashrich_rt(trace_cap=128):
    # trace_cap=128 SHARES executables with test_campaign/test_causal's
    # wal_kv runs (the r8 one-compile rule); trace_cap=4 is the
    # wrapped-ring specimen --tt-smoke also builds
    from bench import _make_crashrich_runtime
    return _make_crashrich_runtime("wal_kv", trace_cap=trace_cap)


def _saturating_rt(**kw):
    from bench import _make_saturating_runtime
    return _make_saturating_runtime(**kw)


def _lane_tree(state, lane):
    return jax.tree.map(lambda a: np.asarray(a)[lane], state)


def _assert_lanes_equal(a, b):
    """Leaf-for-leaf bitwise equality of two single-lane pytrees, with
    the first offending leaf named."""
    fa = jax.tree_util.tree_flatten_with_path(a)[0]
    fb = jax.tree.leaves(b)
    assert len(fa) == len(fb)
    for (path, la), lb in zip(fa, fb):
        assert np.array_equal(np.asarray(la), np.asarray(lb)), \
            f"leaf {jax.tree_util.keystr(path)} diverged"


# ---------------------------------------------------------------------------
# (1) checkpoint fidelity
# ---------------------------------------------------------------------------

class TestCheckpointFidelity:
    def test_harvest_never_perturbs_and_child_continues_bitwise(self):
        rt = _crashrich_rt()
        seeds = np.arange(12, dtype=np.uint32)
        parent, _ = rt.run(rt.init_batch(seeds), 30_000, 16)
        pfp = rt.fingerprints(parent)

        log = CheckpointLog()
        harvested, _ = rt.run(rt.init_batch(seeds), 30_000, 16,
                              ckpt_every=32, ckpt_log=log)
        # zero perturbation: harvesting is pure observation
        assert (rt.fingerprints(harvested) == pfp).all()
        assert len(log) > 2          # entry + >=2 mid-flight

        # pick a lane with a real mid-flight checkpoint
        steps = np.asarray(harvested.steps)
        lane = int(np.argmax(steps))
        ck = log.nearest(lane)
        assert 0 < ck.steps < int(steps[lane])

        # continue on BOTH runners: fingerprint, crash verdict, and
        # every leaf (observation planes included) match the parent
        child_f = rt.run_fused(seed_batch_from(ck, 3), 30_000, 16)
        assert (rt.fingerprints(child_f) == pfp[lane]).all()
        child_c, _ = rt.run(seed_batch_from(ck, 2), 30_000, 16)
        assert (rt.fingerprints(child_c) == pfp[lane]).all()
        for child in (child_f, child_c):
            assert (np.asarray(child.crashed)
                    == np.asarray(parent.crashed)[lane]).all()
            assert (np.asarray(child.crash_code)
                    == np.asarray(parent.crash_code)[lane]).all()
        _assert_lanes_equal(_lane_tree(parent, lane),
                            _lane_tree(child_f, 0))
        _assert_lanes_equal(_lane_tree(parent, lane),
                            _lane_tree(child_c, 1))

    def test_fused_harvest_matches_single_dispatch(self):
        rt = _crashrich_rt()
        seeds = np.arange(8, dtype=np.uint32)
        control = rt.run_fused(rt.init_batch(seeds), 30_000, 16)
        log = CheckpointLog()
        seg = rt.run_fused(rt.init_batch(seeds), 30_000, 16,
                           ckpt_every=32, ckpt_log=log)
        assert (rt.fingerprints(seg) == rt.fingerprints(control)).all()
        assert len(log) >= 2
        assert rt.last_ckpt_log is log

    def test_advance_exact_counts_dispatches(self):
        rt = _saturating_rt(trace_cap=16, sketch_slots=4)
        st = advance_exact(rt, rt.init_batch(np.arange(4)), 11, chunk=4)
        assert (np.asarray(st.steps) == 11).all()

    def test_checkpoint_lane_rejects_unbatched(self):
        rt = _saturating_rt(trace_cap=16, sketch_slots=4)
        with pytest.raises(ValueError, match="BATCHED"):
            checkpoint_lane(rt._template, 0)


# ---------------------------------------------------------------------------
# (durable form) save/load — the MIGRATION r20 versioned contract
# ---------------------------------------------------------------------------

class TestSaveLoad:
    def _ckpt(self, rt):
        st = advance_exact(rt, rt.init_batch(np.arange(4)), 8, chunk=4)
        return checkpoint_lane(st, 1,
                               signature=rt.cfg.structural_signature())

    def test_roundtrip_continues_identically(self, tmp_path):
        rt = _saturating_rt(trace_cap=16, sketch_slots=4)
        parent = rt.run_fused(rt.init_batch(np.arange(4)), 64, 4)
        ck = self._ckpt(rt)
        p = str(tmp_path / "lane.npz")
        ck.save(p)
        ck2 = LaneCheckpoint.load(p, rt)
        assert ck2.steps == ck.steps == 8
        assert ck2.signature == rt.cfg.structural_signature()
        child = rt.run_fused(seed_batch_from(ck2, 1, rt=rt), 64, 4)
        assert (rt.fingerprints(child)[0]
                == rt.fingerprints(parent)[1])

    def test_pre_r20_batch_snapshot_rejected_cleanly(self, tmp_path):
        from madsim_tpu.runtime import checkpoint as batch_ckpt
        rt = _saturating_rt(trace_cap=16, sketch_slots=4)
        p = str(tmp_path / "batch.npz")
        batch_ckpt.save(p, rt.init_batch(np.arange(2)))
        with pytest.raises(CheckpointMismatch, match="pre-r20"):
            LaneCheckpoint.load(p, rt)

    def test_world_signature_checked_at_load(self, tmp_path):
        rt = _saturating_rt(trace_cap=16, sketch_slots=4)
        p = str(tmp_path / "lane.npz")
        self._ckpt(rt).save(p)
        other = _crashrich_rt()              # different world entirely
        with pytest.raises(CheckpointMismatch, match="world signature"):
            LaneCheckpoint.load(p, other)

    def test_observability_difference_loads_fine(self, tmp_path):
        # same WORLD, different observability build: load succeeds (the
        # upgrade is seed_batch_from's job, not a rejection)
        rt = _saturating_rt(trace_cap=16, sketch_slots=4)
        p = str(tmp_path / "lane.npz")
        self._ckpt(rt).save(p)
        up = rt.derived(trace_cap=64, profile=True)
        ck = LaneCheckpoint.load(p, up)
        child = up.run_fused(seed_batch_from(ck, 1, rt=up), 64, 4)
        parent = rt.run_fused(rt.init_batch(np.arange(4)), 64, 4)
        assert (up.fingerprints(child)[0] == rt.fingerprints(parent)[1])


# ---------------------------------------------------------------------------
# (2) observability-upgrade matrix + world mismatch
# ---------------------------------------------------------------------------

class TestUpgradeMatrix:
    def test_every_gate_combo_preserves_fingerprint(self):
        """The satellite contract: seed_batch_from into a runtime with
        MORE observability compiled in — every combo of trace_cap,
        profile, latency_hist on/off — preserves fingerprints and the
        crash verdict of the continuation."""
        rt = _saturating_rt()        # all planes off: the lean build
        seeds = np.arange(4)
        parent = rt.run_fused(rt.init_batch(seeds), 64, 4)
        want = int(rt.fingerprints(parent)[2])
        st = advance_exact(rt, rt.init_batch(seeds), 8, chunk=4)
        ck = checkpoint_lane(st, 2,
                             signature=rt.cfg.structural_signature())
        for tc in (0, 16):
            for prof in (False, True):
                for lat in (0, 8):
                    up = rt.derived(trace_cap=tc, profile=prof,
                                    latency_hist=lat)
                    child = up.run_fused(
                        seed_batch_from(ck, 1, rt=up), 64, 4)
                    got = int(up.fingerprints(child)[0])
                    assert got == want, (tc, prof, lat)
                    assert (bool(np.asarray(child.crashed)[0])
                            == bool(np.asarray(parent.crashed)[2]))

    def test_upgraded_ring_records_the_window(self):
        rt = _saturating_rt()
        st = advance_exact(rt, rt.init_batch(np.arange(2)), 8, chunk=4)
        ck = checkpoint_lane(st, 0)
        up = rt.derived(trace_cap=64)
        child = up.run_fused(seed_batch_from(ck, 1, rt=up), 64, 4)
        from madsim_tpu.obs.rings import ring_records
        recs = ring_records(child, 0)
        # the fresh ring starts AT the checkpoint: first record is
        # dispatch 8, nothing dropped, window fully held
        assert int(np.asarray(recs["step"])[0]) == 8
        assert recs["dropped"] == 0

    def test_different_world_raises_not_garbage(self):
        rt = _saturating_rt()
        st = advance_exact(rt, rt.init_batch(np.arange(2)), 8, chunk=4)
        ck = checkpoint_lane(st, 0,
                             signature=rt.cfg.structural_signature())
        other = _crashrich_rt()
        with pytest.raises(CheckpointMismatch):
            seed_batch_from(ck, 1, rt=other)
        # and leaf-level mismatch is caught even WITHOUT a signature
        ck_unsigned = checkpoint_lane(st, 0)
        with pytest.raises(CheckpointMismatch):
            seed_batch_from(ck_unsigned, 1, rt=other)


# ---------------------------------------------------------------------------
# (3) time travel: window replay, complete chains, bucket compatibility
# ---------------------------------------------------------------------------

def _truncated_crash(rt, seeds, log):
    state, _ = rt.run(rt.init_batch(seeds), 30_000, 16,
                      ckpt_every=32, ckpt_log=log)
    steps = np.asarray(state.steps)
    for lane in np.nonzero(np.asarray(state.crashed))[0]:
        exp = explain_crash(state, int(lane))
        if exp["truncated"] and steps[lane] > 40:
            return state, int(lane), exp
    raise AssertionError("workload produced no wrap-truncated crash")


class TestTimeTravelExplain:
    def test_replay_recovers_complete_chain_bucket_compatible(self,
                                                              tmp_path):
        rt = _crashrich_rt(trace_cap=4)      # ring wraps immediately
        log = CheckpointLog()
        state, lane, live = _truncated_crash(
            rt, np.arange(24, dtype=np.uint32), log)
        tpath = str(tmp_path / "window.trace.json")
        full = explain_crash(state, lane, replay=True, rt=rt, ckpts=log,
                             export_trace=tpath)
        again = explain_crash(state, lane, replay=True, rt=rt, ckpts=log)
        assert full["replayed"] and not full["truncated"]
        assert full["chain"] == again["chain"]       # bit-stable
        assert len(full["chain"]) > len(live["chain"])
        # the live truncated chain is a faithful SUFFIX of the full one
        assert full["chain"][-len(live["chain"]):] == live["chain"]
        # completeness honesty: the replayed-complete chain merges into
        # the bucket its truncated sibling opened
        assert fingerprints_match(causal_fingerprint(full),
                                  causal_fingerprint(live))
        assert os.path.getsize(tpath) > 0
        # crash verdict carried through the replay equivalence check
        assert full["crash_code"] == live["crash_code"]

    def test_complete_live_chain_skips_replay(self):
        rt = _crashrich_rt(trace_cap=128)    # big ring: chains complete
        log = CheckpointLog()
        state, _ = rt.run(rt.init_batch(np.arange(8, dtype=np.uint32)),
                          30_000, 16, ckpt_every=32, ckpt_log=log)
        lane = int(np.nonzero(np.asarray(state.crashed))[0][0])
        live = explain_crash(state, lane)
        if live["truncated"]:
            pytest.skip("128-slot ring unexpectedly wrapped")
        out = explain_crash(state, lane, replay=True, rt=rt, ckpts=log)
        assert out["replayed"] is False
        assert out["chain"] == live["chain"]

    def test_no_checkpoints_is_a_clean_error(self):
        rt = _crashrich_rt(trace_cap=4)
        state = rt.run_fused(
            rt.init_batch(np.arange(4, dtype=np.uint32)), 30_000, 512)
        lane = int(np.nonzero(np.asarray(state.crashed))[0][0])
        with pytest.raises(ValueError, match="checkpoint"):
            explain_crash(state, lane, replay=True, rt=rt,
                          ckpts=CheckpointLog())

    def test_bucket_record_upgrades_to_complete_chain(self, tmp_path):
        """Satellite: a replayed-complete observation lands in the
        bucket its truncated sibling opened, and the bucket record is
        UPGRADED to the complete chain (repro handle unchanged)."""
        from madsim_tpu.search.mutate import KnobPlan
        from madsim_tpu.service.buckets import CrashBuckets
        from madsim_tpu.service.store import CorpusStore, store_signature
        rt = _crashrich_rt(trace_cap=4)
        log = CheckpointLog()
        state, lane, live = _truncated_crash(
            rt, np.arange(24, dtype=np.uint32), log)
        store = CorpusStore(str(tmp_path / "c"),
                            signature=store_signature(
                                rt, KnobPlan.from_runtime(rt)))
        buckets = CrashBuckets(store)
        key, opened = buckets.observe_lane(
            state, lane, seed=int(lane), knobs=None, round_no=0,
            worker_id=0)
        assert opened
        rec0 = store.load_bucket(key)
        assert rec0["chain_truncated"] is True
        assert len(rec0["chain"]) == len(live["chain"])

        full = explain_crash(state, lane, replay=True, rt=rt, ckpts=log)
        key2, opened2 = buckets.observe(
            causal_fingerprint(full), seed=int(lane), knobs=None,
            round_no=1, worker_id=0, chain=full["chain"],
            chain_truncated=full["truncated"])
        assert key2 == key and not opened2   # merged, not a second bug
        rec1 = store.load_bucket(key)
        assert rec1["chain_truncated"] is False
        assert len(rec1["chain"]) == len(full["chain"])
        assert rec1["repro"] == rec0["repro"]    # canonical handle kept

    def test_replay_bucket_full_chain_and_triage_links(self, tmp_path):
        """Satellite: replay_bucket(full_chain=True) recovers the
        complete chain + window trace; audit_buckets records chain
        completeness; snapshot/report rows link both."""
        from madsim_tpu import audit_buckets, triage_snapshot
        from madsim_tpu.service import CorpusStore
        from madsim_tpu.service.report import render_text
        d = str(tmp_path / "camp")
        rt = _crashrich_rt(trace_cap=4)
        res = fuzz(rt, max_steps=4096, batch=24, max_rounds=1,
                   dry_rounds=3, chunk=512, corpus_dir=d, worker_id=0)
        assert res["buckets_total"] >= 1
        store = CorpusStore(d, create=False)
        key = store.bucket_keys()[0]
        assert store.load_bucket(key).get("chain_truncated") is True
        crashed, _code, exp = replay_bucket(
            rt, d, key, 4096, chunk=512, full_chain=True,
            window_trace=True)
        assert crashed and exp is not None and not exp["truncated"]
        rec = store.load_bucket(key)
        assert rec["chain_truncated"] is False
        assert len(rec["chain"]) == len(exp["chain"])
        assert os.path.exists(
            store.bucket_path(key, ".window.trace.json"))
        aud = audit_buckets(rt, store, max_steps=4096, budget=2)
        row = next(r for r in aud["audited"] if r["bucket"] == key)
        assert row["chain_complete"] is True
        _n, snap = triage_snapshot(store)
        bk = snap["buckets"][key]
        assert bk["chain_complete"] and bk["window_trace"]
        text = render_text(snap)
        assert "full+tr" in text and ".window.trace.json" in text

    def test_live_lane_replays_to_exact_step(self):
        # a lane the sweep left RUNNING (hit max_steps live) replays to
        # exactly its live dispatch count — not to halt, which would
        # honestly diverge the fingerprint and raise ReplayDivergence
        from bench import _make_light_runtime
        rt = _make_light_runtime(trace_cap=4)     # never halts, tiny ring
        log = CheckpointLog()
        state, _ = rt.run(rt.init_batch(np.arange(2)), 2048, 256,
                          ckpt_every=512, ckpt_log=log)
        assert not bool(np.asarray(state.halted)[0])
        live = explain_crash(state, 0)
        assert live["truncated"]                  # 4-slot ring wrapped
        full = explain_crash(state, 0, replay=True, rt=rt, ckpts=log)
        assert full["replayed"] and not full["truncated"]
        assert full["chain"][-len(live["chain"]):] == live["chain"]

    def test_log_signature_is_per_snapshot(self):
        # a log accumulated across DIFFERENT runtimes keeps each
        # snapshot's own world signature — a later run's _ckpt_setup
        # stamp must not retroactively re-badge earlier harvests
        rt1 = _saturating_rt(trace_cap=16, sketch_slots=4)
        rt2 = _crashrich_rt()
        log = CheckpointLog()
        rt1.run(rt1.init_batch(np.arange(2)), 64, 4,
                ckpt_every=8, ckpt_log=log)
        n1 = len(log)
        rt2.run(rt2.init_batch(np.arange(2, dtype=np.uint32)), 256, 16,
                ckpt_every=32, ckpt_log=log)
        assert len(log) > n1 and log.signature == \
            rt2.cfg.structural_signature()
        oldest = log.checkpoints(0)[-1]           # an rt1-era snapshot
        assert oldest.signature == rt1.cfg.structural_signature()
        with pytest.raises(CheckpointMismatch):
            seed_batch_from(oldest, 1, rt=rt2)

    def test_replay_window_expect_mismatch_raises(self):
        rt = _saturating_rt()
        st = advance_exact(rt, rt.init_batch(np.arange(2)), 8, chunk=4)
        ck = checkpoint_lane(st, 0)
        with pytest.raises(ReplayDivergence, match="fingerprint"):
            replay_window(rt, ck, max_steps=64, chunk=4,
                          expect=dict(fingerprint=-1))

    def test_full_chain_replay_from_handle(self):
        # t=0 is always a checkpoint when the (seed) handle is known
        rt = _crashrich_rt(trace_cap=4)
        state = rt.run_fused(
            rt.init_batch(np.arange(8, dtype=np.uint32)), 30_000, 512)
        lane = int(np.nonzero(np.asarray(state.crashed))[0][0])
        rep = full_chain_replay(
            rt, seed=int(lane),
            expect=dict(fingerprint=int(rt.fingerprints(state)[lane]),
                        crashed=bool(np.asarray(state.crashed)[lane]),
                        crash_code=int(np.asarray(state.crash_code)[lane])),
            trace_cap=int(np.asarray(state.steps)[lane]) + 1)
        assert not rep["explain"]["truncated"]
        assert rep["explain"]["replayed_from_step"] == 0


# ---------------------------------------------------------------------------
# (4) divergence microscope + the sketch bound fix
# ---------------------------------------------------------------------------

class TestDivergenceMicroscope:
    def test_sketch_divergence_names_its_bound(self):
        # sketch_every=4: this workload halts near step 17, so the
        # default 64-dispatch fold period would never fill a slot
        rt = _saturating_rt(trace_cap=16,
                            sketch_slots=4).derived(sketch_every=4)
        st = rt.run_fused(
            rt.init_batch(np.asarray([7, 7, 9], np.uint32)), 64, 4)
        same = sketch_divergence(st, 0, 1)
        assert same["bound"] == "exhausted" and same["slot"] == same["slots"]
        diff = sketch_divergence(st, 0, 2)
        assert diff["bound"] == "sketch-slot"
        assert diff["slot"] < diff["slots"]

    def test_microscope_names_stable_first_dispatch(self):
        rt = _crashrich_rt(trace_cap=4)
        r1 = divergence_report(rt, 3, 5, max_steps=20_000, chunk=512)
        r2 = divergence_report(rt, 3, 5, max_steps=20_000, chunk=512)
        assert r1["diverged"]
        f = r1["first"]
        assert f is not None and f == r2["first"]
        assert f["kind"] in ("dispatch", "halt")
        if f["kind"] == "dispatch":
            # the tie that flipped: both sides' records at one step,
            # with genuinely different dispatch tokens
            assert f["a"]["step"] == f["b"]["step"] == f["step"]
            tok = ("kind", "node", "src", "tag")
            assert tuple(f["a"][k] for k in tok) != \
                tuple(f["b"][k] for k in tok)
        assert r1["suffix_a"] and r1["suffix_b"]

    def test_microscope_identical_lanes_report_no_divergence(self):
        rt = _crashrich_rt(trace_cap=4)
        r = divergence_report(rt, 3, 3, max_steps=20_000, chunk=512)
        assert r["diverged"] is False
        assert r["probe"]["bound"] == "exhausted"

    def test_microscope_two_track_trace(self, tmp_path):
        import json
        rt = _crashrich_rt(trace_cap=4)
        p = str(tmp_path / "pair.trace.json")
        r = divergence_report(rt, 3, 5, max_steps=20_000, chunk=512,
                              export_trace=p)
        assert r["trace_path"] == p
        with open(p) as f:
            doc = json.load(f)
        pids = {e.get("pid") for e in doc["traceEvents"]}
        assert pids == {0, 1}
        names = {e["args"]["name"] for e in doc["traceEvents"]
                 if e.get("ph") == "M" and e.get("name") == "process_name"}
        assert names == {"lane_a", "lane_b"}
        # flow binding is global by (cat, id): the two lanes' flow ids
        # must be disjoint or the viewer draws cross-lane arrows
        ids = [{e["id"] for e in doc["traceEvents"]
                if e.get("pid") == p and "id" in e} for p in (0, 1)]
        assert ids[0] and ids[1] and not (ids[0] & ids[1])

    def test_microscope_requires_a_difference(self):
        rt = _crashrich_rt(trace_cap=4)
        with pytest.raises(ValueError, match="diverge"):
            divergence_report(rt, 3)


# ---------------------------------------------------------------------------
# flagship fidelity matrix (slow lane): raft / wal_kv / percolator /
# minipg, run AND run_fused — the acceptance bar's named foursome
# ---------------------------------------------------------------------------

def _raft_rt():
    from madsim_tpu import NetConfig, Scenario, SimConfig, ms, sec
    from madsim_tpu.models.raft import make_raft_runtime
    cfg = SimConfig(n_nodes=5, event_capacity=128, time_limit=sec(3),
                    net=NetConfig(packet_loss_rate=0.05,
                                  send_latency_min=ms(1),
                                  send_latency_max=ms(10)))
    sc = Scenario()
    sc.at(sec(1)).kill_random()
    sc.at(sec(1) + ms(400)).restart_random()
    return make_raft_runtime(5, 8, n_cmds=4, scenario=sc, cfg=cfg)


def _percolator_rt():
    from madsim_tpu import ms
    from madsim_tpu.models.percolator import make_percolator_runtime
    from madsim_tpu.runtime.chaos import slow_disk
    return make_percolator_runtime(
        scenario=slow_disk(ms(100), ms(20), ms(700), node=0))


def _minipg_rt():
    from madsim_tpu.models.minipg import make_minipg_runtime
    return make_minipg_runtime(n_clients=2, n_txns=4)


@pytest.mark.slow
class TestFlagshipFidelity:
    @pytest.mark.parametrize("make,max_steps,chunk,every", [
        (_raft_rt, 20_000, 512, 2048),
        (lambda: _crashrich_rt(trace_cap=0), 30_000, 16, 64),
        (_percolator_rt, 60_000, 256, 1024),
        (_minipg_rt, 60_000, 256, 1024),
    ], ids=["raft", "wal_kv", "percolator", "minipg"])
    def test_checkpoint_continues_bit_identical(self, make, max_steps,
                                                chunk, every):
        rt = make()
        seeds = np.arange(6, dtype=np.uint32)
        parent, _ = rt.run(rt.init_batch(seeds), max_steps, chunk)
        pfp = rt.fingerprints(parent)
        log = CheckpointLog()
        harvested, _ = rt.run(rt.init_batch(seeds), max_steps, chunk,
                              ckpt_every=every, ckpt_log=log)
        assert (rt.fingerprints(harvested) == pfp).all()
        lane = int(np.argmax(np.asarray(harvested.steps)))
        ck = log.nearest(lane)
        assert ck is not None
        child_f = rt.run_fused(seed_batch_from(ck, 2), max_steps, chunk)
        child_c, _ = rt.run(seed_batch_from(ck, 2), max_steps, chunk)
        for child in (child_f, child_c):
            assert (rt.fingerprints(child) == pfp[lane]).all()
            assert (np.asarray(child.crashed)
                    == np.asarray(parent.crashed)[lane]).all()
            assert (np.asarray(child.crash_code)
                    == np.asarray(parent.crash_code)[lane]).all()
        _assert_lanes_equal(_lane_tree(parent, lane),
                            _lane_tree(child_c, 0))


@pytest.mark.slow
class TestRaceFullChain:
    def test_confirmed_race_attaches_complete_chain(self):
        from bench import _make_racy_runtime
        from madsim_tpu.analyze.races import confirm_race, find_races
        rt = _make_racy_runtime(trace_cap=256)
        seeds = np.arange(32, dtype=np.uint32)
        state = rt.run_fused(rt.init_batch(seeds), 20_000, 512)
        lanes = np.nonzero(np.asarray(state.crashed))[0]
        assert len(lanes)
        confirmed = None
        for cand in find_races(state, int(lanes[0]), max_pairs=4):
            conf = confirm_race(rt, int(seeds[lanes[0]]), cand,
                                max_steps=20_000, full_chain=True)
            if conf["status"] == "confirmed":
                confirmed = conf
                break
        if confirmed is None:
            pytest.skip("no candidate confirmed in this window")
        if confirmed["diff"]["commuted"]["crashed"]:
            assert confirmed["chain"], confirmed.keys()
            assert "chain_complete" in confirmed
