"""Chain replication: master-driven membership, lease-gated tail reads,
idempotent write propagation — linearizability checked with the same
oracle as KV-on-Raft, plus a per-event two-tails invariant that only a
synchronized virtual clock can state exactly."""

import numpy as np
import pytest

pytestmark = pytest.mark.slow  # chaos-sweep-heavy (r7 durations triage);
# tier-1/ci.sh fast skip it so the fast lane fits its 870s budget cold

from madsim_tpu import Scenario, SimConfig, NetConfig, ms, sec
from madsim_tpu.harness.simtest import SimFailure, run_seeds
from madsim_tpu.models import chain as C
from madsim_tpu.models.chain import extract_histories, make_chain_runtime
from madsim_tpu.native import check_kv_history

R, NC, OPS = 3, 2, 20
SEEDS = np.arange(8)


def _cfg(time_limit=sec(10), loss=0.0):
    return SimConfig(n_nodes=1 + R + NC, event_capacity=384,
                     payload_words=12, time_limit=time_limit,
                     net=NetConfig(packet_loss_rate=loss,
                                   send_latency_min=ms(1),
                                   send_latency_max=ms(8)))


def _opn(state):
    return np.asarray(state.node_state["c_opn"])[:, 1 + R:]


class TestChain:
    def test_clean_run_linearizable(self):
        rt = make_chain_runtime(R, NC, OPS, cfg=_cfg())
        state = run_seeds(rt, SEEDS, max_steps=40_000)
        assert (_opn(state) >= OPS).all()
        for h in extract_histories(state, R, NC):
            assert check_kv_history(h)
        # all replicas converged on the same registers
        kv = np.asarray(state.node_state["kv"])[:, 1:1 + R]
        assert (kv == kv[:, :1]).all()

    @pytest.mark.parametrize("victim", [1, 2, 3])  # head, middle, tail
    def test_kill_each_position(self, victim):
        # the chain must reconfigure around a dead head, middle, or tail;
        # writes stranded mid-chain are repaired by client retry-through-
        # head, reads move to the new tail after the lease drains
        sc = Scenario()
        sc.at(ms(250)).kill(victim)  # mid-workload (20 ops run ~600ms+)
        rt = make_chain_runtime(R, NC, OPS, scenario=sc,
                                cfg=_cfg(time_limit=sec(12)))
        state = run_seeds(rt, SEEDS, max_steps=60_000)
        assert (_opn(state) >= OPS).all()
        for h in extract_histories(state, R, NC):
            assert check_kv_history(h)

    def test_blip_restart_rejoins_safely(self):
        # killed and restarted BEFORE the detector fires: the replica
        # resumes in-chain with persisted registers; writes that passed it
        # while dead are un-acked (propagation stalled) and client retries
        # re-propagate them through the full chain
        sc = Scenario()
        sc.at(ms(250)).kill(2)
        sc.at(ms(300)).restart(2)     # dead_after is 100ms; detector needs
        sc.at(ms(500)).kill(2)        # sustained silence to trigger
        sc.at(ms(550)).restart(2)
        rt = make_chain_runtime(R, NC, OPS, scenario=sc,
                                cfg=_cfg(time_limit=sec(12)))
        state = run_seeds(rt, SEEDS, max_steps=60_000)
        assert (_opn(state) >= OPS).all()
        for h in extract_histories(state, R, NC):
            assert check_kv_history(h)

    def test_loss_chaos_linearizable(self):
        sc = Scenario()
        sc.at(ms(250)).kill_random(among=range(1, 1 + R))
        rt = make_chain_runtime(R, NC, OPS, scenario=sc,
                                cfg=_cfg(time_limit=sec(12), loss=0.05))
        state = run_seeds(rt, SEEDS, max_steps=80_000)
        assert (_opn(state) >= OPS).all()
        for h in extract_histories(state, R, NC):
            assert check_kv_history(h)

    def test_buggy_master_wait_caught_by_invariant(self):
        # a master that activates a new epoch WITHOUT waiting for old
        # leases to drain is a real protocol bug: pause the tail (so it
        # keeps believing in its lease), let the impatient master promote
        # a new tail, resume — two lease-holding tails coexist and the
        # per-event invariant must catch it
        sc = Scenario()
        sc.at(ms(150)).pause(R)       # the initial tail goes silent
        sc.at(ms(330)).resume(R)      # back before its 400ms lease expires
        rt = make_chain_runtime(R, NC, OPS, scenario=sc,
                                cfg=_cfg(time_limit=sec(8)),
                                lease=ms(400), master_wait=ms(1))
        with pytest.raises(SimFailure) as ei:
            run_seeds(rt, np.arange(16), max_steps=60_000)
        assert ei.value.code == C.CRASH_TWO_TAILS

    def test_replay_stable(self):
        sc = Scenario()
        sc.at(ms(250)).kill(1)
        rt = make_chain_runtime(R, NC, OPS, scenario=sc,
                                cfg=_cfg(time_limit=sec(6)))
        assert rt.check_determinism(seed=13, max_steps=30_000)
