"""SLO latency plane (DESIGN §17): histograms as pure observers, SLO as
a crash code.

The load-bearing properties: (1) the plane is an observation lever —
trajectories are bit-identical leaf-for-leaf with it on, off, compiled
out, or lane-masked, and the lh_*/ev_root_t columns are excluded from
fingerprints; (2) the sojourn histogram equals a host replay of the
step's own rule (now − earliest eligible deadline) and the e2e
histogram equals a parent-walk of the flight-recorder ring (the
root-inheritance rule, end to end); (3) buckets SATURATE; (4) quantile
estimates are exact bucket-CDF lower bounds; (5) `slo_invariant` fires
deterministically with CRASH_SLO, replays by seed, and buckets next to
ordinary crashes; (6) the fuzzer's lat_bonus scales admission energy
and fuzz rounds carry the latency fields; (7) pre-r16 checkpoints are
rejected loudly.
"""

import io

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from madsim_tpu import (CRASH_SLO, JsonlObserver, NetConfig, Runtime,
                        Scenario, SimConfig, ms, sec, slo_invariant,
                        summarize)
from madsim_tpu.core.state import TRACE_FIELDS
from madsim_tpu.core.types import EV_MSG, EV_SUPER, EV_TIMER
from madsim_tpu.models.pingpong import PingPong, state_spec
from madsim_tpu.obs import (format_latency, latency_histogram_rows,
                            latency_summary, ring_records)
from madsim_tpu.parallel.stats import (lane_e2e_p99, latency_bucket_edges,
                                       latency_counters, latency_digest)

I32_MAX = 2**31 - 1
TAG_PING = 1        # pingpong's ping message tag (models/pingpong.py)


def _pingpong_rt(lat=24, target=6, n_nodes=2, scenario=None, loss=0.0,
                 trace_cap=0, complete=True, slo_target=0, invariant=None,
                 root_kinds=()):
    cfg = SimConfig(n_nodes=n_nodes, time_limit=sec(5), latency_hist=lat,
                    trace_cap=trace_cap,
                    complete_kinds=(((EV_MSG, TAG_PING),)
                                    if lat and complete else ()),
                    root_kinds=root_kinds if lat else (),
                    slo_target=slo_target,
                    net=NetConfig(packet_loss_rate=loss,
                                  send_latency_min=ms(1),
                                  send_latency_max=ms(4)))
    return Runtime(cfg, [PingPong(n_nodes, target=target)], state_spec(),
                   scenario=scenario, invariant=invariant)


def _nonlatency_state(state) -> dict:
    out = {}
    for name in type(state).__dataclass_fields__:
        if name in TRACE_FIELDS or name in ("node_state", "ext"):
            continue
        out[name] = np.asarray(getattr(state, name))
    for i, leaf in enumerate(jax.tree.leaves(state.node_state)):
        out[f"node_state_{i}"] = np.asarray(leaf)
    return out


class TestLatencyPlane:
    def test_latency_never_perturbs_trajectory(self):
        seeds = np.arange(16, dtype=np.uint32)
        rt0 = _pingpong_rt(lat=0)
        base, _ = rt0.run(rt0.init_batch(seeds), 256, 64)
        ref = _nonlatency_state(base)
        for lanes in (None, [0, 3], []):
            rt = _pingpong_rt(lat=24)
            st, _ = rt.run(rt.init_batch(seeds, latency_lanes=lanes),
                           256, 64)
            got = _nonlatency_state(st)
            assert ref.keys() == got.keys()
            for k in ref:
                assert (ref[k] == got[k]).all(), f"lanes={lanes}: {k}"
            assert (rt0.fingerprints(base) == rt.fingerprints(st)).all()

    def test_fused_equals_chunked_on_latency_columns(self):
        rt = _pingpong_rt(lat=24, target=40, trace_cap=32)
        seeds = np.arange(8, dtype=np.uint32)
        chunked, _ = rt.run(rt.init_batch(seeds), 256, 64)
        fused = rt.run_fused(rt.init_batch(seeds), 256, 64)
        for f in TRACE_FIELDS:
            assert (np.asarray(getattr(chunked, f))
                    == np.asarray(getattr(fused, f))).all(), f
        assert int(np.asarray(fused.lh_e2e).sum()) > 0

    def test_partial_lanes_cannot_split_outcomes(self):
        seeds = np.arange(8, dtype=np.uint32)
        rt = _pingpong_rt(lat=24)
        sampled, _ = rt.run(rt.init_batch(seeds, latency_lanes=[0, 1]),
                            256, 64)
        allon, _ = rt.run(rt.init_batch(seeds), 256, 64)
        assert (rt.fingerprints(sampled) == rt.fingerprints(allon)).all()
        assert (summarize(rt, sampled, seeds)["distinct_outcomes"]
                == summarize(rt, allon, seeds)["distinct_outcomes"])

    def test_masked_lanes_record_nothing(self):
        rt = _pingpong_rt(lat=24, target=40)
        st = rt.run_fused(rt.init_batch(np.arange(4), latency_lanes=[2]),
                          128, 64)
        for f in ("lh_sojourn", "lh_e2e", "lh_slo_miss"):
            v = np.asarray(getattr(st, f))
            assert v[[0, 1, 3]].sum() == 0, f
        assert np.asarray(st.lh_e2e)[2].sum() > 0

    def test_latency_lanes_requires_compiled_plane(self):
        rt = _pingpong_rt(lat=0)
        with pytest.raises(ValueError, match="latency"):
            rt.init_batch(np.arange(4), latency_lanes=[0])

    def test_sojourn_matches_host_replay(self):
        # the step's own rule, replayed on the host: before each step,
        # compute the earliest ELIGIBLE deadline from the pre-state
        # table (all earliest ties share it, so the random tie-break
        # doesn't matter); sojourn = post-now − that deadline. Node-
        # summed per lane — attribution is covered by the e2e walk.
        from madsim_tpu.utils.hostcopy import owned_host_copy
        rt = _pingpong_rt(lat=24, target=40, n_nodes=3)
        B = 4
        state = rt.init_batch(np.arange(B, dtype=np.uint32))
        LB = rt.cfg.latency_hist
        ref = np.zeros((B, LB), np.int64)
        for _ in range(120):
            pre = {k: owned_host_copy(getattr(state, k))
                   for k in ("t_deadline", "t_kind", "t_node", "alive",
                             "paused", "halted", "steps", "now")}
            state, _ = rt.run(state, 1, 1)
            post_now = np.asarray(state.now)
            post_steps = np.asarray(state.steps)
            for b in range(B):
                if pre["halted"][b] or post_steps[b] == pre["steps"][b]:
                    continue        # frozen or no dispatch
                kind = pre["t_kind"][b].astype(np.int64)
                node = np.clip(pre["t_node"][b].astype(np.int64), 0,
                               rt.cfg.n_nodes - 1)
                parked = (pre["alive"][b][node] & pre["paused"][b][node]
                          & (kind != EV_SUPER))
                elig = (kind != 0) & ~parked
                dmin = int(pre["t_deadline"][b][elig].min())
                soj = max(int(post_now[b]) - dmin, 0)
                bkt = (0 if soj == 0
                       else min(int(soj).bit_length(), LB - 1))
                ref[b, bkt] += 1
            if bool(np.asarray(state.halted).all()):
                break
        got = np.asarray(state.lh_sojourn).sum(axis=1)     # [B, LB]
        assert (got == ref).all(), (got, ref)
        assert ref.sum() > 0

    def test_e2e_matches_ring_parent_walk(self):
        # root-inheritance end to end on a direct request/reply chain:
        # every ring completion's tr_lat equals now(completion) −
        # now(its chain's root), roots being external dispatches
        from madsim_tpu.models.rpc_echo import TAG_ECHO, make_echo_runtime
        from madsim_tpu.net import rpc
        rtag = rpc.reply_tag(TAG_ECHO)
        cfg = SimConfig(n_nodes=3, event_capacity=64, time_limit=sec(5),
                        latency_hist=24, trace_cap=512,
                        complete_kinds=((EV_MSG, rtag),),
                        root_kinds=((EV_MSG, rtag),),
                        net=NetConfig(send_latency_min=ms(1),
                                      send_latency_max=ms(8)))
        rt = make_echo_runtime(n_nodes=3, target=6, cfg=cfg)
        st = rt.run_fused(rt.init_batch(np.arange(6)), 1024, 256)
        checked = 0
        for b in range(6):
            recs = ring_records(st, b)
            assert recs["dropped"] == 0
            lat = np.asarray(recs["lat"])
            step_at = {int(s): i for i, s in enumerate(recs["step"])}
            for i in np.nonzero(lat >= 0)[0]:
                j = int(i)
                while True:
                    p = int(recs["parent"][j])
                    if p < 0 or p not in step_at:
                        root_now = int(recs["now"][j])
                        break
                    jp = step_at[p]
                    if (int(recs["kind"][jp]) == EV_MSG
                            and int(recs["tag"][jp]) == rtag):
                        root_now = int(recs["now"][jp])
                        break
                    j = jp
                assert int(lat[i]) == int(recs["now"][i]) - root_now
                checked += 1
        assert checked > 0

    def test_scenario_row_mints_root_at_dispatch(self):
        # deferred boots are external causes that mint roots at THEIR
        # dispatch time: with the whole world arriving at ms(500),
        # every chain's root is >= ms(500), so no measured latency can
        # exceed the time since boot — if roots were the absolute
        # clock's zero, completions near `now` would violate the bound
        sc = Scenario()
        sc.at(ms(500)).boot(0)
        sc.at(ms(500)).boot(1)
        rt = _pingpong_rt(lat=24, target=40, scenario=sc, trace_cap=512)
        st = rt.run_fused(rt.init_batch(np.arange(2)), 256, 64)
        recs = ring_records(st, 0)
        lat = np.asarray(recs["lat"])
        done = lat >= 0
        assert done.any()
        now_at = np.asarray(recs["now"])[done]
        assert (now_at >= ms(500)).all()
        assert (lat[done] <= now_at - ms(500)).all(), \
            "a latency exceeded time-since-boot: root not minted at " \
            "the scenario row's dispatch"

    def test_buckets_saturate_no_wraparound(self):
        rt = _pingpong_rt(lat=24, target=40)
        st = rt.init_batch(np.arange(4))
        st = st.replace(
            lh_sojourn=jnp.full_like(st.lh_sojourn, I32_MAX),
            lh_e2e=jnp.full_like(st.lh_e2e, I32_MAX - 1),
            lh_slo_miss=jnp.full_like(st.lh_slo_miss, I32_MAX))
        final = rt.run_fused(st, 256, 64)
        for f in ("lh_sojourn", "lh_e2e", "lh_slo_miss"):
            v = np.asarray(getattr(final, f))
            assert (v >= 0).all() and (v <= I32_MAX).all(), f
        assert (np.asarray(final.lh_sojourn) == I32_MAX).all()

    def test_slo_target_is_dynamic(self):
        # same executable, different targets: miss counts move, nothing
        # else does (slo_target is observation-side state)
        rt = _pingpong_rt(lat=24, target=40)
        base = rt.run_fused(rt.init_batch(np.arange(4)), 256, 64)
        assert int(np.asarray(base.lh_slo_miss).sum()) == 0   # target 0
        st = rt.set_slo_target(rt.init_batch(np.arange(4)), 1)
        tight = rt.run_fused(st, 256, 64)
        assert int(np.asarray(tight.lh_slo_miss).sum()) > 0
        assert (rt.fingerprints(base) == rt.fingerprints(tight)).all()
        assert (np.asarray(base.lh_e2e)
                == np.asarray(tight.lh_e2e)).all()
        rt0 = _pingpong_rt(lat=0)
        with pytest.raises(ValueError, match="latency"):
            rt0.set_slo_target(rt0.init_batch(np.arange(2)), 5)


class TestFlagshipEquivalence:
    """Leaf-for-leaf equivalence with the plane on/off/compiled-out over
    the flagships — wal_kv fast, raft/shard_kv slow (the r7/r15
    pattern)."""

    def _assert_transparent(self, make_rt, seeds, steps, chunk):
        rt_on = make_rt(True)
        rt_off = make_rt(False)
        on, _ = rt_on.run(rt_on.init_batch(seeds), steps, chunk)
        off, _ = rt_off.run(rt_off.init_batch(seeds), steps, chunk)
        fused = rt_on.run_fused(rt_on.init_batch(seeds), steps, chunk)
        ref = _nonlatency_state(off)
        got = _nonlatency_state(on)
        assert ref.keys() == got.keys()
        for k in ref:
            assert (ref[k] == got[k]).all(), k
        assert (rt_on.fingerprints(on) == rt_off.fingerprints(off)).all()
        for f in TRACE_FIELDS:
            assert (np.asarray(getattr(on, f))
                    == np.asarray(getattr(fused, f))).all(), f
        return on

    def test_wal_kv_latency_transparent(self):
        from madsim_tpu.models.wal_kv import M_ACK, make_wal_kv_runtime

        def make(lat):
            sc = Scenario()
            for t in range(6):
                sc.at(ms(150) + ms(250) * t).kill(0)
                sc.at(ms(210) + ms(250) * t).restart(0)
            cfg = SimConfig(n_nodes=3, event_capacity=256, payload_words=8,
                            time_limit=sec(10),
                            latency_hist=20 if lat else 0,
                            complete_kinds=(((EV_MSG, M_ACK),)
                                            if lat else ()),
                            net=NetConfig(send_latency_min=ms(1),
                                          send_latency_max=ms(8)))
            return make_wal_kv_runtime(n_clients=2, n_ops=8, wal_cap=64,
                                       sync_wal=False, scenario=sc, cfg=cfg)

        on = self._assert_transparent(
            make, np.arange(16, dtype=np.uint32), 2048, 512)
        assert int(np.asarray(on.lh_e2e).sum()) > 0

    @pytest.mark.slow
    def test_raft_latency_transparent(self):
        from madsim_tpu.models.raft import make_raft_runtime

        def make(lat):
            cfg = SimConfig(n_nodes=5, event_capacity=128,
                            time_limit=sec(3),
                            latency_hist=20 if lat else 0,
                            complete_kinds=(((EV_MSG, 1),) if lat else ()),
                            net=NetConfig(packet_loss_rate=0.05,
                                          send_latency_min=ms(1),
                                          send_latency_max=ms(10)))
            sc = Scenario()
            sc.at(sec(1)).kill_random()
            sc.at(sec(1) + ms(400)).restart_random()
            return make_raft_runtime(5, 8, n_cmds=4, scenario=sc, cfg=cfg)

        self._assert_transparent(
            make, np.arange(64, dtype=np.uint32), 1500, 256)

    @pytest.mark.slow
    def test_shard_kv_latency_transparent(self):
        from madsim_tpu.models.shard_kv import CMD, T_NEW, \
            make_shard_runtime

        def make(lat):
            cfg = SimConfig(n_nodes=11, event_capacity=160,
                            payload_words=12, time_limit=sec(60),
                            latency_hist=24 if lat else 0,
                            complete_kinds=(((EV_MSG, CMD),)
                                            if lat else ()),
                            root_kinds=(((EV_TIMER, T_NEW),)
                                        if lat else ()),
                            net=NetConfig(send_latency_min=ms(1),
                                          send_latency_max=ms(10)))
            return make_shard_runtime(n_groups=2, rg=3, rc=3, n_clients=2,
                                      n_ops=4, max_cfg=4, cfg=cfg)

        on = self._assert_transparent(
            make, np.arange(64, dtype=np.uint32), 4096, 512)
        assert int(np.asarray(on.lh_e2e).sum()) > 0


class TestDigestAndReport:
    def test_digest_compiled_out_is_none(self):
        rt = _pingpong_rt(lat=0)
        st, _ = rt.run(rt.init_batch(np.arange(2)), 128, 64)
        assert latency_digest(st) is None
        assert latency_counters(st) is None
        assert latency_summary(st) is None
        assert lane_e2e_p99(st) is None
        assert summarize(rt, st)["latency"] is None
        assert "compiled out" in format_latency(None)

    def test_quantiles_are_bucket_cdf_lower_bounds(self):
        # crafted histogram: 100 samples in bucket 3 ([4, 8)), 1 sample
        # in bucket 10 ([512, 1024)) — p50/p90 read edge 4, p999 reads
        # edge 512; exact, deterministic
        rt = _pingpong_rt(lat=24, target=40)
        st = rt.init_batch(np.arange(2))
        lh = np.zeros(np.asarray(st.lh_e2e).shape, np.int32)
        lh[:, 0, 3] = 100
        lh[:, 0, 10] = 1
        st = st.replace(lh_e2e=jnp.asarray(lh))
        c = latency_counters(st)
        assert c["e2e_p50"] == 4 and c["e2e_p90"] == 4
        assert c["e2e_p999"] == 512
        assert (np.asarray(lane_e2e_p99(st)) == 4).all()
        edges = latency_bucket_edges(24)
        assert edges[0] == 0 and edges[1] == 1 and edges[3] == 4
        rows = latency_histogram_rows(st)
        assert {r["bucket"] for r in rows} == {3, 10}

    def test_summary_masking_and_render(self):
        rt = _pingpong_rt(lat=24, target=40, slo_target=1)
        st = rt.run_fused(rt.init_batch(np.arange(8),
                                        latency_lanes=[1, 4]), 256, 64)
        c = latency_counters(st)
        assert c["lanes"] == 2
        per_lane = np.asarray(st.lh_e2e).sum((1, 2))
        assert c["e2e_hist"].sum() == per_lane[[1, 4]].sum()
        s = latency_summary(st)
        assert s["completions"] == int(per_lane[[1, 4]].sum())
        assert s["slo_miss"] == s["completions"]       # target 1 tick
        txt = format_latency(s, node_names=["ping", "pong"])
        assert "ping" in txt and "slo_miss" in txt
        rep = summarize(rt, st, np.arange(8))
        assert rep["latency"]["lanes"] == 2
        assert rep["latency"]["slo_miss"] == s["slo_miss"]

    def test_all_masked_batch_reads_zero(self):
        rt = _pingpong_rt(lat=24, target=40)
        st = rt.run_fused(rt.init_batch(np.arange(4), latency_lanes=[]),
                          128, 64)
        c = latency_counters(st)
        assert c["lanes"] == 0
        assert c["e2e_hist"].sum() == 0 and c["e2e_p99"] == 0

    def test_lat_ring_column_needs_both_gates(self):
        rt = _pingpong_rt(lat=0, target=40, trace_cap=16)
        st = rt.run_fused(rt.init_batch(np.arange(2)), 128, 64)
        assert "lat" not in ring_records(st, 0)
        rt2 = _pingpong_rt(lat=24, target=40, trace_cap=16)
        st2 = rt2.run_fused(rt2.init_batch(np.arange(2)), 128, 64)
        recs = ring_records(st2, 0)
        assert "lat" in recs and (np.asarray(recs["lat"]) >= -1).all()
        assert (np.asarray(recs["lat"]) >= 0).any()

    def test_rolling_p99_counter_track(self):
        from madsim_tpu.obs import counter_track_events
        rt = _pingpong_rt(lat=24, target=40, trace_cap=64)
        st = rt.run_fused(rt.init_batch(np.arange(2)), 192, 64)
        evs = counter_track_events(st, lane=0)
        p99s = [e for e in evs if e["name"].startswith("e2e_p99:")]
        assert p99s and all(e["args"]["p99_us"] >= 0 for e in p99s)


class TestSloInvariant:
    def test_fires_deterministically_with_crash_slo(self):
        rt = _pingpong_rt(lat=24, target=40,
                          invariant=slo_invariant(p99_le=1, min_count=4))
        a = rt.run_fused(rt.init_batch(np.arange(8)), 256, 64)
        b = rt.run_fused(rt.init_batch(np.arange(8)), 256, 64)
        assert (np.asarray(a.crash_code) == CRASH_SLO).all()
        assert (np.asarray(a.crash_code) == np.asarray(b.crash_code)).all()
        assert (np.asarray(a.steps) == np.asarray(b.steps)).all()
        assert (rt.fingerprints(a) == rt.fingerprints(b)).all()
        # seed replay reproduces the SLO crash (the repro contract)
        single, _ = rt.run_single(3, 256, 64)
        assert int(np.asarray(single.crash_code)[0]) == CRASH_SLO

    def test_min_count_gates_firing(self):
        rt = _pingpong_rt(lat=24, target=6,
                          invariant=slo_invariant(p99_le=1,
                                                  min_count=10**6))
        st = rt.run_fused(rt.init_batch(np.arange(4)), 256, 64)
        assert not np.asarray(st.crashed).any()

    def test_arg_validation(self):
        with pytest.raises(ValueError, match="p99_le"):
            slo_invariant()
        with pytest.raises(ValueError, match="q must"):
            slo_invariant(q="p42", target=5)

    def test_raises_on_compiled_out_plane(self):
        rt = _pingpong_rt(lat=0, invariant=slo_invariant(p99_le=1))
        with pytest.raises(ValueError, match="latency plane"):
            rt.run(rt.init_batch(np.arange(2)), 64, 64)

    def test_slo_crash_buckets_next_to_crashes(self, tmp_path):
        # SLO-as-crash inherits the triage stack: a durable fuzz on an
        # SLO-violating runtime must open a causal-fingerprint bucket
        # whose crash_code is CRASH_SLO, like any safety bug
        from madsim_tpu.search.fuzz import fuzz
        from madsim_tpu.service.store import CorpusStore
        sc = Scenario()
        sc.at(ms(40)).kill_random()
        sc.at(ms(400)).restart_random()
        rt = _pingpong_rt(lat=24, target=40, scenario=sc, trace_cap=64,
                          n_nodes=4,
                          invariant=slo_invariant(p99_le=1, min_count=4))
        d = str(tmp_path / "c")
        res = fuzz(rt, max_steps=300, batch=8, max_rounds=2, dry_rounds=9,
                   chunk=128, corpus_dir=d)
        assert CRASH_SLO in res["crash_repros"]
        store = CorpusStore(d, create=False)
        codes = {store.load_bucket(k)["crash_code"]
                 for k in store.bucket_keys()}
        assert CRASH_SLO in codes


class TestLatBonusAndRecords:
    def test_corpus_lat_bonus_scales_admission_energy(self):
        from bench import _make_saturating_runtime
        from madsim_tpu.search.corpus import Corpus
        from madsim_tpu.search.mutate import KnobPlan
        rt = _make_saturating_runtime()
        plan = KnobPlan.from_runtime(rt)
        c = Corpus(plan, lat_bonus=1.0)
        kb = plan.base_batch(2)
        c.observe(kb, np.arange(2), np.asarray([1, 2], np.uint64),
                  np.zeros(2, bool), np.zeros(2, np.int64),
                  np.full(2, -1, np.int64), 0,
                  lat_p99=np.asarray([100, 1000], np.int32))
        by_hash = {e["hash"]: e["energy"] for e in c.entries}
        assert by_hash[2] == pytest.approx(2.0)    # worst tail: x(1+1)
        assert by_hash[1] == pytest.approx(1.1)    # 100/1000 relative
        # latency-blind corpus ignores the signal entirely
        c0 = Corpus(plan, lat_bonus=0.0)
        c0.observe(kb, np.arange(2), np.asarray([1, 2], np.uint64),
                   np.zeros(2, bool), np.zeros(2, np.int64),
                   np.full(2, -1, np.int64), 0,
                   lat_p99=np.asarray([100, 1000], np.int32))
        assert all(e["energy"] == 1.0 for e in c0.entries)

    def test_fuzz_rounds_carry_latency_fields(self):
        sc = Scenario()
        sc.at(ms(40)).kill_random()
        sc.at(ms(400)).restart_random()
        rt = _pingpong_rt(lat=24, target=6, scenario=sc, n_nodes=4)
        from madsim_tpu.search.fuzz import fuzz
        obs = JsonlObserver(io.StringIO())
        fuzz(rt, max_steps=300, batch=8, max_rounds=3, dry_rounds=9,
             chunk=128, lat_bonus=1.0, observer=obs)
        rounds = [r for r in obs.records if r.get("kind") == "fuzz_round"]
        assert rounds
        for rec in rounds:
            assert "lat_p99" in rec and "slo_miss" in rec
            assert rec["lat_p99"] >= 0
        # a plane-free runtime emits rounds WITHOUT the fields
        rt0 = _pingpong_rt(lat=0, target=6, scenario=sc, n_nodes=4)
        obs0 = JsonlObserver(io.StringIO())
        fuzz(rt0, max_steps=300, batch=8, max_rounds=2, dry_rounds=9,
             chunk=128, observer=obs0)
        r0 = [r for r in obs0.records if r.get("kind") == "fuzz_round"]
        assert r0 and all("lat_p99" not in r for r in r0)

    def test_sweep_done_record_carries_latency(self):
        rt = _pingpong_rt(lat=24, target=40, slo_target=1)
        obs = JsonlObserver(io.StringIO())
        rt.run(rt.init_batch(np.arange(4)), 128, 64, observer=obs)
        done = [r for r in obs.records if r["kind"] == "done"][-1]
        assert done["lat_p99"] >= 0 and done["slo_miss"] > 0

    def test_timeline_p99_curve(self, tmp_path):
        from madsim_tpu.service.campaign import campaign_timeline
        from madsim_tpu.service.store import CorpusStore
        d = str(tmp_path / "c")
        store = CorpusStore(d, signature=["sig"])
        store.append_metrics(0, dict(t=1000.0, rounds_done=1, coverage=3,
                                     wall_s=1.0, lat_p99=250_000,
                                     slo_miss=2))
        store.append_metrics(0, dict(t=1002.0, rounds_done=2, coverage=5,
                                     wall_s=2.0, lat_p99=310_000,
                                     slo_miss=4))
        tl = campaign_timeline(store)
        assert tl["p99_curve"] == [[0.0, 250_000], [2.0, 310_000]]
        # rows without the field contribute nothing (pre-r16 dirs)
        store.append_metrics(1, dict(t=1003.0, rounds_done=1, coverage=6,
                                     wall_s=1.0))
        assert len(campaign_timeline(store)["p99_curve"]) == 2


class TestCheckpointMigration:
    def test_pre_r16_checkpoint_rejected_by_leaf_count(self, tmp_path):
        # the MIGRATION r16 contract: a pre-r16 checkpoint (no lh_*/
        # ev_root_t/slo_target/tr_lat leaves — 7 fewer) fails load()
        # loudly on the leaf count, not by silent misalignment
        from madsim_tpu.runtime import checkpoint
        rt = _pingpong_rt(lat=24)
        st = rt.init_batch(np.arange(2))
        p = str(tmp_path / "ck.npz")
        checkpoint.save(p, st)
        with np.load(p) as z:
            leaves = {k: z[k] for k in z.files}
        n = len([k for k in leaves if k.startswith("leaf_")])
        stripped = {k: v for k, v in leaves.items()
                    if not k.startswith("leaf_")}
        for i in range(n - 7):
            stripped[f"leaf_{i}"] = leaves[f"leaf_{i}"]
        p2 = str(tmp_path / "old.npz")
        np.savez_compressed(p2, **stripped)
        with pytest.raises(ValueError, match="leaves"):
            checkpoint.load(p2, st)
