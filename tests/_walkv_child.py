"""Child process for the kill -9 durability test (run by
tests/test_real_runtime.py::TestRealProcessDeath).

Runs a 2-node WAL-KV workload (server node 0 + client node 1) against
real sockets with on-disk stable storage (`RealRuntime(data_dir=...)`),
printing an `ACKED v0 v1` snapshot of the client's per-key acked values
after every poll tick. The parent watches stdout and SIGKILLs this whole
process mid-run — the real-world power-fail the reference's std mode gets
for free from actual files (std/fs.rs:1-60) and the sim models with
page-cache-vs-disk views (fs.py).

argv: data_dir base_port sync|nosync [transport]
"""

import asyncio
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# this workload is real-sockets + real-disk; the chip is irrelevant — and
# the environment's sitecustomize pins jax at the (possibly wedged) TPU
# tunnel, so force CPU exactly the way tests/conftest.py does
os.environ.setdefault("JAX_PLATFORMS", "cpu")
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

from madsim_tpu import SimConfig
from madsim_tpu.core.types import ms, sec
from madsim_tpu.models.wal_kv import (WalKvClient, WalKvServer,
                                      wal_persist_spec, wal_state_spec)
from madsim_tpu.real.runtime import RealRuntime


def main():
    data_dir, base_port, sync_flag = (
        sys.argv[1], int(sys.argv[2]), sys.argv[3])
    transport = sys.argv[4] if len(sys.argv) > 4 else "udp"
    cfg = SimConfig(n_nodes=2, time_limit=sec(60))
    # wal_cap larger than total ops: no checkpoint fires, so in the
    # nosync world NOTHING ever reaches the disk view — the red case is
    # deterministic once one ack is out
    rt = RealRuntime(
        cfg,
        [WalKvServer(n_keys=2, wal_cap=64, sync_wal=sync_flag == "sync"),
         WalKvClient(n_ops=40, keys_per_client=2,
                     timeout=ms(80), think=ms(5))],
        wal_state_spec(2, 2, 64, 2), node_prog=[0, 1],
        base_port=base_port, persist=wal_persist_spec(),
        data_dir=data_dir, transport=transport)

    async def scenario():
        await rt.start()
        while True:                     # parent SIGKILLs us mid-loop
            await asyncio.sleep(0.02)
            acked = [int(v) for v in rt.nodes[1].state["acked"]]
            print("ACKED", *acked, flush=True)

    asyncio.run(scenario())


if __name__ == "__main__":
    main()
