"""Extension framework tests (the plugin.rs analog): custom per-trajectory
state, scenario-scheduled custom ops, per-event hooks, node-reset hooks."""

import jax.numpy as jnp
import numpy as np

from madsim_tpu import Runtime, Scenario, SimConfig, ms, sec
from madsim_tpu.core import types as T
from madsim_tpu.core.extension import Extension, OP_USER
from madsim_tpu.harness.simtest import run_seeds
from madsim_tpu.models.pingpong import PingPong, state_spec

OP_SET_BUDGET = OP_USER + 1


class PowerMeter(Extension):
    """Example resource simulator: per-node event-energy accounting with a
    scenario-settable budget — the kind of custom resource madsim users
    register via add_simulator (runtime/mod.rs:66)."""

    name = "power"

    def __init__(self, n_nodes):
        self.n = n_nodes

    def state(self, cfg):
        return dict(
            used=jnp.zeros((self.n,), jnp.int32),   # events dispatched
            budget=jnp.full((self.n,), 10**9, jnp.int32),
        )

    def on_op(self, cfg, sub, op, target, src, payload, key):
        hit = op == OP_SET_BUDGET
        t = jnp.clip(target, 0, self.n - 1)
        sub = dict(sub)
        sub["budget"] = sub["budget"].at[t].set(
            jnp.where(hit, payload[0], sub["budget"][t]))
        return sub

    def on_event(self, cfg, sub, state, record):
        n = jnp.clip(record["node"], 0, self.n - 1)
        hit = record["fired"] & (record["kind"] != T.EV_SUPER)
        sub = dict(sub)
        sub["used"] = sub["used"].at[n].set(
            jnp.where(hit, sub["used"][n] + 1, sub["used"][n]))
        return sub

    def reset_node(self, cfg, sub, node, when):
        n = jnp.clip(node, 0, self.n - 1)
        sub = dict(sub)
        sub["used"] = sub["used"].at[n].set(
            jnp.where(when, 0, sub["used"][n]))
        return sub


class TestExtension:
    def _rt(self, scenario=None):
        n = 3
        cfg = SimConfig(n_nodes=n, time_limit=sec(30))
        return Runtime(cfg, [PingPong(n, target=10)], state_spec(),
                       scenario=scenario, extensions=[PowerMeter(n)])

    def test_per_event_accounting(self):
        rt = self._rt()
        state = run_seeds(rt, np.arange(8), max_steps=8000)
        used = np.asarray(state.ext["power"]["used"])
        assert (used.sum(axis=1) > 20).all()        # events were metered
        assert (used[:, 0] > 0).all()               # pinger did work

    def test_custom_op_scheduled(self):
        sc = Scenario()
        sc.at(ms(1)).custom(OP_SET_BUDGET, node=1, payload=(777,))
        rt = self._rt(scenario=sc)
        state = run_seeds(rt, np.arange(4), max_steps=8000)
        budget = np.asarray(state.ext["power"]["budget"])
        assert (budget[:, 1] == 777).all()
        assert (budget[:, 0] == 10**9).all()        # untouched

    def test_reset_on_kill(self):
        sc = Scenario()
        sc.at(ms(50)).kill(1)
        sc.at(sec(25)).restart(1)                   # near the end
        rt = self._rt(scenario=sc)
        state, _ = rt.run(rt.init_batch(np.arange(4)), 40_000)
        used = np.asarray(state.ext["power"]["used"])
        # node 1's meter was reset at kill; it saw few events afterwards
        assert (used[:, 1] < used[:, 0]).all()

    def test_determinism_with_extension(self):
        rt = self._rt()
        assert rt.check_determinism(seed=11, max_steps=6000)
