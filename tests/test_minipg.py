"""minipg: the postgres-analog session protocol over the sim TCP stack —
handshake/auth, pipelined statements, exactly-once transactions — under
chaos, plus the SAME protocol code over real sockets (the
madsim-tokio-postgres dual-world claim, socket.rs:6-13)."""

import numpy as np
import pytest

from madsim_tpu import Scenario, SimConfig, NetConfig, ms, sec
from madsim_tpu.harness.simtest import run_seeds
from madsim_tpu.models import minipg
from madsim_tpu.models.minipg import make_minipg_runtime

SEEDS = np.arange(8)


pytestmark = pytest.mark.slow  # measured in --durations; ci.sh fast skips

def _cfg(loss=0.0, time_limit=sec(10)):
    return SimConfig(n_nodes=3, event_capacity=64, payload_words=8,
                     time_limit=time_limit,
                     net=NetConfig(packet_loss_rate=loss,
                                   send_latency_min=ms(1),
                                   send_latency_max=ms(8)))


def _check_final_kv(state, n_clients, n_txns):
    """Committed transactions (odd tids) must be exactly what the table
    holds; rolled-back ones (even tids) must be invisible."""
    kv = np.asarray(state.node_state["kv"])[:, minipg.SERVER]
    last_commit = max((t for t in range(1, n_txns + 1) if t % 2 == 1),
                      default=0)
    for c in range(1, n_clients + 1):
        v = c * 10000 + last_commit * 10
        np.testing.assert_array_equal(kv[:, (c - 1) * 2], v)
        np.testing.assert_array_equal(kv[:, (c - 1) * 2 + 1], v + 1000)


def _done(state):
    return np.asarray(state.node_state["c_done"])[:, 1:]


class TestSessions:
    def test_clean_run_commits_and_rolls_back(self):
        rt = make_minipg_runtime(n_clients=2, n_txns=4, cfg=_cfg())
        state = run_seeds(rt, SEEDS, max_steps=30_000)
        assert (_done(state) == 1).all()
        _check_final_kv(state, 2, 4)

    def test_wrong_password_refused(self):
        # the refusal path: ERROR / connection reset, never READY (a READY
        # with bad credentials would crash via the in-model oracle)
        rt = make_minipg_runtime(n_clients=2, n_txns=2, cfg=_cfg(),
                                 wrong_password=True)
        # rejected lanes never halt on their own — cap virtual time (a
        # DYNAMIC knob: no recompile) so the run stops right after the
        # refused handshakes instead of burning the full step budget
        state = run_seeds(rt, SEEDS, max_steps=30_000,
                          time_limit_override=sec(2))
        rej = np.asarray(state.node_state["c_rej"])[:, 1:]
        assert (rej == 1).all()


class TestChaos:
    def test_commits_survive_server_kills(self):
        # the server dies mid-session repeatedly; clients re-handshake and
        # re-run their current txn — txn-id dedup makes commits
        # exactly-once, and the pipelined verify-GETs check visibility
        sc = Scenario()
        for t in range(3):
            sc.at(ms(250 + 500 * t)).kill(minipg.SERVER)
            sc.at(ms(250 + 500 * t) + ms(120)).restart(minipg.SERVER)
        rt = make_minipg_runtime(n_clients=2, n_txns=4, scenario=sc,
                                 cfg=_cfg(time_limit=sec(10)))
        state = run_seeds(rt, SEEDS, max_steps=60_000)
        assert (_done(state) == 1).all()
        _check_final_kv(state, 2, 4)

    def test_complete_under_loss(self):
        rt = make_minipg_runtime(n_clients=2, n_txns=4,
                                 cfg=_cfg(loss=0.10, time_limit=sec(12)))
        state = run_seeds(rt, SEEDS, max_steps=60_000)
        assert (_done(state) == 1).all()
        _check_final_kv(state, 2, 4)

    def test_replay_stable(self):
        sc = Scenario()
        sc.at(ms(300)).kill(minipg.SERVER)
        sc.at(ms(450)).restart(minipg.SERVER)
        rt = make_minipg_runtime(n_clients=2, n_txns=3, scenario=sc,
                                 cfg=_cfg(loss=0.05))
        assert rt.check_determinism(seed=9, max_steps=30_000)


@pytest.mark.realworld
class TestRealWorld:
    """The same PgServer/PgClient classes — zero changes — over real
    asyncio sockets (the dual-world contract)."""

    @pytest.mark.parametrize("transport,port", [("udp", 19500),
                                                ("tcp", 19520),
                                                ("local", 19540)])
    def test_minipg_over_real_sockets(self, transport, port):
        from madsim_tpu.models.minipg import (PgClient, PgServer,
                                              pg_state_spec)
        from madsim_tpu.real.runtime import RealRuntime
        n, n_txns = 2, 2
        cfg = SimConfig(n_nodes=n, time_limit=sec(60), payload_words=8)
        # eager (uncompiled) handler dispatch costs ~5-15ms per event on an
        # idle box and several times that under a parallel test run, so
        # pace the real world WAY below that budget: one client, slow
        # ticks, and a stall timeout far above worst-case queueing delay —
        # a too-eager watchdog under CPU saturation causes reset livelock
        # (congestion collapse), exactly like an aggressive TCP RTO
        rt = RealRuntime(cfg, [PgServer(n, 4, tick=ms(110)),
                               PgClient(n_txns, tick=ms(140),
                                        stall=ms(6000))],
                         pg_state_spec(n, 4), node_prog=[0, 1],
                         base_port=port, transport=transport)
        rt.run(duration=35.0)
        assert not rt.crashed
        done = [int(s["c_done"]) for s in rt.states()[1:]]
        assert all(d == 1 for d in done), done
        kv = np.asarray(rt.states()[0]["kv"])
        v = 1 * 10000 + 1 * 10        # last committed tid = 1 (tid 2 rolls back)
        assert kv[0] == v
        assert kv[1] == v + 1000
