"""RPC echo service tests — BASELINE.md config 3 (the tonic-example analog:
server + clients, typed calls with retries, under loss and kill/restart)."""

import numpy as np

from madsim_tpu import Scenario, SimConfig, NetConfig, ms, sec
from madsim_tpu.harness.simtest import run_seeds
from madsim_tpu.models.rpc_echo import make_echo_runtime

SEEDS = np.arange(8)


def _cfg(loss=0.0, time_limit=sec(20)):
    return SimConfig(n_nodes=6, event_capacity=256, time_limit=time_limit,
                     net=NetConfig(packet_loss_rate=loss,
                                   send_latency_min=ms(1),
                                   send_latency_max=ms(10)))


class TestEcho:
    def test_all_clients_complete(self):
        rt = make_echo_runtime(n_nodes=6, target=10, cfg=_cfg())
        state = run_seeds(rt, SEEDS, max_steps=10_000)
        acked = np.asarray(state.node_state["acked"])
        assert (acked[:, 1:] >= 10).all()
        served = np.asarray(state.node_state["served"])[:, 0]
        assert (served >= 50).all()  # 5 clients x 10 calls (>= for retries)
        # halted via the global halt_when, before the time limit
        assert (np.asarray(state.now) < sec(20)).all()

    def test_completes_under_heavy_loss(self):
        rt = make_echo_runtime(n_nodes=6, target=5, cfg=_cfg(loss=0.3))
        state = run_seeds(rt, SEEDS, max_steps=40_000)
        acked = np.asarray(state.node_state["acked"])
        assert (acked[:, 1:] >= 5).all()
        # at-least-once: retries mean the server served >= acked total
        served = np.asarray(state.node_state["served"])[:, 0]
        assert (served >= 25).all()

    def test_server_kill_restart_midway(self):
        # kill at 20ms: 16 sequential calls at >= 2ms RTT each cannot have
        # completed yet, so every seed must ride out the dead window
        sc = Scenario()
        sc.at(ms(20)).kill(0)
        sc.at(sec(2)).restart(0)
        rt = make_echo_runtime(n_nodes=6, target=16, scenario=sc, cfg=_cfg())
        state = run_seeds(rt, SEEDS, max_steps=40_000)
        acked = np.asarray(state.node_state["acked"])
        assert (acked[:, 1:] >= 16).all()
        # the dead window forced client retries past the restart
        assert (np.asarray(state.now) > sec(2)).all()
