"""Shared harness for the r17 bit-identical-when-disabled contract.

The gray-failure fault plane (r17) added engine machinery — one-way
partition cuts, per-node clock skew, slow-disk emission delay, torn-write
kill flush — that is DYNAMIC: always compiled in, masked to identity at
the zero defaults. The contract is that a scenario using none of the new
ops produces trajectories BIT-IDENTICAL to r16, leaf for leaf, chunked
and fused.

"Identical to r16" is enforced against captured truth, not a slogan:
`scripts/capture_golden.py` ran these exact workloads AT r16 HEAD (before
any r17 engine change landed) and froze per-leaf sha256 digests into
`tests/data/golden_r16_leaves.json`; `tests/test_grayfail.py` re-runs
them on the current tree and compares digest-for-digest. Every r16 leaf
must still exist and hash identically — new r17 leaves are allowed (they
are exactly what the simconfig-v5 signature bump gates), but no r16 leaf
may move by a single bit.

Keep the builders here frozen: they define what the golden file means.
"""

from __future__ import annotations

import hashlib
import json
import os

import jax
import numpy as np

GOLDEN_PATH = os.path.join(os.path.dirname(__file__), "data",
                           "golden_r16_leaves.json")

# run parameters are part of the frozen definition
RUNS = dict(
    pingpong=dict(seeds=64, max_steps=4000, chunk=256),
    wal_kv=dict(seeds=32, max_steps=30_000, chunk=512),
)


def build_pingpong():
    """The saturating pingpong chaos workload (bench.py's regime), with
    the recorder compiled in so ring columns are covered too."""
    from madsim_tpu import NetConfig, Runtime, Scenario, SimConfig, ms, sec
    from madsim_tpu.models.pingpong import PingPong, state_spec
    sc = Scenario()
    sc.at(ms(40)).kill_random()
    sc.at(ms(400)).restart_random()
    cfg = SimConfig(n_nodes=4, time_limit=sec(5), trace_cap=64,
                    net=NetConfig(send_latency_min=ms(1),
                                  send_latency_max=ms(1)))
    return Runtime(cfg, [PingPong(4, target=6)], state_spec(), scenario=sc)


def build_wal_kv():
    """The WAL-KV kill/restart chaos matrix (tests/test_fs.py's shape):
    stable storage, persist masks, recovery — the fs-layer workload."""
    from madsim_tpu import Scenario, ms
    from madsim_tpu.models.wal_kv import SERVER, make_wal_kv_runtime
    sc = Scenario()
    for t in range(4):
        sc.at(ms(250) + ms(400) * t).kill(SERVER)
        sc.at(ms(250) + ms(400) * t + ms(120)).restart(SERVER)
    return make_wal_kv_runtime(n_clients=2, n_ops=12, wal_cap=8,
                               sync_wal=True, scenario=sc)


BUILDERS = dict(pingpong=build_pingpong, wal_kv=build_wal_kv)


def leaf_digests(state) -> dict:
    """{leaf path: sha256(shape|dtype|bytes)} over a batched final state."""
    out = {}
    for path, leaf in jax.tree_util.tree_flatten_with_path(state)[0]:
        a = np.asarray(leaf)
        h = hashlib.sha256()
        h.update(f"{a.shape}|{a.dtype}|".encode())
        h.update(np.ascontiguousarray(a).tobytes())
        out[jax.tree_util.keystr(path)] = h.hexdigest()
    return out


_RUN_CACHE: dict = {}


def run_workload(name: str) -> dict:
    """-> {"run": digests, "run_fused": digests} for one frozen workload.

    Memoized per process: the r17 (vs r16 truth) and r19 (vs r18 truth)
    equivalence suites compare the SAME current-tree digests against
    different captured goldens, so one pytest session pays for each
    workload exactly once."""
    if name in _RUN_CACHE:
        return _RUN_CACHE[name]
    p = RUNS[name]
    rt = BUILDERS[name]()
    seeds = np.arange(p["seeds"], dtype=np.uint32)
    chunked, _ = rt.run(rt.init_batch(seeds), p["max_steps"], p["chunk"])
    fused = rt.run_fused(rt.init_batch(seeds), p["max_steps"], p["chunk"])
    out = {"run": leaf_digests(chunked), "run_fused": leaf_digests(fused)}
    _RUN_CACHE[name] = out
    return out


def capture(path: str = GOLDEN_PATH) -> dict:
    doc = {name: run_workload(name) for name in BUILDERS}
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "w") as f:
        json.dump(doc, f, indent=1, sort_keys=True)
    return doc


def load_golden(path: str = GOLDEN_PATH) -> dict:
    with open(path) as f:
        return json.load(f)
