"""Two-phase commit fuzz: atomicity under loss and coordinator crashes, and
the seeded early-decide bug being caught with a reproducing seed."""

import numpy as np
import pytest

pytestmark = pytest.mark.slow  # chaos-sweep-heavy (r7 durations triage);
# tier-1/ci.sh fast skip it so the fast lane fits its 870s budget cold

from madsim_tpu import Scenario, SimConfig, NetConfig, ms, sec
from madsim_tpu.harness.simtest import SimFailure, run_seeds
from madsim_tpu.models import two_phase_commit as TPC
from madsim_tpu.models.two_phase_commit import make_tpc_runtime

N, TX = 5, 6
SEEDS = np.arange(8)


def _cfg(loss=0.0, time_limit=sec(20)):
    return SimConfig(n_nodes=N, event_capacity=128, time_limit=time_limit,
                     net=NetConfig(packet_loss_rate=loss,
                                   send_latency_min=ms(1),
                                   send_latency_max=ms(10)))


class TestTwoPhaseCommit:
    def test_clean_run_atomic_and_complete(self):
        rt = make_tpc_runtime(N, TX, cfg=_cfg())
        state = run_seeds(rt, SEEDS, max_steps=20_000)
        dec = np.asarray(state.node_state["decided"])  # [B, N, TX]
        # every tx decided on every participant, identically
        assert (dec[:, 1:, :] != TPC.NONE).all()
        for b in range(len(SEEDS)):
            for t in range(TX):
                vals = set(dec[b, 1:, t].tolist())
                assert len(vals) == 1, f"seed {b} tx {t} diverged: {vals}"
        # with p_yes=0.85^4 ~ 52%, both outcomes occur across the batch
        assert (dec == TPC.COMMIT).any() and (dec == TPC.ABORT).any()

    def test_loss_and_coordinator_crash_stays_atomic(self):
        sc = Scenario()
        sc.at(ms(100)).kill(0)
        sc.at(ms(600)).restart(0)
        sc.at(ms(900)).kill(0)
        sc.at(ms(1400)).restart(0)
        rt = make_tpc_runtime(N, TX, scenario=sc,
                              cfg=_cfg(loss=0.1, time_limit=sec(30)))
        state = run_seeds(rt, SEEDS, max_steps=60_000)
        dec = np.asarray(state.node_state["decided"])
        for b in range(len(SEEDS)):
            for t in range(TX):
                vals = set(dec[b, 1:, t].tolist()) - {TPC.NONE}
                assert len(vals) <= 1  # never both COMMIT and ABORT

    def test_early_decide_bug_caught(self):
        # decide after 2 of 4 votes under loss: a missing NO vote wrongly
        # commits; the NO-voter's assert (or the global invariant) fires
        rt = make_tpc_runtime(N, TX, early_decide_quorum=2, p_yes=0.6,
                              cfg=_cfg(loss=0.15, time_limit=sec(30)))
        with pytest.raises(SimFailure) as ei:
            run_seeds(rt, np.arange(48), max_steps=60_000)
        assert ei.value.code in (TPC.CRASH_DIVERGED, TPC.CRASH_NO_VOTE_COMMIT)
        # the reported seed reproduces alone
        state, _ = rt.run_single(ei.value.seed, max_steps=60_000)
        assert bool(state.crashed.all())

    def test_determinism(self):
        rt = make_tpc_runtime(N, TX, cfg=_cfg(loss=0.05))
        assert rt.check_determinism(seed=99, max_steps=20_000)

    def test_fast_tick_duplicate_acks_still_complete(self):
        # regression: tick < 2*max latency retransmits DECIDE while its ACK
        # is in flight; stale duplicate ACKs must not pre-ack the next tx
        # (which would leave its DECIDE unsent and decided[k] = NONE)
        rt = make_tpc_runtime(N, TX, tick=ms(12), cfg=_cfg())
        state = run_seeds(rt, np.arange(16), max_steps=40_000)
        dec = np.asarray(state.node_state["decided"])
        assert (dec[:, 1:, :] != TPC.NONE).all()
