"""Dual-world tests: the SAME Program classes that run vectorized in the
simulator run here against real asyncio time and real UDP sockets — the
test-both-worlds idiom of the reference's CI (ci.yml runs the suite with and
without --cfg madsim; SURVEY.md §4.5)."""

import numpy as np
import pytest

from madsim_tpu import SimConfig
from madsim_tpu.core.types import ms, sec
from madsim_tpu.models.pingpong import PingPong, state_spec
from madsim_tpu.models.rpc_echo import (EchoClient, EchoServer,
                                        server_state_spec)
from madsim_tpu.real.runtime import RealRuntime


@pytest.mark.realworld
class TestRealWorld:
    def test_pingpong_over_real_udp(self):
        n = 3
        cfg = SimConfig(n_nodes=n, time_limit=sec(10))
        rt = RealRuntime(cfg, [PingPong(n, target=5, retry=ms(30))],
                         state_spec(), base_port=19300)
        rt.run(duration=5.0)
        assert not rt.crashed
        st0 = rt.states()[0]
        assert int(st0["acked"]) >= 5           # pinger finished over UDP
        got = sum(int(s["pings_got"]) for s in rt.states()[1:])
        assert got >= 5

    def test_echo_service_over_real_udp(self):
        cfg = SimConfig(n_nodes=4, time_limit=sec(10))
        rt = RealRuntime(cfg, [EchoServer(), EchoClient(target=5,
                                                        timeout=ms(50))],
                         server_state_spec(), node_prog=[0, 1, 1, 1],
                         base_port=19320)
        rt.run(duration=5.0)
        assert not rt.crashed
        acked = [int(s["acked"]) for s in rt.states()[1:]]
        assert all(a >= 5 for a in acked), acked
        assert int(rt.states()[0]["served"]) >= 15

    def test_kill_restart_real(self):
        # supervisor surface works against real sockets: kill a responder
        # mid-run, restart it, the pinger's retries recover
        import asyncio

        n = 2
        cfg = SimConfig(n_nodes=n, time_limit=sec(10))
        rt = RealRuntime(cfg, [PingPong(n, target=8, retry=ms(30))],
                         state_spec(), base_port=19340)

        async def scenario():
            await rt.start()
            await asyncio.sleep(0.15)
            rt.kill(1)
            await asyncio.sleep(0.4)
            await rt.restart(1)
            try:
                await asyncio.wait_for(rt._halted.wait(), timeout=5.0)
            except asyncio.TimeoutError:
                pass
            for i in range(n):
                rt.kill(i)

        asyncio.run(scenario())
        assert not rt.crashed
        assert int(rt.states()[0]["acked"]) >= 8


@pytest.mark.realworld
class TestRealTcp:
    def test_pingpong_over_real_tcp(self):
        # same program, third transport: length-delimited frames over real
        # TCP connections (the std/net/tcp.rs backend shape)
        n = 3
        cfg = SimConfig(n_nodes=n, time_limit=sec(10))
        rt = RealRuntime(cfg, [PingPong(n, target=5, retry=ms(30))],
                         state_spec(), base_port=19360, transport="tcp")
        rt.run(duration=5.0)
        assert not rt.crashed
        assert int(rt.states()[0]["acked"]) >= 5

    def test_echo_over_real_tcp_with_server_restart(self):
        import asyncio

        cfg = SimConfig(n_nodes=3, time_limit=sec(10))
        rt = RealRuntime(cfg, [EchoServer(), EchoClient(target=6,
                                                        timeout=ms(60))],
                         server_state_spec(), node_prog=[0, 1, 1],
                         base_port=19380, transport="tcp")

        async def scenario():
            await rt.start()
            await asyncio.sleep(0.2)
            rt.kill(0)                       # connections die for real
            await asyncio.sleep(0.3)
            await rt.restart(0)
            try:
                await asyncio.wait_for(rt._halted.wait(), timeout=6.0)
            except asyncio.TimeoutError:
                pass
            for i in range(3):
                rt.kill(i)

        asyncio.run(scenario())
        assert not rt.crashed
        acked = [int(s["acked"]) for s in rt.states()[1:]]
        assert all(a >= 6 for a in acked), acked


@pytest.mark.realworld
class TestRealDurability:
    def test_wal_kv_persists_across_real_restart(self):
        # the std/fs.rs twin: RealRuntime(persist=...) keeps stable-storage
        # leaves across restart, so the WAL-KV durability oracle (an acked
        # write must never be un-written) holds over real sockets too
        import asyncio

        from madsim_tpu.models.wal_kv import (WalKvClient, WalKvServer,
                                              wal_persist_spec,
                                              wal_state_spec)

        cfg = SimConfig(n_nodes=2, time_limit=sec(30))
        rt = RealRuntime(cfg, [WalKvServer(n_keys=2, wal_cap=8),
                               WalKvClient(n_ops=10, keys_per_client=2,
                                           timeout=ms(80), think=ms(10))],
                         wal_state_spec(2, 2, 8, 2), node_prog=[0, 1],
                         base_port=19420, persist=wal_persist_spec())

        async def scenario():
            await rt.start()
            await asyncio.sleep(0.25)
            rt.kill(0)                    # power-fail the server for real
            await asyncio.sleep(0.25)
            await rt.restart(0)           # disk view survives, memory dies
            try:
                await asyncio.wait_for(rt._halted.wait(), timeout=8.0)
            except asyncio.TimeoutError:
                pass
            for i in range(2):
                rt.kill(i)

        asyncio.run(scenario())
        assert not rt.crashed             # the durability oracle is armed
        assert int(rt.states()[1]["c_opn"]) >= 10

    def test_pingpong_completes_under_injected_loss(self):
        # loopback never drops, so inject loss in the runtime itself: the
        # retry timers must still carry the workload to completion
        n = 3
        cfg = SimConfig(n_nodes=n, time_limit=sec(20))
        rt = RealRuntime(cfg, [PingPong(n, target=6, retry=ms(25))],
                         state_spec(), base_port=19440, loss=0.3)
        rt.run(duration=8.0)
        assert not rt.crashed
        assert int(rt.states()[0]["acked"]) >= 6


@pytest.mark.realworld
class TestCompiledDispatch:
    def test_echo_service_compiled(self):
        # compiled=True routes every event through a jitted handler
        # (XLA) instead of eager op dispatch — same Programs, same
        # effects contract, production-ish per-event cost after warmup
        cfg = SimConfig(n_nodes=3, time_limit=sec(30))
        rt = RealRuntime(cfg, [EchoServer(), EchoClient(target=5,
                                                        timeout=ms(150))],
                         server_state_spec(), node_prog=[0, 1, 1],
                         base_port=19700, compiled=True)
        rt.run(duration=20.0)      # first events pay their combo compiles
        assert not rt.crashed
        acked = [int(s["acked"]) for s in rt.states()[1:]]
        assert all(a >= 5 for a in acked), acked
        assert int(rt.states()[0]["served"]) >= 10
        assert len(rt._compiled_fns) >= 3   # the combos actually compiled


@pytest.mark.realworld
class TestBatchedDrain:
    """batch_drain=K: events queue and run through ONE jitted scan per
    drain (real/runtime.py _drain) — the amortized-dispatch mode. Same
    Programs, same effects contract; these tests pin the semantics the
    batching must not change."""

    def test_echo_fanout_batched(self):
        cfg = SimConfig(n_nodes=4, time_limit=sec(30))
        rt = RealRuntime(cfg, [EchoServer(), EchoClient(target=5,
                                                        timeout=ms(150))],
                         server_state_spec(), node_prog=[0, 1, 1, 1],
                         base_port=19730, batch_drain=8)
        rt.run(duration=20.0)
        assert not rt.crashed
        acked = [int(s["acked"]) for s in rt.states()[1:]]
        assert all(a >= 5 for a in acked), acked
        assert int(rt.states()[0]["served"]) >= 15

    def test_kill_restart_batched(self):
        # drain-time liveness: events queued for a node killed between
        # enqueue and drain are dropped; restart invalidates the stacked
        # cache so the fresh state is what later drains see
        import asyncio

        n = 2
        cfg = SimConfig(n_nodes=n, time_limit=sec(10))
        rt = RealRuntime(cfg, [PingPong(n, target=8, retry=ms(30))],
                         state_spec(), base_port=19750, batch_drain=8)

        async def scenario():
            await rt.start()
            await asyncio.sleep(0.2)
            rt.kill(1)
            await asyncio.sleep(0.3)
            await rt.restart(1)
            try:
                await asyncio.wait_for(rt._halted.wait(), timeout=6.0)
            except asyncio.TimeoutError:
                pass
            for i in range(n):
                rt.kill(i)

        asyncio.run(scenario())
        assert not rt.crashed
        assert int(rt.states()[0]["acked"]) >= 8

    def test_depth1_drain_bypasses_scan(self):
        # guard rail (r5): a one-event drain amortizes nothing (measured
        # 0.64x eager on the depth-1 ping-pong shape) — it must run
        # through per-event compiled dispatch, not the scan, with
        # identical behavior. Ping-pong with one client IS depth-1
        # traffic, so this workload exercises the bypass end to end; the
        # post-warm spies prove the bypass actually took it.
        import asyncio

        n = 2
        cfg = SimConfig(n_nodes=n, time_limit=sec(10))
        rt = RealRuntime(cfg, [PingPong(n, target=5, retry=ms(30))],
                         state_spec(), base_port=19860, batch_drain=8)
        calls = {"single": 0}

        async def scenario():
            await rt.start()            # warms both dispatch paths
            # wrap the post-warm cached per-event fns: any further call
            # is a real depth-1 bypass, not warmup
            for k, f in list(rt._compiled_fns.items()):
                def mk(f=f):
                    def wrapped(*a):
                        calls["single"] += 1
                        return f(*a)
                    return wrapped
                rt._compiled_fns[k] = mk()
            try:
                await asyncio.wait_for(rt._halted.wait(), timeout=8.0)
            except asyncio.TimeoutError:
                pass
            for i in range(n):
                rt.kill(i)

        asyncio.run(scenario())
        assert not rt.crashed
        assert int(rt.states()[0]["acked"]) >= 5
        assert calls["single"] > 0      # the bypass path actually ran

    def test_kill_purges_queued_events(self):
        # a killed process's pending events must never fire: events
        # already enqueued for the drain are purged by kill(), so a
        # kill+restart inside the coalescing window can't replay
        # old-incarnation events against the recovered state
        import jax.numpy as jnp

        cfg = SimConfig(n_nodes=2, time_limit=sec(5))
        rt = RealRuntime(cfg, [PingPong(2, target=1, retry=ms(30))],
                         state_spec(), base_port=19790, batch_drain=4)
        rt.nodes[0].alive = rt.nodes[1].alive = True
        z = jnp.zeros((cfg.payload_words,), jnp.int32)
        rt._queue.append((1, 2, 0, 1, z))
        rt._queue.append((0, 2, 0, 1, z))
        rt.kill(1)
        assert [ev[0] for ev in rt._queue] == [0]

    def test_cancel_purges_queued_timer_firings(self):
        # per-event parity: a cancel must also retract a timer firing
        # that landed in the drain queue during the coalescing window
        # (in per-event mode the handle is cancelled before it fires);
        # messages and other nodes' timers survive
        import jax.numpy as jnp

        from madsim_tpu.real.runtime import _Staged

        cfg = SimConfig(n_nodes=2, time_limit=sec(5))
        rt = RealRuntime(cfg, [PingPong(2, target=1, retry=ms(30))],
                         state_spec(), base_port=19795, batch_drain=4)
        z = jnp.zeros((cfg.payload_words,), jnp.int32)
        rt._queue.append((0, 2, 0, 5, z))   # node 0 timer tag 5: purged
        rt._queue.append((0, 1, 1, 5, z))   # node 0 MESSAGE: survives
        rt._queue.append((1, 2, 0, 5, z))   # node 1 timer: survives
        staged = _Staged(rt.nodes[0].state, [], [],
                         [dict(m=True, tag=5)], False, 0, False)
        rt._apply_effects(rt.nodes[0], staged)
        assert [(ev[0], ev[1]) for ev in rt._queue] == [(0, 1), (1, 2)]

    def test_coalescing_delay_still_completes(self):
        cfg = SimConfig(n_nodes=3, time_limit=sec(30))
        rt = RealRuntime(cfg, [EchoServer(), EchoClient(target=5,
                                                        timeout=ms(150))],
                         server_state_spec(), node_prog=[0, 1, 1],
                         base_port=19770, batch_drain=16)
        rt.drain_delay = 0.002   # trade per-hop latency for drain depth
        rt.run(duration=20.0)
        assert not rt.crashed
        acked = [int(s["acked"]) for s in rt.states()[1:]]
        assert all(a >= 5 for a in acked), acked


@pytest.mark.realworld
class TestRealCancelTimer:
    @pytest.mark.parametrize("compiled,batch", [(False, 0), (True, 0),
                                                (True, 8)])
    def test_cancel_really_cancels_wall_clock_timer(self, compiled, batch):
        # dual-world parity for ctx.cancel_timer: the asyncio timer is
        # genuinely cancelled, red/green via the do_cancel knob — in all
        # three dispatch modes (eager / per-event compiled / batched
        # drain, whose cancels apply host-side after the drain)
        import jax.numpy as jnp

        from madsim_tpu.core.api import Program

        class CancelDemo(Program):
            SLOW, DO_CANCEL = 1, 2

            def __init__(self, do_cancel):
                self.do_cancel = do_cancel

            def init(self, ctx):
                ctx.set_timer(ms(400), self.SLOW)
                ctx.set_timer(ms(30), self.DO_CANCEL)

            def on_timer(self, ctx, tag, payload):
                st = dict(ctx.state)
                st["fired"] = st["fired"] + (tag == self.SLOW)
                ctx.cancel_timer(self.SLOW, when=(tag == self.DO_CANCEL)
                                 & self.do_cancel)
                ctx.state = st

        def run(do_cancel):
            cfg = SimConfig(n_nodes=1, time_limit=sec(5))
            rt = RealRuntime(cfg, [CancelDemo(do_cancel)],
                             dict(fired=jnp.asarray(0, jnp.int32)),
                             base_port=19680, compiled=compiled,
                             batch_drain=batch)
            # compile warmup happens in start() BEFORE the duration
            # window opens, so both modes fit the same budget
            rt.run(duration=1.0)
            return int(rt.states()[0]["fired"])

        assert run(True) == 0
        assert run(False) == 1


@pytest.mark.realworld
class TestTransportSeam:
    """The std/net/mod.rs:33-49 seam: backends are a registry, not
    if-branches inside the runtime (VERDICT r2 missing #1)."""

    def test_pingpong_over_local_transport(self):
        # third shipped backend: the in-memory UCX-slot transport with a
        # dedicated progress worker per node (std/net/ucx.rs:43-60 shape)
        n = 3
        cfg = SimConfig(n_nodes=n, time_limit=sec(10))
        rt = RealRuntime(cfg, [PingPong(n, target=5, retry=ms(30))],
                         state_spec(), base_port=19460, transport="local")
        rt.run(duration=5.0)
        assert not rt.crashed
        assert int(rt.states()[0]["acked"]) >= 5

    def test_third_party_transport_plugs_in_untouched(self):
        # the proof the seam is real: a transport defined HERE, outside
        # the package, registers and carries a workload with zero
        # RealRuntime edits — the slot a UCX/RDMA binding would fill
        from madsim_tpu.real.transport import (LocalTransport, TRANSPORTS,
                                               register_transport)

        @register_transport("test-rdma")
        class CountingTransport(LocalTransport):
            delivered = 0

            async def _progress(self, node):
                q = self._outbox[node]
                while True:
                    dst, pkt = await q.get()
                    if dst in self._up:
                        CountingTransport.delivered += 1
                        self.deliver(dst, pkt)

        try:
            n = 2
            cfg = SimConfig(n_nodes=n, time_limit=sec(10))
            rt = RealRuntime(cfg, [PingPong(n, target=4, retry=ms(30))],
                             state_spec(), base_port=19480,
                             transport="test-rdma")
            rt.run(duration=5.0)
            assert not rt.crashed
            assert int(rt.states()[0]["acked"]) >= 4
            assert CountingTransport.delivered >= 8   # it really carried it
        finally:
            TRANSPORTS.pop("test-rdma", None)


@pytest.mark.realworld
class TestRealProcessDeath:
    """kill -9 of the actual OS process — the durability bar the in-process
    restart() twin can't reach (VERDICT r2 missing #2). Stable storage is
    RealRuntime(data_dir=...): fs disk views spilled with fsync + atomic
    rename after every event, reloaded on boot (std/fs.rs:1-60 twin)."""

    def _run_child_until_acked(self, data_dir, port, sync_flag, min_acked,
                               transport="udp"):
        import os
        import signal
        import subprocess
        import sys as _sys
        import time as _time

        child = subprocess.Popen(
            [_sys.executable,
             os.path.join(os.path.dirname(__file__), "_walkv_child.py"),
             data_dir, str(port), sync_flag, transport],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)
        last = [0, 0]
        # generous: the child pays a full cold jax import (~20s when the
        # suite runs cache-cold on this 1-core box) before any protocol
        # traffic; 30s flaked exactly once under a cold `ci.sh full`.
        # select-bounded: a child that wedges BEFORE printing anything
        # (e.g. backend init) must time the test out, not hang it — a
        # blocking `for line in stdout` would never reach a deadline
        # check.
        import select
        deadline = _time.monotonic() + 120
        try:
            while _time.monotonic() < deadline:
                ready, _, _ = select.select([child.stdout], [], [], 1.0)
                if not ready:
                    continue
                line = child.stdout.readline()
                if not line:                     # EOF: child exited
                    break
                if line.startswith("ACKED"):
                    last = [int(v) for v in line.split()[1:]]
                    if min(last) >= min_acked:
                        break
        finally:
            child.send_signal(signal.SIGKILL)    # real power-fail
            child.wait()
        # a vacuous run (child died, never acked) must fail loudly, not
        # let the recovery assertions pass on all-zeros
        assert min(last) >= min_acked, \
            f"child never acked {min_acked}; last={last}, " \
            f"stderr={child.stderr.read()[-2000:]}"
        return last                              # lower bound on acks

    def _recover_kv(self, data_dir, port):
        # a brand-new process image: fresh runtime, same disk. Server
        # init runs WAL-KV recovery (mount, load DB, replay WAL).
        import asyncio

        from madsim_tpu.models.wal_kv import (WalKvClient, WalKvServer,
                                              wal_persist_spec,
                                              wal_state_spec)

        cfg = SimConfig(n_nodes=2, time_limit=sec(10))
        rt = RealRuntime(
            cfg, [WalKvServer(n_keys=2, wal_cap=64),
                  WalKvClient(n_ops=1, keys_per_client=2)],
            wal_state_spec(2, 2, 64, 2), node_prog=[0, 1],
            base_port=port, persist=wal_persist_spec(), data_dir=data_dir)

        async def boot():
            await rt.start(nodes=[0])   # server only: recovery, no new ops
            rt.kill(0)

        asyncio.run(boot())
        return [int(v) for v in rt.states()[0]["kv"]]

    @pytest.mark.parametrize("transport,port", [("udp", 19600),
                                                ("tcp", 19740)])
    def test_synced_writes_survive_kill9(self, tmp_path, transport, port):
        # durability is a property of the storage layer, not the wire:
        # the same oracle must hold over either transport
        acked = self._run_child_until_acked(str(tmp_path), port, "sync",
                                            min_acked=2,
                                            transport=transport)
        kv = self._recover_kv(str(tmp_path), 19620)
        # every write the client saw acked must be on disk: node 1 owns
        # keys 0..1 and writes strictly increasing values per key
        assert kv[0] >= acked[0] and kv[1] >= acked[1], (kv, acked)

    def test_unsynced_writes_lost_without_sync(self, tmp_path):
        # red case: with the WAL sync removed, acks promise durability
        # the disk never got — kill -9 must lose them (wal_cap > n_ops so
        # no checkpoint ever syncs the table). Proves the sync gate is
        # load-bearing in the REAL world too, mirroring tests/test_fs.py.
        acked = self._run_child_until_acked(str(tmp_path), 19640, "nosync",
                                            min_acked=1)
        kv = self._recover_kv(str(tmp_path), 19660)
        assert kv[0] < acked[0], (kv, acked)      # the lost write
