"""Shared device preflight for the runnable examples.

This environment may pin jax at a TPU tunnel (a sitecustomize registers
the axon platform whenever PALLAS_AXON_POOL_IPS is set); a WEDGED tunnel
then hangs the first backend touch forever. Probe it in a killable child
and fall back to CPU — but ONLY when the tunnel env var is present:
without it there is no hang risk, and the user's platform choice
(default, or an explicit JAX_PLATFORMS) must be respected.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def ensure_safe_backend():
    if not os.environ.get("PALLAS_AXON_POOL_IPS"):
        return          # no tunnel pin: nothing can wedge
    from bench import _force_cpu_inprocess, _tpu_alive
    # retry once: transient tunnel flakes are common and cheap to re-probe
    # (a WEDGED verdict is disk-cached by _tpu_alive, so the second probe
    # of a truly dead tunnel costs nothing)
    if not (_tpu_alive() or _tpu_alive()):
        _force_cpu_inprocess()
