"""Headline demo: fuzz 10k MadRaft-style clusters under chaos in one go.

    python examples/fuzz_raft.py [num_seeds]

Prints a fleet report; on any invariant violation prints the repro line
and replays the failing seed with a full event trace.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from _preflight import ensure_safe_backend  # noqa: E402

ensure_safe_backend()   # CPU fallback iff a wedged TPU tunnel would hang us

import numpy as np

from madsim_tpu import Scenario, SimConfig, NetConfig, ms, sec
from madsim_tpu.harness.simtest import SimFailure, run_seeds
from madsim_tpu.models.raft import make_raft_runtime
from madsim_tpu.parallel.stats import summarize
from madsim_tpu.runtime.trace import print_trace


def main():
    n_seeds = int(sys.argv[1]) if len(sys.argv) > 1 else 10_000
    cfg = SimConfig(n_nodes=5, event_capacity=128, time_limit=sec(6),
                    net=NetConfig(packet_loss_rate=0.05))
    sc = Scenario()
    for t in range(4):
        sc.at(ms(800 + 900 * t)).kill_random()
        sc.at(ms(1300 + 900 * t)).restart_random()
    sc.at(sec(2)).partition([0, 1])
    sc.at(sec(3)).heal()

    rt = make_raft_runtime(5, log_capacity=16, n_cmds=6, scenario=sc, cfg=cfg)
    seeds = np.arange(n_seeds)
    try:
        state = run_seeds(rt, seeds, max_steps=30_000, chunk=1024)
    except SimFailure as e:
        print(e)
        print(f"\n--- replaying seed {e.seed} ---")
        _, events = rt.run_single(e.seed, max_steps=30_000)
        print_trace(events, 0, limit=200)
        raise SystemExit(1)

    rep = summarize(rt, state, seeds)
    print("fleet report:")
    for k, v in rep.items():
        print(f"  {k}: {v}")


if __name__ == "__main__":
    main()
