"""Coverage-driven fuzzing: sweep seed batches until the schedule space
dries up, harvesting every distinct failure on the way.

    python examples/explore_coverage.py [batch] [max_rounds]

The reference's lever is a fixed seed count (MADSIM_TEST_NUM, macros
lib.rs:152-167); here each round's distinct-schedule yield is measured
(SimState.sched_hash), so the sweep stops when more seeds stop buying
new interleavings — and a buggy protocol's crashes are collected per
code with their first repro seed instead of aborting the hunt.

Demo workload: WAL-KV with the durability sync REMOVED under power-fail
chaos — the oracle (an acked write must never be un-written) has real
violations to find.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from _preflight import ensure_safe_backend  # noqa: E402

ensure_safe_backend()   # CPU fallback iff a wedged TPU tunnel would hang us

import numpy as np

from madsim_tpu import ProgressObserver, Scenario, ms
from madsim_tpu.models import wal_kv
from madsim_tpu.models.wal_kv import make_wal_kv_runtime
from madsim_tpu.parallel.explore import explore


def main():
    batch = int(sys.argv[1]) if len(sys.argv) > 1 else 256
    max_rounds = int(sys.argv[2]) if len(sys.argv) > 2 else 8

    sc = Scenario()
    for t in range(6):
        sc.at(ms(150) + ms(250) * t).kill(0)
        sc.at(ms(210) + ms(250) * t).restart(0)
    rt = make_wal_kv_runtime(n_clients=2, n_ops=12, wal_cap=64,
                             sync_wal=False, scenario=sc)

    # live per-round coverage growth on stderr while the sweep runs
    # (obs/progress.py; swap in JsonlObserver to persist the records)
    out = explore(rt, max_steps=60_000, batch=batch, max_rounds=max_rounds,
                  observer=ProgressObserver())
    print(f"seeds run           : {out['seeds_run']}")
    print(f"distinct schedules  : {out['distinct_schedules']}")
    print(f"new per round       : {out['new_per_round']}")
    print(f"saturated           : {out['saturated']}")
    print(f"crashed trajectories: {out['crashes']}")
    for code, seed in out["crash_first_seed_by_code"].items():
        name = ("LOST_WRITE" if code == wal_kv.CRASH_LOST_WRITE
                else f"code {code}")
        print(f"  {name}: repro with MADSIM_TEST_SEED={seed}")


if __name__ == "__main__":
    main()
