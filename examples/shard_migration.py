"""ShardKV demo: watch shards migrate between Raft groups, live.

    python examples/shard_migration.py [num_seeds]

Fuzzes a full sharded-KV deployment — a raft-replicated config service,
two kv Raft groups, and clients — while the controller keeps moving
shards between groups. Per-lane report: how many configurations
committed, where every shard ended up, and whether each lane's client
history stayed linearizable across the migrations (checked with the
native C++ checker).
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from _preflight import ensure_safe_backend  # noqa: E402

ensure_safe_backend()   # CPU fallback iff a wedged TPU tunnel would hang us

import numpy as np

from madsim_tpu import NetConfig, Scenario, SimConfig, ms, sec
from madsim_tpu.harness.simtest import run_seeds
from madsim_tpu.models.shard_kv import (
    extract_histories, grp_of, make_shard_runtime)
from madsim_tpu.native import check_kv_history

RC, RG, G, NC, S = 3, 3, 2, 2, 4
CLIENTS_BASE = RC + G * RG


def main():
    n_seeds = int(sys.argv[1]) if len(sys.argv) > 1 else 16
    sc = Scenario()
    for t in range(3):  # chaos on the servers while shards move
        sc.at(ms(1200 + 1500 * t)).kill_random(among=range(CLIENTS_BASE))
        sc.at(ms(1900 + 1500 * t)).restart_random(among=range(CLIENTS_BASE))
    cfg = SimConfig(n_nodes=CLIENTS_BASE + NC, event_capacity=160,
                    payload_words=12, time_limit=sec(60),
                    net=NetConfig(packet_loss_rate=0.05,
                                  send_latency_min=ms(1),
                                  send_latency_max=ms(10)))
    rt = make_shard_runtime(n_groups=G, rg=RG, rc=RC, n_clients=NC,
                            n_ops=6, max_cfg=4, scenario=sc, cfg=cfg)
    state = run_seeds(rt, np.arange(n_seeds), max_steps=120_000)

    ns = {k: np.asarray(v) for k, v in state.node_state.items()}
    hists = extract_histories(state, CLIENTS_BASE, NC)
    for b in range(n_seeds):
        cfg_n = int(ns["cfg_n"][b, :RC].max())
        ctrl = int(ns["cfg_n"][b, :RC].argmax())   # a controller that's
        asn = int(ns["cfg_hist"][b, ctrl, cfg_n])  # fully caught up
        owners = [int(grp_of(asn, s)) for s in range(S)]
        done = ns["c_opn"][b, CLIENTS_BASE:]
        lin = check_kv_history(hists[b])
        print(f"seed {b:3d}: configs={cfg_n} shard->group={owners} "
              f"client_ops={list(done)} linearizable={lin}")
        assert lin
    print(f"\nall {n_seeds} lanes linearizable across live migrations")


if __name__ == "__main__":
    main()
