"""The world switch: one protocol, two runtimes.

Runs the same EchoServer/EchoClient programs (a) vectorized in the
simulator over 1024 seeds with faults, then (b) against real asyncio time
and UDP sockets on localhost — the madsim `--cfg madsim` dual-build,
selected at runtime construction.

    python examples/dual_world.py
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from _preflight import ensure_safe_backend  # noqa: E402

ensure_safe_backend()   # CPU fallback iff a wedged TPU tunnel would hang us

import numpy as np

from madsim_tpu import Scenario, SimConfig, NetConfig, ms, sec
from madsim_tpu.harness.simtest import run_seeds
from madsim_tpu.models.rpc_echo import (EchoClient, EchoServer,
                                        make_echo_runtime, server_state_spec)
from madsim_tpu.real.runtime import RealRuntime


def main():
    # --- world 1: the simulator --------------------------------------
    cfg = SimConfig(n_nodes=4, event_capacity=256, time_limit=sec(20),
                    net=NetConfig(packet_loss_rate=0.2))
    sc = Scenario()
    sc.at(ms(30)).kill(0)
    sc.at(sec(1)).restart(0)
    rt = make_echo_runtime(n_nodes=4, target=10, scenario=sc, cfg=cfg)
    state = run_seeds(rt, np.arange(1024), max_steps=40_000)
    acked = np.asarray(state.node_state["acked"])[:, 1:]
    print(f"sim world: 1024 seeds, 20% loss, server kill/restart -> "
          f"all clients acked >= 10: {bool((acked >= 10).all())}")

    # --- world 2: real sockets, same classes -------------------------
    rt2 = RealRuntime(SimConfig(n_nodes=4, time_limit=sec(10)),
                      [EchoServer(), EchoClient(target=10, timeout=ms(50))],
                      server_state_spec(), node_prog=[0, 1, 1, 1],
                      base_port=19500)
    rt2.run(duration=5.0)
    acked = [int(s["acked"]) for s in rt2.states()[1:]]
    print(f"real world: UDP on 127.0.0.1 -> client acks {acked}, "
          f"server served {int(rt2.states()[0]['served'])}")


if __name__ == "__main__":
    main()
