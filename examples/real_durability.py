"""Real-world durability demo: the WAL-KV server survives kill -9.

    python examples/real_durability.py [data_dir]

Phase 1 runs a WAL-KV server + client over real UDP sockets with
on-disk stable storage (`RealRuntime(data_dir=...)` — the std/fs.rs
twin: fs disk views spilled with fsync + atomic rename after every
event). Phase 2 "power-fails" by constructing a COMPLETELY FRESH
runtime over the same data_dir — exactly what a new OS process sees —
and shows the server's recovery (mount, load checkpoint, replay WAL)
observing every previously-acked write. tests/test_real_runtime.py
does the honest version with a real SIGKILLed child process.
"""

import asyncio
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

# real sockets + real disk: the accelerator is irrelevant, so force the
# host platform (the environment may pin jax at a TPU tunnel)
os.environ.setdefault("JAX_PLATFORMS", "cpu")
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

from madsim_tpu import SimConfig
from madsim_tpu.core.types import ms, sec
from madsim_tpu.models.wal_kv import (WalKvClient, WalKvServer,
                                      wal_persist_spec, wal_state_spec)
from madsim_tpu.real.runtime import RealRuntime


def make_rt(data_dir, port):
    cfg = SimConfig(n_nodes=2, time_limit=sec(30))
    return RealRuntime(
        cfg, [WalKvServer(n_keys=2, wal_cap=64),
              WalKvClient(n_ops=8, keys_per_client=2,
                          timeout=ms(80), think=ms(10))],
        wal_state_spec(2, 2, 64, 2), node_prog=[0, 1], base_port=port,
        persist=wal_persist_spec(), data_dir=data_dir)


def main():
    data_dir = sys.argv[1] if len(sys.argv) > 1 else tempfile.mkdtemp(
        prefix="walkv_demo_")
    print(f"stable storage: {data_dir}")

    rt = make_rt(data_dir, 19800)
    rt.run(duration=3.0)
    acked = [int(v) for v in rt.states()[1]["acked"]]
    kv_mem = [int(v) for v in rt.states()[0]["kv"]]
    print(f"phase 1: client acked per-key values {acked}; "
          f"server kv (memory) {kv_mem}")

    # phase 2: a fresh runtime = a fresh process image; only the disk
    # survives. Server init recovers: mount, load DB, replay WAL.
    rt2 = make_rt(data_dir, 19820)

    async def boot():
        await rt2.start(nodes=[0])    # server only: recovery, no new ops
        rt2.kill(0)

    asyncio.run(boot())
    kv_disk = [int(v) for v in rt2.states()[0]["kv"]]
    print(f"phase 2: recovered kv after simulated kill -9 {kv_disk}")
    ok = all(d >= a for d, a in zip(kv_disk, acked))
    print("durability holds: every acked write recovered"
          if ok else "DURABILITY VIOLATION")
    sys.exit(0 if ok else 1)


if __name__ == "__main__":
    main()
