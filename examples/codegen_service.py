"""Schema-first service definition — the tonic-build workflow, both worlds.

The reference defines RPC services in .proto files and generates the
client/server API at build time (madsim-tonic-build). Here the same
schema shape generates a Python module (`python -m madsim_tpu.net.codegen
schema.proto -o schema_pb.py`, or `generate()` in-process as below), and
the implementation runs UNCHANGED in the batched simulator and against
real sockets.

Run:  python examples/codegen_service.py
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from _preflight import ensure_safe_backend  # noqa: E402

ensure_safe_backend()   # CPU fallback iff a wedged TPU tunnel would hang us

import jax.numpy as jnp
import numpy as np

from madsim_tpu import Program, Runtime, SimConfig, NetConfig, ms, sec
from madsim_tpu.harness.simtest import run_seeds
from madsim_tpu.net import codegen, rpc

SCHEMA = """
syntax = "proto3";

message PutReq { int32 key = 1; int32 val = 2; }
message PutRsp { int32 ok = 1; }
message GetReq { int32 key = 1; }
message GetRsp { int32 val = 1; int32 found = 2; }

service Store {
  rpc Put(PutReq) returns (PutRsp);
  rpc Get(GetReq) returns (GetRsp);
}
"""

# generate + load the module (a build would write this to store_pb.py)
pb = {}
exec(compile(codegen.generate(SCHEMA), "store_pb.py", "exec"), pb)

N_KEYS = 4


class StoreImpl(pb["StoreBase"]):
    """Fill in the generated handle_* hooks; everything else —
    tag hashing, dispatch, unpack/pack, reply routing — is generated."""

    def handle_put(self, ctx, st, req, when):
        k = jnp.clip(req["key"], 0, N_KEYS - 1)
        onehot = jnp.arange(N_KEYS) == k
        st["kv"] = jnp.where(onehot & when, req["val"], st["kv"])
        st["has"] = st["has"] | (onehot & when)
        return dict(ok=jnp.asarray(when, jnp.int32))

    def handle_get(self, ctx, st, req, when):
        k = jnp.clip(req["key"], 0, N_KEYS - 1)
        onehot = jnp.arange(N_KEYS) == k
        return dict(val=jnp.where(onehot, st["kv"], 0).sum(),
                    found=(st["has"] & onehot).any().astype(jnp.int32))


T_RETRY = 1


class Client(Program):
    """put(k, 100+k) for each key, then get(0) and halt on found."""

    def init(self, ctx):
        st = dict(ctx.state)
        st["call_id"] = rpc.new_call_id(ctx)
        pb["store_put"](ctx, 0, st["call_id"], retry_timer_tag=T_RETRY,
                        timeout=ms(40), key=0, val=100)
        ctx.state = st

    def _issue(self, ctx, st, step, call_id, when):
        done_puts = step >= N_KEYS
        k = jnp.clip(step, 0, N_KEYS - 1)
        pb["store_put"](ctx, 0, call_id, retry_timer_tag=T_RETRY,
                        timeout=ms(40), key=k, val=100 + k,
                        when=when & ~done_puts)
        pb["store_get"](ctx, 0, call_id, retry_timer_tag=T_RETRY,
                        timeout=ms(40), key=0, when=when & done_puts)

    def on_timer(self, ctx, tag, payload):
        st = dict(ctx.state)
        retry = (tag == T_RETRY) & (payload[0] == st["call_id"])
        self._issue(ctx, st, st["step"], st["call_id"], retry)
        ctx.state = st

    def on_message(self, ctx, src, tag, payload):
        st = dict(ctx.state)
        hit = rpc.is_reply(tag) & rpc.matches(payload, st["call_id"])
        is_get = tag == rpc.reply_tag(pb["StoreBase"].Get.tag)
        get_rsp = pb["unpack_get_rsp"](payload[1:])
        ctx.crash_if(hit & is_get & (get_rsp["val"] != 100), 7)
        st["step"] = st["step"] + hit
        new_id = rpc.new_call_id(ctx)
        self._issue(ctx, st, st["step"], new_id, hit & ~is_get)
        st["call_id"] = jnp.where(hit & ~is_get, new_id, st["call_id"])
        ctx.halt_if(hit & is_get & (ctx.node == 1))
        ctx.state = st


def spec():
    z = jnp.asarray(0, jnp.int32)
    return dict(kv=jnp.zeros((N_KEYS,), jnp.int32),
                has=jnp.zeros((N_KEYS,), bool), call_id=z, step=z)


def main():
    cfg = SimConfig(n_nodes=2, time_limit=sec(20),
                    net=NetConfig(packet_loss_rate=0.1))
    rt = Runtime(cfg, [StoreImpl(), Client()], spec(), node_prog=[0, 1])
    state = run_seeds(rt, np.arange(64), max_steps=20_000)
    kv = np.asarray(state.node_state["kv"])[:, 0]
    print(f"64 seeds under 10% loss: all halted={bool(state.halted.all())}, "
          f"store contents (seed 0): {kv[0].tolist()}")
    assert (kv == [100, 101, 102, 103]).all()
    print("generated service OK: schema -> Layouts + dispatch + client "
          "stubs, protocol logic only in handle_put/handle_get")


if __name__ == "__main__":
    main()
