"""The full fuzzing workflow on a real seeded bug, end to end:

    coverage-driven explore  ->  crash harvest  ->  chaos-script ddmin
    ->  faithful repro report  ->  single-seed replay

    python examples/fuzz_workflow.py

Target: two-phase commit with `early_decide_quorum=2` — the classic
protocol bug (coordinator decides before all votes arrive), which chaos
turns into observable atomicity violations. The reference's workflow
for this is "run N seeds, print the failing seed" (MADSIM_TEST_NUM +
the repro line); here the sweep is coverage-metered, every distinct
crash code is harvested with its first seed, the chaos script shrinks
to its load-bearing rows, and the seed replays alone for inspection.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from _preflight import ensure_safe_backend  # noqa: E402

ensure_safe_backend()   # CPU fallback iff a wedged TPU tunnel would hang us

import numpy as np

from madsim_tpu import Scenario, explore, minimize_scenario, ms
from madsim_tpu.models import two_phase_commit as tpc
from madsim_tpu.models.two_phase_commit import make_tpc_runtime

CODE_NAMES = {tpc.CRASH_DIVERGED: "DIVERGED (commit here, abort there)",
              tpc.CRASH_NO_VOTE_COMMIT: "COMMIT against a NO vote"}


def main():
    from madsim_tpu import NetConfig, SimConfig, sec

    # 15% loss is what actually triggers the bug (a dropped NO vote +
    # quorum-2 decide); the kill/restart rows are red herrings the
    # minimizer should expose as noise
    cfg = SimConfig(n_nodes=5, event_capacity=192, time_limit=sec(30),
                    net=NetConfig(packet_loss_rate=0.15))
    sc = Scenario()
    for t in range(3):
        sc.at(ms(200 + 400 * t)).kill_random(among=range(1, 5))
        sc.at(ms(400 + 400 * t)).restart_random(among=range(1, 5))
    rt = make_tpc_runtime(5, 6, scenario=sc, cfg=cfg,
                          early_decide_quorum=2, p_yes=0.6)

    print("== explore: coverage-metered sweep, crashes harvested ==")
    out = explore(rt, max_steps=40_000, batch=64, max_rounds=4)
    print(f"seeds run {out['seeds_run']}, distinct schedules "
          f"{out['distinct_schedules']}, crashes {out['crashes']}")
    if not out["crash_first_seed_by_code"]:
        print("no crashes found (unexpected for the seeded bug)")
        sys.exit(1)

    for code, seed in sorted(out["crash_first_seed_by_code"].items()):
        print(f"\n== crash {CODE_NAMES.get(code, code)}: first seed "
              f"{seed} ==")
        minimal, info = minimize_scenario(rt, seed, max_steps=40_000)
        print(f"chaos script shrank {info['kept'] + info['dropped']} -> "
              f"{info['kept']} rows ({info['runs']} candidate runs):")
        print(minimal.describe())
        # the shrunken script still reproduces, single lane
        rt.set_scenario(minimal)
        st, _ = rt.run(rt.init_single(seed), 40_000, collect_events=False)
        ok = bool(np.asarray(st.crashed).any())
        print(f"single-seed replay under minimal script: "
              f"{'reproduces' if ok else 'LOST THE BUG'}")
        rt.set_scenario(sc)
        if not ok:
            sys.exit(1)
    print("\nworkflow complete")


if __name__ == "__main__":
    main()
