"""An always-on fuzzing service: N workers, one durable corpus dir.

    python examples/fuzz_service.py CORPUS_DIR [workers] [rounds] [shards]

The CI-farm shape (ROADMAP "production traffic"): every invocation
RESUMES the campaign in CORPUS_DIR — worker processes pick up at their
persisted round counts, merge each other's coverage at round syncs, and
dedup crashes into shared causal-fingerprint buckets. Kill it however
you like (Ctrl-C, SIGKILL, power loss): nothing past the last round sync
is lost, and the next invocation converges to the run that was never
killed. Run it again with a larger `rounds` to keep an existing campaign
growing. `shards` > 1 makes every worker a mesh-sharded campaign of
that width (DESIGN §15 — worker processes force their own virtual CPU
mesh; on real chips pin one worker per host and let the mesh span its
devices): processes x shards compose, all namespaces stay disjoint.

Prints live campaign stats while the workers run, then the merged
report: coverage, per-worker rounds, and one line per deduped crash
bucket with its durable (seed, knobs) repro handle.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from _preflight import ensure_safe_backend  # noqa: E402

ensure_safe_backend()   # CPU fallback iff a wedged TPU tunnel would hang us

from madsim_tpu import ProgressObserver, campaign_report, run_campaign  # noqa: E402

# the crash-rich wal_kv matrix (lost unsynced writes under kill/restart
# chaos): one shared definition with --mode campaign and the search tests
FACTORY = "bench:_make_crashrich_runtime"
FACTORY_KWARGS = dict(kind="wal_kv", trace_cap=64, sketch_slots=4)


def main():
    if len(sys.argv) < 2:
        print(__doc__)
        sys.exit(2)
    corpus_dir = sys.argv[1]
    workers = int(sys.argv[2]) if len(sys.argv) > 2 else 2
    rounds = int(sys.argv[3]) if len(sys.argv) > 3 else 4
    shards = int(sys.argv[4]) if len(sys.argv) > 4 else 1

    print(f"campaign: {workers} workers x {shards} shard(s) x {rounds} "
          f"rounds (campaign total) -> {corpus_dir}")
    try:
        rep = run_campaign(
            FACTORY, corpus_dir, workers=workers, max_rounds=rounds,
            max_steps=4096, batch=48, chunk=512, shards=shards,
            factory_kwargs=FACTORY_KWARGS, observer=ProgressObserver(),
            poll_s=1.0)
    except KeyboardInterrupt:
        print("\ninterrupted — campaign state is durable; rerun to resume")
        if not os.path.exists(os.path.join(corpus_dir, "MANIFEST.json")):
            # interrupted before any worker created the store
            sys.exit(0)
        rep = campaign_report(corpus_dir)

    print(f"\n  coverage: {rep['coverage_keys']} distinct schedules "
          f"({rep['corpus_entries']} corpus entries, "
          f"{rep['schedules_per_sec']}/s)")
    for w, d in sorted(rep["workers_detail"].items()):
        print(f"  worker {w}: {d['rounds_done']} rounds, "
              f"{d['corpus_entries']} live entries, {d['wall_s']}s")
    print(f"  crash buckets: {rep['buckets_merged']} "
          f"({rep['crash_observations']} observations deduped)")
    for b in rep["bucket_detail"]:
        mini = " [minimized]" if b["minimized"] else ""
        print(f"    {b['key']}  code {b['crash_code']}  "
              f"x{b['observations']}  repro seed {b['repro']['seed']} "
              f"(worker {b['repro']['worker_id']}, "
              f"round {b['repro']['round']}){mini}")
    print("\nrerun the same command (or with more rounds) to resume; "
          "replay a bucket with madsim_tpu.replay_bucket(rt, dir, key)")


if __name__ == "__main__":
    main()
