"""Open-loop shard_kv: measured tail latency under fuzzable traffic.

The seed of the ROADMAP's big-world flagship ("planet-scale shard_kv
under million-client open traffic, p99 invariants read off the profiler
digest"), at demo scale: an OPEN-loop client population drives a sharded
KV cluster, and the new SLO latency plane (SimConfig.latency_hist,
DESIGN §17) reports p50/p99/p999 end-to-end request latency straight off
the on-device histograms — zero extra host round-trips, fused runner.

Open-loop means arrivals don't wait for completions: each client NODE is
booted by a scenario row at a Poisson-ish arrival time (`Scenario.boot`
— spare event-table rows ARE the client generator), then issues its ops.
Because scenario row TIMES live on the fuzzer's knob plane
(search/mutate.py time_nudge), the traffic shape itself is mutable: run
with `fuzz` and the campaign hunts arrival patterns that amplify the
tail, with `lat_bonus` steering admissions toward high-p99 lanes and an
SLO invariant turning misses into first-class crash findings.

Latency semantics here (the DESIGN §17 chain-correctness rule):
  root_kinds     = ((EV_TIMER, T_NEW),): each new-request timer MINTS a
                   fresh root, so retries/config-chasing of one op stay
                   under that op's root
  complete_kinds = ((EV_MSG, CMD),): the command ARRIVING at a shard
                   server completes the measured leg. shard_kv replies
                   ride the raft APPLY (a replication-ack dispatch whose
                   causal chain descends from the server's boot, not the
                   request), so the client→group request path — routing,
                   wrong-group redirects, retries under chaos — is the
                   chain-correct leg; every retry arrival re-measures
                   cumulatively from the op's root, so the histogram's
                   tail IS time-to-reach-the-group. Direct-reply servers
                   (wal_kv, rpc_echo) can complete on the reply itself.

Usage:
  python examples/open_loop_kv.py [batch] [fuzz]
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from _preflight import ensure_safe_backend  # noqa: E402

ensure_safe_backend()   # CPU fallback iff a wedged TPU tunnel would hang us

import numpy as np  # noqa: E402

from madsim_tpu import (NetConfig, Scenario, SimConfig,  # noqa: E402
                        format_latency, latency_summary, ms, sec,
                        slo_invariant, summarize)
from madsim_tpu.core.types import EV_MSG, EV_TIMER  # noqa: E402
from madsim_tpu.models.shard_kv import (CMD, T_NEW,  # noqa: E402
                                        make_shard_runtime)

RC, RG, G, CLIENTS = 3, 3, 2, 4
N_OPS = 2
SLO_US = ms(400)        # miss-counter target for the report
SLO_CRASH_US = ms(800)  # fuzz: a lane whose own p99 passes this CRASHES —
                        # above the baseline tail, so the fuzzer must find
                        # traffic/chaos shapes that amplify it


def make_open_loop_runtime(arrival_seed: int = 0, mean_gap=ms(120),
                           invariant=None):
    """The open-loop cluster: servers boot at t=0, each client node
    boots at a Poisson-ish arrival drawn host-side (fixed seed — the
    arrival SCHEDULE is scenario data, so every lane shares it and the
    fuzzer mutates it via the knob plane; per-lane jitter comes from
    the simulation's own randomness)."""
    n = RC + G * RG + CLIENTS
    arrivals_rng = np.random.default_rng(arrival_seed)
    sc = Scenario()
    # arrivals start once the groups have had time to elect/configure,
    # so e2e measures request service, not the cluster's cold start
    t = sec(2)
    for c in range(CLIENTS):
        t += int(arrivals_rng.exponential(mean_gap))
        sc.at(t).boot(RC + G * RG + c)
    # a little server chaos so the tail has something to amplify —
    # random kills are fuzzer-retargetable (NODE_RANDOM + pool knobs)
    servers = tuple(range(RC, RC + G * RG))
    sc.at(sec(3)).kill_random(among=servers)
    sc.at(sec(3) + ms(600)).restart_random(among=servers)
    cfg = SimConfig(
        n_nodes=n, event_capacity=192, payload_words=12,
        time_limit=sec(30),
        latency_hist=24,
        complete_kinds=((EV_MSG, CMD),),
        root_kinds=((EV_TIMER, T_NEW),),
        slo_target=SLO_US,
        net=NetConfig(send_latency_min=ms(1), send_latency_max=ms(10)))
    return make_shard_runtime(n_groups=G, rg=RG, rc=RC, n_clients=CLIENTS,
                              n_ops=N_OPS, max_cfg=4, scenario=sc, cfg=cfg,
                              extra_invariant=invariant)


def main():
    batch = int(sys.argv[1]) if len(sys.argv) > 1 else 64
    do_fuzz = "fuzz" in sys.argv[1:]
    rt = make_open_loop_runtime()
    print(f"open-loop shard_kv: {rt.cfg.n_nodes} nodes "
          f"({G} groups x {RG} + {RC} ctrl + {CLIENTS} clients), "
          f"B={batch}, SLO p99 <= {SLO_US}us")
    seeds = np.arange(batch, dtype=np.uint32)
    final = rt.run_fused(rt.init_batch(seeds), 60_000, 1024)
    rep = summarize(rt, final, seeds)
    lat = rep["latency"]
    print(f"halted {rep['halted']}/{rep['batch']}  "
          f"crashed {rep['crashed']}  "
          f"distinct schedules {rep['distinct_schedules']}")
    print(format_latency(latency_summary(final)))
    if lat["e2e_p99"] > SLO_US:
        print(f"!! p99 {lat['e2e_p99']}us exceeds the {SLO_US}us SLO")
    if not do_fuzz:
        return
    # hunt tail amplification: the corpus pays extra energy for
    # admissions whose lanes sit at the round's worst p99, and the SLO
    # invariant turns a p99 regression into a crash code (CRASH_SLO)
    # with a (seed, knobs) repro, bucketable like any safety bug
    from madsim_tpu import ProgressObserver, fuzz
    rt_slo = make_open_loop_runtime(
        invariant=slo_invariant(p99_le=SLO_CRASH_US, min_count=4))
    res = fuzz(rt_slo, max_steps=60_000, batch=max(batch // 2, 16),
               max_rounds=6, dry_rounds=3, chunk=1024,
               lat_bonus=1.0, observer=ProgressObserver())
    print(f"fuzz: {res['distinct_schedules']} distinct schedules, "
          f"crash codes {sorted(res['crash_repros'])}")
    for code, rep_h in res["crash_repros"].items():
        print(f"  code {code}: seed {rep_h['seed']} (round "
              f"{rep_h['round']})")


if __name__ == "__main__":
    main()
