"""Coverage-guided schedule fuzzing vs blind seed sweeps, side by side.

    python examples/fuzz_search.py [rounds] [batch]

Runs the same chaos workload two ways at the same device budget: blind
`explore()` (fresh seeds, fixed fault script — it saturates) and the
coverage-guided `fuzz()` (corpus + on-device mutation of fault times,
targets, latencies, and PCT tie-break nudges — it keeps finding new
interleavings). Prints both coverage curves and, if the fuzzer found
crashes, the minimized fault script of each repro.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from _preflight import ensure_safe_backend  # noqa: E402

ensure_safe_backend()   # CPU fallback iff a wedged TPU tunnel would hang us

from madsim_tpu import ProgressObserver, explore, fuzz  # noqa: E402

# fixed-latency chaos: the schedule space seeds alone can reach is small,
# so the blind sweep goes dry — the regime where searching the knob space
# (instead of sampling seeds) pays; one shared definition with the
# search_ab bench and the search tests
from bench import _make_saturating_runtime as make_runtime  # noqa: E402


def main():
    rounds = int(sys.argv[1]) if len(sys.argv) > 1 else 6
    batch = int(sys.argv[2]) if len(sys.argv) > 2 else 128
    kw = dict(max_steps=1500, batch=batch, max_rounds=rounds,
              dry_rounds=rounds + 1, chunk=256)

    print(f"blind explore(): {rounds} rounds x {batch} seeds")
    blind = explore(make_runtime(), observer=ProgressObserver(), **kw)

    print(f"\nfuzz(): same budget, coverage-guided")
    res = fuzz(make_runtime(), observer=ProgressObserver(),
               minimize=True, **kw)

    print(f"\n  blind:  {blind['distinct_schedules']:>5} distinct "
          f"schedules  {blind['new_per_round']}")
    print(f"  fuzzer: {res['distinct_schedules']:>5} distinct "
          f"schedules  {res['new_per_round']}")
    print(f"  corpus: {res['corpus_size']} entries; operator use: "
          f"{res['mutation_ops']}")
    for code, rep in res["crash_repros"].items():
        print(f"\n  crash code {code}: seed {rep['seed']} "
              f"(round {rep['round']}) — fault script:")
        print(rep["script"])
        mini = res.get("minimized", {}).get(code)
        if mini and "script" in mini:
            print(f"  minimized to {mini['kept']} rows "
                  f"({mini['runs']} batched dispatches):")
            print(mini["script"])


if __name__ == "__main__":
    main()
