"""Real-ecosystem wire interop: a gRPC gateway in front of a RealRuntime
service.

When the reference builds for production, madsim-tonic re-exports REAL
tonic (madsim-tonic/src/lib.rs:7-8) — its services are wire-compatible
with any gRPC peer. This runtime's real twin natively speaks its own
`[tag, src, payload-words]` datagram format (real/runtime.py), so
third-party interop goes through a GATEWAY: a stock grpcio server
(HTTP/2 on a TCP port — the standard gRPC wire) that adapts each method
of a net/codegen.py-generated service onto the runtime's wire format.

The demo is three parties:
  backend  — a separate OS process running RealRuntime + the generated
             Store service (the same StoreImpl shape as
             examples/codegen_service.py), node 0 on UDP base_port.
  gateway  — THIS process: grpc.server() with one generic handler per
             schema method; a gRPC request's bytes are the request
             message's int32 words (little-endian, field order — exactly
             the generated Layout), forwarded as a framework datagram
             from gateway node id 1, reply matched by call id.
  client   — a vanilla grpcio channel. It does NOT import the framework:
             it packs requests with plain struct from the schema alone —
             the third-party-peer proof.

Run:  python examples/grpc_gateway.py
Skips (exit 0 with a note) if grpcio is not installed.
"""

import itertools
import json
import os
import socket
import struct
import subprocess
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from _preflight import ensure_safe_backend  # noqa: E402

SCHEMA = """
syntax = "proto3";

message PutReq { int32 key = 1; int32 val = 2; }
message PutRsp { int32 ok = 1; }
message GetReq { int32 key = 1; }
message GetRsp { int32 val = 1; int32 found = 2; }

service Store {
  rpc Put(PutReq) returns (PutRsp);
  rpc Get(GetReq) returns (GetRsp);
}
"""

BASE_PORT = 19820        # UDP: node 0 = backend, node 1 = gateway
GRPC_PORT = 19840        # TCP: the standard gRPC wire
PAYLOAD_WORDS = 8
N_KEYS = 4
REPLY_BIT = 1 << 30


# ---------------------------------------------------------------- backend
def backend_main(duration: float):
    """Child-process entry: the RealRuntime service node."""
    ensure_safe_backend()
    import asyncio

    import jax.numpy as jnp

    from madsim_tpu import SimConfig, sec
    from madsim_tpu.net import codegen
    from madsim_tpu.real.runtime import RealRuntime

    pb = {}
    exec(compile(codegen.generate(SCHEMA), "store_pb.py", "exec"), pb)

    class StoreImpl(pb["StoreBase"]):
        def handle_put(self, ctx, st, req, when):
            k = jnp.clip(req["key"], 0, N_KEYS - 1)
            onehot = jnp.arange(N_KEYS) == k
            st["kv"] = jnp.where(onehot & when, req["val"], st["kv"])
            st["has"] = st["has"] | (onehot & when)
            return dict(ok=jnp.asarray(when, jnp.int32))

        def handle_get(self, ctx, st, req, when):
            k = jnp.clip(req["key"], 0, N_KEYS - 1)
            onehot = jnp.arange(N_KEYS) == k
            return dict(val=jnp.where(onehot, st["kv"], 0).sum(),
                        found=(st["has"] & onehot).any().astype(jnp.int32))

    spec = dict(kv=jnp.zeros((N_KEYS,), jnp.int32),
                has=jnp.zeros((N_KEYS,), jnp.bool_))
    # n_nodes=2 but ONLY node 0 starts: node 1's address belongs to the
    # external gateway process (start(nodes=[0]) leaves its port unbound)
    rt = RealRuntime(SimConfig(n_nodes=2, payload_words=PAYLOAD_WORDS,
                               time_limit=sec(600)),
                     [StoreImpl()], spec, node_prog=[0, 0],
                     base_port=BASE_PORT)

    async def main():
        await rt.start(nodes=[0])
        print("backend: ready", flush=True)
        await asyncio.sleep(duration)

    asyncio.run(main())


# ---------------------------------------------------------------- gateway
class UdpBridge:
    """One UDP socket at the gateway's node address; serialized
    request/reply round-trips into the runtime's wire format."""

    def __init__(self, methods):
        self.methods = methods           # path -> (tag, req_w, rsp_w)
        self.sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        self.sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self.sock.bind(("127.0.0.1", BASE_PORT + 1))
        self.sock.settimeout(1.0)
        self.lock = threading.Lock()
        self.call_ids = itertools.count(1)

    def round_trip(self, path: str, req_bytes: bytes) -> bytes:
        tag, req_w, rsp_w = self.methods[path]
        assert len(req_bytes) == 4 * req_w, \
            f"{path}: want {4 * req_w} request bytes, got {len(req_bytes)}"
        body = struct.unpack(f"<{req_w}i", req_bytes) if req_w else ()
        with self.lock:
            call_id = next(self.call_ids)
            payload = (call_id,) + body
            payload += (0,) * (PAYLOAD_WORDS - len(payload))
            frame = struct.pack(f"<ii{PAYLOAD_WORDS}i", tag, 1, *payload)
            for _ in range(5):           # UDP: retry on (unlikely) loss
                self.sock.sendto(frame, ("127.0.0.1", BASE_PORT))
                try:
                    while True:
                        data, _ = self.sock.recvfrom(65536)
                        if len(data) != 8 + 4 * PAYLOAD_WORDS:
                            continue
                        rtag, _src, *words = struct.unpack(
                            f"<ii{PAYLOAD_WORDS}i", data)
                        if rtag == (tag | REPLY_BIT) and words[0] == call_id:
                            return struct.pack(
                                f"<{rsp_w}i", *words[1:1 + rsp_w])
                except socket.timeout:
                    continue
        raise TimeoutError(f"no reply from backend for {path}")


def make_gateway(methods):
    import grpc
    bridge = UdpBridge(methods)

    class Handler(grpc.GenericRpcHandler):
        def service(self, call_details):
            path = call_details.method
            if path not in methods:
                return None

            def behavior(request, context, path=path):
                return bridge.round_trip(path, request)

            # bytes in/out: the message format is the schema's int32
            # words — any gRPC stack that can send bytes interoperates
            return grpc.unary_unary_rpc_method_handler(behavior)

    from concurrent import futures
    server = grpc.server(futures.ThreadPoolExecutor(max_workers=4))
    server.add_generic_rpc_handlers((Handler(),))
    server.add_insecure_port(f"127.0.0.1:{GRPC_PORT}")
    return server, bridge


def schema_methods():
    """path -> (tag, req_words, rsp_words), derived from the schema the
    same way the backend derives it (exec the generated module)."""
    from madsim_tpu.net import codegen
    pb = {}
    exec(compile(codegen.generate(SCHEMA), "store_pb.py", "exec"), pb)
    messages, services = codegen.parse(SCHEMA)
    out = {}
    for sname, rpcs in services.items():
        base = pb[f"{sname}Base"]
        for meth, req, _rs, rsp, _ps in rpcs:
            out[f"/store.{sname}/{meth}"] = (
                getattr(base, meth).tag, len(messages[req]),
                len(messages[rsp]))
    return out


# ---------------------------------------------------------------- client
def third_party_client():
    """A vanilla gRPC caller: no framework imports, just the schema.
    Returns the observed results dict."""
    import grpc
    ch = grpc.insecure_channel(f"127.0.0.1:{GRPC_PORT}")
    put = ch.unary_unary("/store.Store/Put")
    get = ch.unary_unary("/store.Store/Get")
    deadline = time.time() + 20
    while True:      # backend's jax import takes a few seconds; retry
        try:
            put(struct.pack("<2i", 0, 100), timeout=8)
            break
        except grpc.RpcError:
            if time.time() > deadline:
                raise
            time.sleep(0.5)
    put(struct.pack("<2i", 1, 101), timeout=8)
    out = {}
    for k in (0, 1, 3):
        val, found = struct.unpack("<2i", get(struct.pack("<i", k),
                                              timeout=8))
        out[k] = (val, found)
    ch.close()
    return out


def spawn_backend() -> subprocess.Popen:
    """Start the backend child pinned to CPU (a wedged TPU tunnel would
    hang its jax import forever). Shared by main() and the test so the
    spawn/teardown sequence cannot drift between them."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("PALLAS_AXON_POOL_IPS", None)
    return subprocess.Popen(
        [sys.executable, os.path.abspath(__file__), "--backend"],
        env=env, cwd=os.path.dirname(os.path.abspath(__file__)))


def reap_backend(backend: subprocess.Popen) -> None:
    backend.terminate()
    try:
        backend.wait(timeout=10)
    except subprocess.TimeoutExpired:
        # a child wedged in jax import can ignore SIGTERM; never let the
        # reap raise out of a finally (it would mask the real failure and
        # leak the process + its bound UDP port)
        backend.kill()
        backend.wait()


def run_demo():
    """Spawn backend, run gateway, drive the third-party client; returns
    the observed results. Shared by main() and tests/test_grpc_gateway."""
    backend = spawn_backend()
    server = bridge = None
    try:
        server, bridge = make_gateway(schema_methods())
        server.start()
        return third_party_client()
    finally:
        if server is not None:
            server.stop(0)
        if bridge is not None:
            bridge.sock.close()
        reap_backend(backend)


def main():
    try:
        import grpc  # noqa: F401
    except ImportError:
        print(json.dumps({"metric": "grpc_gateway_demo",
                          "skipped": "grpcio not installed"}))
        return
    ensure_safe_backend()
    results = run_demo()
    assert results[0] == (100, 1), results
    assert results[1] == (101, 1), results
    assert results[3] == (0, 0), results
    print(json.dumps({
        "metric": "grpc_gateway_demo", "ok": True,
        "results": {str(k): v for k, v in results.items()},
        "note": ("vanilla grpc client -> HTTP/2 -> gateway -> "
                 "framework UDP wire -> RealRuntime service"),
    }))


if __name__ == "__main__":
    if "--backend" in sys.argv:
        backend_main(duration=60.0)
    else:
        main()
