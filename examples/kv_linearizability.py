"""Linearizability fuzzing: KV-on-Raft under chaos, histories checked by
the native Wing-Gong checker.

    python examples/kv_linearizability.py [num_seeds]
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from _preflight import ensure_safe_backend  # noqa: E402

ensure_safe_backend()   # CPU fallback iff a wedged TPU tunnel would hang us

import numpy as np

from madsim_tpu import Scenario, SimConfig, NetConfig, ms, sec
from madsim_tpu.harness.simtest import run_seeds
from madsim_tpu.models.raft_kv import extract_histories, make_kv_runtime
from madsim_tpu.native import check_kv_history


def main():
    n_seeds = int(sys.argv[1]) if len(sys.argv) > 1 else 256
    n_raft, n_clients = 5, 3
    cfg = SimConfig(n_nodes=n_raft + n_clients, event_capacity=384,
                    payload_words=12, time_limit=sec(8),
                    net=NetConfig(packet_loss_rate=0.08))
    sc = Scenario()
    for t in range(4):
        sc.at(ms(700 + 800 * t)).kill_random(among=range(n_raft))
        sc.at(ms(1200 + 800 * t)).restart_random(among=range(n_raft))
    sc.at(sec(2)).partition([0, 1])
    sc.at(sec(3)).heal()

    rt = make_kv_runtime(n_raft, n_clients, n_keys=3, n_ops=8,
                         log_capacity=48, scenario=sc, cfg=cfg)
    state = run_seeds(rt, np.arange(n_seeds), max_steps=60_000, chunk=1024)
    hists = extract_histories(state, n_raft, n_clients)
    ok = sum(check_kv_history(h) for h in hists)
    completed = sum(int((h["resp"] >= 0).sum()) for h in hists)
    pending = sum(int((h["resp"] < 0).sum()) for h in hists)
    print(f"{n_seeds} seeds: {ok}/{n_seeds} histories linearizable, "
          f"{completed} ops completed, {pending} pending at halt")
    if ok != n_seeds:
        bad = next(i for i, h in enumerate(hists) if not check_kv_history(h))
        print(f"NON-LINEARIZABLE history at seed {bad}: {hists[bad]}")
        raise SystemExit(1)


if __name__ == "__main__":
    main()
