"""minipg demo: one postgres-shaped session protocol, two worlds.

    python examples/session_protocol.py          # simulated, with chaos
    python examples/session_protocol.py --real   # real asyncio sockets

Sim mode fuzzes 1k sessions under server kills and packet loss; every
response is oracle-checked in-model. Real mode runs the SAME protocol
classes over loopback UDP.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from _preflight import ensure_safe_backend  # noqa: E402

ensure_safe_backend()   # CPU fallback iff a wedged TPU tunnel would hang us

import numpy as np

from madsim_tpu import Scenario, SimConfig, NetConfig, ms, sec
from madsim_tpu.harness.simtest import run_seeds
from madsim_tpu.models.minipg import make_minipg_runtime


def sim_mode():
    n_seeds = 1_024
    cfg = SimConfig(n_nodes=3, event_capacity=384, payload_words=8,
                    time_limit=sec(10),
                    net=NetConfig(packet_loss_rate=0.05,
                                  send_latency_min=ms(1),
                                  send_latency_max=ms(8)))
    sc = Scenario()
    sc.at(ms(300)).kill(0)
    sc.at(ms(450)).restart(0)
    rt = make_minipg_runtime(n_clients=2, n_txns=4, scenario=sc, cfg=cfg)
    state = run_seeds(rt, np.arange(n_seeds), max_steps=60_000, chunk=1024)
    done = np.asarray(state.node_state["c_done"])[:, 1:]
    print(f"{n_seeds} seeds x 2 clients x 4 txns under kill+loss chaos:")
    print(f"  sessions completed: {(done == 1).mean() * 100:.1f}%")
    print(f"  every response verified in-model (status, read-your-writes, "
          f"commit visibility) — zero violations")


def real_mode():
    from madsim_tpu.models.minipg import PgClient, PgServer, pg_state_spec
    from madsim_tpu.real.runtime import RealRuntime
    cfg = SimConfig(n_nodes=2, time_limit=sec(60), payload_words=8)
    rt = RealRuntime(cfg, [PgServer(2, 4, tick=ms(110)),
                           PgClient(2, tick=ms(140), stall=ms(6000))],
                     pg_state_spec(2, 4), node_prog=[0, 1],
                     base_port=19900)
    rt.run(duration=30.0)
    done = int(rt.states()[1]["c_done"])
    kv = np.asarray(rt.states()[0]["kv"])
    print(f"real sockets: client done={done}, table={kv.tolist()}")
    assert done == 1 and not rt.crashed


if __name__ == "__main__":
    real_mode() if "--real" in sys.argv else sim_mode()
