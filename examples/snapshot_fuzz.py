"""Raft snapshot fuzz: the log window is far smaller than the workload, so
trajectories only survive through compaction + InstallSnapshot — and a
node that slept through most of the run recovers via snapshot transfer.

    python examples/snapshot_fuzz.py [num_seeds]
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from _preflight import ensure_safe_backend  # noqa: E402

ensure_safe_backend()   # CPU fallback iff a wedged TPU tunnel would hang us

import numpy as np

from madsim_tpu import Scenario, SimConfig, NetConfig, ms, sec
from madsim_tpu.harness.simtest import run_seeds
from madsim_tpu.models.raft import make_raft_runtime


def main():
    n_seeds = int(sys.argv[1]) if len(sys.argv) > 1 else 4_096
    cmds, log_cap = 40, 12           # 40 proposals through a 12-entry window
    cfg = SimConfig(n_nodes=5, event_capacity=256, time_limit=sec(12),
                    net=NetConfig(packet_loss_rate=0.05))
    sc = Scenario()
    sc.at(ms(400)).kill(0)           # node 0 misses almost everything
    sc.at(sec(5)).restart(0)         # ...and can only catch up by snapshot
    for t in range(3):
        sc.at(ms(900 + 900 * t)).kill_random(among=range(1, 5))
        sc.at(ms(1400 + 900 * t)).restart_random(among=range(1, 5))

    rt = make_raft_runtime(5, log_capacity=log_cap, n_cmds=cmds,
                           compact_threshold=4, scenario=sc, cfg=cfg)
    state = run_seeds(rt, np.arange(n_seeds), max_steps=40_000, chunk=1024)

    ns = state.node_state
    snap = np.asarray(ns["snap_len"])
    commit = np.asarray(ns["commit"])
    print(f"seeds: {n_seeds}")
    print(f"commit (min/median/max over seeds, cluster max): "
          f"{commit.max(1).min()} / {int(np.median(commit.max(1)))} / "
          f"{commit.max(1).max()}")
    print(f"snapshots: every live node compacted in "
          f"{(snap.max(1) > 0).mean() * 100:.1f}% of seeds; "
          f"node 0 recovered via InstallSnapshot in "
          f"{(snap[:, 0] > 0).mean() * 100:.1f}%")
    print(f"log window never exceeded {log_cap} entries; "
          f"safety checked after every event (digest chain below the "
          f"snapshot boundary)")


if __name__ == "__main__":
    main()
