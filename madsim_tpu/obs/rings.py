"""Read the on-device flight-recorder ring out of a final SimState.

The ring is written inside the step (core/step.py, gated on
cfg.trace_cap > 0 and the per-lane `trace_on` sampling mask set by
`Runtime.init_batch(trace_lanes=...)`): the last trace_cap FIRED events
per sampled lane, with `trace_pos` counting every event ever recorded, so
`pos > cap` means the ring wrapped and the oldest `pos - cap` records were
overwritten. Unlike the `collect_events` stream there are no frozen-lane
`fired=False` rows to filter — the ring only ever holds real dispatches.

Host-boundary cost: O(trace_cap) ints per sampled lane, transferred once,
after the sweep — against O(steps x batch) for `collect_events`, which is
why the ring is the path that works with `run_fused`.
"""

from __future__ import annotations

import numpy as np

from ..core.state import TRACE_FIELDS
from ..utils.hostcopy import owned_host_copy

# record columns = the tr_* schema fields, names sans prefix
_COLS = tuple(f[3:] for f in TRACE_FIELDS if f.startswith("tr_"))


def _require_addressable(state, what: str) -> None:
    leaf = state.trace_on
    if not getattr(leaf, "is_fully_addressable", True):
        raise ValueError(
            f"{what} needs an addressable state: this batch spans "
            "non-addressable shards (multi-process sharding). Read rings "
            "from the host that owns the lane — e.g. rebuild a local "
            "state from `leaf.addressable_shards` / the per-host slice "
            "that was assembled into the global batch — or gather the "
            "tr_* columns explicitly before exporting")


def sampled_lanes(state) -> np.ndarray:
    """Indices of the lanes whose rings recorded (the `trace_lanes` the
    batch was initialized with, as observed from the state itself)."""
    _require_addressable(state, "sampled_lanes")
    on = np.atleast_1d(np.asarray(state.trace_on))
    return np.nonzero(on)[0]


def ring_records(state, lane: int = 0) -> dict:
    """One lane's ring, unwrapped into chronological order (host-side).

    Returns {now, step, kind, node, src, tag, parent, lamport: int32[n],
    total: int, dropped: int} where n = min(total, trace_cap) (`parent`/
    `lamport` are the causal-lineage pair, obs/causal.py — absent only
    for pre-r10 states), `total` is every event
    the lane ever recorded and `dropped` counts ring-wrap overwrites
    (oldest-first). Raises if the runtime compiled the ring out or the
    lane was not sampled — a silent empty trace would read as "nothing
    happened". Under multi-process sharding, read on the host that owns
    the lane (see the error message for the recipe) — the ring survives
    the sharded `run_fused` fine; only the host-side read is local.
    """
    _require_addressable(state, "ring_records")
    # OWNED host copies (utils/hostcopy): the returned columns are held
    # by the caller across later donated runs of the same state buffers —
    # a zero-copy view would dangle (the PR-2 warm-cache bug class).
    # Columns a state lacks (pre-r10 checkpoints, synthetic fixtures
    # without the lineage pair) are simply absent from the record dict —
    # consumers .get() them (obs/trace.py, obs/causal.py).
    # .shape alone — a np.asarray here would device-to-host copy every
    # column a second time just to learn its length
    if state.tr_now.shape[-1] == 0:
        raise ValueError("trace ring is compiled out (cfg.trace_cap == 0)")
    # zero-size columns are COMPILED-OUT columns riding a narrower gate
    # than the ring itself (tr_qlen needs cfg.profile too) — skip them
    # like absent ones, same .get() contract for consumers
    cols = {k: owned_host_copy(getattr(state, f"tr_{k}")) for k in _COLS
            if hasattr(state, f"tr_{k}")
            and getattr(state, f"tr_{k}").shape[-1] > 0}
    pos = np.asarray(state.trace_pos)
    on = np.asarray(state.trace_on)
    # LOGICAL capacity is the dynamic state operand (cfg.trace_cap);
    # column length is its power-of-two bucket — rows past cap are
    # never written (core/step.py), so readers index mod cap only.
    # States without the operand (pre-bucketing checkpoints, synthetic
    # fixtures) degrade to column length == capacity.
    cap_arr = np.asarray(getattr(state, "trace_cap",
                                 cols["now"].shape[-1]))
    if cols["now"].ndim == 2:          # batched state: select the lane
        cols = {k: v[lane] for k, v in cols.items()}
        pos, on = pos[lane], on[lane]
        cap_arr = cap_arr[lane] if cap_arr.ndim else cap_arr
    if cols["now"].shape[0] == 0:
        raise ValueError("trace ring is compiled out (cfg.trace_cap == 0)")
    cap = int(cap_arr)
    if not bool(on):
        raise ValueError(
            f"lane {lane} was not sampled (init_batch trace_lanes mask); "
            f"sampled lanes: {sampled_lanes(state).tolist()}")
    total = int(pos)
    n = min(total, cap)
    # oldest surviving record sits at pos % cap once wrapped, at 0 before
    start = total % cap if total > cap else 0
    order = (start + np.arange(n)) % cap
    out = {k: v[order] for k, v in cols.items()}
    out["total"] = total
    out["dropped"] = total - n
    return out
