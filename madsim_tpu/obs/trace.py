"""Chrome-trace/Perfetto export: the visual timeline over virtual time.

Converts either source of event records into one JSON document that
chrome://tracing and ui.perfetto.dev open directly:

  * a `collect_events` stream (Runtime.run(collect_events=True) /
    run_single) — frozen-lane `fired=False` records are filtered out per
    the overshoot contract (runtime/runtime.py run() docstring: consumers
    filter on `fired`, never on step count);
  * a flight-recorder ring (obs/rings.py) from any final state, including
    one produced by `run_fused`.

Track layout: one thread track per node (tid = node id, named via
thread_name metadata), virtual-time microseconds on the time axis (the
engine's tick IS a microsecond, so no scaling). Every dispatch renders as
an instant event; supervisor ops (kill/restart/clog/...) land on the track
of the node they act on, named "SUPER:<OP>", so a chaos script reads
straight off the timeline. Ring sources with lineage columns (r10) also
render message causality: every resolvable happens-before edge becomes a
Perfetto flow arrow (`ph:"s"` at the enqueuing dispatch, `ph:"f"` at the
child), and instant args carry step/lamport/parent for trace-side joins
against `explain_crash` chains and divergence reports. Attribution-plane
rings (r23, cfg.span_attr — marked by the `qw` queue-wait column)
additionally render every recorded completion as an async REQUEST
DURATION span: a `ph:"b"`/`ph:"e"` pair from the request's root dispatch
to its completion (id = the completion's dispatch index, args carry
lat_us), so tail requests read as long bars above the instant tracks and
join against `explain_latency` critical paths.

Export contract: `export_chrome_trace` returns the INSTANT count only.
Flow arrows, counter samples, and request spans ride in the document but
are never counted — they annotate dispatches. A document written from a
build with a plane disabled is byte-identical to one written before that
plane existed (golden-JSON tested against the frozen r22 capture,
tests/test_spans.py).
"""

from __future__ import annotations

import json

import numpy as np

from ..core import types as T

_KIND = {T.EV_MSG: "MSG", T.EV_TIMER: "TIMER", T.EV_SUPER: "SUPER"}
_OP = {v: k[3:] for k, v in vars(T).items() if k.startswith("OP_")}


def _event(now, kind, node, src, tag, **extra):
    k = _KIND.get(kind, f"?{kind}")
    if kind == T.EV_SUPER:
        name = f"SUPER:{_OP.get(tag, tag)}"
    else:
        name = f"{k}:tag{tag}"
    return dict(name=name, ph="i", s="t", ts=now, pid=0, tid=node,
                args=dict(src=src, tag=tag, **extra))


def _doc(events: list[dict], node_names=None, node_args=None) -> dict:
    # counter-track events (ph="C", obs/profiler.py) carry no tid —
    # thread metadata names only the per-node instant/flow tracks.
    # node_args (r17) folds extra per-node facts (clock skew, disk
    # latency) into the thread metadata args, so a gray-failure run's
    # fault assignment reads straight off the Perfetto track list.
    tids = sorted({e["tid"] for e in events if "tid" in e})
    meta = [dict(name="thread_name", ph="M", pid=0, tid=t,
                 args=dict(name=(node_names[t] if node_names is not None
                                 else f"node{t}"),
                           **((node_args or {}).get(t, {}))))
            for t in tids]
    return dict(traceEvents=meta + events, displayTimeUnit="ms")


def to_chrome_events(source, b: int = 0) -> list[dict]:
    """Normalize a record source into Chrome-trace instant events.

    `source` is either the dict returned by `collect_events=True` (leaves
    shaped [steps, batch, ...]; `b` selects the lane and `fired=False`
    frozen-lane records are dropped) or a `ring_records()` dict (already
    one lane, already only real dispatches).

    Every instant event's `args` carries `step` — the dispatch index —
    so Perfetto queries can join the timeline against divergence
    reports and `explain_crash` chains (a stream's k-th `fired` record
    IS dispatch k, matching the ring's `tr_step`). Ring sources with
    lineage columns (r10) additionally carry `parent` and `lamport`,
    and each resolvable happens-before edge is rendered as a Perfetto
    FLOW arrow: a `ph:"s"` at the parent dispatch paired with a
    `ph:"f"` at the child (id = the child's dispatch index), appended
    after the instants.
    """
    if "fired" in source:                      # collect_events stream
        cols = {k: np.asarray(source[k])[:, b]
                for k in ("fired", "now", "kind", "node", "src", "tag")}
        idx = np.nonzero(cols["fired"])[0]
        return [_event(int(cols["now"][i]), int(cols["kind"][i]),
                       int(cols["node"][i]), int(cols["src"][i]),
                       int(cols["tag"][i]), step=k)
                for k, i in enumerate(idx)]
    cols = source                              # ring_records dict
    n = len(np.asarray(cols["now"]))
    steps = cols.get("step")
    parents = cols.get("parent")
    lamports = cols.get("lamport")
    out = []
    for i in range(n):
        extra = {}
        if steps is not None:
            extra["step"] = int(steps[i])
        if lamports is not None:
            extra["lamport"] = int(lamports[i])
        if parents is not None:
            extra["parent"] = int(parents[i])
        out.append(_event(int(cols["now"][i]), int(cols["kind"][i]),
                          int(cols["node"][i]), int(cols["src"][i]),
                          int(cols["tag"][i]), **extra))
    if steps is not None and parents is not None:
        # message causality as arrows on the per-node tracks: one flow
        # start ("s") at the enqueuing dispatch, one finish ("f") at the
        # child, bound by id = child dispatch index (each dispatch has
        # exactly one parent). Edges whose parent fell off the ring are
        # simply not drawn — the wrap contract (obs/causal.py).
        present = {int(s): i for i, s in enumerate(steps)}
        for i in range(n):
            p = int(parents[i])
            if p < 0 or p not in present:
                continue
            j = present[p]
            flow = dict(name="causal", cat="causal", id=int(steps[i]),
                        pid=0)
            out.append(dict(flow, ph="s", ts=int(cols["now"][j]),
                            tid=int(cols["node"][j])))
            out.append(dict(flow, ph="f", bp="e", ts=int(cols["now"][i]),
                            tid=int(cols["node"][i])))
    lats = cols.get("lat")
    if steps is not None and lats is not None and "qw" in cols:
        # request duration spans (r23): one async "b"/"e" pair per
        # recorded completion, spanning its root dispatch → completion
        # in virtual time (ts = now − recorded e2e), id = the
        # completion's dispatch index — joinable against
        # `explain_latency` output. Gated on the `qw` column, the
        # attribution plane's ring marker (cfg.span_attr): a span-off
        # document is byte-identical to what r22 wrote.
        for i in range(n):
            lat = int(lats[i])
            if lat < 0:
                continue
            span = dict(name=f"request:tag{int(cols['tag'][i])}",
                        cat="request", id=int(steps[i]), pid=0)
            out.append(dict(span, ph="b", ts=int(cols["now"][i]) - lat,
                            args=dict(step=int(steps[i]), lat_us=lat,
                                      node=int(cols["node"][i]))))
            out.append(dict(span, ph="e", ts=int(cols["now"][i])))
    return out


def export_chrome_trace(path: str, events=None, b: int = 0,
                        state=None, lane: int = 0, node_names=None) -> int:
    """Write one lane's trace as Chrome/Perfetto JSON; returns the number
    of INSTANT events written — which equals the lane's `fired=True`
    record count (collect_events source) or its surviving ring length
    (state source). Causal flow arrows (`ph:"s"/"f"` pairs, emitted for
    ring sources with lineage columns) and request duration spans
    (`ph:"b"/"e"` pairs, emitted for attribution-plane rings, r23) ride
    in the document but are not counted — they annotate dispatches,
    they aren't dispatches.

    Pass exactly one source: `events` (+ `b`) from a
    `collect_events=True` run, or `state` (+ `lane`) to read the
    flight-recorder ring of a final state — the only trace source a
    `run_fused` sweep has.
    """
    if (events is None) == (state is None):
        raise ValueError("pass exactly one of events= or state=")
    node_args = None
    if state is not None:
        from .rings import ring_records
        out = to_chrome_events(ring_records(state, lane))
        # gray-failure fault assignment (r17) on the track args: the
        # lane's final per-node clock skew and disk latency, included
        # only when some node actually carries a fault — a clean run's
        # golden document is byte-identical to r16's
        skew = np.asarray(state.skew)
        dlat = np.asarray(state.disk_lat)
        if skew.ndim == 2:          # batched state: this lane's view
            skew, dlat = skew[lane], dlat[lane]
        if skew.any() or dlat.any():
            node_args = {n: dict(skew=int(skew[n]),
                                 disk_lat=int(dlat[n]))
                         for n in range(skew.shape[0])}
    else:
        out = to_chrome_events(events, b)
    with open(path, "w") as f:
        json.dump(_doc(out, node_names, node_args), f)
    return sum(1 for e in out if e["ph"] == "i")
