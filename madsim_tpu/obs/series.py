"""Windowed-series reports + true sim-time Perfetto counter tracks.

The WHEN layer of the observability stack (DESIGN §22): the r15/r16
profiler answers *where* effort went and *how long* requests took —
over the WHOLE run, one number per counter. This module renders the
r21 windowed telemetry plane (cfg.series_windows, the sr_* SimState
columns): the same pressure and tail signals bucketed by VIRTUAL TIME,
so a partition at t=2s reads as a spike in windows 2-3 and a heal
reads as the curve coming back down — the shape the recovery oracle
(`harness.recovery_invariant`) judges and the fuzzer's burst_bonus
hunts.

Three consumers:

  * `series_summary` / `format_series` — the operator report: batch-
    merged per-window rows off the on-device
    `parallel.stats.series_digest` reduction (O(W·K) host transfer),
    with the fault-marker words decoded to names.
  * `lane_series` — ONE lane's raw window columns as host numpy (per-
    lane triage, dashboard sparklines): unlike the ring, this is the
    whole run's timeline — windows never wrap, late events clamp into
    the last window instead of evicting the first.
  * `series_counter_track_events` — Perfetto counter tracks with
    timestamps at true window starts (w · window_len). The ring-derived
    tracks in obs/profiler.py go silent for everything older than
    trace_cap dispatches; these cover t=0 to now at window granularity
    regardless of run length, and `counter_track_events` prefers them
    when the plane is compiled in (satellite: ring path stays as the
    fine-grained fallback).
"""

from __future__ import annotations

import numpy as np

from ..core import types as T
from ..parallel.stats import latency_bucket_edges, series_counters

# fault-marker bit -> operator-facing name (core/types.py SRF_*)
SRF_NAMES = ((T.SRF_KILL, "kill"), (T.SRF_BOOT, "boot"),
             (T.SRF_PARTITION, "partition"), (T.SRF_HEAL, "heal"),
             (T.SRF_NET, "net"), (T.SRF_GRAY, "gray"),
             (T.SRF_CONN, "conn"))


def fault_names(word: int) -> list[str]:
    """Decode a sr_fault bitmask word into sorted marker names."""
    return [nm for bit, nm in SRF_NAMES if int(word) & bit]


def _window_p99(lat: np.ndarray) -> np.ndarray:
    """Per-window p99 lower-bound edges (ticks) from a [W, LB] int
    window-latency histogram — the host-side twin of the all-integer
    CDF rule in `harness.recovery` / `parallel.stats` (same
    `latency_bucket_edges` table, so reports and oracle agree)."""
    counts = lat.astype(np.int64)
    total = counts.sum(-1)                                # [W]
    cdf = counts.cumsum(-1)
    need = np.maximum((total * 99 + 99) // 100, 1)[:, None]
    b = (cdf >= need).argmax(-1)
    edges = latency_bucket_edges(lat.shape[1])
    return np.where(total > 0, edges[b], 0)


def lane_series(state, lane: int = 0) -> dict | None:
    """One lane's windowed series as host numpy: the whole-run timeline
    for per-lane triage and sparklines. None when the plane is compiled
    out (cfg.series_windows == 0), the state is unbatched, or the lane
    was masked out of recording (`init_batch(series_lanes=)`) — a
    masked lane's windows are all-zero by construction, which would
    render as a healthy flatline; None says "not recorded" instead.

    Keys: windows, window_len (this lane's dynamic knob), now, touched
    (windows with any sim-time coverage, overflow window included),
    dispatch [W, N], busy [W, N], qhw/drop/dup/complete/slo_miss/fault
    [W], and — latency-plane builds — lat [W, LB] plus the derived
    e2e_p99 [W] lower-bound edges."""
    sq = getattr(state, "sr_qhw", None)
    if sq is None or sq.ndim != 2 or sq.shape[1] == 0:
        return None
    if not bool(np.asarray(state.sr_on)[lane]):
        return None
    W = int(sq.shape[1])
    wl = max(int(np.asarray(state.window_len)[lane]), 1)
    now = int(np.asarray(state.now)[lane])
    out = dict(
        windows=W, window_len=wl, now=now,
        touched=min(now // wl, W - 1) + 1,
        dispatch=np.asarray(state.sr_dispatch[lane]),
        busy=np.asarray(state.sr_busy[lane]),
        qhw=np.asarray(sq[lane]),
        drop=np.asarray(state.sr_drop[lane]),
        dup=np.asarray(state.sr_dup[lane]),
        complete=np.asarray(state.sr_complete[lane]),
        slo_miss=np.asarray(state.sr_slo_miss[lane]),
        fault=np.asarray(state.sr_fault[lane]),
    )
    sl = state.sr_lat
    if sl.ndim == 3 and sl.shape[1] > 0 and sl.shape[2] > 0:
        lat = np.asarray(sl[lane])
        out["lat"] = lat
        out["e2e_p99"] = _window_p99(lat)
    return out


def series_summary(state) -> dict | None:
    """The windowed-series report for a batched state: one row per
    window off the on-device `parallel.stats.series_digest` reduction
    (batch-merged over the recording lanes), fault words decoded.
    None when the plane is compiled out or the state is unbatched.

    Row fields: window, t0_us (window start at the dominant
    window_len), dispatches, busy_us, qhw (deepest queue any recording
    lane saw in that window), drops, dups, completions, slo_miss,
    e2e_p99 (merged lower-bound estimate; latency builds only), and
    faults (decoded marker names — which disruptions/heals DISPATCHED
    in this window, batch-OR)."""
    c = series_counters(state)
    if c is None:
        return None
    disp = np.asarray(c["dispatch"], np.int64)            # [W, N]
    busy = np.asarray(c["busy"], np.int64)
    wl = max(c["window_len"], 1)
    rows = []
    for w in range(c["windows"]):
        row = dict(window=w, t0_us=w * wl,
                   dispatches=int(disp[w].sum()),
                   busy_us=int(busy[w].sum()),
                   qhw=int(c["qhw"][w]),
                   drops=int(c["drop"][w]), dups=int(c["dup"][w]),
                   completions=int(c["complete"][w]),
                   slo_miss=int(c["slo_miss"][w]),
                   faults=fault_names(c["fault"][w]))
        if "e2e_p99_by_window" in c:
            row["e2e_p99"] = int(c["e2e_p99_by_window"][w])
        rows.append(row)
    return dict(lanes=c["lanes"], windows=c["windows"],
                window_len=c["window_len"], rows=rows)


def format_series(summary: dict | None) -> str:
    """Render a `series_summary` dict as a fixed-width text table —
    the operator-facing sim-time timeline."""
    if summary is None:
        return "series plane compiled out (SimConfig.series_windows=0)"
    has_lat = any("e2e_p99" in r for r in summary["rows"])
    head = (f"{'win':>4} {'t0_us':>10} {'dispatch':>9} {'qhw':>5} "
            f"{'drops':>6} {'dups':>5} {'compl':>6}")
    if has_lat:
        head += f" {'p99_us':>7} {'miss':>5}"
    head += "  faults"
    lines = [
        f"recorded lanes: {summary['lanes']}  windows: "
        f"{summary['windows']} x {summary['window_len']}us",
        head,
    ]
    for r in summary["rows"]:
        line = (f"{r['window']:>4} {r['t0_us']:>10} {r['dispatches']:>9} "
                f"{r['qhw']:>5} {r['drops']:>6} {r['dups']:>5} "
                f"{r['completions']:>6}")
        if has_lat:
            line += f" {r.get('e2e_p99', 0):>7} {r['slo_miss']:>5}"
        line += "  " + (",".join(r["faults"]) if r["faults"] else "-")
        lines.append(line)
    return "\n".join(lines)


def _counter(name: str, ts: int, value, series: str = "value",
             pid: int = 0) -> dict:
    # same Chrome-trace counter shape as obs/profiler.py emits — kept
    # local so the module import graph stays acyclic (profiler imports
    # this module lazily for the satellite derivation)
    return dict(name=name, ph="C", ts=int(ts), pid=pid,
                args={series: float(value)})


def series_counter_track_events(state, lane: int = 0,
                                node_names=None) -> list[dict]:
    """Perfetto counter-track events for one lane from its windowed
    series — timestamps at TRUE window starts (w · window_len) on the
    same virtual-time axis as the r7 instants, covering the whole run
    regardless of trace_cap wrap:

      queue_depth    per-window event-table occupancy high-water
      busy_pct:<n>   node n's busy share of each window's span
      e2e_p99        merged per-window p99 lower bound (latency-plane
                     builds; cluster-wide — per-node tails stay on the
                     ring-derived rolling track)
      slo_miss       per-window SLO miss count (latency-plane builds)
      fault          the window's raw SRF_* marker word (0 = quiet)

    Returns [] when the plane is compiled out or the lane is masked —
    the caller (obs/profiler.counter_track_events) falls back to the
    ring-reconstructed tracks then."""
    ls = lane_series(state, lane)
    if ls is None:
        return []
    wl, now = ls["window_len"], ls["now"]
    W = ls["windows"]
    N = ls["dispatch"].shape[1]
    label = [node_names[n] if node_names is not None else f"node{n}"
             for n in range(N)]
    out = []
    for w in range(ls["touched"]):
        ts = w * wl
        # the last structural window absorbs everything past W·wl
        # (the clamp rule), so its span stretches to `now`
        span = max((now - ts) if w == W - 1 else wl, 1)
        span = min(span, max(now - ts, 1))
        out.append(_counter("queue_depth", ts, ls["qhw"][w], "depth"))
        for n in range(N):
            out.append(_counter(
                f"busy_pct:{label[n]}", ts,
                round(100.0 * int(ls["busy"][w, n]) / span, 2),
                "busy_pct"))
        if "e2e_p99" in ls:
            out.append(_counter("e2e_p99", ts, ls["e2e_p99"][w],
                                "p99_us"))
            out.append(_counter("slo_miss", ts, ls["slo_miss"][w],
                                "misses"))
        out.append(_counter("fault", ts, ls["fault"][w], "srf_bits"))
    return out
