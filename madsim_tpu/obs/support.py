"""Green-run support extraction: the happens-before slice a SUCCESS
depended on.

The causal plane (r10) walks parent edges backward from a crash to
explain a failure; this module points the same walk at a SUCCESS — the
LDFI move (Alvaro et al., "Lineage-driven Fault Injection"): run green,
extract the support of the good outcome, and let the fault planner cut
precisely those edges instead of spraying faults blind. The support of
a lane is the set of message edges (src → dst at a sim-time instant)
and timer firings (node, deadline) on the lineage chain from the lane's
success witness (`harness.success_witness`, default: its last dispatch)
back to an external root.

Wrap honesty (the r11 suffix contract, verbatim): ring wrap truncates
lineage at the ROOT end, so a support extracted from a wrapped ring is
a faithful SUFFIX of the true support — `truncated=True` rides the
support dict and every consumer must treat the edge set as a lower
bound, never as "the whole story". `extract_support(replay=True)`
refuses to settle for the suffix: it re-executes the lane's
(seed, knobs) repro handle from the t=0 checkpoint (r20 window replay)
with the ring upgraded to hold every dispatch, and extracts the support
from the unwrapped replayed ring instead.

Everything here is host-side numpy over `ring_records()` reads — no
jitted program changes shape because a support was extracted.
"""

from __future__ import annotations

import numpy as np

from ..core import types as T
from .causal import walk_lineage
from .rings import ring_records


def support_from_records(recs: dict, witness=None) -> dict | None:
    """Extract the support of one lane's outcome from its ring records.

    `recs` is a `ring_records()` dict; `witness` a finder built by
    `harness.success_witness` (None = the lane's last dispatch). Returns
    None when the witness matches no record (the lane never dispatched
    its declared success event — there is no support to extract), else:

      msg_edges    [(src, dst, now)] — message deliveries on the chain
      timer_edges  [(node, now)]     — timer firings on the chain
      depth        chain length (records walked, witness included)
      witness_step the dispatch index walked back from
      truncated    ring wrap cut the walk: the edges are a faithful
                   SUFFIX of the true support (honest lower bound)
      root_external  the walk reached an external cause (complete)
    """
    n = len(np.asarray(recs["step"]))
    if n == 0:
        return None
    if witness is None:
        idx = n - 1
    else:
        idx = witness(recs)
        if idx is None:
            return None
    walk = walk_lineage(recs, from_step=int(recs["step"][idx]))
    msg_edges: list[tuple[int, int, int]] = []
    timer_edges: list[tuple[int, int]] = []
    for rec in walk["chain"]:
        if rec["kind"] == T.EV_MSG:
            msg_edges.append((rec["src"], rec["node"], rec["now"]))
        elif rec["kind"] == T.EV_TIMER:
            timer_edges.append((rec["node"], rec["now"]))
    return dict(msg_edges=msg_edges, timer_edges=timer_edges,
                depth=len(walk["chain"]),
                witness_step=int(recs["step"][idx]),
                truncated=walk["truncated"],
                root_external=walk["root_external"])


def extract_support(state, lane: int = 0, *, witness=None,
                    replay: bool = False, rt=None, seed: int | None = None,
                    knobs: dict | None = None, nudge: int | None = None,
                    max_steps: int = 100_000, chunk: int = 512) -> dict | None:
    """The support of a live lane's outcome (`support_from_records` over
    its ring), with the r20 escape hatch for wrapped rings: when the
    live support comes back `truncated=True` and `replay=True`, the
    lane's (seed[, knobs][, nudge]) handle is replayed from t=0 with
    the ring upgraded to hold the whole window (`full_chain_replay`
    machinery) and the support re-extracted from the unwrapped ring —
    full fidelity at replay cost. Returns None when the witness never
    matched; the result carries `lane` and `replayed`.

    Raises (via ring_records) if the ring is compiled out or the lane
    unsampled; ValueError if replay=True without rt= and seed=.
    """
    sup = support_from_records(ring_records(state, lane), witness)
    if sup is not None and sup["truncated"] and replay:
        if rt is None or seed is None:
            raise ValueError("extract_support(replay=True) needs rt= and "
                             "seed= (the lane's repro handle)")
        from .timetravel import init_checkpoint, replay_window
        until = int(np.asarray(state.steps).reshape(-1)[lane])
        ckpt = init_checkpoint(rt, seed, knobs=knobs, nudge=nudge)
        win = replay_window(rt, ckpt, until_step=until,
                            max_steps=max_steps, chunk=chunk)
        rsup = support_from_records(ring_records(win["state"], 0), witness)
        if rsup is not None:
            rsup.update(lane=int(lane), replayed=True)
            return rsup
    if sup is not None:
        sup.update(lane=int(lane), replayed=False)
    return sup
