"""Request spans: per-hop critical paths over the attribution plane (r23).

The lineage layer (obs/causal.py, r10) answers *why an event happened*;
the span layer answers *where a request's time went*. With
`cfg.span_attr` the engine carries a per-row span accumulator
(core/state.py `ev_span`) and records each dispatch's own queue-wait in
the ring's `qw` column — which makes every completion's chain
decomposable ON THE HOST into per-hop (wait, transit) segments from the
ring alone:

    wait(hop)    = qw[hop]                      the dispatch's sojourn
                                                past its deadline
    transit(hop) = (now[hop] − qw[hop])         deadline minus the
                   − now[parent]                parent's dispatch time
                                                (= emission delay:
                                                network / disk / timer)

Segments TELESCOPE: over a completion's chain, Σ wait + Σ transit ==
the ring's recorded e2e latency, exactly — the same identity the
on-device `sa_tail` fold maintains (core/step.py), which is what
tests/test_spans.py cross-checks device-vs-host.

A chain here is exactly the critical path: the engine's parent edge
records the dispatch that ENQUEUED each event, so a request's chain IS
the unique dependency path that determined its completion time (fan-in
joins would need multi-parent edges; the engine's event model has
none — the caveat is documented on `request_span`).

Chains stop where the device's measurement stops (core/step.py root
rule): at an external root (parent == -1: scenario row, boot, host
injection — the root's own wait is NOT part of any request) or at a
root-kind re-mint (a `cfg.root_kinds` dispatch restarts the clock for
its emissions — the closed-loop client's new-request convention).

Everything here is host-side numpy over a `ring_records()` read, same
altitude as obs/causal.py; `explain_latency(replay=True)` rides the
r20 window replay to recover chains the live ring wrapped past.
"""

from __future__ import annotations

import numpy as np

from .causal import _rec_at
from .rings import ring_records


def _require_span(recs: dict) -> None:
    if "qw" not in recs:
        raise ValueError(
            "no span columns in the ring: build with "
            "SimConfig(span_attr=True) (and trace_cap > 0) — the qw "
            "queue-wait column is what makes per-hop attribution "
            "host-recoverable")


def _is_root_kind(recs: dict, i: int, root_kinds) -> bool:
    return any(int(recs["kind"][i]) == int(k) and int(recs["tag"][i]) == int(t)
               for k, t in root_kinds)


def request_span(recs: dict, from_step: int | None = None, *,
                 root_kinds=()) -> dict:
    """Decompose one dispatch's causal chain into per-hop segments.

    `recs` is a `ring_records()` dict from a `span_attr` build;
    `from_step` the DISPATCH INDEX to decompose (default: the lane's
    last recorded dispatch — for a completion, pass its step). Returns

      hops         hop records, OLDEST first, ENDING at `from_step`;
                   each is the causal record (step/now/kind/node/src/
                   tag/parent/lamport) plus wait_us / transit_us /
                   seg_us (wait + transit; transit_us is None on the
                   oldest hop of a truncated chain — its parent's
                   dispatch time is gone)
      root         the record the chain is measured FROM (the external
                   root or the re-mint dispatch), or None if truncated
      reminted     the root is a `root_kinds` re-mint, not an external
      truncated    the walk hit a parent overwritten by ring wrap —
                   hops are a faithful SUFFIX, totals partial
      lat_us       now(from_step) − now(root), None when truncated
      wait_us / transit_us    segment totals over the resolved hops
      dominant     {hop, node, seg_us} of the FIRST strictly-largest
                   segment walking root→completion — the same
                   strict-> update rule the device's dominant-segment
                   fold applies (core/step.py), so the two agree
                   hop-for-hop; None when no hop resolved fully

    The single-parent caveat: the engine's parent edge is the dispatch
    that ENQUEUED the event, so a chain is the request's one dependency
    path — which for this event model IS the critical path. Protocols
    that logically join several messages (quorums) surface only the
    edge of the message that actually enqueued the continuation.

    Raises ValueError on a ring without span columns (`span_attr` off),
    an empty ring, or a `from_step` the ring does not hold.
    """
    _require_span(recs)
    steps = np.asarray(recs["step"])
    n = len(steps)
    if n == 0:
        raise ValueError("empty ring — nothing to decompose "
                         "(did the lane ever dispatch?)")
    by_step = {int(s): i for i, s in enumerate(steps)}
    if from_step is None:
        i = n - 1
    elif int(from_step) in by_step:
        i = by_step[int(from_step)]
    else:
        raise ValueError(f"dispatch step {from_step} is not in the ring "
                         "(overwritten by wrap, or never recorded)")

    idxs = []                    # chain indices, NEWEST first
    root_i = None
    reminted = False
    truncated = False
    while True:
        idxs.append(i)
        parent = int(recs["parent"][i])
        if parent < 0:
            # external mint: the event roots at its OWN dispatch — it
            # is the chain's clock origin, not one of its hops
            root_i = idxs.pop()
            break
        if parent not in by_step:
            truncated = True
            break
        ip = by_step[parent]
        if _is_root_kind(recs, ip, root_kinds):
            root_i = ip
            reminted = True
            break
        i = ip

    idxs.reverse()               # oldest hop first
    hops = []
    wait_total = 0
    transit_total = 0
    for k, j in enumerate(idxs):
        h = _rec_at(recs, j)
        h["wait_us"] = int(recs["qw"][j])
        prev_now = (int(recs["now"][idxs[k - 1]]) if k > 0
                    else int(recs["now"][root_i]) if root_i is not None
                    else None)
        if prev_now is None:     # oldest hop of a truncated chain
            h["transit_us"] = None
            h["seg_us"] = None
        else:
            h["transit_us"] = (int(recs["now"][j]) - h["wait_us"]
                               - prev_now)
            h["seg_us"] = h["wait_us"] + h["transit_us"]
        wait_total += h["wait_us"]
        transit_total += h["transit_us"] or 0
        hops.append(h)

    dominant = None
    for k, h in enumerate(hops):
        if h["seg_us"] is not None and (dominant is None
                                        or h["seg_us"] > dominant["seg_us"]):
            dominant = dict(hop=k, node=h["node"], seg_us=h["seg_us"])

    root = _rec_at(recs, root_i) if root_i is not None else None
    lat = (int(recs["now"][idxs[-1]]) - root["now"]
           if root is not None and idxs else None)
    return dict(hops=hops, root=root, reminted=reminted,
                truncated=truncated, lat_us=lat,
                wait_us=wait_total, transit_us=transit_total,
                dominant=dominant)


def request_spans(state, lane: int = 0, *, root_kinds=(),
                  slo_target: int | None = None) -> list[dict]:
    """Every completion the lane's ring still holds, decomposed: a
    `request_span` per record with a recorded e2e latency (the ring's
    `lat` column, `cfg.complete_kinds`), ring order, each extended with
    `step` / `lat_us` (the ring's own measurement — asserted equal to
    the span's root-walk when the chain resolved) and, when
    `slo_target` is given, `tail` (lat > target). Raises like
    `request_span`; completions whose chain wrapped come back
    `truncated=True` rather than being dropped."""
    recs = ring_records(state, lane)
    _require_span(recs)
    if "lat" not in recs:
        raise ValueError("no completion latencies in the ring: set "
                         "cfg.complete_kinds (and latency_hist > 0)")
    lat = np.asarray(recs["lat"])
    out = []
    for i in np.nonzero(lat >= 0)[0]:
        sp = request_span(recs, int(recs["step"][i]),
                          root_kinds=root_kinds)
        if sp["lat_us"] is not None:
            assert sp["lat_us"] == int(lat[i]), \
                (sp["lat_us"], int(lat[i]))   # the telescoping identity
        sp["step"] = int(recs["step"][i])
        sp["lat_us"] = int(lat[i])
        if slo_target is not None:
            sp["tail"] = int(lat[i]) > int(slo_target)
        out.append(sp)
    return out


def explain_latency(state, lane: int = 0, *, rank: int = 0,
                    root_kinds=None, replay: bool = False, rt=None,
                    ckpts=None, max_steps: int = 100_000, chunk: int = 512,
                    trace_cap: int | None = None,
                    export_trace: str | None = None) -> dict:
    """Name the hop-by-hop critical path of a lane's slowest request.

    Ranks the lane's recorded completions by e2e latency (`rank=0` the
    slowest, 1 the runner-up, ...; ties break toward the earlier
    dispatch, so re-running on the same state names the same request)
    and returns its `request_span` extended with
      lane / rank / step      which request this is
      slo_target / slo_miss   the lane's dynamic SLO verdict for it
      dropped                 the ring's wrap-overwrite count
      replayed [/ from_step]  whether window replay recovered the chain

    `root_kinds` defaults from `rt.cfg` when a runtime is passed (the
    usual call shape), else to () — external roots only.

    replay=True (the r20 playbook, same shape as
    `explain_crash(replay=True)`): when the live chain is wrap-
    truncated, pass `rt=` and the sweep's harvested `ckpts=`
    (obs.timetravel.CheckpointLog from `run(ckpt_every=...)`) and the
    chain is recovered by WINDOW REPLAY from the newest checkpoint
    preceding it, ring sized to the whole window, equivalence asserted
    on fingerprint + crash verdict (ReplayDivergence on mismatch) —
    `truncated=False` guaranteed whenever a checkpoint precedes the
    chain's root. `export_trace=` writes the Perfetto trace (with the
    request duration spans, obs/trace.py) of whichever state the
    answer came from.

    Raises ValueError when the ring/span columns are compiled out, the
    lane recorded no completions, or `rank` is out of range.
    """
    if root_kinds is None:
        root_kinds = tuple(rt.cfg.root_kinds) if rt is not None else ()

    def pick(recs):
        if "lat" not in recs:
            raise ValueError("no completion latencies in the ring: set "
                             "cfg.complete_kinds (and latency_hist > 0)")
        lat = np.asarray(recs["lat"])
        done = np.nonzero(lat >= 0)[0]
        if len(done) == 0:
            raise ValueError(f"lane {lane} recorded no completions — "
                             "nothing to explain")
        if not 0 <= rank < len(done):
            raise ValueError(f"rank {rank} out of range: the ring holds "
                             f"{len(done)} completions")
        # slowest first; ties toward the earlier dispatch (stable sort
        # over (-lat, step) — deterministic on re-run by construction)
        order = sorted(done, key=lambda i: (-int(lat[i]),
                                            int(recs["step"][i])))
        i = order[rank]
        return int(recs["step"][i]), int(lat[i])

    def lane_scalar(leaf):
        a = np.asarray(leaf)
        return a[lane] if a.ndim else a

    recs = ring_records(state, lane)
    _require_span(recs)
    step, lat = pick(recs)
    span = request_span(recs, step, root_kinds=root_kinds)
    slo = int(lane_scalar(state.slo_target))
    out = dict(span, lane=int(lane), rank=int(rank), step=step,
               lat_us=lat, slo_target=slo,
               slo_miss=bool(slo > 0 and lat > slo),
               dropped=int(recs["dropped"]), replayed=False)

    if replay and span["truncated"]:
        if rt is None:
            raise ValueError("explain_latency(replay=True) needs rt= "
                             "(and usually ckpts= — a CheckpointLog "
                             "harvested with run(ckpt_every=...))")
        from .timetravel import replay_window
        live = dict(fingerprint=int(rt.fingerprints(state)[lane]),
                    crashed=bool(lane_scalar(state.crashed)),
                    crash_code=int(lane_scalar(state.crash_code)),
                    crash_node=int(lane_scalar(state.crash_node)))
        lane_steps = int(np.asarray(state.steps).reshape(-1)[lane])
        live_halted = bool(np.asarray(state.halted).reshape(-1)[lane])
        until = None if live_halted else lane_steps
        cks = (ckpts.iter_checkpoints(lane, before_step=step)
               if ckpts is not None else ())
        any_ckpt = False
        best = None
        for ckpt in cks:
            any_ckpt = True
            win = replay_window(
                rt, ckpt, until_step=until, max_steps=max_steps,
                chunk=chunk, expect=live,
                trace_cap=(trace_cap if trace_cap is not None
                           else max(16, lane_steps - ckpt.steps)))
            rrecs = ring_records(win["state"], 0)
            rspan = request_span(rrecs, step, root_kinds=root_kinds)
            cand = {**out, **rspan, "lat_us": lat, "replayed": True,
                    "from_step": int(ckpt.steps)}
            if not rspan["truncated"]:
                out = cand
                if export_trace is not None:
                    from .trace import export_chrome_trace
                    export_chrome_trace(export_trace, state=win["state"],
                                        lane=0)
                    out["trace_path"] = export_trace
                return out
            if best is None or len(rspan["hops"]) > len(best["hops"]):
                best = cand      # root precedes this checkpoint too
        if not any_ckpt:
            raise ValueError(
                f"no harvested checkpoint covers lane {lane} before "
                f"dispatch {step} — run with ckpt_every=...")
        out = best if best is not None else out

    if export_trace is not None:
        from .trace import export_chrome_trace
        export_chrome_trace(export_trace, state=state, lane=lane)
        out["trace_path"] = export_trace
    return out


def format_span(exp: dict) -> str:
    """Render an `explain_latency` / `request_span` dict as an aligned
    per-hop table: one line per hop (node, wait, transit, segment), the
    dominant hop starred, totals and the SLO verdict in the footer."""
    lines = []
    lat = exp.get("lat_us")
    head = f"request @ step {exp['step']}" if "step" in exp else "request"
    if lat is not None:
        head += f": {lat} us e2e"
    slo = exp.get("slo_target", 0)
    if slo:
        head += (f" (SLO {slo} us — "
                 + ("MISS" if exp.get("slo_miss") else "ok") + ")")
    lines.append(head)
    root = exp.get("root")
    if root is not None:
        lines.append(
            f"  root: {'re-mint' if exp.get('reminted') else 'external'}"
            f" @ step {root['step']} node {root['node']} t={root['now']}")
    elif exp.get("truncated"):
        lines.append("  root: lost to ring wrap (chain is a suffix; "
                     "replay=True recovers it)")
    dom = exp.get("dominant") or {}
    for k, h in enumerate(exp["hops"]):
        star = " *" if dom.get("hop") == k else "  "
        tr = ("?" if h["transit_us"] is None else h["transit_us"])
        sg = ("?" if h["seg_us"] is None else h["seg_us"])
        lines.append(f"{star}hop {k}: node {h['node']} "
                     f"kind={h['kind']} tag={h['tag']} "
                     f"wait={h['wait_us']} transit={tr} seg={sg}")
    tail = (f"  totals: wait={exp['wait_us']} transit={exp['transit_us']}"
            + (" (partial — truncated)" if exp.get("truncated") else ""))
    lines.append(tail)
    if dom:
        lines.append(f"  bottleneck: node {dom['node']} "
                     f"(hop {dom['hop']}, {dom['seg_us']} us)")
    return "\n".join(lines)
