"""Live sweep progress: one overwritten status line on a TTY.

The operator-facing end of the metrics layer: where JsonlObserver feeds
dashboards, ProgressObserver answers "is my 100k-seed sweep actually
moving?" without attaching a profiler. Same hooks, same records — it just
renders instead of persisting. Throttled to `min_interval` seconds so a
fine-grained chunk loop doesn't spend its wall-clock printing.
"""

from __future__ import annotations

import sys
import time


def _rate(x: float) -> str:
    for div, suffix in ((1e9, "G"), (1e6, "M"), (1e3, "k")):
        if x >= div:
            return f"{x / div:.1f}{suffix}"
    return f"{x:.0f}"


def _lat(rec) -> str:
    """Render the round/sweep's tail-latency column ("  p99 1.2ms"
    plus "  slo_miss N" when misses were counted) — empty when the
    record predates the latency plane (r16) or the build compiles it
    out."""
    if rec.get("lat_p99") is None:
        return ""
    p99 = rec["lat_p99"]
    txt = (f"  p99 {p99 / 1000:.1f}ms" if p99 >= 1000
           else f"  p99 {p99}us")
    if rec.get("slo_miss"):
        txt += f"  slo_miss {rec['slo_miss']}"
    return txt


def _targeted(rec) -> str:
    """Render the round's targeted-arm column ("  targeted 3/16 pool 8":
    admitted/launched lineage-synthesized lanes plus the support pool
    depth) — empty when the record predates the ldfi plane (r22) or the
    campaign never aimed."""
    if rec.get("targeted") is None:
        return ""
    txt = f"  targeted {rec.get('targeted_yield', 0)}/{rec['targeted']}"
    if rec.get("support_pool"):
        txt += f" pool {rec['support_pool']}"
    return txt


def _top_yield(op_yield) -> str:
    """Render the most productive mutation operator of a round/shard
    ("  yield time_nudge:3") — empty when nothing was admitted or the
    record predates yield attribution (r15)."""
    if not op_yield:
        return ""
    name, n = max(op_yield.items(), key=lambda kv: kv[1])
    return f"  yield {name}:{n}" if n else ""


class ProgressObserver:
    def __init__(self, stream=None, min_interval: float = 0.5):
        self.stream = stream if stream is not None else sys.stderr
        self.min_interval = min_interval
        self._last = 0.0
        self._line_open = False

    def _show(self, text: str, force: bool = False) -> None:
        now = time.monotonic()
        if not force and now - self._last < self.min_interval:
            return
        self._last = now
        self.stream.write("\r\x1b[2K" + text if self._line_open
                          else text)
        self.stream.flush()
        self._line_open = True

    def on_chunk(self, rec):
        b, h = rec["batch"], rec["lanes_halted"]
        # h is None on non-addressable (multi-process) batches, where
        # the runner can't fetch the per-lane halted vector
        halted = (f"halted {h}/{b} ({100 * h / max(b, 1):.0f}%)"
                  if h is not None else f"batch {b}")
        stashed = (f"  +{rec['stashed_total']} stashed"
                   if rec.get("stashed_total") else "")
        self._show(
            f"chunk {rec['chunk']:>4}  steps {rec['steps_done']:>8}  "
            f"{halted}{stashed}  "
            f"{_rate(rec['lane_steps_per_sec'])} lane-steps/s")

    def on_compact(self, rec):
        self._show(
            f"compact @{rec['steps_done']}: {rec['from_batch']} -> "
            f"{rec['to_batch']} lanes ({rec['stashed']} stashed)",
            force=True)
        self._line_open = False     # keep the repack visible
        self.stream.write("\n")

    def on_round(self, rec):
        if rec.get("kind") == "campaign":
            # the multi-process campaign rollup (service/campaign.py):
            # one line per poll of the shared corpus dir
            self._show(
                f"campaign {rec['uptime_s']:>5.0f}s  "
                f"{rec['workers_alive']}/{rec['workers']} workers  "
                f"corpus {rec['corpus_entries']}  "
                f"coverage {rec['coverage_keys']}  "
                f"buckets {rec['buckets']}  "
                f"{rec['schedules_per_sec']:.1f} sched/s", force=True)
            return
        if rec.get("kind") == "triage":
            # service.triage snapshot at a supervisor segment boundary:
            # the one-line "what changed" readout (full detail:
            # python -m madsim_tpu.service.report <dir> --against prev)
            if rec.get("empty"):
                change = "no change"
            elif "coverage_added" in rec:
                change = (f"+{rec['coverage_added']} coverage  "
                          f"{rec.get('buckets_new', 0)} new / "
                          f"{rec.get('buckets_regressed', 0)} regressed / "
                          f"{rec.get('buckets_stale', 0)} stale buckets")
            else:
                change = "baseline"
            self._show(f"triage snapshot {rec['snapshot']:04d}  {change}",
                       force=True)
            self._line_open = False
            self.stream.write("\n")
            return
        if rec.get("kind") == "supervisor":
            # service.supervise_campaign segment boundary
            dead = rec.get("dead_workers") or []
            self._show(
                f"supervisor seg {rec['segment']}  "
                f"rounds->{rec['max_rounds']}  "
                f"restarts {rec['restarts']}  pruned {rec['pruned']}"
                + (f"  dead {dead}" if dead else ""), force=True)
            self._line_open = False
            self.stream.write("\n")
            return
        # explore() rounds and fuzz() rounds share the schema; fuzz adds
        # corpus_size (and kind="fuzz_round")
        corpus = (f"  corpus {rec['corpus_size']}"
                  if "corpus_size" in rec else "")
        shards = (f"  x{rec['shards']} shards"
                  if rec.get("shards", 1) > 1 else "")
        self._show(
            f"round {rec['round']:>3}  +{rec['new_schedules']} new "
            f"schedules ({rec['distinct_total']} distinct)  "
            f"crashes {rec['crashes']}{corpus}{shards}{_lat(rec)}"
            f"{_top_yield(rec.get('op_yield'))}{_targeted(rec)}",
            force=True)
        if rec.get("shards", 1) > 1 and rec.get("per_shard"):
            # one row per shard — a mesh campaign's telemetry must not
            # collapse the mesh into one line (wall_s is the round's
            # campaign wall: shards run concurrently, so per-shard
            # rates share it)
            wall = max(rec.get("wall_s", 0.0), 1e-9)
            self.stream.write("\n")
            for row in rec["per_shard"]:
                self.stream.write(
                    f"  shard {row['shard']} (w{row['worker_id']})  "
                    f"corpus {row['corpus_size']:>4}  "
                    f"coverage {row['coverage']:>5}  "
                    f"+{row['new']} new  crashes {row['crashes']}  "
                    f"{_rate(row['seeds_run'] / wall)} sched/s"
                    f"{_top_yield(row.get('op_yield'))}\n")
            self.stream.flush()
            self._line_open = False

    def on_done(self, rec):
        parts = [f"done: {rec.get('steps_done', rec.get('seeds_run', 0))} "
                 f"steps" if "steps_done" in rec
                 else f"done: {rec.get('seeds_run', 0)} seeds"]
        if rec.get("lanes_halted") is not None:
            parts.append(f"halted {rec['lanes_halted']}/{rec['batch']}")
        if "distinct_total" in rec:
            parts.append(f"{rec['distinct_total']} distinct schedules")
        if rec.get("lat_p99") is not None:
            parts.append(_lat(rec).strip())
        if "wall_s" in rec:
            parts.append(f"{rec['wall_s']:.2f}s")
        self._show("  ".join(parts), force=True)
        self.stream.write("\n")
        self.stream.flush()
        self._line_open = False
