"""Causal lineage: happens-before edges, crash explanation, divergence depth.

The flight recorder (r7) answers *what* a lane dispatched; the lineage
layer (r10) answers *why*: every recorded event carries `parent` — the
dispatch index of the step that ENQUEUED it (-1 = external: a scenario
row, a node boot, a host-injected op) — and `lamport`, the acting node's
Lamport clock after the dispatch (clock = max(own, carried) + 1, the
classic rule; the carried timestamp rides in the event table's
`ev_prov` provenance matrix). Parent edges form the happens-before DAG of the
trajectory; walking them backward from a crash yields the minimal causal
chain that produced it — the batched analog of reading a madsim replay
log backwards from the panic.

Wrap/overflow contract (DESIGN §12): `parent` is a DISPATCH INDEX, not a
ring slot — it stays meaningful after the ring wraps. Every valid
dispatch of a sampled lane is recorded, so a parent index either still
sits in the ring (the edge resolves) or was overwritten by wrap (the
chain reports `truncated=True` and stops there). A chain can therefore
always be trusted as far as it goes; it just may not reach t=0.

Everything here is host-side numpy over a `ring_records()` read — one
O(trace_cap) transfer after the sweep, nothing during it.
"""

from __future__ import annotations

import hashlib

import numpy as np

from .rings import ring_records

# record fields copied into chain/edge dicts (lineage pair included)
_FIELDS = ("step", "now", "kind", "node", "src", "tag", "parent", "lamport")

# how many chain records (counted back from the crash dispatch) the
# fingerprint covers by default — deep enough to separate bugs that share
# a crash code, shallow enough that modest rings still reach full depth
FINGERPRINT_DEPTH = 8


def _rec_at(recs: dict, i: int) -> dict:
    return {k: int(recs[k][i]) for k in _FIELDS if k in recs}


def walk_lineage(recs: dict, from_step: int | None = None) -> dict:
    """Walk parent edges backward through one lane's ring — the shared
    spine of crash explanation (`explain_crash`) and green-support
    extraction (`obs/support.py`), factored out so the two cannot drift.

    `recs` is a `ring_records()` dict; `from_step` the DISPATCH INDEX to
    start from (default: the lane's last recorded dispatch). Returns
      chain          event records, OLDEST first, ENDING at `from_step`
      truncated      walk hit a parent overwritten by ring wrap — the
                     chain is a faithful SUFFIX of the full one
      root_external  walk reached parent == -1 (scenario row / boot /
                     host injection): the chain is causally complete

    Raises ValueError on a pre-r10 ring (no lineage columns), an empty
    ring, or a `from_step` the ring does not hold.
    """
    if "parent" not in recs:
        raise ValueError("no lineage columns: state predates r10 or was "
                         "built without cfg.trace_cap > 0")
    steps = np.asarray(recs["step"])
    n = len(steps)
    if n == 0:
        raise ValueError("empty ring — nothing to walk "
                         "(did the lane ever dispatch?)")
    by_step = {int(s): i for i, s in enumerate(steps)}
    if from_step is None:
        i = n - 1                          # the lane's last dispatch
    elif int(from_step) in by_step:
        i = by_step[int(from_step)]
    else:
        raise ValueError(f"dispatch step {from_step} is not in the ring "
                         "(overwritten by wrap, or never recorded)")
    chain = []
    truncated = False
    root_external = False
    while True:
        chain.append(_rec_at(recs, i))
        parent = int(recs["parent"][i])
        if parent < 0:
            root_external = True
            break
        if parent not in by_step:          # overwritten by ring wrap
            truncated = True
            break
        i = by_step[parent]
    chain.reverse()
    return dict(chain=chain, truncated=truncated,
                root_external=root_external)


def happens_before(recs: dict) -> list[tuple[int, int]]:
    """The resolvable happens-before edges of one lane's ring, as
    (parent_step, child_step) dispatch-index pairs. `recs` is a
    `ring_records()` dict; edges whose parent was overwritten by ring
    wrap (or is external, parent == -1) are omitted — they exist in the
    execution, just not in the surviving window."""
    if "parent" not in recs:
        raise ValueError("no lineage columns: state predates r10 or was "
                         "built without cfg.trace_cap > 0")
    steps = np.asarray(recs["step"])
    present = set(steps.tolist())
    return [(int(p), int(c)) for p, c in zip(recs["parent"], steps)
            if int(p) >= 0 and int(p) in present]


def explain_crash(state, lane: int = 0, *, replay: bool = False,
                  rt=None, ckpts=None, max_steps: int = 100_000,
                  chunk: int = 512, trace_cap: int | None = None,
                  export_trace: str | None = None) -> dict:
    """Walk parent edges backward from a lane's last recorded dispatch —
    for a crashed lane, the crash dispatch (the invariant/deadlock check
    runs inside the same step it implicates) — to the minimal causal
    chain the ring still holds.

    Returns a dict:
      chain       list of event records, OLDEST first, ENDING at the
                  crash dispatch; each carries step/now/kind/node/src/
                  tag/parent/lamport
      truncated   True when the walk hit a parent overwritten by ring
                  wrap (the chain is a faithful SUFFIX of the full one)
      root_external  True when the chain reached a parent of -1 — an
                  external cause (scenario row / node boot / injection)
      crashed / crash_code / crash_node   the lane's crash verdict
      lane, dropped   lane index and ring-wrap overwrite count

    replay=True (r20, DESIGN §21) refuses to settle for the truncated
    suffix: pass the runtime (`rt=`) and the sweep's harvested
    `ckpts=` (an obs.timetravel.CheckpointLog from
    `run(ckpt_every=...)`) and the chain is recovered by WINDOW REPLAY
    from the nearest checkpoint with the ring upgraded to hold the
    whole window — `truncated=False` guaranteed whenever a checkpoint
    precedes the chain's root, equivalence asserted on fingerprint +
    crash verdict, and `export_trace=` writes a focused Perfetto trace
    of just the window. The replayed-complete chain stays
    bucket-compatible with the live truncated observation
    (deepest-common-suffix, `fingerprints_match`).

    Raises (via ring_records) if the ring is compiled out or the lane
    was not sampled; raises ValueError on an empty ring or a pre-r10
    state without lineage columns.
    """
    if replay:
        if rt is None:
            raise ValueError("explain_crash(replay=True) needs rt= (and "
                             "usually ckpts= — a CheckpointLog harvested "
                             "with run(ckpt_every=...))")
        from .timetravel import time_travel_explain
        return time_travel_explain(rt, state, lane, ckpts=ckpts,
                                   max_steps=max_steps, chunk=chunk,
                                   trace_cap=trace_cap,
                                   export_trace=export_trace)
    recs = ring_records(state, lane)
    try:
        walk = walk_lineage(recs)
    except ValueError as e:
        if "empty ring" in str(e):
            raise ValueError(f"lane {lane} recorded no events — nothing "
                             "to explain (did the lane ever dispatch?)")
        raise

    def _lane_scalar(leaf):
        a = np.asarray(leaf)
        return a[lane] if a.ndim else a

    return dict(
        chain=walk["chain"],
        truncated=walk["truncated"],
        root_external=walk["root_external"],
        crashed=bool(_lane_scalar(state.crashed)),
        crash_code=int(_lane_scalar(state.crash_code)),
        crash_node=int(_lane_scalar(state.crash_node)),
        lane=int(lane),
        dropped=int(recs["dropped"]),
    )


def _chain_tokens(chain: list[dict]) -> list[tuple]:
    """The lane- and wrap-invariant content of a chain record: what the
    event WAS (kind/node/src/tag), never WHEN it ran (step, now, lamport
    are all shifted by seed and wrap point — hashing them would split one
    bug into a bucket per lane)."""
    return [(int(c["kind"]), int(c["node"]), int(c["src"]), int(c["tag"]))
            for c in chain]


def _digest(crash_sig: tuple, toks: list[tuple], marker: str = "") -> str:
    blob = repr((crash_sig, toks, marker)).encode()
    return hashlib.sha256(blob).hexdigest()[:16]


def causal_fingerprint(exp: dict, depth: int = FINGERPRINT_DEPTH) -> dict:
    """Hash an `explain_crash` chain into a crash-dedup fingerprint:
    one bug = one bucket, across lanes, seeds, and processes.

    The chain is consumed SUFFIX-first (the records nearest the crash),
    because that end is wrap-stable: ring wrap truncates chains at the
    ROOT end, so two observations of one bug truncated at different wrap
    points share their deepest suffix (obs/causal.py wrap contract — a
    chain is always a faithful suffix). The fingerprint therefore covers
    the last `depth` records plus the crash verdict (code, node), and
    carries the ladder of progressive suffix digests so a SHORTER
    truncated chain of the same bug can still be matched to the bucket
    (`fingerprints_match`) instead of opening a second one.

    The `truncated` flag is folded in honestly, as COMPLETENESS: a chain
    that reached its external root within `depth` records hashes a root
    marker (its causal history is the whole story), while a chain cut by
    wrap truncation — or by the depth cap itself — does not. Two complete
    chains of different length are different bugs even when their
    suffixes agree; a cut chain can never be distinguished from a deeper
    one on suffix evidence alone, so it matches by deepest common suffix.

    Returns {key, suffix_hashes, depth, complete, crash_code, crash_node,
    kind="causal"}: `key` is the canonical bucket id for THIS observation
    (the deepest digest, root marker folded in when complete), and
    `suffix_hashes[k-1]` the digest of the last k records — the match
    ladder. Raises ValueError on an empty chain.
    """
    chain = exp["chain"]
    if not chain:
        raise ValueError("cannot fingerprint an empty causal chain")
    crash_sig = (int(exp["crash_code"]), int(exp["crash_node"]))
    toks = _chain_tokens(chain)[-depth:]
    complete = (bool(exp["root_external"]) and not bool(exp["truncated"])
                and len(chain) <= depth)
    suffix_hashes = [_digest(crash_sig, toks[len(toks) - k:])
                     for k in range(1, len(toks) + 1)]
    key = _digest(crash_sig, toks, marker="root" if complete else "cut")
    return dict(key=key, suffix_hashes=suffix_hashes, depth=len(toks),
                complete=complete, crash_code=crash_sig[0],
                crash_node=crash_sig[1], kind="causal")


def code_fingerprint(crash_code: int, crash_node: int) -> dict:
    """The degraded fingerprint for lineage-less builds (cfg.trace_cap ==
    0): dedup by crash verdict alone. Same schema as `causal_fingerprint`
    so bucket stores handle both; `kind="code"` marks the lower
    resolution (distinct bugs sharing a code WILL share a bucket)."""
    key = f"code-{int(crash_code):08x}-n{int(crash_node)}"
    return dict(key=key, suffix_hashes=[], depth=0, complete=False,
                crash_code=int(crash_code), crash_node=int(crash_node),
                kind="code")


def race_fingerprint(cand: dict, diff: dict | None = None) -> dict:
    """Fingerprint a CONFIRMED schedule race (analyze/races.py) for
    bucket dedup: the same token pair at the same node is the same
    finding across lanes, seeds, nudges, and workers. The pair is
    order-normalized (a race is symmetric in its two events — the
    observed order is an artifact of which schedule was seen first)
    and hashes only the events' wrap-stable identity tokens, never
    step/now/lamport (`_chain_tokens` rationale).

    Same schema as `causal_fingerprint` so `service/buckets.py` stores
    and `merged_buckets` folds it unchanged; `kind="race"` matches by
    key equality only (`fingerprints_match` treats non-causal kinds
    that way). `crash_code`/`crash_node` carry the COMMUTED outcome's
    verdict when `diff` is given (what the race flips the run into) —
    0/-1 for races confirmed by fingerprint divergence alone."""
    ta = tuple(int(cand["a"][k]) for k in ("kind", "node", "src", "tag"))
    tb = tuple(int(cand["b"][k]) for k in ("kind", "node", "src", "tag"))
    toks = sorted((ta, tb))
    commuted = (diff or {}).get("commuted", {})
    code = int(commuted.get("crash_code", 0))
    node = int(commuted.get("crash_node", -1))
    key = "race-" + _digest((int(cand["node"]),), toks, marker="race")
    return dict(key=key, suffix_hashes=[], depth=2, complete=True,
                crash_code=code, crash_node=node, kind="race")


def fingerprints_match(a: dict, b: dict) -> bool:
    """Whether two fingerprints denote the same bug — the deepest-common-
    suffix rule. Equal keys always match. Otherwise two causal
    fingerprints match when their suffix digests agree at the deepest
    depth BOTH observed, unless both chains are complete (both reached
    their external root: different depths then mean genuinely different
    causal histories, not different wrap points)."""
    if a["key"] == b["key"]:
        return True
    if a.get("kind") != "causal" or b.get("kind") != "causal":
        return False
    if a["complete"] and b["complete"]:
        return False
    # a cut chain as long as (or longer than) a complete one cannot be
    # the same bug: the complete chain is the bug's WHOLE history, and a
    # cut chain always hides at least one more record than it shows
    # (truncation fires only when a parent existed but was overwritten,
    # and the depth cap only when deeper records existed) — so a same-bug
    # cut observation is strictly shorter than the complete chain
    if a["complete"] and b["depth"] >= a["depth"]:
        return False
    if b["complete"] and a["depth"] >= b["depth"]:
        return False
    m = min(a["depth"], b["depth"])
    if m == 0:
        return False
    return a["suffix_hashes"][m - 1] == b["suffix_hashes"][m - 1]


def sketch_divergence(state, lane_a: int, lane_b: int) -> dict:
    """Where two lanes' schedules first diverged, from their on-device
    prefix-coverage sketches (cfg.sketch_slots > 0). Returns
    {slot, step_bound, every, slots, bound}: `slot` is the first sketch
    index where the lanes differ, `step_bound` the corresponding upper
    bound on the first divergent dispatch index — the lanes' first
    `slot * every` dispatches hashed identically.

    `bound` names WHICH kind of answer this is, instead of callers
    inferring it from `slot == slots` (the r20 small fix):
      "sketch-slot"  a recorded slot genuinely differs — `step_bound`
                     is a real divergence bound;
      "exhausted"    NO recorded checkpoint differs (identical
                     schedules within the sketch window, or divergence
                     past slot `slots`, or the lanes halted before
                     filling the differing slot) — `slot == slots` and
                     `step_bound` is only the end of the recorded
                     window, NOT evidence of divergence.
    Consumers that need a true step: the divergence microscope
    (obs/timetravel.divergence_report) refines "sketch-slot" to an
    exact checkpoint-step by window replay and falls back to the whole
    run on "exhausted"."""
    sk = np.asarray(state.cov_sketch)
    if sk.ndim != 2 or sk.shape[1] == 0:
        raise ValueError("prefix sketch is compiled out "
                         "(cfg.sketch_slots == 0) or state is unbatched")
    every = int(np.atleast_1d(np.asarray(state.sketch_every)).reshape(-1)[0])
    a, b = sk[lane_a], sk[lane_b]
    differs = a != b
    slots = sk.shape[1]
    found = bool(differs.any())
    slot = int(differs.argmax()) if found else slots
    return dict(slot=slot, step_bound=(slot + 1) * every, every=every,
                slots=slots,
                bound="sketch-slot" if found else "exhausted")
