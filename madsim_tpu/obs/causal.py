"""Causal lineage: happens-before edges, crash explanation, divergence depth.

The flight recorder (r7) answers *what* a lane dispatched; the lineage
layer (r10) answers *why*: every recorded event carries `parent` — the
dispatch index of the step that ENQUEUED it (-1 = external: a scenario
row, a node boot, a host-injected op) — and `lamport`, the acting node's
Lamport clock after the dispatch (clock = max(own, carried) + 1, the
classic rule; the carried timestamp rides in the event table's
`ev_prov` provenance matrix). Parent edges form the happens-before DAG of the
trajectory; walking them backward from a crash yields the minimal causal
chain that produced it — the batched analog of reading a madsim replay
log backwards from the panic.

Wrap/overflow contract (DESIGN §12): `parent` is a DISPATCH INDEX, not a
ring slot — it stays meaningful after the ring wraps. Every valid
dispatch of a sampled lane is recorded, so a parent index either still
sits in the ring (the edge resolves) or was overwritten by wrap (the
chain reports `truncated=True` and stops there). A chain can therefore
always be trusted as far as it goes; it just may not reach t=0.

Everything here is host-side numpy over a `ring_records()` read — one
O(trace_cap) transfer after the sweep, nothing during it.
"""

from __future__ import annotations

import numpy as np

from .rings import ring_records

# record fields copied into chain/edge dicts (lineage pair included)
_FIELDS = ("step", "now", "kind", "node", "src", "tag", "parent", "lamport")


def _rec_at(recs: dict, i: int) -> dict:
    return {k: int(recs[k][i]) for k in _FIELDS if k in recs}


def happens_before(recs: dict) -> list[tuple[int, int]]:
    """The resolvable happens-before edges of one lane's ring, as
    (parent_step, child_step) dispatch-index pairs. `recs` is a
    `ring_records()` dict; edges whose parent was overwritten by ring
    wrap (or is external, parent == -1) are omitted — they exist in the
    execution, just not in the surviving window."""
    if "parent" not in recs:
        raise ValueError("no lineage columns: state predates r10 or was "
                         "built without cfg.trace_cap > 0")
    steps = np.asarray(recs["step"])
    present = set(steps.tolist())
    return [(int(p), int(c)) for p, c in zip(recs["parent"], steps)
            if int(p) >= 0 and int(p) in present]


def explain_crash(state, lane: int = 0) -> dict:
    """Walk parent edges backward from a lane's last recorded dispatch —
    for a crashed lane, the crash dispatch (the invariant/deadlock check
    runs inside the same step it implicates) — to the minimal causal
    chain the ring still holds.

    Returns a dict:
      chain       list of event records, OLDEST first, ENDING at the
                  crash dispatch; each carries step/now/kind/node/src/
                  tag/parent/lamport
      truncated   True when the walk hit a parent overwritten by ring
                  wrap (the chain is a faithful SUFFIX of the full one)
      root_external  True when the chain reached a parent of -1 — an
                  external cause (scenario row / node boot / injection)
      crashed / crash_code / crash_node   the lane's crash verdict
      lane, dropped   lane index and ring-wrap overwrite count

    Raises (via ring_records) if the ring is compiled out or the lane
    was not sampled; raises ValueError on an empty ring or a pre-r10
    state without lineage columns.
    """
    recs = ring_records(state, lane)
    if "parent" not in recs:
        raise ValueError("no lineage columns: state predates r10 or was "
                         "built without cfg.trace_cap > 0")
    n = len(np.asarray(recs["step"]))
    if n == 0:
        raise ValueError(f"lane {lane} recorded no events — nothing to "
                         "explain (did the lane ever dispatch?)")
    by_step = {int(s): i for i, s in enumerate(recs["step"])}
    chain = []
    i = n - 1                              # the lane's last dispatch
    truncated = False
    root_external = False
    while True:
        chain.append(_rec_at(recs, i))
        parent = int(recs["parent"][i])
        if parent < 0:
            root_external = True
            break
        if parent not in by_step:          # overwritten by ring wrap
            truncated = True
            break
        i = by_step[parent]
    chain.reverse()

    def _lane_scalar(leaf):
        a = np.asarray(leaf)
        return a[lane] if a.ndim else a

    return dict(
        chain=chain,
        truncated=truncated,
        root_external=root_external,
        crashed=bool(_lane_scalar(state.crashed)),
        crash_code=int(_lane_scalar(state.crash_code)),
        crash_node=int(_lane_scalar(state.crash_node)),
        lane=int(lane),
        dropped=int(recs["dropped"]),
    )


def sketch_divergence(state, lane_a: int, lane_b: int) -> dict:
    """Where two lanes' schedules first diverged, from their on-device
    prefix-coverage sketches (cfg.sketch_slots > 0). Returns
    {slot, step_bound, every, slots}: `slot` is the first sketch index
    where the lanes differ (== slots when no recorded checkpoint
    differs), and `step_bound` the corresponding upper bound on the
    first divergent dispatch index — the lanes' first `slot * every`
    dispatches hashed identically."""
    sk = np.asarray(state.cov_sketch)
    if sk.ndim != 2 or sk.shape[1] == 0:
        raise ValueError("prefix sketch is compiled out "
                         "(cfg.sketch_slots == 0) or state is unbatched")
    every = int(np.atleast_1d(np.asarray(state.sketch_every)).reshape(-1)[0])
    a, b = sk[lane_a], sk[lane_b]
    differs = a != b
    slots = sk.shape[1]
    slot = int(differs.argmax()) if differs.any() else slots
    return dict(slot=slot, step_bound=(slot + 1) * every, every=every,
                slots=slots)
