"""Static triage dashboard: one self-contained HTML file, zero deps.

The operator surface of the campaign triage plane (service/triage.py):
`render_html(cur, diff)` turns a snapshot (+ its diff against the
previous one) into a single document with inline-SVG sparklines for the
coverage / schedules-per-sec / p99 curves, per-recipe and per-operator
attribution bars, the bucket lifecycle table with repro one-liners, and
the repro-health audit verdicts. No server, no JavaScript, no external
assets — the file is the artifact, so it attaches to a CI run or an
email and still renders in ten years.

Rendering rules (kept deliberately boring): every chart is a single
series in one hue, so identity lives in titles and row labels, never in
a legend the reader must color-match; values and labels wear text ink,
never the series color; lifecycle/audit verdicts use the reserved
status palette WITH their word — color never carries meaning alone;
hover detail rides native SVG ``<title>`` tooltips. Light and dark are
both real: the dark values are selected steps, not an automatic invert.
"""

from __future__ import annotations

import html as _html
import json

# palette: validated reference instance (light / dark pairs). Marks use
# the single categorical slot-1 blue; status colors are reserved for
# verdicts and always ship beside their word.
_CSS = """
.triage-root {
  color-scheme: light;
  --surface-1: #fcfcfb; --page: #f9f9f7;
  --ink-1: #0b0b0b; --ink-2: #52514e; --ink-3: #898781;
  --grid: #e1e0d9; --axis: #c3c2b7;
  --border: rgba(11,11,11,0.10);
  --series-1: #2a78d6;
  --good: #0ca30c; --warn: #fab219; --serious: #ec835a;
  --critical: #d03b3b;
  font-family: system-ui, -apple-system, "Segoe UI", sans-serif;
  background: var(--page); color: var(--ink-1);
  margin: 0; padding: 24px;
}
@media (prefers-color-scheme: dark) {
  :root:where(:not([data-theme="light"])) .triage-root {
    color-scheme: dark;
    --surface-1: #1a1a19; --page: #0d0d0d;
    --ink-1: #ffffff; --ink-2: #c3c2b7; --ink-3: #898781;
    --grid: #2c2c2a; --axis: #383835;
    --border: rgba(255,255,255,0.10);
    --series-1: #3987e5;
  }
}
:root[data-theme="dark"] .triage-root {
  color-scheme: dark;
  --surface-1: #1a1a19; --page: #0d0d0d;
  --ink-1: #ffffff; --ink-2: #c3c2b7; --ink-3: #898781;
  --grid: #2c2c2a; --axis: #383835;
  --border: rgba(255,255,255,0.10);
  --series-1: #3987e5;
}
.triage-root h1 { font-size: 20px; margin: 0 0 2px; }
.triage-root h2 { font-size: 14px; margin: 24px 0 8px; color: var(--ink-2);
                  font-weight: 600; }
.triage-root .sub { color: var(--ink-3); font-size: 12px; margin: 0 0 16px; }
.tiles { display: flex; flex-wrap: wrap; gap: 12px; }
.tile { background: var(--surface-1); border: 1px solid var(--border);
        border-radius: 8px; padding: 12px 16px; min-width: 150px; }
.tile .label { font-size: 12px; color: var(--ink-2); }
.tile .value { font-size: 26px; font-weight: 600; margin-top: 2px; }
.tile .delta { font-size: 12px; margin-top: 2px; color: var(--ink-2); }
.tile .delta.bad { color: var(--critical); }    /* more bugs = attention */
.tile .delta.good { color: #006300; }           /* coverage up = progress */
.tile .delta.flat { color: var(--ink-3); }
@media (prefers-color-scheme: dark) {
  :root:where(:not([data-theme="light"])) .triage-root
    .tile .delta.good { color: #0ca30c; }
}
:root[data-theme="dark"] .triage-root .tile .delta.good { color: #0ca30c; }
.tile svg { display: block; margin-top: 6px; }
.bars { background: var(--surface-1); border: 1px solid var(--border);
        border-radius: 8px; padding: 12px 16px; display: inline-block;
        vertical-align: top; margin-right: 12px; min-width: 300px; }
.bars .row { display: flex; align-items: center; gap: 8px;
             margin: 4px 0; font-size: 12px; }
.bars .name { width: 120px; color: var(--ink-2); text-align: right; }
.bars .track { flex: 1; height: 16px; }
.bars .val { width: 48px; color: var(--ink-1);
             font-variant-numeric: tabular-nums; }
table.buckets { border-collapse: collapse; width: 100%;
                background: var(--surface-1); border: 1px solid
                var(--border); border-radius: 8px; font-size: 12.5px; }
table.buckets th { text-align: left; color: var(--ink-2); font-weight:
                   600; padding: 8px 10px; border-bottom: 1px solid
                   var(--grid); }
table.buckets td { padding: 7px 10px; border-bottom: 1px solid
                   var(--grid); vertical-align: top;
                   font-variant-numeric: tabular-nums; }
table.buckets tr:last-child td { border-bottom: none; }
.badge { display: inline-block; border-radius: 999px; padding: 1px 8px;
         font-size: 11px; font-weight: 600; color: #fff; }
.badge.new { background: var(--serious); }
.badge.regressed { background: var(--critical); }
.badge.grew { background: var(--series-1); }
.badge.stale { background: var(--ink-3); }
.badge.known { background: var(--axis); color: var(--ink-1); }
.badge.pass { background: var(--good); }
.badge.fail { background: var(--critical); }
.badge.flaky { background: var(--warn); color: #0b0b0b; }
.badge.unaudited { background: var(--axis); color: var(--ink-1); }
.mono { font-family: ui-monospace, Menlo, Consolas, monospace;
        font-size: 11.5px; color: var(--ink-2); }
"""

_SYM = {"new": "●", "regressed": "▲", "grew": "↗", "stale": "○",
        "known": "·", "pass": "✓", "fail": "✗", "flaky": "≈",
        "unaudited": "—"}


def _esc(x) -> str:
    return _html.escape(str(x), quote=True)


def _fmt(v) -> str:
    v = float(v)
    if v >= 1e6:
        return f"{v / 1e6:.1f}M"
    if v >= 1e4:
        return f"{v / 1e3:.1f}K"
    if v == int(v):
        return f"{int(v):,}"
    return f"{v:,.2f}"


def sparkline_svg(curve, w: int = 220, h: int = 44,
                  unit: str = "") -> str:
    """One single-series sparkline: 2px line in the series hue, ~10%
    area wash to the baseline, an end dot (r=4) with a 2px surface
    ring, and a native ``<title>`` tooltip per sampled point (the
    no-JS hover layer). `curve` is the timeline's [[t_rel_s, value],
    ...]; empty/None renders an em-dash placeholder."""
    if not curve:
        return '<span class="sub">&mdash;</span>'
    ts = [float(t) for t, _v in curve]
    vs = [float(v) for _t, v in curve]
    t0, t1 = min(ts), max(ts)
    v0, v1 = min(vs), max(vs)
    pad = 5.0
    sx = ((w - 2 * pad) / (t1 - t0)) if t1 > t0 else 0.0
    sy = ((h - 2 * pad) / (v1 - v0)) if v1 > v0 else 0.0

    def xy(t, v):
        return (pad + (t - t0) * sx,
                h - pad - (v - v0) * sy)

    pts = [xy(t, v) for t, v in zip(ts, vs)]
    line = " ".join(f"{x:.1f},{y:.1f}" for x, y in pts)
    area = (f"{pts[0][0]:.1f},{h - 1:.1f} " + line
            + f" {pts[-1][0]:.1f},{h - 1:.1f}")
    ex, ey = pts[-1]
    # sampled hover targets (every point; invisible 8px circles so the
    # native tooltip has a real hit area)
    hits = "".join(
        f'<circle cx="{x:.1f}" cy="{y:.1f}" r="8" fill="transparent">'
        f"<title>t+{ts[i]:.0f}s: {_fmt(vs[i])}{_esc(unit)}</title>"
        f"</circle>"
        for i, (x, y) in enumerate(pts))
    return (
        f'<svg width="{w}" height="{h}" viewBox="0 0 {w} {h}" '
        f'role="img" aria-label="sparkline, last {_fmt(vs[-1])}'
        f'{_esc(unit)}">'
        f'<line x1="{pad}" y1="{h - 1}" x2="{w - pad}" y2="{h - 1}" '
        f'stroke="var(--axis)" stroke-width="1"/>'
        f'<polygon points="{area}" fill="var(--series-1)" '
        f'fill-opacity="0.1"/>'
        f'<polyline points="{line}" fill="none" stroke="var(--series-1)" '
        f'stroke-width="2" stroke-linejoin="round" '
        f'stroke-linecap="round"/>'
        f'<circle cx="{ex:.1f}" cy="{ey:.1f}" r="6" '
        f'fill="var(--surface-1)"/>'
        f'<circle cx="{ex:.1f}" cy="{ey:.1f}" r="4" '
        f'fill="var(--series-1)"/>'
        f"{hits}</svg>")


def _tile(label: str, value, delta: str | None = None,
          curve=None, unit: str = "", delta_tone: str = "bad") -> str:
    spark = sparkline_svg(curve, unit=unit) if curve else ""
    d = ""
    if delta:
        # tone is per-METRIC (delta_tone: coverage growth is progress,
        # bucket growth is attention) and only applies when some count
        # is nonzero — "+0 new, 0 regressed vs prev" reads flat
        cls = (delta_tone if any(c.isdigit() and c != "0" for c in delta)
               else "flat")
        d = f'<div class="delta {cls}">{_esc(delta)}</div>'
    return (f'<div class="tile"><div class="label">{_esc(label)}</div>'
            f'<div class="value">{_esc(value)}</div>{d}{spark}</div>')


def series_sparklines_html(summary: dict | None) -> str:
    """Sim-time sparkline tiles off a `series_summary` dict (the r21
    windowed telemetry plane, obs/series.py): dispatches, queue
    high-water, and per-window e2e p99 on the VIRTUAL-time axis —
    every other curve on this dashboard is wall-clock campaign
    history; these are one run's own timeline, so a partition window
    reads as a spike at its sim-time offset. The fault markers ride
    as a decoded footnote (which windows saw disruptions/heals).
    Empty string when there is nothing to render (plane compiled out
    or no summary attached — the section simply doesn't appear)."""
    if not summary or not summary.get("rows"):
        return ""
    rows = summary["rows"]
    ts = [r["t0_us"] / 1e6 for r in rows]       # virtual seconds

    def curve(key):
        return [[ts[i], rows[i].get(key, 0)] for i in range(len(rows))]

    tiles = [
        _tile("Dispatches / window",
              _fmt(max(r["dispatches"] for r in rows)),
              curve=curve("dispatches")),
        _tile("Queue high-water",
              _fmt(max(r["qhw"] for r in rows)),
              curve=curve("qhw")),
    ]
    if any("e2e_p99" in r for r in rows):
        tiles.append(_tile(
            "e2e p99 / window",
            f"{_fmt(max(r.get('e2e_p99', 0) for r in rows))}us",
            curve=curve("e2e_p99"), unit="us"))
        tiles.append(_tile("SLO misses / window",
                           _fmt(sum(r["slo_miss"] for r in rows)),
                           curve=curve("slo_miss")))
    marks = [f"w{r['window']} {'+'.join(r['faults'])}"
             for r in rows if r["faults"]]
    note = ("fault windows: " + " &middot; ".join(_esc(m) for m in marks)
            if marks else "no fault markers")
    return (
        f"<h2>Sim-time telemetry &mdash; {_esc(summary['windows'])} "
        f"windows &times; {_esc(summary['window_len'])}us of virtual "
        f"time ({_esc(summary['lanes'])} recording lanes)</h2>"
        f'<div class="tiles">{"".join(tiles)}</div>'
        f'<p class="sub">{note}</p>')


def attribution_bars_html(title: str, counts: dict,
                          order=None) -> str:
    """One attribution panel: a horizontal bar per class, single hue
    (identity is the row label — magnitude is the only encoding), 16px
    bars with a 4px rounded data end and the value at the tip in text
    ink. Zero-count classes are listed muted so the accounting contract
    stays visible (everything sums to the total, nothing hides)."""
    keys = [k for k in (order or sorted(counts)) if k in counts]
    keys += [k for k in sorted(counts) if k not in keys]
    total = sum(counts.values()) or 1
    peak = max(counts.values(), default=0) or 1
    rows = []
    for k in keys:
        v = int(counts[k])
        # floor 5px: the path below spends 4px on the rounded data-end,
        # so anything smaller would emit a malformed negative h segment
        bw = max(5, round(180 * v / peak)) if v else 0
        bar = ("" if not v else
               f'<svg width="188" height="16" viewBox="0 0 188 16">'
               f'<path d="M0,0 h{bw - 4} a4,4 0 0 1 4,4 v8 a4,4 0 0 1 '
               f'-4,4 h-{bw - 4} z" fill="var(--series-1)">'
               f"<title>{_esc(k)}: {v} ({100 * v / total:.0f}%)</title>"
               f"</path></svg>")
        rows.append(
            f'<div class="row"><div class="name">{_esc(k)}</div>'
            f'<div class="track">{bar}</div>'
            f'<div class="val">{v or "·"}</div></div>')
    return (f'<div class="bars"><h2>{_esc(title)}</h2>'
            + "".join(rows)
            + f'<div class="row"><div class="name">total</div>'
              f'<div class="track"></div>'
              f'<div class="val">{sum(counts.values())}</div></div></div>')


def _lifecycle_of(key: str, diff: dict | None) -> str:
    from ..service.triage import bucket_lifecycle
    return bucket_lifecycle(key, diff)


def _badge(cls: str) -> str:
    # word + symbol + color: meaning never rides color alone
    return (f'<span class="badge {cls}">{_SYM.get(cls, "")}&nbsp;'
            f"{_esc(cls)}</span>")


def _repro_line(b: dict) -> str:
    r = b.get("repro", {})
    parts = [f"seed={r.get('seed')}", f"round={r.get('round')}",
             f"worker={r.get('worker_id')}"]
    if "nudge" in r:
        parts.append(f"nudge={r['nudge']}")
    if b.get("minimized"):
        parts.append("minimized")
    return " ".join(parts)


def bucket_table_html(cur: dict, diff: dict | None) -> str:
    rows = []
    order = sorted(
        cur.get("buckets", {}).items(),
        key=lambda kv: ({"new": 0, "regressed": 1, "grew": 2,
                         "known": 3, "stale": 4}
                        .get(_lifecycle_of(kv[0], diff), 3),
                        -kv[1]["observations"], kv[0]))
    from ..service.triage import bucket_audit
    for key, b in order:
        cls = _lifecycle_of(key, diff)
        a = bucket_audit(cur, key, b.get("members", ()))
        astat = (a or {}).get("status", "unaudited")
        # r20 chain column: complete chain vs truncated-at-wrap, with
        # the replayed-window trace linked when replay_bucket/audit
        # wrote one (a file path — the dashboard is serverless, so the
        # link is the store-relative name, always worded)
        if "chain_complete" not in b:
            chain = '<span class="sub">unknown</span>'
        else:
            chain = ("complete" if b["chain_complete"]
                     else "truncated at wrap")
            if b.get("window_trace"):
                chain += (' &middot; <span class="mono">buckets/'
                          f"{_esc(b['window_trace'][:16])}&hellip;"
                          ".window.trace.json</span>")
        rows.append(
            "<tr>"
            f'<td class="mono">{_esc(key[:16])}</td>'
            f"<td>{_badge(cls)}</td>"
            f"<td>{b['crash_code']}</td>"
            f"<td>{_esc(b['recipe'])}</td>"
            f"<td>{_esc(b['op'])}</td>"
            f"<td>{b['observations']}</td>"
            f"<td>{b['first_round']}&ndash;{b['last_round']}</td>"
            f"<td>{_badge(astat)}</td>"
            f"<td>{chain}</td>"
            f'<td class="mono">{_esc(_repro_line(b))}</td>'
            "</tr>")
    if not rows:
        rows = ['<tr><td colspan="10" class="sub">no buckets — the '
                "campaign found no crashes (yet)</td></tr>"]
    head = "".join(f"<th>{h}</th>" for h in (
        "bucket", "lifecycle", "code", "recipe", "operator", "obs",
        "rounds", "repro health", "chain", "repro handle"))
    return (f'<table class="buckets"><thead><tr>{head}</tr></thead>'
            f'<tbody>{"".join(rows)}</tbody></table>')


def workers_table_html(cur: dict) -> str:
    rows = []
    for label, h in sorted(cur.get("workers_health", {}).items()):
        stale = _badge("stale") if h.get("stale") else _badge("pass")
        rows.append(
            f'<tr><td class="mono">{_esc(label)}</td>'
            f"<td>{h.get('rounds_done', 0)}</td>"
            f"<td>{h.get('sync_gap_s', 0)}s</td>"
            f"<td>{h.get('age_s', 0)}s</td>"
            f"<td>{stale}</td></tr>")
    if not rows:
        return '<p class="sub">no worker timeline rows yet</p>'
    head = "".join(f"<th>{h}</th>" for h in (
        "worker", "rounds", "sync cadence", "age vs newest", "health"))
    return (f'<table class="buckets"><thead><tr>{head}</tr></thead>'
            f'<tbody>{"".join(rows)}</tbody></table>')


def render_html(cur: dict, diff: dict | None = None,
                title: str = "madsim campaign triage") -> str:
    """The whole dashboard as one HTML string (write it wherever —
    `service.report --html out.html` does)."""
    st = cur.get("store", {})
    curves = cur.get("curves", {})
    slo = cur.get("slo") or {}
    d_new = len((diff or {}).get("buckets", {}).get("new", ()))
    d_reg = len((diff or {}).get("buckets", {}).get("regressed", ()))
    d_cov = (diff or {}).get("coverage", {}).get("added", 0)
    tiles = [
        _tile("Coverage keys", _fmt(st.get("coverage_total", 0)),
              delta=(f"+{d_cov} vs prev" if diff else None),
              delta_tone="good",
              curve=curves.get("coverage")),
        _tile("Crash buckets", _fmt(st.get("buckets_total", 0)),
              delta=(f"+{d_new} new, {d_reg} regressed vs prev"
                     if diff else None)),
        _tile("Observations", _fmt(st.get("crash_observations", 0))),
        _tile("Schedules/s", (_fmt(cur["rate"]["last"])
                              if cur.get("rate") else "—"),
              curve=curves.get("rate")),
        _tile("e2e p99", (f"{_fmt(cur['p99']['last'])}us"
                          if cur.get("p99") else "—"),
              # the SLO verdict beside the quantile (r23): what target
              # the campaign ran against and how many requests blew it
              delta=(f"SLO {_fmt(slo['target'])}us — "
                     f"{_fmt(slo.get('miss', 0))} miss"
                     if slo.get("target") else None),
              curve=curves.get("p99"), unit="us"),
        _tile("Rounds", _fmt(st.get("max_round", 0))),
    ]
    attr = cur.get("attribution", {})
    from ..runtime.scenario import RECIPE_FAMILIES
    fam_order = list(RECIPE_FAMILIES) + ["base"]
    empty_note = ""
    if diff is not None and diff.get("empty"):
        empty_note = ('<p class="sub">diff vs previous snapshot: '
                      "EMPTY — nothing changed</p>")
    return f"""<!DOCTYPE html>
<html lang="en"><head><meta charset="utf-8">
<meta name="viewport" content="width=device-width, initial-scale=1">
<title>{_esc(title)}</title>
<style>{_CSS}</style></head>
<body class="triage-root">
<h1>{_esc(title)}</h1>
<p class="sub">snapshot of {_esc(st.get("entries", 0))} corpus entries
&middot; {_esc(st.get("max_round", 0))} rounds &middot;
{_esc(len(cur.get("workers_health", {})))} workers
&middot; generated from the durable store alone</p>
{empty_note}
<div class="tiles">{"".join(tiles)}</div>
<h2>Attribution — every key and bucket accounted, `base` = unattributable</h2>
<div>
{attribution_bars_html("Coverage by recipe",
                       attr.get("recipe_coverage", {}), fam_order)}
{attribution_bars_html("Buckets by recipe",
                       attr.get("recipe_buckets", {}), fam_order)}
{attribution_bars_html("Coverage by operator",
                       attr.get("operator_coverage", {}))}
{attribution_bars_html("Buckets by operator",
                       attr.get("operator_buckets", {}))}
{attribution_bars_html("Coverage by origin",
                       attr.get("origin_coverage", {}),
                       ["targeted", "havoc"])}
{attribution_bars_html("Buckets by origin",
                       attr.get("origin_buckets", {}),
                       ["targeted", "havoc"])}
</div>
{series_sparklines_html(cur.get("series"))}
<h2>Buckets — lifecycle, attribution, repro health</h2>
{bucket_table_html(cur, diff)}
<h2>Workers</h2>
{workers_table_html(cur)}
<p class="sub">triage format v{_esc(cur.get("version", "?"))}
&middot; quiet_rounds={_esc(cur.get("quiet_rounds", "?"))}
&middot; diff lifecycle: {json.dumps({k: len(v) for k, v in
(diff or {}).get("buckets", {}).items()}) if diff else "no diff"}</p>
</body></html>
"""
