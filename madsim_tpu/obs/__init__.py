"""Observability layer: flight-recorder rings, trace export, sweep metrics.

madsim's debuggability contract is that a seed reproduces an execution you
can *watch* (env_logger + MADSIM_TEST_SEED replay). The batched engine keeps
that contract at three altitudes, each with a deliberate host-boundary cost
(DESIGN.md "Observability discipline"):

  * rings.py    — read the on-device flight-recorder ring (cfg.trace_cap):
                  the last N events per sampled lane, resident in SimState,
                  so even `run_fused` while_loop sweeps come back with
                  traces. O(trace_cap) per sampled lane crosses the host
                  boundary, once, at the end.
  * trace.py    — export ring contents or `collect_events` streams as
                  Chrome-trace/Perfetto JSON: one track per node,
                  virtual-time timestamps, supervisor ops as instant
                  events.
  * metrics.py  — SweepObserver: a callback protocol hooked into the chunk
                  boundaries run()/run_compacting()/explore() already pay
                  for; JsonlObserver writes the records as JSONL.
  * progress.py — ProgressObserver: live one-line sweep progress on a TTY.
  * causal.py   — (r10) the WHY layer over the ring: happens-before edges
                  from the per-event lineage pair (parent dispatch +
                  Lamport clock), `explain_crash` walks them backward
                  from a crash to its minimal causal chain, and
                  `sketch_divergence` reads where two lanes' schedules
                  first split from the on-device prefix sketches.
  * profiler.py — (r15) the WHERE layer: reports + Perfetto counter
                  tracks over the `cfg.profile` counter plane
                  (SimState pf_* columns — per-node dispatch/busy,
                  queue pressure, drop/delay, kill/boot counts), fed by
                  the on-device `parallel.stats.profile_digest`
                  reduction, and — r16 — the HOW-LONG layer over the
                  `cfg.latency_hist` plane: `latency_summary` /
                  `format_latency` render p50/p99/p999 + SLO misses
                  from `parallel.stats.latency_digest`, plus a rolling
                  per-node e2e-p99 Perfetto track off the `tr_lat`
                  ring column. O(counters + buckets) per sweep crosses
                  the host boundary, at syncs the runners already pay.
  * dashboard.py— (r18) the standing operator surface: render a triage
                  snapshot (+ diff) from service/triage.py as ONE
                  self-contained HTML file — inline-SVG sparklines for
                  the coverage/rate/p99 curves, attribution bars,
                  bucket lifecycle table with repro one-liners — no
                  server, no JS deps; pure read side of the store.
  * series.py   — (r21) the WHEN layer: the windowed telemetry plane
                  (cfg.series_windows, SimState sr_* columns) rendered
                  as sim-time reports and TRUE sim-time Perfetto
                  counter tracks — per-window dispatch/queue/drop/
                  latency/fault series bucketed by virtual time, fed
                  by the on-device `parallel.stats.series_digest`
                  reduction (O(W·K) per sweep). Window timestamps
                  never wrap: where the ring-derived r15/r16 counter
                  tracks go silent past trace_cap, the series tracks
                  cover t=0 to now, and `counter_track_events`
                  prefers them when the plane is compiled in.
  * timetravel.py—(r20) the WHEN-AGAIN layer: lane checkpoints
                  harvested at existing chunk syncs
                  (`run(ckpt_every=K)` -> CheckpointLog), window
                  replay with observability UPGRADED
                  (`replay_window` / `explain_crash(replay=True)`
                  recover FULL untruncated causal chains + focused
                  Perfetto window traces; equivalence asserted on
                  fingerprint + crash verdict), and the divergence
                  microscope (`divergence_report` names two lanes'
                  first divergent dispatch by replaying from their
                  last common checkpoint under full tracing).
  * spans.py    — (r23) the WHERE-DID-THE-TIME-GO layer: decompose a
                  completion's causal chain into per-hop (queue-wait,
                  transit) segments off the `span_attr` ring columns —
                  segments telescope to the recorded e2e latency
                  exactly — and `explain_latency` names the slowest
                  request's hop-by-hop critical path (replay=True
                  recovers wrap-truncated chains via r20 window
                  replay, same playbook as explain_crash).
  * support.py  — (r22) the WHY-IT-WORKED layer: walk the same lineage
                  columns BACKWARD from a success witness in a GREEN
                  lane to the support of its success — the message and
                  timer edges the outcome causally depended on — the
                  extraction half of lineage-driven fault targeting
                  (search/ldfi.py synthesizes cuts against it).
"""

from .causal import (causal_fingerprint, code_fingerprint, explain_crash,
                     fingerprints_match, happens_before, sketch_divergence,
                     walk_lineage)
from .dashboard import render_html, sparkline_svg
from .metrics import JsonlObserver, SweepObserver, TeeObserver
from .timetravel import (CheckpointLog, ReplayDivergence, divergence_report,
                         full_chain_replay, replay_window)
from .profiler import (attribution_summary, counter_track_events,
                       curve_brief, export_profile_trace,
                       format_attribution, format_latency, format_profile,
                       latency_histogram_rows, latency_summary,
                       profile_summary)
from .progress import ProgressObserver
from .rings import ring_records, sampled_lanes
from .series import (fault_names, format_series, lane_series,
                     series_counter_track_events, series_summary)
from .spans import (explain_latency, format_span, request_span,
                    request_spans)
from .support import extract_support, support_from_records
from .trace import export_chrome_trace, to_chrome_events

__all__ = [
    "SweepObserver", "JsonlObserver", "TeeObserver", "ProgressObserver",
    "ring_records", "sampled_lanes", "to_chrome_events",
    "export_chrome_trace",
    "explain_crash", "happens_before", "sketch_divergence",
    "causal_fingerprint", "code_fingerprint", "fingerprints_match",
    "walk_lineage", "support_from_records", "extract_support",
    "profile_summary", "format_profile", "counter_track_events",
    "export_profile_trace",
    "latency_summary", "format_latency", "latency_histogram_rows",
    "attribution_summary", "format_attribution",
    "series_summary", "format_series", "lane_series",
    "series_counter_track_events", "fault_names",
    "render_html", "sparkline_svg", "curve_brief",
    "CheckpointLog", "replay_window", "full_chain_replay",
    "divergence_report", "ReplayDivergence",
    "request_span", "request_spans", "explain_latency", "format_span",
]
