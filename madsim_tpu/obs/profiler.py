"""Sim-profiler reports + Perfetto counter tracks over the counter AND
latency planes.

The WHERE/HOW-LONG layer of the observability stack (DESIGN §16/§17):
the r7 ring answers *what happened*, the r10 lineage answers *why* —
this module answers *where the simulated cluster spends its effort*
(the `cfg.profile` pf_* counters) and *how long requests take* (the
`cfg.latency_hist` lh_* histograms, r16: `latency_summary` /
`format_latency` render p50/p99/p999 + SLO misses off the on-device
`parallel.stats.latency_digest` reduction, and `counter_track_events`
adds a rolling per-node e2e p99 track from the `tr_lat` ring column
next to busy%/queue depth). All columns live IN SimState and survive
the fused while_loop with zero new host round-trips. Two consumers:

  * `profile_summary` / `format_profile` — the report object: batch-sum
    counters off the on-device `parallel.stats.profile_digest` reduction
    (O(counters) host transfer) plus derived rates — per-node busy%,
    dispatch mix by event kind, drop rate, mean imposed delay, queue
    high-water percentiles. `summarize()` carries an abbreviated form in
    its `profile` key.
  * Perfetto COUNTER tracks next to the r7 instants and r10 flow arrows:
    `counter_track_events` renders queue depth over virtual time (the
    `tr_qlen` ring column — compiled in when profile AND trace are),
    cumulative per-node busy% (derived from the ring's now-deltas —
    each dispatch's clock advance belongs to its acting node), and the
    lane's divergence-from-consensus step off the r10 `cov_sketch`.
    `export_profile_trace` writes one document with instants + flows +
    counters, so a crash timeline and the pressure curves line up on
    one virtual-time axis in ui.perfetto.dev.
"""

from __future__ import annotations

import json

import numpy as np

from ..parallel.stats import (attribution_counters, latency_bucket_edges,
                              latency_counters, profile_counters)
from .rings import ring_records
from .trace import _doc, to_chrome_events

# pf_dispatch's kind axis, named (core/types.py event kinds; FREE never
# counts — a valid dispatch is never EV_FREE)
KIND_NAMES = ("free", "msg", "timer", "super")


def profile_summary(state) -> dict | None:
    """The profiler report for a (finished or running) batched state:
    raw batch-sum counters plus derived rates. None when the counter
    plane is compiled out (cfg.profile=False) or the state is unbatched.

    Derived fields:
      busy_pct[n]    node n's busy virtual time as % of the profiled
                     lanes' total virtual time (sums to ~100 when every
                     dispatch advanced the clock — idle gaps and
                     zero-delta dispatches make it undershoot, never
                     overshoot)
      dispatch_mix   total dispatches by event kind name
      drop_rate      drops per dispatched event
      mean_delay_us  pf_delay / delivered message dispatches
    """
    c = profile_counters(state)
    if c is None:
        return None
    disp = np.asarray(c["dispatch"], np.int64)          # [N, K]
    busy = np.asarray(c["busy"], np.int64)              # [N]
    total_disp = int(disp.sum())
    total_now = max(c["now_sum"], 1)
    msgs = int(disp[:, 1].sum())
    out = dict(
        lanes=c["lanes"],
        dispatches=total_disp,
        dispatch_by_node=disp.sum(-1).tolist(),
        dispatch_mix={KIND_NAMES[k]: int(disp[:, k].sum())
                      for k in range(disp.shape[1]) if disp[:, k].sum()},
        busy_us=busy.tolist(),
        busy_pct=[round(100.0 * b / total_now, 2) for b in busy.tolist()],
        kills=c["kill"].tolist(),
        restarts=c["restart"].tolist(),
        drops=c["drop"],
        drop_rate=round(c["drop"] / max(total_disp, 1), 4),
        delay_ticks=c["delay"],
        mean_delay_us=round(c["delay"] / max(msgs, 1), 1),
        queue_p50=c["qmax_p50"], queue_p90=c["qmax_p90"],
        queue_max=c["qmax_max"],
        steps_p50=c["steps_p50"], steps_p90=c["steps_p90"],
        steps_max=c["steps_max"],
    )
    return out


def format_profile(summary: dict, node_names=None) -> str:
    """Render a `profile_summary` dict as a fixed-width text table —
    the operator-facing report (`python -m`-free: print it)."""
    if summary is None:
        return "profiler compiled out (SimConfig.profile=False)"
    N = len(summary["dispatch_by_node"])
    name = (node_names if node_names is not None
            else [f"node{n}" for n in range(N)])
    lines = [
        f"profiled lanes: {summary['lanes']}  "
        f"dispatches: {summary['dispatches']}  "
        f"mix: {summary['dispatch_mix']}",
        f"drops: {summary['drops']} ({summary['drop_rate']:.2%}/event)  "
        f"mean delay: {summary['mean_delay_us']}us  "
        f"queue p50/p90/max: {summary['queue_p50']}/"
        f"{summary['queue_p90']}/{summary['queue_max']}",
        f"{'node':<12} {'dispatches':>10} {'busy_us':>12} {'busy%':>7} "
        f"{'kills':>6} {'boots':>6}",
    ]
    for n in range(N):
        lines.append(
            f"{name[n]:<12} {summary['dispatch_by_node'][n]:>10} "
            f"{summary['busy_us'][n]:>12} {summary['busy_pct'][n]:>7} "
            f"{summary['kills'][n]:>6} {summary['restarts'][n]:>6}")
    return "\n".join(lines)


def latency_summary(state) -> dict | None:
    """The latency report for a (finished or running) batched state:
    merged histogram quantiles plus derived rates, off the on-device
    `parallel.stats.latency_digest` reduction (O(buckets) transfer).
    None when the plane is compiled out (cfg.latency_hist == 0) or the
    state is unbatched.

    Quantile estimates are bucket-CDF LOWER bounds in ticks (µs) —
    deterministic, conservative (DESIGN §17). `slo_miss_rate` is
    misses per completion (0 when slo_target was 0 or nothing
    completed)."""
    c = latency_counters(state)
    if c is None:
        return None
    e2e = np.asarray(c["e2e_hist"], np.int64)           # [N, B]
    soj = np.asarray(c["sojourn_hist"], np.int64)
    completions = int(e2e.sum())
    return dict(
        lanes=c["lanes"],
        buckets=int(e2e.shape[1]),
        completions=completions,
        completions_by_node=e2e.sum(-1).tolist(),
        e2e_p50=c["e2e_p50"], e2e_p90=c["e2e_p90"],
        e2e_p99=c["e2e_p99"], e2e_p999=c["e2e_p999"],
        e2e_p99_by_node=c["e2e_p99_by_node"],
        sojourn_p50=c["sojourn_p50"], sojourn_p90=c["sojourn_p90"],
        sojourn_p99=c["sojourn_p99"], sojourn_p999=c["sojourn_p999"],
        sojourn_events=int(soj.sum()),
        slo_miss=c["slo_miss"],
        slo_miss_by_node=c["slo_miss_by_node"],
        slo_miss_rate=round(c["slo_miss"] / max(completions, 1), 4),
    )


def format_latency(summary: dict, node_names=None) -> str:
    """Render a `latency_summary` dict as a fixed-width text table —
    the operator-facing SLO report."""
    if summary is None:
        return "latency plane compiled out (SimConfig.latency_hist=0)"
    N = len(summary["completions_by_node"])
    name = (node_names if node_names is not None
            else [f"node{n}" for n in range(N)])
    lines = [
        f"recorded lanes: {summary['lanes']}  "
        f"completions: {summary['completions']}  "
        f"slo_miss: {summary['slo_miss']} "
        f"({summary['slo_miss_rate']:.2%})",
        f"e2e p50/p90/p99/p999: {summary['e2e_p50']}/"
        f"{summary['e2e_p90']}/{summary['e2e_p99']}/"
        f"{summary['e2e_p999']}us  "
        f"sojourn p50/p99: {summary['sojourn_p50']}/"
        f"{summary['sojourn_p99']}us",
        f"{'node':<12} {'completions':>12} {'e2e_p99':>9} {'slo_miss':>9}",
    ]
    for n in range(N):
        lines.append(
            f"{name[n]:<12} {summary['completions_by_node'][n]:>12} "
            f"{summary['e2e_p99_by_node'][n]:>9} "
            f"{summary['slo_miss_by_node'][n]:>9}")
    return "\n".join(lines)


def attribution_summary(state) -> dict | None:
    """The tail-attribution report for a batched state (r23, DESIGN
    §24): where SLO-missing requests spent their time, off the
    on-device `parallel.stats.attribution_digest` reduction (O(N)
    transfer). Per COMPLETION node: tail count and that cohort's
    accumulated queue-wait / transit / hop totals; plus the
    bottleneck-node histogram (which node owned each tail's dominant
    segment — attribution proper, usually a different node than where
    the request completed). None when the plane is compiled out
    (cfg.span_attr False) or the state is unbatched."""
    from ..core.state import SA_COUNT, SA_HOPS, SA_NET, SA_QWAIT
    c = attribution_counters(state)
    if c is None:
        return None
    t = np.asarray(c["tail"], np.int64)                 # [N, SA]
    bn = c["bottleneck"]
    tails = int(t[:, SA_COUNT].sum())
    qwait = int(t[:, SA_QWAIT].sum())
    net = int(t[:, SA_NET].sum())
    return dict(
        lanes=c["lanes"], slo_target=c["slo_target"], tails=tails,
        qwait_us=qwait, net_us=net,
        wait_share=(round(qwait / (qwait + net), 4)
                    if qwait + net else None),
        hops_mean=(round(int(t[:, SA_HOPS].sum()) / tails, 2)
                   if tails else None),
        tails_by_node=t[:, SA_COUNT].tolist(),
        qwait_by_node=t[:, SA_QWAIT].tolist(),
        net_by_node=t[:, SA_NET].tolist(),
        bottleneck_by_node=bn,
        bottleneck_node=(int(np.argmax(bn)) if tails else None),
    )


def format_attribution(summary: dict, node_names=None) -> str:
    """Render an `attribution_summary` dict as a fixed-width table —
    the operator-facing answer to "who owns the tail". The bottleneck
    column counts DOMINANT segments owned; the starred row is the
    cluster's bottleneck node."""
    if summary is None:
        return ("attribution plane compiled out "
                "(SimConfig.span_attr=False)")
    N = len(summary["tails_by_node"])
    name = (node_names if node_names is not None
            else [f"node{n}" for n in range(N)])
    ws = summary["wait_share"]
    lines = [
        f"recorded lanes: {summary['lanes']}  "
        f"slo_target: {summary['slo_target']}us  "
        f"tail requests: {summary['tails']}",
        f"tail time split: wait {summary['qwait_us']}us / "
        f"transit {summary['net_us']}us"
        + (f" (wait share {ws:.1%})" if ws is not None else "")
        + (f"  mean hops: {summary['hops_mean']}"
           if summary["hops_mean"] is not None else ""),
        f"{'node':<12} {'tails':>8} {'wait_us':>12} {'transit_us':>12} "
        f"{'bottleneck':>11}",
    ]
    for n in range(N):
        star = " *" if summary["bottleneck_node"] == n else ""
        lines.append(
            f"{name[n]:<12} {summary['tails_by_node'][n]:>8} "
            f"{summary['qwait_by_node'][n]:>12} "
            f"{summary['net_by_node'][n]:>12} "
            f"{summary['bottleneck_by_node'][n]:>11}{star}")
    return "\n".join(lines)


def curve_brief(curve) -> dict | None:
    """Summarize a [[t, value], ...] series (the campaign timeline's
    coverage/rate/p99 curves) into the stat-tile shape the triage
    snapshots persist and the dashboard renders: point count, min /
    p50 / p90 / max over values, and the last value. None for an empty
    series (build without that plane). Deterministic — a pure function
    of the series, so it is safe inside the byte-stable snapshot body."""
    if not curve:
        return None
    vals = np.asarray([v for _t, v in curve], np.float64)
    return dict(
        n=int(len(curve)),
        min=round(float(vals.min()), 3),
        p50=round(float(np.percentile(vals, 50)), 3),
        p90=round(float(np.percentile(vals, 90)), 3),
        max=round(float(vals.max()), 3),
        last=round(float(vals[-1]), 3))


def latency_histogram_rows(state) -> list[dict] | None:
    """The merged histograms as JSON-able rows (one per bucket with any
    count): {bucket, lo_us, e2e, sojourn} — dashboard/ingest format.
    None when the plane is compiled out."""
    c = latency_counters(state)
    if c is None:
        return None
    e2e = np.asarray(c["e2e_hist"], np.int64).sum(0)
    soj = np.asarray(c["sojourn_hist"], np.int64).sum(0)
    edges = latency_bucket_edges(len(e2e))
    return [dict(bucket=int(b), lo_us=int(edges[b]),
                 e2e=int(e2e[b]), sojourn=int(soj[b]))
            for b in range(len(e2e)) if e2e[b] or soj[b]]


def _counter(name: str, ts: int, value, series: str = "value",
             pid: int = 0) -> dict:
    return dict(name=name, ph="C", ts=int(ts), pid=pid,
                args={series: float(value)})


def counter_track_events(state, lane: int = 0, node_names=None,
                         consensus=None, recs=None,
                         p99_window: int = 64) -> list[dict]:
    """Perfetto counter-track events for one lane, from the ring window
    (cfg.trace_cap > 0; the lane must be sampled):

      queue_depth    event-table occupancy at each dispatch (`tr_qlen` —
                     present only on cfg.profile builds; omitted, not
                     zeroed, elsewhere)
      busy_pct:<n>   node n's cumulative busy share of the ring window's
                     virtual time, from the ring's now-deltas (the delta
                     of each dispatch belongs to its record's node) —
                     window-relative after a ring wrap
      cov_divergence 0/1 step track: whether this lane's prefix sketch
                     had left the batch-consensus prefix by the
                     checkpoint nearest each ring record (cfg.sketch_slots
                     builds only; `consensus` overrides the batch modal,
                     e.g. with a campaign's cross-round consensus)
      e2e_p99:<n>    ROLLING p99 of the last `p99_window` completions at
                     node n, from the `tr_lat` ring column (present only
                     on cfg.latency_hist builds with complete_kinds) —
                     the tail curve over virtual time, next to the
                     pressure curves it correlates with

    Timestamps ride the same virtual-time axis as the r7 instants, so
    the tracks align with the event timeline in one document. Pass an
    already-unwrapped `recs` (a `ring_records` dict for this lane) to
    skip re-reading the ring — `export_profile_trace` does, halving
    its host transfer.

    r21: when the windowed series plane is compiled in
    (cfg.series_windows > 0) and this lane records, the queue_depth /
    busy% / e2e_p99 tracks are DERIVED FROM THE SERIES instead
    (obs/series.py) — true window-start timestamps covering the whole
    run, where the ring reconstruction goes silent for everything
    older than trace_cap dispatches after a wrap. The ring paths below
    remain the fallback (finer grain: one point per dispatch, per-node
    p99) when the plane is off or the lane is series-masked; the
    cov_divergence track is ring/sketch-based either way.
    """
    from .series import series_counter_track_events
    out = series_counter_track_events(state, lane, node_names=node_names)
    from_series = bool(out)
    if recs is None:
        if from_series:
            # series-only build: the ring may be compiled out entirely —
            # the series tracks stand on their own (no cov_divergence /
            # per-node rolling p99, which are ring/sketch-derived)
            try:
                recs = ring_records(state, lane)
            except ValueError:
                return out
        else:
            recs = ring_records(state, lane)
    n = len(recs["now"])
    qlen = recs.get("qlen")
    if qlen is not None and not from_series:
        out += [_counter("queue_depth", recs["now"][i], qlen[i], "depth")
                for i in range(n)]
    # cumulative busy% per node over the ring window
    if n and not from_series:
        t0 = int(recs["now"][0])
        nodes = sorted({int(x) for x in recs["node"]})
        label = {nd: (node_names[nd] if node_names is not None
                      else f"node{nd}") for nd in nodes}
        busy = {nd: 0 for nd in nodes}
        prev = t0
        for i in range(n):
            now_i = int(recs["now"][i])
            busy[int(recs["node"][i])] += now_i - prev
            prev = now_i
            span = max(now_i - t0, 1)
            for nd in nodes:
                out.append(_counter(f"busy_pct:{label[nd]}", now_i,
                                    round(100.0 * busy[nd] / span, 2),
                                    "busy_pct"))
    # rolling per-node e2e p99 over the ring window's completions
    lat = recs.get("lat")
    if lat is not None and n and not from_series:
        label = {}
        window: dict[int, list] = {}
        for i in range(n):
            li = int(lat[i])
            if li < 0:          # not a completion dispatch
                continue
            nd = int(recs["node"][i])
            if nd not in window:
                window[nd] = []
                label[nd] = (node_names[nd] if node_names is not None
                             else f"node{nd}")
            w = window[nd]
            w.append(li)
            if len(w) > p99_window:
                del w[0]
            out.append(_counter(
                f"e2e_p99:{label[nd]}", recs["now"][i],
                float(np.percentile(np.asarray(w), 99)), "p99_us"))
    sk = np.asarray(getattr(state, "cov_sketch", np.zeros((0, 0))))
    if n and sk.ndim == 2 and sk.shape[1] > 0:
        from ..parallel.stats import first_divergence_slots
        every = int(np.atleast_1d(
            np.asarray(state.sketch_every)).reshape(-1)[0])
        div_slot = int(first_divergence_slots(
            sk, consensus=consensus)[lane])
        # 0/1 step track sampled at the ring records: diverged once the
        # record's dispatch index passes the first divergent checkpoint
        for i in range(n):
            diverged = int(recs["step"][i]) >= (div_slot + 1) * every - 1
            out.append(_counter("cov_divergence", recs["now"][i],
                                1.0 if diverged else 0.0, "diverged"))
    return out


def export_profile_trace(path: str, state, lane: int = 0,
                         node_names=None, consensus=None) -> int:
    """Write one Perfetto/Chrome JSON document for `lane`: the r7
    instant events and r10 flow arrows (`to_chrome_events` over the
    ring) PLUS the profiler counter tracks, all on one virtual-time
    axis. Returns the instant-event count (the `export_chrome_trace`
    contract — counters annotate dispatches, they aren't dispatches)."""
    recs = ring_records(state, lane)     # one unwrap serves both halves
    events = to_chrome_events(recs)
    events += counter_track_events(state, lane, node_names=node_names,
                                   consensus=consensus, recs=recs)
    with open(path, "w") as f:
        json.dump(_doc(events, node_names), f)
    return sum(1 for e in events if e["ph"] == "i")
