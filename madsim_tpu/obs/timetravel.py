"""Time-travel replay (r20, DESIGN §21): lane checkpoints, full-fidelity
window replay with UPGRADED observability, and the divergence microscope.

The engine's promise is that one seed reproduces an entire execution;
until r20 the debugging story still topped out at *printing* whatever
the live run happened to record — `explain_crash` chains truncate at
ring wrap, and a sweep that ran lean (ring off, profiler off) was blind
after the fact. The checkpoint primitive (core/state.checkpoint_lane /
seed_batch_from) closes the gap: any harvested lane snapshot re-seeds a
fresh batch that continues bit-identically, and because every
observability plane is observation-ONLY (TRACE_FIELDS — no randomness,
no replay-domain writes), the continuation may be compiled with MORE
instrumentation than the original run without changing the trajectory.
"Replay the window with a big ring" is therefore a sound operation, and
this module packages the three moves built on it:

  * `CheckpointLog` — the harvest `Runtime.run(ckpt_every=K)` /
    `run_fused(ckpt_every=K)` fill at their existing chunk syncs;
  * `replay_window` / `full_chain_replay` / `time_travel_explain` —
    re-execute from the nearest checkpoint with ring/profiler/latency
    plane upgraded, assert equivalence on fingerprint + crash verdict,
    and recover the FULL (`truncated=False`) causal chain plus a
    focused Perfetto trace of just the window;
  * `divergence_report` — the microscope: bound two lanes' first
    schedule divergence with the r10 cov_sketch, replay both lanes from
    the last common checkpoint under full tracing, and name the first
    divergent dispatch (step, node, kind, the tie that flipped) with
    side-by-side ring suffixes and a two-track Perfetto export.

Equivalence discipline: a replay's claim is only as good as its match
to the live observation, so every replay that has a live reference
asserts fingerprint + crash verdict against it (ReplayDivergence on
mismatch, with one retry to absorb the known jaxlib persistent-cache
first-invocation transient — ROADMAP r12)."""

from __future__ import annotations

import json

import numpy as np

from ..core.state import LaneCheckpoint, checkpoint_lane, seed_batch_from
from ..utils.hostcopy import owned_host_copy
from . import causal
from .rings import ring_records
from .trace import _doc, export_chrome_trace, to_chrome_events


class ReplayDivergence(RuntimeError):
    """A window replay did not reproduce the live observation
    (fingerprint or crash verdict mismatch after the one-retry
    transient guard) — either the checkpoint belongs to a different
    run or the engine is genuinely nondeterministic here."""


class CheckpointLog:
    """The harvest of a `run(ckpt_every=K)` / `run_fused(ckpt_every=K)`
    sweep: owned host copies of the whole batch at successive
    ~K-step boundaries, read back per lane as `LaneCheckpoint`s.

    Memory: one snapshot is a full host copy of the batch state (the
    price of being able to re-seed ANY lane); `keep` bounds the window
    (oldest dropped) — None keeps everything, the default for
    debugging-sized sweeps. `signature` is stamped by the harvesting
    runtime so checkpoints carry the world-shape contract."""

    def __init__(self, every: int | None = None, keep: int | None = None):
        self.every = every
        self.keep = keep
        self.signature = None
        self.snaps: list[dict] = []   # dicts: steps_done, state (host)

    def __len__(self) -> int:
        return len(self.snaps)

    def harvest(self, state, steps_done: int | None = None) -> None:
        """Append one snapshot (owned host copy — safe across later
        donated runs of the same buffers, utils/hostcopy). The
        CURRENT `signature` (stamped by the harvesting runtime's
        _ckpt_setup) is captured PER SNAPSHOT: a log accumulated
        across runs of different runtimes keeps each snapshot's own
        world contract — a later run must not retroactively re-badge
        earlier harvests."""
        self.snaps.append(dict(steps_done=steps_done,
                               state=owned_host_copy(state),
                               signature=self.signature))
        if self.keep is not None and len(self.snaps) > self.keep:
            del self.snaps[0]

    def lane_steps(self, lane: int) -> list[int]:
        """The lane's dispatch count at each snapshot (monotone; stops
        advancing once the lane halts)."""
        return [int(np.asarray(s["state"].steps)[lane])
                for s in self.snaps]

    def iter_checkpoints(self, lane: int, before_step: int | None = None,
                         live_only: bool = True):
        """Lazily yield the lane's checkpoints NEWEST first — the
        per-leaf gather + owned host copy is paid per checkpoint
        CONSUMED, so callers that stop at the first (nearest/
        time_travel_explain's common case) never materialize the rest.
        `before_step` keeps only snapshots taken at or before that
        dispatch count; `live_only` (default) drops snapshots where the
        lane had already halted — a halted lane's snapshot is its final
        state, not a restart point."""
        for snap in reversed(self.snaps):
            st = snap["state"]
            if live_only and bool(np.asarray(st.halted)[lane]):
                continue
            steps = int(np.asarray(st.steps)[lane])
            if before_step is not None and steps > before_step:
                continue
            yield checkpoint_lane(st, lane,
                                  signature=snap.get("signature",
                                                     self.signature))

    def checkpoints(self, lane: int, before_step: int | None = None,
                    live_only: bool = True) -> list[LaneCheckpoint]:
        """`iter_checkpoints` materialized to a list."""
        return list(self.iter_checkpoints(lane, before_step=before_step,
                                          live_only=live_only))

    def nearest(self, lane: int, step: int | None = None,
                live_only: bool = True) -> LaneCheckpoint | None:
        """The LATEST checkpoint of `lane` at or before `step` (None =
        the latest live one) — the one window replay restarts from."""
        return next(self.iter_checkpoints(lane, before_step=step,
                                          live_only=live_only), None)


# ---------------------------------------------------------------------------
# exact-step advance + handle-based checkpoints
# ---------------------------------------------------------------------------

def advance_exact(rt, state, steps: int, chunk: int = 512):
    """Advance a batched state by EXACTLY `steps` dispatches per live
    lane (power-of-two chunk decomposition, the `state_at` discipline:
    at most log2(chunk) distinct scan lengths ever compile, shared
    through the program cache). Halted lanes freeze; an all-halted
    batch stops early."""
    remaining = int(steps)
    runner = rt._run_chunk[False]
    while remaining > 0:
        c = min(int(chunk), 1 << (remaining.bit_length() - 1))
        state, _ = runner(state, c)
        remaining -= c
        if bool(state.halted.all()):
            break
    return state


def init_checkpoint(rt, seed: int, knobs: dict | None = None,
                    nudge: int | None = None) -> LaneCheckpoint:
    """The trivial checkpoint every repro handle implies: the t=0 state
    of `(seed[, knobs][, nudge])` on `rt`. Makes "no harvested
    checkpoint" a degenerate case of window replay instead of a
    different code path — replaying from init IS replaying from the
    step-0 checkpoint (just the most expensive one)."""
    state = rt.init_batch(np.asarray([seed], np.uint32))
    if knobs is not None:
        from ..search.mutate import apply_repro_knobs
        state, _ = apply_repro_knobs(rt, state, knobs)
    if nudge is not None:
        from ..search.pct import with_prio_nudge
        state = with_prio_nudge(state, np.asarray([nudge], np.int32))
    return checkpoint_lane(state, 0,
                           signature=rt.cfg.structural_signature())


# ---------------------------------------------------------------------------
# window replay
# ---------------------------------------------------------------------------

def _verdict_of(state, lane: int = 0) -> dict:
    def pick(leaf):
        a = np.asarray(leaf)
        return a.reshape(-1)[lane] if a.ndim else a
    return dict(crashed=bool(pick(state.crashed)),
                crash_code=int(pick(state.crash_code)),
                crash_node=int(pick(state.crash_node)))


def replay_window(rt, ckpt: LaneCheckpoint, *, until_step: int | None = None,
                  max_steps: int = 100_000, chunk: int = 512,
                  trace_cap: int | None = None, profile: bool | None = None,
                  latency_hist: int | None = None,
                  sketch_slots: int | None = None,
                  expect: dict | None = None,
                  export_trace: str | None = None, batch: int = 1) -> dict:
    """Re-execute from a lane checkpoint with observability UPGRADED.

    Derives a runtime from `rt` with the requested planes compiled in
    (`trace_cap` defaults to covering the whole window so the ring
    never wraps; `profile`/`latency_hist`/`sketch_slots` override when
    not None), seeds a `batch`-clone child from `ckpt`
    (`seed_batch_from` adapts the observation planes, resetting the
    ring so the window starts from an empty recorder), and runs it —
    to exactly `until_step` total dispatches (exact-step advance) or
    until crash/halt (`until_step=None`, bounded by `max_steps`).

    `expect` asserts equivalence against the live observation: any of
    crashed/crash_code/crash_node/fingerprint present in the dict is
    compared to the replay (only meaningful for a full replay to halt);
    a mismatch is retried ONCE (the known persistent-cache
    first-invocation transient never survives re-invocation) and then
    raises ReplayDivergence.

    Returns {state, rt (the upgraded runtime), from_step, steps,
    fingerprint, crashed, crash_code, crash_node[, trace_path]};
    `export_trace` additionally writes the lane-0 ring as a focused
    Perfetto trace of JUST the window."""
    overrides: dict = {}
    if trace_cap is None:
        span = (int(until_step) - ckpt.steps if until_step is not None
                else int(max_steps))
        trace_cap = max(16, span)
    overrides["trace_cap"] = int(trace_cap)
    if profile is not None:
        overrides["profile"] = bool(profile)
    if latency_hist is not None:
        overrides["latency_hist"] = int(latency_hist)
    if sketch_slots is not None:
        overrides["sketch_slots"] = int(sketch_slots)
    changed = {k: v for k, v in overrides.items()
               if getattr(rt.cfg, k) != v}
    wrt = rt.derived(**changed) if changed else rt

    def once():
        st = seed_batch_from(ckpt, batch, rt=wrt, reset_planes=("ring",))
        if until_step is not None:
            st = advance_exact(wrt, st, int(until_step) - ckpt.steps, chunk)
        else:
            st = wrt.run_fused(st, max_steps, chunk)
        return st

    st = once()
    out = dict(state=st, rt=wrt, from_step=int(ckpt.steps),
               steps=int(np.asarray(st.steps).reshape(-1)[0]),
               fingerprint=int(wrt.fingerprints(st)[0]),
               **_verdict_of(st, 0))
    if expect is not None:
        def mismatches(o):
            return [k for k in ("crashed", "crash_code", "crash_node",
                                "fingerprint")
                    if k in expect and expect[k] != o[k]]
        bad = mismatches(out)
        if bad:
            # one retry: the jaxlib persistent-cache first-invocation
            # corruption (ROADMAP r12) is transient and never survives
            # a re-invocation; a second mismatch is a real divergence
            st = once()
            out.update(state=st,
                       steps=int(np.asarray(st.steps).reshape(-1)[0]),
                       fingerprint=int(wrt.fingerprints(st)[0]),
                       **_verdict_of(st, 0))
            bad = mismatches(out)
            if bad:
                raise ReplayDivergence(
                    f"window replay from step {ckpt.steps} does not "
                    f"reproduce the live observation on {bad}: "
                    f"expected { {k: expect[k] for k in bad} }, "
                    f"replayed { {k: out[k] for k in bad} }")
    if export_trace is not None:
        export_chrome_trace(export_trace, state=st, lane=0)
        out["trace_path"] = export_trace
    return out


def full_chain_replay(rt, *, ckpt: LaneCheckpoint | None = None,
                      seed: int | None = None, knobs: dict | None = None,
                      nudge: int | None = None, expect: dict | None = None,
                      max_steps: int = 100_000, chunk: int = 512,
                      trace_cap: int | None = None,
                      until_step: int | None = None,
                      export_trace: str | None = None) -> dict:
    """Replay to halt — or to exactly `until_step` dispatches, for a
    lane whose live observation was still running — from `ckpt` (or
    from t=0 via the (seed[, knobs][, nudge]) handle) with a ring
    sized to hold the whole window, then explain the final dispatch
    off the unwrapped ring. Returns the `replay_window` dict plus
    `explain` — the chain is complete (`truncated=False`) whenever
    the checkpoint precedes the crash's causal root (always, for the
    t=0 checkpoint, ring capacity allowing)."""
    if ckpt is None:
        if seed is None:
            raise ValueError("full_chain_replay needs ckpt= or a "
                             "(seed[, knobs][, nudge]) handle")
        ckpt = init_checkpoint(rt, seed, knobs=knobs, nudge=nudge)
    win = replay_window(rt, ckpt, max_steps=max_steps, chunk=chunk,
                        trace_cap=trace_cap, expect=expect,
                        until_step=until_step,
                        export_trace=export_trace)
    exp = causal.explain_crash(win["state"], 0)
    exp["replayed_from_step"] = int(ckpt.steps)
    return dict(win, explain=exp)


def time_travel_explain(rt, state, lane: int = 0, *, ckpts: CheckpointLog,
                        max_steps: int = 100_000, chunk: int = 512,
                        trace_cap: int | None = None,
                        export_trace: str | None = None) -> dict:
    """`explain_crash` that REPLAYS instead of settling for the live
    ring's suffix: walk back through the lane's harvested checkpoints
    (newest first), window-replay from each with a ring sized to hold
    the whole window, and return the first chain that reaches its root
    (`truncated=False` is GUARANTEED when some checkpoint precedes the
    root — every post-checkpoint parent then resolves in the unwrapped
    replay ring). Each replay is equivalence-checked against the live
    lane (fingerprint + crash verdict, ReplayDivergence on mismatch).

    Returns the `explain_crash` dict extended with `replayed=True`,
    `from_step` (the checkpoint used), `fingerprint`, and
    `trace_path` when `export_trace` wrote the focused window trace.
    A live chain that is ALREADY complete returns as-is
    (`replayed=False`) — no replay spent. Raises ValueError when no
    harvested checkpoint covers the lane (harvest with
    `run(ckpt_every=...)`, or use the (seed, knobs) handle via
    `full_chain_replay` — t=0 is always a checkpoint there)."""
    live = dict(_verdict_of(state, lane),
                fingerprint=int(rt.fingerprints(state)[lane]))
    try:
        live_exp = causal.explain_crash(state, lane)
    except ValueError:
        live_exp = None          # ring compiled out / lane unsampled
    if live_exp is not None and not live_exp["truncated"]:
        out = dict(live_exp, replayed=False)
        if export_trace is not None:
            # the caller asked for the window trace either way — the
            # live ring already holds the complete window, export THAT
            export_chrome_trace(export_trace, state=state, lane=lane)
            out["trace_path"] = export_trace
        return out
    crash_step = int(np.asarray(state.steps).reshape(-1)[lane])
    # a crashed/halted lane is frozen: replay runs to halt and lands on
    # the same final state. A lane the live sweep left RUNNING (hit its
    # max_steps while live) must replay to exactly its live dispatch
    # count — running further would honestly diverge the fingerprint.
    live_halted = bool(np.asarray(state.halted).reshape(-1)[lane])
    until = None if live_halted else crash_step
    cks = (ckpts.iter_checkpoints(lane, before_step=crash_step)
           if ckpts is not None else iter(()))
    best = None
    any_ckpt = False
    for ckpt in cks:
        any_ckpt = True
        span = crash_step - ckpt.steps
        rep = full_chain_replay(
            rt, ckpt=ckpt, expect=live, max_steps=max_steps, chunk=chunk,
            trace_cap=(trace_cap if trace_cap is not None
                       else max(16, span)),
            until_step=until,
            export_trace=export_trace)
        exp = dict(rep["explain"], replayed=True,
                   from_step=int(ckpt.steps),
                   fingerprint=rep["fingerprint"])
        if "trace_path" in rep:
            exp["trace_path"] = rep["trace_path"]
        if not exp["truncated"]:
            return exp
        if best is None or len(exp["chain"]) > len(best["chain"]):
            best = exp           # root precedes this checkpoint: step back
    if not any_ckpt:
        raise ValueError(
            f"no harvested checkpoint covers lane {lane} before its "
            f"crash at step {crash_step} — run with ckpt_every=..., or "
            "replay the (seed, knobs) handle via full_chain_replay "
            "(t=0 is always a checkpoint when the handle is known)")
    return best                  # honest: still truncated at the oldest


# ---------------------------------------------------------------------------
# divergence microscope
# ---------------------------------------------------------------------------

_TOKEN_KEYS = ("kind", "node", "src", "tag")


def _pair_state(prt, seed_a, seed_b, knobs_b, nudge_b):
    seeds = np.asarray(
        [seed_a, seed_b if seed_b is not None else seed_a], np.uint32)
    st = prt.init_batch(seeds)
    if knobs_b is not None:
        from ..search.mutate import KnobPlan
        plan = KnobPlan.from_runtime(
            prt, dup_slots=len(np.atleast_1d(knobs_b["dup_src"])))
        st = plan.apply(st, KnobPlan.stack([plan.base_knobs(), knobs_b]))
    if nudge_b is not None:
        from ..search.pct import with_prio_nudge
        base = int(np.asarray(st.prio_nudge).reshape(-1)[0])
        st = with_prio_nudge(st, np.asarray([base, int(nudge_b)], np.int32))
    return st


def _ring_token_rows(recs: dict) -> list[tuple]:
    cols = [np.asarray(recs[k]) for k in _TOKEN_KEYS]
    return [tuple(int(c[i]) for c in cols) for i in range(len(cols[0]))]


def _rec_row(recs: dict, i: int) -> dict:
    keys = ("step", "now", "kind", "node", "src", "tag", "parent",
            "lamport")
    return {k: int(np.asarray(recs[k])[i]) for k in keys if k in recs}


def export_pair_trace(path: str, state_a, state_b,
                      names=("lane_a", "lane_b")) -> int:
    """One Perfetto document with BOTH lanes' tracks: lane A as pid 0,
    lane B as pid 1, each with its per-node thread tracks, flow arrows
    and instant args intact — open it and read the two schedules side
    by side. Returns the total instant-event count."""
    docs = []
    for pid, (st, name) in enumerate(zip((state_a, state_b), names)):
        evs = to_chrome_events(ring_records(st, 0))
        body = _doc(evs, None, None)["traceEvents"]
        for e in body:
            e["pid"] = pid
            # flow binding is by (cat, id) GLOBALLY, not per pid — both
            # lanes replay the same window and emit the same step-keyed
            # flow ids, so un-namespaced ids would draw bogus arrows
            # BETWEEN the two tracks
            if "id" in e:
                e["id"] = (pid << 32) | int(e["id"])
        docs.append(dict(name="process_name", ph="M", pid=pid,
                         args=dict(name=name)))
        docs.extend(body)
    with open(path, "w") as f:
        json.dump(dict(traceEvents=docs, displayTimeUnit="ms"), f)
    return sum(1 for e in docs if e.get("ph") == "i")


def divergence_report(rt, seed_a: int, seed_b: int | None = None, *,
                      knobs_b: dict | None = None,
                      nudge_b: int | None = None,
                      max_steps: int = 20_000, chunk: int = 512,
                      sketch_slots: int = 64, window_pad: int = 8,
                      suffix: int = 16,
                      export_trace: str | None = None) -> dict:
    """The divergence microscope: turn "these lanes diverged somewhere
    around slot 12" into a NAMED first divergent dispatch.

    Lane A runs `seed_a` untouched; lane B is `seed_b`, or `seed_a`
    under `knobs_b` (a fuzz mutant's knob vector) and/or `nudge_b` (a
    PCT tie-break policy — the confirm_race shape). Three moves:

      1. PROBE: run the pair on a sketch-compiled build (derived when
         `rt` lacks one); `sketch_divergence` bounds the first
         divergent schedule slot — `bound="sketch-slot"` gives the
         window [slot*every, (slot+1)*every]; `bound="exhausted"`
         (fingerprints differ but no recorded slot does) falls back to
         the whole run.
      2. REPLAY the window: advance a fresh pair exactly to the window
         start (the last COMMON checkpoint), `checkpoint_lane` both
         lanes, re-seed each through a big-ring derived build
         (`seed_batch_from` upgrade path, ring reset), run the window
         under full tracing.
      3. DIFF step-aligned: the first ring index where the two lanes'
         dispatch tokens (kind, node, src, tag) differ is the first
         divergent dispatch — reported with both sides' records (the
         scheduler tie that flipped), `suffix` records of side-by-side
         ring context, and (optionally) a two-track Perfetto export.

    Deterministic: the same pair yields the same report, dispatch for
    dispatch (the --tt-smoke gate re-runs it and compares)."""
    if seed_b is None and knobs_b is None and nudge_b is None:
        raise ValueError("nothing to diverge: pass seed_b, knobs_b "
                         "and/or nudge_b")
    prt = rt if rt.cfg.sketch_slots > 0 else rt.derived(
        sketch_slots=int(sketch_slots))
    st = prt.run_fused(_pair_state(prt, seed_a, seed_b, knobs_b, nudge_b),
                       max_steps, chunk)
    fps = prt.fingerprints(st)
    verdicts = (_verdict_of(st, 0), _verdict_of(st, 1))
    probe = causal.sketch_divergence(st, 0, 1)
    every = probe["every"]
    steps_ab = np.asarray(st.steps).reshape(-1)
    diverged = (int(fps[0]) != int(fps[1])
                or probe["bound"] == "sketch-slot"
                or verdicts[0] != verdicts[1])
    out = dict(diverged=bool(diverged), probe=probe,
               fingerprints=(int(fps[0]), int(fps[1])),
               verdicts=verdicts,
               steps=(int(steps_ab[0]), int(steps_ab[1])))
    if not diverged:
        return out
    if probe["bound"] == "sketch-slot":
        window_start = probe["slot"] * every
        window_len = every + int(window_pad)
    else:
        window_start = 0
        window_len = int(min(max_steps, max(steps_ab))) + int(window_pad)
    # 2. window replay from the last common checkpoint, full tracing
    st2 = _pair_state(prt, seed_a, seed_b, knobs_b, nudge_b)
    if window_start:
        st2 = advance_exact(prt, st2, window_start, chunk)
    sig = prt.cfg.structural_signature()
    ck_a = checkpoint_lane(st2, 0, signature=sig)
    ck_b = checkpoint_lane(st2, 1, signature=sig)
    trt = prt.derived(trace_cap=max(16, window_len))
    sa = advance_exact(
        trt, seed_batch_from(ck_a, 1, rt=trt, reset_planes=("ring",)),
        window_len, chunk)
    sb = advance_exact(
        trt, seed_batch_from(ck_b, 1, rt=trt, reset_planes=("ring",)),
        window_len, chunk)
    ra, rb = ring_records(sa, 0), ring_records(sb, 0)
    ta, tb = _ring_token_rows(ra), _ring_token_rows(rb)
    n = min(len(ta), len(tb))
    first = None
    for i in range(n):
        if ta[i] != tb[i]:
            first = dict(index=i, step=int(np.asarray(ra["step"])[i]),
                         a=_rec_row(ra, i), b=_rec_row(rb, i),
                         kind="dispatch")
            break
    if first is None and len(ta) != len(tb):
        # schedules agree through the shorter window: the divergence IS
        # one lane halting (crash/halt) while the other dispatches on
        i = n
        longer, recs = ("a", ra) if len(ta) > len(tb) else ("b", rb)
        first = dict(index=i,
                     step=int(np.asarray(recs["step"])[i]),
                     a=_rec_row(ra, i) if longer == "a" else None,
                     b=_rec_row(rb, i) if longer == "b" else None,
                     kind="halt")
    lo = first["index"] if first is not None else 0
    out.update(
        window_start=int(ck_a.steps), window_len=int(window_len),
        bound=probe["bound"], slot=probe["slot"],
        first=first,
        suffix_a=[_rec_row(ra, i)
                  for i in range(lo, min(lo + int(suffix), len(ta)))],
        suffix_b=[_rec_row(rb, i)
                  for i in range(lo, min(lo + int(suffix), len(tb)))])
    if export_trace is not None:
        export_pair_trace(export_trace, sa, sb)
        out["trace_path"] = export_trace
    return out
