"""Sweep metrics: the SweepObserver callback protocol + JSONL sink.

Observers hook the host sync points the runners ALREADY pay for — the
per-chunk `halted.all()` test in `run()`/`run_compacting()`, the
per-round digest harvest in `explore()` — so attaching one adds no new
device round-trips; the only extra cost is reading lanes the host was
blocked on anyway. Record kinds (each a flat JSON-able dict carrying
`kind`):

  chunk    one scan chunk retired (run/run_compacting): steps_done,
           lanes_halted, wall-clock lane_steps_per_sec
  compact  run_compacting re-packed survivors: from_batch/to_batch/stashed
  round    one explore() round harvested: new_schedules, distinct_total,
           crashes — the per-round coverage growth off the existing
           on-device digest. fuzz() rounds arrive as kind="fuzz_round"
           with corpus_size/new_crash_codes, plus (r15) `admitted`,
           `op_yield` — the round's admissions attributed to the havoc
           operator that produced each admitted mutant ("base" =
           untouched lanes; the per-operator counts sum to `admitted`)
           — and `corpus_energy` (the scheduler's energy distribution:
           entries/total/mean/p50/p90/max/crash_entries), plus
           div_slot_p50 (the
           round's median first-divergence slot vs the consensus prefix)
           when the build compiles the prefix sketch in
           (cfg.sketch_slots > 0) — depth telemetry riding the sketch
           transfer the corpus already pays for. Builds with the SLO
           latency plane compiled in (cfg.latency_hist > 0, r16) add
           `lat_p99` (the round batch's merged end-to-end p99 estimate
           in ticks, bucket-CDF lower bound), `lat_p50`, and `slo_miss`
           (completions past the dynamic slo_target this round) — and
           run()'s `done` record carries the same three for plain
           sweeps. Mesh-sharded campaigns
           (search/shard.py) add shards (mesh width) and per_shard —
           one row per device shard: {shard, worker_id, corpus_size,
           coverage, new, crashes, seeds_run} — so renderers can show
           the mesh instead of collapsing it into one line
           (ProgressObserver prints one row per shard). A multi-process
           campaign driver (service/campaign.py) emits kind="campaign"
           rounds: uptime_s, workers_alive, corpus_entries,
           coverage_keys, buckets, schedules_per_sec, buckets_per_min —
           the campaign-level rollup polled from the shared corpus dir —
           and `supervise_campaign` emits kind="supervisor" segment
           records: segment, max_rounds, dead_workers, restarts, pruned
  compile  a runner retraced (= a fresh executable was built, modulo
           persistent-cache compile skips): label (chunk_runner /
           fused_runner / inject), batch, chunk. Fired by
           `compile.COMPILE_LOG` — attach an observer with
           `COMPILE_LOG.attach(obs)` to see WHERE a sweep's
           getting-to-execution time goes (the compile/ layer's split of
           trace/lower/compile stage seconds rides in
           `COMPILE_LOG.snapshot()`)
  done     sweep finished: totals

Dispatch is by attribute, so an observer overrides only the hooks it
cares about; exceptions in observer code propagate (a metrics layer that
silently eats its own bugs measures nothing).
"""

from __future__ import annotations

import json
import os
from typing import IO


class SweepObserver:
    """Base observer: every hook a no-op. Subclass and override."""

    def on_chunk(self, rec: dict) -> None:
        pass

    def on_compact(self, rec: dict) -> None:
        pass

    def on_round(self, rec: dict) -> None:
        pass

    def on_compile(self, rec: dict) -> None:
        pass

    def on_done(self, rec: dict) -> None:
        pass


class JsonlObserver(SweepObserver):
    """Write every record as one JSON line (the dashboard/ingest format).

    `sink` is a path (opened for append; close() or use as a context
    manager) or an open file-like object (caller owns its lifetime).
    Floats are rounded — these are metrics, not measurements to diff.

    Every record is flushed as written, so a SIGKILL'd process's log is
    complete up to its last record; `fsync=True` additionally fsyncs per
    record, extending that claim to power loss — campaign workers use
    it (service/worker.py): under `supervise_campaign` respawns the
    worker log is durable telemetry, and the r15 timeline trusts it.
    fsync needs a real file descriptor; sinks without `fileno()`
    (StringIO) raise at construction rather than silently not syncing.
    """

    def __init__(self, sink: str | IO[str], fsync: bool = False):
        self._own = isinstance(sink, str)
        self._f = open(sink, "a") if self._own else sink
        self._fsync = fsync
        if fsync:
            self._f.fileno()    # fail here, not at first record
        self.records: list[dict] = []

    def _emit(self, rec: dict) -> None:
        rec = {k: (round(v, 3) if isinstance(v, float) else v)
               for k, v in rec.items()}
        self.records.append(rec)
        self._f.write(json.dumps(rec) + "\n")
        self._f.flush()
        if self._fsync:
            os.fsync(self._f.fileno())

    on_chunk = on_compact = on_round = on_compile = on_done = _emit

    def close(self) -> None:
        if self._own:
            self._f.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class TeeObserver(SweepObserver):
    """Fan one sweep out to several observers (e.g. JSONL + progress)."""

    def __init__(self, *observers: SweepObserver):
        self.observers = observers

    def on_chunk(self, rec):
        for o in self.observers:
            o.on_chunk(rec)

    def on_compact(self, rec):
        for o in self.observers:
            o.on_compact(rec)

    def on_round(self, rec):
        for o in self.observers:
            o.on_round(rec)

    def on_compile(self, rec):
        for o in self.observers:
            o.on_compile(rec)

    def on_done(self, rec):
        for o in self.observers:
            o.on_done(rec)
