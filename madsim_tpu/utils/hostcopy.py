"""Owned host copies of device buffers — the donation-aliasing guard.

The PR-2 bug class this exists for: on the CPU backend `np.asarray` of a
device array can be ZERO-COPY — a view into the device buffer. If that
buffer is later DONATED (`donate_argnums`) to another executable, the
"stashed" view reads recycled memory. The failure is timing-dependent and
cache-dependent: it was first observed as 0x01010101 garbage lanes only
when the chunk executable came from the warm persistent compile cache,
whose buffer lifetimes differ from the fresh-compile path — so with the
shared `ProgramCache` and the persistent tier both live, every host-side
stash that outlives the next runner call MUST own its memory.

Rule (DESIGN §10): `np.asarray` is fine for values consumed before the
next jitted call on the same state (reductions, immediate reads);
anything held ACROSS a runner invocation — compaction stashes, ring
readers' returned columns, merge paths — goes through `owned_host_copy`.
"""

from __future__ import annotations

from typing import Any

import jax
import numpy as np


def owned_host_copy(tree: Any) -> Any:
    """Deep host copy of a pytree: every leaf becomes a numpy array that
    OWNS its memory (np.array(copy=True)) — safe to hold across later
    donated executions of the source buffers."""
    return jax.tree.map(lambda a: np.array(a, copy=True), tree)
