"""Mask utilities shared by the dual-world effect helpers.

The simulator's discipline is masked ops: every effect call executes with a
`when` mask so the traced program has static shape. Under jit that's free —
XLA sees one fused program. In the real-world runtime (real/runtime.py)
handlers run EAGERLY, where a masked no-op still costs a dispatch; with
protocol libraries doing W-wide window loops that adds up to tens of ms per
event. `statically_false(mask)` lets effect helpers skip work when the mask
is CONCRETELY all-False: tracers never short-circuit (simulation semantics
untouched), concrete falses cost one host check instead of a jnp op chain.
"""

from __future__ import annotations

import jax


def needed(mask) -> bool:
    """Guard for a masked block of handler logic: always True under
    tracing (the block is part of the compiled program), False eagerly
    when the mask is concretely all-False (skip the dead branch). Lets a
    protocol handler keep ONE code path while the real-world runtime pays
    only for the branch that actually fires."""
    return not statically_false(mask)


def statically_false(mask) -> bool:
    """True iff `mask` is a concrete (non-tracer) value that is all-False —
    i.e. this effect provably does nothing and may be skipped eagerly."""
    if isinstance(mask, jax.core.Tracer):
        return False
    if isinstance(mask, bool):
        return not mask
    try:
        import numpy as np

        return not bool(np.asarray(mask).any())
    except Exception:
        return False
