"""Payload word-layout helpers.

Messages are fixed int32 word vectors (the typed encoding replacing the
reference's `Box<dyn Any>` payloads, net/mod.rs:366 — see core/api.py
`as_payload`). Protocols read/write fixed positions; these helpers keep
those positions named and let non-integer values ride int32 words.

    L = Layout("term", "prev", "commit")
    ctx.send(dst, AE, L.pack(term=st["term"], prev=nxt, commit=c))
    ...
    term = payload[L.term]          # named index, still a plain int

Floats travel by BITCAST (not rounding): `f32_to_word` / `word_to_f32`.
"""

from __future__ import annotations

import jax.numpy as jnp


class Layout:
    """Named word positions for a payload. Attribute access returns the
    word index; `pack` builds the word list in declaration order."""

    def __init__(self, *names: str):
        assert len(set(names)) == len(names), f"duplicate fields: {names}"
        self._names = names
        for i, n in enumerate(names):
            assert not hasattr(self, n), f"reserved field name: {n}"
            setattr(self, n, i)

    @property
    def width(self) -> int:
        return len(self._names)

    def pack(self, **fields):
        """Word list in declaration order; missing fields are 0."""
        unknown = set(fields) - set(self._names)
        assert not unknown, f"unknown payload fields: {sorted(unknown)}"
        zero = jnp.asarray(0, jnp.int32)
        return [jnp.asarray(fields.get(n, zero), jnp.int32)
                for n in self._names]

    def unpack(self, payload):
        """dict of field -> word (positions beyond the payload are absent
        by construction: as_payload zero-pads to cfg.payload_words)."""
        return {n: payload[i] for i, n in enumerate(self._names)}


def f32_to_word(x):
    """Bitcast a float32 value into an int32 payload word (lossless)."""
    return jnp.asarray(x, jnp.float32).view(jnp.int32)


def word_to_f32(w):
    """Recover the float32 from its payload word."""
    return jnp.asarray(w, jnp.int32).view(jnp.float32)
