"""Recurring-timer semantics: tokio's MissedTickBehavior, state-machine
style (reference: sim/time/interval.rs:62-69).

In the state-machine world an interval is a self-rearming timer; what needs
parity is the policy when ticks are missed (node paused, event storm).
`next_tick` computes the next deadline given the tick that just fired:

  BURST: fire all missed ticks back-to-back (schedule at scheduled+period,
         even if that is already in the past — it fires immediately).
  DELAY: restart the cadence from now.
  SKIP:  jump to the next multiple of the period after now.

Usage in on_timer (payload carries the scheduled time):
    nxt = next_tick(ctx.now, payload[0], period, SKIP)
    ctx.set_timer(nxt - ctx.now, MY_TICK, [nxt], when=...)
"""

from __future__ import annotations

import jax.numpy as jnp

BURST, DELAY, SKIP = 0, 1, 2


def next_tick(now, scheduled, period, behavior: int):
    now = jnp.asarray(now, jnp.int32)
    scheduled = jnp.asarray(scheduled, jnp.int32)
    period = jnp.asarray(period, jnp.int32)
    burst = scheduled + period
    delay = now + period
    missed = jnp.maximum(now - scheduled, 0) // period + 1
    skip = scheduled + missed * period
    if behavior == BURST:
        return burst
    if behavior == DELAY:
        return delay
    return skip
