"""The run-twice fixed-point guard, shared by every replay-authoritative
path: `analyze.replay_race`, the campaign-resume verification in
`search.fuzz`/`search.shard`, and `service.replay_bucket`.

Rationale (ROADMAP r12 note): on this jaxlib, the FIRST invocation of a
fused executable deserialized from the persistent compile cache can
return a deterministic-but-wrong result under concurrent machine load;
a re-invocation of the same executable is always correct. A value that
something treats as replay-TRUTH must therefore not depend on that coin
flip: re-run until two CONSECUTIVE invocations agree. Three pairwise
distinct results are beyond the transient — that is real nondeterminism
and must raise, never be papered over. One implementation here, so the
agreement contract cannot drift between its call sites (the PR 7
addendum collapsed the knob-reapply copies into
`search.mutate.apply_repro_knobs` for the same reason).
"""

from __future__ import annotations


def agree_twice(first, again, key_of=lambda r: r, what: str = "replay",
                detail=None):
    """Return a result confirmed by two consecutive agreeing
    invocations.

    `first` is the already-computed first result; `again(first)`
    recomputes it (the callable may ignore its argument — it is handed
    the first result so callers can re-dispatch the same operands
    without re-closing over them). `key_of` projects a result onto the
    values that must agree (comparison keys, not e.g. device handles).
    On first==second, returns `first`; else a third invocation breaks
    the tie (third==second returns `second`). Three distinct results
    raise RuntimeError — `what` names the authority in the message and
    `detail(first, second, third)`, when given, appends specifics."""
    second = again(first)
    if key_of(second) == key_of(first):
        return first
    third = again(first)
    if key_of(third) != key_of(second):
        extra = f": {detail(first, second, third)}" if detail else ""
        raise RuntimeError(
            f"{what} does not replay deterministically — three "
            f"invocations disagree{extra}; this is beyond the known "
            "first-invocation compile-cache transient (ROADMAP r12 note)")
    return second
