"""Heartbeat failure detection as reusable state-machine helpers.

The reference ships NO failure detector — applications roll their own
heartbeats over the simulated network (SURVEY §5: "apps implement their
own heartbeats"). This module makes the pattern a component: fixed-shape
helpers a `Program` calls from its handlers, so any protocol gains a
timeout-based suspect list (the classic eventually-perfect-detector
construction: suspect after `timeout` of silence, rehabilitate on any
message) without hand-rolling the bookkeeping.

State contract — embed via `detector_state(n_nodes)` in the state spec:
  fd_last  int32[N]  virtual time a heartbeat/message was last seen from
                     each peer (self entry is refreshed by `beat`)
  fd_susp  int32[N]  1 while a peer is suspected

Usage inside a Program (see tests/test_detector.py for a full model):
    init:       `reset(st, ctx.now)` (boot grace period — also how a
                restarted node starts from silence, not t=0); arm a
                periodic FD_TICK timer; `beat(ctx)` broadcasts
    on_message: `saw(st, src, ctx.now)` on ANY message (heartbeats and
                protocol traffic both prove liveness)
    on_timer:   `st["fd_susp"] = suspects(st, ctx.now, timeout)`;
                re-arm; optionally react to flips (leader demotion etc.)

All helpers are masked tensor ops — they vectorize under vmap and cost a
few VPU instructions; no gathers.
"""

from __future__ import annotations

import jax.numpy as jnp

TAG_HEARTBEAT = (1 << 29) | 0x5EA7  # above the 29-bit service-tag space


def detector_state(n_nodes: int):
    """State-spec fragment: merge into the program's spec dict."""
    return dict(
        fd_last=jnp.zeros((n_nodes,), jnp.int32),
        fd_susp=jnp.zeros((n_nodes,), jnp.int32),
    )


def reset(st, now, *, when=True):
    """Boot/restart grace period: count every peer as just-seen at `now`.
    Call from `Program.init` — it also makes a RESTARTED node measure
    silence from its rebirth instead of suspecting the world because its
    zeroed memory says everyone was last seen at t=0."""
    st["fd_last"] = jnp.where(when, jnp.full_like(st["fd_last"], now),
                              st["fd_last"])
    st["fd_susp"] = jnp.where(when, jnp.zeros_like(st["fd_susp"]),
                              st["fd_susp"])
    return st


def saw(st, src, now, *, when=True):
    """Record proof of life from `src` at `now` (call on ANY message)."""
    n = st["fd_last"].shape[0]
    oh = jnp.arange(n, dtype=jnp.int32) == src
    st["fd_last"] = jnp.where(oh & when, jnp.maximum(st["fd_last"], now),
                              st["fd_last"])
    return st


def beat(ctx, n_nodes: int, *, when=True):
    """Broadcast a heartbeat to every peer (skips self)."""
    for d in range(n_nodes):
        ctx.send(d, TAG_HEARTBEAT, when=when & (ctx.node != d))


def suspects(st, now, timeout):
    """-> int32[N] suspicion mask: 1 where `timeout` has elapsed since a
    peer's last proof of life. Pure function of the recorded state, so
    callers can also compute hypotheticals (different timeouts) without
    extra bookkeeping. A node never suspects itself if it refreshed its
    own `fd_last` via `saw(st, ctx.node, now)` each tick."""
    return (now - st["fd_last"] > timeout).astype(jnp.int32)
