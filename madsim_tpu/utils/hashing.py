"""Per-trajectory state fingerprints.

madsim's nondeterminism detector logs `hash(rng_byte ^ time)` at every RNG
draw and compares across two same-seed runs (rand.rs:72-96,
runtime/mod.rs:144-187). Because our whole cluster state is one pytree of
tensors, the equivalent check is cheaper and stronger: fold every state leaf
into a 32-bit fingerprint per trajectory and compare across replays — any
divergence anywhere in the state is caught, not just RNG draw order.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

FNV_OFFSET = jnp.uint32(2166136261)
FNV_PRIME = jnp.uint32(16777619)


def _leaf_words(a: jax.Array) -> jax.Array:
    """View a leaf as a flat uint32 vector (value-stable encoding)."""
    if a.dtype == jnp.float32:
        w = lax.bitcast_convert_type(a, jnp.uint32)
    elif a.dtype in (jnp.uint32,):
        w = a
    else:
        w = a.astype(jnp.int32).astype(jnp.uint32)
    return w.reshape(-1)


from ..core.state import TRACE_FIELDS

# The recorder is an observation lever, not a replay domain: two lanes
# running identical trajectories must fingerprint equal whether or not
# one of them was sampled into the ring — otherwise partial
# `trace_lanes` sampling would split every trajectory class in
# `summarize()['distinct_outcomes']` and a sampled sweep's fingerprints
# would never match a replay's.
_OBSERVATION_FIELDS = frozenset(TRACE_FIELDS)


def fingerprint(state) -> jax.Array:
    """uint32 fingerprint of one trajectory's full state pytree —
    excluding the flight-recorder (observation-only) fields.

    vmap this for a batched state. Deterministic given identical values and
    identical pytree structure/shapes.
    """
    if hasattr(state, "trace_pos"):     # SimState: drop the recorder
        state = {k: getattr(state, k)
                 for k in type(state).__dataclass_fields__
                 if k not in _OBSERVATION_FIELDS}
    leaves = jax.tree.leaves(state)
    h = FNV_OFFSET
    for i, leaf in enumerate(leaves):
        w = _leaf_words(jnp.asarray(leaf))
        mix = jnp.arange(w.shape[0], dtype=jnp.uint32) * jnp.uint32(
            2654435761) + jnp.uint32(2 * i + 1)
        lh = jnp.sum(w * mix, dtype=jnp.uint32) if w.shape[0] else jnp.uint32(0)
        h = (h ^ lh) * FNV_PRIME
    return h


# ONE process-level jitted batched fingerprint, shared by every Runtime
# and by find_divergence: jax.jit caches by FUNCTION IDENTITY first, so
# the old per-call `jax.jit(jax.vmap(fingerprint))` retraced on every
# invocation — a compile per fingerprints() call. A module-level jit
# retraces only per state structure/shape (which is the granularity
# executables genuinely differ at).
_BATCH_FP = jax.jit(jax.vmap(fingerprint))


def batch_fingerprints(state) -> jax.Array:
    """uint32[B] fingerprints of a batched state (device array; callers
    np.asarray it). Shared compiled entry across all Runtimes."""
    return _BATCH_FP(state)
