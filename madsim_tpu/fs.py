"""Simulated per-node filesystem with sync-gated durability — the FsSim
analog (sim/fs.rs:154-246), power-fail semantics included.

The reference models files as in-memory buffers with `read_at /
write_all_at / set_len / sync_all`, and left "power failure" — losing
writes that were never synced — as a TODO (fs.rs:48-51). Here that
semantics falls out of the engine's stable-storage design: every file
exists twice,

  fs_mem  — the page-cache view: all writes land here; reads see them
  fs_disk — the durable view: updated ONLY by sync_all

and only `fs_disk`/`fs_dlen` go in the persist mask. A kill therefore
drops the memory view on the floor (the engine resets volatile leaves),
and `mount()` in the program's init restores it from disk — any write
that wasn't synced before the kill is GONE. That's a real power-fail
model, checked red/green by the WAL workload in models/wal_kv.py.

All helpers are masked/traceable; files are fixed [n_files, file_words]
int32 arrays per node (fixed shapes: the TPU discipline), addressed by
static or traced file ids and dynamic word offsets.
"""

from __future__ import annotations

import jax.numpy as jnp

__all__ = ["fs_state", "fs_persist", "mount", "read_at", "write_all_at",
           "set_len", "sync_all", "file_len"]


def fs_state(n_files: int, file_words: int):
    """State-schema fragment: merge into your Program's state_spec."""
    F, S = n_files, file_words
    return dict(
        fs_mem=jnp.zeros((F, S), jnp.int32),
        fs_mlen=jnp.zeros((F,), jnp.int32),
        fs_disk=jnp.zeros((F, S), jnp.int32),
        fs_dlen=jnp.zeros((F,), jnp.int32),
    )


def fs_persist():
    """Persist-mask fragment: ONLY the disk view survives kill/restart."""
    return dict(fs_mem=False, fs_mlen=False, fs_disk=True, fs_dlen=True)


def mount(st, *, when=True):
    """Rebuild the memory view from disk — call in Program.init. After a
    power-fail this is where unsynced writes are observably absent."""
    w = jnp.asarray(when)
    st["fs_mem"] = jnp.where(w, st["fs_disk"], st["fs_mem"])
    st["fs_mlen"] = jnp.where(w, st["fs_dlen"], st["fs_mlen"])


def file_len(st, f):
    """Current (memory-view) length in words (fs.rs metadata analog)."""
    return st["fs_mlen"][f]


def read_at(st, f, offset, width: int):
    """Read `width` words at `offset` (static width, dynamic offset) from
    the memory view — reads observe unsynced writes, as with a page cache
    (fs.rs:154-177). Words beyond the file length read as 0."""
    S = st["fs_mem"].shape[1]
    idx = jnp.asarray(offset, jnp.int32) + jnp.arange(width, dtype=jnp.int32)
    vals = st["fs_mem"][f, jnp.clip(idx, 0, S - 1)]
    return jnp.where((idx < st["fs_mlen"][f]) & (idx < S), vals, 0)


def write_all_at(st, f, offset, words, *, when=True):
    """Write a word vector at `offset` into the MEMORY view
    (fs.rs:179-207 write_all_at): durable only after sync_all. Returns the
    ok mask (False if the write would overrun the fixed file capacity —
    the disk-full analog)."""
    S = st["fs_mem"].shape[1]
    words = jnp.atleast_1d(jnp.asarray(words, jnp.int32))
    width = words.shape[0]
    offset = jnp.asarray(offset, jnp.int32)
    ok = jnp.asarray(when) & (offset >= 0) & (offset + width <= S)
    idx = jnp.clip(offset + jnp.arange(width, dtype=jnp.int32), 0, S - 1)
    st["fs_mem"] = st["fs_mem"].at[f, idx].set(
        jnp.where(ok, words, st["fs_mem"][f, idx]))
    st["fs_mlen"] = st["fs_mlen"].at[f].set(
        jnp.where(ok, jnp.maximum(st["fs_mlen"][f], offset + width),
                  st["fs_mlen"][f]))
    return ok


def set_len(st, f, new_len, *, when=True):
    """Truncate/extend the memory view (fs.rs:209-227 set_len): shrinking
    zeroes the dropped words, growing zero-fills — both only durable after
    sync_all."""
    S = st["fs_mem"].shape[1]
    new_len = jnp.clip(jnp.asarray(new_len, jnp.int32), 0, S)
    w = jnp.asarray(when)
    ks = jnp.arange(S, dtype=jnp.int32)
    st["fs_mem"] = st["fs_mem"].at[f].set(
        jnp.where(w & (ks >= new_len), 0, st["fs_mem"][f]))
    st["fs_mlen"] = st["fs_mlen"].at[f].set(
        jnp.where(w, new_len, st["fs_mlen"][f]))


def sync_all(st, f, *, when=True):
    """Flush file `f`: disk view := memory view (fs.rs:229-246 sync_all).
    The ONLY operation that makes writes survive a power-fail."""
    w = jnp.asarray(when)
    st["fs_disk"] = st["fs_disk"].at[f].set(
        jnp.where(w, st["fs_mem"][f], st["fs_disk"][f]))
    st["fs_dlen"] = st["fs_dlen"].at[f].set(
        jnp.where(w, st["fs_mlen"][f], st["fs_dlen"][f]))
