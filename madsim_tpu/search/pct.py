"""PCT-style priority perturbation over the event scheduler's tie-breaks.

PCT (probabilistic concurrency testing) derandomizes schedule search: pick
a small set of priority-change points and run the schedule those priorities
induce, instead of sampling uniformly. The batched analog here: the
scheduler's only free decision is the tie-break among earliest-deadline
events (core/step.py), and `SimState.prio_nudge` replaces that uniform
draw with a DETERMINISTIC priority order keyed on (nudge, slot identity).
One nudge value = one tie-breaking policy; sweeping nudges enumerates
policies the way PCT enumerates priority assignments — and because the
nudge is a per-lane dynamic operand, a whole batch of policies runs as one
dispatch with zero recompiles.

Contract (tested in tests/test_search.py): `prio_nudge == 0` is
bit-identical to the hook's absence — the uniform draw happens (and
consumes its key) either way, and the nudged pick only replaces it under a
`where` on the nudge. Nudged runs stay fully deterministic: same seed +
same nudge = same trajectory, so (seed, nudge) is a complete repro handle.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..parallel import stats


def with_prio_nudge(state, nudge):
    """Set the per-lane PCT nudge on a (batched) state. `nudge` is a
    scalar (applied to every lane) or an int32[B] array."""
    nudge = jnp.asarray(nudge, jnp.int32)
    return state.replace(
        prio_nudge=jnp.broadcast_to(nudge, state.prio_nudge.shape))


def pct_sweep(rt, seed: int, nudges, max_steps: int, chunk: int = 512,
              fused: bool = True, knobs: dict | None = None, plan=None):
    """Run ONE seed under many tie-break policies in one batch: lane i
    replays `seed` with prio_nudge = nudges[i]. The distinct-schedule
    count over the sweep measures how much of the seed's behavior was
    tie-break luck vs forced by timing.

    `knobs` (one lane's fuzz knob vector, with its KnobPlan) replays a
    MUTANT under the nudge sweep — the handle a fuzz crash repro or a
    race bucket carries; the knobs' own prio_nudge is overridden by the
    sweep per lane (that override IS the sweep). This is what
    `analyze.races.confirm_race` builds its forced-commute batch from.

    Returns a dict with per-lane u64 schedule hashes, the distinct count,
    and {nudge: crash_code} for lanes that crashed (each is replayable
    alone via the same (seed, [knobs,] nudge) handle)."""
    nudges = np.asarray(nudges, np.int32).reshape(-1)
    B = nudges.shape[0]
    state = rt.init_batch(np.full(B, seed, np.uint32))
    if knobs is not None:
        from .mutate import apply_repro_knobs
        state, plan = apply_repro_knobs(rt, state, knobs, plan)
    state = with_prio_nudge(state, nudges)
    if fused:
        state = rt.run_fused(state, max_steps, chunk)
    else:
        state, _ = rt.run(state, max_steps, chunk)
    hashes = stats.sched_hash_u64(state)
    crashed = np.asarray(state.crashed)
    codes = np.asarray(state.crash_code)
    return dict(
        hashes=hashes,
        distinct_schedules=int(len(np.unique(hashes))),
        crashed_by_nudge={int(nudges[i]): int(codes[i])
                          for i in np.nonzero(crashed)[0]},
    )
