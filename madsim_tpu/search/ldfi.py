"""Lineage-driven fault injection: aim the faults, don't spray them.

`fault_perturb` (mutate.py op 7) drifts fault values and toggles flags
BLIND — it has no idea which message ever mattered. The causal plane
already knows: a green lane's ring holds the exact (src → dst, instant)
message edges and (node, deadline) timer firings its success depended
on (`obs/support.py`). This module is the LDFI loop (Alvaro et al.)
over that knowledge, batched:

  1. POOL supports across green lanes (`SupportPool`) — each support is
     the edge set one successful trajectory needed.
  2. RANK cut candidates by a greedy minimal-hitting-set heuristic: the
     edge that appears in the most yet-uncovered supports is the edge
     whose loss the protocol has demonstrably not been tested against
     in the most distinct ways — cut it first.
  3. SYNTHESIZE targeted knob vectors: ordinary `KnobPlan` rows
     (OP_PARTITION_ONEWAY / OP_RESET_PEER / OP_SET_SKEW / OP_SET_DUP)
     whose times and targets come from the extracted edges.

Everything stays ON the knob plane (DESIGN §23): synthesis only writes
host knob dicts that `KnobPlan.apply` bounds-checks like any mutant —
times clip to [0, tlimit], out-of-pool targets fall back to
NODE_RANDOM, values clip to the row's own [lo, hi]. No new jitted
kernel exists here; a targeted round reuses the module-level
`apply_knobs` trace, so warm-cache campaigns add ZERO compile traces
(the acceptance gate in tests/test_ldfi.py).

A scenario can only be aimed where it has fault rows: synthesis maps a
"msg" candidate onto one-way-partition rows whose group mask the edge
actually crosses (direction from step.py: src bit 0 = which side's
sends vanish), then peer-reset / dup rows targeting the edge's
endpoints; a "timer" candidate onto clock-skew rows targeting the
timer's node, then peer-reset rows. A plan with none of these rows
yields no targeted vectors — `fuzz(ldfi=...)` then falls back to pure
havoc for the round (reported honestly via `targeted` counts).
"""

from __future__ import annotations

import dataclasses

import numpy as np

from ..core import types as T
from .mutate import KnobPlan

# fault ops synthesis may retime/retarget, in preference order per
# candidate kind (see module docstring)
_MSG_OPS = (T.OP_PARTITION_ONEWAY, T.OP_RESET_PEER, T.OP_SET_DUP)
_TIMER_OPS = (T.OP_SET_SKEW, T.OP_RESET_PEER)


@dataclasses.dataclass
class LdfiConfig:
    """Knobs of the lineage-driven arm of a fuzz campaign.

    witness: a `harness.success_witness` finder locating the green
      outcome's dispatch (None = a lane's last dispatch).
    frac: fraction of each round's batch given to targeted vectors
      (the rest stays havoc — LDFI aims, havoc keeps exploring).
    lanes: green supports harvested per round (extraction is a host
      walk per lane — bound it).
    max_cuts: edges cut per synthesized vector. 1 is the classic LDFI
      single-fault probe; 2 the default (fault pairs are where
      retry-masks-a-bug stories live).
    lead: ticks before an edge's instant the fault fires — the cut
      must be in force when the message would have flown.
    rank_cap: candidates kept from the hitting-set ranking.
    replay: upgrade wrapped-ring supports by t=0 window replay
      (full fidelity at replay cost; `obs.support.extract_support`).
    """

    witness: object = None
    frac: float = 0.25
    lanes: int = 8
    max_cuts: int = 2
    lead: int = 1_000
    rank_cap: int = 16
    replay: bool = False


def _candidates(sup: dict):
    """A support's cut-candidate keys: ("msg", src, dst) / ("timer",
    node, -1), each with the sim-time instant it was observed at.
    External sends (src < 0) are not cuttable edges."""
    for src, dst, now in sup["msg_edges"]:
        if src >= 0:
            yield ("msg", int(src), int(dst)), int(now)
    for node, now in sup["timer_edges"]:
        yield ("timer", int(node), -1), int(now)


class SupportPool:
    """Supports pooled across lanes (and, sharded, across shards): the
    input to the hitting-set ranking. Each added support becomes one
    row — the set of candidate keys that trajectory depended on; the
    pool also keeps every instant each candidate was observed at, so
    synthesis can aim at real times. `truncated` counts supports that
    were honest suffixes (wrapped rings) — their rows are lower bounds,
    which only ever UNDER-counts a candidate's coverage."""

    def __init__(self):
        self.rows: list[frozenset] = []
        self.times: dict[tuple, list[int]] = {}
        self.seed_of: dict[tuple, int] = {}
        self.truncated = 0

    def __len__(self) -> int:
        return len(self.rows)

    def add(self, sup: dict, seed: int | None = None) -> bool:
        """Fold one `extract_support` result in; False when the support
        had no cuttable edge (nothing for the ranking to see). `seed`
        is the green lane's seed: edge INSTANTS are seed-specific
        (another seed's protocol runs the same edges at different
        times), so synthesis pins each vector to the seed whose
        timing it was aimed at — the LDFI move is replaying the SAME
        run with the cut injected, not spraying the cut at a fresh
        one."""
        keys = set()
        for key, t in _candidates(sup):
            keys.add(key)
            self.times.setdefault(key, []).append(t)
            if seed is not None:
                self.seed_of.setdefault((key, t), int(seed))
        if not keys:
            return False
        self.rows.append(frozenset(keys))
        if sup.get("truncated"):
            self.truncated += 1
        return True

    def merge(self, other: "SupportPool") -> None:
        """Pool another shard's supports in (fuzz_sharded merge point)."""
        self.rows.extend(other.rows)
        for key, ts in other.times.items():
            self.times.setdefault(key, []).extend(ts)
        for kt, s in other.seed_of.items():
            self.seed_of.setdefault(kt, s)
        self.truncated += other.truncated

    def ranked(self, cap: int = 16) -> list[dict]:
        """Greedy minimal hitting set: repeatedly take the candidate
        covering the most yet-uncovered supports (deterministic
        tie-break on the key itself), then pad with the remaining
        candidates by total coverage — up to `cap` entries of
        {key, kind, a, b, times, hits}."""
        hit = {k: {i for i, row in enumerate(self.rows) if k in row}
               for k in self.times}
        uncovered = set(range(len(self.rows)))
        picked: list[tuple] = []
        while uncovered and len(picked) < cap:
            k = max(sorted(hit), key=lambda k: len(hit[k] & uncovered))
            if not hit[k] & uncovered:
                break
            picked.append(k)
            uncovered -= hit.pop(k)
        for k in sorted(hit, key=lambda k: (-len(hit[k]), k)):
            if len(picked) >= cap:
                break
            picked.append(k)
        return [dict(key=k, kind=k[0], a=k[1], b=k[2],
                     times=sorted(self.times[k]),
                     hits=len({i for i, row in enumerate(self.rows)
                               if k in row}))
                for k in picked]


def _rows_by_op(plan: KnobPlan) -> dict[int, list[int]]:
    ops = np.asarray(plan.base["op"])
    out: dict[int, list[int]] = {}
    for r in range(plan.R):
        if plan.time_ok[r]:
            out.setdefault(int(ops[r]), []).append(r)
    return out


def _in_group_a(plan: KnobPlan, r: int, node: int) -> bool:
    """Whether `node` is in a partition row's group-A bitmask (payload
    packs membership 31 nodes/word — step.py encoding)."""
    pay = plan.base["payload"][r]
    w = node // 31
    return w < len(pay) and bool((int(pay[w]) >> (node % 31)) & 1)


def _confine(plan: KnobPlan, r: int, node: int) -> int:
    """Pool confinement at SYNTHESIS time (apply would catch it anyway,
    but falling back early keeps the vector honest about its target):
    an out-of-pool node becomes NODE_RANDOM."""
    if 0 <= node < plan.N and plan.pool_ok[r, node + 1]:
        return int(node)
    return T.NODE_RANDOM


def _retime_heal(plan: KnobPlan, kn: dict, r: int, when: int,
                 used: set) -> None:
    """Drag a cut row's paired OP_HEAL along, preserving the outage
    DURATION. A re-aimed partition whose scenario heal stays at its
    original (now far-future) instant degenerates into a permanent
    cut — and a permanently unreachable node makes protocols abort
    CLEANLY instead of exposing torn state: the oracle that would
    catch the inconsistency can never observe it (measured on the
    Percolator-lite flagship: 0/88 support-aimed permanent cuts
    crash, 13/88 crash once the heal rides along). Pairing rule: the
    nearest time-mutable OP_HEAL row at base time >= the cut row's
    base time; its base delta is the duration kept. Two cuts sharing
    one heal keep the LATER proposed heal (both outages stay open at
    least as long as the shorter one intended)."""
    ops = np.asarray(plan.base["op"])
    times = np.asarray(plan.base["time"])
    base_t = int(times[r])
    best, best_dt = -1, None
    for hr in range(plan.R):
        if int(ops[hr]) != T.OP_HEAL or not plan.time_ok[hr]:
            continue
        dt = int(times[hr]) - base_t
        if dt >= 0 and (best_dt is None or dt < best_dt):
            best, best_dt = hr, dt
    if best < 0:
        return
    new_t = np.int32(int(when) + best_dt)
    if best in used:
        new_t = max(np.int32(kn["row_time"][best]), new_t)
    kn["row_time"][best] = new_t
    kn["row_on"][best] = True
    used.add(best)


def _cut(plan: KnobPlan, kn: dict, cand: dict, t: int, lead: int,
         used: set) -> bool:
    """Aim one unused fault row of `kn` at candidate `cand` around
    instant `t`. Returns False when no row of this plan can express
    the cut (no matching fault op, or a one-way mask the edge does
    not cross)."""
    when = np.int32(max(0, int(t) - int(lead)))
    ops = _MSG_OPS if cand["kind"] == "msg" else _TIMER_OPS
    by_op = cand["_rows_by_op"]
    for op in ops:
        for r in by_op.get(int(op), []):
            if r in used:
                continue
            if op == T.OP_PARTITION_ONEWAY:
                # direction: src bit 0 = 0 cuts A -> not-A, 1 the
                # reverse (step.py) — usable only when the edge
                # actually crosses the row's group mask
                a_src = _in_group_a(plan, r, cand["a"])
                a_dst = _in_group_a(plan, r, cand["b"])
                if a_src == a_dst:
                    continue
                kn["row_flag"][r] = np.int32(0 if a_src else 1)
            elif op == T.OP_RESET_PEER:
                node = cand["b"] if cand["kind"] == "msg" else cand["a"]
                kn["row_node"][r] = np.int32(_confine(plan, r, node))
            elif op == T.OP_SET_DUP:
                kn["row_node"][r] = np.int32(_confine(plan, r, cand["a"]))
                kn["row_val"][r] = np.int32(
                    min(int(plan.val_hi[r]), T.DUP_RATE_CAP * 2 // 3))
            elif op == T.OP_SET_SKEW:
                kn["row_node"][r] = np.int32(_confine(plan, r, cand["a"]))
                # shove the clock hard in one direction; alternate sign
                # by instant so repeated cuts probe both skews
                sign = 1 if (t & 1) == 0 else -1
                kn["row_val"][r] = np.int32(sign * int(plan.val_hi[r]))
            kn["row_time"][r] = when
            kn["row_on"][r] = True
            used.add(r)
            if op == T.OP_PARTITION_ONEWAY:
                _retime_heal(plan, kn, r, int(when), used)
            return True
    return False


def synthesize(plan: KnobPlan, pool: SupportPool, n: int, *,
               max_cuts: int = 2, lead: int = 1_000,
               rank_cap: int = 16, with_seeds: bool = False):
    """Compile the pool's ranked candidates into `n` targeted knob
    vectors (host dicts off `plan.base_knobs()`): vector i cuts up to
    `max_cuts` candidates starting at rank i (wrapping), each at an
    observed instant minus `lead` — so the batch walks the ranking
    while every vector stays a legal mutant. Deterministic: same pool,
    same plan, same vectors. Returns [] when the pool is empty or the
    plan has no row that can express any candidate.

    with_seeds=True returns `(vectors, seeds)` where seeds[i] is the
    green seed whose timing vector i's FIRST cut was aimed at (None
    when the pool never learned one) — the drivers pin the targeted
    lane to that seed so the cut lands in the trajectory it was
    extracted from."""
    cands = pool.ranked(rank_cap)
    if not cands or n <= 0:
        return ([], []) if with_seeds else []
    by_op = _rows_by_op(plan)
    for c in cands:
        c["_rows_by_op"] = by_op
    out = []
    seeds: list[int | None] = []
    for i in range(int(n)):
        kn = plan.base_knobs()
        used: set[int] = set()
        cuts = 0
        pin = None
        for j in range(len(cands)):
            if cuts >= max_cuts:
                break
            cand = cands[(i + j) % len(cands)]
            ts = cand["times"]
            t = ts[(i // max(1, len(cands))) % len(ts)]
            if _cut(plan, kn, cand, t, lead, used):
                if pin is None:
                    pin = pool.seed_of.get((cand["key"], t))
                cuts += 1
        if cuts:
            out.append(kn)
            seeds.append(pin)
    for c in cands:
        del c["_rows_by_op"]
    return (out, seeds) if with_seeds else out
