"""The coverage-guided fuzz loop: mutate -> run -> evaluate, pipelined.

`explore()` (parallel/explore.py) samples the schedule space blindly —
fresh seeds, one fixed fault script. This driver SEARCHES it: every round
schedules parents from the corpus (energy-weighted), derives a batch of
mutants on device (search/mutate.py — zero recompiles, knobs are traced
operands), runs them as one fused dispatch, and admits lanes that reached
never-seen `sched_hash` coverage back into the corpus. Loop-until-dry,
exactly like explore(): the sweep stops when `dry_rounds` consecutive
rounds add no new schedule.

Pipelining (the Podracer discipline, PAPERS.md, same shape as explore()):
round r+1's mutate+init+run is DISPATCHED before the host blocks on round
r's harvest, so corpus bookkeeping overlaps device compute. The price is
one round of corpus staleness — round r+1's parents are scheduled from
the corpus as of round r-1 — which only delays (never loses) coverage
feedback; `pipeline=False` restores the fully-serial AFL loop.

Crashes are harvested, never aborted on: every distinct crash code keeps
its first full repro handle — (seed, knob vector) — because a mutated
lane's behavior is NOT reproducible from the seed alone. `minimize=True`
auto-shrinks each repro's fault rows through `harness.minimize`
(batched ddmin, knob domain — no slot-layout verification gap).

Durable campaigns (r11, `corpus_dir=`): the corpus, the cross-round
consensus sketch, and every crash repro live in a `service.CorpusStore`
directory, synced at round boundaries. A killed campaign resumes from
its last sync and — because everything between syncs is re-derived from
(restored rng state, restored corpus, deterministic seeds) — converges
to exactly the run that was never killed. Crashed lanes are additionally
deduped into causal-fingerprint buckets (service/buckets.py). The price
of the durability contract is that the speculative pipeline is disabled
(round r+1's parents must be scheduled AFTER round r's sync point, or
the persisted rng state could not replay the schedule draw); campaign
throughput instead comes from multiple worker processes sharing the dir
(service/campaign.py — the Podracer split: many cheap actors, one
durable store).
"""

from __future__ import annotations

import time

import jax
import numpy as np

from ..parallel import stats
from .corpus import Corpus, YIELD_NAMES
from .mutate import N_MUT_OPS, OP_NAMES, KnobPlan

# seed-space stride between workers sharing a corpus dir: worker w's round
# r runs seeds [base + w*STRIDE + r*batch, ...) mod 2^32. Campaigns stay
# collision-free while rounds*batch < STRIDE (2^26 ≈ 67M seeds per worker)
# and worker_id < 64 per base_seed (the uint32 seed space holds 64
# strides; the 2^23-worker ID namespace is a separate, wider contract —
# shard bigger fleets across base_seeds).
WORKER_SEED_STRIDE = 1 << 26


def _lat_fields(lat_brief: dict) -> dict:
    """The latency slice of a fuzz-round / done / metrics record
    (obs/metrics.py schema) — ONE definition, so the round records and
    the durable timeline rows can't silently diverge (the
    apply_repro_knobs precedent). `search.shard` imports it too."""
    return dict(lat_p50=lat_brief["e2e_p50"],
                lat_p99=lat_brief["e2e_p99"],
                slo_miss=lat_brief["slo_miss"],
                slo_target=lat_brief.get("slo_target", 0))


def _env_verify_resume() -> bool:
    """Default for the run-twice resume guard when the caller passed
    None: MADSIM_FUZZ_VERIFY_RESUME=1 turns it on fleet-wide (CI and
    the campaign smokes set it) without touching call sites."""
    import os
    return os.environ.get("MADSIM_FUZZ_VERIFY_RESUME", "") not in ("", "0")


def fuzz(rt, max_steps: int, batch: int = 512, max_rounds: int = 16,
         dry_rounds: int = 3, base_seed: int = 0, chunk: int = 512,
         pipeline: bool = True, fused: bool = True, dup_slots: int = 2,
         havoc: int = 3, fresh_frac: float = 0.125, rng_seed: int = 0,
         observer=None, minimize: bool = False, corpus: Corpus | None = None,
         div_bonus: float | None = None, lat_bonus: float | None = None,
         burst_bonus: float | None = None,
         corpus_dir: str | None = None,
         worker_id: int = 0, sync_every: int = 1,
         verify_resume: bool | None = None, ldfi=None):
    """Coverage-guided schedule fuzzing over `rt`'s dynamic fault knobs.

    Round 0 is a blind bootstrap (base knobs, fresh seeds — one explore()
    round) that seeds the corpus; rounds 1.. run mutants. Every lane gets
    a FRESH seed (seed randomness and knob search compose: the knob vector
    moves the fault model, the seed moves the tie-breaks/timeouts within
    it), so a repro is always the (seed, knobs) pair.

    Args beyond explore()'s: dup_slots (spare event rows for the
    row-duplicate operator), havoc (stacked mutations per lane), fresh_frac
    (exploration floor of unmutated lanes per round), rng_seed (corpus
    scheduling + mutation randomness — the whole campaign is replayable),
    minimize (auto-shrink each crash repro's fault rows), corpus (pass a
    prior campaign's corpus to continue it), div_bonus (early-divergence
    admission-energy bonus when the runtime compiles the prefix sketch
    in, cfg.sketch_slots > 0 — see search/corpus.py; 0 restores
    sched_hash-only energy, a sketchless build is always hash-only
    regardless, and None keeps the corpus's setting — the default 1.0
    for a fresh corpus, whatever a passed-in `corpus` was built with),
    lat_bonus (OPT-IN tail-latency admission bonus when the runtime
    compiles the latency plane in, cfg.latency_hist > 0 — admissions
    whose lane's own e2e p99 sits at the round's worst tail get up to
    x(1+lat_bonus) energy, so the fuzzer hunts TAIL AMPLIFICATION; the
    default None/0.0 keeps energy latency-blind, same None-keeps-
    corpus-setting contract as div_bonus), burst_bonus (OPT-IN
    transient-spike admission bonus when the runtime compiles the
    windowed series plane in, cfg.series_windows > 0 — admissions are
    scored by each lane's DEEPEST per-window spike
    (parallel.stats.lane_burst: worst per-window p99, or queue
    high-water without the latency plane), so a mutant that digs one
    deep transient hole outscores one that is merely uniformly slow —
    the admission shape that feeds `recovery_invariant` campaigns;
    same None-keeps-corpus-setting contract).

    Durable-campaign args (corpus_dir is the switch):
      corpus_dir   a service.CorpusStore directory (created on first
                   use, signature-checked on reopen). `max_rounds`
                   becomes the CAMPAIGN total: a resumed call runs only
                   the remaining rounds and returns immediately once
                   rounds_done >= max_rounds or the persisted dry count
                   saturated. With corpus_dir set, `distinct_schedules`
                   reports the campaign's cumulative coverage as seen by
                   this worker (resumes and cross-worker merges fold in).
      worker_id    this process's namespace: entry ids, seed space
                   (WORKER_SEED_STRIDE apart), and state/log file names.
                   Give every concurrent worker on one dir a distinct id.
      sync_every   rounds between durability points (1 = every round).
                   A SIGKILL loses at most the work since the last sync,
                   and the resumed run re-derives it bit-identically.
      verify_resume  run-twice guard (r13, knob-gated; None reads
                   MADSIM_FUZZ_VERIFY_RESUME, default off) on the FIRST
                   round after a resume — exactly the deserialized-
                   executable invocation where this jaxlib's persistent
                   compile cache can return a deterministic-but-wrong
                   result under load (ROADMAP r12 note). The round's
                   (seeds, knobs) batch is re-dispatched until two
                   consecutive invocations agree on (hashes, crashed,
                   codes, sketches), mirroring analyze.replay_race's
                   contract; three distinct results raise. Resume
                   equality is replay-authoritative — a corrupted first
                   invocation would fork the campaign from the run that
                   was never killed.

    ldfi (r22, DESIGN §23): a `search.ldfi.LdfiConfig` turns on the
    lineage-driven arm — green lanes' success supports are extracted
    from their rings (`obs/support.py`, needs cfg.trace_cap > 0; the
    witness is `ldfi.witness`), pooled across lanes, and each round
    after bootstrap gives the LAST `ldfi.frac` of its batch to
    synthesized targeted vectors (ordinary knob rows — apply/minimize/
    replay/buckets all work unchanged) while the rest stays havoc.
    Targeted lanes are a distinct corpus arm: admitted entries carry
    `origin="targeted"` (additive store field), bucket records an
    `origin`, round records and worker state a `targeted_yield`
    counter. The speculative pipeline is disabled (round r+1's
    synthesis needs round r's rings — the durable-store rationale);
    ldfi=None is the pre-r22 fuzzer bit for bit, stores included.

    observer: obs.metrics.SweepObserver — `on_round` records of kind
    "fuzz_round" (explore's round schema + corpus_size/new_crash_codes),
    `on_done` with the final result; hooks ride the harvest the loop
    already blocks on.

    Returns a dict — explore()'s schema (seeds_run/rounds/
    distinct_schedules/new_per_round/saturated/crashes/
    crash_first_seed_by_code — that key keeps explore()'s contract of
    SEED-ALONE repro handles, so it only records crashes from unmutated
    bootstrap lanes; a crash first seen on a mutated lane appears only in
    crash_repros, whose (seed, knobs) pair is its real handle) plus:
      crash_repros      {code: {seed, round, knobs, script}} full handles
      corpus_size       corpus entries at the end
      mutation_ops      {operator name: times applied}
      minimized         {code: minimize_knobs info} when minimize=True
      targeted          (ldfi runs only) {supports, truncated_supports,
                        lanes_run, admitted} — the lineage arm's ledger
    """
    plan = KnobPlan.from_runtime(rt, dup_slots=dup_slots)
    pool = None
    targeted_total = 0
    targeted_yield_total = 0
    if ldfi is not None:
        if rt.cfg.trace_cap <= 0:
            raise ValueError(
                "fuzz(ldfi=...) needs the flight recorder compiled in "
                "(cfg.trace_cap > 0): support extraction walks lineage "
                "rings — there is nothing to aim without them")
        from ..obs.support import extract_support
        from .ldfi import SupportPool, synthesize
        pool = SupportPool()
    op_hist = np.zeros(N_MUT_OPS, np.int64)
    # cumulative coverage-YIELD attribution (vs op_hist's application
    # counts): admissions credited to the admitted lane's last applied
    # operator, "+1" slot = base/untouched lanes (search/corpus.py)
    yield_hist = np.zeros(N_MUT_OPS + 1, np.int64)
    if verify_resume is None:
        verify_resume = _env_verify_resume()
    store = buckets = None
    round_start = 0
    dry = 0
    wall_prior = 0.0
    if corpus_dir is not None:
        from ..service.buckets import CrashBuckets
        from ..service.store import CorpusStore, store_signature
        store = CorpusStore(corpus_dir,
                            signature=store_signature(rt, plan))
        # the r13 shard↔worker mapping numerically overlaps plain
        # worker ids — refuse a namespace a shard GROUP's state already
        # claims (see CorpusStore.claimed_namespaces / DESIGN §15)
        owner = store.claimed_namespaces().get(worker_id)
        if owner is not None and owner != f"worker w{worker_id}":
            from ..service.store import StoreMismatch
            raise StoreMismatch(
                f"worker namespace {worker_id} is already owned by "
                f"{owner} in this corpus dir — a mesh-sharded group's "
                "shards occupy worker_id*shards+s; pick a worker_id "
                "outside every group's range (DESIGN §15)")
        buckets = CrashBuckets(store)
        # the triage plane's read side needs the scenario row table to
        # attribute coverage/buckets to recipe families without a
        # Runtime (service/triage.py); write-once, identical bytes
        # from every worker
        store.write_triage_rows(plan)
        if corpus is None:
            corpus = store.load_corpus(
                plan, worker_id=worker_id, rng_seed=rng_seed,
                fresh_frac=fresh_frac,
                div_bonus=1.0 if div_bonus is None else div_bonus,
                lat_bonus=0.0 if lat_bonus is None else lat_bonus,
                burst_bonus=0.0 if burst_bonus is None else burst_bonus)
        else:
            if corpus.worker_id != worker_id:
                # a mismatched namespace would persist a worker state
                # whose entry order points at files sync never writes —
                # an unresumable store; fail before touching the dir
                raise ValueError(
                    f"corpus.worker_id={corpus.worker_id} != "
                    f"fuzz(worker_id={worker_id}): a durable campaign's "
                    "corpus must mint ids in its worker's namespace "
                    "(build it with Corpus(..., worker_id=) or let "
                    "fuzz load it from the store)")
            corpus.track_evictions = True
        ws = store.load_worker_state(worker_id)
        round_start = int(ws.get("rounds_done", 0))
        dry = int(ws.get("dry", 0))
        wall_prior = float(ws.get("wall_s", 0.0))
        if ws.get("op_hist"):
            op_hist[:] = np.asarray(ws["op_hist"], np.int64)
        if ws.get("op_yield"):
            yield_hist[:] = np.asarray(ws["op_yield"], np.int64)
        if ws.get("targeted_yield") is not None and ldfi is not None:
            # the support pool itself is NOT persisted — a resumed ldfi
            # campaign re-harvests green supports (cheap, a few host
            # walks); only the cumulative admission ledger survives
            targeted_yield_total = int(ws["targeted_yield"])
    if corpus is None:
        corpus = Corpus(plan, rng=np.random.default_rng(rng_seed),
                        fresh_frac=fresh_frac,
                        div_bonus=1.0 if div_bonus is None else div_bonus,
                        lat_bonus=0.0 if lat_bonus is None else lat_bonus,
                        burst_bonus=(0.0 if burst_bonus is None
                                     else burst_bonus))
    else:
        # an explicit div_bonus/lat_bonus/burst_bonus must win over a
        # passed-in corpus's setting — silently keeping the old value
        # would skew any with-vs-without energy comparison run through
        # these args
        if div_bonus is not None:
            corpus.div_bonus = float(div_bonus)
        if lat_bonus is not None:
            corpus.lat_bonus = float(lat_bonus)
        if burst_bonus is not None:
            corpus.burst_bonus = float(burst_bonus)
    master = jax.random.PRNGKey(np.uint32(rng_seed ^ 0x5EED5EED))

    def launch(r):
        """Schedule + mutate + dispatch one round without blocking on
        results (run_fused and the knob kernels are all async)."""
        # explicit mod-2^32 arithmetic: a large worker_id/base_seed wraps
        # deterministically on every numpy instead of overflowing arange
        lane0 = (base_seed + worker_id * WORKER_SEED_STRIDE
                 + r * batch) % (1 << 32)
        seeds = (np.arange(batch, dtype=np.uint64)
                 + np.uint64(lane0)).astype(np.uint32)
        targeted = np.zeros(batch, bool)
        if r == 0 or len(corpus) == 0:
            knobs_dev = {k: v for k, v in plan.base_batch(batch).items()}
            ids = np.full(batch, -1, np.int64)
            hist = None
            last_op = np.full(batch, -1, np.int64)
        else:
            parents, ids = corpus.schedule(batch)
            key = jax.random.fold_in(master, np.uint32(r))
            tvecs, tseeds = [], []
            if pool is not None and len(pool):
                tvecs, tseeds = synthesize(
                    plan, pool, min(batch, max(1, int(batch * ldfi.frac))),
                    max_cuts=ldfi.max_cuts, lead=ldfi.lead,
                    rank_cap=ldfi.rank_cap, with_seeds=True)
            if tvecs:
                # the lineage arm: targeted vectors ride the LAST T
                # lanes. The masked mutate (the shard driver's kernel —
                # module-level jit, traced once per shape) leaves those
                # lanes' parents untouched so the havoc histogram and
                # last-op attribution count ONLY real mutants; the
                # synthesized rows then overwrite them host-side and
                # plan.apply bounds-checks them like any mutant — zero
                # new compiled programs for a targeted round
                tn = len(tvecs)
                mask = np.ones(batch, bool)
                mask[batch - tn:] = False
                knobs_dev, hist, last_op = plan.mutate_masked(
                    parents, key, mask, havoc=havoc)
                knobs_host = {k: np.asarray(v).copy()
                              for k, v in knobs_dev.items()}
                tb = KnobPlan.stack(tvecs)
                for k in knobs_host:
                    knobs_host[k][batch - tn:] = tb[k]
                knobs_dev = knobs_host
                ids = ids.copy()
                ids[batch - tn:] = -1     # no havoc parent to reward
                targeted[batch - tn:] = True
                # pin each targeted lane to the green seed its cut was
                # aimed at: edge instants are seed-specific, so the cut
                # only lands inside the trajectory it was extracted from
                for j, ts_seed in enumerate(tseeds):
                    if ts_seed is not None:
                        seeds[batch - tn + j] = np.uint32(ts_seed)
            else:
                knobs_dev, hist, last_op = plan.mutate(parents, key,
                                                       havoc=havoc)
        state = plan.apply(rt.init_batch(seeds), knobs_dev)
        if fused:
            state = rt.run_fused(state, max_steps, chunk)
        else:
            state, _ = rt.run(state, max_steps, chunk)
        return seeds, ids, knobs_dev, hist, last_op, targeted, state

    def harvest(launched):
        """Block on one round. Transfers the [B] hash/crash lanes plus
        the knob batch (kilobytes — the corpus needs per-lane
        attribution, unlike explore()'s O(distinct) digest) and, when
        the build compiles the prefix sketch in, the [B, S] sketch
        batch (also kilobytes — the divergence-depth signal)."""
        seeds, ids, knobs_dev, hist, last_op, targeted, state = launched
        knobs_host = {k: np.asarray(v) for k, v in knobs_dev.items()}
        hashes = stats.sched_hash_u64(state)
        sk = np.asarray(state.cov_sketch)
        sketches = sk if sk.ndim == 2 and sk.shape[1] > 0 else None
        # tail-latency signal (r16): per-lane e2e p99 for corpus energy
        # + the round's merged brief for telemetry — None on builds
        # without the latency plane (one [B] + one O(buckets)
        # transfer); the brief only when something will consume it
        lat_p99 = stats.lane_e2e_p99(state)
        lat_brief = (stats.latency_brief(state)
                     if lat_p99 is not None
                     and (observer is not None or store is not None)
                     else None)
        # transient-spike signal (r21): per-lane deepest per-window
        # spike for corpus energy — None on builds without the series
        # plane (one [B] transfer)
        burst = stats.lane_burst(state)
        if hist is not None:
            op_hist[:] += np.asarray(hist)
        return (seeds, ids, knobs_host, hashes,
                np.asarray(state.crashed), np.asarray(state.crash_code),
                hist is not None, np.asarray(last_op), sketches, state,
                lat_p99, lat_brief, burst, targeted)

    def verified(harvested):
        """The run-twice resume guard (verify_resume): re-dispatch the
        SAME (seeds, knobs) batch — the knob batch is never donated —
        until two consecutive invocations agree on the authoritative
        outputs (utils.verify.agree_twice: contains the persistent-
        cache first-invocation corruption, raises on real
        nondeterminism)."""
        from ..utils.verify import agree_twice

        def key_of(h):
            hashes, crashed, codes, sketches, lat_p99, burst = \
                h[3], h[4], h[5], h[8], h[10], h[12]
            return (hashes.tobytes(), crashed.tobytes(), codes.tobytes(),
                    None if sketches is None else sketches.tobytes(),
                    None if lat_p99 is None else lat_p99.tobytes(),
                    None if burst is None else burst.tobytes())

        def again(prev):
            seeds, ids, knobs_host = prev[0], prev[1], prev[2]
            mutated, last_op = prev[6], prev[7]
            state = plan.apply(rt.init_batch(seeds), knobs_host)
            if fused:
                state = rt.run_fused(state, max_steps, chunk)
            else:
                state, _ = rt.run(state, max_steps, chunk)
            return harvest((seeds, ids, knobs_host,
                            None if not mutated else
                            np.zeros(N_MUT_OPS, np.int64), last_op,
                            prev[13], state))

        return agree_twice(harvested, again, key_of,
                           what="first post-resume campaign round")

    # under a durable store, `seen` starts at the campaign's cumulative
    # coverage (this worker's view) so dry-detection and the distinct
    # count continue across resumes instead of restarting from zero
    seen: set[int] = corpus.coverage_keys() if store is not None else set()
    crashes: dict[int, int] = {}
    repros: dict[int, dict] = {}
    opened_buckets: list[str] = []
    n_crashed = 0
    new_per_round: list[int] = []
    rounds = 0
    # the speculative pipeline schedules round r+1's parents BEFORE round
    # r's harvest; a durable campaign must schedule AFTER the sync point
    # (or the persisted rng state couldn't replay the draw), so the store
    # forces the serial loop — multi-worker campaigns restore the overlap
    speculate = pipeline and fused and store is None and ldfi is None
    t0 = time.perf_counter()
    pending = (launch(round_start)
               if round_start < max_rounds and dry < dry_rounds else None)
    verify_round = (round_start if verify_resume and store is not None
                    and round_start > 0 else None)
    for r in range(round_start, max_rounds):
        if pending is None:
            break
        nxt = (launch(r + 1) if speculate and r + 1 < max_rounds else None)
        harvested = harvest(pending)
        if r == verify_round:
            harvested = verified(harvested)
        (seeds, ids, knobs_host, hashes, crashed, codes, mutated,
         last_op, sketches, state, lat_p99, lat_brief, burst,
         targeted) = harvested
        rounds += 1
        cstats = corpus.observe(knobs_host, seeds, hashes, crashed, codes,
                                ids, r, sketches=sketches,
                                last_op=last_op, lat_p99=lat_p99,
                                burst=burst,
                                origin=targeted if ldfi is not None
                                else None)
        yield_hist[:] += cstats["op_yield"]
        if ldfi is not None:
            targeted_total += int(targeted.sum())
            targeted_yield_total += int(cstats.get("targeted_yield", 0))
            if len(pool) < ldfi.lanes:
                # harvest green supports: UNMUTATED lanes (bootstrap or
                # havoc no-ops; last_op < 0, not targeted) that did not
                # crash — the undisturbed trajectories whose success
                # support is worth cutting. Bounded: the pool stops
                # growing at ldfi.lanes supports, so the per-lane host
                # walks are a one-time cost, not a per-round tax
                for i in range(len(seeds)):
                    if len(pool) >= ldfi.lanes:
                        break
                    if (bool(crashed[i]) or int(last_op[i]) >= 0
                            or bool(targeted[i])):
                        continue
                    sup = extract_support(
                        state, int(i), witness=ldfi.witness,
                        replay=ldfi.replay, rt=rt, seed=int(seeds[i]),
                        knobs=KnobPlan.lane(knobs_host, int(i)))
                    if sup is not None:
                        pool.add(sup, seed=int(seeds[i]))
        for i in np.nonzero(crashed)[0]:
            c = int(codes[i])
            if not mutated:     # seed-alone handles: bootstrap lanes only
                crashes.setdefault(c, int(seeds[i]))
            if c not in repros:
                kn = KnobPlan.lane(knobs_host, int(i))
                repros[c] = dict(seed=int(seeds[i]), round=r, knobs=kn,
                                 script=plan.to_scenario(kn).describe())
        if buckets is not None and crashed.any():
            # dedup crashes into causal-fingerprint buckets: one
            # representative lane per distinct (crash code, origin) per
            # round keeps the host-side explain work bounded (the chain
            # walk is O(trace_cap) per lane; codes, not lanes, are the
            # cheap first partition — the fingerprint then splits bugs
            # sharing a code across rounds). The origin axis matters:
            # targeted lanes ride the batch TAIL, so a code-only dedup
            # would always hand representation to an earlier havoc lane
            # and the targeted arm could never open a bucket it earned
            coded: set[tuple] = set()
            for i in np.nonzero(crashed)[0]:
                c = (int(codes[i]),
                     bool(targeted[int(i)]) if ldfi is not None else False)
                if c in coded:
                    continue
                coded.add(c)
                key, opened = buckets.observe_lane(
                    state, int(i), seed=int(seeds[i]),
                    knobs=KnobPlan.lane(knobs_host, int(i)),
                    round_no=r, worker_id=worker_id,
                    last_op=int(last_op[int(i)]),
                    origin=(("targeted" if targeted[int(i)] else "havoc")
                            if ldfi is not None else None))
                if opened:
                    opened_buckets.append(key)
        n_crashed += int(crashed.sum())
        fresh = set(hashes.tolist()) - seen
        seen |= fresh
        new_per_round.append(len(fresh))
        dry = dry + 1 if not fresh else 0
        if observer is not None:
            rec = dict(
                kind="fuzz_round", round=rounds, batch=batch,
                seeds_run=rounds * batch, new_schedules=len(fresh),
                distinct_total=len(seen), crashes=n_crashed,
                corpus_size=cstats["size"],
                new_crash_codes=cstats["new_crash_codes"],
                # coverage-yield attribution (r15): the round's
                # admissions credited to the operator that produced
                # each admitted mutant (sums to `admitted`; "base" =
                # untouched lanes), plus where the corpus's mutation
                # budget sits — the fuzzer-effectiveness half of the
                # profiler plane
                admitted=cstats["new"],
                op_yield={YIELD_NAMES[i]: int(cstats["op_yield"][i])
                          for i in range(len(YIELD_NAMES))},
                corpus_energy=corpus.energy_summary(),
                dry_rounds=dry, wall_s=time.perf_counter() - t0)
            if ldfi is not None:
                # the lineage arm's round ledger: lanes given to
                # targeted vectors, their admissions (the slice of
                # `admitted` that was aimed, not sprayed), and the
                # support pool's size/honesty
                rec.update(targeted=int(targeted.sum()),
                           targeted_yield=int(
                               cstats.get("targeted_yield", 0)),
                           support_pool=len(pool))
            if lat_brief is not None:
                # the round's tail (obs/metrics.py schema): merged e2e
                # p50/p99 estimates + SLO misses for this round's batch
                rec.update(_lat_fields(lat_brief))
            if buckets is not None:
                rec["buckets_opened"] = len(opened_buckets)
            if sketches is not None:
                # divergence depth of this round's mutants (median
                # first-divergence slot vs the consensus prefix): how
                # early the round's schedule rewiring bit, off the
                # sketch transfer the corpus already paid for
                rec["div_slot_p50"] = int(np.median(
                    stats.first_divergence_slots(sketches)))
            observer.on_round(rec)
        if store is not None and (
                (r + 1 - round_start) % sync_every == 0
                or dry >= dry_rounds or r + 1 == max_rounds):
            # the durability point: after observe/buckets, BEFORE the
            # next round's schedule draw — a resume restores the rng
            # state saved here and replays that draw identically.
            # The campaign-timeline row goes FIRST: a kill between the
            # two re-runs the round and re-appends an identical row
            # (deduped by rounds_done in campaign_timeline), so the
            # durable timeline has no gaps and no double counts
            wall_now = wall_prior + time.perf_counter() - t0
            mrow = dict(
                t=time.time(), worker=worker_id, rounds_done=r + 1,
                coverage=len(seen), seeds_run=(r + 1) * batch,
                crashes=n_crashed, corpus_size=len(corpus),
                dry=dry, wall_s=round(wall_now, 3),
                op_yield=[int(x) for x in yield_hist])
            if ldfi is not None:
                mrow["targeted_yield"] = targeted_yield_total
            if lat_brief is not None:
                # the durable p99 timeline (campaign_report folds the
                # rows into a p99_curve): this sync's round-batch tail
                mrow.update(_lat_fields(lat_brief))
            store.append_metrics(worker_id, mrow)
            store.sync(corpus, worker_id, rounds_done=r + 1, dry=dry,
                       op_hist=op_hist, op_yield=yield_hist,
                       wall_s=wall_now,
                       targeted_yield=(targeted_yield_total
                                       if ldfi is not None else None))
        if dry >= dry_rounds:
            break
        pending = nxt if nxt is not None else (
            launch(r + 1) if r + 1 < max_rounds else None)

    result = dict(
        seeds_run=rounds * batch,
        rounds=rounds,
        distinct_schedules=len(seen),
        new_per_round=new_per_round,
        saturated=dry >= dry_rounds,
        crash_first_seed_by_code=crashes,
        crashes=n_crashed,
        crash_repros=repros,
        corpus_size=len(corpus),
        mutation_ops={OP_NAMES[i]: int(op_hist[i])
                      for i in range(N_MUT_OPS)},
        # campaign-cumulative coverage yield by operator (the
        # effectiveness view op_hist's application counts cannot give:
        # an operator that runs constantly but never buys coverage
        # shows up here as 0)
        mutation_yield={YIELD_NAMES[i]: int(yield_hist[i])
                        for i in range(len(YIELD_NAMES))},
        corpus_energy=corpus.energy_summary(),
    )
    if ldfi is not None:
        result["targeted"] = dict(
            supports=len(pool), truncated_supports=pool.truncated,
            lanes_run=targeted_total, admitted=targeted_yield_total)
    if store is not None:
        result.update(
            corpus_dir=store.dir,
            rounds_done_total=round_start + rounds,
            buckets_opened=opened_buckets,
            buckets_total=len(store.bucket_keys()))
    if minimize and repros:
        from ..harness.minimize import minimize_knobs
        result["minimized"] = {}
        for c, rep in repros.items():
            try:
                minimal, info = minimize_knobs(rt, plan, rep["knobs"],
                                               rep["seed"], max_steps,
                                               chunk)
                result["minimized"][c] = dict(info, knobs=minimal)
            except Exception as e:  # noqa: BLE001 - repro handle still stands
                result["minimized"][c] = dict(error=f"{type(e).__name__}: {e}")
        if buckets is not None:
            # attach the shrunk fault script to the buckets this run
            # opened (matched by crash code — the repro/minimize tables
            # are code-keyed): the bucket's canonical (seed, knobs) repro
            # stays untouched, the minimal script is reporting
            for key in buckets.new_keys:
                rec_b = store.load_bucket(key)
                mini = result["minimized"].get(int(rec_b["crash_code"]))
                if mini and "script" in mini:
                    rec_b["minimized"] = {
                        k: v for k, v in mini.items() if k != "knobs"}
                    store.write_bucket(key, rec_b)
    if observer is not None:
        observer.on_done(dict(
            kind="done", distinct_total=len(seen),
            wall_s=time.perf_counter() - t0,
            **{k: v for k, v in result.items()
               if k not in ("crash_repros", "minimized")}))
    return result
