"""Coverage-guided schedule search (r9): the subsystem that SEARCHES the
schedule space instead of sampling it.

  corpus.py   energy-scheduled corpus of knob vectors, deduped by
              sched_hash coverage
  mutate.py   the per-lane knob schema + jitted on-device mutation engine
  pct.py      PCT-style tie-break perturbation (SimState.prio_nudge)
  fuzz.py     the pipelined loop-until-dry driver

See DESIGN.md §11 "Search discipline".
"""

from .corpus import Corpus
from .fuzz import fuzz
from .mutate import N_MUT_OPS, OP_NAMES, KnobPlan
from .pct import pct_sweep, with_prio_nudge

__all__ = ["Corpus", "KnobPlan", "fuzz", "pct_sweep", "with_prio_nudge",
           "OP_NAMES", "N_MUT_OPS"]
