"""Coverage-guided schedule search (r9): the subsystem that SEARCHES the
schedule space instead of sampling it.

  corpus.py   energy-scheduled corpus of knob vectors, deduped by
              sched_hash coverage
  mutate.py   the per-lane knob schema + jitted on-device mutation engine
  pct.py      PCT-style tie-break perturbation (SimState.prio_nudge)
  fuzz.py     the pipelined loop-until-dry driver
  shard.py    the mesh-sharded campaign driver (r13): device-local
              corpus shards, on-device mutation fan-out, all-gather
              coverage merge
  ldfi.py     lineage-driven fault targeting (r22): green-run support
              pooling + hitting-set scenario synthesis, armed via
              fuzz(ldfi=LdfiConfig(...)) / fuzz_sharded(ldfi=...)

See DESIGN.md §11 "Search discipline", §15 "Sharding discipline", and
§23 "Targeted-fault discipline".
"""

from .corpus import Corpus, merge_consensus
from .fuzz import fuzz
from .ldfi import LdfiConfig, SupportPool, synthesize
from .mutate import N_MUT_OPS, OP_NAMES, KnobPlan
from .pct import pct_sweep, with_prio_nudge
from .shard import fuzz_sharded, shard_worker_id

__all__ = ["Corpus", "KnobPlan", "fuzz", "fuzz_sharded", "pct_sweep",
           "with_prio_nudge", "merge_consensus", "shard_worker_id",
           "OP_NAMES", "N_MUT_OPS",
           "LdfiConfig", "SupportPool", "synthesize"]
