"""Vectorized mutation engine: per-lane fault-knob vectors, mutated on device.

PR 3's structural/dynamic split made every fault knob a traced operand:
scenario rows are initial-state DATA (event-table rows), and loss/latency/
jitter/prio_nudge live in SimState. A mutant is therefore nothing but a
different initial state — `apply_knobs` rewrites the scenario slots and the
network scalars of a whole batch in one jitted call, and `mutate` derives a
batch of mutants from a batch of parents as one jitted program. Zero
recompiles per campaign: the mutation loop touches only operands.

The knob vector (one lane) — everything the fuzzer may perturb:

  row_time  i32[R]   scenario row fire times (HALT/INIT rows pinned)
  row_node  i32[R]   row targets (NODE_RANDOM = -1 preserved; reshuffles
                     stay inside the row's `among=` pool)
  row_on    bool[R]  row enabled (drop/revive; HALT/INIT pinned on)
  dup_src   i32[D]   dup slots: clone of scenario row dup_src[d] ...
  dup_time  i32[D]   ... firing at dup_time[d] (row duplicate operator;
  dup_on    bool[D]  D spare event-table slots past the scenario segment)
  loss      f32      packet loss rate
  lat_lo/hi i32      send-latency range
  jitter    i32      per-op jitter bound (only on jitter-enabled builds)
  prio_nudge i32     PCT tie-break policy (core/step.py; 0 = reference)

Bounds are enforced at APPLY time, not trusted from the mutator: times clip
to [0, tlimit], targets to [-1, N-1] (and only on rows where a target is
meaningful), loss to [0, 0.99], lat_lo <= lat_hi, pinned rows keep their
base time and stay enabled — a mutant can explore, never corrupt.
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp
import numpy as np

from ..compile.cache import COMPILE_LOG
from ..core import prng
from ..core import types as T
from ..ops import select as sel

# mutation operator ids (the op histogram in fuzz results uses this order)
OP_NAMES = ("time_nudge", "target_reshuffle", "row_toggle", "row_dup",
            "latency_perturb", "loss_perturb", "prio_perturb",
            "fault_perturb")
N_MUT_OPS = len(OP_NAMES)

# ops whose node target is meaningful and pool-restricted (step.py
# _apply_super: the random-target pool packing); everything else keeps its
# base node. The r17 per-node fault ops ride along: the fuzzer may move
# WHICH node's clock drifts or disk stalls, pool-confined like kills.
# The r19 connection-fault ops join the same way: the fuzzer may move
# WHOSE connections get torn or which node's datagrams duplicate.
_NODE_OPS = (T.OP_KILL, T.OP_RESTART, T.OP_PAUSE, T.OP_RESUME,
             T.OP_CLOG_NODE, T.OP_UNCLOG_NODE,
             T.OP_SET_SKEW, T.OP_SET_DISK,
             T.OP_RESET_PEER, T.OP_SET_DUP)
# r17/r19 fault value/flag knobs: rows whose TAIL payload word carries
# a bounded value (skew rate / disk latency / dup-delivery rate), whose
# payload[-2] carries the torn flag, and whose src carries the
# one-way-cut direction. OP_SET_DUP rides the existing fault_perturb
# havoc operator through val_ok — zero per-round recompiles, zero new
# knob-vector keys (the store schema moves via the simconfig-v6 bump).
_VAL_OPS = (T.OP_SET_SKEW, T.OP_SET_DISK, T.OP_SET_DUP)
# rows that must never move, drop, or duplicate: HALT carries the
# time-limit contract, INIT rows interact with the template's deferred-boot
# bookkeeping (runtime.py _build_template)
_PINNED_OPS = (T.OP_HALT, T.OP_INIT)

_LAT_CAP = 30_000_000      # 30 simulated seconds — mutation bound, not a
                           # model limit (deadlines stay far from T_INF)
_JIT_CAP = 1_000_000


@dataclasses.dataclass
class KnobPlan:
    """The static half of a fuzz campaign: which knobs exist for this
    Runtime's scenario, their base values, and the mutability guards.
    Everything per-shape here is passed to the jitted kernels as an
    OPERAND, so two campaigns with equal (R, D, N, capacity) shapes share
    one compiled mutate/apply program."""

    n_init: int                 # scenario rows start at this slot
    R: int                      # scenario rows (incl. the auto-HALT)
    D: int                      # dup slots (free event rows past them)
    N: int                      # nodes
    payload_words: int
    jitter_gate: bool           # static build gate (NetConfig.op_jitter_max)
    base: dict                  # np arrays: time/op/node/src [R], payload [R,P]
    time_ok: np.ndarray         # bool[R]
    node_ok: np.ndarray         # bool[R]
    drop_ok: np.ndarray         # bool[R]
    pool_ok: np.ndarray         # bool[R, N+1]: pool_ok[r, t+1] — target t
                                # allowed for row r (t = -1 always allowed)
    # r17 gray-failure knob guards: which rows carry a mutable tail VALUE
    # (skew rate / disk latency — bounds per row, enforced at apply),
    # a one-way-cut DIRECTION flag (src), or a TORN flag (payload[-2])
    val_ok: np.ndarray          # bool[R]
    val_lo: np.ndarray          # int32[R] — value bound, 0 on non-val rows
    val_hi: np.ndarray          # int32[R]
    dir_ok: np.ndarray          # bool[R]
    torn_ok: np.ndarray         # bool[R]
    net0: tuple                 # (loss, lat_lo, lat_hi, jitter) base scalars

    @staticmethod
    def from_runtime(rt, dup_slots: int = 2) -> "KnobPlan":
        cfg = rt.cfg
        rows = rt.scenario.build(cfg)
        R = rows["op"].shape[0]
        n_init = cfg.n_nodes
        # dup slots live past the scenario segment; they must exist in the
        # table BEFORE any emission claims slots (apply runs on the init
        # state), so capacity-bound them instead of failing
        D = max(0, min(int(dup_slots), cfg.event_capacity - n_init - R))
        op = rows["op"]
        pinned = np.isin(op, _PINNED_OPS)
        node_ok = np.isin(op, _NODE_OPS)
        N = cfg.n_nodes
        pool_ok = np.zeros((R, N + 1), bool)
        pool_ok[:, 0] = True                       # NODE_RANDOM always legal
        # only the words node ids can pack into count as "a pool was
        # given" — the r17 value-carrying ops keep their values in the
        # TAIL payload words (step.py _apply_super applies the same rule)
        n_pool_words = min(cfg.payload_words, (N + 30) // 31)
        for r in range(R):
            pay = rows["payload"][r][:n_pool_words]
            if node_ok[r] and pay.any():
                # pool-restricted random target (31 nodes/word packing):
                # reshuffles must stay inside the pool — the in-bounds
                # contract chaos recipes rely on (kill servers, not clients)
                for t in range(N):
                    pool_ok[r, t + 1] = bool(
                        (int(pay[t // 31]) >> (t % 31)) & 1)
            else:
                pool_ok[r, 1:] = True
        # r17 fault knobs: per-row value bounds (skew is a ±rate, disk
        # latency a nonnegative tick count), flag carriers
        val_ok = np.isin(op, _VAL_OPS)
        dir_ok = op == T.OP_PARTITION_ONEWAY
        torn_ok = (op == T.OP_SET_DISK) & (cfg.payload_words >= 2)
        val_lo = np.where(op == T.OP_SET_SKEW, -T.SKEW_CAP, 0)
        val_hi = np.where(op == T.OP_SET_SKEW, T.SKEW_CAP,
                          np.where(op == T.OP_SET_DISK, T.DISK_LAT_CAP,
                                   np.where(op == T.OP_SET_DUP,
                                            T.DUP_RATE_CAP, 0)))
        return KnobPlan(
            n_init=n_init, R=R, D=D, N=N, payload_words=cfg.payload_words,
            jitter_gate=cfg.net.op_jitter_max > 0,
            base=dict(time=rows["time"].astype(np.int32),
                      op=op.astype(np.int32),
                      node=rows["node"].astype(np.int32),
                      src=rows["src"].astype(np.int32),
                      payload=rows["payload"].astype(np.int32)),
            time_ok=~pinned, node_ok=node_ok, drop_ok=~pinned,
            pool_ok=pool_ok,
            val_ok=val_ok, val_lo=val_lo.astype(np.int32),
            val_hi=val_hi.astype(np.int32), dir_ok=dir_ok, torn_ok=torn_ok,
            net0=(float(cfg.net.packet_loss_rate),
                  int(cfg.net.send_latency_min),
                  int(cfg.net.send_latency_max),
                  int(cfg.net.op_jitter_max)))

    # -- knob construction -------------------------------------------------
    def base_knobs(self) -> dict:
        """The unmutated knob vector: exactly the Runtime's own scenario
        and NetConfig (applying it is a no-op modulo slot bookkeeping)."""
        loss, lo, hi, jit = self.net0
        P = self.payload_words
        pay = self.base["payload"]
        # r17 fault knobs, read back from where build() encoded them:
        # value = tail word P-1 (skew rate / disk latency), flag = the
        # one-way direction (src bit 0) or the torn flag (word P-2)
        row_val = np.where(self.val_ok, pay[:, P - 1], 0).astype(np.int32)
        row_flag = np.where(
            self.dir_ok, self.base["src"] & 1,
            np.where(self.torn_ok, pay[:, P - 2] if P >= 2
                     else np.zeros(self.R, np.int32), 0)).astype(np.int32)
        return dict(
            row_time=self.base["time"].copy(),
            row_node=self.base["node"].copy(),
            row_on=np.ones(self.R, bool),
            row_val=row_val, row_flag=row_flag,
            dup_src=np.zeros(self.D, np.int32),
            dup_time=np.full(self.D, T.T_INF, np.int32),
            dup_on=np.zeros(self.D, bool),
            loss=np.float32(loss), lat_lo=np.int32(lo), lat_hi=np.int32(hi),
            jitter=np.int32(jit), prio_nudge=np.int32(0))

    def base_batch(self, batch: int) -> dict:
        return self.stack([self.base_knobs()] * batch)

    @staticmethod
    def stack(knobs_list) -> dict:
        return {k: np.stack([kn[k] for kn in knobs_list])
                for k in knobs_list[0]}

    @staticmethod
    def lane(knobs_batch, i: int) -> dict:
        """One lane's knob vector as owned host arrays (corpus entries)."""
        return {k: np.array(np.asarray(v)[i]) for k, v in knobs_batch.items()}

    def _guards(self) -> dict:
        return dict(time_ok=jnp.asarray(self.time_ok),
                    node_ok=jnp.asarray(self.node_ok),
                    drop_ok=jnp.asarray(self.drop_ok),
                    pool_ok=jnp.asarray(self.pool_ok),
                    val_ok=jnp.asarray(self.val_ok),
                    val_lo=jnp.asarray(self.val_lo),
                    val_hi=jnp.asarray(self.val_hi),
                    dir_ok=jnp.asarray(self.dir_ok),
                    torn_ok=jnp.asarray(self.torn_ok))

    # -- the two jitted kernels -------------------------------------------
    def mutate(self, knobs_batch, key, havoc: int = 3):
        """Derive a batch of mutants: per lane, `havoc` stacked operators
        drawn uniformly (the AFL havoc stage, vectorized). `knobs_batch`
        is host or device arrays [B, ...]; `key` one PRNG key. Returns
        (device knob batch, int32[N_MUT_OPS] operator histogram,
        int32[B] per-lane LAST applied operator — -1 when no operator
        landed; the coverage-yield attribution handle, search/fuzz.py).
        havoc=0 is the degenerate identity (the blind-sampling control:
        fuzz(havoc=0) reduces to explore() with knob plumbing)."""
        kb = {k: jnp.asarray(v) for k, v in knobs_batch.items()}
        if havoc <= 0:
            B = int(kb["row_time"].shape[0])
            return (kb, jnp.zeros((N_MUT_OPS,), jnp.int32),
                    jnp.full((B,), -1, jnp.int32))
        return _mutate_batch(kb, key, self._guards(), havoc)

    def mutate_masked(self, knobs_batch, key, mask, havoc: int = 3):
        """The SPMD variant for the mesh-sharded driver (search/shard.py):
        one jitted call over a MESH-SHARDED knob batch, with a per-lane
        bool `mask` selecting which lanes keep the mutant (False lanes
        pass their parent through untouched — how bootstrap shards ride
        a mixed round without a separate dispatch). One executable per
        mesh width instead of one per device, and the mutation math
        partitions over the lane axis — it never leaves each shard's
        device. With mask all-True this computes exactly `mutate()`
        (same key split, same operators; the selects are identity), so
        the 1-shard campaign stays bit-identical to the unsharded
        fuzzer. Returns (device knob batch, histogram over MASKED lanes
        only — a passed-through lane's draws never count, and its
        last-op attribution is -1 like an unmutated lane's)."""
        kb = {k: jnp.asarray(v) for k, v in knobs_batch.items()}
        if havoc <= 0:
            B = int(kb["row_time"].shape[0])
            return (kb, jnp.zeros((N_MUT_OPS,), jnp.int32),
                    jnp.full((B,), -1, jnp.int32))
        return _mutate_batch_masked(kb, key, self._guards(), havoc,
                                    jnp.asarray(mask))

    def apply(self, state, knobs_batch):
        """Write a knob batch into a batched init state: scenario slots
        [n_init, n_init+R+D) plus the network/priority scalars. Bounds
        enforced here (see module docstring). One jitted call; state is
        not donated (callers may hand the result to donating runners)."""
        kb = {k: jnp.asarray(v) for k, v in knobs_batch.items()}
        base = {k: jnp.asarray(v) for k, v in self.base.items()}
        return _apply_batch(state, kb, base, self._guards(),
                            self.n_init, self.jitter_gate)

    # -- human-facing rendering -------------------------------------------
    def to_scenario(self, knobs: dict):
        """Render one knob vector as a Scenario (repro reports / ddmin
        hand-off): enabled rows with their mutated times/targets, dup
        clones as real rows. The network/priority scalars don't fit a
        Scenario — carry them alongside (fuzz repros do)."""
        from ..runtime.scenario import Scenario, _Row
        sc = Scenario()
        kn = {k: np.asarray(v) for k, v in knobs.items()}

        def row_src_pay(r):
            """The row's src/payload with the r17 fault knobs rendered
            in (same bounds as apply); values ride the full payload —
            describe() falls back to it when payload_tail is absent."""
            src = int(self.base["src"][r])
            pay = [int(w) for w in self.base["payload"][r]]
            P = self.payload_words
            if self.val_ok[r]:
                pay[P - 1] = int(np.clip(kn["row_val"][r],
                                         self.val_lo[r], self.val_hi[r]))
            if self.torn_ok[r]:
                pay[P - 2] = int(kn["row_flag"][r]) & 1
            if self.dir_ok[r]:
                src = int(kn["row_flag"][r]) & 1
            return src, tuple(pay)

        for r in range(self.R):
            on = bool(kn["row_on"][r]) or not self.drop_ok[r]
            if not on:
                continue
            t = (int(kn["row_time"][r]) if self.time_ok[r]
                 else int(self.base["time"][r]))
            node = (int(kn["row_node"][r]) if self.node_ok[r]
                    else int(self.base["node"][r]))
            src, pay = row_src_pay(r)
            sc.rows.append(_Row(t, int(self.base["op"][r]), node, src, pay))
        for d in range(self.D):
            if not bool(kn["dup_on"][d]):
                continue
            srow = int(np.clip(kn["dup_src"][d], 0, self.R - 1))
            if not self.drop_ok[srow]:
                continue
            node = (int(kn["row_node"][srow]) if self.node_ok[srow]
                    else int(self.base["node"][srow]))
            src, pay = row_src_pay(srow)
            sc.rows.append(_Row(int(kn["dup_time"][d]),
                                int(self.base["op"][srow]), node, src, pay))
        sc.rows.sort(key=lambda r: r.time)
        return sc


def apply_repro_knobs(rt, state, knobs: dict, plan: "KnobPlan" = None):
    """Re-apply ONE repro handle's knob vector to every lane of a batched
    init state — the `(seed, knobs[, nudge])` replay idiom shared by
    `pct_sweep` and `analyze/races` (confirm/replay/scan). Infers the
    KnobPlan's dup-slot count from the vector itself when no plan is
    given, so a handle loaded from a bucket replays without knowing the
    campaign's dup_slots. Returns (state, plan)."""
    if plan is None:
        plan = KnobPlan.from_runtime(
            rt, dup_slots=len(np.atleast_1d(knobs["dup_src"])))
    B = int(np.atleast_1d(np.asarray(state.halted)).shape[0])
    return plan.apply(state, KnobPlan.stack([knobs] * B)), plan


# ---------------------------------------------------------------------------
# jitted kernels — MODULE-LEVEL jits (the utils/hashing discipline): traces
# are cached per shape, not per KnobPlan instance, so two campaigns over
# equally-shaped scenarios share one executable.
# ---------------------------------------------------------------------------


def _take_rows(mat, idx):
    """mat[idx] for mat[R, P] and idx[D] via one-hot matmul (gathers
    serialize on TPU — ops/select.py rationale)."""
    oh = (idx[:, None] == jnp.arange(mat.shape[0], dtype=jnp.int32))
    return jnp.einsum("dr,rp->dp", oh.astype(mat.dtype), mat)


def _mutate_one(kn, key, g, havoc):
    R = kn["row_time"].shape[0]
    D = kn["dup_src"].shape[0]
    N = g["pool_ok"].shape[1] - 1
    hist = jnp.zeros((N_MUT_OPS,), jnp.int32)
    last_op = jnp.asarray(-1, jnp.int32)
    for k in prng.split(key, havoc):
        ks = prng.split(k, 16)
        op = prng.randint(ks[0], 0, N_MUT_OPS - 1)

        # 0: time nudge — multi-scale delta on one mutable row
        r_t, ok_t = sel.masked_choice(ks[1], g["time_ok"])
        mag = prng.randint(ks[2], 6, 20)                   # 64us .. ~1s
        raw = jax.random.randint(ks[3], (), 0,
                                 (jnp.int32(1) << mag), dtype=jnp.int32)
        delta = jnp.where(prng.bernoulli(ks[4], 0.5), raw + 1, -(raw + 1))
        oh_t = sel.row_onehot(R, r_t) & (op == 0) & ok_t
        row_time = jnp.clip(
            kn["row_time"] + jnp.where(oh_t, delta, 0), 0, T.T_INF - 1)

        # 1: target reshuffle — redraw inside the row's pool (or back to
        # NODE_RANDOM when the draw falls outside it)
        r_n, ok_n = sel.masked_choice(ks[5], g["node_ok"])
        cand = prng.randint(ks[6], -1, N - 1)
        allowed = sel.take1(sel.take_row(g["pool_ok"], r_n), cand + 1)
        new_node = jnp.where(allowed, cand, jnp.asarray(T.NODE_RANDOM,
                                                        jnp.int32))
        oh_n = sel.row_onehot(R, r_n) & (op == 1) & ok_n
        row_node = jnp.where(oh_n, new_node, kn["row_node"])

        # 2: row toggle — drop (or revive) one droppable row
        r_d, ok_d = sel.masked_choice(ks[7], g["drop_ok"])
        row_on = kn["row_on"] ^ (sel.row_onehot(R, r_d) & (op == 2) & ok_d)

        dup_src, dup_time, dup_on = kn["dup_src"], kn["dup_time"], kn["dup_on"]
        dup_eff = jnp.asarray(False)
        if D > 0:
            # 3: row duplicate — toggle a dup slot; turning it on clones a
            # droppable row at a nearby time
            d_i = prng.randint(ks[8], 0, D - 1)
            s_r, ok_s = sel.masked_choice(ks[9], g["drop_ok"])
            dup_eff = ok_s
            oh_d = sel.row_onehot(D, d_i) & (op == 3) & ok_s
            turn_on = oh_d & ~kn["dup_on"]
            near = prng.randint(ks[10], -200_000, 200_000)  # ±200ms
            dup_on = kn["dup_on"] ^ oh_d
            dup_src = jnp.where(turn_on, s_r, kn["dup_src"])
            dup_time = jnp.where(
                turn_on,
                jnp.clip(sel.take1(row_time, s_r) + near, 0, T.T_INF - 1),
                kn["dup_time"])

        # 4: latency perturbation — shift the (lo, hi) pair (and the
        # jitter bound on jitter-enabled builds)
        is4 = op == 4
        dlo = prng.randint(ks[2], -5_000, 5_000)
        dhi = prng.randint(ks[3], -20_000, 20_000)
        lat_lo = jnp.where(is4, jnp.clip(kn["lat_lo"] + dlo, 0, _LAT_CAP),
                           kn["lat_lo"])
        lat_hi = jnp.where(is4, jnp.clip(kn["lat_hi"] + dhi, 0, _LAT_CAP),
                           kn["lat_hi"])
        jitter = jnp.where(is4, jnp.clip(kn["jitter"] + dlo, 0, _JIT_CAP),
                           kn["jitter"])

        # 5: loss perturbation — drift, with an occasional reset to 0
        drift = (prng.uniform(ks[4]) - 0.5) * 0.2
        reset = prng.bernoulli(ks[7], 0.2)
        # drift caps at 0.9 (beyond that lanes mostly stall to tlimit —
        # wasted budget) but never pulls a hotter BASE loss down: the cap
        # is max(0.9, parent), so bases in (0.9, 0.99] stay reachable
        loss = jnp.where(op == 5,
                         jnp.where(reset, jnp.float32(0.0),
                                   jnp.clip(kn["loss"] + drift, 0.0,
                                            jnp.maximum(jnp.float32(0.9),
                                                        kn["loss"]))),
                         kn["loss"])

        # 6: priority perturbation — a fresh PCT tie-break policy
        bits = jax.random.randint(ks[11], (), -(2**31) + 1, 2**31 - 1,
                                  dtype=jnp.int32)
        prio = jnp.where(op == 6, bits, kn["prio_nudge"])

        # 7: fault perturbation (r17) — pick a gray-failure row and
        # either nudge its bounded VALUE (skew rate / disk latency;
        # delta scales with the row's own bound span, clip at apply
        # re-enforces it) or toggle its FLAG (one-way direction /
        # torn mode). Guard-aware: value-only rows never get a flag
        # toggle and vice versa.
        fault_ok = g["val_ok"] | g["dir_ok"] | g["torn_ok"]
        r_f, ok_f = sel.masked_choice(ks[12], fault_ok)
        has_flag = sel.take1(g["dir_ok"] | g["torn_ok"], r_f)
        has_val = sel.take1(g["val_ok"], r_f)
        want_flag = prng.bernoulli(ks[13], 0.35)
        do_flag = has_flag & (want_flag | ~has_val)
        oh_f = sel.row_onehot(R, r_f) & (op == 7) & ok_f
        span = g["val_hi"] - g["val_lo"]
        vdelta = (prng.randint(ks[14], -8, 8)
                  * jnp.maximum(span // 64, 1))
        row_val = jnp.clip(
            kn["row_val"] + jnp.where(oh_f & ~do_flag, vdelta, 0),
            g["val_lo"], g["val_hi"])
        row_flag = jnp.where(oh_f & do_flag, kn["row_flag"] ^ 1,
                             kn["row_flag"])

        kn = dict(row_time=row_time, row_node=row_node, row_on=row_on,
                  row_val=row_val, row_flag=row_flag,
                  dup_src=dup_src, dup_time=dup_time, dup_on=dup_on,
                  loss=loss, lat_lo=lat_lo, lat_hi=lat_hi, jitter=jitter,
                  prio_nudge=prio)
        # count the op only when it actually wrote something: a draw whose
        # guard found no mutable row (or no dup slot) is a no-op, and the
        # histogram feeds fuzz()'s `mutation_ops` / --search-smoke's
        # "operators used" gate
        applied = (((op == 0) & ok_t) | ((op == 1) & ok_n)
                   | ((op == 2) & ok_d) | ((op == 3) & dup_eff)
                   | ((op >= 4) & (op <= 6)) | ((op == 7) & ok_f))
        hist = hist + ((jnp.arange(N_MUT_OPS, dtype=jnp.int32) == op)
                       & applied).astype(jnp.int32)
        # the lane's LAST applied operator: the coverage-yield
        # attribution handle (search/fuzz.py) — when this lane's mutant
        # is admitted, exactly one operator gets the credit, so
        # per-operator yield sums to the round's admissions
        last_op = jnp.where(applied, op, last_op)
    return kn, hist, last_op


@functools.partial(jax.jit, static_argnames=("havoc",))
def _mutate_batch(knobs, key, guards, havoc):
    COMPILE_LOG.note_trace("mutate",
                           batch=int(knobs["row_time"].shape[0]),
                           havoc=havoc)
    keys = jax.random.split(key, knobs["row_time"].shape[0])
    out, hist, last_op = jax.vmap(_mutate_one, in_axes=(0, 0, None, None))(
        knobs, keys, guards, havoc)
    return out, hist.sum(0), last_op


@functools.partial(jax.jit, static_argnames=("havoc",))
def _mutate_batch_masked(knobs, key, guards, havoc, mask):
    COMPILE_LOG.note_trace("mutate_masked",
                           batch=int(knobs["row_time"].shape[0]),
                           havoc=havoc)
    keys = jax.random.split(key, knobs["row_time"].shape[0])
    out, hist, last_op = jax.vmap(_mutate_one, in_axes=(0, 0, None, None))(
        knobs, keys, guards, havoc)

    def sel(new, old):
        return jnp.where(mask.reshape((-1,) + (1,) * (new.ndim - 1)),
                         new, old)

    return ({k: sel(out[k], knobs[k]) for k in knobs},
            (hist * mask[:, None]).sum(0),
            jnp.where(mask, last_op, jnp.asarray(-1, jnp.int32)))


@functools.partial(jax.jit, static_argnames=("n_init", "jitter_gate"))
def _apply_batch(state, knobs, base, guards, n_init, jitter_gate):
    COMPILE_LOG.note_trace("apply_knobs",
                           batch=int(state.halted.shape[0]))
    R = base["op"].shape[0]
    N = guards["pool_ok"].shape[1] - 1

    def one(s, kn):
        D = kn["dup_src"].shape[0]
        row_on = jnp.where(guards["drop_ok"], kn["row_on"], True)
        row_time = jnp.where(guards["time_ok"],
                             jnp.clip(kn["row_time"], 0, s.tlimit),
                             base["time"])
        row_node = jnp.where(guards["node_ok"],
                             jnp.clip(kn["row_node"], -1, N - 1),
                             base["node"])
        # pool membership is enforced HERE, not trusted from the mutator:
        # a hand-edited or corpus-loaded knob vector with an out-of-pool
        # target falls back to NODE_RANDOM (the mutator's own fallback),
        # so the chaos-recipe in-bounds contract holds for any input
        oh_pool = ((row_node + 1)[:, None]
                   == jnp.arange(N + 1, dtype=jnp.int32)[None, :])
        in_pool = (guards["pool_ok"] & oh_pool).any(axis=1)
        row_node = jnp.where(guards["node_ok"] & ~in_pool,
                             jnp.asarray(T.NODE_RANDOM, jnp.int32), row_node)
        # r17 fault knobs, bounds enforced HERE like everything else:
        # values clip to the row's own [lo, hi] (skew stays a ±rate,
        # disk latency nonnegative), flags collapse to one bit; a
        # hand-edited vector can explore, never corrupt. Values land in
        # the TAIL payload words (P-1 value, P-2 torn), the direction
        # in src bit 0 — the encoding _apply_super reads.
        P = base["payload"].shape[1]
        row_val = jnp.clip(kn["row_val"], guards["val_lo"],
                           guards["val_hi"])
        row_pay = base["payload"].astype(jnp.int32)
        row_pay = row_pay.at[:, P - 1].set(
            jnp.where(guards["val_ok"], row_val, row_pay[:, P - 1]))
        if P >= 2:
            row_pay = row_pay.at[:, P - 2].set(
                jnp.where(guards["torn_ok"], kn["row_flag"] & 1,
                          row_pay[:, P - 2]))
        row_src = jnp.where(guards["dir_ok"], kn["row_flag"] & 1,
                            base["src"])
        seg_deadline = [jnp.where(row_on, row_time,
                                  jnp.asarray(T.T_INF, jnp.int32))]
        seg_kind = [jnp.where(row_on, T.EV_SUPER, T.EV_FREE)]
        seg_node = [row_node]
        seg_src = [row_src]
        seg_tag = [base["op"]]
        seg_payload = [row_pay]
        if D > 0:
            dsrc = jnp.clip(kn["dup_src"], 0, R - 1)
            d_ok = kn["dup_on"] & sel.take1(guards["drop_ok"], dsrc)
            seg_deadline.append(jnp.where(
                d_ok, jnp.clip(kn["dup_time"], 0, s.tlimit),
                jnp.asarray(T.T_INF, jnp.int32)))
            seg_kind.append(jnp.where(d_ok, T.EV_SUPER, T.EV_FREE))
            seg_node.append(sel.take1(row_node, dsrc))
            seg_src.append(sel.take1(row_src, dsrc))
            seg_tag.append(sel.take1(base["op"], dsrc))
            seg_payload.append(_take_rows(row_pay, dsrc))
        lo = n_init
        hi = n_init + R + D

        def put(col, segs):
            v = jnp.concatenate(segs).astype(col.dtype)
            return col.at[lo:hi].set(v)

        lat_lo = jnp.clip(kn["lat_lo"], 0, _LAT_CAP)
        return s.replace(
            t_deadline=put(s.t_deadline, seg_deadline),
            t_kind=put(s.t_kind, seg_kind),
            t_node=put(s.t_node, seg_node),
            t_src=put(s.t_src, seg_src),
            t_tag=put(s.t_tag, seg_tag),
            t_payload=put(s.t_payload, seg_payload),
            loss=jnp.clip(kn["loss"], 0.0, 0.99),
            lat_lo=lat_lo,
            lat_hi=jnp.maximum(lat_lo, jnp.clip(kn["lat_hi"], 0, _LAT_CAP)),
            jitter=(jnp.clip(kn["jitter"], 0, _JIT_CAP) if jitter_gate
                    else s.jitter),
            prio_nudge=kn["prio_nudge"])

    return jax.vmap(one)(state, knobs)
