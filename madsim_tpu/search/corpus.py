"""The fuzz corpus: interesting knob vectors, energy-scheduled.

AFL keeps inputs that reached new edges; the schedule fuzzer keeps knob
vectors whose lane produced a `sched_hash` never seen before — the corpus
is KEYED AND DEDUPED by the coverage digest itself (one entry per distinct
u64 schedule hash), so it can only grow when coverage grows. Host-side and
numpy-only: the corpus is bookkeeping between device rounds, sized in
kilobytes, and never on the hot path (corpus work overlaps device compute
in the pipelined fuzz loop exactly like explore()'s dedup).

Energy rules (the AFL-style scheduler, simplified to what the batched
setting needs):
  - admission energy 1.0; a lane that CRASHED enters with 3.0 (crash
    neighborhoods are where more crashes live);
  - a parent whose mutant discovered a new schedule is rewarded
    (energy x1.5, capped) — productive regions get more mutation budget;
  - every round all energies decay x`decay` toward a floor, so stale
    entries fade instead of starving newcomers;
  - `schedule()` samples parents with probability proportional to energy,
    and keeps `fresh_frac` of each batch on the UNMUTATED base knobs — an
    exploration floor so the corpus never traps the sweep in one basin;
  - (r16, opt-in) lanes whose OWN end-to-end latency p99 sits high get
    an admission bonus scaled by how close to the round's worst tail
    they are (up to x(1+lat_bonus)) — the divergence-bonus treatment
    applied to TAIL AMPLIFICATION, so the fuzzer can hunt admissions
    that push p99 up, not just ones that rewire the schedule. Fed by
    the on-device latency plane (SimState.lh_e2e, cfg.latency_hist);
    lat_bonus=0 (the default) keeps energy latency-blind and a build
    without the plane is always blind regardless.
  - (r21, opt-in) lanes whose DEEPEST TRANSIENT SPIKE sits high get an
    admission bonus scaled by how close to the round's worst spike
    they are (up to x(1+burst_bonus)) — the lat_bonus treatment
    applied to the WINDOWED series (SimState sr_*, cfg.series_windows):
    the per-lane metric is the worst per-WINDOW p99 (queue high-water
    on latency-less builds), so a mutant that digs one deep transient
    hole which the aggregate p99 then averages away — exactly the
    trajectory shape the recovery oracle judges — outscores a mutant
    that is merely uniformly slow. Fed by `parallel.stats.lane_burst`;
    burst_bonus=0 (the default) keeps energy burst-blind and a build
    without the series plane is always blind regardless.
  - (r10) lanes that diverged from the campaign's consensus prefix EARLY
    get an admission bonus scaled by depth (up to x(1+div_bonus)),
    computed from the on-device prefix-coverage sketches
    (SimState.cov_sketch): an early split means the mutation rewired the
    schedule near its root, and everything downstream of it is new
    territory — the per-prefix signal the terminal sched_hash alone
    cannot see. (r11) The consensus prefix is CROSS-ROUND: per-slot value
    counts accumulate over every observed round (and, through the
    durable store, every prior campaign segment), so novelty is judged
    against the whole campaign's history, not just the current batch —
    the ROADMAP follow-on the r10 per-round modal left open.

Multi-process namespacing (r11): entry ids carry the worker id in their
high bits (`worker_id << _ID_SHIFT | counter`), so two workers sharing a
corpus dir can never mint colliding ids — the by-id parent-reward and
eviction attribution stays sound when entries merge across processes
(a foreign parent id either resolves to the merged copy or to nobody,
never to the wrong entry).
"""

from __future__ import annotations

import numpy as np

from ..parallel.stats import first_divergence_slots
from .mutate import N_MUT_OPS, OP_NAMES, KnobPlan

# op_yield's attribution buckets: one per havoc operator, plus "base"
# for admitted lanes no operator touched (bootstrap / fresh-floor lanes
# and mutants whose every draw was guarded into a no-op)
YIELD_NAMES = OP_NAMES + ("base",)

# entry id = (worker_id << _ID_SHIFT) | per-worker monotonic counter.
# 2^40 admissions per worker and 2^23 workers fit int64 with headroom.
_ID_SHIFT = 40


def split_entry_id(eid: int) -> tuple[int, int]:
    """(worker_id, counter) of a namespaced entry id."""
    return int(eid) >> _ID_SHIFT, int(eid) & ((1 << _ID_SHIFT) - 1)


class Corpus:
    def __init__(self, plan: KnobPlan, rng=None, max_entries: int = 4096,
                 fresh_frac: float = 0.125, decay: float = 0.97,
                 reward: float = 1.5, energy_cap: float = 8.0,
                 div_bonus: float = 1.0, lat_bonus: float = 0.0,
                 burst_bonus: float = 0.0, worker_id: int = 0):
        self.plan = plan
        self.rng = rng if rng is not None else np.random.default_rng(0)
        self.max_entries = int(max_entries)
        self.fresh_frac = float(fresh_frac)
        self.decay = float(decay)
        self.reward = float(reward)
        self.energy_cap = float(energy_cap)
        self.div_bonus = float(div_bonus)   # 0 = sched_hash-only energy
        self.lat_bonus = float(lat_bonus)   # 0 = latency-blind energy
        self.burst_bonus = float(burst_bonus)  # 0 = burst-blind energy
        self.worker_id = int(worker_id)
        self.entries: list[dict] = []   # slot-stable: eviction replaces
        self._seen: set[int] = set()    # every hash ever admitted (dedupe)
        self.crash_codes: set[int] = set()
        # parent attribution is by monotonic entry id, not slot index:
        # schedule() hands out ids and observe() rewards through this map,
        # so an eviction (same round or, under the pipelined loop, a later
        # one) can never hand a stale parent's reward to the slot's fresh
        # occupant — the reward just finds nobody. Ids are namespaced by
        # worker (see module docstring), so the same holds across
        # processes sharing a durable corpus dir.
        self._next_id = self.worker_id << _ID_SHIFT
        self._by_id: dict[int, dict] = {}
        # cross-round consensus prefix: per-slot {sketch value: count}
        # over every lane ever observed (kilobytes of host bookkeeping;
        # serialized with the corpus by service/store.py)
        self._slot_counts: list[dict[int, int]] | None = None
        # durable-store hook: when a CorpusStore drives this corpus it
        # flips this on so entries evicted BETWEEN two syncs are still
        # persisted (their coverage keys are part of _seen and must
        # survive a resume); off by default so in-memory campaigns don't
        # accumulate dead entries
        self.track_evictions = False
        self.evicted_unsynced: list[dict] = []
        # mesh-shard hook (r13, search/shard.py): when on, observe()
        # also queues each OWN admission into an outbox the sharded
        # driver drains at merge points — the in-memory counterpart of
        # the store's immutable entry files, so shard corpora can
        # exchange exactly the entries admitted since the last merge.
        # Foreign admissions (admit_foreign) never enter the outbox:
        # re-broadcasting them would only ping-pong already-shared keys.
        self.track_admissions = False
        self.admitted_unmerged: list[dict] = []
        # consensus DELTA counters (shard mode only): what this corpus
        # folded since the last cross-shard merge. merge_consensus()
        # drains them into the campaign tally, so repeated merges never
        # double-count the shared history. Never pruned — bounded by
        # the lanes observed between two merges.
        self._slot_delta: list[dict[int, int]] | None = None

    def __len__(self) -> int:
        return len(self.entries)

    # ------------------------------------------------------------------
    def coverage_keys(self) -> set[int]:
        """Every sched_hash ever admitted (a copy): the corpus's coverage
        frontier — survives evictions, merges across workers."""
        return set(self._seen)

    def consensus_sketch(self) -> np.ndarray | None:
        """The campaign's consensus prefix: per-slot modal sketch value
        over every observed round (ties break to the smallest value, the
        `parallel.stats.first_divergence_slots` rule). None before any
        sketched round was observed."""
        if self._slot_counts is None:
            return None
        out = np.zeros(len(self._slot_counts), np.uint32)
        for j, counts in enumerate(self._slot_counts):
            # max count, ties to smallest value — sort keys first
            best_v, best_c = 0, -1
            for v in sorted(counts):
                if counts[v] > best_c:
                    best_v, best_c = v, counts[v]
            out[j] = best_v
        return out

    def _fold_sketches(self, sk: np.ndarray) -> None:
        if self._slot_counts is None:
            self._slot_counts = [dict() for _ in range(sk.shape[1])]
        if self.track_admissions and self._slot_delta is None:
            self._slot_delta = [dict() for _ in range(sk.shape[1])]
        for j in range(sk.shape[1]):
            counts = self._slot_counts[j]
            vals, cnts = np.unique(sk[:, j], return_counts=True)
            for v, c in zip(vals.tolist(), cnts.tolist()):
                counts[int(v)] = counts.get(int(v), 0) + int(c)
                if self._slot_delta is not None:
                    dj = self._slot_delta[j]
                    dj[int(v)] = dj.get(int(v), 0) + int(c)
            if len(counts) > 8192:
                # bound the per-slot tally on very long campaigns: keep
                # the hottest half, deterministically (count desc, value
                # asc) — pruning is a pure function of the counter state,
                # so an interrupted+resumed campaign prunes identically
                keep = sorted(counts.items(),
                              key=lambda kv: (-kv[1], kv[0]))[:4096]
                self._slot_counts[j] = dict(keep)

    def admit_foreign(self, entry: dict) -> bool:
        """Merge one entry harvested by ANOTHER worker (service/store.py
        scan): admitted only when its coverage key is new here, keeping
        its foreign id and admission energy. Returns True on admission.
        The merge is lock-free by construction — ids are namespaced per
        worker and entries are immutable once written, so merging is
        order-independent set union keyed by sched_hash."""
        h = int(entry["hash"])
        if h in self._seen:
            return False
        self._seen.add(h)
        if entry.get("crash_code", 0):
            self.crash_codes.add(int(entry["crash_code"]))
        self._insert(dict(entry))
        return True

    def _insert(self, entry: dict) -> None:
        self._by_id[entry["id"]] = entry
        if len(self.entries) < self.max_entries:
            self.entries.append(entry)
        else:                        # replace the coldest slot
            j = int(np.argmin([e["energy"] for e in self.entries]))
            del self._by_id[self.entries[j]["id"]]
            if self.track_evictions:
                self.evicted_unsynced.append(self.entries[j])
            self.entries[j] = entry

    # ------------------------------------------------------------------
    def energy_summary(self) -> dict:
        """The corpus's energy distribution — where the scheduler's
        mutation budget is concentrated (fuzz_round records carry it):
        entry count, total/mean/percentile energies, and how many live
        entries came from crashing lanes."""
        if not self.entries:
            return dict(entries=0)
        en = np.asarray([e["energy"] for e in self.entries])
        return dict(
            entries=len(self.entries),
            total=round(float(en.sum()), 3),
            mean=round(float(en.mean()), 3),
            p50=round(float(np.percentile(en, 50)), 3),
            p90=round(float(np.percentile(en, 90)), 3),
            max=round(float(en.max()), 3),
            crash_entries=sum(1 for e in self.entries
                              if e.get("crash_code", 0)))

    # ------------------------------------------------------------------
    def observe(self, knobs_batch, seeds, hashes_u64, crashed, codes,
                parent_ids, round_no: int, sketches=None,
                last_op=None, lat_p99=None, burst=None,
                origin=None) -> dict:
        """Fold one harvested round into the corpus. `knobs_batch` is the
        HOST knob batch that ran, `hashes_u64` the per-lane schedule
        hashes, `parent_ids` the corpus entry id each lane mutated from
        (schedule()'s ids; -1 for base/bootstrap lanes), `sketches` the
        optional [B, S] prefix-coverage sketch batch (SimState.cov_sketch
        — enables the early-divergence admission bonus), `last_op` the
        optional int[B] per-lane LAST applied havoc operator
        (KnobPlan.mutate's third output; -1 = untouched), `lat_p99` the
        optional int[B] per-lane end-to-end p99 estimate
        (parallel.stats.lane_e2e_p99 — enables the opt-in tail-latency
        admission bonus when self.lat_bonus > 0), `burst` the optional
        int[B] per-lane deepest-transient-spike metric
        (parallel.stats.lane_burst off the windowed series — enables
        the opt-in burst admission bonus when self.burst_bonus > 0).
        `origin` the optional bool[B] LDFI mask (search/ldfi.py):
        True marks a lane that ran a lineage-targeted vector — its
        admitted entry is tagged `origin="targeted"` (an ADDITIVE key:
        havoc entries carry no origin at all, so campaigns without the
        LDFI arm stay byte-identical at the store level) and the stats
        gain `targeted_yield`, targeted admissions counted the same way
        op_yield's "base" slot counts them (a targeted lane's last_op
        is -1). Returns
        admission stats; with `last_op` given they include `op_yield` —
        admissions attributed by operator (int64[N_MUT_OPS + 1], last
        slot = "base"), summing exactly to `new`: which operators'
        mutants actually bought coverage, not just which ran."""
        new = 0
        new_crash_codes = []
        targeted_yield = 0
        op_yield = (np.zeros(N_MUT_OPS + 1, np.int64)
                    if last_op is not None else None)
        div_slot = None
        n_slots = 0
        if sketches is not None:
            sk = np.asarray(sketches)
            if sk.ndim == 2 and sk.shape[1] > 0:
                # fold into the CROSS-ROUND consensus counters first, then
                # measure each lane against the updated campaign modal —
                # round 1 of a fresh corpus reproduces the old per-round
                # modal exactly; later rounds judge novelty against the
                # whole campaign's history (and, via the durable store,
                # prior segments and other workers)
                self._fold_sketches(sk)
                if self.div_bonus > 0:
                    n_slots = sk.shape[1]
                    div_slot = first_divergence_slots(
                        sk, consensus=self.consensus_sketch())
        lat_rel = None
        if lat_p99 is not None and self.lat_bonus > 0:
            lp = np.asarray(lat_p99, np.float64)
            lat_max = float(lp.max()) if lp.size else 0.0
            if lat_max > 0:
                # tail-amplification bonus scale: each lane's p99
                # relative to the round's worst tail, in [0, 1]
                lat_rel = lp / lat_max
        burst_rel = None
        if burst is not None and self.burst_bonus > 0:
            bp = np.asarray(burst, np.float64)
            burst_max = float(bp.max()) if bp.size else 0.0
            if burst_max > 0:
                # burst-amplification bonus scale: each lane's deepest
                # per-window spike relative to the round's worst, [0, 1]
                burst_rel = bp / burst_max
        for e in self.entries:
            e["energy"] = max(0.05, e["energy"] * self.decay)
        for i in range(len(seeds)):
            h = int(hashes_u64[i])
            hit_crash = bool(crashed[i])
            if hit_crash and int(codes[i]) not in self.crash_codes:
                self.crash_codes.add(int(codes[i]))
                new_crash_codes.append(int(codes[i]))
            if h in self._seen:
                continue
            self._seen.add(h)
            new += 1
            if op_yield is not None:
                o = int(last_op[i])
                op_yield[o if 0 <= o < N_MUT_OPS else N_MUT_OPS] += 1
            energy = 3.0 if hit_crash else 1.0
            slot = None
            if div_slot is not None:
                # early-divergence bonus: a lane whose schedule left the
                # round's consensus prefix at slot j gets up to
                # x(1 + div_bonus) admission energy, linear in how early
                # (j == n_slots — never diverged in-window — gets none)
                slot = int(div_slot[i])
                energy *= 1.0 + self.div_bonus * (n_slots - slot) / n_slots
            if lat_rel is not None:
                # tail-latency bonus (r16): a lane whose own p99 sits
                # at the round's worst tail gets up to x(1 + lat_bonus)
                # admission energy, linear in relative tail height —
                # the divergence-bonus treatment for tail amplification
                energy *= 1.0 + self.lat_bonus * float(lat_rel[i])
            if burst_rel is not None:
                # transient-spike bonus (r21): a lane whose deepest
                # per-window spike sits at the round's worst gets up
                # to x(1 + burst_bonus) admission energy — amplifies
                # mutants by their worst MOMENT, not worst aggregate
                energy *= 1.0 + self.burst_bonus * float(burst_rel[i])
            entry = dict(id=self._next_id, hash=h, seed=int(seeds[i]),
                         knobs=KnobPlan.lane(knobs_batch, i),
                         energy=min(self.energy_cap, energy),
                         round=int(round_no), div_slot=slot,
                         crash_code=int(codes[i]) if hit_crash else 0)
            if origin is not None and bool(origin[i]):
                entry["origin"] = "targeted"
                targeted_yield += 1
            self._next_id += 1
            self._insert(entry)
            if self.track_admissions:
                self.admitted_unmerged.append(entry)
            parent = self._by_id.get(int(parent_ids[i]))
            if parent is not None:
                parent["energy"] = min(
                    self.energy_cap, parent["energy"] * self.reward)
        out = dict(new=new, size=len(self.entries),
                   new_crash_codes=new_crash_codes)
        if op_yield is not None:
            out["op_yield"] = op_yield
        if origin is not None:
            out["targeted_yield"] = targeted_yield
        return out

    # ------------------------------------------------------------------
    def schedule(self, batch: int):
        """Pick the next round's parents: energy-weighted sampling with
        replacement, with a `fresh_frac` floor of unmutated base lanes.
        Returns (host knob batch [batch, ...], parent entry ids [batch],
        -1 for base lanes)."""
        ids = np.full(batch, -1, np.int64)
        out = [self.plan.base_knobs() for _ in range(batch)]
        if self.entries:
            en = np.asarray([e["energy"] for e in self.entries])
            p = en / en.sum()
            pick = self.rng.choice(len(self.entries), size=batch, p=p)
            mutate_lane = self.rng.random(batch) >= self.fresh_frac
            for i in range(batch):
                if mutate_lane[i]:
                    ent = self.entries[int(pick[i])]
                    out[i] = ent["knobs"]
                    ids[i] = ent["id"]
        return KnobPlan.stack(out), ids


def merge_consensus(corpora, tally=None):
    """The consensus all-reduce, applied to corpus state (r13): drain
    every shard corpus's DELTA counters (what it folded since the last
    merge) into the campaign tally, then install an independent copy of
    the tally as every corpus's consensus counters — afterwards each
    shard's divergence energy measures novelty against the whole
    campaign's history, not just its own shard's (the r10 cross-shard
    follow-on). Returns the updated tally; the driver (search/shard.py)
    threads it between merges.

    Delta-based on purpose: installing the tally and then re-summing
    whole counter sets at the next merge would count the shared history
    once per shard. Summing only the per-shard deltas keeps the tally
    exact, and makes the fold associative/commutative — merge order
    cannot fork shards. Deltas never prune (`_fold_sketches` bounds
    them by the lanes between merges); the tally itself is pruned with
    the same deterministic rule as a corpus's own counters, applied at
    install time, so every shard holds the identical post-prune view.
    The 1-shard sharded campaign never calls this (nothing is
    cross-shard there), keeping it bit-identical to the unsharded
    fuzzer by construction."""
    deltas = [c._slot_delta for c in corpora if c._slot_delta is not None]
    if not deltas and tally is None:
        return None
    n_slots = max([len(d) for d in deltas]
                  + ([len(tally)] if tally is not None else []))
    merged: list[dict[int, int]] = [
        dict(tally[j]) if tally is not None and j < len(tally) else dict()
        for j in range(n_slots)]
    for d in deltas:
        for j, counts in enumerate(d):
            mj = merged[j]
            for v, c in counts.items():
                mj[v] = mj.get(v, 0) + c
    for j, mj in enumerate(merged):
        if len(mj) > 8192:
            keep = sorted(mj.items(), key=lambda kv: (-kv[1], kv[0]))[:4096]
            merged[j] = dict(keep)
    for c in corpora:
        c._slot_counts = [dict(s) for s in merged]
        if c._slot_delta is not None:
            c._slot_delta = [dict() for _ in range(n_slots)]
    return merged
