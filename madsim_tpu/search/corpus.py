"""The fuzz corpus: interesting knob vectors, energy-scheduled.

AFL keeps inputs that reached new edges; the schedule fuzzer keeps knob
vectors whose lane produced a `sched_hash` never seen before — the corpus
is KEYED AND DEDUPED by the coverage digest itself (one entry per distinct
u64 schedule hash), so it can only grow when coverage grows. Host-side and
numpy-only: the corpus is bookkeeping between device rounds, sized in
kilobytes, and never on the hot path (corpus work overlaps device compute
in the pipelined fuzz loop exactly like explore()'s dedup).

Energy rules (the AFL-style scheduler, simplified to what the batched
setting needs):
  - admission energy 1.0; a lane that CRASHED enters with 3.0 (crash
    neighborhoods are where more crashes live);
  - a parent whose mutant discovered a new schedule is rewarded
    (energy x1.5, capped) — productive regions get more mutation budget;
  - every round all energies decay x`decay` toward a floor, so stale
    entries fade instead of starving newcomers;
  - `schedule()` samples parents with probability proportional to energy,
    and keeps `fresh_frac` of each batch on the UNMUTATED base knobs — an
    exploration floor so the corpus never traps the sweep in one basin;
  - (r10) lanes that diverged from the round's consensus prefix EARLY get
    an admission bonus scaled by depth (up to x(1+div_bonus)), computed
    from the on-device prefix-coverage sketches (SimState.cov_sketch):
    an early split means the mutation rewired the schedule near its
    root, and everything downstream of it is new territory — the
    per-prefix signal the terminal sched_hash alone cannot see.
"""

from __future__ import annotations

import numpy as np

from ..parallel.stats import first_divergence_slots
from .mutate import KnobPlan


class Corpus:
    def __init__(self, plan: KnobPlan, rng=None, max_entries: int = 4096,
                 fresh_frac: float = 0.125, decay: float = 0.97,
                 reward: float = 1.5, energy_cap: float = 8.0,
                 div_bonus: float = 1.0):
        self.plan = plan
        self.rng = rng if rng is not None else np.random.default_rng(0)
        self.max_entries = int(max_entries)
        self.fresh_frac = float(fresh_frac)
        self.decay = float(decay)
        self.reward = float(reward)
        self.energy_cap = float(energy_cap)
        self.div_bonus = float(div_bonus)   # 0 = sched_hash-only energy
        self.entries: list[dict] = []   # slot-stable: eviction replaces
        self._seen: set[int] = set()    # every hash ever admitted (dedupe)
        self.crash_codes: set[int] = set()
        # parent attribution is by monotonic entry id, not slot index:
        # schedule() hands out ids and observe() rewards through this map,
        # so an eviction (same round or, under the pipelined loop, a later
        # one) can never hand a stale parent's reward to the slot's fresh
        # occupant — the reward just finds nobody
        self._next_id = 0
        self._by_id: dict[int, dict] = {}

    def __len__(self) -> int:
        return len(self.entries)

    # ------------------------------------------------------------------
    def observe(self, knobs_batch, seeds, hashes_u64, crashed, codes,
                parent_ids, round_no: int, sketches=None) -> dict:
        """Fold one harvested round into the corpus. `knobs_batch` is the
        HOST knob batch that ran, `hashes_u64` the per-lane schedule
        hashes, `parent_ids` the corpus entry id each lane mutated from
        (schedule()'s ids; -1 for base/bootstrap lanes), `sketches` the
        optional [B, S] prefix-coverage sketch batch (SimState.cov_sketch
        — enables the early-divergence admission bonus). Returns
        admission stats."""
        new = 0
        new_crash_codes = []
        div_slot = None
        n_slots = 0
        if sketches is not None and self.div_bonus > 0:
            sk = np.asarray(sketches)
            if sk.ndim == 2 and sk.shape[1] > 0:
                div_slot = first_divergence_slots(sk)
                n_slots = sk.shape[1]
        for e in self.entries:
            e["energy"] = max(0.05, e["energy"] * self.decay)
        for i in range(len(seeds)):
            h = int(hashes_u64[i])
            hit_crash = bool(crashed[i])
            if hit_crash and int(codes[i]) not in self.crash_codes:
                self.crash_codes.add(int(codes[i]))
                new_crash_codes.append(int(codes[i]))
            if h in self._seen:
                continue
            self._seen.add(h)
            new += 1
            energy = 3.0 if hit_crash else 1.0
            slot = None
            if div_slot is not None:
                # early-divergence bonus: a lane whose schedule left the
                # round's consensus prefix at slot j gets up to
                # x(1 + div_bonus) admission energy, linear in how early
                # (j == n_slots — never diverged in-window — gets none)
                slot = int(div_slot[i])
                energy *= 1.0 + self.div_bonus * (n_slots - slot) / n_slots
            entry = dict(id=self._next_id, hash=h, seed=int(seeds[i]),
                         knobs=KnobPlan.lane(knobs_batch, i),
                         energy=min(self.energy_cap, energy),
                         round=int(round_no), div_slot=slot,
                         crash_code=int(codes[i]) if hit_crash else 0)
            self._next_id += 1
            self._by_id[entry["id"]] = entry
            if len(self.entries) < self.max_entries:
                self.entries.append(entry)
            else:                        # replace the coldest slot
                j = int(np.argmin([e["energy"] for e in self.entries]))
                del self._by_id[self.entries[j]["id"]]
                self.entries[j] = entry
            parent = self._by_id.get(int(parent_ids[i]))
            if parent is not None:
                parent["energy"] = min(
                    self.energy_cap, parent["energy"] * self.reward)
        return dict(new=new, size=len(self.entries),
                    new_crash_codes=new_crash_codes)

    # ------------------------------------------------------------------
    def schedule(self, batch: int):
        """Pick the next round's parents: energy-weighted sampling with
        replacement, with a `fresh_frac` floor of unmutated base lanes.
        Returns (host knob batch [batch, ...], parent entry ids [batch],
        -1 for base lanes)."""
        ids = np.full(batch, -1, np.int64)
        out = [self.plan.base_knobs() for _ in range(batch)]
        if self.entries:
            en = np.asarray([e["energy"] for e in self.entries])
            p = en / en.sum()
            pick = self.rng.choice(len(self.entries), size=batch, p=p)
            mutate_lane = self.rng.random(batch) >= self.fresh_frac
            for i in range(batch):
                if mutate_lane[i]:
                    ent = self.entries[int(pick[i])]
                    out[i] = ent["knobs"]
                    ids[i] = ent["id"]
        return KnobPlan.stack(out), ids
