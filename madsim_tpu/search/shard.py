"""Mesh-sharded fuzz campaigns: the whole search loop, scaled out (r13).

`fuzz()` (search/fuzz.py) drives one device's worth of lanes from one
host-side corpus — `run_fused_sharded` ran a sweep SPMD since r6, but the
SEARCH loop never used it. This driver shards the campaign itself over a
JAX mesh, the Podracer batched-actor split (PAPERS.md) applied to
schedule search:

  - each device shard owns a corpus slice and a seed space. A shard is
    just another worker id (the r11 insight): shard `s` of worker `w` in
    an `S`-shard campaign mints entry ids in namespace `w*S + s`, runs
    seeds `WORKER_SEED_STRIDE` apart, and schedules parents with its own
    rng stream (`rng_seed + s`) — so cross-shard merge is the same
    merge-by-construction the multi-process campaign already proved;
  - mutation never leaves the device: the round's parent knob batch
    lands on the mesh already lane-sharded, and ONE masked SPMD havoc
    dispatch (`KnobPlan.mutate_masked`) derives every shard's mutants in
    place — XLA partitions the all-operand mutation math over the lane
    axis, so each shard's draws happen on its own device, bootstrap
    shards ride the same dispatch behind the mask, and one executable
    serves the whole mesh width; `apply_knobs` then writes the mutants
    into the sharded init state SPMD and the round runs as one fused
    dispatch whose only cross-shard traffic is the halt all-reduce;
  - per-round host harvests shrink to the coverage question: the
    campaign-global dedup rides the all-gathered O(distinct) coverage
    digest (`parallel.stats.coverage_digest` over the sharded batch —
    its lexsort lowers to an all-gather + replicated sort, and only the
    packed distinct prefix crosses to the host via `digest_hashes`),
    and round-level divergence telemetry rides the on-device consensus
    all-reduce (`consensus_allreduce`) instead of shipping per-lane
    sketches to a host modal. Per-shard corpora still read their own
    [batch] lanes — kilobytes, the same bill `fuzz()` pays per shard;
  - shards exchange what they learned at MERGE points (every
    `merge_every` rounds, and at every durability sync): admissions
    since the last merge flow through each corpus's outbox into every
    other shard (`admit_foreign` — keyed by coverage, order-independent)
    and the cross-round consensus sketch counters fold through
    `corpus.merge_consensus`, so divergence energy rewards novelty
    against the WHOLE campaign's history — the r10 cross-shard
    follow-on, one all-reduce wider.

Bit-identity contract: at `shards=1` nothing is cross-shard — no merge
runs, namespace/seed/rng formulas collapse to `fuzz()`'s — and the
1-device-mesh executables compute the unsharded values, so the sharded
campaign is bit-identical to the unsharded fuzzer (coverage keys, entry
files, energies; tests/test_shard.py holds it over saturating,
crash-rich wal_kv, and flagship raft).

Durable campaigns (`corpus_dir=`): every shard syncs into the same
`service.CorpusStore` under its own namespace, but the GROUP's scheduler
state (all shards' orders/energies/rng + the consensus tally) is one
atomic json per sync (`state/g<worker>.json`) — a SIGKILL can never tear
the shards of one worker apart, and a resume restores every shard to the
same round. Cross-process campaigns compose: another process's shards
(or plain `fuzz()` workers) are just more namespaces merged at sync.
"""

from __future__ import annotations

import time

import jax
import numpy as np

from ..parallel import stats
from ..parallel.mesh import SEED_AXIS, seed_mesh
from .corpus import Corpus, YIELD_NAMES, merge_consensus
from .fuzz import WORKER_SEED_STRIDE, _env_verify_resume, _lat_fields
from .mutate import N_MUT_OPS, OP_NAMES, KnobPlan


def shard_worker_id(worker_id: int, shard: int, shards: int) -> int:
    """The shard↔worker-namespace mapping: shard `shard` of worker
    `worker_id` in an `shards`-wide campaign owns namespace
    `worker_id*shards + shard`. Collapses to `worker_id` at shards=1
    (the bit-identity case), keeps groups disjoint, and inherits the
    WORKER_SEED_STRIDE contract: seed spaces stay collision-free while
    workers*shards <= 64 per base_seed (shard bigger fleets across
    base_seeds, exactly like workers)."""
    return worker_id * shards + shard


def fuzz_sharded(rt, max_steps: int, batch: int = 512, shards: int | None
                 = None, devices=None, max_rounds: int = 16,
                 dry_rounds: int = 3, base_seed: int = 0, chunk: int = 512,
                 pipeline: bool = True, fused: bool = True,
                 dup_slots: int = 2, havoc: int = 3,
                 fresh_frac: float = 0.125, rng_seed: int = 0,
                 observer=None, minimize: bool = False,
                 div_bonus: float | None = None,
                 lat_bonus: float | None = None,
                 burst_bonus: float | None = None, merge_every: int = 1,
                 corpus_dir: str | None = None, worker_id: int = 0,
                 sync_every: int = 1, verify_resume: bool | None = None,
                 ldfi=None):
    """Coverage-guided schedule fuzzing, sharded across a device mesh.

    `batch` is PER SHARD: a round runs `shards*batch` lanes as one SPMD
    dispatch, so throughput scales with the mesh while every shard's
    search loop keeps `fuzz()`'s exact shape. `shards` defaults to every
    local device (pass `devices` to pin a subset; the mesh is 1-D over
    `devices[:shards]`). `merge_every` sets the cross-shard exchange
    cadence in rounds (coverage entries + consensus fold); dry-stop and
    campaign totals are always judged on the GLOBAL coverage frontier
    (the all-gathered digest), so a late merge can delay sharing, never
    coverage accounting. Durable campaigns (`corpus_dir=`) merge at
    every sync point instead (`sync_every` — the persisted group state
    must be post-merge so a resume restores what the shards knew);
    `verify_resume` adds the run-twice guard on the first post-resume
    round (see `fuzz()`).

    `ldfi` (an `LdfiConfig`, r22) arms the lineage-targeted search arm
    exactly as in `fuzz()`, with ONE support pool shared across the
    mesh: every shard harvests green supports into it and every shard's
    targeted tail is synthesized against the pooled hitting set — the
    cross-shard pooling the single-corpus fuzzer can't do. Targeted
    rows ride the tail of each mutating shard's lane slice behind the
    same masked SPMD havoc dispatch (mask off ⇒ parents pass through,
    zero extra compiled programs); the one extra cost is a host
    round-trip of the round's knob batch to splice the rows in. The
    pool itself is not persisted across resume — only the cumulative
    admission ledger (`targeted_yield` in the group state) survives;
    the pool re-harvests within a round or two.

    Returns `fuzz()`'s result schema plus:
      shards        the mesh width
      per_shard     [{shard, worker_id, corpus_size, coverage, crashes,
                     seeds_run}] — one row per shard, the view
                    ProgressObserver renders per round
    Other args match `fuzz()`. Randomness: shard s's corpus scheduler
    draws from rng_seed+s, while the mutation master is fuzz()'s exact
    formula (one key per round, split over all S*B lanes) — at shards=1
    both collapse to `fuzz(rng_seed=rng_seed)`'s streams exactly.
    """
    if devices is None:
        devices = jax.devices()
    if shards is None:
        shards = len(devices)
    if shards > len(devices):
        raise ValueError(f"shards={shards} > available devices "
                         f"({len(devices)}) — grow the mesh (e.g. "
                         "XLA_FLAGS=--xla_force_host_platform_device_"
                         "count=N on CPU) or lower shards")
    devices = list(devices)[:shards]
    mesh = seed_mesh(devices)
    S = shards
    plan = KnobPlan.from_runtime(rt, dup_slots=dup_slots)
    eff_w = [shard_worker_id(worker_id, s, S) for s in range(S)]
    # ONE mutation master, fuzz()'s exact formula: the global per-round
    # key splits over all S*B lanes, so shards draw distinct mutations
    # by lane position and the 1-shard stream equals fuzz()'s
    master = jax.random.PRNGKey(np.uint32(rng_seed ^ 0x5EED5EED))
    op_hist = np.zeros(N_MUT_OPS, np.int64)
    yield_hist = np.zeros(N_MUT_OPS + 1, np.int64)   # see fuzz()
    if verify_resume is None:
        verify_resume = _env_verify_resume()
    pool = None
    targeted_total = 0
    targeted_yield_total = 0
    if ldfi is not None:
        if rt.cfg.trace_cap <= 0:
            raise ValueError(
                "fuzz_sharded(ldfi=...) needs the flight recorder "
                "compiled in (cfg.trace_cap > 0) — support extraction "
                "walks the causal ring")
        from ..obs.support import extract_support
        from .ldfi import SupportPool, synthesize
        pool = SupportPool()    # ONE pool, shared across the mesh

    stores = buckets = None
    tally = None
    round_start = 0
    dry = 0
    wall_prior = 0.0
    if corpus_dir is not None:
        from ..service.buckets import CrashBuckets
        from ..service.store import CorpusStore, store_signature
        sig = store_signature(rt, plan)
        # one store handle per shard: scan cursors and entry-write dedup
        # are per-corpus state, exactly like one handle per worker
        stores = [CorpusStore(corpus_dir, signature=sig) for _ in range(S)]
        buckets = CrashBuckets(stores[0])
        # triage-plane row table (service/triage.py attribution) —
        # write-once, identical bytes from every worker/shard
        stores[0].write_triage_rows(plan)
        group = stores[0].load_shard_group_state(worker_id)
        from ..service.store import StoreMismatch
        if group and group.get("shards") != S:
            raise StoreMismatch(
                f"corpus dir holds a {group.get('shards')}-shard group "
                f"state for worker {worker_id}; resuming with shards={S} "
                "would remap every shard namespace — finish or discard "
                "the old group first")
        # the shard↔worker mapping numerically overlaps plain worker
        # ids (group 0 at 2 shards owns namespaces 0 AND 1): refuse a
        # namespace some OTHER owner's scheduler state already claims,
        # before any entry file could collide
        own = f"shard group g{worker_id}"
        claimed = stores[0].claimed_namespaces()
        for ns in eff_w:
            owner = claimed.get(ns)
            if owner is not None and owner != own:
                raise StoreMismatch(
                    f"namespace {ns} (shard {ns - eff_w[0]} of {own}) is "
                    f"already owned by {owner} in this corpus dir — give "
                    "every worker on one dir the same shards= and "
                    "non-overlapping ids (worker_id*shards+s must be "
                    "unique; see DESIGN §15)")
        round_start = int(group.get("rounds_done", 0)) if group else 0
        dry = int(group.get("dry", 0)) if group else 0
        wall_prior = float(group.get("wall_s", 0.0)) if group else 0.0
        if group and group.get("op_hist"):
            op_hist[:] = np.asarray(group["op_hist"], np.int64)
        if group and group.get("op_yield"):
            yield_hist[:] = np.asarray(group["op_yield"], np.int64)
        if group and group.get("targeted_yield") is not None \
                and ldfi is not None:
            targeted_yield_total = int(group["targeted_yield"])
        shard_states = group.get("shard_states") if group else None
        corpora = []
        for s in range(S):
            c = stores[s].load_corpus(
                plan, worker_id=eff_w[s], rng_seed=rng_seed + s,
                fresh_frac=fresh_frac,
                div_bonus=1.0 if div_bonus is None else div_bonus,
                lat_bonus=0.0 if lat_bonus is None else lat_bonus,
                burst_bonus=0.0 if burst_bonus is None else burst_bonus,
                state=(shard_states[s] if shard_states else None))
            c.track_admissions = True
            corpora.append(c)
        if group and group.get("tally") is not None:
            tally = [{int(v): int(c) for v, c in slot}
                     for slot in group["tally"]]
        merge_every = sync_every     # persisted state must be post-merge
    else:
        corpora = []
        for s in range(S):
            c = Corpus(plan, rng=np.random.default_rng(rng_seed + s),
                       fresh_frac=fresh_frac, worker_id=eff_w[s],
                       div_bonus=1.0 if div_bonus is None else div_bonus,
                       lat_bonus=0.0 if lat_bonus is None else lat_bonus,
                       burst_bonus=(0.0 if burst_bonus is None
                                    else burst_bonus))
            c.track_admissions = True
            corpora.append(c)
    if div_bonus is not None:
        for c in corpora:
            c.div_bonus = float(div_bonus)
    if lat_bonus is not None:
        for c in corpora:
            c.lat_bonus = float(lat_bonus)
    if burst_bonus is not None:
        for c in corpora:
            c.burst_bonus = float(burst_bonus)

    from jax.sharding import NamedSharding, PartitionSpec as P
    lane_sharding = NamedSharding(mesh, P(SEED_AXIS))

    def launch(r):
        """Schedule per-shard parents, derive the round's mutants as ONE
        masked SPMD dispatch over the mesh-sharded knob batch, and run
        the round fused — nothing here blocks (mutate/apply/run/digest
        are all queued async)."""
        seeds_np = []
        parent_knobs = []
        ids_list = []
        mutated = []
        for s in range(S):
            lane0 = (base_seed + eff_w[s] * WORKER_SEED_STRIDE
                     + r * batch) % (1 << 32)
            seeds_np.append((np.arange(batch, dtype=np.uint64)
                             + np.uint64(lane0)).astype(np.uint32))
            if r == 0 or len(corpora[s]) == 0:
                parent_knobs.append(plan.base_batch(batch))
                ids_list.append(np.full(batch, -1, np.int64))
                mutated.append(False)
            else:
                parents, ids_s = corpora[s].schedule(batch)
                parent_knobs.append(parents)
                ids_list.append(ids_s)
                mutated.append(True)
        seeds = np.concatenate(seeds_np)
        ids = np.concatenate(ids_list)
        # per-leaf device_put keeps the dict's key order (a pytree put
        # would sort it, reordering entry-npz members vs fuzz()'s
        # bootstrap rounds — bit-identity is checked down to store
        # bytes); each leaf lands already sharded over the mesh
        parents_global = {
            k: jax.device_put(
                np.concatenate([p[k] for p in parent_knobs]),
                lane_sharding)
            for k in parent_knobs[0]}
        targeted = np.zeros(batch * S, bool)
        if any(mutated):
            # one SPMD havoc dispatch for the whole mesh: bootstrap
            # shards' lanes pass through unmutated via the mask (and
            # never count in the histogram); the mutation math
            # partitions over the lane axis — it never leaves each
            # shard's device, and one executable serves the mesh width
            mask_np = np.repeat(np.asarray(mutated, bool), batch)
            deal = None
            if pool is not None and len(pool):
                # the targeted arm (r22): synthesize against the ONE
                # mesh-shared pool, deal the vectors round-robin over
                # the mutating shards' lane-slice tails, and mask those
                # tails off — the SPMD havoc dispatch passes their
                # parents through (hist/last_op count real mutants
                # only) and the rows are spliced in host-side below
                per = min(batch, max(1, int(batch * ldfi.frac)))
                mut_idx = [s for s in range(S) if mutated[s]]
                tvecs, tseeds = synthesize(plan, pool, per * len(mut_idx),
                                           max_cuts=ldfi.max_cuts,
                                           lead=ldfi.lead,
                                           rank_cap=ldfi.rank_cap,
                                           with_seeds=True)
                if tvecs:
                    deal = [[] for _ in range(S)]
                    deal_seeds = [[] for _ in range(S)]
                    for j, v in enumerate(tvecs):
                        s = mut_idx[j % len(mut_idx)]
                        if len(deal[s]) < per:
                            deal[s].append(v)
                            deal_seeds[s].append(tseeds[j])
                    for s in mut_idx:
                        tn = len(deal[s])
                        if tn:
                            lo = (s + 1) * batch - tn
                            hi = (s + 1) * batch
                            mask_np[lo:hi] = False
                            targeted[lo:hi] = True
                            # pin targeted lanes to the green seeds
                            # their cuts were timed against (edge
                            # instants are seed-specific)
                            for j, ts_seed in enumerate(deal_seeds[s]):
                                if ts_seed is not None:
                                    seeds[lo + j] = np.uint32(ts_seed)
            mask = jax.device_put(mask_np, lane_sharding)
            knobs_dev, hist, last_op = plan.mutate_masked(
                parents_global,
                jax.random.fold_in(master, np.uint32(r)), mask,
                havoc=havoc)
            if deal is not None and targeted.any():
                # splice the synthesized rows over the masked tails —
                # one host round-trip of the knob batch, the targeted
                # arm's only extra cost (zero new compiled programs:
                # apply/run see an ordinary mesh-sharded knob dict)
                spliced = {k: np.asarray(v).copy()
                           for k, v in knobs_dev.items()}
                for s in range(S):
                    tn = len(deal[s])
                    if not tn:
                        continue
                    lo, hi = (s + 1) * batch - tn, (s + 1) * batch
                    stacked = KnobPlan.stack(deal[s])
                    for k in spliced:
                        spliced[k][lo:hi] = stacked[k]
                    ids[lo:hi] = -1      # synthesized, not a parent's kid
                knobs_dev = {k: jax.device_put(v, lane_sharding)
                             for k, v in spliced.items()}
        else:
            knobs_dev, hist = parents_global, None
            last_op = np.full(batch * S, -1, np.int64)
        # init on the default device, then place lanes over the mesh
        # BEFORE the knob write, so apply_knobs runs SPMD per shard
        from ..parallel.mesh import shard_batch
        state = shard_batch(rt.init_batch(seeds), mesh)
        state = plan.apply(state, knobs_dev)
        if fused:
            # run_fused_sharded is the lane→shard dispatch plumbing;
            # the state is already mesh-placed, so its device_put is a
            # no-op re-placement and the round runs as one SPMD dispatch
            state = rt.run_fused_sharded(state, max_steps, chunk,
                                         mesh=mesh)
        else:
            state, _ = rt.run(state, max_steps, chunk)
        # the all-gathered O(distinct) coverage digest (queued async):
        # campaign-global dedup without shipping [S*B] hashes per round
        pairs, n = stats.coverage_digest(state)
        return (seeds, ids, knobs_dev, hist, last_op, mutated, targeted,
                state, pairs, n)

    def harvest(launched):
        """Block on one round. Per-shard corpora read their own [batch]
        hash/crash/knob lanes (kilobytes per shard — the same bill
        fuzz() pays); the global dedup reads only the digest prefix."""
        (seeds, ids, knobs_dev, hist, last_op, mutated, targeted, state,
         pairs, n) = launched
        knobs_host = {k: np.asarray(v) for k, v in knobs_dev.items()}
        hashes = stats.sched_hash_u64(state)
        digest = stats.digest_hashes(pairs, n)
        sk = np.asarray(state.cov_sketch)
        sketches = sk if sk.ndim == 2 and sk.shape[1] > 0 else None
        # tail-latency signal (r16) — fuzz()'s harvest shape, so the
        # 1-shard campaign's corpus energies stay byte-identical; the
        # brief only when something will consume it
        lat_p99 = stats.lane_e2e_p99(state)
        lat_brief = (stats.latency_brief(state)
                     if lat_p99 is not None
                     and (observer is not None or stores is not None)
                     else None)
        # transient-spike signal (r21) — fuzz()'s harvest shape
        burst = stats.lane_burst(state)
        if hist is not None:
            op_hist[:] += np.asarray(hist)
        # `targeted` rides LAST so _verified_harvest's positional
        # key_of indices stay valid
        return (seeds, ids, knobs_host, hashes, digest,
                np.asarray(state.crashed), np.asarray(state.crash_code),
                mutated, np.asarray(last_op), sketches, state,
                lat_p99, lat_brief, burst, targeted)

    def do_merge():
        """The cross-shard exchange: admissions since the last merge
        flow into every other shard (order-independent set union keyed
        by coverage), then the consensus counters fold through one
        tally — every shard leaves judging novelty against the whole
        campaign's history."""
        nonlocal tally
        if S == 1:
            corpora[0].admitted_unmerged.clear()
            return
        outboxes = [list(c.admitted_unmerged) for c in corpora]
        for c in corpora:
            c.admitted_unmerged.clear()
        for s in range(S):
            for t in range(S):
                if t == s:
                    continue
                for e in outboxes[t]:
                    corpora[s].admit_foreign(e)
        tally = merge_consensus(corpora, tally)

    def sync_group(rounds_done, dry_now, wall_s, lat_brief=None):
        do_merge()
        merged = 0
        for s in range(S):
            merged += stores[s].merge_foreign(corpora[s])
            stores[s].persist_entries(corpora[s], eff_w[s])
        # timeline row BEFORE the group commit (fuzz()'s ordering: a
        # kill between the two re-appends an identical row on resume;
        # campaign_timeline dedups by rounds_done)
        mrow = dict(
            t=time.time(), worker=worker_id, shards=S,
            rounds_done=rounds_done, coverage=len(seen),
            seeds_run=rounds_done * batch * S, crashes=n_crashed,
            corpus_size=sum(len(c) for c in corpora),
            dry=dry_now, wall_s=round(wall_s, 3),
            op_yield=[int(x) for x in yield_hist])
        if lat_brief is not None:
            mrow.update(_lat_fields(lat_brief))
        if ldfi is not None:
            mrow["targeted_yield"] = targeted_yield_total
        stores[0].append_metrics(worker_id, mrow, group=True)
        stores[0].write_shard_group_state(
            corpora, worker_id=worker_id, shards=S,
            rounds_done=rounds_done, dry=dry_now, op_hist=op_hist,
            wall_s=wall_s, tally=tally, op_yield=yield_hist,
            targeted_yield=(targeted_yield_total if ldfi is not None
                            else None))
        return merged

    # global coverage frontier: on resume, the union of every shard's
    # cumulative view — dry detection continues across resumes
    seen: set[int] = set()
    shard_seen: list[set[int]] = [set() for _ in range(S)]
    if stores is not None:
        for s in range(S):
            keys = corpora[s].coverage_keys()
            shard_seen[s] = keys
            seen |= keys
    crashes: dict[int, int] = {}
    repros: dict[int, dict] = {}
    opened_buckets: list[str] = []
    n_crashed = 0
    shard_crashes = [0] * S
    # codes any shard already knows (restored crash_codes on a resume)
    # are not news to a later round's record
    seen_crash_codes: set[int] = set()
    for c in corpora:
        seen_crash_codes |= c.crash_codes
    new_per_round: list[int] = []
    rounds = 0
    # speculation launches r+1 before r is harvested; the targeted arm
    # needs r's green supports IN the pool before synthesizing r+1
    speculate = pipeline and fused and stores is None and ldfi is None
    t0 = time.perf_counter()
    pending = (launch(round_start)
               if round_start < max_rounds and dry < dry_rounds else None)
    verify_round = (round_start if verify_resume and stores is not None
                    and round_start > 0 else None)
    for r in range(round_start, max_rounds):
        if pending is None:
            break
        nxt = (launch(r + 1) if speculate and r + 1 < max_rounds else None)
        harvested = harvest(pending)
        if r == verify_round:
            harvested = _verified_harvest(
                rt, plan, harvested, harvest, max_steps, chunk, fused, mesh)
        (seeds, ids, knobs_host, hashes, digest, crashed, codes, mutated,
         last_op, sketches, state, lat_p99, lat_brief, burst,
         targeted) = harvested
        rounds += 1
        corpus_size = 0
        per_shard_rows = []
        round_new_codes: list[int] = []
        round_yield = np.zeros(N_MUT_OPS + 1, np.int64)
        round_targeted_yield = 0
        for s in range(S):
            lo, hi = s * batch, (s + 1) * batch
            sk_s = sketches[lo:hi] if sketches is not None else None
            cstats = corpora[s].observe(
                {k: v[lo:hi] for k, v in knobs_host.items()},
                seeds[lo:hi], hashes[lo:hi], crashed[lo:hi], codes[lo:hi],
                ids[lo:hi], r, sketches=sk_s, last_op=last_op[lo:hi],
                lat_p99=(lat_p99[lo:hi] if lat_p99 is not None else None),
                burst=(burst[lo:hi] if burst is not None else None),
                origin=(targeted[lo:hi] if ldfi is not None else None))
            round_yield += cstats["op_yield"]
            round_targeted_yield += int(cstats.get("targeted_yield", 0))
            shard_seen[s] |= set(hashes[lo:hi].tolist())
            corpus_size += cstats["size"]
            shard_crashes[s] += int(crashed[lo:hi].sum())
            # campaign-level "new" means new to EVERY shard's view —
            # a code one shard already knows is not news to the round
            for c in cstats["new_crash_codes"]:
                if c not in seen_crash_codes:
                    seen_crash_codes.add(c)
                    round_new_codes.append(c)
            per_shard_rows.append(dict(
                shard=s, worker_id=eff_w[s],
                corpus_size=cstats["size"],
                coverage=len(shard_seen[s]),
                new=cstats["new"],
                # per-shard operator yield: this shard's admissions by
                # producing operator (ProgressObserver renders the top)
                op_yield={YIELD_NAMES[i]: int(cstats["op_yield"][i])
                          for i in range(len(YIELD_NAMES))
                          if cstats["op_yield"][i]},
                energy=corpora[s].energy_summary(),
                crashes=int(crashed[lo:hi].sum()),
                seeds_run=rounds * batch))
        yield_hist[:] += round_yield
        if ldfi is not None:
            targeted_total += int(targeted.sum())
            targeted_yield_total += round_targeted_yield
            if len(pool) < ldfi.lanes:
                # harvest green supports into the mesh-shared pool:
                # untouched (last_op == -1), uncrashed, un-aimed lanes
                # from ANY shard — bounded one-time host ring walks
                for i in range(len(seeds)):
                    if len(pool) >= ldfi.lanes:
                        break
                    if (bool(crashed[i]) or int(last_op[i]) >= 0
                            or bool(targeted[i])):
                        continue
                    sup = extract_support(
                        state, int(i), witness=ldfi.witness,
                        replay=ldfi.replay, rt=rt, seed=int(seeds[i]),
                        knobs=KnobPlan.lane(knobs_host, int(i)))
                    if sup is not None:
                        pool.add(sup, seed=int(seeds[i]))
        for i in np.nonzero(crashed)[0]:
            c = int(codes[i])
            if not mutated[int(i) // batch]:
                crashes.setdefault(c, int(seeds[i]))
            if c not in repros:
                kn = KnobPlan.lane(knobs_host, int(i))
                repros[c] = dict(seed=int(seeds[i]), round=r, knobs=kn,
                                 script=plan.to_scenario(kn).describe())
        if buckets is not None and crashed.any():
            # one representative per (code, origin) per round — a
            # code-only dedup would always elect an earlier havoc lane
            # over the tail-riding targeted lanes (see fuzz.py)
            coded: set[tuple] = set()
            for i in np.nonzero(crashed)[0]:
                c = (int(codes[i]),
                     bool(targeted[int(i)]) if ldfi is not None else False)
                if c in coded:
                    continue
                coded.add(c)
                key, opened = buckets.observe_lane(
                    state, int(i), seed=int(seeds[i]),
                    knobs=KnobPlan.lane(knobs_host, int(i)),
                    round_no=r, worker_id=eff_w[int(i) // batch],
                    last_op=int(last_op[int(i)]),
                    origin=(("targeted" if targeted[int(i)] else "havoc")
                            if ldfi is not None else None))
                if opened:
                    opened_buckets.append(key)
        n_crashed += int(crashed.sum())
        fresh = set(digest.tolist()) - seen
        seen |= fresh
        new_per_round.append(len(fresh))
        dry = dry + 1 if not fresh else 0
        if observer is not None:
            rec = dict(
                kind="fuzz_round", round=rounds, batch=batch, shards=S,
                seeds_run=rounds * batch * S, new_schedules=len(fresh),
                distinct_total=len(seen), crashes=n_crashed,
                corpus_size=corpus_size,
                new_crash_codes=round_new_codes,
                per_shard=per_shard_rows,
                # campaign-wide admissions + yield this round (the
                # per-shard split rides in per_shard): sums over shards,
                # so the per-operator counts still sum to `admitted`
                admitted=int(round_yield.sum()),
                op_yield={YIELD_NAMES[i]: int(round_yield[i])
                          for i in range(len(YIELD_NAMES))},
                dry_rounds=dry, wall_s=time.perf_counter() - t0)
            if lat_brief is not None:
                rec.update(_lat_fields(lat_brief))
            if ldfi is not None:
                rec.update(targeted=int(targeted.sum()),
                           targeted_yield=round_targeted_yield,
                           support_pool=len(pool))
            if buckets is not None:
                rec["buckets_opened"] = len(opened_buckets)
            if sketches is not None:
                # round-level divergence depth off the on-device
                # consensus all-reduce — the mesh's modal prefix, not a
                # host re-computation over [S*B] lanes
                modal = stats.consensus_allreduce(state.cov_sketch)
                rec["div_slot_p50"] = int(np.median(
                    stats.first_divergence_slots(sketches,
                                                 consensus=modal)))
            observer.on_round(rec)
        at_merge = (r + 1 - round_start) % merge_every == 0
        stopping = dry >= dry_rounds or r + 1 == max_rounds
        if stores is not None and (at_merge or stopping):
            sync_group(r + 1, dry,
                       wall_prior + time.perf_counter() - t0,
                       lat_brief=lat_brief)
        elif stores is None and (at_merge or stopping):
            do_merge()
        if dry >= dry_rounds:
            break
        pending = nxt if nxt is not None else (
            launch(r + 1) if r + 1 < max_rounds else None)

    result = dict(
        seeds_run=rounds * batch * S,
        rounds=rounds,
        shards=S,
        distinct_schedules=len(seen),
        new_per_round=new_per_round,
        saturated=dry >= dry_rounds,
        crash_first_seed_by_code=crashes,
        crashes=n_crashed,
        crash_repros=repros,
        corpus_size=sum(len(c) for c in corpora),
        per_shard=[dict(shard=s, worker_id=eff_w[s],
                        corpus_size=len(corpora[s]),
                        coverage=len(shard_seen[s]),
                        crashes=shard_crashes[s],
                        seeds_run=rounds * batch)
                   for s in range(S)],
        mutation_ops={OP_NAMES[i]: int(op_hist[i])
                      for i in range(N_MUT_OPS)},
        mutation_yield={YIELD_NAMES[i]: int(yield_hist[i])
                        for i in range(len(YIELD_NAMES))},
    )
    if ldfi is not None:
        result["targeted"] = dict(
            supports=len(pool), truncated_supports=pool.truncated,
            lanes_run=targeted_total, admitted=targeted_yield_total)
    if stores is not None:
        result.update(
            corpus_dir=stores[0].dir,
            rounds_done_total=round_start + rounds,
            buckets_opened=opened_buckets,
            buckets_total=len(stores[0].bucket_keys()))
    if minimize and repros:
        from ..harness.minimize import minimize_knobs
        result["minimized"] = {}
        for c, rep in repros.items():
            try:
                minimal, info = minimize_knobs(rt, plan, rep["knobs"],
                                               rep["seed"], max_steps,
                                               chunk)
                result["minimized"][c] = dict(info, knobs=minimal)
            except Exception as e:  # noqa: BLE001 - repro handle still stands
                result["minimized"][c] = dict(error=f"{type(e).__name__}: {e}")
        if buckets is not None:
            # attach the shrunk fault script to buckets this run opened
            # (matched by crash code — same reporting contract as fuzz())
            for key in buckets.new_keys:
                rec_b = stores[0].load_bucket(key)
                mini = result["minimized"].get(int(rec_b["crash_code"]))
                if mini and "script" in mini:
                    rec_b["minimized"] = {
                        k: v for k, v in mini.items() if k != "knobs"}
                    stores[0].write_bucket(key, rec_b)
    if observer is not None:
        observer.on_done(dict(
            kind="done", distinct_total=len(seen),
            wall_s=time.perf_counter() - t0,
            **{k: v for k, v in result.items()
               if k not in ("crash_repros", "minimized", "per_shard")}))
    return result


def _verified_harvest(rt, plan, harvested, harvest_fn, max_steps, chunk,
                      fused, mesh):
    """The run-twice resume guard (knob-gated, see fuzz(verify_resume=)):
    re-dispatch the SAME (seeds, knobs) batch until two consecutive
    invocations agree on the authoritative outputs
    (utils.verify.agree_twice — a resumed campaign's first fused
    invocation is exactly the deserialized-executable case of the
    persistent-cache transient; real nondeterminism raises)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ..utils.verify import agree_twice

    def key_of(h):
        hashes, crashed, codes, sketches, lat_p99, burst = \
            h[3], h[5], h[6], h[9], h[11], h[13]
        return (hashes.tobytes(), crashed.tobytes(), codes.tobytes(),
                None if sketches is None else sketches.tobytes(),
                None if lat_p99 is None else lat_p99.tobytes(),
                None if burst is None else burst.tobytes())

    def again(prev):
        # prev is a HARVESTED tuple: (seeds, ids, knobs_host, hashes,
        # digest, crashed, codes, mutated, last_op, sketches, state).
        # The knob batch was never donated, so re-placing the host copy
        # over the mesh re-dispatches the identical round.
        seeds, ids, knobs_host, mutated = prev[0], prev[1], prev[2], prev[7]
        last_op, targeted = prev[8], prev[14]
        sharding = NamedSharding(mesh, P(SEED_AXIS))
        knobs_dev = {k: jax.device_put(v, sharding)
                     for k, v in knobs_host.items()}
        from ..parallel.mesh import shard_batch
        state = plan.apply(shard_batch(rt.init_batch(seeds), mesh),
                           knobs_dev)
        if fused:
            # already mesh-placed; run_fused_sharded's device_put is a
            # no-op re-placement
            state = rt.run_fused_sharded(state, max_steps, chunk,
                                         mesh=mesh)
        else:
            state, _ = rt.run(state, max_steps, chunk)
        pairs, n = stats.coverage_digest(state)
        return harvest_fn((seeds, ids, knobs_dev, None, last_op,
                           mutated, targeted, state, pairs, n))

    return agree_twice(harvested, again, key_of,
                       what="first post-resume campaign round")
